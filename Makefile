# Dev-flow entry points.  Same commands CI runs — a green `make lint
# test-quick` locally means a green tier-1.
#
#   make lint        the ruff gate (correctness subset E9/F63/F7/F82;
#                    loud failure if ruff is installed but broken, skip
#                    only on a genuinely ruff-less image) + both
#                    tcdp-lint passes at zero findings
#   make lint-diff   pre-commit path: lint only files changed vs REV
#   make test-quick  the ~90 s iteration tier (pytest -m quick)
#   make test        full tier-1 (everything not marked slow)
#   make perf-gate   re-price benchmarks/perf_pins.json through the
#                    digital twin; fails on a modeled regression
#   make postmortem  DIR=<shared run dir>: merge blackbox bundles and
#                    print the root-cause verdict

PY ?= python
REV ?= HEAD~1

.PHONY: lint lint-diff test test-quick perf-gate postmortem

lint:
	$(PY) -m pytest tests/test_lint.py::test_ruff_gate -q
	$(PY) tools/tcdp_lint.py

lint-diff:
	$(PY) -m pytest tests/test_lint.py::test_ruff_gate -q
	$(PY) tools/tcdp_lint.py --diff $(REV)

test-quick:
	$(PY) -m pytest tests/ -q -m quick

test:
	$(PY) -m pytest tests/ -q -m 'not slow'

perf-gate:
	$(PY) tools/twin_report.py --records . --gate

postmortem:
	$(PY) tools/postmortem.py $(DIR)
