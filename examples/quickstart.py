"""Library-level quickstart: compressed-DP training in ~40 lines.

The role of the reference's ``CIFAR10/demo.ipynb`` — the minimal path from
"I have a model" to "gradients are compressed before the reduction".  Runs
anywhere: real chips, or CPU emulation via
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``.

    python examples/quickstart.py
"""

import os
import sys

# runnable from a fresh checkout without installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tpu_compressed_dp.harness.dawn import MODELS
from tpu_compressed_dp.models.common import init_model, make_apply_fn
from tpu_compressed_dp.parallel.dp import CompressionConfig, init_ef_state
from tpu_compressed_dp.parallel.mesh import make_data_mesh
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.step import make_train_step

# 1. a mesh over every attached device (the data-parallel world)
mesh = make_data_mesh()
ndev = mesh.shape["data"]

# 2. any model in the zoo (or your own flax module taking (x, train=...))
module = MODELS["resnet9"](0.25)
params, stats = init_model(module, jax.random.key(0),
                           jnp.zeros((1, 32, 32, 3), jnp.float32))

# 3. the compression surface: method x granularity x payload mode x EF
comp = CompressionConfig(
    method="topk",            # topk | randomk | thresholdv | terngrad | qsgd
                              # | powersgd (stateful: also pass
                              # comp=init_comp_state(params, comp, ndev)
                              # to TrainState.create)
    granularity="layerwise",  # or "entiremodel"
    mode="simulate",          # or "wire" for genuinely sparse payloads
    ratio=0.01,               # keep 1% of coordinates
    error_feedback=True,      # residual is part of the train state
)

opt = SGD(lr=0.05, momentum=0.9, nesterov=True, weight_decay=5e-4)
state = TrainState.create(params, stats, opt.init(params),
                          init_ef_state(params, comp, ndev), jax.random.key(1))
train_step = make_train_step(make_apply_fn(module), opt, comp, mesh)

# 4. feed batches; everything else (forward, backward, compress, psum,
#    update, metrics) is one compiled step
rng = np.random.default_rng(0)
bs = 64 * ndev
batch = {
    "input": jnp.asarray(rng.standard_normal((bs, 32, 32, 3), dtype=np.float32)),
    "target": jnp.asarray(rng.integers(0, 10, size=(bs,), dtype=np.int32)),
}
for i in range(10):
    state, metrics = train_step(state, batch)
    if (i + 1) % 5 == 0:
        m = jax.device_get(metrics)
        frac = m["comm/sent_elems"] / m["comm/dense_elems"]
        print(f"step {i+1}: loss {m['loss']:.3f}  "
              f"payload {frac*100:.1f}% of dense")

print("done — see the harnesses for full training protocols")
