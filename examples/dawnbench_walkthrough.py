"""Narrative walkthrough of the DAWNBench experiment progression.

The role of the reference's ``CIFAR10/experiments.ipynb`` (VERDICT r4
missing #4): the story of the paper's CIFAR protocol as a runnable script —
each stage prints what it is about to show, runs it through the SAME harness
entry points the real experiments use, and summarises what the numbers mean.
Scaled down (synthetic data, few epochs) so it completes in minutes on CPU;
every stage names the full-protocol command that produces the recorded
artifact in ``benchmarks/``.

    python examples/dawnbench_walkthrough.py            # CPU-friendly
    python examples/dawnbench_walkthrough.py --full     # the real protocol
                                                        # (chip, ~5 min/run)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def stage(title, full_cmd):
    print(f"\n{'=' * 72}\n## {title}\n"
          f"   full protocol: {full_cmd}\n{'=' * 72}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="run the real 24/40-epoch protocol instead of the "
                         "8-epoch narrative scale")
    args = ap.parse_args()
    from tpu_compressed_dp.harness import dawn

    # narrative scale: the EASY synthetic set (class-colour blobs) on a
    # quarter-width net, where 6 epochs visibly learn (dense saturates,
    # compressed methods show their EF warm-up lag) in ~1 min on a chip
    # and a few minutes on a laptop CPU; runs on whatever backend jax
    # lands on (like examples/quickstart.py).  --full switches to the
    # full-width net + the non-saturating --synthetic_hard benchmark the
    # recorded grids use.
    if args.full:
        common = ["--synthetic_hard", "--log_dir", ""]
    else:
        common = ["--synthetic", "--synthetic_n", "1024", "--epochs", "6",
                  "--batch_size", "256", "--channels_scale", "0.25",
                  "--log_dir", ""]

    # ------------------------------------------------------------------
    stage("1. The dense baseline — the DAWNBench recipe itself",
          "python -m tpu_compressed_dp.harness.dawn  (94% CIFAR-10; with a "
          "real dataset use tools/reproduce_headline.py)")
    print("ResNet-9, bs 512, lr triangle peaking 0.4 at epoch 5 — the\n"
          "reference's dawn.py protocol verbatim.  On --synthetic_hard the\n"
          "24-epoch run lands ~0.96 test accuracy (benchmarks/convergence_*).")
    dense = dawn.main(common + ["--momentum", "0.9"])
    print(f"-> test acc {dense['test acc']:.4f}")

    # ------------------------------------------------------------------
    stage("2. Layer-wise Top-K — the paper's first compression claim",
          "tools/convergence_sweep.py --only topk-lw-1%  "
          "(recorded: 0.9609 vs dense 0.9619, convergence_r4.tsv)")
    print("Keep the top 1% of each layer's gradient by magnitude, with\n"
          "error feedback accumulating what was dropped.  Same protocol,\n"
          "99% fewer coordinates synced.")
    topk = dawn.main(common + ["--momentum", "0.9", "--compress", "layerwise",
                               "--method", "topk", "--ratio", "0.01",
                               "--error_feedback"])
    print(f"-> test acc {topk['test acc']:.4f}  "
          f"(sent fraction {topk.get('sent frac', 1.0):.4f})")

    # ------------------------------------------------------------------
    stage("3. Wire mode — actually-small payloads, not simulation",
          "tools/convergence_sweep.py --only topk-em-1%-wire  "
          "(recorded: 0.9619 — parity with simulate)")
    print("The reference SIMULATES compression (dense all-reduce of a\n"
          "zeroed tensor); mode='wire' ships the real packed payload\n"
          "(values + indices over all_gather) and bills measured bytes —\n"
          "NIC-validated to ~3% in benchmarks/transport_validation_r5.tsv.")
    wire = dawn.main(common + ["--momentum", "0.9", "--compress",
                               "entiremodel", "--method", "topk", "--ratio",
                               "0.01", "--error_feedback", "--mode", "wire"])
    print(f"-> test acc {wire['test acc']:.4f}  "
          f"(wire fraction {wire.get('wire frac', 1.0):.4f} of dense bits)")

    # ------------------------------------------------------------------
    stage("4. The operator the paper found fragile — and what fixes it",
          "tools/convergence_sweep.py --only adaptive-lw-EF-40ep  "
          "(recorded: 0.9624 = dense parity at ~1.1% sent)")
    print("Adaptive threshold (keep |g| >= max|g|/2 per layer) sends ~0.02%\n"
          "and stalls without help (0.485 in 24 epochs).  Error feedback\n"
          "turns it into a dense-parity method: the residual accumulates\n"
          "until it crosses the bar, self-regulating density to ~1%.")
    # the recorded dense-parity row is the 40-epoch recipe (the harness's
    # 40-epoch rule covers randomk/thresholdv but not adaptive_threshold)
    ada = dawn.main(common + ["--momentum", "0.9", "--compress", "layerwise",
                              "--method", "adaptive_threshold",
                              "--error_feedback"]
                    + (["--epochs", "40"] if args.full else []))
    print(f"-> test acc {ada['test acc']:.4f}  "
          f"(sent fraction {ada.get('sent frac', 0.0):.5f})")

    # ------------------------------------------------------------------
    print(f"\n{'=' * 72}\n## Where this goes next\n{'=' * 72}")
    print("* multi-chip projection: benchmarks/time_to_accuracy_r5.tsv —\n"
          "  compression pays where the link is slow (DCN-class, stable\n"
          "  across latency/overlap assumptions: tta_sensitivity_r5.tsv);\n"
          "* the wire fast path: Block-Top-K (benchmarks/wire_wall_r5.txt);\n"
          "* the LM/stretch side: harness.lm --preset llama3_8b\n"
          "  (benchmarks/lm_throughput_r5.txt, MFU 0.72 at 128k vocab).")
    summary = {
        "dense": dense["test acc"], "topk_lw_1pct": topk["test acc"],
        "wire_topk_1pct": wire["test acc"], "adaptive_EF": ada["test acc"],
    }
    print("\nwalkthrough summary:", summary)
    return summary


if __name__ == "__main__":
    main()
