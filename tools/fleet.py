#!/usr/bin/env python
"""Fleet CLI — many jobs, one device pool (tpu_compressed_dp/fleet/).

Three subcommands over one shared ``--fleet_dir``:

``submit``
    Validate a JSON job spec (a file, or ``-`` for stdin) and drop it
    into the admission queue.  Spec schema (see
    :class:`tpu_compressed_dp.fleet.spec.JobSpec`)::

        {"job_id": "lm-a", "priority": 0,
         "min_world": 2, "max_world": 4,
         "command": ["python", "-m", "tpu_compressed_dp.harness.lm",
                     "--synthetic", "--heartbeat", "fleet/hb/hb.json",
                     "--prom", "fleet/prom/metrics.prom"],
         "target_updates": null, "checkpoint_dir": "ckpts/lm-a"}

``run``
    The scheduler process: admits the queue over a ``--devices``-sized
    pool, places/preempts/resumes jobs as subprocesses, writes per-job +
    pool Prometheus rollups and ``fleet_*`` JSONL events under the fleet
    dir.  Each child is launched through
    ``utils.resilience.spawn_supervised`` with ``TCDP_JOB_ID`` (so the
    harness job-scopes its heartbeat/prom/event files and labels its
    exposition), ``TCDP_FLEET_WORLD`` and ``TCDP_FLEET_DEVICES`` (the
    assigned device-id slice), plus the usual ``TCDP_RESTART_COUNT``
    incarnation.  Eviction is the PR-8 preempt path: SIGTERM -> the
    harness drains + cuts an emergency save -> exit 75 -> requeued for
    bitwise resume when capacity returns.  The v1 subprocess controller
    is NOT resizable — elastic in-place shrink/grow needs the in-process
    controller (see the fleet drill in tools/chaos_drill.py); here an
    elastic spec still helps (the job places anywhere in
    [min_world, max_world]) but preemption always evicts whole jobs.

``status``
    Print the pool record and the per-job table from the shared dir
    (works from any process while ``run`` ticks).

Heartbeat verdicts: point each job's ``--heartbeat`` at
``<fleet_dir>/hb/hb.json`` — the harness's ``--job_id`` scoping turns
that into ``hb/<job_id>.hb.json``, which the controller polls with
``check_heartbeat`` after ``--grace`` seconds; an unhealthy job is
killed and requeued until its restart budget is spent.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from tpu_compressed_dp.fleet import (FleetScheduler, JobController, JobSpec,
                                     SpecError)
from tpu_compressed_dp.fleet import state as fstate
from tpu_compressed_dp.obs.export import EventStream, job_scoped_path
from tpu_compressed_dp.utils.resilience import (check_heartbeat,
                                                read_heartbeat,
                                                spawn_supervised)


class SubprocessController(JobController):
    """Jobs as supervised child processes (``resizable = False``: v1
    preemption evicts whole jobs; in-place shrink/grow is the in-process
    controller's territory)."""

    resizable = False

    def __init__(self, fleet_dir: str, *, term_timeout_s: float = 30.0,
                 grace_s: float = 60.0, hb_max_age_s: float = 120.0,
                 log=print):
        self.fleet_dir = fleet_dir
        self.term_timeout_s = float(term_timeout_s)
        self.grace_s = float(grace_s)
        self.hb_max_age_s = float(hb_max_age_s)
        self.log = log
        self.children: Dict[str, "object"] = {}
        self.started_at: Dict[str, float] = {}
        self.incarnations: Dict[str, int] = {}

    def _hb_path(self, job_id: str) -> Optional[str]:
        return job_scoped_path(
            os.path.join(self.fleet_dir, "hb", "hb.json"), job_id)

    def start(self, spec: JobSpec, world: int, devices: Tuple[int, ...],
              *, resume: bool) -> None:
        os.makedirs(os.path.join(self.fleet_dir, "hb"), exist_ok=True)
        inc = self.incarnations.get(spec.job_id, 0)
        self.children[spec.job_id] = spawn_supervised(
            spec.command, restart_count=inc,
            extra_env={"TCDP_JOB_ID": spec.job_id,
                       "TCDP_FLEET_WORLD": str(world),
                       "TCDP_FLEET_DEVICES": ",".join(str(d) for d in devices)},
            log=self.log)
        self.incarnations[spec.job_id] = inc + 1
        self.started_at[spec.job_id] = time.time()
        self.log(f"fleet: started {spec.job_id} world={world} "
                 f"devices={list(devices)} resume={resume}")

    def evict(self, job_id: str) -> int:
        child = self.children.pop(job_id, None)
        if child is None:
            return -1
        if child.poll() is None:
            child.terminate()  # the harness's preempt path: emergency save
            try:
                child.wait(timeout=self.term_timeout_s)
            except Exception:
                child.kill()
                child.wait()
        return int(child.returncode)

    def poll(self, job_id: str) -> Dict[str, object]:
        child = self.children.get(job_id)
        if child is None:
            return {"exit_code": -1}
        out: Dict[str, object] = {"exit_code": child.poll()}
        if out["exit_code"] is not None:
            self.children.pop(job_id, None)
        hb_path = self._hb_path(job_id)
        hb = read_heartbeat(hb_path) if hb_path else None
        if hb is not None:
            watermark = hb.get("last_good_step", hb.get("step"))
            if isinstance(watermark, (int, float)):
                out["applied_updates"] = int(watermark)
            skew = hb.get("straggler_skew_s")
            if isinstance(skew, (int, float)):
                # the flight recorder's live cross-rank step-time skew: the
                # scheduler's straggler-eviction policy keys off this
                out["straggler_skew_s"] = float(skew)
        if (out["exit_code"] is None
                and time.time() - self.started_at.get(job_id, 0.0)
                > self.grace_s):
            problems = check_heartbeat(hb_path, max_age_s=self.hb_max_age_s,
                                       hb=hb)
            out["healthy"] = not problems
            if problems:
                self.log(f"fleet: {job_id} heartbeat: {problems[0]}")
        return out

    def shutdown(self) -> None:
        """Terminate every surviving child (the run loop's finally — an
        interrupted scheduler must not orphan its jobs)."""
        for job_id in list(self.children):
            rc = self.evict(job_id)
            self.log(f"fleet: shutdown: {job_id} exited {rc}")


def run_submit(args) -> int:
    text = (sys.stdin.read() if args.spec == "-"
            else open(args.spec).read())
    try:
        spec = JobSpec.parse(text)
    except SpecError as e:
        print(f"fleet: invalid spec: {e}")
        return 2
    path = fstate.submit_job(args.fleet_dir, spec, ts=time.time())
    print(f"fleet: queued {spec.job_id} (priority {spec.priority}, world "
          f"[{spec.min_world}, {spec.max_world}]) -> {path}")
    return 0


def run_run(args) -> int:
    controller = SubprocessController(
        args.fleet_dir, term_timeout_s=args.term_timeout,
        grace_s=args.grace, hb_max_age_s=args.max_age)
    events = EventStream(fstate.events_path(args.fleet_dir),
                         meta={"pool_size": args.devices})
    sched = FleetScheduler(args.fleet_dir, args.devices, controller,
                           events=events, max_restarts=args.max_restarts)
    try:
        ticks = sched.run(interval_s=args.interval,
                          max_ticks=args.max_ticks,
                          until_idle=args.until_idle)
    finally:
        controller.shutdown()
        events.close()
    c = sched.counters
    print(f"fleet: {ticks} ticks — {c['finishes']} finished, "
          f"{c['failures']} failed, {c['evictions']} evictions, "
          f"{c['shrinks']} shrinks, {c['readmits']} readmits")
    failed = [j for j in sched.jobs.values() if j.status == "failed"]
    return 1 if failed else 0


def run_status(args) -> int:
    pool = fstate.read_pool_record(args.fleet_dir)
    if pool is None:
        print(f"fleet: no pool record under {args.fleet_dir} (scheduler "
              "not started?)")
        return 2
    c = pool.get("counters", {})
    print(f"pool: {pool['pool_size']} devices, "
          f"{pool.get('devices_free', '?')} free, "
          f"{pool.get('jobs_running', '?')} running / "
          f"{pool.get('jobs_waiting', '?')} waiting "
          f"(tick {pool.get('ticks', '?')}; "
          f"evictions={c.get('evictions', 0)} shrinks={c.get('shrinks', 0)} "
          f"readmits={c.get('readmits', 0)})")
    rows = fstate.list_job_records(args.fleet_dir)
    if rows:
        print(f"{'job':<20} {'status':<8} {'prio':>4} {'world':>5} "
              f"{'applied':>8} {'restarts':>8} devices")
        for r in rows:
            print(f"{r['job_id']:<20} {r['status']:<8} "
                  f"{r.get('priority', 0):>4} {r.get('world', 0):>5} "
                  f"{r.get('applied_updates', 0):>8} "
                  f"{r.get('restarts', 0):>8} {r.get('devices', [])}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("submit", help="queue one JSON job spec")
    ps.add_argument("--fleet_dir", type=str, required=True)
    ps.add_argument("--spec", type=str, required=True,
                    help="path to the JSON job spec ('-' = stdin)")

    pr = sub.add_parser("run", help="the scheduler process")
    pr.add_argument("--fleet_dir", type=str, required=True)
    pr.add_argument("--devices", type=int, required=True,
                    help="device-pool size the placements bin-pack")
    pr.add_argument("--interval", type=float, default=5.0,
                    help="seconds between scheduler ticks")
    pr.add_argument("--max_ticks", type=int, default=None,
                    help="stop after this many ticks (default: run forever)")
    pr.add_argument("--until_idle", action="store_true",
                    help="exit once every admitted job finished and the "
                         "queue is empty")
    pr.add_argument("--max_restarts", type=int, default=3,
                    help="per-job crash budget (preemptions are free)")
    pr.add_argument("--grace", type=float, default=60.0,
                    help="seconds after a (re)start before heartbeat "
                         "verdicts apply")
    pr.add_argument("--max_age", type=float, default=120.0,
                    help="heartbeat staleness bound for the health verdict")
    pr.add_argument("--term_timeout", type=float, default=30.0,
                    help="seconds to wait for a SIGTERM'd job's emergency "
                         "save before SIGKILL")

    pt = sub.add_parser("status", help="print pool + per-job records")
    pt.add_argument("--fleet_dir", type=str, required=True)

    args = p.parse_args(argv)
    if args.cmd == "submit":
        return run_submit(args)
    if args.cmd == "run":
        return run_run(args)
    return run_status(args)


if __name__ == "__main__":
    sys.exit(main())
