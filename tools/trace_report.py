#!/usr/bin/env python
"""Offline report from a JSONL telemetry event stream (``--events``).

Renders, from the records the harnesses emit through
:mod:`tpu_compressed_dp.obs.export`:

  * a **per-phase step-time breakdown** — mean/p50/p95 of the host
    timeline's data-wait / dispatch / (sampled) device-drain splits, and
    the data-wait fraction — the "where does a step's wall time go"
    table the paper's thesis needs;
  * a **throughput trajectory** — per epoch / log window: examples|tokens
    per second, MFU, per-chip comm MB/s, loss;
  * optionally (``--chrome out.json``) a **chrome://tracing /
    ui.perfetto.dev trace-event export** of the host timeline, one span
    per phase per step.

With ``--merge``, takes MULTIPLE per-rank event streams and emits one
cross-rank chrome://tracing export with a process lane per rank (lane
index = argument position; reuses ``tools/postmortem.py``'s merge) — the
visual the straggler gauges summarise to one number.

Usage::

    python tools/trace_report.py events.jsonl
    python tools/trace_report.py events.jsonl --chrome trace.json
    python tools/trace_report.py r0.jsonl r1.jsonl r2.jsonl \\
        --merge --chrome merged.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from tpu_compressed_dp.obs.export import SCHEMA_VERSION, read_all_events
from tpu_compressed_dp.obs.trace import percentile

WINDOW_KINDS = ("epoch", "step")  # records that carry metrics + timeline


def check_schema(events: List[Dict[str, Any]]) -> None:
    vs = {e.get("v") for e in events}
    unknown = vs - {SCHEMA_VERSION}
    if unknown:
        raise ValueError(
            f"event stream carries unknown schema version(s) {sorted(unknown)}"
            f" (this tool understands v{SCHEMA_VERSION})")


def step_spans(events: List[Dict[str, Any]]) -> List[Dict[str, float]]:
    """All per-step host-timeline records, in stream order."""
    out: List[Dict[str, float]] = []
    for e in events:
        if e.get("kind") in WINDOW_KINDS:
            out.extend(e.get("step_spans") or [])
    return out


def phase_breakdown(events: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """``{phase: {mean_ms, p50_ms, p95_ms, share}}`` over every step span
    in the stream.  ``share`` is the phase's fraction of step wall time —
    computed against the SAME steps the phase was measured on, so the
    sampled ``device`` split (``device_sync_every > 0`` records it only
    every Nth step) is not diluted by the unsampled steps' totals."""
    spans = step_spans(events)
    out: Dict[str, Dict[str, float]] = {}
    for ph in ("data", "dispatch", "device", "total"):
        have = [s for s in spans if s.get(ph) is not None and ph in s]
        if not have:
            continue
        vals = sorted(s[ph] for s in have)
        denom = sum(s.get("total", 0.0) for s in have)
        out[ph] = {
            "mean_ms": sum(vals) / len(vals) * 1e3,
            "p50_ms": percentile(vals, 0.50) * 1e3,
            "p95_ms": percentile(vals, 0.95) * 1e3,
            "share": (sum(vals) / denom) if denom > 0 else 0.0,
        }
    return out


def throughput_rows(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One row per epoch/step window: loss + throughput + MFU + comm rate."""
    rows = []
    for e in events:
        if e.get("kind") not in WINDOW_KINDS:
            continue
        m = e.get("metrics") or {}
        thr = e.get("throughput") or {}
        rows.append({
            "window": e.get("epoch", e.get("step", "?")),
            "kind": e["kind"],
            "loss": m.get("train loss", m.get("loss")),
            "rate": thr.get("throughput/examples_per_sec",
                            thr.get("throughput/tokens_per_sec")),
            "rate_unit": ("ex/s" if "throughput/examples_per_sec" in thr
                          else "tok/s"),
            "mfu": thr.get("throughput/mfu"),
            "tflops": thr.get("throughput/model_tflops_per_chip"),
            "comm_mb_s": m.get("comm MB/s"),
            "skipped": (e.get("guard") or {}).get("guard/skipped"),
        })
    return rows


def chrome_trace_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Trace-event-format spans (``ph='X'``, microseconds) of the host
    timeline — load in chrome://tracing or ui.perfetto.dev."""
    spans = step_spans(events)
    if not spans:
        return []
    t_base = min(s["t0"] for s in spans)
    out = []
    for i, s in enumerate(spans):
        t = (s["t0"] - t_base) * 1e6
        for ph in ("data", "dispatch", "device"):
            dur = s.get(ph)
            if dur is None:
                continue
            out.append({"name": ph, "cat": "host", "ph": "X", "pid": 0,
                        "tid": 0, "ts": t, "dur": dur * 1e6,
                        "args": {"step_index": i}})
            t += dur * 1e6
    return out


def _fmt(v: Optional[float], spec: str = "10.2f") -> str:
    return format(v, spec) if isinstance(v, (int, float)) else " " * 7 + "-"


def render_report(events: List[Dict[str, Any]]) -> str:
    check_schema(events)
    lines = []
    start = next((e for e in events if e.get("kind") == "run_start"), {})
    ctx = {k: v for k, v in start.items()
           if k not in ("v", "kind", "ts")}
    lines.append(f"run: {json.dumps(ctx)}")

    bd = phase_breakdown(events)
    lines.append("")
    lines.append("per-phase step-time breakdown (host timeline):")
    lines.append(f"  {'phase':<10}{'mean ms':>10}{'p50 ms':>10}"
                 f"{'p95 ms':>10}{'share':>8}")
    for ph in ("data", "dispatch", "device", "total"):
        if ph not in bd:
            continue
        r = bd[ph]
        share = "" if ph == "total" else f"{r['share']*100:7.1f}%"
        lines.append(f"  {ph:<10}{r['mean_ms']:>10.2f}{r['p50_ms']:>10.2f}"
                     f"{r['p95_ms']:>10.2f}{share:>8}")
    if not bd:
        lines.append("  (no step spans in stream)")

    lines.append("")
    lines.append("throughput trajectory:")
    lines.append(f"  {'window':>8}  {'loss':>10}{'rate':>12} unit "
                 f"{'MFU':>8}{'TF/chip':>10}{'comm MB/s':>11}{'skipped':>9}")
    for r in throughput_rows(events):
        lines.append(
            f"  {r['window']:>8}  {_fmt(r['loss'], '10.4f')}"
            f"{_fmt(r['rate'], '12.1f')} {r['rate_unit']:<4}"
            f"{_fmt(r['mfu'], '8.4f')}{_fmt(r['tflops'], '10.3f')}"
            f"{_fmt(r['comm_mb_s'], '11.3f')}{_fmt(r['skipped'], '9.0f')}")

    guard = [e for e in events if e.get("kind") == "guard"]
    if guard:
        lines.append("")
        lines.append(f"guard events: {len(guard)} "
                     f"(last: {json.dumps({k: v for k, v in guard[-1].items() if k.startswith('guard/')})})")
    return "\n".join(lines)


def render_schedule(path: str) -> str:
    """Render the per-chunk collective placement recorded by
    ``tools/overlap_evidence.py`` (``benchmarks/overlap_hlo_r8.txt``)
    alongside the host report: which ``tcdp.chunk<ii>`` collective sits
    where in the compiled schedule, and how much model compute remains to
    hide it — the overlap, directly.  The host timeline cannot see device
    phases; the AOT schedule artifact is the device-side view."""
    lines = ["", f"compiled-schedule overlap ({path}):"]
    try:
        txt = open(path).read()
    except OSError as e:
        return "\n".join(lines + [f"  (unreadable: {e})"])
    for ln in txt.splitlines():
        if ln.startswith("== ") or "chunk=" in ln or "summary:" in ln:
            lines.append("  " + ln.strip())
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("events", nargs="+",
                   help="JSONL event stream(s) (harness --events); more "
                        "than one requires --merge")
    p.add_argument("--merge", action="store_true",
                   help="treat each events argument as one rank's stream "
                        "and emit a cross-rank chrome trace with rank "
                        "lanes (requires --chrome)")
    p.add_argument("--chrome", type=str, default=None,
                   help="write a chrome://tracing trace-event JSON here")
    p.add_argument("--json", action="store_true",
                   help="emit the breakdown/trajectory as JSON instead of text")
    p.add_argument("--schedule", type=str, default=None,
                   help="also render the per-chunk collective placement "
                        "from an overlap_evidence output file "
                        "(benchmarks/overlap_hlo_r8.txt)")
    p.add_argument("--control", action="store_true",
                   help="also render the adaptive-controller rung "
                        "trajectory (control_decision records; see "
                        "tools/control_report.py for the full report)")
    args = p.parse_args(argv)
    if args.merge:
        if not args.chrome:
            p.error("--merge requires --chrome OUT.json")
        try:
            from tools.postmortem import rank_lane_events
        except ImportError:  # script mode: sys.path[0] is tools/
            from postmortem import rank_lane_events
        spans_by_rank: Dict[int, List[Dict[str, Any]]] = {}
        for rank, path in enumerate(args.events):
            evs = read_all_events(path)
            check_schema(evs)
            spans_by_rank[rank] = step_spans(evs)
            print(f"rank {rank}: {len(spans_by_rank[rank])} step spans "
                  f"({path})")
        with open(args.chrome, "w") as f:
            json.dump({"traceEvents": rank_lane_events(spans_by_rank),
                       "displayTimeUnit": "ms"}, f)
        print(f"cross-rank chrome trace: {args.chrome} "
              "(load in chrome://tracing or ui.perfetto.dev)")
        return 0
    if len(args.events) > 1:
        p.error("multiple event streams need --merge")
    # a rotated stream (--events_max_mb) is stitched back together here
    events = read_all_events(args.events[0])
    if args.json:
        payload = {"phase_breakdown": phase_breakdown(events),
                   "throughput": throughput_rows(events)}
        if args.schedule:
            payload["schedule"] = render_schedule(args.schedule).splitlines()
        if args.control:
            try:
                from tools.control_report import decision_rows, summarize
            except ImportError:  # script mode: sys.path[0] is tools/
                from control_report import decision_rows, summarize
            decs = decision_rows(events)
            payload["control"] = {"decisions": decs,
                                  "summary": summarize(decs)}
        print(json.dumps(payload, indent=2))
    else:
        print(render_report(events))
        if args.schedule:
            print(render_schedule(args.schedule))
        if args.control:
            try:
                from tools.control_report import (
                    render_report as render_control)
            except ImportError:  # script mode: sys.path[0] is tools/
                from control_report import render_report as render_control
            print("")
            print(render_control(events))
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump({"traceEvents": chrome_trace_events(events),
                       "displayTimeUnit": "ms"}, f)
        print(f"\nchrome trace: {args.chrome} "
              "(load in chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
