"""Stage-accounted profile of the element-wise wire Top-K sync chain.

The element Top-K wire path (`ops/wire.py:_leaf_sync_topk`) has been the
framework's slowest mode for three rounds (~2.4x dense at the 125M LM
config).  Round 4's diagnosis named four element-granular stages — threshold,
payload gather, scatter-add reconstruction, EF scatter — without individual
numbers on the current code.  This tool produces those numbers the trustworthy
way (round-4 memory: standalone op timings at this scale thrash the allocator
and lie): a ladder of CUMULATIVE prefix chains, each jitted with donated
inputs and run under `shard_map` over a 1-device data axis exactly like the
harness step; per-stage cost is the difference between consecutive rungs.
Every rung returns a scalar that data-depends on all its stages so XLA cannot
DCE a stage out of a longer rung.

Usage (on the TPU chip):
    python tools/wire_profile.py --n 125000000 --ratio 0.01 [--iters 30]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

if __package__ in (None, ""):  # script run: repo root onto sys.path
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from tpu_compressed_dp.compat import shard_map

from tpu_compressed_dp.ops import compressors, kernels, wire


def _stage_chain(upto: str, n: int, keep: int, axis_name: str = "data"):
    """Build a chain running stages up to and including `upto`.

    Stage order: mag -> threshold -> pack -> gather -> combine -> ef.
    Returns (out_scalar,) so everything stays live.
    """

    def chain(flat: jax.Array):
        mag = jnp.abs(flat).astype(jnp.float32)
        out = jnp.sum(mag[:8])
        if upto == "mag":
            return out
        t = kernels.topk_threshold(mag, keep)
        out = out + t
        if upto == "threshold":
            return out
        mask = mag >= t
        idx = wire.packed_indices_from_mask(mask, keep)
        out = out + jnp.sum(idx[:8].astype(jnp.float32))
        if upto == "pack":
            return out
        payload = wire._sorted_gather(flat, idx)
        out = out + jnp.sum(payload[:8])
        if upto == "gather":
            return out
        world = jax.lax.psum(1, axis_name)
        g_vals = wire._all_gather(payload, axis_name)
        g_idx = wire._all_gather(idx, axis_name)
        dense = wire._scatter_combine(flat.shape, flat.dtype, g_idx, g_vals,
                                      world)
        out = out + jnp.sum(dense[:8])
        if upto == "combine":
            return out
        new_ef = flat.at[idx].set(0, indices_are_sorted=True,
                                  unique_indices=True,
                                  mode="promise_in_bounds")
        out = out + jnp.sum(new_ef[:8])
        return out

    return chain


def _pack_sub_chain(upto: str, n: int, keep: int):
    """Sub-stages of the SHIPPED packed_indices_from_mask (pack v2, r5:
    one fused row-starts gather + bf16 MXU tri-matmul), cumulative from the
    threshold rung.  Mirrors ops/wire.py — update both together."""

    def chain(flat: jax.Array):
        lanes = 128
        mag = jnp.abs(flat).astype(jnp.float32)
        t = kernels.topk_threshold(mag, keep)
        mask = mag >= t
        pad = (-n) % lanes
        m2 = jnp.pad(mask, (0, pad)).reshape(-1, lanes)
        row_counts = jnp.sum(m2, axis=1, dtype=jnp.int32)
        out = jnp.sum(row_counts[:8].astype(jnp.float32))
        if upto == "p_rowcounts":
            return out
        row_ends = jnp.cumsum(row_counts)
        ends_hist = jnp.zeros((keep + 1,), jnp.int32).at[
            jnp.minimum(row_ends, keep)].add(
                1, indices_are_sorted=True, mode="promise_in_bounds")
        out = out + jnp.sum(ends_hist[:8].astype(jnp.float32))
        if upto == "p_hist":
            return out
        row_of = jnp.cumsum(ends_hist)[:keep]
        valid = row_of < m2.shape[0]
        row_of = jnp.where(valid, row_of, m2.shape[0] - 1)
        out = out + jnp.sum(row_of[:8].astype(jnp.float32))
        if upto == "p_rowof":
            return out
        ranks = jnp.arange(1, keep + 1, dtype=jnp.int32)
        row_starts = wire._sorted_gather(row_ends - row_counts, row_of)
        within = ranks - row_starts
        out = out + jnp.sum(within[:8].astype(jnp.float32))
        if upto == "p_startsgather":
            return out
        rows = wire._sorted_gather(m2, row_of).astype(jnp.bfloat16)
        out = out + jnp.sum(rows[:8].astype(jnp.float32))
        if upto == "p_rowgather":
            return out
        tri = jnp.tril(jnp.ones((lanes, lanes), jnp.bfloat16))
        prefix = jax.lax.dot(rows, tri.T,
                             preferred_element_type=jnp.float32)
        hit = (prefix >= within[:, None].astype(jnp.float32)) & (rows > 0)
        col = jnp.argmax(hit, axis=1).astype(jnp.int32)
        idx = jnp.where(valid, row_of * lanes + col, 0)
        return out + jnp.sum(idx[:8].astype(jnp.float32))

    return chain


PACK_SUBS = ["p_rowcounts", "p_hist", "p_rowof", "p_startsgather",
             "p_rowgather", "p_matmul"]


def _pack_scatter_chain(n: int, keep: int, axis_name: str = "data"):
    """EXPERIMENT: replace pack+gather+EF with one elementwise slot
    computation + a sorted full-tensor scatter-add.

    Every element's payload slot is computable without any per-rank gather:
    ``slot = row_start[row] + in_row_prefix - 1`` (in-row prefix = one MXU
    tri-matmul over the full mask).  Dead elements alias the most recent
    live slot with a 0 contribution, keeping the flattened slot sequence
    nondecreasing, so ONE scatter-add with ``indices_are_sorted=True``
    emits the packed (values, indices) payload in a single streaming pass —
    if XLA's TPU scatter lowering honours the hint.  EF is elementwise.
    """

    def chain(flat: jax.Array):
        lanes = 128
        mag = jnp.abs(flat).astype(jnp.float32)
        t = kernels.topk_threshold(mag, keep)
        pad = (-n) % lanes
        m2 = jnp.pad(mag >= t, (0, pad)).reshape(-1, lanes)
        cnt = jnp.sum(m2, axis=1, dtype=jnp.int32)
        row_end = jnp.cumsum(cnt)
        row_start = row_end - cnt
        tri = jnp.tril(jnp.ones((lanes, lanes), jnp.float32))
        prefix = (m2.astype(jnp.float32) @ tri.T).astype(jnp.int32)  # inclusive
        slot = row_start[:, None] + jnp.maximum(prefix - 1, 0)
        slot = jnp.minimum(slot, keep)          # overflow + tail -> slot `keep`
        live = m2 & (slot < keep) & (prefix > 0)
        sf = slot.reshape(-1)
        acc_pad = jnp.pad(flat, (0, pad))
        pos = jnp.arange(n + pad, dtype=jnp.int32)
        contrib_v = jnp.where(live.reshape(-1), acc_pad, 0.0)
        contrib_i = jnp.where(live.reshape(-1), pos, 0)
        vals = jnp.zeros((keep + 1,), flat.dtype).at[sf].add(
            contrib_v, indices_are_sorted=True, mode="promise_in_bounds")[:keep]
        idx = jnp.zeros((keep + 1,), jnp.int32).at[sf].add(
            contrib_i, indices_are_sorted=True, mode="promise_in_bounds")[:keep]
        new_ef = jnp.where(mag >= t, 0.0, flat)          # elementwise EF
        world = jax.lax.psum(1, axis_name)
        g_vals = wire._all_gather(vals, axis_name)
        g_idx = wire._all_gather(idx, axis_name)
        dense = (jnp.zeros(flat.shape, flat.dtype)
                 .at[g_idx.reshape(-1)].add(g_vals.reshape(-1)) / world)
        return jnp.sum(dense[:8]) + jnp.sum(new_ef[:8]) + jnp.sum(vals[:8])

    return chain


def _sharded_chain(upto: str, n: int, keep: int, cfg, axis_name: str = "data"):
    """Stage ladder for the OWNER-SHARDED transport (transport='sharded'):
    mag -> threshold -> select_pack (the shipped `wire._select_pack`
    dispatch: one fused Pallas pass or the XLA mask/pack/gather chain,
    depending on `kernels.pallas_mode()`) -> route (dispatch-aware bucket
    build + all_to_all) -> reduce (owner scatter-add) -> return (shard
    all_gather + scatter/concat) -> ef.  Mirrors
    ops/wire_sharded.sharded_combine — update both together.  On one device the collectives are self-copies, so the route/
    return rungs price the bucketisation and reduction machinery, not link
    time — the same caveat as the base ladder's all_gather rungs."""
    from tpu_compressed_dp.ops import wire_sharded

    def chain(flat: jax.Array):
        mag = jnp.abs(flat).astype(jnp.float32)
        out = jnp.sum(mag[:8])
        if upto == "mag":
            return out
        t = kernels.topk_threshold(mag, keep)
        out = out + t
        if upto == "threshold":
            return out
        vals, idx, _cnt = wire._select_pack(flat, mag, t, keep)
        out = (out + jnp.sum(idx[:8].astype(jnp.float32))
               + jnp.sum(vals[:8]))
        if upto == "select_pack":
            return out
        world = jax.lax.psum(1, axis_name)
        plan = wire_sharded.make_shard_plan(
            n, keep, world, 1, cfg.shard_route_factor, cfg.shard_return_factor)
        W, cap, shard_n = plan.world, plan.cap_dest, plan.shard_n
        slot, accepted, dest = wire_sharded._per_dest_slots(idx, None, plan)
        local = (idx - dest * shard_n).astype(jnp.int32)
        if kernels.use_bucket_route(idx.shape[0], W, cap):
            bvals, bidx = kernels.fused_bucket_route(
                vals, idx, dest, W, cap, shard_n)
        else:
            bvals = jnp.zeros((W * cap + 1,), flat.dtype
                              ).at[slot].add(vals)[:-1].reshape(W, cap)
            bidx = jnp.full((W * cap + 1,), shard_n, jnp.int32
                            ).at[slot].set(local)[:-1].reshape(W, cap)
        rvals = jax.lax.all_to_all(bvals, axis_name, 0, 0)
        ridx = jax.lax.all_to_all(bidx, axis_name, 0, 0)
        out = out + jnp.sum(rvals[0, :8])
        if upto == "route":
            return out
        shard = jnp.zeros((shard_n + 1,), flat.dtype)
        occ = jnp.zeros((shard_n + 1,), jnp.int32)
        if W <= 16:
            for w in range(W):
                shard = shard.at[ridx[w]].add(
                    rvals[w], indices_are_sorted=True,
                    mode="promise_in_bounds")
                occ = occ.at[ridx[w]].add(
                    1, indices_are_sorted=True, mode="promise_in_bounds")
        else:
            shard = shard.at[ridx.reshape(-1)].add(rvals.reshape(-1))
            occ = occ.at[ridx.reshape(-1)].add(1)
        shard, occ = shard[:shard_n], occ[:shard_n]
        out = out + jnp.sum(shard[:8])
        if upto == "reduce":
            return out
        if plan.dense_return:
            dense = wire._all_gather(shard, axis_name).reshape(-1)[:n] / world
        else:
            mask = occ > 0
            rix = wire.packed_indices_from_mask(mask, plan.cap_ret)
            rvalid = (jnp.arange(1, plan.cap_ret + 1, dtype=jnp.int32)
                      <= jnp.minimum(jnp.sum(mask, dtype=jnp.int32),
                                     plan.cap_ret))
            sel = jnp.where(rvalid, shard.at[rix].get(
                mode="promise_in_bounds"), 0)
            g_v = wire._all_gather(sel, axis_name)
            g_i = wire._all_gather(jnp.where(rvalid, rix, 0), axis_name)
            offs = jnp.arange(W, dtype=jnp.int32)[:, None] * shard_n
            dense = (jnp.zeros((W * shard_n,), flat.dtype)
                     .at[(g_i + offs).reshape(-1)].add(g_v.reshape(-1))
                     [:n] / world)
        out = out + jnp.sum(dense[:8])
        if upto == "return":
            return out
        new_ef = flat.at[idx].set(0, indices_are_sorted=True,
                                  unique_indices=True,
                                  mode="promise_in_bounds")
        return out + jnp.sum(new_ef[:8])

    return chain


def _hier_chain(upto: str, n: int, keep: int, cfg, axis_name: str = "data"):
    """Stage ladder for the HIERARCHICAL transport (transport=
    'hierarchical'): mag -> threshold -> pack (the shipped
    `wire._select_pack` dispatch + scatter the dense
    contribution) -> ici_reduce (intra-pod dense psum) -> recompress (pod
    union pack + per-chip slab slice) -> dcn_route (the grouped owner-
    sharded exchange across pods) -> return (the second intra-pod psum
    summing disjoint slab partials) -> ef.  Mirrors ops/wire._hier_combine
    — update both together.  Run with --devices >= dp_pods*2 (forced host
    devices) so the grouped collectives exist; on fewer devices than pods
    the plan constructor raises."""
    from tpu_compressed_dp.ops import wire_sharded

    def chain(flat: jax.Array):
        mag = jnp.abs(flat).astype(jnp.float32)
        out = jnp.sum(mag[:8])
        if upto == "mag":
            return out
        t = kernels.topk_threshold(mag, keep)
        out = out + t
        if upto == "threshold":
            return out
        vals, idx, _cnt = wire._select_pack(flat, mag, t, keep)
        contrib = jnp.zeros((n,), flat.dtype).at[idx].set(
            vals, indices_are_sorted=True, unique_indices=True,
            mode="promise_in_bounds")
        out = out + jnp.sum(contrib[:8])
        if upto == "pack":
            return out
        world = jax.lax.psum(1, axis_name)
        plan = wire_sharded.make_hier_plan(
            n, keep, world, cfg.dp_pods, cfg.hier_route_factor_ici,
            cfg.hier_route_factor_dcn)
        pods, chips = plan.pods, plan.chips
        ici_groups, dcn_groups = wire_sharded.hier_axis_groups(world, pods)
        pod_sum = (jax.lax.psum(contrib, axis_name,
                                axis_index_groups=ici_groups)
                   if chips > 1 else contrib)
        out = out + jnp.sum(pod_sum[:8])
        if upto == "ici_reduce":
            return out
        cap = plan.cap_union
        mask = pod_sum != 0
        nnz = jnp.sum(mask, dtype=jnp.int32)
        uidx = wire.packed_indices_from_mask(mask, cap)
        uvalid = (jnp.arange(1, cap + 1, dtype=jnp.int32)
                  <= jnp.minimum(nnz, cap))
        uvals = jnp.where(
            uvalid, pod_sum.at[uidx].get(mode="promise_in_bounds"), 0.0)
        uidx = jnp.where(uvalid, uidx, 0)
        c_rank = jax.lax.axis_index(axis_name) % chips
        s_vals = jax.lax.dynamic_slice_in_dim(
            uvals, c_rank * plan.slab, plan.slab)
        s_idx = jax.lax.dynamic_slice_in_dim(
            uidx, c_rank * plan.slab, plan.slab)
        s_valid = jax.lax.dynamic_slice_in_dim(
            uvalid, c_rank * plan.slab, plan.slab)
        out = out + jnp.sum(s_vals[:8])
        if upto == "recompress":
            return out
        dense_u, _, _, _, _ = wire_sharded.sharded_combine(
            s_vals, s_idx, plan.dcn, axis_name, valid=s_valid,
            axis_index_groups=dcn_groups)
        partial = dense_u[:n]
        out = out + jnp.sum(partial[:8])
        if upto == "dcn_route":
            return out
        total = (jax.lax.psum(partial, axis_name,
                              axis_index_groups=ici_groups)
                 if chips > 1 else partial)
        out = out + jnp.sum(total[:8]) / world
        if upto == "return":
            return out
        new_ef = flat.at[idx].set(0, indices_are_sorted=True,
                                  unique_indices=True,
                                  mode="promise_in_bounds")
        return out + jnp.sum(new_ef[:8])

    return chain


def _dispatch_chain(upto: str, n: int, keep: int, axis_name: str = "data"):
    """Ladder over the SHIPPED select+pack dispatch (`wire._select_pack`):
    one rung covers select+pack+gather, because that is exactly what the
    fused kernel collapses.  Under ``pallas off`` the rung lowers to the
    XLA mask -> `packed_indices_from_mask` -> `_sorted_gather` chain; under
    auto/force it is one `kernels.fused_select_pack` call — so timing the
    SAME ladder under both modes prices the toggle on identical stage
    boundaries (the `--compare` table)."""

    def chain(flat: jax.Array):
        mag = jnp.abs(flat).astype(jnp.float32)
        out = jnp.sum(mag[:8])
        if upto == "mag":
            return out
        t = kernels.topk_threshold(mag, keep)
        out = out + t
        if upto == "threshold":
            return out
        vals, idx, count = wire._select_pack(flat, mag, t, keep)
        out = (out + jnp.sum(vals[:8])
               + jnp.sum(idx[:8].astype(jnp.float32))
               + count.astype(jnp.float32))
        if upto == "select_pack":
            return out
        world = jax.lax.psum(1, axis_name)
        g_vals = wire._all_gather(vals, axis_name)
        g_idx = wire._all_gather(idx, axis_name)
        dense = wire._scatter_combine(flat.shape, flat.dtype, g_idx, g_vals,
                                      world)
        out = out + jnp.sum(dense[:8])
        if upto == "combine":
            return out
        new_ef = flat.at[idx].set(0, indices_are_sorted=True,
                                  unique_indices=True,
                                  mode="promise_in_bounds")
        return out + jnp.sum(new_ef[:8])

    return chain


STAGES = ["mag", "threshold", "pack", "gather", "combine", "ef"]
DISPATCH_STAGES = ["mag", "threshold", "select_pack", "combine", "ef"]
SHARDED_STAGES = ["mag", "threshold", "select_pack", "route", "reduce",
                  "return", "ef"]
HIER_STAGES = ["mag", "threshold", "pack", "ici_reduce", "recompress",
               "dcn_route", "return", "ef"]


def time_fn(fn, x, iters: int, warmup_s: float = 3.0):
    """Round-4 discipline: time-based warmup with a value fetch per burst
    (`jax.device_get` is the barrier; `block_until_ready` is not on axon)."""
    t_end = time.time() + warmup_s
    while time.time() < t_end:
        jax.device_get(fn(x))
    t0 = time.time()
    for _ in range(iters):
        out = fn(x)
    jax.device_get(out)
    return (time.time() - t0) / iters


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=125_000_000)
    ap.add_argument("--ratio", type=float, default=0.01)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--subs", action="store_true",
                    help="also profile packed_indices_from_mask sub-stages")
    ap.add_argument("--pack2", action="store_true",
                    help="run the (negative-result) full-scatter formulation")
    ap.add_argument("--compare", action="store_true",
                    help="price the fused-kernel toggle: time the shipped "
                         "_select_pack ladder under pallas off AND "
                         "--pallas_mode, print XLA vs Pallas columns per "
                         "stage (intended home: the TPU chip — forcing "
                         "off-TPU runs kernels interpreted, which is a "
                         "correctness rehearsal, not a timing)")
    ap.add_argument("--pallas_mode", default="force",
                    choices=["auto", "force"],
                    help="the non-off column of --compare")
    ap.add_argument("--transport", default="allgather",
                    choices=["allgather", "sharded", "hierarchical"],
                    help="profile the flat all_gather combine, the "
                         "owner-sharded route/reduce/return chain, or the "
                         "two-level ici-reduce/recompress/dcn-route ladder")
    ap.add_argument("--devices", type=int, default=1,
                    help="mesh size for the ladder (sharded bucket geometry "
                         "scales with W; >1 needs forced host devices)")
    ap.add_argument("--shard_route_factor", type=float, default=1.25)
    ap.add_argument("--shard_return_factor", type=float, default=1.25)
    ap.add_argument("--dp_pods", type=int, default=2,
                    help="hierarchical: DCN axis of the dp_pods x dp_chips "
                         "virtual mesh (must divide --devices)")
    ap.add_argument("--hier_route_factor_ici", type=float, default=1.25)
    ap.add_argument("--hier_route_factor_dcn", type=float, default=1.25)
    args = ap.parse_args(argv)

    n = args.n
    keep = compressors.topk_keep_count(n, args.ratio)
    mesh = Mesh(np.array(jax.devices()[:args.devices]), ("data",))
    x = jax.device_put(
        jax.random.normal(jax.random.key(args.seed), (n,), jnp.float32))

    if args.transport == "sharded":
        from tpu_compressed_dp.parallel.dp import CompressionConfig

        cfg = CompressionConfig(
            method="topk", mode="wire", transport="sharded", ratio=args.ratio,
            shard_route_factor=args.shard_route_factor,
            shard_return_factor=args.shard_return_factor)
        stages = SHARDED_STAGES
        build = lambda st: _sharded_chain(st, n, keep, cfg)
    elif args.transport == "hierarchical":
        from tpu_compressed_dp.parallel.dp import CompressionConfig

        cfg = CompressionConfig(
            method="topk", mode="wire", transport="hierarchical",
            ratio=args.ratio, dp_pods=args.dp_pods,
            hier_route_factor_ici=args.hier_route_factor_ici,
            hier_route_factor_dcn=args.hier_route_factor_dcn)
        stages = HIER_STAGES
        build = lambda st: _hier_chain(st, n, keep, cfg)
    else:
        stages = STAGES
        build = lambda st: _stage_chain(st, n, keep)

    print(f"# wire Top-K stage ladder [{args.transport}]: n={n} keep={keep} "
          f"({100*keep/n:.2f}%) device={jax.devices()[0].platform} "
          f"W={args.devices}")
    prev = 0.0
    rows = []
    for st in stages:
        fn = jax.jit(shard_map(
            build(st),
            mesh=mesh, in_specs=P(), out_specs=P()))
        dt = time_fn(fn, x, args.iters)
        rows.append((st, dt * 1e3, (dt - prev) * 1e3))
        print(f"{st:10s} cumulative {dt*1e3:8.2f} ms   stage {max((dt-prev)*1e3, 0.0):8.2f} ms")
        prev = dt
    total = rows[-1][1]
    print(f"# chain total {total:.2f} ms; element-granular random-access "
          f"stages = gather+combine+ef")
    if args.subs:
        prev = rows[1][1] / 1e3   # threshold rung is the sub-ladder's base
        print("# pack sub-stages (cumulative from threshold rung):")
        for st in PACK_SUBS:
            fn = jax.jit(shard_map(_pack_sub_chain(st, n, keep),
                                   mesh=mesh, in_specs=P(), out_specs=P()))
            dt = time_fn(fn, x, args.iters)
            print(f"{st:14s} cumulative {dt*1e3:8.2f} ms   "
                  f"stage {max((dt-prev)*1e3, 0.0):8.2f} ms")
            prev = dt
    if args.pack2:
        fn = jax.jit(shard_map(_pack_scatter_chain(n, keep),
                               mesh=mesh, in_specs=P(), out_specs=P()))
        dt = time_fn(fn, x, args.iters)
        print(f"pack2-scatter-formulation full chain {dt*1e3:8.2f} ms "
              f"(vs ladder total {total:.2f} ms)")
    if args.compare:
        # same ladder, two dispatch modes: re-jit per mode because the
        # pallas decision is made at trace time inside _select_pack
        cols = {}
        prev_mode = kernels.pallas_mode()
        try:
            for mode in ("off", args.pallas_mode):
                kernels.set_pallas_mode(mode)
                cum = []
                for st in DISPATCH_STAGES:
                    fn = jax.jit(shard_map(_dispatch_chain(st, n, keep),
                                           mesh=mesh, in_specs=P(),
                                           out_specs=P()))
                    cum.append(time_fn(fn, x, args.iters) * 1e3)
                cols[mode] = cum
        finally:
            kernels.set_pallas_mode(prev_mode)
        xla, pal = cols["off"], cols[args.pallas_mode]
        print(f"# pallas compare [_select_pack ladder]: per-stage ms, "
              f"pallas=off vs pallas={args.pallas_mode}")
        print(f"{'stage':12s} {'xla_ms':>9s} {'pallas_ms':>9s} "
              f"{'delta_ms':>9s}")
        px = pp = 0.0
        for st, cx, cp in zip(DISPATCH_STAGES, xla, pal):
            sx, sp = max(cx - px, 0.0), max(cp - pp, 0.0)
            print(f"{st:12s} {sx:9.2f} {sp:9.2f} {sp - sx:+9.2f}")
            px, pp = cx, cp
        print(f"{'total':12s} {xla[-1]:9.2f} {pal[-1]:9.2f} "
              f"{pal[-1] - xla[-1]:+9.2f}")
    return rows


if __name__ == "__main__":
    main()
