#!/usr/bin/env python
"""Tail-and-apply consumer for the delta state stream (model-push channel).

A training job armed with ``--stream_dir`` appends Top-K parameter deltas
(plus periodic full keyframes) to a shared directory on the compressed wire
codec — see :mod:`tpu_compressed_dp.stream`.  This tool is the read-only
side of that channel: an eval or serving replica tails the segment stream,
applies each verified segment to its host-side reconstruction, and
publishes materialised snapshots — no Orbax, no JAX, no training imports.

  * default / ``--poll N`` — poll the stream every N seconds (default 5),
    apply new segments as they commit, write the heartbeat after every
    scan.  Runs until killed; Ctrl-C exits 0.
  * ``--once`` — catch up once and exit (cron-friendly).  Exit 0 = caught
    up, 1 = the stream is unusable (no verifiable keyframe — fall back
    to a full checkpoint), 2 = not (yet) a stream directory.
  * ``--snapshot_dir`` — materialise ``snapshot-<step>.npz`` (one array
    per parameter path) whenever the reconstruction is *exact* — anchored
    at a window boundary AND caught up to the producer's head — so a
    serving process only ever loads bitwise-faithful parameters.
  * ``--heartbeat`` — JSON liveness file (``stream_lag_s``, applied
    seq/step, corrupt-segment count) for ``tools/watchdog.py --check``.

All published files go through the shared-dir protocol (write a
``*.<pid>.tmp`` sibling, ``os.replace`` into place) — concurrent readers
never see a torn snapshot or heartbeat::

    python tools/stream_serve.py /runs/lm17/stream --once --snapshot_dir /serve
    python tools/stream_serve.py /runs/lm17/stream --poll 10 --heartbeat hb.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

import numpy as np

from tpu_compressed_dp.stream.reader import StreamReader
from tpu_compressed_dp.stream.store import StreamCorrupt, is_stream_dir


def _publish(path: str, data: bytes) -> None:
    """Atomic shared-dir write: tmp sibling + os.replace (TCDP102)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _write_heartbeat(path: str, reader: StreamReader) -> None:
    m = reader.metrics()
    hb = {
        "ts": time.time(),
        "applied_seq": int(reader.applied_seq),
        "applied_step": int(reader.applied_step),
        "exact": bool(reader.exact),
        "stream_lag_s": float(m["stream/lag_s"]),
        "stream_corrupt_segments": float(m["stream/corrupt_segments"]),
        "stream_bytes_read": float(reader.bytes_read),
    }
    _publish(path, json.dumps(hb).encode("utf-8"))


def _write_snapshot(directory: str, reader: StreamReader) -> str:
    """Materialise the current (exact) reconstruction as one npz, one
    array per parameter path, published atomically."""
    os.makedirs(directory, exist_ok=True)
    params = reader.params_dict()
    path = os.path.join(directory, f"snapshot-{int(reader.applied_step)}.npz")
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **params)
    os.replace(tmp, path)
    return path


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("dir", help="delta stream directory (harness --stream_dir)")
    p.add_argument("--once", action="store_true",
                   help="catch up once and exit (cron-friendly)")
    p.add_argument("--poll", type=float, default=5.0,
                   help="seconds between stream scans (default 5)")
    p.add_argument("--snapshot_dir", type=str, default=None,
                   help="publish snapshot-<step>.npz here whenever the "
                        "reconstruction is exact (window boundary + caught "
                        "up to head)")
    p.add_argument("--heartbeat", type=str, default=None,
                   help="JSON liveness file for watchdog --check "
                        "(stream_lag_s et al.), rewritten after every scan")
    args = p.parse_args(argv)

    if not os.path.isdir(args.dir):
        print(f"stream_serve: no such directory: {args.dir}")
        return 2
    reader = StreamReader(args.dir)
    last_snapshot_step = None
    while True:
        try:
            applied = reader.catch_up()
        except StreamCorrupt as err:
            # no verifiable keyframe anywhere: this stream cannot seed a
            # consumer — the caller falls back to a full checkpoint
            print(f"stream_serve: UNUSABLE: {err}")
            return 1
        if applied:
            print(f"stream_serve: applied {applied} segment(s), "
                  f"seq={reader.applied_seq} step={reader.applied_step} "
                  f"exact={reader.exact}")
        if (args.snapshot_dir and reader.exact
                and reader.applied_step != last_snapshot_step):
            out = _write_snapshot(args.snapshot_dir, reader)
            last_snapshot_step = reader.applied_step
            print(f"stream_serve: snapshot {out}")
        if args.heartbeat:
            _write_heartbeat(args.heartbeat, reader)
        if args.once:
            if not is_stream_dir(args.dir):
                print(f"stream_serve: not a stream dir (yet): {args.dir}")
                return 2
            return 0
        try:
            time.sleep(max(args.poll, 0.1))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
