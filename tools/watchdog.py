#!/usr/bin/env python
"""Heartbeat watchdog — the check-only half of the ROADMAP watchdog item.

Reads the liveness file a harness writes under ``--heartbeat`` (payload:
``ts``, ``step``, ``last_good_step``, and the telemetry snapshot the
observability layer added — step rate, p95 step latency) and exits nonzero
when the run is unhealthy, so a cron job / systemd timer / supervisor can
alert or relaunch:

  exit 0  healthy
  exit 1  unhealthy (stale / wedged / stalled; reasons on stdout)
  exit 2  heartbeat missing or unreadable

Checks (see :func:`tpu_compressed_dp.utils.resilience.check_heartbeat`):

  * **stale** — ``ts`` older than ``--max_age``: process dead or hung.
  * **wedged** — ``step - last_good_step > --max_wedge``: alive but every
    step is being vetoed by the step guard (the failure liveness alone
    cannot see; pair with ``--guard``).
  * **stalled** — telemetry ``steps_per_sec`` below ``--min_step_rate``:
    alive and applying updates, but crawling.

Usage::

    python tools/watchdog.py --check --heartbeat /path/hb.json
    python tools/watchdog.py --check --heartbeat hb.json \\
        --max_age 120 --max_wedge 200 --min_step_rate 0.01

The auto-relaunch half (acting on this exit code) remains a ROADMAP open
item; this tool deliberately only observes.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from tpu_compressed_dp.utils.resilience import check_heartbeat, read_heartbeat


def run_check(args) -> int:
    # single read: passing the parsed record into check_heartbeat keeps the
    # verdict and the printed payload consistent even if the harness's
    # atomic os.replace lands mid-check
    hb = read_heartbeat(args.heartbeat)
    if hb is None:
        print(f"watchdog: MISSING {args.heartbeat}")
        return 2
    problems = check_heartbeat(
        args.heartbeat,
        max_age_s=args.max_age,
        max_wedge_steps=args.max_wedge,
        min_steps_per_sec=args.min_step_rate,
        hb=hb,
    )
    if problems:
        for pr in problems:
            print(f"watchdog: UNHEALTHY: {pr}")
        return 1
    tele = hb.get("telemetry") or {}
    rate = tele.get("steps_per_sec")
    print("watchdog: healthy "
          f"(step={hb.get('step')}, last_good_step={hb.get('last_good_step')}"
          + (f", {rate:.3g} steps/s" if isinstance(rate, (int, float)) else "")
          + ")")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--check", action="store_true", required=True,
                   help="run the health check (the only mode; the relaunch "
                        "half is a ROADMAP open item)")
    p.add_argument("--heartbeat", type=str, required=True,
                   help="heartbeat JSON path (harness --heartbeat)")
    p.add_argument("--max_age", type=float, default=60.0,
                   help="seconds before a heartbeat counts as stale "
                        "(choose > the harness --heartbeat_interval)")
    p.add_argument("--max_wedge", type=int, default=None,
                   help="max steps last_good_step may trail the attempt "
                        "counter (default: no wedge check)")
    p.add_argument("--min_step_rate", type=float, default=None,
                   help="min telemetry steps/sec (default: no stall check)")
    return run_check(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
