#!/usr/bin/env python
"""Heartbeat watchdog — check and (now) relaunch halves of the ROADMAP
watchdog item.

``--check`` reads the liveness file a harness writes under ``--heartbeat``
(payload: ``ts``, ``step``, ``last_good_step``, and the telemetry snapshot
the observability layer added — step rate, p95 step latency) and exits
nonzero when the run is unhealthy, so a cron job / systemd timer /
supervisor can alert or relaunch:

  exit 0  healthy
  exit 1  unhealthy (stale / wedged / stalled; reasons on stdout)
  exit 2  heartbeat missing or unreadable

Checks (see :func:`tpu_compressed_dp.utils.resilience.check_heartbeat`):

  * **stale** — ``ts`` older than ``--max_age``: process dead or hung.
  * **wedged** — ``step - last_good_step > --max_wedge``: alive but every
    step is being vetoed by the step guard (the failure liveness alone
    cannot see; pair with ``--guard``).
  * **stalled** — telemetry ``steps_per_sec`` below ``--min_step_rate``:
    alive and applying updates, but crawling.
  * **slow tail** — telemetry ``step_p95_ms`` above ``--max_step_p95_ms``:
    the mean rate still passes but the tail latency regressed past the
    run's budget (set it from the digital twin's modeled step time, e.g.
    the matching ``benchmarks/perf_pins.json`` pin x 1.1 — the perf gate
    enforced live).
  * **checkpoint-stale** — heartbeat ``ckpt_age_s`` (plus the heartbeat's
    own age) exceeds ``--max_ckpt_age``: the run is making progress it
    could not recover — a crash now loses that much work.
  * **stream-stale** — heartbeat ``stream_lag_s`` (plus the heartbeat's
    own age) exceeds ``--max_stream_lag``: the delta state stream stopped
    advancing — warm rejoin and serving consumers are going stale.
  * **straggler** — heartbeat ``straggler_skew_s`` (the flight recorder's
    live cross-rank step-time skew) exceeds ``--max_straggler_skew``: one
    rank is pacing the whole world's collectives.

``--relaunch`` is the acting half: it supervises the training command given
after ``--``, runs the SAME health check every ``--interval`` seconds
(after a ``--grace`` warm-up so the first heartbeat can appear), and on an
unhealthy/missing verdict kills the child (if still alive — a wedged run is
alive but useless), waits out a capped exponential backoff, and respawns.
A healthy check resets the backoff; a clean child exit (rc 0) ends
supervision; after ``--max_relaunches`` restarts it gives up with the
child's last exit code (or 1).  The restart budget is CONSECUTIVE — any
healthy check refills it — so a long-lived run that crashes once a day is
not eventually abandoned.

A child that exits ``PREEMPT_EXIT`` (75) respawns immediately — no
backoff, no budget burn — but that free pass is rate-capped: more than
``--max_preempts`` preempt exits within ``--preempt_window`` seconds is a
preempt STORM (a scheduler or broken environment preempting in a tight
loop) and is handled like any unhealthy verdict.

With ``--elastic_dir`` the relaunch is ELASTIC-aware: every spawn exports
``TCDP_RESTART_COUNT`` (the child's heartbeat incarnation) plus, when the
rendezvous directory holds a committed world epoch, the epoch and
coordinator address (``TCDP_RENDEZVOUS_EPOCH``/``TCDP_RENDEZVOUS_ADDR``)
— so a restarted host rejoins the RUNNING world's readmit barrier instead
of forming a fresh one (train/rendezvous.py).  A child that parks on its
join deadline exits nonzero; the watchdog's backoff is the retry loop.

Usage::

    python tools/watchdog.py --check --heartbeat /path/hb.json
    python tools/watchdog.py --check --heartbeat hb.json \\
        --max_age 120 --max_wedge 200 --min_step_rate 0.01
    python tools/watchdog.py --relaunch --heartbeat hb.json \\
        --interval 30 --grace 120 --max_relaunches 5 -- \\
        python -m tpu_compressed_dp.harness.dawn --synthetic --guard \\
            --heartbeat hb.json
"""

from __future__ import annotations

import argparse
import collections
import os
import subprocess
import sys
import time
from typing import Callable, List, Optional

from tpu_compressed_dp.utils.resilience import (PREEMPT_EXIT, check_heartbeat,
                                                read_heartbeat,
                                                spawn_supervised)


def run_check(args) -> int:
    # single read: passing the parsed record into check_heartbeat keeps the
    # verdict and the printed payload consistent even if the harness's
    # atomic os.replace lands mid-check
    hb = read_heartbeat(args.heartbeat)
    if hb is None:
        print(f"watchdog: MISSING {args.heartbeat}")
        return 2
    problems = check_heartbeat(
        args.heartbeat,
        max_age_s=args.max_age,
        max_wedge_steps=args.max_wedge,
        min_steps_per_sec=args.min_step_rate,
        max_step_p95_ms=args.max_step_p95_ms,
        max_ckpt_age_s=args.max_ckpt_age,
        max_stream_lag_s=args.max_stream_lag,
        max_straggler_skew_s=args.max_straggler_skew,
        hb=hb,
    )
    if problems:
        for pr in problems:
            print(f"watchdog: UNHEALTHY: {pr}")
        return 1
    tele = hb.get("telemetry") or {}
    rate = tele.get("steps_per_sec")
    print("watchdog: healthy "
          f"(step={hb.get('step')}, last_good_step={hb.get('last_good_step')}"
          + (f", {rate:.3g} steps/s" if isinstance(rate, (int, float)) else "")
          + ")")
    return 0


def kill_child(child, term_timeout_s: float = 10.0) -> None:
    """Terminate a (possibly wedged) child: SIGTERM, bounded wait, SIGKILL.
    A no-op when the child already exited."""
    if child.poll() is not None:
        return
    child.terminate()
    try:
        child.wait(timeout=term_timeout_s)
    except Exception:
        child.kill()
        child.wait()


def supervise(spawn: Callable[[], "subprocess.Popen"],
              check: Callable[[], int],
              *,
              interval_s: float,
              grace_s: float,
              max_relaunches: int,
              backoff_s: float = 5.0,
              backoff_cap_s: float = 300.0,
              sleep: Callable[[float], None] = time.sleep,
              kill: Callable[..., None] = kill_child,
              log: Callable[[str], None] = print,
              max_checks: Optional[int] = None,
              preempt_exit_code: Optional[int] = PREEMPT_EXIT,
              max_preempts: Optional[int] = 8,
              preempt_window_s: float = 600.0) -> int:
    """The relaunch decision loop, with every side effect injectable so the
    unit test can drive it against a fake child and a scripted check
    sequence (tests/test_observability.py::TestWatchdogRelaunch).

    Protocol per tick: sleep ``interval_s``; a child that exited cleanly
    (rc 0) ends supervision with 0; a child that exited with
    ``preempt_exit_code`` (the harness's PREEMPT_EXIT after a SIGTERM
    emergency save) is respawned IMMEDIATELY — no backoff and no burn of
    the consecutive budget, preemption being the environment's fault, not
    the run's; otherwise consult ``check`` (the heartbeat verdict — 0
    healthy / 1 unhealthy / 2 missing).  Healthy
    resets the consecutive-restart counter (and so the backoff).  Unhealthy
    or missing: if the consecutive budget is spent, give up (child's exit
    code, else 1); otherwise kill whatever is left of the child, back off
    ``backoff_s * 2^consecutive`` capped at ``backoff_cap_s``, respawn, and
    re-enter the grace period (no checks for ``grace_s`` — a fresh process
    needs time to write its first heartbeat).

    **Preempt-storm guard**: free preempt respawns are rate-capped — more
    than ``max_preempts`` preempt exits inside a sliding
    ``preempt_window_s`` window stops counting as "the environment's
    fault" (a scheduler or broken env preempting in a tight loop would
    otherwise respawn forever, never touching the budget) and falls
    through to the unhealthy path: consecutive budget, capped backoff,
    give-up with the child's exit code.  ``max_preempts=None`` disables
    the cap.  The window clock is the supervisor's own cumulative slept
    time (deterministic under the injected ``sleep``).
    """
    child = spawn()
    consecutive = 0
    grace_until = grace_s  # relative clock: ticks since (re)launch
    ticks_since_launch = 0.0
    slept = 0.0  # cumulative slept time: the storm window's clock
    preempts: "collections.deque[float]" = collections.deque()
    checks = 0
    try:
        while True:
            sleep(interval_s)
            slept += interval_s
            ticks_since_launch += interval_s
            if child.poll() is not None and child.returncode == 0:
                log("watchdog: child exited cleanly; supervision done")
                return 0
            storm = False
            if (child.poll() is not None and preempt_exit_code is not None
                    and child.returncode == preempt_exit_code):
                preempts.append(slept)
                while preempts and slept - preempts[0] > preempt_window_s:
                    preempts.popleft()
                if max_preempts is None or len(preempts) <= max_preempts:
                    # preemption is not a failure: the child cut an
                    # emergency checkpoint and exited deliberately.
                    # Respawn NOW — no backoff, no consecutive-budget
                    # burn, no health check consumed (the freed capacity
                    # may already be back)
                    log(f"watchdog: child preempted "
                        f"(exit {preempt_exit_code}); relaunching "
                        "immediately")
                    child = spawn()
                    ticks_since_launch = 0.0
                    continue
                storm = True
                log(f"watchdog: preempt storm: {len(preempts)} preempt "
                    f"exits within {preempt_window_s:g}s (cap "
                    f"{max_preempts}) — treating as unhealthy")
            if not storm and ticks_since_launch < grace_until:
                continue  # fresh (re)launch: let the heartbeat appear
            rc = 1 if storm else check()
            checks += 1
            if rc == 0:
                consecutive = 0
            else:
                if consecutive >= max_relaunches:
                    died_rc = child.poll()
                    kill(child)
                    # a positive rc is the child's own failure code;
                    # killed-by-us (negative) or alive-but-wedged reports 1
                    code = (died_rc if died_rc is not None and died_rc > 0
                            else 1)
                    log(f"watchdog: giving up after {consecutive} "
                        f"consecutive relaunches (exit {code})")
                    return int(code)
                delay = min(backoff_s * (2.0 ** consecutive), backoff_cap_s)
                log(f"watchdog: unhealthy (check rc={rc}); relaunch "
                    f"#{consecutive + 1}/{max_relaunches} after {delay:.0f}s "
                    "backoff")
                kill(child)
                sleep(delay)
                slept += delay
                child = spawn()
                consecutive += 1
                ticks_since_launch = 0.0
            if max_checks is not None and checks >= max_checks:
                # test hook: bounded supervision
                kill(child)
                return 0
    except BaseException:
        # Ctrl-C or an unexpected check/spawn error must not orphan the
        # training child: a detached run keeps writing the heartbeat, and
        # a restarted watchdog would then supervise a process it never
        # spawned (both reporting healthy on the same file).
        kill(child)
        raise


def run_relaunch(args, cmd: List[str]) -> int:
    if not cmd:
        print("watchdog: --relaunch needs the training command after `--`")
        return 2

    # seed from our own environment so a re-executed watchdog keeps the
    # child's incarnation monotone instead of resetting it to 0
    launches = {"n": int(os.environ.get("TCDP_RESTART_COUNT", "0") or 0)}

    def spawn():
        # spawn_supervised composes the child env: TCDP_RESTART_COUNT
        # seeds the child Heartbeat's incarnation (strictly larger per
        # respawn, so a relaunched worker's heartbeats are
        # distinguishable from its previous life's stale file), and with
        # --elastic_dir the committed-epoch rejoin hint lands the child
        # in the RUNNING world's join barrier
        # (train/rendezvous.maybe_rejoin_from_env) instead of a fresh one
        child = spawn_supervised(
            cmd, restart_count=launches["n"],
            elastic_dir=getattr(args, "elastic_dir", None),
            log=lambda s: print(f"watchdog: {s}"))
        launches["n"] += 1
        print(f"watchdog: launching: {' '.join(cmd)}")
        return child

    return supervise(
        spawn, lambda: run_check(args),
        interval_s=args.interval, grace_s=args.grace,
        max_relaunches=args.max_relaunches,
        backoff_s=args.backoff, backoff_cap_s=args.backoff_cap,
        max_preempts=(None if args.max_preempts <= 0 else args.max_preempts),
        preempt_window_s=args.preempt_window)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="one-shot health check (exit 0/1/2)")
    mode.add_argument("--relaunch", action="store_true",
                      help="supervise the command after `--`: restart on "
                           "wedge/death with capped backoff")
    p.add_argument("--heartbeat", type=str, required=True,
                   help="heartbeat JSON path (harness --heartbeat)")
    p.add_argument("--max_age", type=float, default=60.0,
                   help="seconds before a heartbeat counts as stale "
                        "(choose > the harness --heartbeat_interval)")
    p.add_argument("--max_wedge", type=int, default=None,
                   help="max steps last_good_step may trail the attempt "
                        "counter (default: no wedge check)")
    p.add_argument("--min_step_rate", type=float, default=None,
                   help="min telemetry steps/sec (default: no stall check)")
    p.add_argument("--max_step_p95_ms", type=float, default=None,
                   help="max telemetry p95 step latency in ms — budget it "
                        "from the twin's modeled step time (perf pin x "
                        "tolerance); default: no tail-latency check")
    p.add_argument("--max_ckpt_age", type=float, default=None,
                   help="max seconds since the run's last durable "
                        "checkpoint (heartbeat ckpt_age_s + heartbeat age; "
                        "default: no checkpoint-staleness check)")
    p.add_argument("--max_stream_lag", type=float, default=None,
                   help="max seconds since the last delta-stream segment "
                        "(heartbeat stream_lag_s + heartbeat age; default: "
                        "no stream-staleness check)")
    p.add_argument("--max_straggler_skew", type=float, default=None,
                   help="max cross-rank step-time skew in seconds "
                        "(heartbeat straggler_skew_s, from the flight "
                        "recorder's live phase profiles; default: no "
                        "straggler check)")
    p.add_argument("--interval", type=float, default=30.0,
                   help="relaunch mode: seconds between health checks")
    p.add_argument("--grace", type=float, default=120.0,
                   help="relaunch mode: seconds after a (re)launch before "
                        "checks resume (first heartbeat + compile time)")
    p.add_argument("--max_relaunches", type=int, default=5,
                   help="relaunch mode: consecutive restarts before giving "
                        "up (a healthy check refills the budget)")
    p.add_argument("--backoff", type=float, default=5.0,
                   help="relaunch mode: initial backoff seconds (doubles "
                        "per consecutive restart)")
    p.add_argument("--backoff_cap", type=float, default=300.0,
                   help="relaunch mode: backoff ceiling")
    p.add_argument("--max_preempts", type=int, default=8,
                   help="relaunch mode: preempt-storm guard — more than "
                        "this many PREEMPT_EXIT respawns inside "
                        "--preempt_window seconds counts as unhealthy "
                        "(consecutive budget + backoff) instead of a free "
                        "immediate relaunch; <= 0 disables the cap")
    p.add_argument("--preempt_window", type=float, default=600.0,
                   help="relaunch mode: sliding window (seconds of "
                        "supervisor slept time) for --max_preempts")
    p.add_argument("--elastic_dir", type=str, default=None,
                   help="relaunch mode: the run's shared rendezvous/gossip "
                        "directory (harness --elastic_dir); exports the "
                        "committed world epoch + coordinator address to "
                        "the child so a restarted host REJOINS the running "
                        "world instead of forming a fresh one")
    argv = list(sys.argv[1:] if argv is None else argv)
    # split at the FIRST `--`: left side is parsed STRICTLY (a misspelled
    # watchdog flag is an argparse error, never silently folded into the
    # child command), right side is the training command verbatim
    if "--" in argv:
        cut = argv.index("--")
        argv, cmd = argv[:cut], argv[cut + 1:]
    else:
        cmd = []
    args = p.parse_args(argv)
    if args.check:
        if cmd:
            p.error("--check takes no training command (drop the `-- ...`)")
        return run_check(args)
    return run_relaunch(args, cmd)


if __name__ == "__main__":
    sys.exit(main())
