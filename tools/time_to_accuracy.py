"""Projected multi-chip time-to-accuracy — the Fig. 3/4 synthesis (VERDICT r3 #2).

The paper's headline is accuracy AND time (the reference quoted
minutes-to-93%-top-5, `IMAGENET/train.py:55-136`).  Single-chip compression is
a pure loss: the convergence grid shows the k=1% EF recipe costs 5x dense's
wall-clock on one chip (more epochs + wire overhead).  The payoff the paper
claims is the W-chip regime where gradient sync rides a link too slow to hide
behind compute.  This tool combines:

  * the convergence grid (``benchmarks/convergence_r*.tsv``): epochs to final
    accuracy per method x k, via the recipes in tools/convergence_sweep.py;
  * measured single-chip step times + wire payload bytes (bench.sweep.run_point
    on the same ResNet-9 / bs 512 / 32px workload, real chip);
  * the method-aware per-chip traffic model
    (``utils/meters.per_chip_traffic_bytes``: ring psum 2(W-1)/W vs
    all_gather (W-1)x)

into projected wall-clock to reach a target test accuracy at W chips over an
ICI-class and a DCN-class link, plus the crossover bandwidth below which each
method beats dense.

Model (assumptions printed into the TSV header):
  * compute-bound scaling: per-chip compute time = measured single-chip step
    time / W (global batch fixed at 512; compression-op overhead is inside
    the measured step and scales down with it — optimistic for the
    model-sized sparsify/pack passes at large W);
  * no compute/comm overlap: t_step(W, bw) = t_compute/W + traffic(W)/bw —
    both dense and compressed pay the full serialisation, so the comparison
    is fair even though absolute numbers are pessimistic;
  * sparsity warm-up (geometric ratio decay, harness ``ratio_for_epoch``)
    scales that epoch's payload by ratio_e/ratio_final: the
    ``effective_sent_frac`` column is the run-averaged sent fraction —
    VERDICT r3 weak #3's "the 1% recipe does not send 1% on average".

Usage:
    python tools/time_to_accuracy.py \
        --convergence benchmarks/convergence_r4.tsv \
        --out benchmarks/time_to_accuracy_r4.tsv
"""

from __future__ import annotations

import argparse
import json
import os
import sys

if __package__ in (None, ""):  # script run: repo root onto sys.path
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (name, bytes/sec per chip).  ICI-class: a v5e-generation inter-chip link
# (hundreds of GB/s; we take 1.6 Tbps bidirectional ~ 100 GB/s of usable
# per-direction ring bandwidth as a round conservative figure).  DCN-class:
# 25 Gbit/s host NIC — the reference's own AWS fabric class
# (`SURVEY.md` §6; its NIC meter measured exactly this link).
BANDWIDTHS = [("ici_100GBps", 100e9), ("dcn_25Gbps", 25e9 / 8)]
WORLDS = [8, 32]

STEPS_PER_EPOCH_DEFAULT = 16384 // 512  # the convergence grid's protocol


def parse_tsv(path):
    rows = []
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines()
                 if ln.strip() and not ln.startswith("#")]
    cols = lines[0].split("\t")
    for ln in lines[1:]:
        rows.append(dict(zip(cols, ln.split("\t"))))
    return rows


def grid_args(label: str):
    """The harness args the convergence grid ran this label with."""
    from tools.convergence_sweep import GRID

    for lab, extra in GRID:
        if lab == label:
            return extra
    return None


def arg_val(extra, flag, default=None):
    for i, a in enumerate(extra):
        if a == flag:
            return extra[i + 1]
    return default


def effective_sent_frac(ratio: float, warmup_epochs: int, epochs: int) -> float:
    """Run-averaged sent fraction under the harness's geometric ratio
    warm-up — integrates the harness's OWN per-epoch schedule
    (``dawn.warmup_ratio_for_epoch``) so the projection can never drift from
    what the convergence runs actually sent."""
    from tpu_compressed_dp.harness.dawn import warmup_ratio_for_epoch

    if warmup_epochs <= 0 or ratio >= 1.0:
        return ratio
    total = sum(
        warmup_ratio_for_epoch(e, ratio=ratio, warmup_epochs=warmup_epochs,
                               method="topk")
        for e in range(epochs))
    return total / epochs


def measure_row(label: str, extra, cache: dict, steps: int, warmup: int):
    """Single-chip step time + payload split for this grid point's config,
    on the ResNet-9 bs-512 32px workload (the convergence grid's model).

    Returns ``(record, was_cache_hit)``; the cache key includes the
    measurement parameters AND a hash of the grid point's args, so a
    --steps/--warmup change — or a recipe change under an unchanged label
    (ADVICE r4) — re-measures instead of silently reusing stale numbers."""
    import hashlib

    args_h = hashlib.md5(json.dumps(list(extra)).encode()).hexdigest()[:10]
    key = f"{label}@steps={steps},warmup={warmup},args={args_h}"
    if key in cache:
        return cache[key], True
    from tpu_compressed_dp.bench.sweep import run_point

    method = arg_val(extra, "--method")
    rec = run_point(
        model="resnet9", image_size=32, num_classes=10, batch_size=512,
        method=method,
        granularity=arg_val(extra, "--compress", "layerwise"),
        mode=arg_val(extra, "--mode", "simulate"),
        ratio=float(arg_val(extra, "--ratio", 0.01)),
        threshold=float(arg_val(extra, "--threshold", 1e-3)),
        qstates=int(arg_val(extra, "--qstates", 255)),
        error_feedback="--error_feedback" in extra,
        steps=steps, warmup=warmup,
    )
    cache[key] = rec
    return rec, False


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--convergence", default="benchmarks/convergence_r3.tsv")
    ap.add_argument("--out", default="benchmarks/time_to_accuracy_r4.tsv")
    ap.add_argument("--target", type=float, default=0.95)
    ap.add_argument("--dense_label", default="dense-step",
                    help="baseline row label (the step-schedule dense control)")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--measure_cache", default="benchmarks/.tta_measure_cache.json")
    ap.add_argument("--sensitivity_out", default=None,
                    help="also write a latency x overlap sensitivity TSV "
                         "(VERDICT r4 #8): speedups vs dense at overlap in "
                         "{0, 0.5, 1} and per-collective latency in "
                         "{1, 10, 100} us")
    args = ap.parse_args(argv)

    conv = parse_tsv(args.convergence)
    cache = {}
    if os.path.exists(args.measure_cache):
        with open(args.measure_cache) as f:
            cache = json.load(f)

    steps_pe = STEPS_PER_EPOCH_DEFAULT

    # --- assemble per-row physics -----------------------------------------
    physics = []  # (row, rec, epochs, eff_frac, tc_total_s, bytes_fn)
    for row in conv:
        extra = grid_args(row["label"])
        if extra is None:
            print(f"## skip {row['label']}: not in GRID", file=sys.stderr)
            continue
        rec, hit = measure_row(row["label"], extra, cache, args.steps,
                               args.warmup)
        if not hit:
            with open(args.measure_cache, "w") as f:
                json.dump(cache, f)
        epochs = int(row["epochs"])
        ratio = float(arg_val(extra, "--ratio", 1.0) or 1.0)
        n_w = int(arg_val(extra, "--ratio_warmup_epochs", 0) or 0)
        eff = effective_sent_frac(ratio, n_w, epochs) if ratio < 1.0 else None
        # warm-up epochs send a LARGER payload: scale total traffic by the
        # run-average ratio over the final ratio
        traffic_scale = (eff / ratio) if eff is not None else 1.0
        psum_b = rec.get("payload_mb_psum", rec.get("payload_mb_per_step", 0.0)) * 1e6
        ag_b = rec.get("payload_mb_allgather", 0.0) * 1e6
        a2a_b = rec.get("payload_mb_alltoall", 0.0) * 1e6
        if rec.get("transport") == "all_gather" and "payload_mb_psum" not in rec:
            psum_b, ag_b = 0.0, rec["payload_mb_per_step"] * 1e6
        tc_total = epochs * steps_pe * rec["step_ms"] / 1e3  # single-chip s
        physics.append(dict(
            row=row, rec=rec, epochs=epochs, eff=eff,
            traffic_scale=traffic_scale, psum_b=psum_b, ag_b=ag_b,
            a2a_b=a2a_b,
            tc_total=tc_total))

    dense = next((p for p in physics if p["row"]["label"] == args.dense_label),
                 None)
    if dense is None:
        raise SystemExit(f"dense baseline {args.dense_label!r} not in grid")

    from tpu_compressed_dp.utils.meters import per_chip_traffic_bytes

    def totals(p, w):
        """(total compute seconds at W, total per-chip traffic bytes at W)."""
        per_step = per_chip_traffic_bytes(p["psum_b"], p["ag_b"], w,
                                          p.get("a2a_b", 0.0))
        return (p["tc_total"] / w,
                p["epochs"] * steps_pe * per_step * p["traffic_scale"])

    def n_collectives(p):
        """Per-step collective count; measured where the sync engine reports
        it, else the static bucket count a dense 25 MB-bucketed sync would
        issue (dense rows carry no comm stats)."""
        nc = p["rec"].get("num_collectives")
        if nc:
            return float(nc)
        model_bytes = p["rec"].get("dense_mb_per_step", 26.0) * 1e6
        return max(1.0, -(-model_bytes // (25 * 1024 * 1024)))

    def wall_at(p, w, bw, lat_s=0.0, overlap=0.0):
        """Projected seconds-to-target with a per-collective latency term and
        an overlap fraction: comm exposed = max(0, comm - overlap * compute).
        overlap=0 reproduces the original no-overlap model; overlap=1 is
        perfect latency hiding (XLA's scheduler upper bound)."""
        comp, traffic = totals(p, w)
        comm = traffic / bw + p["epochs"] * steps_pe * n_collectives(p) * lat_s
        return comp + max(0.0, comm - overlap * comp)

    cols = ["label", "method", "ratio", "mode", "epochs", "test_acc",
            "converged", "effective_sent_frac", "step_ms_1chip",
            "payload_mb_psum", "payload_mb_allgather",
            "payload_mb_alltoall"]
    for w in WORLDS:
        for name, _ in BANDWIDTHS:
            cols += [f"wall_min_w{w}_{name}", f"speedup_w{w}_{name}"]
        cols += [f"crossover_gbps_w{w}"]

    out_rows = []
    for p in physics:
        row = p["row"]
        r = {
            "label": row["label"], "method": row["method"],
            "ratio": row["ratio"], "mode": row["mode"],
            "epochs": p["epochs"], "test_acc": row["test_acc"],
            "converged": float(row["test_acc"]) >= args.target,
            "effective_sent_frac": (round(p["eff"], 5)
                                    if p["eff"] is not None else ""),
            "step_ms_1chip": p["rec"]["step_ms"],
            "payload_mb_psum": round(p["psum_b"] / 1e6, 4),
            "payload_mb_allgather": round(p["ag_b"] / 1e6, 4),
            "payload_mb_alltoall": round(p.get("a2a_b", 0.0) / 1e6, 4),
        }
        for w in WORLDS:
            a_m, b_m = totals(p, w)
            a_d, b_d = totals(dense, w)
            for name, bw in BANDWIDTHS:
                wall = a_m + b_m / bw
                wall_d = a_d + b_d / bw
                r[f"wall_min_w{w}_{name}"] = round(wall / 60.0, 2)
                r[f"speedup_w{w}_{name}"] = round(wall_d / wall, 3)
            # crossover: bandwidth below which this method's wall-clock beats
            # dense's.  wall_m(bw) = A_m + B_m/bw; compression typically pays
            # more compute (A_m > A_d) to send less (B_m < B_d) — it wins
            # exactly when bw < (B_d - B_m) / (A_m - A_d).
            if p is dense:
                r[f"crossover_gbps_w{w}"] = ""
            elif a_m > a_d and b_m < b_d:
                r[f"crossover_gbps_w{w}"] = round(
                    (b_d - b_m) / (a_m - a_d) * 8 / 1e9, 3)
            elif a_m <= a_d and b_m <= b_d:
                r[f"crossover_gbps_w{w}"] = "always"
            else:
                r[f"crossover_gbps_w{w}"] = "never"
        out_rows.append(r)
        print(json.dumps(r), flush=True)

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        f.write(
            "# Projected multi-chip time-to-accuracy (tools/time_to_accuracy.py).\n"
            f"# target test acc {args.target}; rows with converged=False did NOT\n"
            "# reach it — their wall-clock is to their OWN final accuracy and is\n"
            "# not comparable.  PROJECTION assumptions: compute-bound 1/W step\n"
            "# scaling from the measured single-chip step (global batch 512\n"
            "# fixed), no compute/comm overlap, bandwidth-only link model (no\n"
            "# latency term, so layerwise's per-leaf collectives are billed\n"
            "# free of launch overhead).  traffic = method-aware per-chip bytes\n"
            "# (ring psum 2(W-1)/W, all_gather (W-1)x; utils/meters.py).\n"
            "# crossover_gbps_wW: link bandwidth (Gbit/s per chip) below which\n"
            "# the method's projected wall-clock to target beats dense's at W\n"
            "# chips.  effective_sent_frac: run-averaged sent fraction\n"
            "# including sparsity warm-up epochs (VERDICT r3 weak #3).\n")
        f.write("\t".join(cols) + "\n")
        for r in out_rows:
            f.write("\t".join(str(r[c]) for c in cols) + "\n")
    print(f"wrote {args.out} ({len(out_rows)} rows)", file=sys.stderr)

    if not args.sensitivity_out:
        return

    # --- latency x overlap sensitivity (VERDICT r4 #8) --------------------
    # The headline projection bills layerwise's per-leaf collectives free of
    # launch overhead and assumes zero overlap — both favour compression.
    # This grid stresses both axes: per-collective latency 1/10/100 us and
    # comm/compute overlap 0/0.5/1.  verdict column: "faster"/"slower" when
    # the speedup vs dense stays on the same side of 1.0 across all nine
    # combos, "mixed" when the conclusion depends on the assumption.
    LATS = [1e-6, 10e-6, 100e-6]
    OVERLAPS = [0.0, 0.5, 1.0]
    scols = ["label", "w", "link", "n_collectives_per_step"]
    for ov in OVERLAPS:
        for lat in LATS:
            scols.append(f"speedup_ov{ov:g}_lat{int(lat*1e6)}us")
    scols.append("verdict")
    with open(args.sensitivity_out, "w") as f:
        f.write(
            "# Sensitivity of the time-to-accuracy projection to the two\n"
            "# assumptions the headline table fixes at zero: per-collective\n"
            "# launch latency and comm/compute overlap.  speedup = dense\n"
            "# wall-clock / method wall-clock to the same target, with BOTH\n"
            "# sides paying the same latency and enjoying the same overlap\n"
            "# fraction (exposed comm = max(0, comm - overlap*compute)).\n"
            "# verdict: faster/slower = same side of 1.0 at every combo;\n"
            "# mixed = the conclusion depends on the assumption.\n")
        f.write("\t".join(scols) + "\n")
        for p in physics:
            if p is dense:
                continue
            for w in WORLDS:
                for name, bw in BANDWIDTHS:
                    r = {"label": p["row"]["label"], "w": w, "link": name,
                         "n_collectives_per_step": n_collectives(p)}
                    sps = []
                    for ov in OVERLAPS:
                        for lat in LATS:
                            sp = (wall_at(dense, w, bw, lat, ov)
                                  / wall_at(p, w, bw, lat, ov))
                            r[f"speedup_ov{ov:g}_lat{int(lat*1e6)}us"] = round(sp, 3)
                            sps.append(sp)
                    r["verdict"] = ("faster" if min(sps) > 1.0 else
                                    "slower" if max(sps) < 1.0 else "mixed")
                    f.write("\t".join(str(r[c]) for c in scols) + "\n")
    print(f"wrote {args.sensitivity_out}", file=sys.stderr)


if __name__ == "__main__":
    main()
