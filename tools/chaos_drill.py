#!/usr/bin/env python
"""Chaos drill: run the fault-injection matrix and assert the step guard's
invariants end to end.

What it proves (the ISSUE 3 acceptance criteria, each as a named drill):

  * ``skip_consistency`` — NaN injected into ONE worker's gradients at step k
    => the cross-worker vote vetoes the update everywhere: params, optimizer
    buffers, batch stats and EF residual are bitwise equal to their pre-step
    values, and every other step applies normally.
  * ``comp_hold`` — same, for the stateful compressor path (PowerSGD): the
    warm-start Q factors are held bitwise on the skipped step.
  * ``loss_scale`` — an Inf backs the dynamic loss scale off by
    ``backoff``; ``growth_interval`` consecutive good steps regrow it.
  * ``ef_identity`` — on non-skipped steps the EF identity holds through the
    guarded sync: world-mean(transmitted) + local residual change accounts
    for the full gradient, i.e. ``psum(acc - new_ef)/W == synced`` per
    worker (checked for the simulate and wire+sharded transports).
  * ``poison_control`` — the control arm: the SAME injection with the guard
    OFF poisons the parameters (proves the injection actually fires and the
    guard is what contains it).
  * ``max_skips`` — an every-step injection wedges the run; the host-side
    check raises GuardExceeded once the consecutive-skip streak passes
    ``max_consecutive_skips``.
  * ``crash_recovery`` — a host-crash injection mid-run recovers through
    ``run_with_recovery`` (Orbax restore + replay) to a final state bitwise
    identical to the uncrashed run — chaos is step-counter driven, so the
    replay reproduces the same faults.

Checkpoint drills (the ISSUE 9 acceptance rows — utils/checkpoint.py):

  * ``ckpt_preempt`` — ``crash=preempt`` delivers a REAL self-SIGTERM at
    step N; the loop drains the in-flight async save, cuts an emergency
    checkpoint and the relaunched run resumes to a final state bitwise
    identical to the uninterrupted one (an in-graph NaN injection landing
    after the preemption point proves the replay lines up).
  * ``ckpt_corrupt`` — a flipped payload byte in the latest checkpoint is
    caught by the manifest digest; restore walks back to the previous
    verifiable step (``ckpt/rollback_steps`` + ``ckpt_rollback`` event)
    instead of raising.
  * ``stream_corrupt`` — same discipline for the delta state stream
    (stream/): a flipped byte in a mid-window delta segment is caught by
    the segment manifest digest; the consumer walks BACK to its stored
    keyframe (bitwise) and re-converges bitwise at the next keyframe +
    window close.  A torn keyframe with no later anchor makes the stream
    unusable: ``warm_rejoin`` refuses it and the joiner falls back to the
    full Orbax restore path instead of adopting a half-applied state.

Elastic drills (the ISSUE 7 acceptance row — train/elastic.py):

  * ``elastic_gossip`` — heartbeat-directory failure detection: a silent
    peer is declared dead within ``peer_timeout``; a stale file of a dead
    prior incarnation never refreshes liveness; a restarted peer (higher
    incarnation) becomes a rejoin candidate.
  * ``elastic_remesh`` — ``crash=mid_collective`` kills worker w at step N;
    survivors convert the fault, remesh W -> W-1, and continue to
    completion.  Replicated state (params/opt/batch stats) is bitwise the
    pre-kill value; the EF migration matches the declared fold-or-drop
    semantics bitwise (fold: survivor row 0 += lost row, exact fp32; drop:
    ``elastic/dropped_ef_norm`` == the lost rows' L2, fp64-accumulated).
  * ``elastic_readmit`` — scale back up: the parked worker rejoins at a
    barrier with a zero EF row and PowerSGD factors broadcast-re-warmed
    from survivor row 0, then trains at full W again.
  * ``elastic_cascade`` — ``crash=during_remesh``: a second worker dies
    while survivors are inside ``handle_failure``; the dead set is unioned
    and the shrink restarts (one cascading remesh down to ``min_world``),
    and a union landing below ``min_world`` raises a clean PeerFailed
    naming every dead rank instead of wedging.
  * ``elastic_matrix`` — the kill-step x worker x EF-policy cross, plus a
    wire+sharded-transport variant (the owner partition recomputes at W-1).

Control drill (the ISSUE 11 acceptance row — control/):

  * ``control_resume`` — a crash-relaunch mid-decision-window resumes the
    adaptive compression controller bitwise: the checkpointed ControlState
    carries the open window's accumulators, so the relaunched run replays
    the same rung schedule and the same ``control_decision`` events, field
    for field, as the uninterrupted run.

Fleet drill (the ISSUE 12 acceptance row — fleet/ + tools/fleet.py):

  * ``fleet`` — three jobs, one 8-device pool: a high-priority arrival
    EVICTS one job (emergency checkpoint + exit 75, resumed when capacity
    clears) and SHRINKS an elastic one through the readmit barrier; freed
    slices bin-pack back (the evictee re-places, the shrunk job grows
    back to ``max_world``), every job finishes bitwise identical to a
    solo run of its applied-update/world trajectory, and every transition
    lands as ``fleet_*`` JSONL events + per-job Prometheus rollups.
  * ``fleet_matrix`` — the EF-policy cross (fold/drop) plus the rigid
    cell (no elastic slot => the planner preempts by eviction only).

Usage::

    python tools/chaos_drill.py --quick     # tier-1 smoke subset (~4 drills)
    python tools/chaos_drill.py             # full matrix (slow)
    python tools/chaos_drill.py --list      # quick/slow drill-row matrix

Exit code 0 = every invariant held.
"""

from __future__ import annotations

import os

if __name__ == "__main__":
    # standalone invocation: an 8-device virtual CPU mesh, set up before the
    # first jax import (importers — the test suite, whose conftest already
    # did this — get no side effects)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
            + " --xla_backend_optimization_level=0").strip()

import argparse
import dataclasses
import tempfile
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


# ---------------------------------------------------------------- fixtures

def _mesh(n=8):
    from tpu_compressed_dp.parallel.mesh import make_data_mesh

    return make_data_mesh(n)


def _tiny_setup(mesh, comp_cfg, guard_cfg, chaos, *, momentum=0.9, seed=0,
                with_factory=False, control_cfg=None):
    """TinyMLP + optimizer + state + guarded train step on ``mesh``."""
    import flax.linen as nn

    from tpu_compressed_dp.control import init_control_state
    from tpu_compressed_dp.models.common import init_model, make_apply_fn
    from tpu_compressed_dp.parallel.dp import init_comp_state, init_ef_state
    from tpu_compressed_dp.train.guard import init_guard_state
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState
    from tpu_compressed_dp.train.step import make_train_step

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(4)(x)

    module = TinyMLP()
    params, stats = init_model(module, jax.random.key(seed),
                               jnp.zeros((1, 4, 4, 3), jnp.float32))
    opt = SGD(lr=0.05, momentum=momentum, nesterov=momentum > 0)
    ndev = mesh.shape["data"]
    state = TrainState.create(
        params, stats, opt.init(params),
        init_ef_state(params, comp_cfg, ndev), jax.random.key(seed + 1),
        comp=init_comp_state(params, comp_cfg, ndev),
        guard=init_guard_state(guard_cfg),
        control=init_control_state(control_cfg),
    )

    def step_for(m, cfg=comp_cfg):
        # the elastic drills rebuild the step over the W-1 mesh — same
        # module/opt/config, new world (the sharded transport's owner
        # partition recomputes at trace time); the control drill rebuilds
        # it per RUNG (same mesh, new compression config)
        return make_train_step(make_apply_fn(module), opt, cfg, m,
                               guard_cfg=guard_cfg, chaos=chaos, donate=False)

    step = step_for(mesh)
    if with_factory:
        return state, step, step_for
    return state, step


def _batch(seed=0, n=32):
    rng = np.random.RandomState(seed)
    return {
        "input": jnp.asarray(rng.randn(n, 4, 4, 3).astype(np.float32)),
        "target": jnp.asarray(rng.randint(0, 4, n).astype(np.int32)),
    }


def _snap(state, fields=("params", "opt_state", "batch_stats", "ef", "comp")):
    return {f: jax.tree.map(np.asarray, getattr(state, f)) for f in fields}


def _assert_bitwise(a, b, what):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}: leaf not bitwise equal")


class _Recorder:
    """Minimal EventStream stand-in: records (kind, fields) in memory."""

    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        self.events.append((kind, fields))


def _flip_byte_in_step(directory, step) -> str:
    """Flip one byte in the middle of the step's largest payload file —
    size-preserving, so only the manifest digest can catch it."""
    sdir = os.path.join(directory, str(step))
    target, size = None, -1
    for root, _, files in os.walk(sdir):
        for f in files:
            p = os.path.join(root, f)
            s = os.path.getsize(p)
            if s > size:
                target, size = p, s
    assert target is not None and size > 0, f"nothing to corrupt in {sdir}"
    with open(target, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    return target


# ------------------------------------------------------------------ drills

def drill_skip_consistency(mesh, *, kind="nan", target="grads", worker=2,
                           bad_step=2, n_steps=5) -> Dict:
    """One poisoned worker at one step => identical global skip; everything
    the step mutates held bitwise; all other steps applied."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.guard import GuardConfig
    from tpu_compressed_dp.utils.chaos import ChaosConfig

    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True)
    gcfg = GuardConfig(loss_scaling=False, max_consecutive_skips=10)
    chaos = ChaosConfig(kind=kind, target=target, steps=(bad_step,),
                        worker=worker)
    state, step = _tiny_setup(mesh, comp, gcfg, chaos)
    batch = _batch()
    nonfinite = []
    for i in range(n_steps):
        pre = _snap(state) if i == bad_step else None
        state, m = step(state, batch)
        nonfinite.append(float(m["guard/nonfinite"]))
        if i == bad_step:
            _assert_bitwise(pre, _snap(state),
                            f"skip_consistency[{kind}/{target}] held state")
            assert float(m["guard/skip_streak"]) == 1.0
            assert float(m["guard/last_good_step"]) == bad_step
        assert np.isfinite(float(m["loss"]))
    expected = [1.0 if i == bad_step else 0.0 for i in range(n_steps)]
    assert nonfinite == expected, (nonfinite, expected)
    assert int(state.step) == n_steps
    for leaf in jax.tree.leaves(state.ef):
        assert np.all(np.isfinite(np.asarray(leaf))), "EF picked up poison"
    return {"nonfinite": nonfinite}


def drill_comp_hold(mesh) -> Dict:
    """PowerSGD warm-start Q (TrainState.comp) held bitwise on the vetoed
    step, mutated on good steps."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.guard import GuardConfig
    from tpu_compressed_dp.utils.chaos import ChaosConfig

    comp = CompressionConfig(method="powersgd", rank=2, error_feedback=True)
    gcfg = GuardConfig(loss_scaling=False)
    chaos = ChaosConfig(kind="inf", target="grads", steps=(1,), worker=0)
    state, step = _tiny_setup(mesh, comp, gcfg, chaos)
    batch = _batch()
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 0.0
    pre = _snap(state, ("comp", "ef"))
    good_comp = {k: np.asarray(v) for k, v in state.comp.items()}
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 1.0
    _assert_bitwise(pre, _snap(state, ("comp", "ef")), "comp_hold")
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 0.0
    moved = any(not np.array_equal(np.asarray(state.comp[k]), good_comp[k])
                for k in good_comp)
    assert moved, "comp never updates on good steps?"
    return {}


def drill_loss_scale(mesh) -> Dict:
    """Backoff on the bad step, regrowth after growth_interval good steps."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.guard import GuardConfig
    from tpu_compressed_dp.utils.chaos import ChaosConfig

    comp = CompressionConfig(method=None)
    gcfg = GuardConfig(init_scale=1024.0, backoff=0.5, growth=2.0,
                       growth_interval=3, loss_scaling=True)
    chaos = ChaosConfig(kind="inf", target="loss", steps=(1,), worker=0)
    state, step = _tiny_setup(mesh, comp, gcfg, chaos, momentum=0.0)
    batch = _batch()
    scales = []
    for _ in range(6):
        state, m = step(state, batch)
        scales.append(float(m["guard/loss_scale"]))
    assert scales == [1024.0, 512.0, 512.0, 512.0, 1024.0, 1024.0], scales
    return {"scales": scales}


def drill_ef_identity(mesh, transport="allgather", mode="simulate") -> Dict:
    """transmitted + residual == gradient on a non-vetoed guarded sync:
    per worker, ``psum(acc - new_ef)/W == synced`` where acc = grad + ef."""
    from tpu_compressed_dp.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from tpu_compressed_dp.parallel.dp import CompressionConfig, make_grad_sync

    cfg = CompressionConfig(method="topk", ratio=0.25, error_feedback=True,
                            mode=mode, transport=transport,
                            granularity="entiremodel")
    sync = make_grad_sync(cfg, "data")
    n = 512
    W = mesh.shape["data"]
    rng = np.random.RandomState(3)
    grads = jnp.asarray(rng.randn(W, n).astype(np.float32))
    efs = jnp.asarray(0.1 * rng.randn(W, n).astype(np.float32))

    def local(g, e):
        ok = jnp.asarray(True)
        synced, new_ef, _, _ = sync({"w": g[0]}, {"w": e[0]}, (),
                                    jax.random.key(0), ok=ok)
        sent = g[0] + e[0] - new_ef["w"]  # what this worker transmitted
        mean_sent = jax.lax.psum(sent, "data") / jax.lax.psum(1, "data")
        gap = jnp.max(jnp.abs(mean_sent - synced["w"]))
        return gap[None], new_ef["w"][None]

    gap, new_ef = jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data"))))(grads, efs)
    assert float(jnp.max(gap)) < 1e-5, float(jnp.max(gap))
    return {"max_gap": float(jnp.max(gap))}


def drill_poison_control(mesh) -> Dict:
    """Control arm: guard OFF, same injection => params DO go nonfinite
    (the injection is real; the guard is what contains it)."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.utils.chaos import ChaosConfig

    comp = CompressionConfig(method=None)
    chaos = ChaosConfig(kind="nan", target="grads", steps=(1,), worker=4)
    state, step = _tiny_setup(mesh, comp, None, chaos, momentum=0.0)
    batch = _batch()
    for _ in range(2):
        state, m = step(state, batch)
    finite = all(np.all(np.isfinite(np.asarray(l)))
                 for l in jax.tree.leaves(state.params))
    assert not finite, "chaos injection did not fire"
    return {}


def drill_max_skips(mesh) -> Dict:
    """Every-step injection wedges the run; the host check raises."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.guard import (GuardConfig, GuardExceeded,
                                               check_guard_metrics)
    from tpu_compressed_dp.utils.chaos import ChaosConfig

    comp = CompressionConfig(method=None)
    gcfg = GuardConfig(loss_scaling=False, max_consecutive_skips=3)
    chaos = ChaosConfig(kind="nan", target="grads", every=1, worker=0)
    state, step = _tiny_setup(mesh, comp, gcfg, chaos, momentum=0.0)
    batch = _batch()
    raised_at = None
    try:
        for i in range(8):
            state, m = step(state, batch)
            check_guard_metrics(jax.device_get(m), gcfg)
    except GuardExceeded:
        raised_at = i
    assert raised_at == 3, f"GuardExceeded at step {raised_at}, expected 3"
    return {"raised_at_step": raised_at}


def drill_crash_recovery(mesh, *, crash_at_step=5, chaos_spec=None) -> Dict:
    """Host-crash at step N + run_with_recovery == the uncrashed run,
    bitwise — including when in-graph chaos fires around the crash (the
    step-counter-driven injection replays identically after restore)."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.guard import GuardConfig
    from tpu_compressed_dp.utils import resilience
    from tpu_compressed_dp.utils.chaos import ChaosConfig, CrashInjector
    from tpu_compressed_dp.utils.checkpoint import Checkpointer

    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True)
    gcfg = GuardConfig(loss_scaling=False)
    chaos = (ChaosConfig.parse(chaos_spec) if chaos_spec
             else ChaosConfig(kind="nan", target="grads", steps=(3,), worker=1))
    epochs, steps_per_epoch = 4, 2
    batches = [_batch(seed=s) for s in range(steps_per_epoch)]

    def run(crash: Optional[CrashInjector], ckpt_dir: Optional[str]):
        state, step = _tiny_setup(mesh, comp, gcfg, chaos)
        ckpt = Checkpointer(ckpt_dir) if ckpt_dir else None

        def epoch_fn(state, epoch):
            for i, b in enumerate(batches):
                if crash is not None:
                    crash.check(epoch * steps_per_epoch + i)
                state, _ = step(state, b)
            if ckpt:
                ckpt.save(state, {"epoch": epoch})
            return state

        if ckpt:
            final, info = resilience.run_with_recovery(
                epoch_fn, state, epochs, checkpointer=ckpt,
                on_restore=lambda s: s.with_mesh_sharding(mesh))
            ckpt.close()
        else:
            info = {"restores": 0}
            final = state
            for e in range(epochs):
                final = epoch_fn(final, e)
        return final, info

    clean, _ = run(None, None)
    with tempfile.TemporaryDirectory() as td:
        crashed, info = run(CrashInjector(crash_at_step),
                            os.path.join(td, "ck"))
    assert info["restores"] == 1, info
    _assert_bitwise(_snap(clean), _snap(crashed), "crash_recovery state")
    assert int(clean.step) == int(crashed.step) == epochs * steps_per_epoch
    for f in ("loss_scale", "skips", "total_skipped", "last_good_step"):
        assert np.array_equal(np.asarray(getattr(clean.guard, f)),
                              np.asarray(getattr(crashed.guard, f))), f
    return {"restores": info["restores"]}


def drill_ckpt_preempt(mesh, *, preempt_at_step=3, n_steps=6) -> Dict:
    """``crash=preempt`` (a real self-SIGTERM) mid-run => the loop cuts an
    emergency checkpoint (draining the in-flight async save first) and the
    relaunched run resumes to a final state bitwise identical to the
    uninterrupted one — including an in-graph NaN injection landing AFTER
    the preemption point, proving the replay lines up step-for-step."""
    import time

    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.guard import GuardConfig
    from tpu_compressed_dp.utils.chaos import ChaosConfig, CrashInjector
    from tpu_compressed_dp.utils.checkpoint import Checkpointer
    from tpu_compressed_dp.utils.resilience import (Preempted,
                                                    PreemptionHandler)

    comp = CompressionConfig(method="powersgd", rank=2, error_feedback=True)
    gcfg = GuardConfig(loss_scaling=False)
    # NaN at step 4 — AFTER the preempt at 3 — fires only in the resumed
    # half, so a misaligned replay cannot pass the bitwise check
    chaos = ChaosConfig(kind="nan", target="grads", steps=(4,), worker=2,
                        crash_at_step=preempt_at_step, crash_mode="preempt")
    batches = [_batch(seed=s) for s in range(n_steps)]

    clean, step = _tiny_setup(mesh, comp, gcfg, chaos)
    for i in range(n_steps):
        clean, _ = step(clean, batches[i])

    with tempfile.TemporaryDirectory() as td:
        state, step = _tiny_setup(mesh, comp, gcfg, chaos)
        ckpt = Checkpointer(td)
        crash = CrashInjector(chaos.crash_at_step, mode=chaos.crash_mode)
        handler = PreemptionHandler(log=lambda s: None).install()
        assert handler.installed, "drill must run on the main thread"
        preempted_at = None
        try:
            i = 0
            while i < n_steps:
                crash.check(i)          # preempt mode: self-SIGTERM, no raise
                if crash.fired and not handler.triggered:
                    # the signal lands within a few bytecodes; wait it out
                    # deterministically rather than racing the handler
                    for _ in range(1000):
                        if handler.triggered:
                            break
                        time.sleep(0.001)
                handler.check(i)
                state, _ = step(state, batches[i])
                i += 1
                if i % 2 == 0:
                    ckpt.save_async(state, {"step_i": i})
            raise AssertionError("preempt never fired")
        except Preempted as err:
            preempted_at = err.step
            # the emergency-save path: drain the in-flight async write,
            # then cut the final checkpoint synchronously
            ckpt.drain(raise_error=False)
            ckpt.save(state, {"step_i": i, "emergency": True})
            ckpt.close()
        finally:
            handler.uninstall()
        assert preempted_at == preempt_at_step, preempted_at

        # "relaunch": fresh process state, restore, run the remaining steps
        state2, step2 = _tiny_setup(mesh, comp, gcfg, chaos)
        ckpt2 = Checkpointer(td)
        state2, meta = ckpt2.restore(state2)
        ckpt2.close()
        state2 = state2.with_mesh_sharding(mesh)
        assert meta.get("emergency") is True, meta
        i = int(meta["step_i"])
        assert i == preempt_at_step, (i, meta)
        while i < n_steps:
            state2, _ = step2(state2, batches[i])
            i += 1

    _assert_bitwise(_snap(clean), _snap(state2), "ckpt_preempt state")
    assert int(clean.step) == int(state2.step) == n_steps
    for f in ("loss_scale", "skips", "total_skipped", "last_good_step"):
        assert np.array_equal(np.asarray(getattr(clean.guard, f)),
                              np.asarray(getattr(state2.guard, f))), f
    return {"preempted_at": preempted_at, "resumed_from": preempt_at_step,
            "bitwise": True}


def drill_ckpt_corrupt(mesh, *, n_steps=4) -> Dict:
    """A corrupted latest checkpoint (one flipped payload byte — the
    manifest digest is the only thing that can notice) => restore walks
    back to the newest verifiable step instead of raising, records
    ``ckpt/rollback_steps`` and emits a ``ckpt_rollback`` event."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.guard import GuardConfig
    from tpu_compressed_dp.utils.checkpoint import Checkpointer

    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True)
    gcfg = GuardConfig(loss_scaling=False)
    state, step = _tiny_setup(mesh, comp, gcfg, None)
    batch = _batch()
    with tempfile.TemporaryDirectory() as td:
        ckpt = Checkpointer(td)
        snaps = {}
        for i in range(n_steps):
            state, _ = step(state, batch)
            ckpt.save(state, {"step_i": i + 1})
            snaps[int(state.step)] = _snap(state)
        ckpt.close()

        _flip_byte_in_step(td, n_steps)   # newest step, now torn

        fresh, _ = _tiny_setup(mesh, comp, gcfg, None)
        ckpt2 = Checkpointer(td)
        ckpt2.events = _Recorder()
        restored, meta = ckpt2.restore(fresh)
        assert int(restored.step) == n_steps - 1, int(restored.step)
        assert int(meta["step_i"]) == n_steps - 1, meta
        _assert_bitwise(snaps[n_steps - 1], _snap(restored),
                        "ckpt_corrupt fallback state")
        assert ckpt2.metrics()["ckpt/rollback_steps"] == 1.0
        kinds = [k for k, _ in ckpt2.events.events]
        assert "ckpt_rollback" in kinds, kinds
        ckpt2.close()
    return {"rollback_steps": 1, "restored_step": n_steps - 1}


def drill_stream_corrupt(mesh, *, keyframe_every=4) -> Dict:
    """A flipped payload byte in a mid-window delta segment is caught by
    the segment manifest digest => the consumer walks back to its stored
    keyframe bitwise and re-converges bitwise once the next keyframe and
    window close land; a torn keyframe with no later anchor makes the
    stream unusable => ``warm_rejoin`` returns no adoption info and the
    joiner takes the full-restore path."""
    import copy

    from tpu_compressed_dp.stream.reader import StreamReader
    from tpu_compressed_dp.stream.rejoin import warm_rejoin
    from tpu_compressed_dp.stream.store import (StreamCorrupt,
                                                segment_payload_path)
    from tpu_compressed_dp.stream.writer import StreamWriter

    rng = np.random.RandomState(7)
    params = {"dense": {"kernel": rng.randn(48, 8).astype(np.float32)},
              "bias": rng.randn(64).astype(np.float32)}

    def advance():
        params["dense"]["kernel"] = (
            params["dense"]["kernel"]
            + rng.randn(48, 8).astype(np.float32) * 0.01)
        params["bias"] = (params["bias"]
                          + rng.randn(64).astype(np.float32) * 0.01)

    def flip(path):
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))

    def quiet(*a, **k):
        pass

    @dataclasses.dataclass
    class Joiner:
        params: dict
        step: int

    with tempfile.TemporaryDirectory() as td:
        sd = os.path.join(td, "stream")
        w = StreamWriter(sd, ratio=0.25, keyframe_every=keyframe_every,
                         log=quiet)
        w.append(params, step=1)                    # seq 0: keyframe
        kf_params = copy.deepcopy(params)
        advance(); w.append(params, step=2)         # seq 1: delta
        advance(); w.append(params, step=3)         # seq 2: delta (mid-window)

        flip(segment_payload_path(sd, 2))           # torn delta

        r = StreamReader(sd, log=quiet)
        r.catch_up()
        # the digest notices; the consumer never serves the torn delta —
        # it reverts to the last keyframe's reconstruction, bitwise
        assert r.metrics()["stream/corrupt_segments"] == 1.0
        assert int(r.applied_seq) == 0 and int(r.applied_step) == 1
        _assert_bitwise(kf_params, r.params_like(kf_params),
                        "stream_corrupt walk-back")

        advance(); w.append(params, step=4)         # seq 3: flush (skipped —
        #                                             awaiting a keyframe)
        advance(); w.append(params, step=5)         # seq 4: fresh keyframe
        kf2 = copy.deepcopy(params)
        r.catch_up()
        assert int(r.applied_seq) == 4, int(r.applied_seq)
        _assert_bitwise(kf2, r.params_like(kf2), "stream_corrupt re-anchor")
        advance(); w.sync(params, step=6)           # window-closing flush
        r.catch_up()
        assert r.exact, "head not exact after sync"
        _assert_bitwise(params, r.params_like(params),
                        "stream_corrupt reconverged head")
        w.close()

        # half two: a torn KEYFRAME with no later anchor is unusable — the
        # reader raises and warm rejoin refuses to adopt anything
        sd2 = os.path.join(td, "stream2")
        params2 = {"w": rng.randn(128).astype(np.float32)}
        w2 = StreamWriter(sd2, ratio=0.25, keyframe_every=keyframe_every,
                          log=quiet)
        w2.append(params2, step=1)                  # seq 0: keyframe
        params2["w"] = params2["w"] + 0.5
        w2.append(params2, step=2)                  # seq 1: delta
        w2.close()
        flip(segment_payload_path(sd2, 0))
        try:
            StreamReader(sd2, log=quiet).catch_up()
            raise AssertionError("torn keyframe went unnoticed")
        except StreamCorrupt:
            pass
        joiner = Joiner(params=copy.deepcopy(params2), step=0)
        adopted, info = warm_rejoin(joiner, sd2, log=quiet)
        assert info is None and adopted is joiner, (
            "warm rejoin adopted from an unusable stream")
    return {"corrupt_segments": 1, "walkback_seq": 0, "reconverged": True,
            "keyframe_fallback": True}


def drill_control_resume(mesh, *, preempt_at_step=4, n_steps=9) -> Dict:
    """Crash-relaunch MID-decision-window resumes the adaptive controller
    bitwise: the saved ControlState (riding the checkpoint next to guard)
    carries the open window's accumulators, so the relaunched run replays
    the SAME rung schedule and the SAME ``control_decision`` events,
    field for field, as the uninterrupted run — the modeled signal makes
    every decision a pure function of checkpointed state."""
    from tpu_compressed_dp.control import (ControlConfig, Controller,
                                           comp_for_rung)
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.guard import GuardConfig
    from tpu_compressed_dp.utils.checkpoint import Checkpointer

    base = CompressionConfig(method="topk", ratio=0.5, error_feedback=True)
    # window=3, preempt at 4 => the crash lands one update INTO a window;
    # modeled comm (1e6 bits @ 100 Mbit/s = 10 ms/update) >> the pinned
    # 0.5 ms budget, so the schedule is down, down, then hold at the floor
    ctrl_cfg = ControlConfig(method="topk", rungs=(0.5, 0.25, 0.125),
                             window=3, budget_ms=0.5)
    gcfg = GuardConfig(loss_scaling=False)
    batches = [_batch(seed=s) for s in range(n_steps)]
    bits_per_update = 1e6

    def span(state, step_for, controller, i0, i1):
        cache = {}
        for i in range(i0, i1):
            rung = int(np.asarray(state.control.rung))
            if rung not in cache:
                cache[rung] = step_for(mesh, comp_for_rung(base, ctrl_cfg,
                                                           rung))
            state, _ = cache[rung](state, batches[i])
            new_control, _ = controller.tick(
                state.control, applied=int(state.step),
                signals=controller.window_signals(mean_bits=bits_per_update))
            state = state.replace(control=new_control)
        return state

    def decisions(rec):
        return [(k, f) for k, f in rec.events if k == "control_decision"]

    # the uninterrupted run
    rec_clean = _Recorder()
    clean, _, step_for = _tiny_setup(mesh, base, gcfg, None,
                                     with_factory=True, control_cfg=ctrl_cfg)
    clean = span(clean, step_for, Controller(ctrl_cfg, events=rec_clean),
                 0, n_steps)

    with tempfile.TemporaryDirectory() as td:
        # first life: preempt mid-window, emergency save
        rec_a = _Recorder()
        s1, _, sf1 = _tiny_setup(mesh, base, gcfg, None, with_factory=True,
                                 control_cfg=ctrl_cfg)
        s1 = span(s1, sf1, Controller(ctrl_cfg, events=rec_a),
                  0, preempt_at_step)
        ckpt = Checkpointer(td)
        ckpt.save(s1, {"step_i": preempt_at_step, "emergency": True})
        ckpt.close()

        # "relaunch": fresh process state, restore, finish the run
        rec_b = _Recorder()
        s2, _, sf2 = _tiny_setup(mesh, base, gcfg, None, with_factory=True,
                                 control_cfg=ctrl_cfg)
        ckpt2 = Checkpointer(td)
        s2, meta = ckpt2.restore(s2)
        ckpt2.close()
        s2 = s2.with_mesh_sharding(mesh)
        assert int(meta["step_i"]) == preempt_at_step, meta
        # the open window's accumulation rode the checkpoint
        assert int(np.asarray(s2.control.win_updates)) == \
            preempt_at_step % ctrl_cfg.window, jax.device_get(s2.control)
        s2 = span(s2, sf2, Controller(ctrl_cfg, events=rec_b),
                  preempt_at_step, n_steps)

    fields = ("params", "opt_state", "batch_stats", "ef", "control")
    _assert_bitwise(_snap(clean, fields), _snap(s2, fields),
                    "control_resume state")
    assert int(clean.step) == int(s2.step) == n_steps
    # the decision STREAM is identical: pre-crash events + post-crash
    # events == the uninterrupted run's, field for field
    assert decisions(rec_a) + decisions(rec_b) == decisions(rec_clean), (
        decisions(rec_a) + decisions(rec_b), decisions(rec_clean))
    rungs = [f["rung_to"] for _, f in decisions(rec_clean)]
    dirs = [f["direction"] for _, f in decisions(rec_clean)]
    assert rungs == [1, 2, 2], rungs
    assert dirs == ["down", "down", "hold"], dirs
    return {"decisions": len(rungs), "rungs": rungs,
            "resumed_mid_window": True}


# ----------------------------------------------------------- elastic drills

def drill_elastic_gossip(mesh=None) -> Dict:
    """Heartbeat-gossip failure detection on a simulated clock: silence
    past the timeout => dead (and only then); a restart (higher
    incarnation) => rejoin candidate, never liveness of the dead life."""
    from tpu_compressed_dp.train.elastic import (PeerFailed, PeerGossip,
                                                 write_peer_heartbeat)

    clock = {"t": 1000.0}
    with tempfile.TemporaryDirectory() as td:
        g = PeerGossip(td, 0, 4, peer_timeout_s=5.0, now=lambda: clock["t"])
        for r in (1, 2, 3):
            write_peer_heartbeat(td, r, 0, ts=clock["t"])
        assert g.check() == {}, "fresh peers misread as dead"
        clock["t"] += 4.0                       # rank 2 goes silent here
        for r in (1, 3):
            write_peer_heartbeat(td, r, 1, ts=clock["t"])
        assert g.check() == {}, "silence below the timeout misread as death"
        clock["t"] += 4.0                       # rank 2 now 8s stale (> 5s)
        for r in (1, 3):
            write_peer_heartbeat(td, r, 2, ts=clock["t"])
        try:
            g.raise_if_dead(step=7)
            raise AssertionError("gossip missed the dead peer")
        except PeerFailed as pf:
            assert pf.failed == (2,) and pf.step == 7, pf
        assert g.dead == (2,)
        # the dead life's stale file keeps aging out; a RESTARTED rank 2
        # (higher incarnation) is a rejoin candidate, not a resurrection
        clock["t"] += 1.0
        write_peer_heartbeat(td, 2, 0, incarnation=1, ts=clock["t"])
        assert g.rejoin_candidates() == {2: 1}
        assert g.dead == (2,), "rejoin candidacy must not undeclare death"
        g.readmit(2)
        assert g.dead == () and g.check() == {}
    return {"detected": [2]}


def drill_elastic_remesh(mesh, *, kill_step=2, worker=3, policy="fold",
                         n_steps=5, transport="allgather",
                         mode="simulate") -> Dict:
    """Mid-collective kill of one worker => coordinated abort, W -> W-1
    remesh, bitwise EF fold-or-drop, and the run completes on survivors."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.elastic import ElasticConfig, ElasticRuntime
    from tpu_compressed_dp.utils.chaos import ChaosConfig, maybe_crash_injector

    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True,
                             mode=mode, transport=transport,
                             granularity="entiremodel")
    chaos = ChaosConfig.parse(
        f"crash=mid_collective,crash_at_step={kill_step},worker={worker},"
        f"peer_timeout=30")
    crash = maybe_crash_injector(chaos)
    state, step, step_for = _tiny_setup(mesh, comp, None, chaos,
                                        with_factory=True)
    el = ElasticRuntime(ElasticConfig(ef_policy=policy), mesh, chaos=chaos,
                        log=lambda s: None)
    W = int(mesh.shape["data"])
    batch = _batch(n=56)                 # 56 divides both W=8 and W-1=7
    i, killed = 0, False
    while i < n_steps:
        try:
            crash.check(i)
            new_state, m = step(state, batch)
            crash.check(i, phase="mid_collective")
        except Exception as err:
            failure = el.failure_from(err)
            assert failure is not None, f"unconverted fault: {err!r}"
            assert failure.failed == (worker,) and failure.step == kill_step
            # donate=False: the pre-dispatch state is live — the abort
            # discards the in-flight step, exactly the declared semantics
            pre = _snap(state)
            old_ef = jax.device_get(state.ef)
            state = el.handle_failure(state, failure)
            post = _snap(state, ("params", "opt_state", "batch_stats"))
            _assert_bitwise({k: pre[k] for k in post}, post,
                            "elastic_remesh replicated state")
            dropped_sq = 0.0
            for la, lb in zip(jax.tree.leaves(old_ef),
                              jax.tree.leaves(jax.device_get(state.ef))):
                la, lb = np.asarray(la), np.asarray(lb)
                expect = np.delete(la, worker, axis=0)
                if policy == "fold":
                    expect = expect.copy()
                    expect[0] = expect[0] + la[worker]
                else:
                    dropped_sq += float(
                        np.sum(la[worker].astype(np.float64) ** 2))
                assert np.array_equal(expect, lb), \
                    f"EF {policy} migration not bitwise"
            if policy == "drop":
                assert el.dropped_ef_norm == float(np.sqrt(dropped_sq))
            else:
                assert el.dropped_ef_norm == 0.0
            assert el.world == W - 1 and el.parked == (worker,)
            step = step_for(el.mesh)     # owner partition recomputes here
            killed = True
            continue
        state = new_state
        i += 1
    assert killed, "mid-collective kill never fired"
    assert int(state.step) == n_steps
    assert el.remesh_count == 1 and el.peer_failures == 1
    assert set(el.metrics()) == {
        "elastic/peer_failures", "elastic/remesh_count",
        "elastic/dropped_ef_norm", "elastic/remesh_latency_ms",
        "elastic/remesh_ms", "stream/rejoin_bytes"}
    assert el.metrics()["elastic/remesh_ms"] >= el.remesh_latency_ms
    for leaf in jax.tree.leaves(state.ef):
        assert np.asarray(leaf).shape[0] == W - 1
    return {"world": el.world, "dropped_ef_norm": el.dropped_ef_norm}


def drill_elastic_readmit(mesh) -> Dict:
    """Scale-up re-admission: the parked worker rejoins with a zero EF row
    and PowerSGD factors broadcast-re-warmed from survivor row 0, then the
    run trains at full W again."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.elastic import (ElasticConfig,
                                                 ElasticRuntime, PeerFailed)

    comp = CompressionConfig(method="powersgd", rank=2, error_feedback=True)
    state, step, step_for = _tiny_setup(mesh, comp, None, None,
                                        with_factory=True)
    el = ElasticRuntime(ElasticConfig(), mesh, log=lambda s: None)
    W = int(mesh.shape["data"])
    batch = _batch(n=56)
    state, _ = step(state, batch)        # warm the PowerSGD factors
    state = el.handle_failure(state, PeerFailed((2,), step=1, reason="drill"))
    assert el.world == W - 1 and el.parked == (2,)
    state, _ = step_for(el.mesh)(state, batch)   # one step on survivors
    state = el.readmit(state)
    assert el.world == W and el.parked == ()
    for leaf in jax.tree.leaves(jax.device_get(state.comp)):
        a = np.asarray(leaf)
        assert a.shape[0] == W
        assert np.array_equal(a[-1], a[0]), "comp re-warm not a broadcast"
    for leaf in jax.tree.leaves(jax.device_get(state.ef)):
        assert not np.any(np.asarray(leaf)[-1]), "rejoiner EF row not zero"
    state, _ = step_for(el.mesh)(state, batch)   # trains at full W again
    assert int(state.step) == 3
    return {"world": el.world, "readmits": el.readmit_count}


def drill_elastic_cascade(mesh) -> Dict:
    """``crash=during_remesh``: a SECOND worker dies while survivors are
    inside ``handle_failure``.  The runtime unions the dead set and
    restarts the shrink from the uncommitted mesh — one cascading remesh
    down to ``min_world`` — and a union that would land BELOW
    ``min_world`` raises a clean PeerFailed naming every dead rank
    (mesh untouched) instead of wedging or committing a stale world."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.elastic import (ElasticConfig,
                                                 ElasticRuntime, PeerFailed)
    from tpu_compressed_dp.utils.chaos import ChaosConfig, maybe_crash_injector

    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True,
                             mode="simulate", granularity="entiremodel")
    chaos = ChaosConfig.parse(
        "crash=during_remesh,crash_at_step=2,worker=5,peer_timeout=30")
    state, step, step_for = _tiny_setup(mesh, comp, None, None,
                                        with_factory=True)
    W = int(mesh.shape["data"])
    batch = _batch(n=48)                 # 48 divides W=8 and W-2=6

    # arm 1: the union (8 - 2 = 6) lands exactly ON min_world => one
    # cascading shrink commits
    el = ElasticRuntime(ElasticConfig(ef_policy="fold", min_world=W - 2),
                        mesh, chaos=chaos,
                        crash=maybe_crash_injector(chaos), log=lambda s: None)
    state, _ = step(state, batch)
    pre = _snap(state)
    old_ef = jax.device_get(state.ef)
    state = el.handle_failure(state, PeerFailed((3,), step=2, reason="drill"))
    assert el.world == W - 2 and el.parked == (3, 5), (el.world, el.parked)
    assert el.cascade_count == 1 and el.remesh_count == 1
    assert el.peer_failures == 2, el.peer_failures
    post = _snap(state, ("params", "opt_state", "batch_stats"))
    _assert_bitwise({k: pre[k] for k in post}, post,
                    "elastic_cascade replicated state")
    for la, lb in zip(jax.tree.leaves(old_ef),
                      jax.tree.leaves(jax.device_get(state.ef))):
        la, lb = np.asarray(la), np.asarray(lb)
        expect = np.delete(la, [3, 5], axis=0)
        # one fold of the UNION: row0 + sum(lost rows), matching migrate_ef
        expect[0] = expect[0] + la[[3, 5]].sum(axis=0)
        assert np.array_equal(expect, lb), "cascade EF fold not bitwise"
    state, _ = step_for(el.mesh)(state, batch)   # survivors keep training
    assert int(state.step) == 2

    # arm 2: the union would land BELOW min_world => a clean PeerFailed
    # naming both ranks, nothing committed
    chaos2 = ChaosConfig.parse(
        "crash=during_remesh,crash_at_step=2,worker=5,peer_timeout=30")
    state2, _ = _tiny_setup(mesh, comp, None, None)
    el2 = ElasticRuntime(ElasticConfig(ef_policy="fold", min_world=W - 1),
                         mesh, chaos=chaos2,
                         crash=maybe_crash_injector(chaos2),
                         log=lambda s: None)
    try:
        el2.handle_failure(state2, PeerFailed((3,), step=2, reason="drill"))
        raise AssertionError("below-min_world cascade did not raise")
    except PeerFailed as pf:
        assert pf.failed == (3, 5), pf
        assert "min_world" in (pf.reason or ""), pf
    assert el2.world == W and el2.remesh_count == 0, "stale world committed"
    return {"world": el.world, "cascades": el.cascade_count}


def drill_fleet(mesh, *, policy="fold", elastic=True) -> Dict:
    """Three jobs, one 8-device pool (the ISSUE 12 acceptance drill): a
    high-priority arrival EVICTS one job (emergency checkpoint, exit 75)
    and — when jobA is elastic — SHRINKS another through the readmit
    barrier; freed slices bin-pack back, and every job finishes bitwise
    identical to a solo run of the same applied-update/world trajectory.
    ``elastic=False`` runs the rigid cell: no shrink candidate, so the
    planner evicts instead (evict-only preemption path)."""
    from tpu_compressed_dp.fleet import FleetScheduler, JobController, JobSpec
    from tpu_compressed_dp.fleet import state as fstate
    from tpu_compressed_dp.obs.export import EventStream, read_events
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.parallel.mesh import make_data_mesh
    from tpu_compressed_dp.train.elastic import (ElasticConfig,
                                                 ElasticRuntime, PeerFailed)
    from tpu_compressed_dp.utils.checkpoint import Checkpointer
    from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT

    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True,
                             mode="simulate", granularity="entiremodel")
    pool = int(mesh.shape["data"])
    devs_all = list(mesh.devices.flat)   # pool id i -> physical device
    # targets chosen so jobA outlives jobC: the freed slices have a live
    # elastic job to grow back into (the readmit half of the shrink)
    targets = {"jobA": 8, "jobB": 5, "jobC": 3}
    batches = {j: [_batch(seed=base + i, n=12) for i in range(targets[j])]
               for j, base in (("jobA", 100), ("jobB", 200), ("jobC", 300))}
    specs = [
        JobSpec("jobA", ("sim",), priority=0,
                min_world=3 if elastic else 4, max_world=4,
                target_updates=targets["jobA"]),
        JobSpec("jobB", ("sim",), priority=0, min_world=3, max_world=3,
                target_updates=targets["jobB"]),
        JobSpec("jobC", ("sim",), priority=10, min_world=4, max_world=4,
                target_updates=targets["jobC"]),
    ]

    class _SimController(JobController):
        """In-process jobs: one training update per poll, shrink/grow
        through the job's own ElasticRuntime, eviction = a real emergency
        checkpoint + PREEMPT_EXIT, resume = restore on the newly granted
        slice.  Pool ids are capacity bookkeeping; each placement maps
        them onto the drill mesh's physical devices."""

        resizable = True

        def __init__(self, root):
            self.root = root
            self.jobs: Dict[str, Dict] = {}
            self.finals: Dict[str, Dict] = {}
            self.traj = []               # (job_id, kind, applied, world)

        def _ckpt_dir(self, job_id):
            return os.path.join(self.root, "ckpt", job_id)

        def start(self, spec, world, devices, *, resume):
            m = make_data_mesh(devices=tuple(devs_all[d] for d in devices))
            state, _, step_for = _tiny_setup(m, comp, None, None,
                                             with_factory=True)
            el = ElasticRuntime(ElasticConfig(ef_policy=policy), m,
                                log=lambda s: None)
            applied = 0
            if resume:
                ck = Checkpointer(self._ckpt_dir(spec.job_id))
                state, meta = ck.restore(state)
                ck.close()
                state = state.with_mesh_sharding(m)
                assert meta.get("emergency") is True, meta
                applied = int(meta["applied"])
            self.jobs[spec.job_id] = {
                "spec": spec, "state": state, "el": el,
                "step": step_for(m), "step_for": step_for,
                "applied": applied}

        def evict(self, job_id):
            j = self.jobs.pop(job_id)
            ck = Checkpointer(self._ckpt_dir(job_id))
            ck.save(j["state"], {"applied": j["applied"], "emergency": True})
            ck.close()
            return PREEMPT_EXIT

        def shrink(self, job_id, world):
            j = self.jobs[job_id]
            el = j["el"]
            self.traj.append((job_id, "shrink", j["applied"], world))
            while el.world > world:
                j["state"] = el.handle_failure(
                    j["state"], PeerFailed((el.world - 1,), step=j["applied"],
                                           reason="fleet preemption"))
            j["step"] = j["step_for"](el.mesh)

        def grow(self, job_id, world, new_devices):
            j = self.jobs[job_id]
            self.traj.append((job_id, "readmit", j["applied"], world))
            j["state"] = j["el"].readmit(j["state"])
            assert j["el"].world == world, (j["el"].world, world)
            j["step"] = j["step_for"](j["el"].mesh)

        def poll(self, job_id):
            j = self.jobs[job_id]
            j["state"], _ = j["step"](j["state"],
                                      batches[job_id][j["applied"]])
            j["applied"] += 1
            if j["applied"] >= targets[job_id]:
                self.finals[job_id] = _snap(j["state"])
                self.jobs.pop(job_id)
                return {"exit_code": 0, "applied_updates": j["applied"]}
            return {"exit_code": None, "applied_updates": j["applied"]}

    with tempfile.TemporaryDirectory() as td:
        ctrl = _SimController(td)
        events = EventStream(fstate.events_path(td))
        now = [0.0]

        def wall():
            now[0] += 1.0
            return now[0]

        sched = FleetScheduler(td, pool, ctrl, events=events, wall=wall,
                               log=lambda s: None)
        sched.submit(specs[0])
        sched.submit(specs[1])
        for t in range(64):
            if t == 3:
                sched.submit(specs[2])   # the high-priority arrival
            sched.tick()
            if sched.idle():
                break
        events.close()

        assert sched.idle(), "fleet never drained"
        for job_id, tgt in targets.items():
            job = sched.jobs[job_id]
            assert job.status == "done" and job.applied == tgt, \
                (job_id, job.status, job.applied)
        c = sched.counters
        want = ({"evictions": 1, "shrinks": 1, "readmits": 1} if elastic
                else {"evictions": 1, "shrinks": 0, "readmits": 0})
        for k, v in want.items():
            assert c[k] == v, (k, c[k], v)
        assert c["preemptions"] == 0 and c["failures"] == 0, c

        # every transition is on the wire: fleet_* JSONL events + per-job
        # Prometheus rollups with the job label
        kinds = {e["kind"] for e in read_events(fstate.events_path(td))}
        need = {"fleet_submit", "fleet_admit", "fleet_place", "fleet_evict",
                "fleet_finish"}
        if elastic:
            need |= {"fleet_shrink", "fleet_readmit"}
        assert need <= kinds, need - kinds
        for job_id in targets:
            prom = open(
                f"{fstate.prom_dir(td)}/{job_id}.fleet.prom").read()
            assert f'job="{job_id}"' in prom and "fleet_world" in prom
        assert "fleet_devices_free" in open(
            f"{fstate.prom_dir(td)}/fleet.prom").read()

        # bitwise acceptance: each job vs a solo run replaying the same
        # applied-update count and (for jobA) the same world trajectory
        traj = {}
        for job_id, kind, applied, world in ctrl.traj:
            traj.setdefault(job_id, []).append((applied, kind, world))
        solo_world = {"jobA": 4, "jobB": 3, "jobC": 4}
        for job_id, tgt in targets.items():
            m = make_data_mesh(
                devices=tuple(devs_all[:solo_world[job_id]]))
            state, _, step_for = _tiny_setup(m, comp, None, None,
                                             with_factory=True)
            el = ElasticRuntime(ElasticConfig(ef_policy=policy), m,
                                log=lambda s: None)
            step = step_for(m)
            for i in range(tgt):
                for at, kind, world in traj.get(job_id, ()):
                    if at != i:
                        continue
                    if kind == "shrink":
                        while el.world > world:
                            state = el.handle_failure(
                                state, PeerFailed((el.world - 1,), step=i,
                                                  reason="fleet preemption"))
                    else:
                        state = el.readmit(state)
                    step = step_for(el.mesh)
                state, _ = step(state, batches[job_id][i])
            _assert_bitwise(_snap(state), ctrl.finals[job_id],
                            f"fleet {job_id} vs solo")

    return {"world": pool, "evictions": c["evictions"],
            "shrinks": c["shrinks"], "readmits": c["readmits"],
            "bitwise": True}


def drill_forensics(mesh) -> Dict:
    """Every injected failure leaves a valid black box and the postmortem
    names the injected root cause — rank AND kind — from the bundles
    alone; a clean run leaves none and the recorder never perturbs the
    trajectory (bitwise with/without).

    Four simulated ranks share one flight dir per case, each failure
    raised through its REAL plane (guard wedge, mid-collective
    ChaosCrash, self-SIGTERM preemption, manifest verification):

      nan          chaos nan/grads worker=1 wedges the guard -> every
                   rank dumps ``guard_exceeded``; verdict names worker 1
      dead_peer    mid_collective kill of worker 2 -> the dying rank
                   dumps ``chaos_crash``, survivors ``peer_failed``;
                   verdict names rank 2
      preempt      a real SIGTERM on rank 0 (chaos crash=preempt through
                   PreemptionHandler) -> verdict ``preempt`` rank 0
      corruption   one flipped payload byte + explicit-step restore ->
                   ``ckpt_corrupt`` bundle; verdict ``corruption``
    """
    import time

    from tpu_compressed_dp.obs.flight import (FlightRecorder, read_bundles,
                                              validate_bundle)
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.elastic import PeerFailed
    from tpu_compressed_dp.train.guard import GuardConfig, GuardExceeded
    from tpu_compressed_dp.utils.chaos import (ChaosConfig, ChaosCrash,
                                               CrashInjector)
    from tpu_compressed_dp.utils.checkpoint import (CheckpointCorrupt,
                                                    Checkpointer)
    from tpu_compressed_dp.utils.resilience import (Preempted,
                                                    PreemptionHandler)

    try:
        from tools.postmortem import classify, merge_timeline
    except ImportError:
        from postmortem import classify, merge_timeline

    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True)
    ranks = 4

    def recorders(directory, chaos=None):
        out = []
        for r in range(ranks):
            fl = FlightRecorder(rank=r, capacity=32, directory=directory,
                                meta={"drill": "forensics"})
            if chaos is not None:
                fl.note_chaos(chaos)
            out.append(fl)
        return out

    def check_bundles(directory, expect_ranks):
        bundles = read_bundles(directory)
        assert sorted(bundles) == sorted(expect_ranks), (
            sorted(bundles), sorted(expect_ranks))
        for r, b in bundles.items():
            problems = validate_bundle(b)
            assert not problems, (r, problems)
        return bundles

    verdicts = {}

    # --- nan: chaos nan/grads on worker 1 wedges the guard everywhere
    gcfg = GuardConfig(loss_scaling=False, max_consecutive_skips=2)
    chaos = ChaosConfig(kind="nan", target="grads", every=1, worker=1)
    state, step = _tiny_setup(mesh, comp, gcfg, chaos)
    batch = _batch()
    for i in range(4):
        state, metrics = step(state, batch)
    m = jax.device_get(metrics)
    with tempfile.TemporaryDirectory() as td:
        for fl in recorders(td, chaos):
            fl.note_step(3, m)
            try:
                from tpu_compressed_dp.train.guard import check_guard_metrics
                check_guard_metrics(m, gcfg, flight=fl)
                raise AssertionError("guard did not wedge")
            except GuardExceeded:
                pass
        bundles = check_bundles(td, range(ranks))
        assert all(b["reason"] == "guard_exceeded"
                   for b in bundles.values()), bundles
        v = classify(bundles)
        assert (v["kind"], v["rank"]) == ("nan", 1), v
        assert merge_timeline(bundles), "empty merged timeline"
        verdicts["nan"] = v

    # --- dead_peer: mid-collective kill of worker 2; survivors raise
    # PeerFailed naming it, the dying rank's own injector self-reports
    chaos = ChaosConfig(crash_at_step=1, crash_mode="mid_collective",
                        worker=2)
    with tempfile.TemporaryDirectory() as td:
        fls = recorders(td, chaos)
        crash = CrashInjector(1, mode="mid_collective", worker=2)
        crash.flight = fls[2]
        try:
            crash.check(1, phase="mid_collective")
            raise AssertionError("injector did not fire")
        except ChaosCrash as err:
            fls[2].observe(err)
        for r in (0, 1, 3):
            fls[r].observe(PeerFailed((2,), step=1,
                                      reason="gossip heartbeat stale"))
        bundles = check_bundles(td, range(ranks))
        assert bundles[2]["reason"] == "chaos_crash", bundles[2]
        v = classify(bundles)
        assert (v["kind"], v["rank"]) == ("dead_peer", 2), v
        verdicts["dead_peer"] = v

    # --- preempt: a REAL self-SIGTERM on rank 0, observed through the
    # handler; peers raise PeerFailed — preempt must win the priority
    chaos = ChaosConfig(crash_at_step=0, crash_mode="preempt")
    with tempfile.TemporaryDirectory() as td:
        fls = recorders(td, chaos)
        crash = CrashInjector(0, mode="preempt")
        crash.flight = fls[0]
        handler = PreemptionHandler(log=lambda s: None).install()
        assert handler.installed, "drill must run on the main thread"
        try:
            crash.check(0)          # self-SIGTERM, no raise
            for _ in range(1000):   # signal lands within a few bytecodes
                if handler.triggered:
                    break
                time.sleep(0.001)
            handler.check(0)
            raise AssertionError("preempt never fired")
        except Preempted as err:
            fls[0].observe(err)
        finally:
            handler.uninstall()
        for r in (1, 2, 3):
            fls[r].observe(PeerFailed((0,), step=0, reason="peer exited"))
        bundles = check_bundles(td, range(ranks))
        assert bundles[0]["reason"] == "preempt", bundles[0]
        v = classify(bundles)
        assert (v["kind"], v["rank"]) == ("preempt", 0), v
        verdicts["preempt"] = v

    # --- corruption: flipped payload byte + explicit-step restore — the
    # manifest digest trips and the Checkpointer dumps before raising
    state, step = _tiny_setup(mesh, comp, GuardConfig(loss_scaling=False),
                              None)
    with tempfile.TemporaryDirectory() as td:
        ck_dir, fl_dir = os.path.join(td, "ck"), os.path.join(td, "fl")
        fls = recorders(fl_dir)
        ckpt = Checkpointer(ck_dir, flight=fls[0])
        state, _ = step(state, batch)
        ckpt.save(state, {"step_i": 1})
        ckpt.close()
        _flip_byte_in_step(ck_dir, 1)
        # any structure-matching target works; the restore raises on the
        # manifest digest before it rebuilds state
        ckpt2 = Checkpointer(ck_dir, flight=fls[0])
        try:
            ckpt2.restore(state, step=1)
            raise AssertionError("corrupt restore did not raise")
        except CheckpointCorrupt:
            pass
        finally:
            ckpt2.close()
        bundles = check_bundles(fl_dir, [0])
        assert bundles[0]["reason"] == "ckpt_corrupt", bundles[0]
        v = classify(bundles)
        assert v["kind"] == "corruption", v
        verdicts["corruption"] = v

    # --- control: a clean run dumps NOTHING, and recording is
    # trajectory-neutral (bitwise with vs without a recorder).  Same
    # compiled step + same start state for both trajectories — the only
    # difference is the host-side recorder, which is the claim under test.
    with tempfile.TemporaryDirectory() as td:
        plain = observed = state
        fls = recorders(td)
        for i in range(3):
            plain, _ = step(plain, batch)
            observed, m2 = step(observed, batch)
            for fl in fls:
                fl.note_step(i, jax.device_get(m2))
        for fl in fls:
            fl.publish()  # phase profiles are NOT bundles
        assert read_bundles(td) == {}, "clean run left blackbox bundles"
        _assert_bitwise(_snap(plain), _snap(observed), "forensics control")
    return {"verdicts": {k: v["kind"] for k, v in verdicts.items()},
            "ranks": {k: v["rank"] for k, v in verdicts.items()},
            "clean_bundles": 0, "bitwise": True}


# -------------------------------------------------------------------- main

QUICK = ["skip_consistency", "loss_scale", "max_skips", "crash_recovery",
         "elastic_gossip", "elastic_remesh", "ckpt_preempt", "ckpt_corrupt",
         "stream_corrupt", "control_resume", "fleet", "forensics"]
FULL = QUICK + ["comp_hold", "ef_identity", "poison_control",
                "skip_matrix", "ef_identity_sharded",
                "elastic_readmit", "elastic_cascade", "elastic_matrix",
                "fleet_matrix"]


def expand_rows(names) -> list:
    """The concrete drill rows a name list runs — matrix groups expand to
    their cells, everything else maps 1:1.  ``--list`` prints these and the
    tier-1 registration test (tests/test_chaos_drill.py) keys off them."""
    rows = []
    for name in names:
        if name == "skip_matrix":
            rows += [f"skip[{kind},{target},w{worker}]"
                     for kind in ("nan", "inf")
                     for target in ("grads", "loss")
                     for worker in (0, 7)]
        elif name == "elastic_matrix":
            rows += [f"elastic[{policy},w{worker},s{kill_step}]"
                     for policy in ("fold", "drop")
                     for worker in (0, 7)
                     for kill_step in (0, 3)]
            rows.append("elastic[sharded-wire]")
        elif name == "fleet_matrix":
            rows += ["fleet[fold]", "fleet[drop]", "fleet[rigid]"]
        else:
            rows.append(name)
    return rows


def run_drills(names, mesh=None) -> Dict[str, Dict]:
    mesh = mesh or _mesh()
    results = {}
    for name in names:
        if name == "skip_matrix":
            # the full kind x target x worker cross
            for kind in ("nan", "inf"):
                for target in ("grads", "loss"):
                    for worker in (0, 7):
                        key = f"skip[{kind},{target},w{worker}]"
                        results[key] = drill_skip_consistency(
                            mesh, kind=kind, target=target, worker=worker)
                        print(f"PASS {key}")
            continue
        if name == "elastic_matrix":
            # kill-step x worker x EF-policy cross, plus the wire+sharded
            # variant (owner partition recomputed over W-1)
            for policy in ("fold", "drop"):
                for worker in (0, 7):
                    for kill_step in (0, 3):
                        key = f"elastic[{policy},w{worker},s{kill_step}]"
                        results[key] = drill_elastic_remesh(
                            mesh, kill_step=kill_step, worker=worker,
                            policy=policy)
                        print(f"PASS {key}")
            key = "elastic[sharded-wire]"
            results[key] = drill_elastic_remesh(
                mesh, transport="sharded", mode="wire", worker=5,
                policy="fold")
            print(f"PASS {key}")
            continue
        if name == "fleet_matrix":
            # EF-policy cells through the shrink/readmit barrier, plus the
            # rigid cell (no shrink candidate => evict-only preemption)
            for key, kwargs in (("fleet[fold]", {"policy": "fold"}),
                                ("fleet[drop]", {"policy": "drop"}),
                                ("fleet[rigid]", {"elastic": False})):
                results[key] = drill_fleet(mesh, **kwargs)
                print(f"PASS {key}")
            continue
        if name == "ef_identity_sharded":
            results[name] = drill_ef_identity(mesh, transport="sharded",
                                              mode="wire")
        else:
            results[name] = globals()[f"drill_{name}"](mesh)
        print(f"PASS {name}")
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--quick", action="store_true",
                   help="tier-1 smoke subset (skip_consistency, loss_scale, "
                        "max_skips, crash_recovery, elastic_gossip, "
                        "elastic_remesh, ckpt_preempt, ckpt_corrupt, "
                        "stream_corrupt, control_resume, fleet, forensics)")
    p.add_argument("--drill", action="append", default=None,
                   help="run only the named drill(s)")
    p.add_argument("--list", action="store_true",
                   help="print the quick/slow drill-row matrix (matrix "
                        "groups expanded to their cells) and exit")
    args = p.parse_args(argv)
    if args.list:
        # CI discovery surface: one row per concrete drill, tier-tagged.
        # tests/test_chaos_drill.py asserts every quick row is registered
        # here and collectible (a drill function exists for it).
        slow_only = [n for n in FULL if n not in QUICK]
        print("quick:")
        for row in expand_rows(QUICK):
            print(f"  {row}")
        print("slow:")
        for row in expand_rows(slow_only):
            print(f"  {row}")
        return 0
    names = args.drill or (QUICK if args.quick else FULL)
    run_drills(names)
    print(f"chaos drill: {len(names)} drill group(s) passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
