"""One-command reproduction of the reference's headline numbers the moment a
real dataset lands (VERDICT r4 missing #1 / next #5).

Zero egress blocks the datasets themselves in the build environment; this
tool makes readiness a fact rather than a claim: it autodetects the dataset
under ``--data_dir``, runs the EXACT headline protocol end-to-end through
the same harness entry points the rest of the framework uses, and exits
nonzero unless the reference's number is met.

  CIFAR-10 (default): the DAWNBench protocol the reference's README quotes —
      ResNet-9, bs 512, 24 epochs, dawn lr triangle (peak 0.4 at epoch 5),
      momentum 0.9, Crop/FlipLR/Cutout augmentation.  Asserts test accuracy
      >= 0.94 (`/root/reference/CIFAR10/README.md:3` claims 94% in 79 s on
      V100; `dawn.py:105-110` the protocol).
  ImageNet (--imagenet): the progressive 128->224->288 recipe
      (`IMAGENET/train.py:60-72`), rect-val at 288.  Asserts top-5 >= 0.93
      (`train.py:55-56`).

With no dataset present it prints the expected on-disk manifest and exits 2
("ready, waiting for data") — the same check `--manifest` prints directly.

Usage:
    python tools/reproduce_headline.py --data_dir ./data            # CIFAR
    python tools/reproduce_headline.py --imagenet --data_dir ./imagenet
    python tools/reproduce_headline.py --manifest
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if __package__ in (None, ""):  # script run: repo root onto sys.path
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MANIFEST = {
    "cifar10": {
        "layout": "torchvision CIFAR-10 python format under <data_dir>",
        "files": [
            "cifar-10-batches-py/data_batch_1 .. data_batch_5  (pickle, "
            "10k x {data: uint8[10000,3072] RGB CHW-flattened, labels})",
            "cifar-10-batches-py/test_batch",
            "cifar-10-batches-py/batches.meta",
        ],
        "loader": "tpu_compressed_dp.data.cifar10.load_cifar10 "
                  "(torchvision.datasets.CIFAR10, download=False)",
        "protocol": "ResNet-9 bs512 24ep dawn-lr 0.4 momentum 0.9, "
                    "Crop(32)/FlipLR/Cutout(8) per-epoch presampled",
        "headline": "test_acc >= 0.94 (CIFAR10/README.md:3)",
    },
    "imagenet": {
        "layout": "ImageFolder: <data_dir>/train/<wnid>/*.JPEG, "
                  "<data_dir>/validation/<wnid>/*.JPEG (1000 wnid dirs — "
                  "'validation', matching the reference's fast-imagenet "
                  "layout and harness/imagenet.py)",
        "loader": "tpu_compressed_dp.data.imagenet.ImageFolder (+ persisted "
                  "aspect-ratio index for rect-val)",
        "protocol": "ResNet-50 progressive 128->224->288 phase schedule "
                    "(IMAGENET/train.py:60-72), rect-val 288, bn0 init, "
                    "label smoothing off, bs per train.py",
        "headline": "top5 >= 0.93 (IMAGENET/train.py:55-56)",
    },
}


def detect_cifar(data_dir: str) -> bool:
    d = os.path.join(data_dir, "cifar-10-batches-py")
    return all(os.path.exists(os.path.join(d, f))
               for f in ["data_batch_1", "data_batch_5", "test_batch"])


def detect_imagenet(data_dir: str) -> bool:
    # the harness loads <data_dir>/validation (reference fast-imagenet layout)
    t = os.path.join(data_dir, "train")
    v = os.path.join(data_dir, "validation")
    if not (os.path.isdir(t) and os.path.isdir(v)):
        return False
    classes = [x for x in os.listdir(t) if os.path.isdir(os.path.join(t, x))]
    return len(classes) >= 2


def run_cifar(args) -> int:
    from tpu_compressed_dp.harness import dawn

    t0 = time.time()
    summary = dawn.main([
        "--data_dir", args.data_dir,
        "--network", "resnet9",
        "--batch_size", "512",
        "--momentum", "0.9",
        "--peak_lr", "0.4",
        "--log_dir", args.log_dir,
    ] + (["--dtype", "bfloat16"] if args.bf16 else []))
    wall = time.time() - t0
    acc = float(summary["test acc"])
    verdict = "PASS" if acc >= args.cifar_target else "FAIL"
    print(json.dumps({
        "protocol": "cifar10-dawnbench-24ep", "test_acc": acc,
        "target": args.cifar_target, "verdict": verdict,
        "wall_s": round(wall, 1),
        "reference_claim": "94% in 79 s on one V100 (CIFAR10/README.md:3)",
    }))
    return 0 if verdict == "PASS" else 1


def run_imagenet(args) -> int:
    from tpu_compressed_dp.harness import imagenet as inet

    t0 = time.time()
    # positional data root; phases default None = the reference one-machine
    # 128->224->288 schedule; best-gated checkpointing at the 93 floor
    argv = [args.data_dir, "--arch", "resnet50", "--init_bn0", "--no_bn_wd",
            "--best_floor", "93.0"]
    if args.log_dir:
        argv += ["--logdir", args.log_dir]
    summary = inet.main(argv)
    wall = time.time() - t0
    top5 = float(summary.get("top5", 0.0)) / 100.0
    verdict = "PASS" if top5 >= args.imagenet_target else "FAIL"
    print(json.dumps({
        "protocol": "imagenet-progressive-128-224-288", "top5": top5,
        "target": args.imagenet_target, "verdict": verdict,
        "wall_s": round(wall, 1),
        "reference_claim": "93.0 top-5 (IMAGENET/train.py:55-56)",
    }))
    return 0 if verdict == "PASS" else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_dir", default="./data")
    ap.add_argument("--log_dir", default="")
    ap.add_argument("--imagenet", action="store_true")
    ap.add_argument("--manifest", action="store_true",
                    help="print the expected on-disk formats and exit")
    ap.add_argument("--bf16", action="store_true",
                    help="CIFAR protocol in bf16 compute (fp32 is the "
                         "parity default)")
    ap.add_argument("--cifar_target", type=float, default=0.94)
    ap.add_argument("--imagenet_target", type=float, default=0.93)
    args = ap.parse_args(argv)

    if args.manifest:
        print(json.dumps(MANIFEST, indent=2))
        return 0
    which = "imagenet" if args.imagenet else "cifar10"
    found = (detect_imagenet if args.imagenet else detect_cifar)(args.data_dir)
    if not found:
        print(f"# no {which} dataset under {args.data_dir!r}; expected layout:",
              file=sys.stderr)
        print(json.dumps(MANIFEST[which], indent=2), file=sys.stderr)
        return 2
    return run_imagenet(args) if args.imagenet else run_cifar(args)


if __name__ == "__main__":
    raise SystemExit(main())
