#!/usr/bin/env python
"""Delta-stream economics bench: rejoin bytes/wall-time delta-vs-full and
steady-state stream bytes per window vs full-checkpoint bytes, at the tiny
LM config (models/transformer.py ``tiny_llama``).

Two questions, each answered as a record pair (delta stream vs full
checkpoint) so the BENCH json reads as a direct comparison:

  * **rejoin** — a relaunched host needs the live params.  Warm path:
    :class:`~tpu_compressed_dp.stream.reader.StreamReader` catch-up over
    the segment stream (what ``--stream_rejoin`` does before the join
    barrier, which then SKIPS the params broadcast).  Full path: an Orbax
    restore of the newest checkpoint.  Reported: bytes moved and wall
    seconds for each, plus the ratio.
  * **steady state** — what one append window costs on disk vs one full
    checkpoint save at the same cadence: keyframe bytes, per-delta bytes,
    amortised bytes/window at ``--keyframe_every``, vs the Orbax step dir
    + manifest.

CPU-honest caveats: wall times are host/filesystem numbers on whatever
machine runs this (no TPU in the loop — the codec's select+pack runs
through the same wire kernels tier-1 exercises); parameter updates are
synthetic per-step perturbations (every coordinate moves, like an
optimizer step, which is the property that sizes a delta), not real LM
training.  The byte accounting — the point of this bench — is exact.

    python tools/stream_bench.py --out BENCH_r12.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import numpy as np


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def _perturb(params, rng, scale=1e-3):
    """Synthetic optimizer step: every coordinate moves a little — the
    worst case for a delta codec and the realistic one."""
    return jax.tree.map(
        lambda p: (p + (rng.standard_normal(p.shape) * scale
                        ).astype(np.float32)), params)


def run(out: str, *, ratio: float, keyframe_every: int, steps: int,
        seed: int) -> dict:
    import tempfile

    from tpu_compressed_dp.models.transformer import init_llama, tiny_llama
    from tpu_compressed_dp.stream.delta import flatten_params
    from tpu_compressed_dp.stream.reader import StreamReader
    from tpu_compressed_dp.stream.store import (list_segments,
                                                read_segment_manifest)
    from tpu_compressed_dp.stream.writer import StreamWriter
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState
    from tpu_compressed_dp.utils.checkpoint import Checkpointer

    cfg = tiny_llama()
    params = jax.tree.map(np.asarray,
                          init_llama(cfg, jax.random.key(seed)))
    vec, _ = flatten_params(params)
    n_params = int(vec.size)
    opt = SGD(lr=0.1, momentum=0.9)
    rng = np.random.default_rng(seed)

    records: List[dict] = []
    with tempfile.TemporaryDirectory() as td:
        sd = os.path.join(td, "stream")
        cd = os.path.join(td, "ckpt")
        w = StreamWriter(sd, ratio=ratio, keyframe_every=keyframe_every,
                         log=lambda *a, **k: None)
        state = TrainState.create(params, {}, opt.init(params), (),
                                  jax.random.key(seed))
        ckpt = Checkpointer(cd)

        # -- steady state: stream every synthetic step, checkpoint once
        t0 = time.monotonic()
        for i in range(steps):
            params = _perturb(params, rng)
            w.append(params, step=i + 1)
        append_s = time.monotonic() - t0
        import dataclasses
        state = dataclasses.replace(state, params=params,
                                    step=state.step + steps)
        t0 = time.monotonic()
        ckpt.save(state, {"step": steps})
        ckpt_save_s = time.monotonic() - t0
        ckpt.close()
        ckpt_bytes = _dir_bytes(cd)

        seg_rows = []
        for q in list_segments(sd):
            man = read_segment_manifest(sd, q)
            seg_rows.append({"seq": q, "kind": man["kind"],
                             "step": man["step"], "bytes": man["bytes"],
                             "nnz": man["nnz"],
                             "window_close": man["window_close"]})
        kf_bytes = [r["bytes"] for r in seg_rows if r["kind"] == "keyframe"]
        mid_bytes = [r["bytes"] for r in seg_rows
                     if r["kind"] == "delta" and not r["window_close"]]
        flush_bytes = [r["bytes"] for r in seg_rows
                       if r["kind"] == "delta" and r["window_close"]]
        stream_total = sum(r["bytes"] for r in seg_rows)
        # one window = keyframe + (keyframe_every - 2) Top-K deltas + the
        # window-closing flush (dense under these synthetic updates)
        window_bytes = (float(np.mean(kf_bytes))
                        + (keyframe_every - 2)
                        * float(np.mean(mid_bytes or [0.0]))
                        + float(np.mean(flush_bytes or [0.0])))

        # -- rejoin: warm catch-up vs full Orbax restore
        w.sync(params, step=steps)   # the barrier flush survivors perform
        t0 = time.monotonic()
        r = StreamReader(sd, log=lambda *a, **k: None)
        r.catch_up()
        warm = {"bytes": int(r.bytes_read),
                "segments": int(r.segments_applied),
                "wall_s": round(time.monotonic() - t0, 4),
                "exact": bool(r.exact)}
        pvec, _ = flatten_params(params)
        rvec, _ = flatten_params(r.params_like(params))
        assert np.array_equal(pvec.view(np.int32), rvec.view(np.int32)), (
            "warm rejoin reconstruction not bitwise")

        fresh = TrainState.create(
            jax.tree.map(np.zeros_like, params), {},
            opt.init(params), (), jax.random.key(seed + 1))
        t0 = time.monotonic()
        restore = Checkpointer(cd)
        restored, _meta = restore.restore(fresh)
        restore.close()
        full = {"bytes": int(ckpt_bytes),
                "wall_s": round(time.monotonic() - t0, 4)}
        fvec, _ = flatten_params(jax.tree.map(np.asarray, restored.params))
        assert np.array_equal(pvec.view(np.int32), fvec.view(np.int32)), (
            "full restore not bitwise")
        w.close()

    dense_bytes = n_params * 4
    result = {
        "n": len(seg_rows),
        "cmd": ("JAX_PLATFORMS=cpu python tools/stream_bench.py "
                f"--out {os.path.basename(out)} --ratio {ratio} "
                f"--keyframe_every {keyframe_every} --steps {steps} "
                f"--seed {seed}"),
        "rc": 0,
        "note": ("CPU smoke: wall times are host/filesystem numbers (no "
                 "TPU in the loop); updates are synthetic per-step "
                 "perturbations where EVERY coordinate moves (optimizer-"
                 "step-like, the dense worst case for the flush); byte "
                 "accounting is exact.  Rejoin reads the newest keyframe "
                 "window only (fresh-reader seek); both reconstructions "
                 "are asserted bitwise against the live params.  The "
                 "full-checkpoint bytes are the whole Orbax step dir "
                 "(params + SGD momentum, zstd-compressed)."),
        "config": {"model": "tiny_llama", "n_params": n_params,
                   "dense_param_bytes": dense_bytes, "ratio": ratio,
                   "keyframe_every": keyframe_every, "steps": steps},
        "rejoin": {
            "warm_stream": warm,
            "full_orbax": full,
            "bytes_ratio_warm_over_full": round(
                warm["bytes"] / max(full["bytes"], 1), 4),
            "wall_ratio_warm_over_full": round(
                warm["wall_s"] / max(full["wall_s"], 1e-9), 4),
        },
        "steady_state": {
            "keyframe_bytes_mean": round(float(np.mean(kf_bytes)), 1),
            "delta_mid_bytes_mean": round(
                float(np.mean(mid_bytes or [0.0])), 1),
            "flush_bytes_mean": round(
                float(np.mean(flush_bytes or [0.0])), 1),
            "window_bytes_amortised": round(window_bytes, 1),
            "bytes_per_append_amortised": round(
                window_bytes / keyframe_every, 1),
            "full_ckpt_bytes": int(ckpt_bytes),
            "full_ckpt_save_s": round(ckpt_save_s, 4),
            "append_s_total": round(append_s, 4),
            "append_ratio_vs_full_ckpt": round(
                (window_bytes / keyframe_every) / max(ckpt_bytes, 1), 6),
            "stream_total_bytes": stream_total,
        },
        "records": seg_rows,
    }
    with open(out + ".tmp", "w") as f:
        json.dump(result, f, indent=1)
    os.replace(out + ".tmp", out)
    return result


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--out", type=str, default="stream_bench.json")
    p.add_argument("--ratio", type=float, default=0.01)
    p.add_argument("--keyframe_every", type=int, default=8)
    p.add_argument("--steps", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    res = run(args.out, ratio=args.ratio,
              keyframe_every=args.keyframe_every, steps=args.steps,
              seed=args.seed)
    rj, ss = res["rejoin"], res["steady_state"]
    print(f"params: {res['config']['n_params']} "
          f"({res['config']['dense_param_bytes']} dense bytes)")
    print(f"rejoin warm: {rj['warm_stream']['bytes']} B "
          f"{rj['warm_stream']['wall_s']} s | full: "
          f"{rj['full_orbax']['bytes']} B {rj['full_orbax']['wall_s']} s "
          f"| bytes x{rj['bytes_ratio_warm_over_full']}")
    print(f"steady state: {ss['bytes_per_append_amortised']} B/append "
          f"vs {ss['full_ckpt_bytes']} B/full-ckpt "
          f"(x{ss['append_ratio_vs_full_ckpt']})")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
