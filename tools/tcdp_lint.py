#!/usr/bin/env python
"""tcdp-lint — two-pass static analyzer for the tpu_compressed_dp tree.

Pass 1 (``--spmd``) traces both sync engines and all three step factories
to jaxprs on a virtual CPU mesh and verifies the SPMD safety contract:
no collectives under worker-divergent control flow (TCDP001), ordered
collective-signature determinism across retraces / engine pairs / the
chunked schedule (TCDP002), donation that can actually alias (TCDP003),
overlap chunk-plan + optimization_barrier chain integrity (TCDP004), and
per-config jaxpr equation budgets that catch accidental unrolling
(TCDP005).  The trace matrix includes the fused compressor kernels under
``pallas_mode`` off AND force, pinning the collective signature across
the kernel toggle.

Pass 2 (``--host``) is an AST walk over the package and ``tools/``
enforcing host-side invariants: no wall-clock reads in replay-
deterministic modules (TCDP101), atomic tmp+``os.replace`` writes to
shared directories (TCDP102), stat-key literals declared in the obs
registry (TCDP103), named_scope strings in the ``tcdp.<phase>`` taxonomy
(TCDP104), and lock-guarded thread-shared attributes (TCDP105).

Usage::

    python tools/tcdp_lint.py                # both passes, human output
    python tools/tcdp_lint.py --json         # machine-readable findings
    python tools/tcdp_lint.py --host         # host pass only (sub-second)
    python tools/tcdp_lint.py --spmd --profile full   # whole 9x2x2x3 matrix
    python tools/tcdp_lint.py --diff HEAD~1  # changed files only (pre-commit)

Suppress a finding with a justified inline pragma::

    t = time.time()  # tcdp-lint: disable=TCDP101 -- operator-facing log only

Exit code 0 iff zero active findings.  Both passes are pure tracing /
parsing — no compilation — so the full run takes seconds on CPU.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _changed_files(rev: str) -> Optional[List[str]]:
    """Repo-relative paths changed since ``rev`` (committed + worktree)."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", rev, "--"],
            cwd=_REPO_ROOT, capture_output=True, text=True, check=True,
            timeout=30).stdout
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        print(f"tcdp-lint: --diff {rev}: {e}", file=sys.stderr)
        return None
    return [ln.strip() for ln in out.splitlines() if ln.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tcdp-lint", description=__doc__.splitlines()[0])
    ap.add_argument("--spmd", action="store_true",
                    help="run only pass 1 (jaxpr SPMD analysis)")
    ap.add_argument("--host", action="store_true",
                    help="run only pass 2 (host-side AST lint)")
    ap.add_argument("--profile", choices=("quick", "full"), default="full",
                    help="SPMD matrix size (default: full; tier-1 uses "
                         "quick)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit machine-readable JSON on stdout")
    ap.add_argument("--diff", metavar="REV", default=None,
                    help="lint only files changed since REV (fast "
                         "pre-commit path; skips pass 1 unless traced "
                         "modules changed)")
    args = ap.parse_args(argv)
    run_spmd = args.spmd or not args.host
    run_host = args.host or not args.spmd

    host_files = None
    if args.diff is not None:
        changed = _changed_files(args.diff)
        if changed is None:
            return 2
        host_files = [f for f in changed if f.endswith(".py") and (
            f.startswith("tpu_compressed_dp/") or f.startswith("tools/"))]
        # pass 1 traces whole subsystems, not files: only worth running
        # when a traced module changed
        traced_prefixes = ("tpu_compressed_dp/parallel/",
                           "tpu_compressed_dp/train/",
                           "tpu_compressed_dp/models/",
                           "tpu_compressed_dp/ops/",
                           "tpu_compressed_dp/analysis/")
        if run_spmd and not any(f.startswith(traced_prefixes)
                                for f in host_files):
            run_spmd = False
        if not host_files:
            run_host = False

    t0 = time.time()
    active = []
    suppressed = []
    stats = {}

    if run_host:
        from tpu_compressed_dp.analysis.hostlint import run_host_pass
        abs_files = (None if host_files is None else
                     [os.path.join(_REPO_ROOT, f) for f in host_files
                      if os.path.exists(os.path.join(_REPO_ROOT, f))])
        a, s = run_host_pass(_REPO_ROOT, files=abs_files)
        active += a
        suppressed += s
        stats["host_files"] = (len(host_files) if host_files is not None
                               else "all")

    if run_spmd:
        # virtual 8-device CPU mesh: XLA_FLAGS must land before the first
        # backend use, and on hosts whose sitecustomize pre-imports a TPU
        # plugin the env alone is too late — force the platform on the
        # config as well (lint is pure tracing; it must never take a chip)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")
        from tpu_compressed_dp.analysis.spmd import run_spmd_pass
        f, spmd_stats = run_spmd_pass(args.profile)
        active += f
        stats.update(spmd_stats)

    elapsed = time.time() - t0
    if args.as_json:
        from tpu_compressed_dp.analysis.report import findings_to_json
        payload = findings_to_json(active, suppressed)
        payload["elapsed_s"] = round(elapsed, 2)
        payload["stats"] = stats
        json.dump(payload, sys.stdout, indent=2)
        print()
    else:
        from tpu_compressed_dp.analysis.report import format_findings
        body = format_findings(list(active) + list(suppressed))
        if body:
            print(body, file=sys.stderr)
        print(f"tcdp-lint: {len(active)} finding(s), "
              f"{len(suppressed)} suppressed, {elapsed:.1f}s "
              f"({stats})", file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    if __package__ in (None, ""):
        sys.path.insert(0, _REPO_ROOT)
    sys.exit(main())
