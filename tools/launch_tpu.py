#!/usr/bin/env python3
"""Multi-host launcher for TPU pods and local multi-process testing.

The reference's cluster launcher (`IMAGENET/train.py`) provisions AWS
machines via ncluster, builds NCCL ring-order env strings, and runs
``torch.distributed.launch``/``mpirun`` per node (`train.py:290-449`).  On
Cloud TPU there is nothing to provision per-worker and no ring strings: every
host of a pod slice runs the SAME command; ``jax.distributed.initialize``
auto-detects the coordinator from the TPU metadata; XLA routes collectives
over ICI/DCN from the mesh layout.  So the launcher reduces to:

  gcloud mode (default) — print or run the one gcloud command that fans the
  training command to all workers:
    python tools/launch_tpu.py --tpu my-pod --zone us-central2-b -- \
        python -m tpu_compressed_dp.harness.imagenet /data --arch resnet50
  Add ``--run`` to execute (needs gcloud auth); default prints it (dry run).

  local mode — spawn N local processes with an explicit rendezvous on
  127.0.0.1, for testing the multi-process code path without hardware (each
  process gets JAX_PLATFORMS=cpu and a slice of
  xla_force_host_platform_device_count devices):
    python tools/launch_tpu.py --local_procs 2 --devices_per_proc 2 -- \
        python -m tpu_compressed_dp.harness.imagenet --synthetic ...
  The harnesses pick up --coordinator/--num_processes/--process_id from the
  injected TPU_CDP_* environment (or accept them as flags).
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys


def tpu_ssh_cmd(tpu: str, zone: str, worker: str, command: str) -> list:
    """The one gcloud TPU-VM ssh invocation every fan-out tool shares
    (also used by tools/dataset_tools.py)."""
    return [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu,
        f"--zone={zone}", f"--worker={worker}", f"--command={command}",
    ]


def build_gcloud_cmd(args, train_cmd: list) -> list:
    inner = " ".join(shlex.quote(c) for c in train_cmd)
    return tpu_ssh_cmd(args.tpu, args.zone, "all",
                       f"cd {shlex.quote(args.workdir)} && {inner}")


def run_local(args, train_cmd: list) -> int:
    port = args.port
    procs = []
    for rank in range(args.local_procs):
        env = dict(os.environ)
        # replace (not append) any inherited device-count flag: duplicated
        # XLA flags are an error, and the parent may be a test process that
        # already forced its own count.  (Inline rather than
        # mesh.force_host_devices: the launcher must not import jax.)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={args.devices_per_proc}")
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": " ".join(flags),
            "TPU_CDP_COORDINATOR": f"127.0.0.1:{port}",
            "TPU_CDP_NUM_PROCESSES": str(args.local_procs),
            "TPU_CDP_PROCESS_ID": str(rank),
        })
        cmd = train_cmd + [
            "--coordinator", f"127.0.0.1:{port}",
            "--num_processes", str(args.local_procs),
            "--process_id", str(rank),
        ]
        procs.append(subprocess.Popen(cmd, env=env))
    # wait on EVERY rank (short-circuiting after the first failure would
    # orphan the rest mid-rendezvous, holding the coordinator port)
    rcs = [p.wait() for p in procs]
    return next((rc for rc in rcs if rc), 0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--tpu", type=str, default=None, help="TPU pod/slice name")
    p.add_argument("--zone", type=str, default="us-central2-b")
    p.add_argument("--workdir", type=str, default="~/tpu_compressed_dp")
    p.add_argument("--run", action="store_true",
                   help="execute the gcloud command (default: print it)")
    p.add_argument("--local_procs", type=int, default=None,
                   help="spawn N local processes instead of gcloud")
    p.add_argument("--devices_per_proc", type=int, default=2)
    p.add_argument("--port", type=int, default=29431)
    p.add_argument("train_cmd", nargs=argparse.REMAINDER,
                   help="training command after --")
    args = p.parse_args(argv)

    train_cmd = args.train_cmd
    if train_cmd and train_cmd[0] == "--":
        train_cmd = train_cmd[1:]
    if not train_cmd:
        p.error("no training command given (append it after --)")

    if args.local_procs:
        return run_local(args, train_cmd)

    if not args.tpu:
        p.error("--tpu NAME required for gcloud mode (or use --local_procs)")
    cmd = build_gcloud_cmd(args, train_cmd)
    print(" ".join(shlex.quote(c) for c in cmd))
    if args.run:
        return subprocess.call(cmd)
    return 0


if __name__ == "__main__":
    sys.exit(main())
