#!/usr/bin/env python
"""Offline report of the adaptive-compression control loop.

Reads a JSONL telemetry event stream (harness ``--events``) from an
``--adaptive`` run and renders, from the ``control_decision`` records the
controller emits at every window close plus the ``control`` metric dict
the epoch/step records carry:

  * the **rung trajectory** — which ladder rung (and knob value) the
    controller sat on at each decision, with the direction it moved;
  * the **per-window comm/compute balance** — the modeled-or-measured
    comm time each window against the hideable-compute budget the
    ``sync_overlap`` chunk schedule exposes, i.e. the signal the
    controller steers on;
  * a one-line **summary** — decisions taken, moves by direction, final
    rung, and whether the loop converged (last K windows held).

With ``--twin_records <dir>`` every decision row also carries a **twin
ms** column: the window's billed bits re-priced through the calibrated
per-fabric digital twin (``tpu_compressed_dp/twin/``) next to the flat
``--adaptive_bw_mbps`` price the controller steered on — the audit of
what each rung decision WOULD have seen under the schedule-aware model.
Topology defaults come from the ``run_start`` record and can be
overridden (``--twin_world/--twin_pods/--twin_transport``).

Usage::

    python tools/control_report.py events.jsonl
    python tools/control_report.py events.jsonl --json
    python tools/control_report.py events.jsonl --twin_records .
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from tpu_compressed_dp.obs.export import SCHEMA_VERSION, read_events

WINDOW_KINDS = ("epoch", "step")  # records that carry the control dict


def check_schema(events: List[Dict[str, Any]]) -> None:
    vs = {e.get("v") for e in events}
    unknown = vs - {SCHEMA_VERSION}
    if unknown:
        raise ValueError(
            f"event stream carries unknown schema version(s) {sorted(unknown)}"
            f" (this tool understands v{SCHEMA_VERSION})")


def decision_rows(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """All ``control_decision`` records, in stream order."""
    return [e for e in events if e.get("kind") == "control_decision"]


def window_rows(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One row per epoch/step window that carries control metrics.  When
    the run billed per-fabric (``--transport hierarchical``/``--dp_pods``),
    each row also carries the DCN-billed share of the wire bits — the
    series the controller's modeled signal prices on a 2-level topology."""
    rows = []
    for e in events:
        if e.get("kind") not in WINDOW_KINDS:
            continue
        c = e.get("control") or {}
        if not c:
            continue
        comm = e.get("comm") or {}
        row = {
            "window": e.get("epoch", e.get("step", "?")),
            "kind": e["kind"],
            "rung": c.get("control/rung"),
            "value": c.get("control/value"),
            "decisions": c.get("control/decisions"),
            "comm_ms": c.get("control/comm_ms"),
            "budget_ms": c.get("control/budget_ms"),
        }
        if comm.get("comm/sent_bits_dcn") or comm.get("comm/sent_bits_ici"):
            row["dcn_bits"] = comm.get("comm/sent_bits_dcn", 0.0)
            row["ici_bits"] = comm.get("comm/sent_bits_ici", 0.0)
        rows.append(row)
    return rows


def build_pricer(events: List[Dict[str, Any]], twin_records: str,
                 world: Optional[int] = None, pods: Optional[int] = None,
                 transport: Optional[str] = None):
    """A :class:`~tpu_compressed_dp.control.signals.TwinPricer` for this
    run: twin fitted from ``twin_records``, topology from the
    ``run_start`` record unless overridden."""
    from tpu_compressed_dp.control.signals import TwinPricer
    from tpu_compressed_dp.twin import calibration_rows, fit

    start = next((e for e in events if e.get("kind") == "run_start"), {})
    rows = calibration_rows(twin_records)
    tr = transport or str(start.get("transport") or "psum")
    if tr == "allgather":
        tr = "all_gather"
    return TwinPricer(
        model=fit(rows).model,
        world=int(world or start.get("devices") or start.get("world") or 8),
        pods=int(pods or start.get("dp_pods") or 1),
        transport=tr, calib_rows=len(rows))


def attach_twin_price(rows: List[Dict[str, Any]], pricer) -> None:
    """Add ``twin_comm_ms`` next to each row's flat-priced ``comm_ms``."""
    for r in rows:
        bits = r.get("bits")
        if isinstance(bits, (int, float)):
            r["twin_comm_ms"] = pricer.comm_ms(float(bits))


def summarize(decisions: List[Dict[str, Any]],
              hold_tail: int = 3) -> Dict[str, Any]:
    """Aggregate the decision stream: move counts, final rung/value, and
    a convergence verdict (the last ``hold_tail`` decisions all held)."""
    by_dir: Dict[str, int] = {}
    for d in decisions:
        by_dir[d.get("direction", "?")] = by_dir.get(
            d.get("direction", "?"), 0) + 1
    tail = decisions[-hold_tail:]
    converged = (len(tail) == hold_tail
                 and all(d.get("direction") == "hold" for d in tail))
    last = decisions[-1] if decisions else {}
    return {
        "decisions": len(decisions),
        "by_direction": by_dir,
        "knob": last.get("knob"),
        "final_rung": last.get("rung_to"),
        "final_value": last.get("value_to"),
        "converged": converged,
    }


def _fmt(v: Optional[float], spec: str = "9.2f") -> str:
    return format(v, spec) if isinstance(v, (int, float)) else " " * 6 + "-"


def render_report(events: List[Dict[str, Any]], pricer=None) -> str:
    check_schema(events)
    lines = []
    start = next((e for e in events if e.get("kind") == "run_start"), {})
    ctx = {k: v for k, v in start.items() if k not in ("v", "kind", "ts")}
    lines.append(f"run: {json.dumps(ctx)}")

    decs = decision_rows(events)
    if pricer is not None:
        attach_twin_price(decs, pricer)
        lines.append(f"twin: W={pricer.world} pods={pricer.pods} "
                     f"transport={pricer.transport} "
                     f"calib_rows={pricer.calib_rows}")
    lines.append("")
    lines.append("rung trajectory (one row per closed window):")
    lines.append(f"  {'#':>4}{'applied':>9}{'updates':>9}{'rung':>6}"
                 f"{'value':>9}{'comm ms':>9}"
                 + (f"{'twin ms':>9}" if pricer is not None else "")
                 + f"{'budget ms':>10}{'bits/upd':>11}  move")
    for d in decs:
        move = d.get("direction", "?")
        if move != "hold":
            move += (f" ({d.get('value_from')} -> {d.get('value_to')})")
        lines.append(
            f"  {d.get('index', '?'):>4}{d.get('applied', '?'):>9}"
            f"{d.get('updates', '?'):>9}{d.get('rung_to', '?'):>6}"
            f"{_fmt(d.get('value_to'), '9.4g')}"
            f"{_fmt(d.get('comm_ms'))}"
            + (f"{_fmt(d.get('twin_comm_ms'))}" if pricer is not None
               else "")
            + f"{_fmt(d.get('budget_ms'), '10.2f')}"
            f"{_fmt(d.get('bits'), '11.3g')}  {move}")
    if not decs:
        lines.append("  (no control_decision records — was the run "
                     "launched with --adaptive?)")

    wins = window_rows(events)
    if wins:
        fabric = any("dcn_bits" in r for r in wins)
        lines.append("")
        lines.append("per-window balance (epoch/step records):")
        lines.append(f"  {'window':>8}{'rung':>6}{'value':>9}"
                     f"{'comm ms':>9}{'budget ms':>10}{'decisions':>11}"
                     + (f"{'dcn b/upd':>11}{'ici b/upd':>11}" if fabric
                        else ""))
        for r in wins:
            lines.append(
                f"  {r['window']:>8}{_fmt(r['rung'], '6.0f')}"
                f"{_fmt(r['value'], '9.4g')}{_fmt(r['comm_ms'])}"
                f"{_fmt(r['budget_ms'], '10.2f')}"
                f"{_fmt(r['decisions'], '11.0f')}"
                + (f"{_fmt(r.get('dcn_bits'), '11.3g')}"
                   f"{_fmt(r.get('ici_bits'), '11.3g')}" if fabric else ""))

    s = summarize(decs)
    lines.append("")
    lines.append(
        f"summary: {s['decisions']} decision(s) "
        f"{json.dumps(s['by_direction'])} knob={s['knob']} "
        f"final rung={s['final_rung']} value={s['final_value']} "
        f"converged={s['converged']}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("events", help="JSONL event stream (harness --events)")
    p.add_argument("--json", action="store_true",
                   help="emit decisions/windows/summary as JSON")
    p.add_argument("--twin_records", default=None,
                   help="dir of BENCH/MULTICHIP record files; when given, "
                        "decision rows gain a twin-priced comm column")
    p.add_argument("--twin_world", type=int, default=None,
                   help="override twin topology: data-parallel world size")
    p.add_argument("--twin_pods", type=int, default=None,
                   help="override twin topology: DCN pod count")
    p.add_argument("--twin_transport", default=None,
                   help="override twin transport schedule "
                        "(psum|all_gather|sharded|hierarchical)")
    args = p.parse_args(argv)
    events = read_events(args.events)
    pricer = None
    if args.twin_records is not None:
        pricer = build_pricer(events, args.twin_records,
                              world=args.twin_world, pods=args.twin_pods,
                              transport=args.twin_transport)
    if args.json:
        check_schema(events)
        decs = decision_rows(events)
        if pricer is not None:
            attach_twin_price(decs, pricer)
        print(json.dumps({"decisions": decs,
                          "windows": window_rows(events),
                          "summary": summarize(decs)}, indent=2))
    else:
        print(render_report(events, pricer=pricer))
    return 0


if __name__ == "__main__":
    sys.exit(main())
