"""Measured-vs-analytic transport validation (VERDICT r4 #4).

The paper MEASURED network traffic at the NIC (`IMAGENET/training/meter.py:
24-47,66-86`); this repo's transport numbers have so far been analytic
(``utils/meters.per_chip_traffic_bytes`` over the measured payload bytes each
sync hands its collective).  This tool closes the loop: it runs a REAL
two-process data-parallel sync on the CPU backend (collectives ride gRPC over
localhost), samples ``lo`` interface bytes around a timed window of sync
steps, and compares measured bytes/step against the analytic model.

Loopback accounting: every payload byte a rank sends appears once in ``lo``
TX and once in ``lo`` RX; we compare ``lo`` TX delta against the sum over
ranks of per-rank transmitted bytes.  A heartbeat-control window (same
duration, zero sync steps) is subtracted to remove coordination-service
baseline traffic.  Expect ratio slightly above 1 (gRPC/TCP framing, ack
overhead) — the point is the SLOPE: payload doubling must double measured
bytes, and method ordering (dense > qsgd > topk-1% > …) must match.

Usage:
    python tools/validate_transport.py --out benchmarks/transport_validation_r5.tsv
(spawns its own two worker subprocesses; CPU-only, no chip contention)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

if __package__ in (None, ""):  # script run: repo root onto sys.path
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PARAM = 2_000_000        # synthetic gradient size (fp32: 8 MB dense payload)
PORT = 12378

CASES = [
    # label, method, mode, ratio/extra
    ("dense", None, "simulate", {}),
    ("topk-1%-wire-EF", "topk", "wire", {"ratio": 0.01, "error_feedback": True}),
    ("blocktopk-1%-wire-EF", "blocktopk", "wire",
     {"ratio": 0.01, "error_feedback": True, "block_size": 256}),
    # owner-sharded transport: the all_to_all route stage bills at
    # (W-1)/W x payload per rank, the shard-return all_gather at (W-1) x —
    # the sent_bits_alltoall bucket closes the measured-vs-analytic loop
    # for the third collective
    ("topk-1%-wire-EF-sharded", "topk", "wire",
     {"ratio": 0.01, "error_feedback": True, "transport": "sharded"}),
    # hierarchical transport on a 2x1 virtual mesh: each process is its own
    # pod (C=1, no intra-pod psum), so the measured loopback bytes are
    # EXACTLY the inter-pod route/return collectives the sent_bits_dcn
    # bucket bills — the per-fabric split's measured-vs-analytic closure
    ("topk-1%-wire-EF-hier", "topk", "wire",
     {"ratio": 0.01, "error_feedback": True, "transport": "hierarchical",
      "dp_pods": 2}),
    ("terngrad-wire", "terngrad", "wire", {}),
]


def lo_bytes():
    with open("/proc/net/dev") as f:
        for line in f.read().splitlines()[2:]:
            iface, _, rest = line.partition(":")
            if iface.strip() == "lo":
                cols = rest.split()
                return int(cols[0]), int(cols[8])
    return 0, 0


def worker(args) -> None:
    """Rank entry: real jax.distributed 2-process CPU mesh, N sync steps."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"127.0.0.1:{args.port}", args.procs, args.rank)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from tpu_compressed_dp.compat import shard_map
    from tpu_compressed_dp.parallel.dp import CompressionConfig, make_grad_sync

    _, method, mode, extra = next(c for c in CASES if c[0] == args.case)
    cfg = CompressionConfig(
        method=method, granularity="entiremodel", mode=mode,
        ratio=extra.get("ratio", 0.01),
        block_size=extra.get("block_size", 256),
        transport=extra.get("transport", "allgather"),
        dp_pods=extra.get("dp_pods", 1),
        error_feedback=extra.get("error_feedback", False))
    sync = make_grad_sync(cfg, "data")
    mesh = Mesh(np.array(jax.devices()), ("data",))
    assert len(jax.devices()) == args.procs
    from jax.sharding import NamedSharding

    def one(g, ef):
        # identical key on every rank (the shared-seed contract wire
        # randomk/quantizer dither relies on)
        key = jax.random.key(7)
        synced, new_ef, _, stats = sync(
            {"g": g}, {"g": ef} if cfg.error_feedback else (), (), key)
        out = synced["g"]
        nef = new_ef["g"] if cfg.error_feedback else ef
        return out, nef, stats

    f = jax.jit(shard_map(
        one, mesh=mesh,
        in_specs=(P("data"), P("data")),
        # synced gradient is replicated post-reduction; EF stays per-rank
        out_specs=(P(), P("data"), P())))
    rng = np.random.default_rng(args.rank)
    sharded = NamedSharding(mesh, P("data"))
    nl = N_PARAM // args.procs
    g = jax.make_array_from_process_local_data(
        sharded, rng.standard_normal((1, nl)).astype(np.float32))
    ef = jax.make_array_from_process_local_data(
        sharded, np.zeros((1, nl), np.float32))
    # warmup/compile
    out, ef, stats = f(g, ef)
    jax.block_until_ready(out)
    stats = jax.device_get(stats)

    def window(steps):
        nonlocal ef
        t0, b0 = time.perf_counter(), lo_bytes()
        o = None
        for _ in range(steps):
            o, ef, _ = f(g, ef)
        if o is not None:
            jax.block_until_ready(o)
        dt = time.perf_counter() - t0
        b1 = lo_bytes()
        return dt, b1[1] - b0[1]

    # timed window, then an equal-duration idle control window (sampled
    # AROUND the sleep, so heartbeat baseline traffic is actually captured)
    dt, tx = window(args.steps)
    b0 = lo_bytes()
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < dt:
        time.sleep(0.01)
    tx_idle = lo_bytes()[1] - b0[1]
    if args.rank == 0:
        rec = {
            "case": args.case,
            "steps": args.steps,
            "lo_tx_per_step": (tx - tx_idle) / args.steps,
            "lo_tx_idle_window": tx_idle,
            "sent_bits": float(stats.get("sent_bits", 0.0)),
            "sent_bits_psum": float(stats.get("sent_bits_psum", 0.0)),
            "sent_bits_allgather": float(stats.get("sent_bits_allgather", 0.0)),
            "sent_bits_alltoall": float(stats.get("sent_bits_alltoall", 0.0)),
            "sent_bits_ici": float(stats.get("sent_bits_ici", 0.0)),
            "sent_bits_dcn": float(stats.get("sent_bits_dcn", 0.0)),
            "sent_bits_dcn_route": float(
                stats.get("sent_bits_dcn_route", 0.0)),
        }
        print("RESULT " + json.dumps(rec), flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="benchmarks/transport_validation_r5.tsv")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--port", type=int, default=PORT)
    # worker-mode internals
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--case", type=str, default="dense")
    args = ap.parse_args(argv)
    if args.worker:
        return worker(args)

    from tpu_compressed_dp.utils.meters import per_fabric_traffic_bytes

    rows = []
    for ci, (label, method, mode, extra) in enumerate(CASES):
        procs = []
        outs = []
        for rank in range(args.procs):
            cmd = [sys.executable, os.path.abspath(__file__), "--worker",
                   "--rank", str(rank), "--case", label,
                   "--steps", str(args.steps), "--procs", str(args.procs),
                   "--port", str(args.port + ci)]
            procs.append(subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))))
        # communicate() drains the pipes while waiting — wait()-then-read
        # deadlocks once a worker logs past the ~64 KB pipe buffer
        outs = [p.communicate()[0] for p in procs]
        rc = [p.returncode for p in procs]
        if any(rc):
            print(f"## {label}: worker failed rc={rc}\n" + outs[0][-2000:],
                  file=sys.stderr)
            continue
        rec = None
        for o in outs:
            for ln in o.splitlines():
                if ln.startswith("RESULT "):
                    rec = json.loads(ln[len("RESULT "):])
        if rec is None:
            print(f"## {label}: no RESULT line\n" + outs[0][-2000:],
                  file=sys.stderr)
            continue
        # analytic: per-rank transmitted bytes/step summed over ranks.
        # Ring all-reduce: each rank transmits 2(W-1)/W x payload;
        # all_gather of worker-distinct payloads: each rank transmits its
        # own payload (W-1) times; all_to_all (the sharded route stage):
        # each rank keeps its own bucket and transmits (W-1)/W x payload.
        w = args.procs
        psum_b = rec["sent_bits_psum"] / 8.0
        ag_b = rec["sent_bits_allgather"] / 8.0
        a2a_b = rec.get("sent_bits_alltoall", 0.0) / 8.0
        ici_b = rec.get("sent_bits_ici", 0.0) / 8.0
        dcn_b = rec.get("sent_bits_dcn", 0.0) / 8.0
        rt_b = rec.get("sent_bits_dcn_route", 0.0) / 8.0
        if psum_b == ag_b == a2a_b == 0.0 and ici_b + dcn_b == 0.0:
            psum_b = rec["sent_bits"] / 8.0
        pods = extra.get("dp_pods", 1)
        # per_fabric degenerates to the flat per_chip arithmetic at pods=1;
        # at pods>1 the hier group collectives bill with their own factors
        per_rank = sum(per_fabric_traffic_bytes(
            psum_b, ag_b, w, a2a_b, ici_b, rt_b, max(dcn_b - rt_b, 0.0),
            pods))
        analytic = per_rank * w
        measured = rec["lo_tx_per_step"]
        rows.append({
            "case": label,
            "analytic_bytes_per_step_all_ranks": round(analytic, 1),
            "measured_lo_tx_bytes_per_step": round(measured, 1),
            "ratio_measured_over_analytic": round(measured / analytic, 3)
            if analytic else "",
            "idle_window_bytes": rec["lo_tx_idle_window"],
            "steps": rec["steps"],
        })
        print(rows[-1], flush=True)
    cols = list(rows[0].keys()) if rows else []
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        f.write(
            "# Measured (loopback NIC) vs analytic transport, 2-process CPU\n"
            "# data-parallel sync over gRPC localhost "
            "(tools/validate_transport.py).\n"
            "# measured = lo TX bytes/step summed over both ranks, idle-window\n"
            "# baseline subtracted; analytic = per_chip_traffic_bytes x ranks\n"
            "# (the same single-source arithmetic every sweep/TTA artifact\n"
            "# bills).  Ratio > 1 = framing/ack overhead; the validation\n"
            "# claims are (a) ratio stable across methods, (b) method\n"
            "# ordering preserved.  Reference parity: meter.py:24-47,66-86.\n")
        f.write("\t".join(cols) + "\n")
        for r in rows:
            f.write("\t".join(str(r[c]) for c in cols) + "\n")
    print(f"wrote {args.out} ({len(rows)} rows)")


if __name__ == "__main__":
    main()
