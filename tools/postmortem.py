#!/usr/bin/env python
"""Cross-rank postmortem: merge blackbox bundles, name the root cause.

When a run dies, every rank's :class:`~tpu_compressed_dp.obs.flight.
FlightRecorder` dumps its ring buffers as ``blackbox.rank<R>.json`` into
the shared dir.  This tool merges those per-rank bundles into one
cross-rank timeline and classifies the failure with a one-line verdict:

  ``corruption``  a rank's checkpoint failed manifest verification
  ``preempt``     a rank received the platform's preemption notice
  ``dead_peer``   a peer vanished (crash/kill); names the dead rank from
                  the survivors' ``PeerFailed`` evidence or the armed
                  chaos scenario
  ``nan``         the step guard wedged AND a rank was injecting
                  nan/inf — names the origin rank from the chaos arm
  ``guard``       the step guard wedged with no injection evidence
  ``straggler``   no distinguished failure, but one rank's mean host
                  step time skews far above its peers'
  ``unknown``     bundles exist but match no signature

Priority is the order above: a preempted rank also makes its peers raise
``PeerFailed``, a corrupt checkpoint surfaces after a crash — the
earliest cause in the chain wins.  All ordering comes from per-record
``seq`` + the trigger step (bundle timestamps are per-rank monotonic
offsets, never compared across ranks).

Usage::

    python tools/postmortem.py /shared/run_dir
    python tools/postmortem.py /shared/run_dir --json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from tpu_compressed_dp.obs.flight import (FLIGHT_SCHEMA, profile_from_spans,
                                          read_bundles, straggler_gauges,
                                          validate_bundle)

#: relative skew (slowest vs fastest rank's mean step time) above which
#: the fallback classification blames a straggler
STRAGGLER_FRAC = 0.25

VERDICT_KINDS = ("corruption", "preempt", "dead_peer", "nan", "guard",
                 "straggler", "unknown")


# ------------------------------------------------------------------ merging

def merge_timeline(bundles: Dict[int, Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    """One cross-rank record list: every ring record annotated with its
    ``rank`` and ``channel``, ordered by (step, rank, seq).  Records
    without a step sort after stepped ones at the same rank — per-rank
    ``seq`` preserves their true local order."""
    merged: List[Dict[str, Any]] = []
    for rank in sorted(bundles):
        rings = bundles[rank].get("rings") or {}
        for channel, ring in rings.items():
            if not isinstance(ring, list):
                continue
            for rec in ring:
                if isinstance(rec, dict):
                    merged.append({"rank": rank, "channel": channel, **rec})

    def order(rec: Dict[str, Any]):
        step = rec.get("step")
        return (step if isinstance(step, int) else sys.maxsize,
                rec.get("rank", 0), rec.get("seq", 0))

    merged.sort(key=order)
    return merged


def rank_lane_events(spans_by_rank: Dict[int, List[Dict[str, Any]]]
                     ) -> List[Dict[str, Any]]:
    """chrome://tracing trace events with one PROCESS LANE PER RANK
    (``pid=rank``) from per-rank step-span lists (the ``step_spans`` a
    harness event stream carries, ``t0`` included).  Reused by
    ``tools/trace_report.py --merge``.  Spans are aligned on each rank's
    earliest ``t0`` — host clocks are per-process, so cross-rank offsets
    show relative pacing (who lags inside a step), not absolute order."""
    out: List[Dict[str, Any]] = []
    for rank in sorted(spans_by_rank):
        spans = [s for s in spans_by_rank[rank] if "t0" in s]
        if not spans:
            continue
        out.append({"name": "process_name", "ph": "M", "pid": rank,
                    "args": {"name": f"rank {rank}"}})
        t_base = min(s["t0"] for s in spans)
        for i, s in enumerate(spans):
            t = (s["t0"] - t_base) * 1e6
            for ph in ("data", "dispatch", "device"):
                dur = s.get(ph)
                if dur is None:
                    continue
                out.append({"name": ph, "cat": "host", "ph": "X",
                            "pid": rank, "tid": 0, "ts": t,
                            "dur": dur * 1e6,
                            "args": {"step_index": i, "rank": rank}})
                t += dur * 1e6
    return out


# ------------------------------------------------------- classification

def straggler_from_bundles(bundles: Dict[int, Dict[str, Any]]
                           ) -> Dict[str, float]:
    """The live ``straggler/*`` gauges recomputed offline from the
    bundles' ``timing`` rings (same aggregation as the recorder)."""
    profiles = {}
    for rank, rec in bundles.items():
        ring = (rec.get("rings") or {}).get("timing") or []
        profiles[rank] = profile_from_spans(rank, ring)
    return straggler_gauges(profiles)


def _chaos_records(bundles: Dict[int, Dict[str, Any]]):
    for rank in sorted(bundles):
        for rec in (bundles[rank].get("rings") or {}).get("chaos") or []:
            if isinstance(rec, dict):
                yield rank, rec


def _verdict(kind: str, rank: int, step: Optional[int],
             detail: str) -> Dict[str, Any]:
    return {"kind": kind, "rank": int(rank),
            "step": step if isinstance(step, int) else None,
            "detail": detail}


def verdict_line(v: Dict[str, Any]) -> str:
    step = v["step"] if v["step"] is not None else "?"
    return (f"postmortem: {v['kind']} rank={v['rank']} step={step} "
            f"— {v['detail']}")


def classify(bundles: Dict[int, Dict[str, Any]]) -> Dict[str, Any]:
    """Root-cause verdict over all per-rank bundles (see module
    docstring for the taxonomy and its priority order)."""
    if not bundles:
        return _verdict("unknown", -1, None, "no blackbox bundles found")
    by_reason: Dict[str, List[int]] = {}
    for rank in sorted(bundles):
        by_reason.setdefault(str(bundles[rank].get("reason")), []).append(rank)

    def step_of(rank: int) -> Optional[int]:
        s = bundles[rank].get("step")
        return s if isinstance(s, int) else None

    if "ckpt_corrupt" in by_reason:
        r = min(by_reason["ckpt_corrupt"])
        msg = (bundles[r].get("error") or {}).get("message", "")
        return _verdict(
            "corruption", r, step_of(r),
            f"rank {r}'s checkpoint failed verification: {msg[:120]}")

    if "preempt" in by_reason:
        r = min(by_reason["preempt"])
        sig = (bundles[r].get("error") or {}).get("signum")
        return _verdict(
            "preempt", r, step_of(r),
            f"rank {r} received the preemption notice"
            + (f" (signal {sig})" if sig else ""))

    if "peer_failed" in by_reason or "chaos_crash" in by_reason:
        dead = set()
        for r in by_reason.get("peer_failed", ()):
            for f in (bundles[r].get("error") or {}).get("failed") or []:
                dead.add(int(f))
        # a crashed rank that managed to dump names itself
        dead.update(by_reason.get("chaos_crash", ()))
        if not dead:
            # survivors raised a bare timeout: fall back to the armed
            # chaos scenario every rank recorded
            for _, rec in _chaos_records(bundles):
                w, at = rec.get("worker"), rec.get("crash_at_step")
                if isinstance(at, (int, float)) and at >= 0 and w is not None:
                    dead.add(int(w))
        reporters = (by_reason.get("peer_failed")
                     or by_reason.get("chaos_crash"))
        rank = min(dead) if dead else -1
        return _verdict(
            "dead_peer", rank, step_of(min(reporters)),
            (f"rank {rank} vanished; {len(reporters)} survivor(s) raised "
             "PeerFailed") if dead else
            "a peer vanished but no bundle names it")

    if "guard_exceeded" in by_reason:
        reporter = min(by_reason["guard_exceeded"])
        for _, rec in _chaos_records(bundles):
            kind, w = rec.get("kind"), rec.get("worker")
            if kind in ("nan", "inf") and w is not None:
                return _verdict(
                    "nan", int(w), step_of(reporter),
                    f"step guard wedged; {kind} was injected into "
                    f"{rec.get('target', '?')} on worker {w}")
        return _verdict(
            "guard", -1, step_of(reporter),
            "step guard wedged (skip streak exceeded) with no injection "
            "evidence — inspect the guard rings for the first bad step")

    gauges = straggler_from_bundles(bundles)
    if (gauges["straggler/frac"] > STRAGGLER_FRAC
            and gauges["straggler/rank"] >= 0):
        r = int(gauges["straggler/rank"])
        return _verdict(
            "straggler", r, None,
            f"rank {r}'s mean host step time skews "
            f"{gauges['straggler/frac'] * 100:.0f}% above the fastest "
            f"rank ({gauges['straggler/skew_s'] * 1e3:.1f} ms/step)")

    first = min(bundles)
    return _verdict(
        "unknown", -1, step_of(first),
        f"{len(bundles)} bundle(s) with reason(s) "
        f"{sorted(by_reason)} match no known signature")


# ----------------------------------------------------------------- report

def render_report(bundles: Dict[int, Dict[str, Any]], *,
                  tail: int = 20) -> str:
    v = classify(bundles)
    lines = [verdict_line(v), ""]
    lines.append(f"{'rank':>6} {'reason':<16} {'step':>8} {'records':>9} "
                 f"{'dumps':>7}  schema")
    for rank in sorted(bundles):
        b = bundles[rank]
        counts = b.get("counts") or {}
        problems = validate_bundle(b)
        lines.append(
            f"{rank:>6} {str(b.get('reason')):<16} "
            f"{str(b.get('step')):>8} {counts.get('records', '?'):>9} "
            f"{counts.get('dumps', '?'):>7}  "
            + ("ok" if not problems else "; ".join(problems)))
    gauges = straggler_from_bundles(bundles)
    if gauges["straggler/rank"] >= 0:
        lines.append("")
        lines.append(
            f"straggler gauges: skew {gauges['straggler/skew_s'] * 1e3:.2f} "
            f"ms/step, slowest rank {int(gauges['straggler/rank'])} "
            f"(+{gauges['straggler/frac'] * 100:.0f}% vs fastest)")
    merged = merge_timeline(bundles)
    if merged:
        lines.append("")
        lines.append(f"cross-rank timeline (last {min(tail, len(merged))} "
                     f"of {len(merged)} records):")
        for rec in merged[-tail:]:
            ctx = {k: v2 for k, v2 in rec.items()
                   if k not in ("rank", "channel", "kind", "seq", "t")}
            lines.append(f"  r{rec['rank']} {rec['channel']:<8} "
                         f"{rec.get('kind', '?'):<12} {json.dumps(ctx)}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("directory",
                   help="shared dir holding blackbox.rank<R>.json bundles")
    p.add_argument("--json", action="store_true",
                   help="emit verdict + per-rank summaries + merged "
                        "timeline as JSON")
    p.add_argument("--tail", type=int, default=20,
                   help="merged-timeline records to show (text mode)")
    args = p.parse_args(argv)
    bundles = read_bundles(args.directory)
    if not bundles:
        print(f"postmortem: no blackbox bundles in {args.directory}")
        return 2
    if args.json:
        payload = {
            "v": FLIGHT_SCHEMA,
            "verdict": classify(bundles),
            "straggler": straggler_from_bundles(bundles),
            "ranks": {
                str(r): {"reason": b.get("reason"), "step": b.get("step"),
                         "counts": b.get("counts"),
                         "problems": validate_bundle(b)}
                for r, b in sorted(bundles.items())},
            "timeline": merge_timeline(bundles),
        }
        print(json.dumps(payload, indent=2))
    else:
        print(render_report(bundles, tail=args.tail))
    return 0


if __name__ == "__main__":
    sys.exit(main())
