"""Bisect the Random-K + error-feedback + momentum divergence (VERDICT r1 #3).

Round-1 observation (`benchmarks/convergence_r1.txt`): wire/simulate Random-K
k=1% WITH error feedback diverges (NaN) under the dawn protocol's momentum-0.9
Nesterov SGD, while Top-K+EF and Block-Top-K+EF converge, and momentum=0 or
EF-off converge.  The reference trains its `RandomKSparsifiedDDP` (EF +
Random-K, `IMAGENET/training/sparsified_ddp.py:408-413`) with momentum-0.9 SGD
(`train_imagenet_nv.py:186-191`) — so either our composition differs, or the
reference's would diverge under the same (CIFAR dawn, high peak lr, Nesterov)
protocol too.

This tool reproduces the dynamics small and fast — one worker, a 2-layer MLP
on non-saturating synthetic data, jitted `lax.scan` over steps — and sweeps
the suspects:

  * momentum value (0 / 0.9)
  * Nesterov on/off (dawn uses Nesterov, `dawn.py:146-148`; the ImageNet
    harness uses plain momentum)
  * EF accumulation style:
      - 'plain'    residual += dropped gradient (the reference rule)
      - 'momentum' DGC-style momentum-corrected EF (Lin et al., ICLR'18
        "Deep Gradient Compression", PAPERS.md): accumulate the *velocity*
        v = mu v + g instead of the raw gradient, send sparse(residual),
        and apply the payload WITHOUT optimizer momentum — momentum lives
        inside the compression stream, so delayed coordinates do not get
        double-amplified by the optimizer's momentum buffer.
  * method: randomk / topk (topk is the converging control)
  * peak lr scale

Also runs the same protocol through a *torch* implementation mirroring the
reference's update rule (masked_select/masked_fill EF + torch.optim.SGD) to
show whether the reference's own arithmetic shares the divergence.

Usage:
    python tools/ef_bisect.py            # full bisect table
    python tools/ef_bisect.py --steps 640 --peak_lr 0.4
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def make_data(seed: int = 0, n: int = 4096, dim: int = 64, classes: int = 10,
              noise_frac: float = 0.15):
    """Teacher-labelled gaussian features + label noise: a task a small MLP
    fits to ~90%, not 100% — gradients stay non-trivial all run."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype(np.float32)
    w_t = rng.randn(dim, classes).astype(np.float32)
    y = np.argmax(x @ w_t + 0.5 * rng.randn(n, classes), axis=1)
    flip = rng.rand(n) < noise_frac
    y[flip] = rng.randint(0, classes, flip.sum())
    return x, y.astype(np.int32)


# ---------------------------------------------------------------- JAX side

def lr_value(schedule: str, peak_lr: float, steps: int, batch: int,
             step: int) -> float:
    """Per-step lr in summed-loss units — ONE scalar implementation consumed
    by both the JAX arm (via a host-built table) and the torch arm, so the
    two bisect arms can never train under different curves.

    'dawn'  — the CIFAR protocol's triangle: ramp to peak at 1/8, anneal to 0
              (`dawn.py:110`).
    'step'  — the reference's ImageNet shape (`IMAGENET/train.py:60-72`):
              linear warmup over the first 1/8, flat at peak to 60%, peak/10
              to 85%, peak/100 after — the regime the reference actually ran
              `RandomKSparsifiedDDP` under (`train_imagenet_nv.py:203-222`).
    """
    warm = max(1, steps // 8)
    if schedule == "dawn":
        return max(min(peak_lr * step / warm,
                       peak_lr * (steps - step) / (steps - warm)), 0.0) / batch
    if schedule != "step":
        raise ValueError(f"unknown schedule {schedule!r}")
    if step < warm:
        return peak_lr * step / warm / batch
    if step < 0.6 * steps:
        return peak_lr / batch
    if step < 0.85 * steps:
        return peak_lr / 10.0 / batch
    return peak_lr / 100.0 / batch


def make_lr_fn(schedule: str, peak_lr: float, steps: int, batch: int):
    """Traced-step lr lookup for the JAX arm: the scalar schedule evaluated
    on host into a table, indexed inside `lax.scan`."""
    import jax.numpy as jnp

    table = jnp.asarray([lr_value(schedule, peak_lr, steps, batch, s)
                         for s in range(steps)], jnp.float32)
    return lambda step: table[step]


def run_jax(momentum: float, nesterov: bool, ef: bool, ef_style: str,
            method: str, ratio: float, steps: int, peak_lr: float,
            batch: int = 512, seed: int = 0, clip: float = 0.0,
            warmup_sparsity: bool = False, schedule: str = "dawn"):
    """Train the MLP under the dawn summed-loss protocol; return per-step loss."""
    import jax
    import jax.numpy as jnp

    x_np, y_np = make_data(seed)
    n, dim = x_np.shape
    classes = int(y_np.max()) + 1
    hidden = 128
    rng = np.random.RandomState(seed + 1)
    params = {
        "w1": jnp.asarray(rng.randn(dim, hidden).astype(np.float32) / np.sqrt(dim)),
        "w2": jnp.asarray(rng.randn(hidden, classes).astype(np.float32) / np.sqrt(hidden)),
    }
    x_all, y_all = jnp.asarray(x_np), jnp.asarray(y_np)

    # dawn protocol scaling (`dawn.py:142-148`): summed loss, lr/bs, wd*bs
    wd = 5e-4 * batch
    lr_at = make_lr_fn(schedule, peak_lr, steps, batch)

    def loss_fn(p, xb, yb):
        h = jnp.maximum(xb @ p["w1"], 0.0)
        logits = h @ p["w2"]
        logz = jax.nn.log_softmax(logits)
        return -jnp.sum(jnp.take_along_axis(logz, yb[:, None], 1))


    def compress(flat, key, step):
        n_el = flat.shape[0]
        if warmup_sparsity:
            # DGC-style sparsity warm-up: keep-ratio decays exponentially
            # from dense to the target over the first quarter of training
            frac = jnp.clip(step / (steps / 4.0), 0.0, 1.0)
            ratio_t = jnp.exp(jnp.log(1.0) * (1 - frac) + jnp.log(ratio) * frac)
        else:
            ratio_t = ratio
        if method == "randomk":
            if warmup_sparsity:
                mask = jax.random.uniform(key, (n_el,)) < ratio_t
            else:
                k = max(1, int(round(ratio * n_el)))
                idx = jax.random.permutation(key, n_el)[:k]
                mask = jnp.zeros(n_el, bool).at[idx].set(True)
        else:  # topk
            k = max(1, int(round(ratio * n_el)))
            t = jnp.sort(jnp.abs(flat))[n_el - k]
            mask = jnp.abs(flat) >= t
        return jnp.where(mask, flat, 0.0), mask

    def step_fn(carry, step):
        p, mom, resid, vel, key = carry
        key, k1, k2 = jax.random.split(key, 3)
        i = jax.random.randint(k1, (batch,), 0, n)
        g = jax.grad(loss_fn)(p, x_all[i], y_all[i])

        lr = lr_at(step)
        new_p, new_mom, new_resid, new_vel = {}, {}, {}, {}
        for name in p:
            gl = g[name].reshape(-1)
            if clip > 0:
                # DGC-style gradient clipping before EF accumulation, in
                # mean-loss units (gl is a summed-loss gradient)
                gnorm = jnp.linalg.norm(gl) / batch
                gl = gl * jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-12))
            if ef and ef_style == "ef21":
                # EF21 (Richtarik et al., 2021): each worker keeps a gradient
                # estimate h and transmits only the compressed *innovation*
                # c = compress(g - h); h += c.  The optimizer consumes the
                # smooth dense estimate h — momentum never sees delayed
                # spikes, which is exactly what blows plain-EF Random-K up.
                innov = gl - resid[name]              # resid doubles as h
                sent, mask = compress(innov, jax.random.fold_in(k2, hash(name) % 997), step)
                h = resid[name] + sent
                d = h + wd * p[name].reshape(-1)
                buf = momentum * mom[name] + d
                upd = d + momentum * buf if nesterov else buf
                new_p[name] = (p[name].reshape(-1) - lr * upd).reshape(p[name].shape)
                new_mom[name] = buf
                new_resid[name], new_vel[name] = h, vel[name]
            elif ef and ef_style == "momentum":
                # DGC (Lin et al.): velocity accumulates into the residual;
                # the optimizer applies the sparse payload directly (no second
                # momentum), and — critically — the *velocity is also masked*
                # at sent coordinates ("momentum factor masking"), so stale
                # momentum stops re-injecting directions that already shipped.
                v = momentum * vel[name] + gl
                acc = resid[name] + v
                sent, mask = compress(acc, jax.random.fold_in(k2, hash(name) % 997), step)
                r = jnp.where(mask, 0.0, acc)
                v = jnp.where(mask, 0.0, v)
                d = sent + wd * p[name].reshape(-1)
                new_p[name] = (p[name].reshape(-1) - lr * d).reshape(p[name].shape)
                new_mom[name] = mom[name]
                new_resid[name], new_vel[name] = r, v
            else:
                acc = (resid[name] + gl) if ef else gl
                sent, mask = compress(acc, jax.random.fold_in(k2, hash(name) % 997), step)
                r = jnp.where(mask, 0.0, acc) if ef else resid[name]
                if ef_style == "clip_sent":
                    # clip the aggregated sparse update itself: bounds the
                    # ~1/k-step residual spike, which local-gradient clipping
                    # cannot (the residual accumulates clipped inflow for
                    # 1/k steps and still releases it at once)
                    snorm = jnp.linalg.norm(sent) / batch
                    sent = sent * jnp.minimum(1.0, 1.0 / jnp.maximum(snorm, 1e-12))
                d = sent + wd * p[name].reshape(-1)
                buf = momentum * mom[name] + d
                upd = d + momentum * buf if nesterov else buf
                new_p[name] = (p[name].reshape(-1) - lr * upd).reshape(p[name].shape)
                new_mom[name] = buf
                new_resid[name], new_vel[name] = r, vel[name]
        lval = loss_fn(p, x_all[i], y_all[i]) / batch
        return (new_p, new_mom, new_resid, new_vel, key), lval

    import jax
    zeros = {k: jnp.zeros(v.size) for k, v in params.items()}
    carry = (params, dict(zeros), dict(zeros), dict(zeros), jax.random.key(seed))
    carry, losses = jax.lax.scan(step_fn, carry, jnp.arange(steps))
    return np.asarray(losses)


# -------------------------------------------------------------- torch side

def run_torch(momentum: float, nesterov: bool, ratio: float, steps: int,
              peak_lr: float, batch: int = 512, seed: int = 0,
              schedule: str = "dawn"):
    """The reference's own arithmetic: per-parameter Random-K EF via
    masked_select/masked_fill (`sparsified_ddp.py:408-413`) + torch.optim.SGD
    momentum (`train_imagenet_nv.py:186-191`), world size 1."""
    import torch

    torch.manual_seed(seed)
    x_np, y_np = make_data(seed)
    x = torch.tensor(x_np)
    y = torch.tensor(y_np, dtype=torch.long)
    n, dim = x.shape
    classes = int(y.max().item()) + 1
    model = torch.nn.Sequential(
        torch.nn.Linear(dim, 128, bias=False),
        torch.nn.ReLU(),
        torch.nn.Linear(128, classes, bias=False),
    )
    wd = 5e-4 * batch
    opt = torch.optim.SGD(model.parameters(), lr=0.0, momentum=momentum,
                          nesterov=nesterov and momentum > 0, weight_decay=wd)
    crit = torch.nn.CrossEntropyLoss(reduction="sum")
    eps = [torch.zeros(p.numel()) for p in model.parameters()]
    gen = torch.Generator().manual_seed(2147483647)  # the reference seed
    losses = []
    for step in range(steps):
        lr = lr_value(schedule, peak_lr, steps, batch, step)
        for gparam in opt.param_groups:
            gparam["lr"] = lr
        i = torch.randint(0, n, (batch,))
        opt.zero_grad()
        loss = crit(model(x[i]), y[i])
        loss.backward()
        with torch.no_grad():
            for p, e in zip(model.parameters(), eps):
                flat = p.grad.reshape(-1)
                flat += e                                     # EF in
                k = max(1, int(round(ratio * flat.numel())))
                mask = torch.randperm(flat.numel(), generator=gen).lt(k)
                e.copy_(flat.masked_fill(mask, 0))            # EF out
                flat.mul_(mask)                               # sparse grad
        opt.step()
        losses.append(loss.item() / batch)
        if not np.isfinite(losses[-1]):
            break
    return np.asarray(losses)


def summarize(name: str, losses: np.ndarray) -> str:
    bad = np.where(~np.isfinite(losses) | (losses > 1e4))[0]
    if bad.size:
        return (f"{name:58s} DIVERGED (loss non-finite/blown-up at step "
                f"{bad[0]}/{len(losses)})")
    return (f"{name:58s} ok   final={losses[-1]:.4f}  "
            f"max={losses.max():.2f}  last10={losses[-10:].mean():.4f}")


def run_operating_point(args):
    """VERDICT r2 #1: map the reference's ACTUAL operating regime — the
    ImageNet step schedule (`IMAGENET/train.py:60-72`), not just dawn's
    triangle — over peak lr x EF flavor, all at momentum 0.9 (the reference's
    `--momentum` default, `train_imagenet_nv.py:48`), Random-K k=1% + EF."""
    rows = []
    print(f"# operating-point map: schedule={args.schedule} steps={args.steps} "
          f"k={args.ratio}", flush=True)
    for peak in (0.4, 0.2, 0.1, 0.05, 0.02):
        dense = run_jax(0.9, True, False, "plain", "randomk", 1.0, args.steps,
                        peak, schedule=args.schedule)
        rows.append(summarize(f"dense       mom=.9 peak={peak}", dense))
        print(rows[-1], flush=True)
        for label, style, clip, warm in (
            ("plain-EF   ", "plain", 0.0, False),
            ("plain-EF+clip", "plain", 1.0, False),
            ("DGC        ", "momentum", 0.0, False),
            ("DGC+warmup ", "momentum", 0.0, True),
            ("plain+warmup", "plain", 0.0, True),
        ):
            losses = run_jax(0.9, True, True, style, "randomk", args.ratio,
                             args.steps, peak, clip=clip, warmup_sparsity=warm,
                             schedule=args.schedule)
            rows.append(summarize(
                f"randomk+{label} mom=.9 peak={peak}", losses))
            print(rows[-1], flush=True)
        if not args.skip_torch:
            losses = run_torch(0.9, True, args.ratio, args.steps, peak,
                               schedule=args.schedule)
            rows.append(summarize(
                f"TORCH ref-rule randomk+EF mom=.9 peak={peak}", losses))
            print(rows[-1], flush=True)
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=640)
    ap.add_argument("--peak_lr", type=float, default=0.4)
    ap.add_argument("--ratio", type=float, default=0.01)
    ap.add_argument("--skip_torch", action="store_true")
    ap.add_argument("--schedule", choices=["dawn", "step"], default="dawn",
                    help="'step' = the reference's ImageNet warmup->step-decay "
                         "shape (train.py:60-72)")
    ap.add_argument("--operating_point", action="store_true",
                    help="sweep peak lr x EF flavor at momentum 0.9 under "
                         "--schedule (VERDICT r2 #1)")
    args = ap.parse_args(argv)

    if args.operating_point:
        return run_operating_point(args)

    rows = []
    cases = [
        # (label, momentum, nesterov, ef, ef_style, method)
        ("dense-ctl   mom=.9 nesterov", None, None, None, None, "dense"),
        ("randomk+EF  mom=.9 nesterov  [r1 diverger]", 0.9, True, True, "plain", "randomk"),
        ("randomk+EF  mom=.9 plain-momentum", 0.9, False, True, "plain", "randomk"),
        ("randomk+EF  mom=0", 0.0, False, True, "plain", "randomk"),
        ("randomk     mom=.9 nesterov  no-EF", 0.9, True, False, "plain", "randomk"),
        ("topk+EF     mom=.9 nesterov  [r1 converger]", 0.9, True, True, "plain", "topk"),
        ("randomk+EF-momentum(DGC) mu=.9", 0.9, False, True, "momentum", "randomk"),
        ("randomk+EF21 mom=.9 nesterov", 0.9, True, True, "ef21", "randomk"),
        ("topk+EF21    mom=.9 nesterov", 0.9, True, True, "ef21", "topk"),
    ]
    clip_cases = [
        # clip the SENT (aggregated sparse) update instead of the local grad
        ("randomk+EF mom=.9 nesterov CLIP-SENT=1", 0.9, True, "clip_sent", "randomk", 0.0, False),
        ("randomk+EF mom=.9 CLIP-SENT + CLIP-local", 0.9, True, "clip_sent", "randomk", 1.0, False),
        # (label, momentum, nesterov, ef_style, method, clip, warmup)
        ("randomk+EF mom=.9 nesterov CLIP=1", 0.9, True, "plain", "randomk", 1.0, False),
        ("randomk+EF mom=.9 nesterov CLIP=1 +WARMUP", 0.9, True, "plain", "randomk", 1.0, True),
        ("randomk+EF mom=.9 nesterov WARMUP only", 0.9, True, "plain", "randomk", 0.0, True),
        ("topk+EF    mom=.9 nesterov CLIP=1", 0.9, True, "plain", "topk", 1.0, False),
    ]
    for label, mom, nest, ef, style, method in cases:
        if method == "dense":
            losses = run_jax(0.9, True, False, "plain", "randomk", 1.0,
                             args.steps, args.peak_lr, schedule=args.schedule)
        else:
            losses = run_jax(mom, nest, ef, style, method, args.ratio,
                             args.steps, args.peak_lr, schedule=args.schedule)
        rows.append(summarize(label, losses))
        print(rows[-1], flush=True)
    for label, mom, nest, style, method, clip, warm in clip_cases:
        losses = run_jax(mom, nest, True, style, method, args.ratio,
                         args.steps, args.peak_lr, clip=clip,
                         warmup_sparsity=warm, schedule=args.schedule)
        rows.append(summarize(label, losses))
        print(rows[-1], flush=True)

    if not args.skip_torch:
        for label, mom, nest in [
            ("TORCH reference-rule randomk+EF mom=.9 nesterov", 0.9, True),
            ("TORCH reference-rule randomk+EF mom=.9 plain", 0.9, False),
            ("TORCH reference-rule randomk+EF mom=0", 0.0, False),
        ]:
            losses = run_torch(mom, nest, args.ratio, args.steps,
                               args.peak_lr, schedule=args.schedule)
            rows.append(summarize(label, losses))
            print(rows[-1], flush=True)
    return rows


if __name__ == "__main__":
    main(sys.argv[1:])
