#!/usr/bin/env python
"""Digital-twin report: calibration, modeled-vs-measured, scale-out, gate.

Fits the per-fabric alpha/beta/gamma cost model
(``tpu_compressed_dp/twin/``) from the repo's committed BENCH/MULTICHIP
records and renders:

  * the **calibration summary** — fitted alpha (ms), beta (ms/MB), gamma
    (ms/hop) per fabric with the row count that identified each, plus
    the per-context compute anchors;
  * the **modeled-vs-measured tables** — every step row and every
    ``--phase_breakdown`` comm-phase row with its residual, worst first
    flagged (the tier-1 suite asserts every step row lands within 15%);
  * the **scale-out projection** — each measured config re-priced at
    W in {64, 256, 1024, 4096} chips (pods = W / pod_size), i.e. the
    digital-twin answer to "what would this run cost on a real pod
    slice", with a blank where the target fabric has no calibration;
  * the **perf gate** (``--gate``) — every pin in
    ``benchmarks/perf_pins.json`` re-priced through the current model,
    exit 1 on a modeled regression beyond tolerance (the tier-1 perf
    ratchet); ``--update_pins`` re-mints every pin at the current price.

Usage::

    python tools/twin_report.py                     # full report
    python tools/twin_report.py --json              # machine-readable
    python tools/twin_report.py --gate              # pin check, rc=1 on fail
    python tools/twin_report.py --update_pins       # re-mint stale pins
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any, Dict, List, Optional

if __package__ in (None, ""):  # script run: repo root onto sys.path
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_compressed_dp.twin import (
    calibration_rows, check_pins, discover_record_paths, fit, load_pins,
    load_record_file, make_pin, save_calibration,
)

PROJECTION_WORLDS = (64, 256, 1024, 4096)


def projection_rows(paths: List[str], calib, *, pod_size: int = 64
                    ) -> List[Dict[str, Any]]:
    """One projection row per measured step record: the config labeled,
    its measured wall, and the twin's price at each projection world."""
    from tpu_compressed_dp.bench.sweep import attach_prediction

    out: List[Dict[str, Any]] = []
    for path in paths:
        rf = load_record_file(path)
        if rf.shape == "sweep":
            recs = list(rf.raw["records"])
        elif rf.shape == "step":
            recs = [rf.raw["parsed"]]
        else:
            continue
        for i, rec in enumerate(recs):
            if "step_ms" not in rec or "transport" not in rec:
                continue
            rec = dict(rec)
            attach_prediction(rec, calib, pod_size=pod_size)
            knob = rec.get("rank") if rec.get("method") == "powersgd" \
                else rec.get("ratio")
            out.append({
                "source": f"{rf.source}[{i}]",
                "config": "{} {} {} {} W={} pods={}".format(
                    rec.get("model"), rec.get("method"),
                    rec.get("transport"), knob, rec.get("devices"),
                    rec.get("dp_pods", 1)),
                "pallas": rec.get("pallas_mode", "off"),
                "measured_ms": float(rec["step_ms"]),
                "pred_step_ms": rec.get("pred_step_ms"),
                "pred_err_frac": rec.get("pred_err_frac"),
                "pred_err_bar_ms": rec.get("pred_err_bar_ms"),
                **{f"w{w}": rec.get(f"pred_step_ms_w{w}")
                   for w in PROJECTION_WORLDS},
            })
    return out


def _f(v: Optional[float], spec: str = "10.1f") -> str:
    return format(v, spec) if isinstance(v, (int, float)) else \
        " " * (int(spec.split(".")[0]) - 1) + "-"


def _pct(v: float, width: int = 8) -> str:
    """A percentage cell that degrades gracefully: a >10x miss (e.g. a
    phase measured at ~0 ms) renders as a bounded marker, not a
    table-breaking number."""
    if abs(v) > 9.995:
        return format(">999%" if v > 0 else "<-999%", f">{width}")
    return format(v, f"{width}.1%")


def render_calibration(calib) -> List[str]:
    lines = ["calibration (alpha: ms/collective, beta: ms/MB, "
             "gamma: ms/hop):"]
    lines.append(f"  {'fabric':<8}{'alpha':>10}{'beta':>10}{'gamma':>10}"
                 f"{'rows':>6}")
    for fab in sorted(calib.fabrics):
        p = calib.fabrics[fab]
        lines.append(f"  {fab:<8}{p.alpha_ms:>10.3f}"
                     f"{p.beta_ms_per_mb:>10.3f}"
                     f"{p.gamma_ms_per_hop:>10.3f}{p.rows:>6}")
    lines.append(f"  fit: {calib.n_step_rows} step + {calib.n_phase_rows} "
                 f"phase rows over {len(calib.contexts)} contexts, "
                 f"step RMS {calib.step_rms_frac:.1%}")
    return lines


def render_residuals(calib) -> List[str]:
    lines = []
    for kind, title in (("step", "modeled vs measured (step rows)"),
                        ("phase", "modeled vs measured (comm phases)")):
        rows = [r for r in calib.residuals if r.kind == kind]
        if not rows:
            continue
        worst = max(rows, key=lambda r: abs(r.err_frac))
        lines.append("")
        lines.append(f"{title}:")
        lines.append(f"  {'row':<44}{'measured':>10}{'modeled':>10}"
                     f"{'err':>8}")
        for r in rows:
            mark = "  <-- worst" if r is worst else ""
            lines.append(f"  {r.label:<44}{r.measured_ms:>10.1f}"
                         f"{r.modeled_ms:>10.1f}{_pct(r.err_frac)}{mark}")
    return lines


def render_projection(proj: List[Dict[str, Any]]) -> List[str]:
    if not proj:
        return []
    lines = ["", "scale-out projection (modeled step ms; "
             f"pods = W / pod_size; '-' = twin refuses to extrapolate):"]
    lines.append(f"  {'config':<46}{'measured':>10}"
                 + "".join(f"{'W=' + str(w):>13}"
                           for w in PROJECTION_WORLDS))
    for row in proj:
        lines.append(f"  {row['config']:<46}{row['measured_ms']:>10.1f}"
                     + "".join(_f(row.get(f"w{w}"), "13.1f")
                               for w in PROJECTION_WORLDS))
    return lines


def render_gate(results) -> List[str]:
    lines = ["perf gate:"]
    lines.append(f"  {'pin':<36}{'pinned':>10}{'modeled':>10}"
                 f"{'change':>9}{'tol':>6}  verdict")
    for r in results:
        frac = r.frac_change
        lines.append(
            f"  {r.name:<36}{r.pinned_ms:>10.1f}{_f(r.modeled_ms)}"
            + (f"{frac:>9.1%}" if frac is not None else f"{'-':>9}")
            + f"{r.tol_frac:>6.0%}  "
            + ("ok" if r.ok else "FAIL") + f" — {r.note}")
    n_bad = sum(1 for r in results if not r.ok)
    lines.append(f"  {len(results)} pin(s), {n_bad} failing")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--records", default=".",
                   help="dir holding BENCH_r*/MULTICHIP_r* artifacts")
    p.add_argument("--pins", default="benchmarks/perf_pins.json",
                   help="perf-pins file for --gate / --update_pins")
    p.add_argument("--pod_size", type=int, default=64,
                   help="chips per pod in the scale-out projection")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as JSON")
    p.add_argument("--gate", action="store_true",
                   help="re-price the pins; exit 1 on any regression")
    p.add_argument("--update_pins", action="store_true",
                   help="re-mint every pin at the current modeled price")
    p.add_argument("--save_calibration", default=None,
                   help="also write the fitted calibration JSON here")
    args = p.parse_args(argv)

    paths = discover_record_paths(args.records)
    rows = calibration_rows(paths)
    if not rows:
        print(f"no calibration rows under {args.records!r} — are the "
              "BENCH_r*.json artifacts there?", file=sys.stderr)
        return 2
    calib = fit(rows)
    if args.save_calibration:
        save_calibration(calib, args.save_calibration)

    if args.update_pins:
        doc = load_pins(args.pins)
        doc["pins"] = [
            make_pin(pin["name"], pin["point"], pin["context"], calib,
                     tol_frac=float(pin.get("tol_frac",
                                            doc.get("tolerance_frac", 0.10))))
            for pin in doc["pins"]]
        with open(args.pins, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"re-minted {len(doc['pins'])} pin(s) in {args.pins}")
        return 0

    gate_results = None
    if args.gate:
        gate_results = check_pins(load_pins(args.pins), calib)

    proj = projection_rows(paths, calib, pod_size=args.pod_size)

    if args.json:
        doc = {
            "fabrics": {f: fp.to_json() for f, fp in calib.fabrics.items()},
            "contexts": dict(calib.contexts),
            "step_rms_frac": calib.step_rms_frac,
            "n_step_rows": calib.n_step_rows,
            "n_phase_rows": calib.n_phase_rows,
            "residuals": [dict(dataclasses.asdict(r),
                               err_frac=r.err_frac)
                          for r in calib.residuals],
            "projection": proj,
        }
        if gate_results is not None:
            doc["gate"] = [dict(dataclasses.asdict(r),
                                frac_change=r.frac_change)
                           for r in gate_results]
        print(json.dumps(doc, indent=1, sort_keys=True))
    elif args.gate:
        print("\n".join(render_gate(gate_results)))
    else:
        lines = render_calibration(calib)
        lines += render_residuals(calib)
        lines += render_projection(proj)
        print("\n".join(lines))

    if gate_results is not None and any(not r.ok for r in gate_results):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
