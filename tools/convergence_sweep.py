"""Method x ratio convergence grid on the non-saturating synthetic benchmark.

The accuracy half of the reference's Fig. 3/4 protocol (`CIFAR10/dawn.py`
sweeps: 24 epochs, 40 for Randomk/Thresholdv, bs 512, peak lr 0.4 at ep 5)
run end-to-end through the dawn harness on ``--synthetic_hard`` data, where
dense tops out ~0.96 test accuracy and weaker optimisation shows as a lower
final score — unlike round 1's saturating blobs (VERDICT r1 #2).

Writes one TSV row per grid point: final train/test accuracy + loss, epoch
count, comm fractions.  Runs serially on whatever backend is live (the real
chip under the driver; keep the host otherwise idle for honest wall times).

Usage:
    python tools/convergence_sweep.py --out benchmarks/convergence_r2.tsv
    python tools/convergence_sweep.py --quick   # 3-epoch smoke of the grid
"""

from __future__ import annotations

import argparse
import os
import sys
import time

if __package__ in (None, ""):  # script run: repo root onto sys.path
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


GRID = [
    # label, harness args (beyond the common protocol)
    ("dense", []),
    ("topk-lw-0.1%", ["--compress", "layerwise", "--method", "topk",
                      "--ratio", "0.001", "--error_feedback"]),
    ("topk-lw-1%", ["--compress", "layerwise", "--method", "topk",
                    "--ratio", "0.01", "--error_feedback"]),
    ("topk-lw-10%", ["--compress", "layerwise", "--method", "topk",
                     "--ratio", "0.1", "--error_feedback"]),
    ("topk-em-1%", ["--compress", "entiremodel", "--method", "topk",
                    "--ratio", "0.01", "--error_feedback"]),
    ("topk-em-1%-wire", ["--compress", "entiremodel", "--method", "topk",
                         "--ratio", "0.01", "--error_feedback",
                         "--mode", "wire"]),
    # the r1 diverger, now stabilised by local + sent clipping (40-epoch rule)
    ("randomk-em-1%-wire-EF", ["--compress", "entiremodel", "--method",
                               "randomk", "--ratio", "0.01",
                               "--error_feedback", "--mode", "wire",
                               "--clip_norm", "1.0",
                               "--clip_sent_norm", "1.0"]),
    ("randomk-em-1%-mom0", ["--compress", "entiremodel", "--method",
                            "randomk", "--ratio", "0.01", "--error_feedback",
                            "--momentum", "0.0"]),
    ("randomk-em-10%", ["--compress", "entiremodel", "--method", "randomk",
                        "--ratio", "0.1", "--error_feedback",
                        "--clip_norm", "1.0", "--clip_sent_norm", "1.0"]),
    ("thresholdv-lw", ["--compress", "layerwise", "--method", "thresholdv",
                       "--threshold", "0.001"]),
    ("adaptive-lw", ["--compress", "layerwise", "--method",
                     "adaptive_threshold"]),
    ("qsgd-lw-8bit", ["--compress", "layerwise", "--method", "qsgd",
                      "--qstates", "255"]),
    ("terngrad-em", ["--compress", "entiremodel", "--method", "terngrad"]),
    ("terngrad-lw", ["--compress", "layerwise", "--method", "terngrad"]),
    ("blocktopk-em-1%-wire", ["--compress", "entiremodel", "--method",
                              "blocktopk", "--ratio", "0.01",
                              "--error_feedback", "--mode", "wire"]),
    # --- r3: the reference's ACTUAL sparsified-DDP operating regime -------
    # (VERDICT r2 #1): ImageNet step schedule (train.py:60-72), momentum 0.9
    # (train_imagenet_nv.py:49), Random-K + EF (sparsified_ddp.py:408-413).
    # The EF-spike analysis (benchmarks/ef_momentum_bisect_r3.txt) puts the
    # stable peak ~10x below dense's; dense-step-mom.9 at the same shape is
    # the control.
    ("dense-step", ["--lr_schedule", "step", "--peak_lr", "0.4"]),
    # k=1% winning recipe (0.9539 vs dense 0.9624 in the r3 pilot): ~10x
    # lower peak than dense (EF-spike stability, ef_momentum_bisect_r3),
    # DGC sparsity warm-up over the first 16 epochs, both clips, 60 epochs
    ("randomk-em-1%-wire-EF-mom9", [
        "--compress", "entiremodel", "--method", "randomk", "--ratio", "0.01",
        "--error_feedback", "--mode", "wire",
        "--lr_schedule", "step", "--peak_lr", "0.04",
        "--epochs", "60", "--ratio_warmup_epochs", "16",
        "--clip_norm", "1.0", "--clip_sent_norm", "1.0"]),
    # k=10% needs no warm-up (EF delay ~10 steps): 0.9526 in the pilot
    ("randomk-em-10%-wire-EF-mom9", [
        "--compress", "entiremodel", "--method", "randomk", "--ratio", "0.1",
        "--error_feedback", "--mode", "wire",
        "--lr_schedule", "step", "--peak_lr", "0.04",
        "--clip_norm", "1.0", "--clip_sent_norm", "1.0"]),
    ("topk-em-1%-wire-EF-step", [
        "--compress", "entiremodel", "--method", "topk", "--ratio", "0.01",
        "--error_feedback", "--mode", "wire",
        "--lr_schedule", "step", "--peak_lr", "0.04"]),
    # --- r4: the paper grid's hardest point, k=0.1% (VERDICT r3 #4) -------
    # EF delay is ~1000 steps per coordinate; start from the k=1% winning
    # recipe shape (step peak 0.04, warm-up, both clips) with the warm-up
    # stretched — the geometric ramp needs more epochs to reach 1e-3.
    ("randomk-em-0.1%-wire-EF-mom9", [
        "--compress", "entiremodel", "--method", "randomk", "--ratio", "0.001",
        "--error_feedback", "--mode", "wire",
        "--lr_schedule", "step", "--peak_lr", "0.04",
        "--epochs", "60", "--ratio_warmup_epochs", "16",
        "--clip_norm", "1.0", "--clip_sent_norm", "1.0"]),
    ("topk-em-0.1%-wire-EF-mom9", [
        "--compress", "entiremodel", "--method", "topk", "--ratio", "0.001",
        "--error_feedback", "--mode", "wire",
        "--lr_schedule", "step", "--peak_lr", "0.04",
        "--epochs", "60", "--ratio_warmup_epochs", "16",
        "--clip_norm", "1.0", "--clip_sent_norm", "1.0"]),
    # randomk at k=0.1% under the 1%-recipe reaches only 0.70 in 60 epochs
    # (learning, not diverging — EF delay ~1000 steps just slows it); the
    # operating-point adjustment stretches the run and the warm-up
    ("randomk-em-0.1%-wire-EF-mom9-long", [
        "--compress", "entiremodel", "--method", "randomk", "--ratio", "0.001",
        "--error_feedback", "--mode", "wire",
        "--lr_schedule", "step", "--peak_lr", "0.04",
        "--epochs", "90", "--ratio_warmup_epochs", "24",
        "--clip_norm", "1.0", "--clip_sent_norm", "1.0"]),
    # the completing point of the k=0.1% operating map: 60/16 -> 0.70,
    # 90/24 -> 0.926, 120/32 -> 0.9604 (~dense parity) — EF delay at
    # k=0.1% costs ~2x the epochs, it does not need a different recipe
    ("randomk-em-0.1%-wire-EF-mom9-120ep", [
        "--compress", "entiremodel", "--method", "randomk", "--ratio", "0.001",
        "--error_feedback", "--mode", "wire",
        "--lr_schedule", "step", "--peak_lr", "0.04",
        "--epochs", "120", "--ratio_warmup_epochs", "32",
        "--clip_norm", "1.0", "--clip_sent_norm", "1.0"]),
    # --- r5: threshold-family science (VERDICT r4 #6) ---------------------
    # V-sweep: the reference's fixed-V operator at the default V=1e-3 ships
    # 97% of coordinates (see thresholdv-lw above) — these rows raise V to
    # trace out the accuracy + sent_frac vs V curve the paper's "V is hard
    # to tune" claim implies (`CIFAR10/core.py:189-193`).  Protocol-faithful:
    # no EF (the reference composes EF only with Random-K), 40 epochs (the
    # 40-epoch rule covers Thresholdv, `dawn.py:105-108`).
    ("thresholdv-lw-V3e-3", ["--compress", "layerwise", "--method",
                             "thresholdv", "--threshold", "0.003"]),
    ("thresholdv-lw-V1e-2", ["--compress", "layerwise", "--method",
                             "thresholdv", "--threshold", "0.01"]),
    ("thresholdv-lw-V3e-2", ["--compress", "layerwise", "--method",
                             "thresholdv", "--threshold", "0.03"]),
    ("thresholdv-lw-V1e-1", ["--compress", "layerwise", "--method",
                             "thresholdv", "--threshold", "0.1"]),
    # Adaptive-threshold (max|g|*0.5/layer, ~0.02% kept) sits at 0.485 in the
    # 24-ep row: is that method-inherent or recipe?  The comparison set:
    # 40-epoch rule alone, EF alone, both — topk at the SAME 0.1% density
    # with EF reaches 0.9619, so EF is the mechanism hypothesis.
    ("adaptive-lw-40ep", ["--compress", "layerwise", "--method",
                          "adaptive_threshold", "--epochs", "40"]),
    ("adaptive-lw-EF", ["--compress", "layerwise", "--method",
                        "adaptive_threshold", "--error_feedback"]),
    ("adaptive-lw-EF-40ep", ["--compress", "layerwise", "--method",
                             "adaptive_threshold", "--error_feedback",
                             "--epochs", "40"]),
    # r5: small-block Block-Top-K — the granularity<->accuracy frontier
    # companion to the throughput bs-sweep (benchmarks/wire_wall_r5.txt):
    # does bs=64 selection (the 1.64x-dense wire point) converge like
    # element Top-K (0.9619) or cost accuracy?
    ("blocktopk-em-1%-wire-bs64", ["--compress", "entiremodel", "--method",
                                   "blocktopk", "--ratio", "0.01",
                                   "--block_size", "64",
                                   "--error_feedback", "--mode", "wire"]),
    # bs=8: near-element selection granularity at ~1.5x-dense wire speed
    # (the covering-row payload path, r5)
    ("blocktopk-em-1%-wire-bs8", ["--compress", "entiremodel", "--method",
                                  "blocktopk", "--ratio", "0.01",
                                  "--block_size", "8",
                                  "--error_feedback", "--mode", "wire"]),
    # the frontier's hardest point: k=0.1% at 8-element blocks, under the
    # recipe that closed element Top-K k=0.1% (step peak 0.04, 16-ep
    # geometric warm-up, both clips, 60 epochs — convergence_r4.tsv)
    ("blocktopk-em-0.1%-wire-bs8-mom9", [
        "--compress", "entiremodel", "--method", "blocktopk",
        "--ratio", "0.001", "--block_size", "8",
        "--error_feedback", "--mode", "wire",
        "--lr_schedule", "step", "--peak_lr", "0.04",
        "--epochs", "60", "--ratio_warmup_epochs", "16",
        "--clip_norm", "1.0", "--clip_sent_norm", "1.0"]),
    # --- r6: PowerSGD rank axis (ops/lowrank.py) --------------------------
    # The low-rank companion to the k-ratio sweeps: r in {1, 2, 4} at
    # layerwise grouping, EF on (Vogels et al. run PowerSGD with EF always;
    # the factors are a biased projection, EF is what makes it converge).
    # Wire cost at r is ~r*(m + n/m)/n of dense — r=1 undercuts even
    # k=0.1% Top-K while riding the psum ring instead of an all_gather.
    ("powersgd-lw-r1", ["--compress", "layerwise", "--method", "powersgd",
                        "--rank", "1", "--error_feedback"]),
    ("powersgd-lw-r2", ["--compress", "layerwise", "--method", "powersgd",
                        "--rank", "2", "--error_feedback"]),
    ("powersgd-lw-r4", ["--compress", "layerwise", "--method", "powersgd",
                        "--rank", "4", "--error_feedback"]),
    # entiremodel: one near-square matrix for the whole gradient — the
    # grouping that maximises the factor payload saving
    ("powersgd-em-r4", ["--compress", "entiremodel", "--method", "powersgd",
                        "--rank", "4", "--error_feedback"]),
]

COLS = ["label", "method", "ratio", "mode", "epochs", "train_acc", "test_acc",
        "train_loss", "test_loss", "sent_frac", "wire_frac", "total_s"]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="benchmarks/convergence_r2.tsv")
    ap.add_argument("--quick", action="store_true", help="3-epoch smoke")
    ap.add_argument("--synthetic_n", type=int, default=16384)
    ap.add_argument("--only", type=str, default=None,
                    help="comma list of labels to run")
    args = ap.parse_args(argv)

    from tpu_compressed_dp.harness import dawn

    only = set(args.only.split(",")) if args.only else None
    rows = []
    for label, extra in GRID:
        if only and label not in only:
            continue
        argv_run = ["--synthetic_hard", "--synthetic_n", str(args.synthetic_n),
                    "--momentum", "0.9", "--log_dir", ""] + extra
        if args.quick:
            argv_run += ["--epochs", "3"]
        print(f"### {label}", flush=True)
        t0 = time.time()
        s = dawn.main(argv_run)
        row = {
            "label": label,
            "method": next((extra[i + 1] for i, a in enumerate(extra)
                            if a == "--method"), "none"),
            "ratio": next((extra[i + 1] for i, a in enumerate(extra)
                           if a == "--ratio"), ""),
            "mode": "wire" if "--mode" in extra else "simulate",
            "epochs": s["epoch"],
            "train_acc": round(s["train acc"], 4),
            "test_acc": round(s["test acc"], 4),
            "train_loss": round(s["train loss"], 4),
            "test_loss": round(s["test loss"], 4),
            "sent_frac": round(s.get("sent frac", 1.0), 5),
            "wire_frac": round(s.get("wire frac", 1.0), 5),
            "total_s": round(time.time() - t0, 1),
        }
        rows.append(row)
        print({k: row[k] for k in ("label", "test_acc", "train_acc")}, flush=True)

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\t".join(COLS) + "\n")
        for r in rows:
            f.write("\t".join(str(r[c]) for c in COLS) + "\n")
    print(f"wrote {args.out} ({len(rows)} rows)")


if __name__ == "__main__":
    main()
