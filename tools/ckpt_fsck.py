#!/usr/bin/env python
"""Offline checkpoint fsck: verify / list / prune a checkpoint directory.

Every checkpoint the :class:`~tpu_compressed_dp.utils.checkpoint.Checkpointer`
commits carries a checksummed manifest (``manifest-<step>.json`` next to the
step directory: per-file sha256 + byte counts, schema-versioned, committed
atomically AFTER the Orbax write).  This tool re-verifies those digests
offline — before resuming a long run on a directory that survived a
preemption, or from cron over a fleet's checkpoint trees:

  * default — verify every step; print OK / CORRUPT per step (legacy steps
    without a manifest are tolerated and flagged), plus orphaned manifests
    whose step directory is gone.  Exit 0 = all verifiable, 1 = something
    is corrupt, 2 = the directory is missing/empty.
  * ``--list`` — one line per step with its manifest summary (file count,
    payload bytes, meta keys), no verification.  Exit 0.
  * ``--prune`` — delete corrupt step directories and their manifests (and
    orphaned manifests), leaving only steps a restore can actually use.
    Exit 0 after pruning.

Delta state streams (:mod:`tpu_compressed_dp.stream`, harness
``--stream_dir``) use the same manifest-checksum discipline per segment,
and fsck covers them with the same verbs: if the target directory is a
stream dir — or has a ``stream/`` subdirectory next to the checkpoints —
segments are verified (``seg N: OK/CORRUPT``) and listed, and ``--prune``
drops superseded delta windows via
:func:`tpu_compressed_dp.stream.store.prune_segments` (``--keep_windows``,
default 2).  Exit semantics are unchanged: 1 = any step OR segment is
corrupt, 2 = nothing verifiable at all (no steps and no segments).

Pure host-side file I/O — no JAX or Orbax import, safe to run anywhere::

    python tools/ckpt_fsck.py /ckpts/run17
    python tools/ckpt_fsck.py /ckpts/run17 --list
    python tools/ckpt_fsck.py /ckpts/run17 --prune
    python tools/ckpt_fsck.py /runs/lm17/stream            # stream dir
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
from typing import List, Optional

from tpu_compressed_dp.stream.store import (is_stream_dir, list_segments,
                                            prune_segments,
                                            read_segment_manifest,
                                            verify_stream)
from tpu_compressed_dp.utils.checkpoint import (list_step_dirs, manifest_path,
                                                read_manifest, verify_step_dir)


def _find_stream_dir(directory: str) -> Optional[str]:
    """The directory itself if it is a delta stream, else its ``stream/``
    subdirectory when a harness kept checkpoints and stream side by side."""
    if is_stream_dir(directory):
        return directory
    sub = os.path.join(directory, "stream")
    if is_stream_dir(sub):
        return sub
    return None


def _orphan_manifests(directory: str, steps: List[int]) -> List[str]:
    """manifest-<step>.json files whose step directory no longer exists
    (a crash between Orbax's delete and the manifest cleanup)."""
    have = {str(s) for s in steps}
    out = []
    for name in sorted(os.listdir(directory)):
        if not (name.startswith("manifest-") and name.endswith(".json")):
            continue
        step = name[len("manifest-"):-len(".json")]
        if step.isdigit() and step not in have:
            out.append(os.path.join(directory, name))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("dir", help="checkpoint directory (harness --checkpoint_dir)")
    p.add_argument("--list", action="store_true",
                   help="list steps + manifest summaries, no verification")
    p.add_argument("--prune", action="store_true",
                   help="delete corrupt step dirs + orphaned manifests; for "
                        "streams, drop superseded delta windows")
    p.add_argument("--keep_windows", type=int, default=2,
                   help="stream --prune: keyframe windows to retain "
                        "(default 2)")
    args = p.parse_args(argv)

    if not os.path.isdir(args.dir):
        print(f"ckpt_fsck: no such directory: {args.dir}")
        return 2
    steps = list_step_dirs(args.dir)
    stream_dir = _find_stream_dir(args.dir)
    seqs = list_segments(stream_dir) if stream_dir is not None else []
    if not steps and not seqs:
        print(f"ckpt_fsck: no checkpoints under {args.dir}")
        return 2

    if args.list:
        for s in steps:
            man = read_manifest(args.dir, s)
            if man is None:
                print(f"step {s}: (no manifest — legacy checkpoint)")
                continue
            files = man.get("files", {}) or {}
            total = sum(int(e.get("bytes", 0)) for e in files.values())
            meta_keys = ",".join(sorted((man.get("meta") or {}).keys())) or "-"
            print(f"step {s}: {len(files)} files, {total} bytes, "
                  f"meta[{meta_keys}]")
        for q in seqs:
            man = read_segment_manifest(stream_dir, q)
            if man is None:
                print(f"seg {q}: (manifest unreadable)")
                continue
            close = " window-close" if man.get("window_close") else ""
            print(f"seg {q}: {man.get('kind')} step {man.get('step')}, "
                  f"{man.get('bytes')} bytes, nnz {man.get('nnz')}{close}")
        return 0

    bad: List[int] = []
    for s in steps:
        problems = verify_step_dir(args.dir, s)
        if problems:
            bad.append(s)
            for pr in problems:
                print(f"step {s}: CORRUPT: {pr}")
        elif read_manifest(args.dir, s) is None and not os.path.exists(
                manifest_path(args.dir, s)):
            print(f"step {s}: OK (legacy, no manifest)")
        else:
            print(f"step {s}: OK")
    orphans = _orphan_manifests(args.dir, steps)
    for o in orphans:
        print(f"orphaned manifest: {o}")

    stream_problems: List[str] = []
    if stream_dir is not None:
        stream_problems, all_seqs = verify_stream(stream_dir)
        bad_seqs = set()
        for pr in stream_problems:
            print(f"stream: CORRUPT: {pr}")
            if pr.startswith("segment "):
                head = pr[len("segment "):].split(":", 1)[0]
                if head.isdigit():
                    bad_seqs.add(int(head))
        for q in all_seqs:
            if q not in bad_seqs:
                print(f"seg {q}: OK")

    if args.prune:
        for s in bad:
            shutil.rmtree(os.path.join(args.dir, str(s)), ignore_errors=True)
            try:
                os.remove(manifest_path(args.dir, s))
            except OSError:
                pass
            print(f"pruned step {s}")
        for o in orphans:
            try:
                os.remove(o)
                print(f"pruned {o}")
            except OSError:
                pass
        if stream_dir is not None:
            dropped = prune_segments(stream_dir,
                                     keep_windows=args.keep_windows)
            for q in dropped:
                print(f"pruned seg {q}")
        return 0
    return 1 if (bad or stream_problems) else 0


if __name__ == "__main__":
    sys.exit(main())
