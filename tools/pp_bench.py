"""PP step-time microbenchmark (VERDICT r2 #6: head out of the tick loop).

Times the (data, pipe) train step at a realistic head size (vocab 32k) on
whatever backend is live (the 8-virtual-device CPU mesh in CI — pipe needs
multiple devices, and the repo has one real chip).  Relative numbers
before/after the deferred-head change are the point, not absolute ms.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 python tools/pp_bench.py
"""
from __future__ import annotations

import os, sys, time

if __name__ == "__main__":
    # standalone: virtual 8-device CPU mesh, set before the first jax import
    # (importers — the test suite, tcdp-lint smoke — get no side effects)
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tpu_compressed_dp.models import transformer as tf
from tpu_compressed_dp.parallel.dp import CompressionConfig
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.pp_step import (
    init_pp_ef_state, make_pp_mesh, make_pp_train_step, stack_layer_params)


def main():
    import dataclasses
    cfg = dataclasses.replace(
        tf.tiny_llama(), vocab_size=32768, dim=128, n_layers=4,
        dtype=jnp.float32)
    dp, pp, M = 2, 4, 8
    mesh = make_pp_mesh(dp, pp)
    params = stack_layer_params(tf.init_llama(cfg, jax.random.key(0)))
    comp = CompressionConfig(method=None)
    opt = SGD(lr=1e-3, momentum=0.9)
    state = TrainState.create(params, {}, opt.init(params),
                              init_pp_ef_state(cfg, params, comp, mesh),
                              jax.random.key(1))
    step = make_pp_train_step(cfg, opt, comp, mesh, microbatches=M)
    T, B = 64, dp * M * 2
    rng = np.random.default_rng(0)
    batch = {"input": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T), dtype=np.int32)),
             "target": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T), dtype=np.int32))}
    for _ in range(2):  # two compiles (donated layouts)
        state, m = step(state, batch)
        jax.device_get(m)
    t0 = time.perf_counter()
    n = 5
    for _ in range(n):
        state, m = step(state, batch)
    jax.device_get(m)
    dt = (time.perf_counter() - t0) / n
    print(f"vocab={cfg.vocab_size} dim={cfg.dim} pp={pp} dp={dp} M={M} T={T} "
          f"B={B}: step {dt*1e3:.1f} ms  loss={float(m['loss']):.3f}")


if __name__ == "__main__":
    main()
