#!/usr/bin/env python3
"""Dataset staging / snapshot / tensorboard utilities — the TPU-native
equivalents of the reference's AWS dataset tools (`IMAGENET/tools/`).

The reference replicates ImageNet onto one EBS volume per worker
(`replicate_imagenet.py`: create volume from snapshot, attach, mount) and
documents a snapshot-creation runbook (`create_imagenet_snapshot.py`).  On
Cloud TPU the durable copy lives in a GCS bucket (the "snapshot") and the
per-worker high-performance copy is the TPU-VM's local SSD (the "EBS
replica"); both reduce to gcloud commands fanned out to every worker of the
pod slice — same fan-out pattern as tools/launch_tpu.py.  All subcommands
PRINT the command by default and execute with ``--run`` (the operator may
not have gcloud auth in this shell).

  # upload a local tree once -> the bucket is the snapshot
  python tools/dataset_tools.py snapshot /data/imagenet gs://my-bucket/imagenet

  # stage the bucket onto every worker's local disk (one rsync per worker)
  python tools/dataset_tools.py stage gs://my-bucket/imagenet /mnt/disks/ssd/imagenet \
      --tpu my-pod --zone us-east5-a

  # tensorboard over the training logdir (the launch_tensorboard.py analog;
  # TPU-VM port 6006 reached via SSH port-forward instead of a public IP)
  python tools/dataset_tools.py tensorboard logs/tb --tpu my-pod --zone us-east5-a
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys

if __package__ in (None, ""):  # script run: tools dir onto sys.path
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from launch_tpu import tpu_ssh_cmd  # noqa: E402 (shared ssh fan-out builder)
else:
    from tools.launch_tpu import tpu_ssh_cmd  # noqa: E402


def stage_cmd(args) -> list:
    """Fan `gcloud storage rsync` to all workers: each copies the dataset
    from GCS to its own local path (the per-worker EBS-replica role)."""
    inner = (f"mkdir -p {shlex.quote(args.dest)} && "
             f"gcloud storage rsync -r {shlex.quote(args.src)} {shlex.quote(args.dest)}")
    return tpu_ssh_cmd(args.tpu, args.zone, "all", inner)


def snapshot_cmd(args) -> list:
    """One upload from wherever the raw tree lives; GCS is the snapshot."""
    return ["gcloud", "storage", "rsync", "-r", args.src, args.dest]


def tensorboard_cmd(args) -> list:
    """Tensorboard on worker 0 with an SSH port-forward back to the
    operator (`launch_tensorboard.py` printed a public AWS IP; TPU-VMs
    aren't publicly routable)."""
    if args.tpu:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "ssh", args.tpu,
            f"--zone={args.zone}", "--worker=0",
            "--", "-L", f"{args.port}:localhost:{args.port}",
            f"tensorboard --logdir={shlex.quote(args.logdir)} --port={args.port}",
        ]
    return ["tensorboard", f"--logdir={args.logdir}", f"--port={args.port}"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("stage", help="rsync a GCS dataset to every worker's local disk")
    s.add_argument("src", help="gs://bucket/path")
    s.add_argument("dest", help="local path on each worker (e.g. /mnt/disks/ssd/imagenet)")
    s.add_argument("--tpu", required=True)
    s.add_argument("--zone", default="us-central2-b")

    c = sub.add_parser("snapshot", help="upload a local tree to GCS (the snapshot)")
    c.add_argument("src")
    c.add_argument("dest", help="gs://bucket/path")

    t = sub.add_parser("tensorboard", help="tensorboard on worker 0 via SSH port-forward")
    t.add_argument("logdir")
    t.add_argument("--tpu", default=None)
    t.add_argument("--zone", default="us-central2-b")
    t.add_argument("--port", type=int, default=6006)

    for sp in (s, c, t):
        sp.add_argument("--run", action="store_true",
                        help="execute (default: print the command)")

    args = p.parse_args(argv)
    cmd = {"stage": stage_cmd, "snapshot": snapshot_cmd,
           "tensorboard": tensorboard_cmd}[args.cmd](args)
    print(" ".join(shlex.quote(c) for c in cmd))
    if args.run:
        return subprocess.call(cmd)
    return 0


if __name__ == "__main__":
    sys.exit(main())
