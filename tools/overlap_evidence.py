"""Compiled-schedule evidence for the comm/compute overlap claim (VERDICT r4 #7).

The reference overlaps gradient communication with backward compute through
hand-registered autograd hooks + 25 MB buckets
(`IMAGENET/training/ddp.py:429-456`).  This framework's round-1..4 answer was
"XLA's scheduler handles it" — an assertion.  This tool replaces the
assertion with the compiled artifact: it AOT-compiles the REAL CIFAR train
step (`train/step.py:make_train_step`, the exact code the harness runs) for
an 8-chip v5e topology (`jax.experimental.topologies` — no 8-chip hardware
needed; the backend emits the true scheduled module, `is_scheduled=true`,
with the production collective emitter configs) and reads the schedule:

  * how many all-reduce instructions the module actually issues per step for
    granularity = layerwise (one psum per parameter) / bucketed (25 MB) /
    entiremodel — i.e. what XLA's all-reduce COMBINER does to the
    collective count before scheduling;
  * where collectives sit in the linear schedule relative to compute
    (fusion/convolution/dot instructions): the fraction of compute scheduled
    AFTER each collective measures how much backward work remains to hide
    the collective behind — 0 after the last collective means the sync runs
    fully exposed at the step's tail.

Findings land in ``benchmarks/overlap_hlo_r5.txt`` and the PARITY.md
overlap paragraph cites them.

Usage:  python tools/overlap_evidence.py [--out benchmarks/overlap_hlo_r5.txt]
"""

from __future__ import annotations

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

COMPUTE_OPS = ("fusion", "convolution", "dot(", "dot.")
COLLECTIVE_RE = re.compile(r"%(all-reduce|all-gather|reduce-scatter)"
                           r"(?:-start)?[\.\s=]")


def build_step(granularity: str, method, mesh, mode: str = "simulate"):
    from tpu_compressed_dp.models.common import make_apply_fn
    from tpu_compressed_dp.bench.sweep import _build_model
    from tpu_compressed_dp.parallel.dp import CompressionConfig, init_ef_state
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState
    from tpu_compressed_dp.train.step import make_train_step
    from tpu_compressed_dp.models.common import init_model

    module, sz, ncls = _build_model("resnet9", 32, 10, 1.0)
    cfg = CompressionConfig(
        method=method, granularity=granularity, mode=mode, ratio=0.01,
        error_feedback=method is not None)
    opt = SGD(lr=0.01, momentum=0.9, weight_decay=5e-4)

    def make_state(seed):
        params, stats = init_model(
            module, jax.random.key(seed),
            jnp.zeros((1, sz, sz, 3), jnp.float32))
        return TrainState.create(
            params, stats, opt.init(params),
            init_ef_state(params, cfg, mesh.shape["data"]),
            jax.random.key(seed + 1))

    state_s = jax.eval_shape(make_state, 0)
    bs = 512
    batch_s = {
        "input": jax.ShapeDtypeStruct((bs, sz, sz, 3), jnp.float32),
        "target": jax.ShapeDtypeStruct((bs,), jnp.int32),
    }
    apply_fn = make_apply_fn(module)
    step = make_train_step(apply_fn, opt, cfg, mesh, grad_scale=1.0)
    return step, state_s, batch_s


def schedule_stats(txt: str):
    """Parse the scheduled ENTRY computation: instruction order IS the
    schedule (``is_scheduled=true``)."""
    entry = txt[txt.index("ENTRY "):]
    lines = entry.splitlines()
    compute_idx = []
    coll = []  # (line_idx, opname, n_operands, bytes)
    for i, ln in enumerate(lines):
        s = ln.strip()
        if not s.startswith("%"):
            continue
        if any(k in s.split("=")[0] or k in s.split("(")[0]
               for k in ("fusion", "convolution")) or " dot(" in s:
            compute_idx.append(i)
        m = COLLECTIVE_RE.search(s)
        if m and "= " in s and ("all-reduce(" in s or "all-gather(" in s
                                or "reduce-scatter(" in s
                                or "-start(" in s):
            # operand count: top-level commas inside the call parens
            call = s[s.index("(", s.index(m.group(1))):]
            depth = 0
            ops = 1
            for ch in call:
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        break
                elif ch == "," and depth == 1:
                    ops += 1
            # payload bytes: sum the shapes of the RESULT tuple (everything
            # left of the call itself)
            call_at = s.find(" " + m.group(1) + (
                "-start(" if "-start(" in s else "("))
            shapes = re.findall(r"(f32|bf16|f16|s32|u32)\[([\d,]*)\]",
                                s[:call_at] if call_at > 0 else s)
            nbytes = 0
            for dt, dims in shapes:
                e = 1
                for d in dims.split(","):
                    if d:
                        e *= int(d)
                nbytes += e * (2 if dt in ("bf16", "f16") else 4)
            coll.append((i, m.group(1), ops, nbytes))
    total_c = len(compute_idx)
    rows = []
    for i, name, ops, nbytes in coll:
        after = sum(1 for c in compute_idx if c > i)
        rows.append(dict(op=name, operands=ops, approx_mb=nbytes / 1e6,
                         compute_after=after,
                         compute_after_frac=after / max(total_c, 1)))
    return rows, total_c


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="benchmarks/overlap_hlo_r5.txt")
    ap.add_argument("--topology", default="v5e:2x4")
    args = ap.parse_args(argv)

    from jax.experimental import topologies

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=args.topology)
    mesh = topologies.make_mesh(topo, (8,), ("data",))

    cases = [
        ("dense-layerwise", None, "layerwise"),
        ("dense-bucketed-25MB", None, "bucketed"),
        ("dense-entiremodel", None, "entiremodel"),
        ("topk1%-EF-layerwise-simulate", "topk", "layerwise"),
    ]
    out_lines = [
        f"# Compiled-schedule overlap evidence — tools/overlap_evidence.py",
        f"# target: {args.topology} (8 chips), REAL train/step.py module,",
        f"# AOT via jax.experimental.topologies (is_scheduled=true output of",
        f"# the production TPU backend; instruction order = the schedule).",
        f"# compute_after_frac: fraction of the module's compute instructions",
        f"# scheduled AFTER the collective — backward work still available to",
        f"# hide it behind.  0.0 => the collective runs fully exposed at the",
        f"# step tail.", ""]
    for label, method, gran in cases:
        step, state_s, batch_s = build_step(gran, method, mesh)
        # make_train_step returns a python wrapper around its internal jit;
        # an outer jit inlines it and exposes .lower for AOT
        txt = jax.jit(step).lower(state_s, batch_s).compile().as_text()
        rows, total_c = schedule_stats(txt)
        sched = "yes" if "is_scheduled=true" in txt else "NO"
        out_lines.append(
            f"== {label}: {len(rows)} collective instr "
            f"(scheduled={sched}, {total_c} compute instr) ==")
        for r in rows:
            out_lines.append(
                f"   {r['op']:14s} operands={r['operands']:3d} "
                f"~{r['approx_mb']:8.2f} MB  "
                f"compute_after={r['compute_after']:4d} "
                f"({100*r['compute_after_frac']:5.1f}%)")
        print(out_lines[-1 - len(rows)])
        for ln in out_lines[-len(rows):]:
            print(ln)
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\n".join(out_lines) + "\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
