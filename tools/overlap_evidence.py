"""Compiled-schedule evidence for the comm/compute overlap claim (VERDICT r4 #7).

The reference overlaps gradient communication with backward compute through
hand-registered autograd hooks + 25 MB buckets
(`IMAGENET/training/ddp.py:429-456`).  This framework's round-1..4 answer was
"XLA's scheduler handles it" — an assertion.  This tool replaces the
assertion with the compiled artifact: it AOT-compiles the REAL CIFAR train
step (`train/step.py:make_train_step`, the exact code the harness runs) for
an 8-chip v5e topology (`jax.experimental.topologies` — no 8-chip hardware
needed; the backend emits the true scheduled module, `is_scheduled=true`,
with the production collective emitter configs) and reads the schedule:

  * how many all-reduce instructions the module actually issues per step —
    what XLA's all-reduce COMBINER does to the collective count before
    scheduling (r5 finding: every per-group psum merges into ONE late
    collective), and what the chunk-pipelined overlap subsystem
    (``sync_overlap=K``, `parallel/overlap.py`) does to keep K separate
    chunk collectives (rows are labelled with their ``tcdp.chunk<ii>``
    scope);
  * where collectives sit in the linear schedule relative to compute
    (fusion/convolution/dot instructions): the fraction of compute scheduled
    AFTER each collective measures how much backward work remains to hide
    the collective behind — 0 after the last collective means the sync runs
    fully exposed at the step's tail.

**Honest denominator** (r8): instructions inside the optimizer's
``tcdp.update`` scope are EXCLUDED from the compute numerator and
denominator.  A chunk's own update ops *depend* on its collective — they
cannot hide it — and the per-chunk optimizer interleave would otherwise
inflate the metric with exactly the ops it schedules after the collectives.
``compute_after_frac`` therefore counts only model (backward) compute.

Per-case summary: ``first`` — the earliest-issued collective's
compute_after_frac (how much of the step's compute window the sync overlaps
at all); ``mean`` over the case's collectives; ``last`` — the tail
exposure.  ``--assert-frac X`` exits nonzero when the ``--assert-case``
row's ``first`` falls below ``X`` — the CI gate for the ISSUE 5 acceptance
artifact (r5 baseline: 0.24–0.39).

Findings land in ``benchmarks/overlap_hlo_r8.txt`` (r5 file kept for
history) and BENCH_r08.json cites them.

Usage::

    python tools/overlap_evidence.py [--out benchmarks/overlap_hlo_r8.txt]
    python tools/overlap_evidence.py --assert-frac 0.60 \\
        --assert-case 'topk1%-EF-wire-sharded-bucketed4MB-overlap4'
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Optional

if __package__ in (None, ""):  # script run: repo root onto sys.path
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

COMPUTE_OPS = ("fusion", "convolution", "dot(", "dot.")
COLLECTIVE_RE = re.compile(r"%(all-reduce|all-gather|reduce-scatter|"
                           r"all-to-all)"
                           r"(?:-start)?[\.\s=]")
CHUNK_RE = re.compile(r"tcdp\.chunk(\d+)")


def build_step(granularity: str, method, mesh, mode: str = "simulate",
               overlap: int = 1, error_feedback: Optional[bool] = None,
               bucket_mb: float = 25.0, transport: str = "allgather",
               dp_pods: int = 1):
    from tpu_compressed_dp.models.common import make_apply_fn
    from tpu_compressed_dp.bench.sweep import _build_model
    from tpu_compressed_dp.parallel.dp import CompressionConfig, init_ef_state
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState
    from tpu_compressed_dp.train.step import make_train_step
    from tpu_compressed_dp.models.common import init_model

    module, sz, ncls = _build_model("resnet9", 32, 10, 1.0)
    cfg = CompressionConfig(
        method=method, granularity=granularity, mode=mode, ratio=0.01,
        error_feedback=(method is not None if error_feedback is None
                        else error_feedback),
        sync_overlap=overlap, bucket_mb=bucket_mb, transport=transport,
        dp_pods=dp_pods)
    opt = SGD(lr=0.01, momentum=0.9, weight_decay=5e-4)

    def make_state(seed):
        params, stats = init_model(
            module, jax.random.key(seed),
            jnp.zeros((1, sz, sz, 3), jnp.float32))
        return TrainState.create(
            params, stats, opt.init(params),
            init_ef_state(params, cfg, mesh.shape["data"]),
            jax.random.key(seed + 1))

    state_s = jax.eval_shape(make_state, 0)
    bs = 512
    batch_s = {
        "input": jax.ShapeDtypeStruct((bs, sz, sz, 3), jnp.float32),
        "target": jax.ShapeDtypeStruct((bs,), jnp.int32),
    }
    apply_fn = make_apply_fn(module)
    step = make_train_step(apply_fn, opt, cfg, mesh, grad_scale=1.0)
    return step, state_s, batch_s


#: Production TPU runs enable XLA's latency-hiding scheduler (the standard
#: LIBTPU_INIT_ARGS in maxtext/pax-style configs): it converts sync
#: collectives into async ``all-reduce-start``/``done`` pairs and actively
#: schedules compute between them.  The evidence should be read off the
#: same configuration; older/compile-only backends that reject the flag
#: fall back to the default scheduler (the output header records which).
LHS_OPTIONS = {"xla_tpu_enable_latency_hiding_scheduler": "true"}
_lhs_active = [True]


def compile_text(lowered) -> str:
    """Compile with the production LHS config, falling back (and recording
    the fact) when this backend rejects the option."""
    if _lhs_active[0]:
        try:
            return lowered.compile(compiler_options=LHS_OPTIONS).as_text()
        except Exception as e:  # unknown-flag / unsupported-option
            print(f"note: LHS compiler option rejected ({e!r}); "
                  "using default scheduler", file=sys.stderr)
            _lhs_active[0] = False
    return lowered.compile().as_text()


def _is_update_op(line: str) -> bool:
    """Optimizer-update instruction: its ``tcdp.update`` named scope
    survives into the HLO metadata op_name.  These ops DEPEND on their
    chunk's collective — counting them as hideable compute would let the
    per-chunk optimizer interleave game the metric."""
    return "tcdp.update" in line


def schedule_stats(txt: str):
    """Parse the scheduled ENTRY computation: instruction order IS the
    schedule (``is_scheduled=true``).  Returns ``(rows, total_compute,
    update_ops)`` where ``rows`` carry per-collective placement and the
    compute counts EXCLUDE optimizer-update ops (counted separately)."""
    entry = txt[txt.index("ENTRY "):]
    lines = entry.splitlines()
    compute_idx = []
    update_ops = 0
    coll = []  # (line_idx, opname, n_operands, bytes, chunk_label)
    for i, ln in enumerate(lines):
        s = ln.strip()
        if not s.startswith("%"):
            continue
        if any(k in s.split("=")[0] or k in s.split("(")[0]
               for k in ("fusion", "convolution")) or " dot(" in s:
            if _is_update_op(s):
                update_ops += 1
            else:
                compute_idx.append(i)
        m = COLLECTIVE_RE.search(s)
        if m and "= " in s and ("all-reduce(" in s or "all-gather(" in s
                                or "reduce-scatter(" in s
                                or "all-to-all(" in s
                                or "-start(" in s):
            # operand count: top-level commas inside the call parens (a
            # matched name with no following call paren — e.g. an async
            # done/update line naming its start op — counts as 1 operand)
            name_at = s.find(m.group(1))
            paren_at = s.find("(", name_at) if name_at >= 0 else -1
            ops = 1
            if paren_at >= 0:
                depth = 0
                for ch in s[paren_at:]:
                    if ch == "(":
                        depth += 1
                    elif ch == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    elif ch == "," and depth == 1:
                        ops += 1
            # payload bytes: sum the shapes of the RESULT tuple (everything
            # left of the call itself)
            call_at = s.find(" " + m.group(1) + (
                "-start(" if "-start(" in s else "("))
            shapes = re.findall(r"(f32|bf16|f16|s32|u32|u8)\[([\d,]*)\]",
                                s[:call_at] if call_at > 0 else s)
            nbytes = 0
            for dt, dims in shapes:
                e = 1
                for d in dims.split(","):
                    if d:
                        e *= int(d)
                nbytes += e * (1 if dt == "u8"
                               else 2 if dt in ("bf16", "f16") else 4)
            cm = CHUNK_RE.search(s)
            chunk = f"c{int(cm.group(1)):02d}" if cm else "-"
            coll.append((i, m.group(1), ops, nbytes, chunk))
    total_c = len(compute_idx)
    rows = []
    for i, name, ops, nbytes, chunk in coll:
        after = sum(1 for c in compute_idx if c > i)
        rows.append(dict(op=name, operands=ops, approx_mb=nbytes / 1e6,
                         chunk=chunk, compute_after=after,
                         compute_after_frac=after / max(total_c, 1)))
    return rows, total_c, update_ops


def case_summary(rows):
    """``(first, mean, last)`` compute_after_frac over a case's collectives:
    ``first`` = the earliest-issued collective (max frac — how much of the
    compute window the sync overlaps at all), ``last`` = tail exposure."""
    if not rows:
        return 0.0, 0.0, 0.0
    fracs = [r["compute_after_frac"] for r in rows]
    return max(fracs), sum(fracs) / len(fracs), min(fracs)


DEFAULT_CASES = [
    # (label, method, granularity, sync_overlap, bucket_mb, mode, transport)
    # NOTE the resnet9 probe model is ~26 MB, so the 25 MB default bucket
    # degenerates to 2 groups — the overlap rows use 4 MB buckets (7
    # groups) so sync_overlap=4 has real chunks to pipeline, with a
    # bucketed4MB sync_overlap=1 row as the like-for-like baseline.
    #
    # The simulate rows psum full-size tensors: this libtpu's AOT backend
    # emits SYNCHRONOUS all-reduce (no -start/-done pairs), and a blocking
    # collective is never scheduled mid-backward, so their overlap is
    # capped by the cross-chunk compress/EF compute (~0.47 at K=4; the
    # ROADMAP notes the async-collective revisit) — chunking's first-
    # collective lift shows HERE (0.22 -> 0.47), the combiner-merge case
    # r5 flagged.  The wire-sharded rows are the real compressed transport
    # — k-element per-group route/reduce/return collectives that escape
    # the all-reduce combiner by construction, interleaved with model
    # compute even at sync_overlap=1 (first~0.81); chunking raises the
    # mean compute-after and attaches the tcdp.chunk scopes.  The overlap4
    # wire row is the ISSUE 5 acceptance row (--assert-case default): the
    # gate pins the SHIPPED schedule's >= 0.60 overlap against regression.
    ("dense-layerwise", None, "layerwise", 1, 25.0, "simulate", "allgather"),
    ("dense-bucketed-25MB", None, "bucketed", 1, 25.0, "simulate",
     "allgather"),
    ("dense-bucketed4MB", None, "bucketed", 1, 4.0, "simulate", "allgather"),
    ("dense-bucketed4MB-overlap4", None, "bucketed", 4, 4.0, "simulate",
     "allgather"),
    ("topk1%-EF-layerwise-simulate", "topk", "layerwise", 1, 25.0,
     "simulate", "allgather"),
    ("topk1%-EF-bucketed4MB", "topk", "bucketed", 1, 4.0, "simulate",
     "allgather"),
    ("topk1%-EF-bucketed4MB-overlap4", "topk", "bucketed", 4, 4.0,
     "simulate", "allgather"),
    ("topk1%-EF-wire-sharded-bucketed4MB", "topk", "bucketed", 1, 4.0,
     "wire", "sharded"),
    ("topk1%-EF-wire-sharded-bucketed4MB-overlap4", "topk", "bucketed", 4,
     4.0, "wire", "sharded"),
    # The hierarchical transport's ICI/DCN/ICI ladder composes with the
    # chunk pipeline unchanged (chunk boundaries wrap whole groups, so
    # each chunk runs its own two-level reduce under its tcdp.chunk
    # scope); the trailing 2 is dp_pods on the 2x4 virtual mesh.
    ("topk1%-EF-wire-hier2x4-bucketed4MB", "topk", "bucketed", 1, 4.0,
     "wire", "hierarchical", 2),
    ("topk1%-EF-wire-hier2x4-bucketed4MB-overlap4", "topk", "bucketed", 4,
     4.0, "wire", "hierarchical", 2),
]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="output artifact (default: benchmarks/"
                         "overlap_hlo_r8.txt for FULL runs; a --cases-"
                         "filtered run prints only, so a quick iteration "
                         "cannot clobber the committed full table)")
    ap.add_argument("--topology", default="v5e:2x4")
    ap.add_argument("--cases", default=None,
                    help="comma-separated case-label substrings to run "
                         "(default: all)")
    ap.add_argument("--assert-frac", type=float, default=None,
                    help="exit 1 unless the --assert-case row's FIRST "
                         "collective has compute_after_frac >= this")
    ap.add_argument("--assert-case",
                    default="topk1%-EF-wire-sharded-bucketed4MB-overlap4",
                    help="case label the --assert-frac gate applies to "
                         "(default: the wire-transport topk-EF overlap row "
                         "— the compressed collectives the paper actually "
                         "ships)")
    args = ap.parse_args(argv)

    from jax.experimental import topologies

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=args.topology)
    mesh = topologies.make_mesh(topo, (8,), ("data",))

    cases = DEFAULT_CASES
    if args.cases:
        wanted = [w.strip() for w in args.cases.split(",") if w.strip()]
        cases = [c for c in cases if any(w in c[0] for w in wanted)]
    out_lines = [
        f"# Compiled-schedule overlap evidence — tools/overlap_evidence.py",
        f"# target: {args.topology} (8 chips), REAL train/step.py module,",
        f"# AOT via jax.experimental.topologies (is_scheduled=true output of",
        f"# the production TPU backend; instruction order = the schedule).",
        f"# compute_after_frac: fraction of the module's MODEL compute",
        f"# instructions (optimizer tcdp.update ops excluded — they depend",
        f"# on the collectives and cannot hide them) scheduled AFTER the",
        f"# collective — backward work still available to hide it behind.",
        f"# 0.0 => the collective runs fully exposed at the step tail.",
        f"# chunk: the tcdp.chunk<ii> overlap scope that issued the",
        f"# collective (sync_overlap=K rows; '-' = unchunked).",
        f"# head: model-compute instructions scheduled BEFORE the earliest",
        f"# collective — the serial head-of-chunk latency (threshold +",
        f"# select + pack before chunk 0's collective can issue) that caps",
        f"# the overlap pipeline's depth; the fused compressor kernels",
        f"# exist to shrink exactly this segment.", ""]
    summaries = {}
    for case in cases:
        label, method, gran, overlap, bucket_mb, mode, transport = case[:7]
        dp_pods = case[7] if len(case) > 7 else 1
        step, state_s, batch_s = build_step(gran, method, mesh, mode=mode,
                                            overlap=overlap,
                                            bucket_mb=bucket_mb,
                                            transport=transport,
                                            dp_pods=dp_pods)
        # make_train_step returns a python wrapper around its internal jit;
        # an outer jit inlines it and exposes .lower for AOT
        txt = compile_text(jax.jit(step).lower(state_s, batch_s))
        rows, total_c, upd = schedule_stats(txt)
        sched = "yes" if "is_scheduled=true" in txt else "NO"
        first, mean, last = case_summary(rows)
        head = total_c - max((r["compute_after"] for r in rows), default=0)
        summaries[label] = (first, mean, last, len(rows))
        out_lines.append(
            f"== {label}: {len(rows)} collective instr "
            f"(scheduled={sched}, {total_c} compute instr, "
            f"{upd} update instr excluded) ==")
        for r in rows:
            out_lines.append(
                f"   {r['op']:14s} chunk={r['chunk']:4s} "
                f"operands={r['operands']:3d} "
                f"~{r['approx_mb']:8.2f} MB  "
                f"compute_after={r['compute_after']:4d} "
                f"({100*r['compute_after_frac']:5.1f}%)")
        out_lines.append(
            f"   summary: first={100*first:.1f}% mean={100*mean:.1f}% "
            f"last={100*last:.1f}% head={head} instr "
            f"({100 * head / max(total_c, 1):.1f}%)")
        for ln in out_lines[-(len(rows) + 2):]:
            print(ln)
    out_lines.append(
        f"# scheduler: latency-hiding "
        f"{'ON' if _lhs_active[0] else 'REJECTED by backend - default used'}"
        f" (options={LHS_OPTIONS})")
    out = args.out
    if out is None and not args.cases:
        out = "benchmarks/overlap_hlo_r8.txt"
    if out is not None:
        if args.cases:
            out_lines.insert(0, f"# PARTIAL run: --cases {args.cases}")
        os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
        with open(out, "w") as f:
            f.write("\n".join(out_lines) + "\n")
        print(f"wrote {out}")
    if args.assert_frac is not None:
        hit = summaries.get(args.assert_case)
        if hit is None:
            print(f"ASSERT-FRAC: case {args.assert_case!r} not run")
            return 1
        first = hit[0]
        ok = first >= args.assert_frac
        print(f"ASSERT-FRAC: {args.assert_case}: first={100*first:.1f}% "
              f"{'>=' if ok else '<'} {100*args.assert_frac:.1f}% -> "
              f"{'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
