"""The fleet scheduler: one decision loop over many supervised jobs.

:class:`FleetScheduler` turns the repo's single-job resilience primitives
into scheduling primitives.  Each ``tick()``:

  1. **admit** — parse the shared-dir admission queue
     (``fleet/state.py``), reject malformed or unplaceable specs with a
     ``fleet_reject`` event, enqueue the rest (``fleet_admit``);
  2. **poll** — ask the :class:`JobController` about every running job:
     a clean exit finishes it (``fleet_finish``), a spontaneous
     ``PREEMPT_EXIT`` requeues it for resume (``fleet_preempt`` — some
     external agent preempted it; its emergency checkpoint makes that
     cheap), an unhealthy heartbeat verdict or crash burns a restart
     from its budget and requeues (``fleet_restart``) until the budget
     is spent (``fleet_fail``);
  3. **plan** — hand the snapshot to the pure planner
     (``fleet/placement.py``) — shrink-before-evict priority preemption,
     no growth while anyone waits;
  4. **execute** — drive the controller through the plan, moving device
     ids through the :class:`~tpu_compressed_dp.fleet.placement.DevicePool`
     and emitting ``fleet_shrink`` / ``fleet_evict`` / ``fleet_place`` /
     ``fleet_readmit`` events;
  5. **export** — atomic per-job status records + pool record
     (``fleet/state.py``) and per-job + pool Prometheus rollups
     (``fleet/*`` metrics, ``job`` label — one file per job, so many jobs
     share one textfile-collector dir without clobbering).

All side effects go through the injected controller/events/wall/sleep, so
multi-job preemption interleavings are unit-tested single-threaded with a
scripted controller (tests/test_fleet.py); ``tools/fleet.py`` provides the
real subprocess controller, the chaos drill an in-process elastic one.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tpu_compressed_dp.fleet import state as fstate
from tpu_compressed_dp.fleet.placement import (DevicePool, Evict, Grow,
                                               Place, Shrink, Slot, Waiting,
                                               plan)
from tpu_compressed_dp.fleet.spec import JobSpec
from tpu_compressed_dp.obs.export import write_prometheus
from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT

__all__ = ["JobController", "FleetScheduler"]


class JobController:
    """The scheduler's only way to touch a job — subclass per execution
    substrate.  ``resizable`` advertises in-place shrink/grow support
    (the in-process drill controller can remesh through the elastic
    readmit barrier; the v1 subprocess controller places and evicts whole
    jobs only)."""

    resizable = False

    def start(self, spec: JobSpec, world: int, devices: Tuple[int, ...],
              *, resume: bool) -> None:
        raise NotImplementedError

    def evict(self, job_id: str) -> int:
        """Preempt the job (SIGTERM -> emergency save); returns the exit
        code — :data:`PREEMPT_EXIT` when the preempt path worked."""
        raise NotImplementedError

    def shrink(self, job_id: str, world: int) -> None:
        raise NotImplementedError(f"{type(self).__name__} is not resizable")

    def grow(self, job_id: str, world: int,
             new_devices: Tuple[int, ...]) -> None:
        raise NotImplementedError(f"{type(self).__name__} is not resizable")

    def poll(self, job_id: str) -> Dict[str, Any]:
        """Status snapshot: ``exit_code`` (None while running),
        ``applied_updates`` (optional progress watermark), ``healthy``
        (optional heartbeat verdict; False triggers a restart)."""
        raise NotImplementedError


@dataclasses.dataclass
class _Job:
    spec: JobSpec
    seq: int
    status: str = "waiting"  # waiting | running | done | failed
    world: int = 0
    devices: Tuple[int, ...] = ()
    applied: int = 0
    restarts: int = 0
    resume: bool = False
    exit_code: Optional[int] = None
    straggler_skew: float = 0.0  # last polled cross-rank step-time skew (s)


class FleetScheduler:
    """See the module docstring.  ``wall`` stamps shared-dir records and
    events (injectable: a replayed tick writes byte-identical files);
    ``max_restarts`` is the per-job CRASH budget — preemptions (evictions
    and spontaneous ``PREEMPT_EXIT``) never burn it, mirroring the
    watchdog's own preempt accounting."""

    def __init__(self, fleet_dir: str, pool_size: int,
                 controller: JobController, *,
                 events=None,
                 wall: Callable[[], float] = time.time,
                 prom: bool = True,
                 max_restarts: int = 3,
                 max_straggler_skew_s: Optional[float] = None,
                 log: Callable[[str], None] = print):
        self.fleet_dir = fleet_dir
        self.pool_size = int(pool_size)
        self.controller = controller
        self.events = events
        self._wall = wall
        self.prom = prom
        self.max_restarts = int(max_restarts)
        #: evict-and-requeue a job whose polled cross-rank step-time skew
        #: (``straggler_skew_s``, the flight recorder's live gauge surfaced
        #: through the heartbeat) exceeds this bound — one slow host paces
        #: every collective, so requeueing onto fresh devices usually beats
        #: letting it drag the world (None = off)
        self.max_straggler_skew_s = max_straggler_skew_s
        self.log = log
        self.pool = DevicePool(self.pool_size)
        self.jobs: Dict[str, _Job] = {}
        self.counters: Dict[str, int] = {
            "admits": 0, "rejects": 0, "placements": 0, "evictions": 0,
            "shrinks": 0, "readmits": 0, "preemptions": 0, "restarts": 0,
            "finishes": 0, "failures": 0}
        self._seq = 0
        self._ticks = 0

    # ------------------------------------------------------------- events
    def _emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(kind, **fields)

    # ------------------------------------------------------------- submit
    def submit(self, spec: JobSpec) -> None:
        """Operator-side enqueue (also what ``tools/fleet.py submit``
        does, from another process)."""
        fstate.submit_job(self.fleet_dir, spec, ts=self._wall())
        self._emit("fleet_submit", job=spec.job_id, priority=spec.priority)

    # -------------------------------------------------------------- admit
    def _admit(self) -> None:
        for spec, rec in fstate.pending_submissions(self.fleet_dir):
            if spec is None:
                job_id = rec.get("job_id", "?")
                self.counters["rejects"] += 1
                self._emit("fleet_reject", job=job_id, error=rec.get("error"))
                self.log(f"fleet: reject {job_id}: {rec.get('error')}")
                fstate.clear_submission(self.fleet_dir, job_id)
                continue
            error = None
            if spec.min_world > self.pool_size:
                error = (f"min_world {spec.min_world} exceeds the pool "
                         f"({self.pool_size} devices)")
            elif spec.job_id in self.jobs:
                error = f"job_id {spec.job_id} already admitted"
            if error is not None:
                self.counters["rejects"] += 1
                self._emit("fleet_reject", job=spec.job_id, error=error)
                self.log(f"fleet: reject {spec.job_id}: {error}")
                fstate.clear_submission(self.fleet_dir, spec.job_id)
                continue
            self.jobs[spec.job_id] = _Job(spec=spec, seq=self._seq)
            self._seq += 1
            self.counters["admits"] += 1
            self._emit("fleet_admit", job=spec.job_id,
                       priority=spec.priority, seq=self.jobs[spec.job_id].seq)
            fstate.clear_submission(self.fleet_dir, spec.job_id)

    # --------------------------------------------------------------- poll
    def _release(self, job: _Job) -> None:
        if job.devices:
            self.pool.release(job.devices)
        job.devices = ()
        job.world = 0

    def _poll_running(self) -> None:
        for job in list(self.jobs.values()):
            if job.status != "running":
                continue
            st = self.controller.poll(job.spec.job_id) or {}
            if "applied_updates" in st:
                job.applied = int(st["applied_updates"])
            if "straggler_skew_s" in st:
                job.straggler_skew = float(st["straggler_skew_s"])
            rc = st.get("exit_code")
            if rc is None:
                straggling = (self.max_straggler_skew_s is not None
                              and job.straggler_skew
                              > self.max_straggler_skew_s)
                if st.get("healthy") is False or straggling:
                    # wedged/stale per the heartbeat verdict — or one rank
                    # pacing the whole world past the straggler bound: kill
                    # it and requeue — the restart budget decides how long
                    # we try
                    rc = self.controller.evict(job.spec.job_id)
                    why = ("straggling "
                           f"(skew {job.straggler_skew:.3g}s > "
                           f"{self.max_straggler_skew_s:g}s)"
                           if straggling else "unhealthy")
                    self.log(f"fleet: {job.spec.job_id} {why}; killed "
                             f"(exit {rc})")
                    self._fail_or_requeue(job, rc)
                continue
            rc = int(rc)
            job.exit_code = rc
            if rc == 0:
                job.status = "done"
                self._release(job)
                self.counters["finishes"] += 1
                self._emit("fleet_finish", job=job.spec.job_id,
                           applied_updates=job.applied)
            elif rc == PREEMPT_EXIT:
                # not OUR eviction (those are synchronous in _execute) —
                # an external preemption; resume costs seconds, not budget
                self._release(job)
                job.status = "waiting"
                job.resume = True
                self.counters["preemptions"] += 1
                self._emit("fleet_preempt", job=job.spec.job_id,
                           exit_code=rc)
            else:
                self._fail_or_requeue(job, rc)

    def _fail_or_requeue(self, job: _Job, rc: Optional[int]) -> None:
        self._release(job)
        job.exit_code = rc
        if job.restarts >= self.max_restarts:
            job.status = "failed"
            self.counters["failures"] += 1
            self._emit("fleet_fail", job=job.spec.job_id, exit_code=rc,
                       restarts=job.restarts)
            return
        job.restarts += 1
        job.status = "waiting"
        job.resume = True
        self.counters["restarts"] += 1
        self._emit("fleet_restart", job=job.spec.job_id, exit_code=rc,
                   restart=job.restarts)

    # ------------------------------------------------------------ execute
    def _snapshot(self) -> Tuple[List[Slot], List[Waiting]]:
        running, waiting = [], []
        for job in self.jobs.values():
            if job.status == "running":
                running.append(Slot(
                    job.spec.job_id, job.spec.priority, job.world,
                    job.spec.min_world, job.spec.max_world, job.seq,
                    elastic=self.controller.resizable and job.spec.elastic))
            elif job.status == "waiting":
                waiting.append(Waiting(
                    job.spec.job_id, job.spec.priority, job.spec.min_world,
                    job.spec.max_world, job.seq, resume=job.resume))
        return running, waiting

    def _execute(self, actions: Sequence) -> None:
        for act in actions:
            job = self.jobs[act.job_id]
            if isinstance(act, Shrink):
                freed = job.devices[act.world:]
                job.devices = job.devices[:act.world]
                job.world = act.world
                self.controller.shrink(job.spec.job_id, act.world)
                self.pool.release(freed)
                self.counters["shrinks"] += 1
                self._emit("fleet_shrink", job=job.spec.job_id,
                           world=act.world, freed=list(freed))
            elif isinstance(act, Evict):
                rc = self.controller.evict(job.spec.job_id)
                self._release(job)
                job.status = "waiting"
                job.resume = True
                job.exit_code = rc
                self.counters["evictions"] += 1
                self._emit("fleet_evict", job=job.spec.job_id, exit_code=rc)
                if rc != PREEMPT_EXIT:
                    self.log(f"fleet: evicted {job.spec.job_id} exited "
                             f"{rc}, not PREEMPT_EXIT({PREEMPT_EXIT}) — "
                             "no emergency save?")
            elif isinstance(act, Place):
                devices = self.pool.allocate(act.world)
                self.controller.start(job.spec, act.world, devices,
                                      resume=act.resume)
                job.status = "running"
                job.world = act.world
                job.devices = devices
                job.resume = False
                job.exit_code = None
                self.counters["placements"] += 1
                self._emit("fleet_place", job=job.spec.job_id,
                           world=act.world, devices=list(devices),
                           resume=act.resume)
            elif isinstance(act, Grow):
                new = self.pool.allocate(act.world - job.world)
                self.controller.grow(job.spec.job_id, act.world, new)
                job.devices = job.devices + new
                job.world = act.world
                self.counters["readmits"] += 1
                self._emit("fleet_readmit", job=job.spec.job_id,
                           world=act.world, devices=list(new))

    # ------------------------------------------------------------- export
    def _job_metrics(self, job: _Job) -> Dict[str, float]:
        return {"fleet/world": float(job.world),
                "fleet/priority": float(job.spec.priority),
                "fleet/applied_updates": float(job.applied),
                "fleet/restarts": float(job.restarts),
                "straggler/skew_s": float(job.straggler_skew)}

    def _export(self) -> None:
        ts = self._wall()
        for job in self.jobs.values():
            fstate.write_job_record(self.fleet_dir, {
                "job_id": job.spec.job_id, "status": job.status,
                "priority": job.spec.priority, "seq": job.seq,
                "world": job.world, "devices": list(job.devices),
                "applied_updates": job.applied, "restarts": job.restarts,
                "resume": job.resume, "exit_code": job.exit_code,
                "ts": ts})
        running = [j for j in self.jobs.values() if j.status == "running"]
        waiting = [j for j in self.jobs.values() if j.status == "waiting"]
        fstate.write_pool_record(self.fleet_dir, {
            "pool_size": self.pool_size, "ticks": self._ticks,
            "devices_free": self.pool.free_count,
            "jobs_running": len(running), "jobs_waiting": len(waiting),
            "counters": dict(self.counters), "ts": ts})
        if not self.prom:
            return
        pdir = fstate.prom_dir(self.fleet_dir)
        for job in self.jobs.values():
            write_prometheus(
                self._job_metrics(job),
                f"{pdir}/{job.spec.job_id}.fleet.prom",
                labels={"job": job.spec.job_id})
        write_prometheus(
            {"fleet/jobs_running": float(len(running)),
             "fleet/jobs_waiting": float(len(waiting)),
             "fleet/devices_free": float(self.pool.free_count),
             "fleet/evictions": float(self.counters["evictions"]),
             "fleet/shrinks": float(self.counters["shrinks"]),
             "fleet/readmits": float(self.counters["readmits"])},
            f"{pdir}/fleet.prom")

    # --------------------------------------------------------------- tick
    def tick(self) -> None:
        self._admit()
        self._poll_running()
        running, waiting = self._snapshot()
        self._execute(plan(self.pool_size, running, waiting))
        self._export()
        self._ticks += 1

    def idle(self) -> bool:
        """True when nothing is running or waiting (the queue may still
        receive submissions — ``run`` keeps polling unless told to stop)."""
        return not any(j.status in ("running", "waiting")
                       for j in self.jobs.values())

    def run(self, *, interval_s: float = 1.0,
            sleep: Callable[[float], None] = time.sleep,
            max_ticks: Optional[int] = None,
            until_idle: bool = False) -> int:
        """Tick until ``max_ticks`` (None = forever) or — with
        ``until_idle`` — until every admitted job has finished AND the
        queue is empty.  Returns the tick count."""
        while max_ticks is None or self._ticks < max_ticks:
            self.tick()
            if until_idle and self.idle():
                break
            sleep(interval_s)
        return self._ticks
