"""Fleet shared-dir protocol: the files a fleet is made of.

Same discipline as ``train/rendezvous.py`` — every record is one JSON file
written to a pid-unique ``*.tmp`` sibling and ``os.replace``d into place
(readers on shared storage never see a torn record), every read is
tolerant (missing/truncated/foreign content degrades to ``None``, never an
exception out of the decision loop).  This module holds NO clocks: callers
pass timestamps in (the scheduler's injectable ``wall``), so a replayed
tick writes byte-identical records.

Layout under one ``fleet_dir``::

    queue/submit.<job_id>.json   admission queue (tools/fleet.py submit)
    jobs/job.<job_id>.json       per-job status record (scheduler-owned)
    pool.json                    pool size + counters (scheduler-owned)
    fleet.events.jsonl           fleet_* JSONL event stream (append-only)
    prom/<job_id>.fleet.prom     per-job Prometheus rollup
    prom/fleet.prom              pool-level rollup

The queue is multi-writer (any operator may submit), everything else is
single-writer (the scheduler process) multi-reader (``tools/fleet.py
status``, dashboards, tests).
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from tpu_compressed_dp.fleet.spec import JobSpec, SpecError

__all__ = [
    "queue_dir", "jobs_dir", "prom_dir", "events_path", "pool_path",
    "submit_job", "pending_submissions", "clear_submission",
    "write_job_record", "read_job_record", "list_job_records",
    "write_pool_record", "read_pool_record",
]


def _read_json(path: str) -> Optional[dict]:
    """Tolerant read: None for missing/torn/foreign content (contract of
    ``utils.resilience.read_heartbeat`` — a reader retries next tick)."""
    try:
        with open(path, "rb") as f:
            rec = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


def _write_json(path: str, rec: dict) -> str:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)
    return path


def queue_dir(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, "queue")


def jobs_dir(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, "jobs")


def prom_dir(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, "prom")


def events_path(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, "fleet.events.jsonl")


def pool_path(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, "pool.json")


# ------------------------------------------------------------ admission queue

def _submit_path(fleet_dir: str, job_id: str) -> str:
    return os.path.join(queue_dir(fleet_dir), f"submit.{job_id}.json")


def submit_job(fleet_dir: str, spec: JobSpec, *, ts: float) -> str:
    """Drop one spec into the admission queue (operator side).  One pending
    submission per job_id — resubmitting before admission replaces it."""
    os.makedirs(queue_dir(fleet_dir), exist_ok=True)
    return _write_json(_submit_path(fleet_dir, spec.job_id),
                       {"spec": spec.to_json(), "ts": float(ts)})


def pending_submissions(fleet_dir: str) -> List[Tuple[JobSpec, dict]]:
    """Parse the queue, oldest first (submit ts, then job_id — both come
    from the record, so admission order replays).  Malformed specs are
    returned with ``spec=None`` inside the raw record under ``"error"`` so
    the scheduler can reject them visibly instead of looping over them."""
    out = []
    for path in sorted(glob.glob(os.path.join(queue_dir(fleet_dir),
                                              "submit.*.json"))):
        m = re.search(r"submit\.(.+)\.json$", os.path.basename(path))
        rec = _read_json(path)
        if m is None or rec is None:
            continue  # torn/in-flight write: picked up next tick
        try:
            spec = JobSpec.from_json(rec.get("spec"))
            if spec.job_id != m.group(1):
                raise SpecError(
                    f"queue file {os.path.basename(path)} names job "
                    f"{spec.job_id!r}")
        except SpecError as e:
            out.append((None, {**rec, "job_id": m.group(1), "error": str(e)}))
            continue
        out.append((spec, rec))
    out.sort(key=lambda sr: (float(sr[1].get("ts", 0.0)),
                             sr[0].job_id if sr[0] else sr[1]["job_id"]))
    return out


def clear_submission(fleet_dir: str, job_id: str) -> None:
    try:
        os.remove(_submit_path(fleet_dir, job_id))
    except OSError:
        pass


# ------------------------------------------------------------- job records

def _job_path(fleet_dir: str, job_id: str) -> str:
    return os.path.join(jobs_dir(fleet_dir), f"job.{job_id}.json")


def write_job_record(fleet_dir: str, rec: Dict[str, Any]) -> str:
    """Scheduler-owned per-job status record (``job_id`` keys the file)."""
    os.makedirs(jobs_dir(fleet_dir), exist_ok=True)
    return _write_json(_job_path(fleet_dir, str(rec["job_id"])), rec)


def read_job_record(fleet_dir: str, job_id: str) -> Optional[dict]:
    rec = _read_json(_job_path(fleet_dir, job_id))
    if rec is None or "job_id" not in rec or "status" not in rec:
        return None
    return rec


def list_job_records(fleet_dir: str) -> List[dict]:
    """All readable job records, sorted by job_id (``fleet.py status``)."""
    out = []
    for path in glob.glob(os.path.join(jobs_dir(fleet_dir), "job.*.json")):
        rec = _read_json(path)
        if rec is not None and "job_id" in rec and "status" in rec:
            out.append(rec)
    out.sort(key=lambda r: str(r["job_id"]))
    return out


# ------------------------------------------------------------- pool record

def write_pool_record(fleet_dir: str, rec: Dict[str, Any]) -> str:
    os.makedirs(fleet_dir, exist_ok=True)
    return _write_json(pool_path(fleet_dir), rec)


def read_pool_record(fleet_dir: str) -> Optional[dict]:
    rec = _read_json(pool_path(fleet_dir))
    if rec is None or "pool_size" not in rec:
        return None
    return rec
