"""Placement: the pure half of the fleet scheduler.

``plan()`` maps one snapshot of the pool (running slots + the waiting
queue) to an ordered action list — shrinks first, then evictions, then
placements, then growth — with no I/O, no clocks, and no randomness, so
the decision loop is replay-deterministic (tcdp-lint TCDP101) and every
preemption scenario is unit-testable as a plain function call.

Policy, in decreasing order of preference (cheapest capacity first):

  1. **Waiting jobs are served by (priority desc, submit seq asc).**  A
     job places as soon as ``free >= min_world``, at ``min(max_world,
     free)`` devices.
  2. **Shrink before evict.**  To fit a waiting job, strictly
     lower-priority ELASTIC slots give up ``world - min_world`` devices
     through the readmit barrier (lowest priority first, latest admitted
     first) — a shrink costs one remesh, an eviction costs a full
     save/restore cycle.
  3. **Evict as the last resort.**  Still short, strictly lower-priority
     slots are evicted (lowest priority first, latest admitted first) via
     the harness's SIGTERM -> emergency save -> exit 75 path; the
     scheduler requeues them with their ORIGINAL submit seq, so they
     reclaim capacity ahead of later arrivals once the pressure clears.
  4. **No growth while anyone waits.**  Freed capacity belongs to the
     waiting queue first; only an empty queue lets running elastic slots
     grow back toward ``max_world`` (priority desc, earliest admitted
     first) — that growth is the readmit half of the shrink in (2).

Equal priority never preempts equal priority: a tie is broken by arrival
only inside the waiting queue, not by taking a peer's devices.

:class:`DevicePool` is the slice allocator the scheduler pairs with the
plan: contiguous-first-fit device ids (falling back to the lowest free
ids when fragmented), so placements map cleanly onto mesh slices.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

__all__ = ["Slot", "Waiting", "Shrink", "Evict", "Place", "Grow", "Action",
           "plan", "DevicePool"]


@dataclasses.dataclass(frozen=True)
class Slot:
    """One running job as the planner sees it.  ``elastic`` means the
    CONTROLLER can resize it in place (an in-process drill job can; a v1
    subprocess job cannot — it only ever places or evicts whole)."""

    job_id: str
    priority: int
    world: int
    min_world: int
    max_world: int
    seq: int
    elastic: bool = True


@dataclasses.dataclass(frozen=True)
class Waiting:
    """One admission-queue entry (``resume`` marks an evicted job coming
    back: it keeps its original ``seq``, so it outranks later arrivals at
    equal priority)."""

    job_id: str
    priority: int
    min_world: int
    max_world: int
    seq: int
    resume: bool = False


@dataclasses.dataclass(frozen=True)
class Shrink:
    job_id: str
    world: int  # new (smaller) world


@dataclasses.dataclass(frozen=True)
class Evict:
    job_id: str


@dataclasses.dataclass(frozen=True)
class Place:
    job_id: str
    world: int
    resume: bool = False


@dataclasses.dataclass(frozen=True)
class Grow:
    job_id: str
    world: int  # new (larger) world


Action = Union[Shrink, Evict, Place, Grow]


def plan(pool_size: int, running: Sequence[Slot],
         waiting: Sequence[Waiting]) -> List[Action]:
    """One tick's decisions over a snapshot; see the module docstring for
    the policy.  The returned actions are ordered for execution: every
    Shrink/Evict lands before the Place it funds."""
    slots: Dict[str, Slot] = {s.job_id: s for s in running}
    free = int(pool_size) - sum(s.world for s in slots.values())
    actions: List[Action] = []
    queue = sorted(waiting, key=lambda w: (-w.priority, w.seq, w.job_id))
    placed_all = True
    for w in queue:
        need = int(w.min_world)
        if need > int(pool_size):
            # validated at admission; defensive here so one impossible spec
            # can never wedge the queue for everyone behind it
            placed_all = False
            continue
        if free < need:
            # (2) shrink strictly-lower-priority elastic slots, cheapest
            # victims first: lowest priority, then latest admitted
            for s in sorted(slots.values(), key=lambda s: (s.priority, -s.seq)):
                if free >= need:
                    break
                if s.priority >= w.priority or not s.elastic:
                    continue
                gain = s.world - s.min_world
                if gain <= 0:
                    continue
                give = min(gain, need - free)
                shrunk = dataclasses.replace(s, world=s.world - give)
                slots[s.job_id] = shrunk
                actions.append(Shrink(s.job_id, shrunk.world))
                free += give
        if free < need:
            # (3) evict, same victim order; an already-shrunk slot frees
            # only its shrunken world
            for s in sorted(slots.values(), key=lambda s: (s.priority, -s.seq)):
                if free >= need:
                    break
                if s.priority >= w.priority:
                    continue
                del slots[s.job_id]
                actions.append(Evict(s.job_id))
                free += s.world
        if free < need:
            placed_all = False  # nobody evictable is big enough; wait
            continue
        world = min(int(w.max_world), free)
        actions.append(Place(w.job_id, world, resume=w.resume))
        slots[w.job_id] = Slot(w.job_id, w.priority, world, w.min_world,
                               w.max_world, w.seq)
        free -= world
    if placed_all and not [a for a in actions if isinstance(a, Evict)]:
        # (4) growth = the readmit half of an earlier shrink; an eviction
        # this tick means its victim requeues next tick — capacity is
        # already spoken for, so growth waits a tick too
        for s in sorted(slots.values(), key=lambda s: (-s.priority, s.seq)):
            if free <= 0:
                break
            if not s.elastic or s.world >= s.max_world:
                continue
            take = min(s.max_world - s.world, free)
            grown = dataclasses.replace(s, world=s.world + take)
            slots[s.job_id] = grown
            actions.append(Grow(s.job_id, grown.world))
            free -= take
    return actions


class DevicePool:
    """Device-id slice allocator: contiguous first-fit, lowest-ids
    fallback when fragmented.  Purely bookkeeping — the controller maps
    ids onto real devices (``jax.devices()[i]`` in the drill)."""

    def __init__(self, pool_size: int):
        self.pool_size = int(pool_size)
        self._free = list(range(self.pool_size))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> Tuple[int, ...]:
        n = int(n)
        if n <= 0 or n > len(self._free):
            raise ValueError(
                f"cannot allocate {n} devices ({len(self._free)} free of "
                f"{self.pool_size})")
        free = sorted(self._free)
        run: Optional[Tuple[int, ...]] = None
        for i in range(len(free) - n + 1):
            window = free[i:i + n]
            if window[-1] - window[0] == n - 1:
                run = tuple(window)
                break
        ids = run if run is not None else tuple(free[:n])
        for d in ids:
            self._free.remove(d)
        return ids

    def release(self, ids: Sequence[int]) -> None:
        for d in ids:
            d = int(d)
            if not (0 <= d < self.pool_size):
                raise ValueError(f"device id {d} outside pool "
                                 f"[0, {self.pool_size})")
            if d in self._free:
                raise ValueError(f"device id {d} double-released")
            self._free.append(d)
