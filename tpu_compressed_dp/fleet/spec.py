"""Job specs — the admission-queue currency of the fleet control plane.

A :class:`JobSpec` is everything the scheduler needs to place, supervise,
preempt, and resume one training job: an identifier (also the namespace
prefix for its heartbeat/Prometheus/event files — see
``obs.export.job_scoped_path``), a priority, an elastic world range
(``min_world <= world <= max_world`` devices; ``min_world == max_world``
pins the job), the harness command, and the checkpoint directory its
eviction path saves into (the PR-8 SIGTERM -> emergency save -> exit 75
contract is what makes eviction cost seconds instead of a lost run).

Specs round-trip through JSON because the admission queue IS files: an
operator (or another service) drops ``tools/fleet.py submit`` records into
``<fleet_dir>/queue/`` and the scheduler admits them on its next tick.
Validation is strict at both ends — a malformed spec must bounce at submit
time (or be rejected with a ``fleet_reject`` event at admit time), never
wedge the decision loop.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Dict, Optional, Sequence, Tuple

__all__ = ["JobSpec", "SpecError", "JOB_ID_RE"]

#: job ids double as file-name prefixes (``job.<id>.json``,
#: ``<id>.metrics.prom``) — keep them path- and label-safe
JOB_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class SpecError(ValueError):
    """A job spec that must not enter the admission queue."""


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One job's contract with the fleet.

    ``priority`` orders admission and preemption: a higher-priority arrival
    may shrink (elastic jobs, down to ``min_world``) or evict (via the
    harness's preempt path) strictly lower-priority jobs to fit.  Ties
    never preempt each other on priority alone — arrival order breaks them,
    latest admitted evicted first.

    ``target_updates`` is the job's completion horizon in APPLIED updates
    (the same counter the step guard and control plane key on); None means
    "runs until its command exits 0".  ``checkpoint_dir`` names where the
    eviction-time emergency save lands and where a re-placed job resumes
    from — a job without one is still schedulable, but eviction loses its
    progress since the operator's own last save.
    """

    job_id: str
    command: Tuple[str, ...]
    priority: int = 0
    min_world: int = 1
    max_world: int = 1
    target_updates: Optional[int] = None
    checkpoint_dir: Optional[str] = None

    def __post_init__(self):
        if not JOB_ID_RE.match(self.job_id or ""):
            raise SpecError(
                f"job_id {self.job_id!r} must match {JOB_ID_RE.pattern} "
                "(it prefixes heartbeat/prom/event file names)")
        object.__setattr__(self, "command", tuple(str(c) for c in self.command))
        if not self.command:
            raise SpecError(f"job {self.job_id}: empty command")
        if not (1 <= int(self.min_world) <= int(self.max_world)):
            raise SpecError(
                f"job {self.job_id}: need 1 <= min_world <= max_world, got "
                f"[{self.min_world}, {self.max_world}]")
        if self.target_updates is not None and int(self.target_updates) < 1:
            raise SpecError(
                f"job {self.job_id}: target_updates must be >= 1 or None")

    @property
    def elastic(self) -> bool:
        """True when the world range is a real range — the job can absorb a
        shrink (and later a readmit/grow) instead of an eviction."""
        return int(self.min_world) < int(self.max_world)

    def to_json(self) -> Dict[str, Any]:
        rec = dataclasses.asdict(self)
        rec["command"] = list(self.command)
        return rec

    @classmethod
    def from_json(cls, rec: Dict[str, Any]) -> "JobSpec":
        if not isinstance(rec, dict):
            raise SpecError(f"job spec must be a JSON object, got {type(rec)}")
        unknown = set(rec) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise SpecError(f"unknown job-spec fields: {sorted(unknown)}")
        command = rec.get("command") or ()
        if isinstance(command, str) or not isinstance(command, Sequence):
            raise SpecError("command must be a list of argv strings")
        kw = dict(rec)
        kw["command"] = tuple(command)
        for field in ("priority", "min_world", "max_world"):
            if field in kw:
                kw[field] = int(kw[field])
        if kw.get("target_updates") is not None:
            kw["target_updates"] = int(kw["target_updates"])
        return cls(**kw)

    @classmethod
    def parse(cls, text: str) -> "JobSpec":
        """Parse a JSON document (the ``tools/fleet.py submit`` payload)."""
        try:
            rec = json.loads(text)
        except ValueError as e:
            raise SpecError(f"job spec is not valid JSON: {e}") from e
        return cls.from_json(rec)
