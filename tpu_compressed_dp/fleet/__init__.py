"""Fleet control plane: many jobs, one device pool.

Turns the single-job resilience primitives (watchdog supervision,
SIGTERM -> emergency save -> exit 75 preemption, elastic shrink/readmit,
heartbeat verdicts) into scheduling primitives: an admission queue of JSON
job specs over a shared device pool, priority preemption via
shrink-before-evict, bin-packing freed slices back into waiting or
shrunken jobs, and per-job Prometheus/JSONL observability.

  * :mod:`~tpu_compressed_dp.fleet.spec` — :class:`JobSpec` (the queue
    currency), strict validation, JSON round-trip.
  * :mod:`~tpu_compressed_dp.fleet.state` — the shared-dir file protocol
    (atomic tmp+``os.replace`` writes, tolerant reads).
  * :mod:`~tpu_compressed_dp.fleet.placement` — the pure planner
    (:func:`plan`) and the :class:`DevicePool` slice allocator.
  * :mod:`~tpu_compressed_dp.fleet.scheduler` — :class:`FleetScheduler`,
    the tick loop driving a :class:`JobController` (subprocess controller
    in ``tools/fleet.py``; in-process elastic controller in the chaos
    drill).
"""

from tpu_compressed_dp.fleet.placement import (DevicePool, Evict, Grow,
                                               Place, Shrink, Slot, Waiting,
                                               plan)
from tpu_compressed_dp.fleet.scheduler import FleetScheduler, JobController
from tpu_compressed_dp.fleet.spec import JobSpec, SpecError

__all__ = [
    "JobSpec", "SpecError",
    "Slot", "Waiting", "Shrink", "Evict", "Place", "Grow", "plan",
    "DevicePool", "FleetScheduler", "JobController",
]
