"""StreamReader: tail-and-apply consumer for the delta segment stream.

Used by ``tools/stream_serve.py`` (model push to eval/serving replicas)
and by :func:`tpu_compressed_dp.stream.rejoin.warm_rejoin` (a joiner
catching up at the rendezvous barrier).  Reconstruction is pure
set-semantics apply, so after any keyframe or window-closing flush the
reader's vector is bitwise equal to the writer's params at that segment.

Corruption policy (the ``stream_corrupt`` chaos drill pins both arms):

* a delta that fails verification WALKS BACK — the reconstruction
  reverts to the stored copy of the current keyframe (bitwise) and the
  reader skips forward to the next verifiable keyframe, which re-anchors
  it exactly;
* a stream with NO verifiable keyframe to anchor on raises
  :class:`~tpu_compressed_dp.stream.store.StreamCorrupt` — the caller
  falls back to a full (Orbax) restore.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from tpu_compressed_dp.stream import delta as dcodec
from tpu_compressed_dp.stream import store

__all__ = ["StreamReader"]


class StreamReader:
    """Incremental consumer over one stream directory.

    ``catch_up()`` scans committed segments past the last scanned seq and
    applies them; call it in a poll loop (serving) or once (rejoin).
    ``exact`` is True when the reconstruction is pinned bitwise to the
    writer's params at the stream head — last applied segment was a
    keyframe or window-closing flush AND nothing newer is committed.
    """

    def __init__(self, directory: str, *, log=print,
                 now=time.monotonic, wall=time.time):
        self.directory = directory
        self._log = log
        self._now = now
        self._wall = wall
        self._vec: Optional[np.ndarray] = None
        self._spec: Optional[List[Dict[str, Any]]] = None
        self._keyframe_vec: Optional[np.ndarray] = None
        self._keyframe_seq = -1
        self._keyframe_step = -1
        self._await_keyframe = False
        self._anchored = False
        self._scanned_seq = -1    # newest seq examined (advances monotonically)
        self._applied_seq = -1    # newest seq reflected in the reconstruction
        self._applied_step = -1
        self._last_ts = 0.0
        self.bytes_read = 0
        self.segments_applied = 0
        self.corrupt_segments = 0

    # --------------------------------------------------------------- tailing

    def catch_up(self) -> int:
        """Apply every committed segment newer than the last scanned one;
        returns the number applied.  The FIRST catch-up of a fresh reader
        seeks to the newest verifiable keyframe and starts there — older
        segments are dead history and are never read.  Raises
        ``StreamCorrupt`` only when the stream leaves NOTHING to anchor
        on (see module docstring)."""
        applied = 0
        seqs = store.list_segments(self.directory)
        if self._scanned_seq < 0 and self._vec is None and seqs:
            anchor = self._seek_anchor(seqs)
            if anchor is not None:
                self._scanned_seq = anchor - 1
        for seq in seqs:
            if seq <= self._scanned_seq:
                continue
            self._scanned_seq = seq
            man = store.read_segment_manifest(self.directory, seq)
            kind = None if man is None else man.get("kind")
            if self._await_keyframe and kind == "delta":
                continue  # skipping forward to the next anchor
            try:
                man, arrays = store.load_segment(self.directory, seq)
            except store.StreamCorrupt as e:
                self.corrupt_segments += 1
                self._walk_back(seq, e)
                continue
            if man["kind"] == "keyframe":
                self._apply_keyframe(man, arrays)
            else:
                if self._vec is None:
                    # deltas before any keyframe we hold: nothing to apply
                    # them to — keep waiting for an anchor
                    self._await_keyframe = True
                    continue
                self._apply_delta(man, arrays)
            applied += 1
            self.segments_applied += 1
            self.bytes_read += int(man.get("bytes", 0))
            self._applied_seq = seq
            self._applied_step = int(man["step"])
            self._last_ts = float(man.get("ts", 0.0))
        if self._vec is None and self._scanned_seq >= 0:
            # segments exist but none anchors: nothing trustworthy to serve
            raise store.StreamCorrupt(
                f"no verifiable keyframe in {self.directory!r} "
                f"(scanned through seq {self._scanned_seq})")
        return applied

    def _seek_anchor(self, seqs: List[int]) -> Optional[int]:
        """A FRESH consumer (rejoin, relaunched server) needs nothing
        before the newest verifiable keyframe — every segment older than
        that anchor is dead history, so skip it unread rather than
        replaying the whole stream.  Returns the seq to start from, or
        None when no keyframe verifies (the forward scan then reports
        corruption exactly as before).  On a pruned stream this is a
        near-no-op; on an unpruned one it caps rejoin cost at one window."""
        for seq in reversed(seqs):
            man = store.read_segment_manifest(self.directory, seq)
            if man is None or man.get("kind") != "keyframe":
                continue
            if not store.verify_segment(self.directory, seq):
                return seq
        return None

    def _apply_keyframe(self, man: Dict[str, Any],
                        arrays: Dict[str, np.ndarray]) -> None:
        vec = arrays["vals"].astype(np.float32, copy=True)
        self._vec = vec
        self._keyframe_vec = vec.copy()
        self._keyframe_seq = int(man["seq"])
        self._keyframe_step = int(man["step"])
        if man.get("spec") is not None:
            self._spec = man["spec"]
        self._await_keyframe = False
        self._anchored = True

    def _apply_delta(self, man: Dict[str, Any],
                     arrays: Dict[str, np.ndarray]) -> None:
        dcodec.apply_delta(self._vec, arrays["idx"],
                           arrays["vals"].astype(np.float32, copy=False))
        self._anchored = bool(man.get("window_close"))

    def _walk_back(self, seq: int, err: BaseException) -> None:
        """A corrupt segment mid-stream: revert to the keyframe copy
        (bitwise) and re-anchor at the next verifiable keyframe."""
        self._log(f"[stream] segment {seq} corrupt ({err}); walking back "
                  f"to keyframe seq {self._keyframe_seq}")
        if self._keyframe_vec is not None:
            self._vec = self._keyframe_vec.copy()
            self._applied_seq = self._keyframe_seq
            self._applied_step = self._keyframe_step
            self._anchored = True
        self._await_keyframe = True

    # --------------------------------------------------------------- surface

    @property
    def applied_seq(self) -> int:
        return self._applied_seq

    @property
    def applied_step(self) -> int:
        return self._applied_step

    @property
    def spec(self) -> Optional[List[Dict[str, Any]]]:
        return self._spec

    @property
    def exact(self) -> bool:
        """Reconstruction is bitwise the writer's params at the head: the
        last applied segment closes a window (or IS a keyframe) and no
        newer segment is committed."""
        if not self._anchored or self._vec is None:
            return False
        head = store.read_head(self.directory)
        if head is not None:
            return int(head["seq"]) <= self._applied_seq
        # no/torn head pointer: it is also what a mid-rewrite or damaged
        # stream looks like, so claim exactness only against the committed
        # segments actually on disk — never by default
        seqs = store.list_segments(self.directory)
        return bool(seqs) and seqs[-1] <= self._applied_seq

    def params_like(self, template_params):
        """The reconstruction as a pytree with the TEMPLATE's structure
        (spec-checked — see :func:`stream.delta.unflatten_like`)."""
        if self._vec is None or self._spec is None:
            raise store.StreamCorrupt(
                f"nothing reconstructed yet from {self.directory!r}")
        return dcodec.unflatten_like(template_params, self._vec, self._spec)

    def params_dict(self) -> Dict[str, np.ndarray]:
        """Template-free ``{leaf path: array}`` view (serving consumers)."""
        if self._vec is None or self._spec is None:
            raise store.StreamCorrupt(
                f"nothing reconstructed yet from {self.directory!r}")
        return dcodec.unflatten_dict(self._vec, self._spec)

    def metrics(self) -> Dict[str, float]:
        """Host-emitter gauges; keys declared in ``obs/registry.py``."""
        lag = (self._wall() - self._last_ts) if self._last_ts else -1.0
        return {
            "stream/lag_s": max(lag, 0.0) if self._last_ts else -1.0,
            "stream/corrupt_segments": float(self.corrupt_segments),
            "stream/last_step": float(self._applied_step),
        }
