"""Warm rejoin: adopt params from the delta stream instead of Orbax.

A joiner at the rendezvous barrier normally restores the FULL checkpoint
before joining.  When a stream directory is live, ``warm_rejoin`` tails
it with a :class:`~tpu_compressed_dp.stream.reader.StreamReader` and
substitutes the reconstruction into the joiner's state — moving only
keyframe + deltas over the shared dir instead of the whole Orbax tree.

Correctness leans on the survivors' side of the protocol: the
coordinator calls :meth:`StreamWriter.sync` at the rejoin barrier (a
window-closing flush), so by the time the joiner's catch-up runs, the
stream head reconstructs to the live params *bitwise* and the barrier's
params broadcast can be skipped entirely.

Any corruption or missing anchor returns ``None`` — the caller falls
back to the full restore path, never a half-adopted state.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from tpu_compressed_dp.stream.reader import StreamReader
from tpu_compressed_dp.stream.store import StreamCorrupt, is_stream_dir

__all__ = ["warm_rejoin"]


def warm_rejoin(state, stream_dir: str, *, log=print, flight=None
                ) -> Tuple[Any, Optional[Dict[str, Any]]]:
    """Catch ``state.params`` up from the segment stream.

    Returns ``(state, info)`` — ``info`` is None when the stream is
    absent, corrupt, or not anchored (callers fall back to full
    restore), else ``{"bytes", "segments", "step", "seq", "exact"}``
    for the rejoin accounting (BENCH rejoin-bytes rows, flight ring).
    """
    if not is_stream_dir(stream_dir):
        return state, None
    reader = StreamReader(stream_dir, log=log)
    try:
        reader.catch_up()
        params = reader.params_like(state.params)
    except (StreamCorrupt, ValueError) as e:
        log(f"[stream] warm rejoin unavailable ({e}); "
            f"falling back to full restore")
        if flight is not None:
            try:
                flight.record("stream", "warm_rejoin_fallback", error=repr(e))
            except Exception:
                pass
        return state, None
    info = {
        "bytes": int(reader.bytes_read),
        "segments": int(reader.segments_applied),
        "step": int(reader.applied_step),
        "seq": int(reader.applied_seq),
        "exact": bool(reader.exact),
    }
    if flight is not None:
        try:
            flight.record("stream", "warm_rejoin", **info)
        except Exception:
            pass
    log(f"[stream] warm rejoin: adopted step {info['step']} from "
        f"{info['segments']} segments ({info['bytes']} bytes, "
        f"exact={info['exact']})")
    return dataclasses.replace(state, params=params), info
