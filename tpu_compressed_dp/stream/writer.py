"""StreamWriter: continuous incremental checkpoints on the delta wire.

Mirrors the Checkpointer's concurrency contract (one writer owns a
directory; ``append_async`` hands the disk write to a background thread
and re-raises its failure at the next barrier) but writes *segments*:
a full keyframe every ``keyframe_every`` appends, Top-K drift deltas
between, and a window-closing flush (every bitwise-changed coordinate)
as the last delta of each window — so ``keyframe + sum(deltas)``
reproduces the live params exactly in fp32 at every window boundary.

The codec (selection, ``last_streamed`` update, window accounting) runs
on the CALLER's thread — segment content is a pure function of the
append sequence, independent of writer-thread timing (TCDP101); only
the ``write_segment`` commit goes to the background thread.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from tpu_compressed_dp.stream import delta as dcodec
from tpu_compressed_dp.stream import store

__all__ = ["StreamWriter"]


class StreamWriter:
    """Appends delta-compressed state segments to a shared directory.

    ``ratio`` is the Top-K density per delta (fraction of model
    coordinates); ``keyframe_every`` is the window length in segments
    (one keyframe, ``keyframe_every - 2`` Top-K deltas, one flush).
    On restart over an existing stream the sequence continues past the
    newest committed segment (manifest or head pointer, whichever is
    newer — a crash can commit a manifest the head never saw) and the
    first append is forced to a keyframe — the new writer has no
    ``last_streamed`` to delta against.

    Set ``.flight`` / ``.events`` (or pass them) the way the
    Checkpointer's are set to tee keyframe/flush lifecycle into the
    ``stream`` flight ring and the ``--events`` stream.
    """

    def __init__(self, directory: str, *, ratio: float = 0.01,
                 keyframe_every: int = 8, flight=None, events=None,
                 log=print, now=time.monotonic, wall=time.time):
        if keyframe_every < 2:
            raise ValueError(
                f"keyframe_every must be >= 2 (a keyframe and its flush), "
                f"got {keyframe_every}")
        self.directory = os.path.abspath(directory)
        self.ratio = float(ratio)
        self.keyframe_every = int(keyframe_every)
        self.flight = flight
        self.events = events
        self._log = log
        self._now = now
        self._wall = wall
        #: last background commit failure popped by a non-raising barrier
        self.last_append_error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._bg_error: Optional[BaseException] = None
        self._op = threading.RLock()   # serialises codec + commit ordering
        self._mx = threading.Lock()    # guards the metric counters
        self._last: Optional[np.ndarray] = None   # last_streamed vector
        self._spec: Optional[List[Dict[str, Any]]] = None
        self._since_keyframe = 0
        self._keyframe_seq = -1
        self._force_keyframe = False
        # continue past the newest COMMITTED segment, not just the head
        # pointer: write_segment commits payload -> manifest -> head, so a
        # crash between the manifest and head replaces leaves a committed
        # segment at head.seq+1 that a head-only restart would silently
        # overwrite — and a tailing reader that already scanned that seq
        # would skip the replacement keyframe and delta off a wrong base
        head = store.read_head(self.directory)
        seqs = store.list_segments(self.directory)
        last = max(int(head["seq"]) if head is not None else -1,
                   seqs[-1] if seqs else -1)
        if last >= 0:
            # continue the on-disk sequence; the first append must anchor
            self._seq = last + 1
            self._force_keyframe = True
        else:
            self._seq = 0
        self._segments = 0
        self._keyframes = 0
        self._bytes = 0
        self._keyframe_bytes = 0
        self._append_ms = 0.0
        self._residual_norm = 0.0
        self._last_step: Optional[int] = None
        self._mark_wall = wall()      # newest commit (or open) wall time

    # ---------------------------------------------------------------- append

    def append(self, params, *, step: int) -> int:
        """Synchronous append: barrier on any in-flight async commit, run
        the codec, and block until the segment is durable.  Returns the
        committed segment seq."""
        self._barrier(raise_error=True)
        seq, kind, man_args = self._encode(params, step=int(step))
        t0 = self._now()
        self._commit(seq, kind, man_args)
        self._committed(seq, kind, int(step),
                        (self._now() - t0) * 1e3, man_args)
        return seq

    def append_async(self, params, *, step: int) -> int:
        """Codec on the caller's thread (so ``last_streamed`` and the
        window accounting stay ordered), disk commit in the background.
        A background failure re-raises at the next barrier and forces the
        following append to a keyframe — the stream must re-anchor past
        the hole."""
        self._barrier(raise_error=True)
        seq, kind, man_args = self._encode(params, step=int(step))

        def _bg():
            t0 = self._now()
            try:
                self._commit(seq, kind, man_args)
            except BaseException as e:  # surfaced at the next barrier
                with self._mx:
                    self._bg_error = e
            else:
                self._committed(seq, kind, int(step),
                                (self._now() - t0) * 1e3, man_args)

        self._thread = threading.Thread(
            target=_bg, name=f"stream-append-{seq}", daemon=True)
        self._thread.start()
        return seq

    def sync(self, params, *, step: int) -> int:
        """Barrier + window-closing flush: after this returns, the stream
        head reconstructs to ``params`` bitwise (fp32).  This is the
        rejoin-barrier primitive — survivors call it so a joiner catching
        up from the stream adopts exactly the live state."""
        self._barrier(raise_error=True)
        seq, kind, man_args = self._encode(params, step=int(step),
                                           force_flush=True)
        t0 = self._now()
        self._commit(seq, kind, man_args)
        self._committed(seq, kind, int(step),
                        (self._now() - t0) * 1e3, man_args)
        return seq

    def request_keyframe(self) -> None:
        """Force the next append to emit a full keyframe (membership
        changes, post-failure re-anchoring)."""
        with self._op:
            self._force_keyframe = True

    # ----------------------------------------------------------------- codec

    def _encode(self, params, *, step: int, force_flush: bool = False):
        """Flatten + select on the caller's thread; returns the
        ``write_segment`` arguments for the commit seam."""
        with self._op:
            vec, spec = dcodec.flatten_params(params)
            respec = (self._spec is not None and spec != self._spec)
            keyframe = (self._last is None or respec or self._force_keyframe
                        or self._since_keyframe == 0)
            seq = self._seq
            self._seq += 1
            if keyframe:
                self._force_keyframe = False
                self._keyframe_seq = seq
                self._since_keyframe = 1
                self._spec = spec
                self._last = vec.copy()
                self._residual_norm = 0.0
                return seq, "keyframe", dict(
                    step=step, keyframe_seq=seq, window_close=True,
                    arrays={"vals": vec}, spec=spec, ts=self._wall())
            window_close = (force_flush
                            or self._since_keyframe >= self.keyframe_every - 1)
            if window_close:
                idx, vals = dcodec.flush_delta(vec, self._last)
                self._since_keyframe = 0      # next append re-anchors
            else:
                keep = dcodec.keep_for_ratio(vec.shape[0], self.ratio)
                idx, vals = dcodec.topk_delta(vec, self._last, keep)
                self._since_keyframe += 1
            dcodec.apply_delta(self._last, idx, vals)
            self._residual_norm = float(
                np.linalg.norm(dcodec.residual_of(vec, self._last)))
            return seq, "delta", dict(
                step=step, keyframe_seq=self._keyframe_seq,
                window_close=window_close,
                arrays={"idx": idx, "vals": vals}, ts=self._wall())

    def _commit(self, seq: int, kind: str, man_args: Dict[str, Any]) -> None:
        """The blocking commit seam for ONE segment (payload + digest +
        manifest + head, each atomic).  Tests inject failures here."""
        spec = man_args.pop("spec", None)
        if spec is not None:
            man_args["spec"] = [dict(e) for e in spec]
        store.write_segment(self.directory, seq=seq, kind=kind, **man_args)

    def _committed(self, seq: int, kind: str, step: int, ms: float,
                   man_args: Dict[str, Any]) -> None:
        nbytes = sum(int(a.nbytes) for a in man_args["arrays"].values())
        with self._mx:
            self._segments += 1
            self._bytes += nbytes
            if kind == "keyframe":
                self._keyframes += 1
                self._keyframe_bytes += nbytes
            self._append_ms = ms
            self._last_step = step
            self._mark_wall = self._wall()
        if kind == "keyframe" or man_args.get("window_close"):
            self._emit("stream_keyframe" if kind == "keyframe"
                       else "stream_flush",
                       seq=seq, step=step, bytes=nbytes, ms=round(ms, 3))

    # ------------------------------------------------------------- barriers

    def _barrier(self, *, raise_error: bool) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        err, self._bg_error = self._bg_error, None
        if err is not None:
            self.last_append_error = err
            # a lost commit leaves a hole: re-anchor past it
            with self._op:
                self._force_keyframe = True
            if raise_error:
                raise err

    def drain(self, *, raise_error: bool = True) -> None:
        """Block until any in-flight async commit lands; with
        ``raise_error=False`` (shutdown paths) a background failure is
        recorded in ``last_append_error`` instead of raised."""
        self._barrier(raise_error=raise_error)

    def close(self) -> None:
        """Drain without raising — close runs in ``finally`` blocks."""
        self._barrier(raise_error=False)

    # --------------------------------------------------------------- surface

    @property
    def head_seq(self) -> int:
        """Seq of the newest ENCODED segment (the rendezvous join record's
        ``stream`` field) — -1 before the first append."""
        with self._op:
            return self._seq - 1

    @property
    def spec(self) -> Optional[List[Dict[str, Any]]]:
        with self._op:
            return None if self._spec is None else [dict(e)
                                                    for e in self._spec]

    def metrics(self) -> Dict[str, float]:
        """Host-emitter counters/gauges; keys declared in
        ``obs/registry.py``."""
        with self._mx:
            return {
                "stream/segments": float(self._segments),
                "stream/keyframes": float(self._keyframes),
                "stream/bytes": float(self._bytes),
                "stream/keyframe_bytes": float(self._keyframe_bytes),
                "stream/append_ms": self._append_ms,
                "stream/residual_norm": self._residual_norm,
                "stream/last_step": float(
                    -1 if self._last_step is None else self._last_step),
            }

    def heartbeat_fields(self) -> Dict[str, float]:
        """The fields the watchdog's ``--max_stream_lag`` check reads out
        of the heartbeat payload."""
        with self._mx:
            return {
                "stream_last_step": int(
                    -1 if self._last_step is None else self._last_step),
                "stream_lag_s": max(self._wall() - self._mark_wall, 0.0),
            }

    def _emit(self, kind: str, **fields) -> None:
        fl = self.flight
        if fl is not None:
            try:
                fl.record("stream", kind, **fields)
            except Exception:
                pass  # telemetry must never fail an append
        ev = self.events
        if ev is None:
            return
        try:
            ev.emit(kind, **fields)
        except Exception:
            pass  # telemetry must never fail an append
