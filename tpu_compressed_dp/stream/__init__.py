"""Delta-compressed state streaming: incremental checkpoints, warm rejoin,
and a model-push channel over the compressed wire.

The gradient path moves 10-300x compressed payloads, but until this
subsystem every state movement — Orbax saves, elastic rejoin adoption,
any serving replica — shipped FULL parameters.  ``stream/`` reuses the
wire compressors (:func:`tpu_compressed_dp.ops.wire.select_pack_topk`)
on **parameter deltas**: each window emits Top-K of
``params - last_streamed`` with an EF-style host residual ("Sparsified
SGD with Memory", arxiv 1809.07599), a window-closing flush makes the
stream lossless — ``keyframe + sum(deltas) == params`` *bitwise* in fp32
— and periodic full keyframes bound recovery depth.  Every segment is
manifest-checksummed like the PR 8 checkpoints, so corruption is
detectable offline (``tools/ckpt_fsck.py``) and at apply time.

Three consumers ride the same segment stream:

  * **incremental checkpoints** — :class:`StreamWriter` appends segments
    continuously (async, like the Checkpointer's background writer);
  * **warm rejoin** — a joiner at the rendezvous barrier adopts params
    from the stream (:func:`warm_rejoin`) instead of a full Orbax
    restore, and the survivors' barrier flush (:meth:`StreamWriter.sync`)
    pins the adopted state bitwise to the live params;
  * **model push** — ``tools/stream_serve.py`` tails the shared dir with
    a :class:`StreamReader` and applies segments onto read-only
    eval/serving replicas.

House rules: every module here is replay-deterministic (TCDP101 —
injectable ``now``/``wall`` clocks only) and every shared-dir commit is
``<path>.<pid>.tmp`` + ``os.replace`` (TCDP102); all ``stream/*`` stat
keys are declared in :mod:`tpu_compressed_dp.obs.registry`.
"""

from tpu_compressed_dp.stream.delta import (apply_delta, flatten_params,
                                            flush_delta, keep_for_ratio,
                                            residual_of, topk_delta,
                                            unflatten_dict, unflatten_like)
from tpu_compressed_dp.stream.reader import StreamReader
from tpu_compressed_dp.stream.rejoin import warm_rejoin
from tpu_compressed_dp.stream.store import (STREAM_SCHEMA, StreamCorrupt,
                                            head_path, is_stream_dir,
                                            list_segments, load_segment,
                                            prune_segments, read_head,
                                            read_segment_manifest,
                                            segment_manifest_path,
                                            segment_payload_path,
                                            verify_segment, verify_stream,
                                            write_segment)
from tpu_compressed_dp.stream.writer import StreamWriter

__all__ = [
    "STREAM_SCHEMA", "StreamCorrupt", "StreamWriter", "StreamReader",
    "warm_rejoin", "write_segment", "read_head", "head_path",
    "is_stream_dir", "list_segments", "load_segment",
    "read_segment_manifest", "segment_payload_path",
    "segment_manifest_path", "verify_segment", "verify_stream",
    "prune_segments", "flatten_params", "unflatten_like", "unflatten_dict",
    "topk_delta", "flush_delta", "apply_delta", "keep_for_ratio",
    "residual_of",
]
