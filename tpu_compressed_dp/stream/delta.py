"""Delta codec: Top-K of parameter drift on the wire select+pack, with
set-semantics payloads so reconstruction is *bitwise*.

The trick that makes the stream lossless without fp-summation hazards:
deltas select coordinates by drift magnitude ``|params - last_streamed|``
(through :func:`tpu_compressed_dp.ops.wire.select_pack_topk` — the same
threshold + select + pack chain the gradient wire runs, Pallas-fused
when dispatched) but transmit the CURRENT VALUES at those coordinates,
and apply by assignment, never addition.  Setting a float is exact in
any dtype, so ``last_streamed[idx] = params[idx]`` holds bitwise, the
host residual ``params - last_streamed`` is exactly zero at every
transmitted coordinate, and a window-closing flush (every coordinate
whose BITS differ) makes ``keyframe + sum(deltas) == params`` exact in
fp32 — the EF-style bounded-residual story of "Sparsified SGD with
Memory" (arxiv 1809.07599) with equality instead of a bound at window
boundaries.

Pure functions over host numpy (plus the jitted wire packer); no I/O,
no clocks — replay-deterministic by construction (TCDP101).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Tuple

import numpy as np

__all__ = [
    "flatten_params", "unflatten_like", "unflatten_dict", "keep_for_ratio",
    "topk_delta", "flush_delta", "apply_delta", "residual_of",
]


def _leaf_paths(params) -> List[str]:
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    return [jax.tree_util.keystr(path) for path, _ in leaves]


def flatten_params(params) -> Tuple[np.ndarray, List[Dict[str, Any]]]:
    """Host-flatten a params pytree to one fp32 vector plus its spec
    (per-leaf path / shape / dtype, in traversal order).  fp32 and
    narrower float leaves (bf16, fp16) round-trip bitwise through the
    fp32 cast; the pinned lossless-window invariant is stated in fp32."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(jax.device_get(params))[0]
    spec = []
    chunks = []
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        spec.append({"path": jax.tree_util.keystr(path),
                     "shape": list(arr.shape), "dtype": str(arr.dtype)})
        chunks.append(arr.astype(np.float32, copy=False).reshape(-1))
    if not chunks:
        return np.zeros((0,), np.float32), spec
    return np.concatenate(chunks), spec


def unflatten_like(template_params, vec: np.ndarray,
                   spec: List[Dict[str, Any]]):
    """Rebuild a params pytree with the TEMPLATE's structure from a flat
    vector, checking the stream's spec against the template leaf-for-leaf
    (path and shape) — a stream from a different model must fail loudly,
    not scatter into the wrong coordinates."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten_with_path(
        jax.device_get(template_params))
    if len(leaves) != len(spec):
        raise ValueError(
            f"stream spec has {len(spec)} leaves, template has "
            f"{len(leaves)}")
    out, off = [], 0
    for (path, leaf), ent in zip(leaves, spec):
        arr = np.asarray(leaf)
        key = jax.tree_util.keystr(path)
        if key != ent["path"] or list(arr.shape) != list(ent["shape"]):
            raise ValueError(
                f"stream spec mismatch at {key}: stream has "
                f"{ent['path']} {ent['shape']}, template {list(arr.shape)}")
        n = arr.size
        out.append(vec[off:off + n].astype(arr.dtype).reshape(arr.shape))
        off += n
    if off != vec.shape[0]:
        raise ValueError(f"flat vector has {vec.shape[0]} elements, "
                         f"template consumes {off}")
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template_params), out)


def unflatten_dict(vec: np.ndarray, spec: List[Dict[str, Any]]
                   ) -> Dict[str, np.ndarray]:
    """Template-free view for serving consumers: ``{leaf path: array}``
    in the stream's own dtypes (``tools/stream_serve.py`` snapshots)."""
    out, off = {}, 0
    for ent in spec:
        n = int(np.prod(ent["shape"])) if ent["shape"] else 1
        out[ent["path"]] = (vec[off:off + n]
                            .astype(np.dtype(ent["dtype"]))
                            .reshape(ent["shape"]))
        off += n
    return out


def keep_for_ratio(n: int, ratio: float) -> int:
    """Coordinates per Top-K delta for an ``n``-element model."""
    return max(1, min(int(n), int(round(float(ratio) * int(n)))))


def _idx_dtype(n: int) -> np.dtype:
    """int32 indices halve delta payload cost (8 B/coord with fp32 vals
    instead of 12); int64 only past 2**31 coordinates per host vector."""
    return np.int32 if n <= np.iinfo(np.int32).max else np.int64


def _changed(vec: np.ndarray, last: np.ndarray) -> np.ndarray:
    """Indices whose BITS differ — value equality would miss -0.0 vs 0.0
    and treat NaN as always-changed; the lossless invariant is bitwise."""
    return np.flatnonzero(vec.view(np.int32) != last.view(np.int32))


@functools.lru_cache(maxsize=16)
def _packer(n: int, keep: int):
    import jax

    from tpu_compressed_dp.ops import wire

    return jax.jit(functools.partial(wire.select_pack_topk, keep=keep))


def topk_delta(vec: np.ndarray, last: np.ndarray, keep: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Top-``keep``-drift coordinates of ``vec`` vs ``last`` as a
    ``(idx ascending, current vals fp32)`` set-semantics pair — idx in
    the narrowest dtype that addresses the vector (see ``_idx_dtype``).

    Selection runs on the wire compress chain (threshold + select+pack);
    when the bitwise-changed set already fits in ``keep`` the delta is
    exact and the window converges early."""
    dt = _idx_dtype(vec.shape[0])
    changed = _changed(vec, last)
    if changed.shape[0] <= keep:
        return changed.astype(dt), vec[changed]
    payload, idx, count = _packer(vec.shape[0], keep)(vec - last)
    del payload  # drift magnitudes selected; the VALUES are what we send
    k = min(int(count), keep)
    idx = np.asarray(idx)[:k].astype(dt)
    return idx, vec[idx]


def flush_delta(vec: np.ndarray, last: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
    """The window-closing delta: EVERY bitwise-changed coordinate, so
    applying it drives the consumer's reconstruction (and the writer's
    ``last_streamed``) to ``vec`` exactly."""
    idx = _changed(vec, last).astype(_idx_dtype(vec.shape[0]))
    return idx, vec[idx]


def apply_delta(recon: np.ndarray, idx: np.ndarray, vals: np.ndarray
                ) -> np.ndarray:
    """In-place set-semantics apply; returns ``recon``."""
    recon[idx] = vals
    return recon


def residual_of(vec: np.ndarray, last: np.ndarray) -> np.ndarray:
    """The EF-style host residual: drift not yet transmitted.  Exactly
    zero at every transmitted coordinate (set semantics), and bitwise
    equal to the cumulative drift at untransmitted ones."""
    return vec - last
