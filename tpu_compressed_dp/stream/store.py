"""Segment store: manifest-checksummed delta/keyframe files on a shared dir.

One monotone segment sequence per stream directory::

    seg-00000000.npz    payload (numpy archive: vals [+ idx for deltas])
    seg-00000000.json   manifest: schema, kind, step, sha256, byte count
    stream.json         head pointer (latest committed seq), atomic

The manifest is the commit marker, exactly like the Checkpointer's
``manifest-<step>.json`` (PR 8): payload first, digest, then manifest,
then the head pointer — each via ``<path>.<pid>.tmp`` + ``os.replace``
(TCDP102), so a tailing consumer never sees a torn segment and a
bit-flipped payload is *detectable* (``verify_segment`` /
``tools/ckpt_fsck.py``) rather than silently applied.

Pure host-side file I/O on numpy — no JAX, no Orbax — so
``tools/ckpt_fsck.py`` and ``tools/stream_serve.py`` stay importable
anywhere the checkpoint fsck already runs.
"""

from __future__ import annotations

import io
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tpu_compressed_dp.utils.checkpoint import digest_file

__all__ = [
    "STREAM_SCHEMA", "StreamCorrupt", "head_path", "segment_payload_path",
    "segment_manifest_path", "write_segment", "read_head", "list_segments",
    "read_segment_manifest", "verify_segment", "load_segment",
    "verify_stream", "prune_segments", "is_stream_dir",
]

#: bump on incompatible segment/manifest layout changes; consumers check
#: it before applying (a newer writer must not be silently misread)
STREAM_SCHEMA = 1

#: segment kinds: a ``keyframe`` carries the full dense vector (recovery
#: anchor), a ``delta`` carries ``(idx, vals)`` set-semantics updates; a
#: delta with ``window_close`` carries EVERY bitwise-changed coordinate,
#: making ``keyframe + sum(deltas)`` reproduce the live params exactly.
KINDS = ("keyframe", "delta")


class StreamCorrupt(RuntimeError):
    """A segment failed manifest verification (missing payload, size or
    digest mismatch, torn/unreadable manifest, schema skew)."""


def head_path(directory: str) -> str:
    return os.path.join(directory, "stream.json")


def segment_payload_path(directory: str, seq: int) -> str:
    return os.path.join(directory, f"seg-{int(seq):08d}.npz")


def segment_manifest_path(directory: str, seq: int) -> str:
    return os.path.join(directory, f"seg-{int(seq):08d}.json")


def _write_atomic(path: str, data: bytes) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    """Tolerant read: None for missing/torn content (the rendezvous /
    heartbeat contract — a reader never crashes on in-flight state)."""
    try:
        with open(path, "rb") as f:
            rec = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


def write_segment(directory: str, *, seq: int, kind: str, step: int,
                  keyframe_seq: int, window_close: bool,
                  arrays: Dict[str, np.ndarray],
                  spec: Optional[List[Dict[str, Any]]] = None,
                  meta: Optional[Dict[str, Any]] = None,
                  ts: float = 0.0) -> Dict[str, Any]:
    """Commit one segment: payload, digest, manifest, head — in that
    order, each atomic.  ``ts`` is the writer's injected wall clock
    (informational; consumers compute lag from it)."""
    if kind not in KINDS:
        raise ValueError(f"unknown segment kind {kind!r}; expected {KINDS}")
    os.makedirs(directory, exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = segment_payload_path(directory, seq)
    _write_atomic(payload, buf.getvalue())
    man: Dict[str, Any] = {
        "v": STREAM_SCHEMA, "seq": int(seq), "kind": kind, "step": int(step),
        "keyframe_seq": int(keyframe_seq), "window_close": bool(window_close),
        "payload": os.path.basename(payload),
        "sha256": digest_file(payload),
        "bytes": os.path.getsize(payload),
        "nnz": int(arrays["vals"].shape[0]) if "vals" in arrays else 0,
        "ts": float(ts), "meta": dict(meta or {}),
    }
    if spec is not None:
        man["spec"] = spec
    _write_atomic(segment_manifest_path(directory, seq),
                  json.dumps(man).encode("utf-8"))
    _write_atomic(head_path(directory), json.dumps({
        "v": STREAM_SCHEMA, "seq": int(seq), "step": int(step),
        "keyframe_seq": int(keyframe_seq), "ts": float(ts),
    }).encode("utf-8"))
    return man


def read_head(directory: str) -> Optional[Dict[str, Any]]:
    """The head pointer (latest committed seq), or None before the first
    segment / on a torn read."""
    rec = _read_json(head_path(directory))
    if rec is None or "seq" not in rec:
        return None
    return rec


def read_segment_manifest(directory: str, seq: int) -> Optional[Dict[str, Any]]:
    return _read_json(segment_manifest_path(directory, seq))


def list_segments(directory: str) -> List[int]:
    """Committed segment seqs on disk (by manifest presence), sorted."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        if name.startswith("seg-") and name.endswith(".json"):
            body = name[len("seg-"):-len(".json")]
            if body.isdigit():
                out.append(int(body))
    return sorted(out)


def is_stream_dir(directory: str) -> bool:
    """True when ``directory`` holds a segment stream (head pointer or at
    least one committed segment manifest)."""
    return (os.path.isfile(head_path(directory))
            or bool(list_segments(directory)))


def verify_segment(directory: str, seq: int) -> List[str]:
    """Verify one segment against its manifest; returns problem strings
    (empty = verifiable).  Unlike legacy checkpoints, a stream segment
    without a manifest is ALWAYS a problem — the manifest is the commit
    marker and this layout never shipped without one."""
    man = read_segment_manifest(directory, seq)
    if man is None:
        if os.path.exists(segment_manifest_path(directory, seq)):
            return ["manifest unreadable (torn commit?)"]
        return ["manifest missing"]
    if man.get("v") != STREAM_SCHEMA:
        return [f"manifest schema {man.get('v')!r} != {STREAM_SCHEMA}"]
    if man.get("kind") not in KINDS:
        return [f"unknown segment kind {man.get('kind')!r}"]
    payload = segment_payload_path(directory, seq)
    if not os.path.isfile(payload):
        return [f"missing payload: {os.path.basename(payload)}"]
    if os.path.getsize(payload) != int(man.get("bytes", -1)):
        return [f"size mismatch: {os.path.getsize(payload)} != "
                f"{man.get('bytes')}"]
    if digest_file(payload) != man.get("sha256"):
        return ["digest mismatch"]
    return []


def load_segment(directory: str, seq: int
                 ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Verify then load one segment; raises :class:`StreamCorrupt` on any
    verification problem (callers walk back to the last keyframe)."""
    problems = verify_segment(directory, seq)
    if problems:
        raise StreamCorrupt(
            f"segment {seq} in {directory}: " + "; ".join(problems))
    man = read_segment_manifest(directory, seq)
    with np.load(segment_payload_path(directory, seq)) as z:
        arrays = {k: z[k] for k in z.files}
    return man, arrays


def verify_stream(directory: str) -> Tuple[List[str], List[int]]:
    """fsck surface: verify every committed segment plus the head pointer.
    Returns ``(problems, segment seqs)`` — empty problems = verifiable."""
    seqs = list_segments(directory)
    problems: List[str] = []
    for seq in seqs:
        for pr in verify_segment(directory, seq):
            problems.append(f"segment {seq}: {pr}")
    head = read_head(directory)
    if head is None and os.path.exists(head_path(directory)):
        problems.append("head pointer unreadable (torn commit?)")
    elif head is not None and seqs and int(head["seq"]) not in seqs:
        problems.append(
            f"head points at segment {head['seq']} with no manifest")
    # orphaned payloads: a crash between the payload replace and the
    # manifest commit leaves an .npz no manifest vouches for
    try:
        names = os.listdir(directory)
    except OSError:
        names = []
    for name in sorted(names):
        if name.startswith("seg-") and name.endswith(".npz"):
            body = name[len("seg-"):-len(".npz")]
            if body.isdigit() and int(body) not in seqs:
                problems.append(f"orphaned payload: {name}")
    return problems, seqs


def prune_segments(directory: str, *, keep_windows: int = 2,
                   dry_run: bool = False) -> List[int]:
    """Drop segments older than the ``keep_windows``-newest *verifiable*
    keyframes (a window is everything from one keyframe up to the next).
    Never removes the newest keyframe chain — pruning can only shorten
    history a recovery no longer needs.  Returns the pruned seqs."""
    if keep_windows < 1:
        raise ValueError(f"keep_windows must be >= 1, got {keep_windows}")
    seqs = list_segments(directory)
    keyframes = []
    for seq in seqs:
        man = read_segment_manifest(directory, seq)
        if (man is not None and man.get("kind") == "keyframe"
                and not verify_segment(directory, seq)):
            keyframes.append(seq)
    if len(keyframes) <= keep_windows:
        return []
    cutoff = keyframes[-keep_windows]
    pruned = [s for s in seqs if s < cutoff]
    if not dry_run:
        for seq in pruned:
            for path in (segment_payload_path(directory, seq),
                         segment_manifest_path(directory, seq)):
                try:
                    os.remove(path)
                except OSError:
                    pass
    return pruned
