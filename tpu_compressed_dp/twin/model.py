"""The scale-out digital twin's cost model.

An alpha-beta-gamma model per FABRIC in the methodology of "Near-Optimal
Sparse Allreduce for Distributed Deep Learning" (arXiv 2201.07598): one
collective on fabric ``f`` costs

    T = count * alpha_f  +  beta_f * per_chip_mb  +  gamma_f * hops

where ``alpha`` is the per-dispatch latency (ms), ``beta`` the inverse
bandwidth (ms per per-chip link MB), and ``gamma`` the per-hop cost (ms per
ring round, scaled by how many pod boundaries a round crosses).  A step's
comm time is the sum over the collective schedule its transport actually
emits — the same schedules the engines bill analytically:

  * ``psum``          ring all-reduce: per-chip traffic ``2(W-1)/W x``
                      payload, ``2(W-1)`` rounds per collective
  * ``all_gather``    ``(W-1) x`` payload per chip, ``W-1`` rounds
  * ``all_to_all``    ``(W-1)/W x`` payload per chip, 1 round (the sharded
                      transport's route stage)
  * ``sharded``       route ``all_to_all`` + shard-return ``all_gather``
  * ``hierarchical``  two dense ICI psums over the ``C = W/pods`` intra-pod
                      ring + a DCN ``all_to_all`` route and ``all_gather``
                      return over ``pods`` participants

Fabric billing follows the repo's binding-constraint convention
(:func:`tpu_compressed_dp.utils.meters.per_fabric_traffic_bytes`): flat
whole-world collectives bill to DCN when ``pods > 1`` (the slow fabric
limits a whole-world ring) and to ICI on a flat mesh; only the
hierarchical transport's group collectives bill per fabric directly.

Everything here is a pure function of its arguments — no clocks, no
filesystem — so fits and predictions replay bitwise (hostlint TCDP101).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Collective", "FabricParams", "CostModel", "TwinPoint",
    "UncalibratedFabricError", "flat_fabric", "flat_schedule",
    "hier_schedule", "schedule_for_point", "predict_step_ms",
    "TOPK_BITS_PER_COORD", "DENSE_BITS_PER_ELEM",
]

#: sparse wire format: fp32 value + int32 index per kept coordinate
TOPK_BITS_PER_COORD = 64
#: dense wire format: fp32 per element
DENSE_BITS_PER_ELEM = 32

#: methods whose payload is a (value, index) coordinate list priced at
#: :data:`TOPK_BITS_PER_COORD` — the twin's forward payload model covers
#: these plus 'none'/'dense'; other methods need explicit payload MB
SPARSE_METHODS = ("topk", "blocktopk", "randomk")


class UncalibratedFabricError(ValueError):
    """Raised when a prediction needs a fabric the calibration has zero
    evidence rows for — the twin refuses to extrapolate it."""


@dataclasses.dataclass(frozen=True)
class Collective:
    """One (possibly aggregated) collective on one fabric.

    count:       how many collective dispatches this entry aggregates
    per_chip_mb: total MB through each chip's links across all of them
    hops:        total ring rounds x pod-boundary span across all of them
    """

    fabric: str
    count: float
    per_chip_mb: float
    hops: float


@dataclasses.dataclass(frozen=True)
class FabricParams:
    """Calibrated alpha/beta/gamma for one fabric plus the evidence count
    behind them (``rows == 0`` means the fabric may not be priced)."""

    alpha_ms: float = 0.0
    beta_ms_per_mb: float = 0.0
    gamma_ms_per_hop: float = 0.0
    rows: int = 0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "FabricParams":
        return cls(alpha_ms=float(d["alpha_ms"]),
                   beta_ms_per_mb=float(d["beta_ms_per_mb"]),
                   gamma_ms_per_hop=float(d["gamma_ms_per_hop"]),
                   rows=int(d["rows"]))


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Per-fabric calibrated terms; prices any collective schedule."""

    fabrics: Dict[str, FabricParams]

    def collective_ms(self, c: Collective) -> float:
        p = self.fabrics.get(c.fabric)
        if p is None or p.rows <= 0:
            raise UncalibratedFabricError(
                f"fabric {c.fabric!r} has no calibration rows — the twin "
                f"refuses to extrapolate it (calibrated: "
                f"{sorted(f for f, q in self.fabrics.items() if q.rows)})")
        return (c.count * p.alpha_ms + p.beta_ms_per_mb * c.per_chip_mb
                + p.gamma_ms_per_hop * c.hops)

    def comm_ms(self, schedule: List[Collective],
                hideable_fraction: float = 0.0) -> float:
        """Exposed comm time for a schedule: the summed collective cost
        with the overlap schedule's hideable fraction discounted — bytes
        the ``sync_overlap`` chunk pipeline buries under remaining
        backward compute don't extend the step."""
        total = sum(self.collective_ms(c) for c in schedule)
        hid = min(max(float(hideable_fraction), 0.0), 1.0)
        return total * (1.0 - hid)


def flat_fabric(pods: int) -> str:
    """Which fabric a flat whole-world collective bills to (the
    binding-constraint convention ``per_fabric_traffic_bytes`` prices)."""
    return "dcn" if pods > 1 else "ici"


def flat_schedule(*, world: int, pods: int = 1, count: float = 1.0,
                  psum_mb: float = 0.0, allgather_mb: float = 0.0,
                  alltoall_mb: float = 0.0) -> List[Collective]:
    """Schedule entries for flat whole-world collectives given their
    summed payload MB (the engines' billed buffers).  ``count`` is the
    number of dispatches the payload is spread across (one per reduction
    group); a whole-world round crosses ``pods`` pod boundaries when the
    mesh is 2-level, which is the span factor on hops."""
    w = max(int(world), 1)
    span = max(int(pods), 1) if pods > 1 else 1
    fab = flat_fabric(pods)
    out: List[Collective] = []
    if psum_mb > 0.0 or (allgather_mb <= 0.0 and alltoall_mb <= 0.0):
        out.append(Collective(
            fabric=fab, count=count,
            per_chip_mb=2.0 * (w - 1) / w * psum_mb,
            hops=count * 2.0 * (w - 1) * span))
    if allgather_mb > 0.0:
        out.append(Collective(
            fabric=fab, count=count,
            per_chip_mb=(w - 1) * allgather_mb,
            hops=count * (w - 1) * span))
    if alltoall_mb > 0.0:
        out.append(Collective(
            fabric=fab, count=count,
            per_chip_mb=(w - 1) / w * alltoall_mb,
            hops=count * 1.0 * span))
    return out


def hier_schedule(*, world: int, pods: int, count: float = 1.0,
                  ici_mb: float = 0.0, dcn_route_mb: float = 0.0,
                  dcn_return_mb: float = 0.0) -> List[Collective]:
    """Schedule entries for the hierarchical transport's group
    collectives: two dense intra-pod psums (``ici_mb`` is their summed
    payload, as billed), then the inter-pod route ``all_to_all`` and
    shard-return ``all_gather`` over ``pods`` participants on DCN."""
    pods = max(int(pods), 1)
    chips = max(int(world) // pods, 1)
    out: List[Collective] = []
    if ici_mb > 0.0 and chips > 1:
        out.append(Collective(
            fabric="ici", count=2.0 * count,
            per_chip_mb=2.0 * (chips - 1) / chips * ici_mb,
            hops=2.0 * count * 2.0 * (chips - 1)))
    if pods > 1:
        out.append(Collective(
            fabric="dcn", count=count,
            per_chip_mb=(pods - 1) / pods * dcn_route_mb,
            hops=count * 1.0))
        out.append(Collective(
            fabric="dcn", count=count,
            per_chip_mb=(pods - 1) * dcn_return_mb,
            hops=count * (pods - 1)))
    return out


# --------------------------------------------------------------- forward


@dataclasses.dataclass(frozen=True)
class TwinPoint:
    """One (W, pods, transport, method, knob) point to price.

    ``n_params`` sizes the gradient; the payload per update is derived
    from the method/knob through the engines' own analytic payload
    functions (``topk_keep_count``, ``sharded_payload_bits``,
    ``hier_payload_bits``) so the twin can never disagree with the billed
    wire accounting.  ``compute_ms`` is the non-comm step time anchor
    (from a calibrated context, or measured); ``hideable_fraction`` is
    the overlap schedule's dischargeable byte share (0.0 = nothing
    pipelines, the entiremodel/sync_overlap=1 case).
    """

    world: int
    transport: str                      # psum|all_gather|sharded|hierarchical
    n_params: int
    dp_pods: int = 1
    method: str = "none"                # none|dense|topk|blocktopk|randomk
    ratio: float = 1.0
    num_collectives: float = 1.0
    compute_ms: float = 0.0
    hideable_fraction: float = 0.0
    route_factor: float = 1.25
    return_factor: float = 1.25


def _sparse_keep(point: TwinPoint) -> int:
    from tpu_compressed_dp.ops.compressors import topk_keep_count
    return topk_keep_count(int(point.n_params), float(point.ratio))


def schedule_for_point(point: TwinPoint) -> List[Collective]:
    """The collective schedule ``point``'s transport emits, with payload
    MB derived analytically from the method/knob."""
    n = int(point.n_params)
    w = max(int(point.world), 1)
    pods = max(int(point.dp_pods), 1)
    t = point.transport
    dense_mb = n * DENSE_BITS_PER_ELEM / 8.0 / 1e6
    if point.method in ("none", "dense") or t == "psum":
        if t != "psum":
            raise ValueError(
                f"dense payloads ride the psum transport, got {t!r}")
        return flat_schedule(world=w, pods=pods,
                             count=point.num_collectives, psum_mb=dense_mb)
    if point.method not in SPARSE_METHODS:
        raise ValueError(
            f"the twin's forward payload model covers {SPARSE_METHODS} and "
            f"dense; got method {point.method!r} (price it via an explicit "
            "schedule instead)")
    keep = _sparse_keep(point)
    if t == "all_gather":
        ag_mb = keep * TOPK_BITS_PER_COORD / 8.0 / 1e6
        return flat_schedule(world=w, pods=pods,
                             count=point.num_collectives, allgather_mb=ag_mb)
    if t == "sharded":
        from tpu_compressed_dp.ops.wire_sharded import sharded_payload_bits
        route_bits, ret_bits = sharded_payload_bits(
            n, keep, w, 1, point.route_factor, point.return_factor)
        return flat_schedule(world=w, pods=pods,
                             count=point.num_collectives,
                             alltoall_mb=route_bits / 8.0 / 1e6,
                             allgather_mb=ret_bits / 8.0 / 1e6)
    if t == "hierarchical":
        from tpu_compressed_dp.ops.wire_sharded import hier_payload_bits
        ici_bits, route_bits, ret_bits = hier_payload_bits(
            n, keep, w, pods, point.route_factor, point.return_factor)
        if pods == 1:
            # single pod: the lone dense psum already reduces the world
            return flat_schedule(world=w, pods=1,
                                 count=point.num_collectives,
                                 psum_mb=ici_bits / 8.0 / 1e6)
        return hier_schedule(world=w, pods=pods,
                             count=point.num_collectives,
                             ici_mb=ici_bits / 8.0 / 1e6,
                             dcn_route_mb=route_bits / 8.0 / 1e6,
                             dcn_return_mb=ret_bits / 8.0 / 1e6)
    raise ValueError(f"unknown transport {t!r}")


def predict_step_ms(model: CostModel, point: TwinPoint) -> float:
    """Modeled step time at ``point``: the compute anchor plus the
    exposed comm of the transport's schedule."""
    sched = schedule_for_point(point)
    return float(point.compute_ms) + model.comm_ms(
        sched, hideable_fraction=point.hideable_fraction)


def schedule_features(schedule: List[Collective]
                      ) -> Dict[str, Tuple[float, float, float]]:
    """Per-fabric ``(count, per_chip_mb, hops)`` sums — the calibration
    fitter's design-matrix features for one row."""
    out: Dict[str, List[float]] = {}
    for c in schedule:
        acc = out.setdefault(c.fabric, [0.0, 0.0, 0.0])
        acc[0] += c.count
        acc[1] += c.per_chip_mb
        acc[2] += c.hops
    return {f: (a, b, h) for f, (a, b, h) in sorted(out.items())}
