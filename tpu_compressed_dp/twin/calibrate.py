"""Least-squares calibration of the twin's per-fabric alpha/beta/gamma.

The joint system solved: each **step row** contributes

    step_ms  =  compute[context]  +  sum_f (alpha_f*cnt + beta_f*mb
                                            + gamma_f*hops)

with one ``compute[context]`` unknown per context key (repeat runs of the
same config share it); each **phase row** contributes the pure comm
equation (no compute term).  The phase rows are what identify the fabric
vector — inside one context every step row carries identical comm
features, so step rows pin the compute terms and bound the residuals by
their within-context repeat spread.

Solved with ``numpy.linalg.lstsq``, then clipped to physical range by an
active-set pass (a negative alpha/beta/gamma is noise, not a wire that
pays you): the most negative fabric coordinate is fixed to zero and the
rest refit, until all are non-negative.  Compute terms are then re-solved
exactly as ``mean(target - comm_pred)`` per context, so clipping never
leaks error into the step rows.

Per-row residuals are first-class output — ``twin_report.py`` renders
them and the tier-1 suite asserts every step row lands within 15%.

Deterministic: pure function of the rows (hostlint TCDP101).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_compressed_dp.twin.model import CostModel, FabricParams
from tpu_compressed_dp.twin.records import CalibRow

__all__ = ["Residual", "Calibration", "fit", "load_calibration",
           "save_calibration"]

_PARAMS_PER_FABRIC = 3   # alpha, beta, gamma


@dataclasses.dataclass(frozen=True)
class Residual:
    """One row's modeled-vs-measured verdict."""

    source: str
    index: int
    kind: str
    label: str
    measured_ms: float
    modeled_ms: float

    @property
    def err_frac(self) -> float:
        return (self.modeled_ms - self.measured_ms) / max(
            self.measured_ms, 1e-9)


@dataclasses.dataclass(frozen=True)
class Calibration:
    """A fitted twin: per-fabric params, per-context compute anchors, and
    the per-row residual table the fit left behind."""

    fabrics: Dict[str, FabricParams]
    contexts: Dict[str, float]          # context key -> compute ms
    residuals: Tuple[Residual, ...]
    n_step_rows: int
    n_phase_rows: int

    @property
    def model(self) -> CostModel:
        return CostModel(fabrics=self.fabrics)

    @property
    def step_rms_frac(self) -> float:
        """RMS relative error over the step rows — the error bar quoted
        next to every prediction (``pred_step_ms`` +/- rms * pred)."""
        fracs = [r.err_frac for r in self.residuals if r.kind == "step"]
        if not fracs:
            return 0.0
        return float(np.sqrt(np.mean(np.square(fracs))))

    def comm_ms_for(self, row: CalibRow) -> float:
        """Price one row's comm features through the fitted fabrics."""
        total = 0.0
        for fab, (cnt, mb, hops) in row.features.items():
            p = self.fabrics.get(fab, FabricParams())
            total += (cnt * p.alpha_ms + mb * p.beta_ms_per_mb
                      + hops * p.gamma_ms_per_hop)
        return total

    def predict_row_ms(self, row: CalibRow) -> Optional[float]:
        """Modeled wall for a calibration row; None when a step row's
        context was never fitted."""
        comm = self.comm_ms_for(row)
        if row.kind != "step":
            return comm
        if row.context not in self.contexts:
            return None
        return self.contexts[row.context] + comm

    def to_json(self) -> dict:
        return {
            "fabrics": {f: p.to_json() for f, p in self.fabrics.items()},
            "contexts": dict(self.contexts),
            "n_step_rows": self.n_step_rows,
            "n_phase_rows": self.n_phase_rows,
            "residuals": [dataclasses.asdict(r) for r in self.residuals],
        }

    @classmethod
    def from_json(cls, d: dict) -> "Calibration":
        return cls(
            fabrics={f: FabricParams.from_json(p)
                     for f, p in d["fabrics"].items()},
            contexts={k: float(v) for k, v in d["contexts"].items()},
            residuals=tuple(Residual(**r) for r in d.get("residuals", [])),
            n_step_rows=int(d["n_step_rows"]),
            n_phase_rows=int(d["n_phase_rows"]))


def _design(rows: Sequence[CalibRow], contexts: List[str],
            fabrics: List[str], free: Dict[Tuple[str, int], int]
            ) -> Tuple[np.ndarray, np.ndarray]:
    """Design matrix: one indicator column per context + the still-free
    fabric coordinates (``free`` maps (fabric, param_i) -> column)."""
    ctx_col = {c: i for i, c in enumerate(contexts)}
    n_cols = len(contexts) + len(free)
    a = np.zeros((len(rows), n_cols))
    b = np.zeros(len(rows))
    for ri, row in enumerate(rows):
        b[ri] = row.target_ms
        if row.kind == "step":
            a[ri, ctx_col[row.context]] = 1.0
        for fab, feats in row.features.items():
            for pi in range(_PARAMS_PER_FABRIC):
                col = free.get((fab, pi))
                if col is not None:
                    a[ri, len(contexts) + col] = feats[pi]
    return a, b


def fit(rows: Sequence[CalibRow]) -> Calibration:
    """Fit alpha/beta/gamma per fabric + a compute term per context from
    normalized calibration rows."""
    rows = list(rows)
    if not rows:
        raise ValueError("no calibration rows — nothing to fit")
    contexts = sorted({r.context for r in rows if r.kind == "step"})
    fabrics = sorted({f for r in rows for f in r.features})
    fabric_rows = {f: sum(1 for r in rows if f in r.features)
                   for f in fabrics}

    # active-set least squares: drop (zero) the most negative fabric
    # coordinate and refit until all remaining ones are non-negative
    free = {(f, pi): i for i, (f, pi) in enumerate(
        (f, pi) for f in fabrics for pi in range(_PARAMS_PER_FABRIC))}
    fixed: Dict[Tuple[str, int], float] = {}
    sol = np.zeros(0)
    while True:
        free = {k: i for i, k in enumerate(sorted(free))}
        a, b = _design(rows, contexts, fabrics, free)
        sol, *_ = np.linalg.lstsq(a, b, rcond=None)
        fab_part = {k: float(sol[len(contexts) + i])
                    for k, i in free.items()}
        neg = [(v, k) for k, v in fab_part.items() if v < -1e-9]
        if not neg:
            break
        _, worst = min(neg)
        fixed[worst] = 0.0
        del free[worst]

    params: Dict[str, FabricParams] = {}
    for f in fabrics:
        vals = []
        for pi in range(_PARAMS_PER_FABRIC):
            if (f, pi) in free:
                vals.append(max(0.0, float(sol[len(contexts)
                                              + free[(f, pi)]])))
            else:
                vals.append(fixed.get((f, pi), 0.0))
        params[f] = FabricParams(alpha_ms=vals[0], beta_ms_per_mb=vals[1],
                                 gamma_ms_per_hop=vals[2],
                                 rows=fabric_rows[f])

    # re-solve compute terms exactly against the clipped fabric vector
    partial = Calibration(fabrics=params, contexts={}, residuals=(),
                          n_step_rows=0, n_phase_rows=0)
    ctx_ms: Dict[str, float] = {}
    for ctx in contexts:
        gaps = [r.target_ms - partial.comm_ms_for(r)
                for r in rows if r.kind == "step" and r.context == ctx]
        ctx_ms[ctx] = float(np.mean(gaps))

    calib = Calibration(
        fabrics=params, contexts=ctx_ms, residuals=(),
        n_step_rows=sum(1 for r in rows if r.kind == "step"),
        n_phase_rows=sum(1 for r in rows if r.kind == "phase"))
    residuals = tuple(
        Residual(source=r.source, index=r.index, kind=r.kind, label=r.label,
                 measured_ms=r.target_ms,
                 modeled_ms=float(calib.predict_row_ms(r)))
        for r in rows)
    return dataclasses.replace(calib, residuals=residuals)


def save_calibration(calib: Calibration, path: str) -> None:
    with open(path, "w") as f:
        json.dump(calib.to_json(), f, indent=1, sort_keys=True)


def load_calibration(path: str) -> Calibration:
    with open(path) as f:
        return Calibration.from_json(json.load(f))
