"""The modeled-performance gate: pinned flagship configs re-priced
through the current twin on every tier-1 run.

``benchmarks/perf_pins.json`` pins a handful of flagship (W, pods,
transport, method, knob) points with the step time the twin modeled when
the pin was minted.  The gate re-fits the calibration from the repo's
records (a deterministic function of the committed artifacts) and
re-prices every pin through the CURRENT model code: a PR that changes the
schedule arithmetic, the payload functions, or the fitter in a way that
inflates a flagship's modeled step time by more than the pin's tolerance
fails tier-1 — the raw-speed ratchet, analogous to what DOTS_PASSED does
for correctness.  Modeled-time DROPS beyond tolerance don't fail (faster
is what we want) but are flagged stale so the pin gets re-minted
(``tools/twin_report.py --update_pins``).

Deterministic: pure function of the pins file + records (TCDP101).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from tpu_compressed_dp.twin.calibrate import Calibration
from tpu_compressed_dp.twin.model import TwinPoint, predict_step_ms

__all__ = ["PinResult", "load_pins", "price_pin", "check_pins",
           "make_pin", "DEFAULT_TOL_FRAC"]

DEFAULT_TOL_FRAC = 0.10

_POINT_KEYS = ("world", "transport", "n_params", "dp_pods", "method",
               "ratio", "num_collectives", "hideable_fraction")


@dataclasses.dataclass(frozen=True)
class PinResult:
    """One pin's verdict after re-pricing through the current model."""

    name: str
    pinned_ms: float
    modeled_ms: Optional[float]
    tol_frac: float
    ok: bool
    note: str

    @property
    def frac_change(self) -> Optional[float]:
        if self.modeled_ms is None:
            return None
        return (self.modeled_ms - self.pinned_ms) / max(self.pinned_ms, 1e-9)


def load_pins(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    pins = doc.get("pins")
    if not isinstance(pins, list) or not pins:
        raise ValueError(f"{path}: expected a non-empty 'pins' list")
    for i, pin in enumerate(pins):
        for key in ("name", "point", "context", "modeled_step_ms"):
            if key not in pin:
                raise ValueError(f"{path}: pins[{i}] missing {key!r}")
    return doc


def _pin_point(pin: dict, calib: Calibration) -> TwinPoint:
    ctx = pin["context"]
    if ctx not in calib.contexts:
        raise KeyError(
            f"pin {pin['name']!r}: context {ctx!r} not in the calibration "
            "(its source record vanished?)")
    kwargs = {k: v for k, v in pin["point"].items() if k in _POINT_KEYS}
    return TwinPoint(compute_ms=calib.contexts[ctx], **kwargs)


def price_pin(pin: dict, calib: Calibration) -> float:
    """The pin's config priced through the CURRENT model + calibration."""
    return predict_step_ms(calib.model, _pin_point(pin, calib))


def check_pins(doc: dict, calib: Calibration) -> List[PinResult]:
    """Re-price every pin; a result is not-ok on a modeled regression
    beyond tolerance OR when the pin can no longer be priced at all."""
    default_tol = float(doc.get("tolerance_frac", DEFAULT_TOL_FRAC))
    out: List[PinResult] = []
    for pin in doc["pins"]:
        tol = float(pin.get("tol_frac", default_tol))
        pinned = float(pin["modeled_step_ms"])
        try:
            modeled = price_pin(pin, calib)
        except (KeyError, ValueError) as e:
            out.append(PinResult(name=pin["name"], pinned_ms=pinned,
                                 modeled_ms=None, tol_frac=tol, ok=False,
                                 note=f"unpriceable: {e}"))
            continue
        frac = (modeled - pinned) / max(pinned, 1e-9)
        if frac > tol:
            ok, note = False, f"modeled regression {frac:+.1%} > {tol:.0%}"
        elif frac < -tol:
            ok, note = True, f"stale pin ({frac:+.1%}) — re-mint it"
        else:
            ok, note = True, "within tolerance"
        out.append(PinResult(name=pin["name"], pinned_ms=pinned,
                             modeled_ms=modeled, tol_frac=tol, ok=ok,
                             note=note))
    return out


def make_pin(name: str, point: Dict, context: str, calib: Calibration,
             tol_frac: float = DEFAULT_TOL_FRAC) -> dict:
    """Mint one pin at the CURRENT modeled price (the update procedure
    ``tools/twin_report.py --update_pins`` runs for every existing pin)."""
    pin = {"name": name, "point": dict(point), "context": context,
           "modeled_step_ms": 0.0, "tol_frac": tol_frac}
    pin["modeled_step_ms"] = round(price_pin(pin, calib), 3)
    return pin
