"""The scale-out digital twin: a calibrated per-fabric alpha/beta/gamma
cost model over the collective schedules the transports actually emit,
fitted from the repo's own BENCH/MULTICHIP acceptance artifacts.

  * :mod:`~tpu_compressed_dp.twin.model`      the cost model + forward
    payload/schedule derivation (predict any (W, pods, transport,
    method, knob) point)
  * :mod:`~tpu_compressed_dp.twin.records`    BENCH/MULTICHIP loader ->
    calibration rows
  * :mod:`~tpu_compressed_dp.twin.calibrate`  least-squares fitter with
    per-row residuals
  * :mod:`~tpu_compressed_dp.twin.gate`       the tier-1 modeled-perf
    ratchet over ``benchmarks/perf_pins.json``

Every module is replay-deterministic (hostlint TCDP101): fits and
predictions are pure functions of the committed artifacts.
"""

from tpu_compressed_dp.twin.calibrate import (        # noqa: F401
    Calibration, Residual, fit, load_calibration, save_calibration,
)
from tpu_compressed_dp.twin.gate import (             # noqa: F401
    PinResult, check_pins, load_pins, make_pin, price_pin,
)
from tpu_compressed_dp.twin.model import (            # noqa: F401
    Collective, CostModel, FabricParams, TwinPoint,
    UncalibratedFabricError, predict_step_ms, schedule_for_point,
)
from tpu_compressed_dp.twin.records import (          # noqa: F401
    CalibRow, RecordFile, calibration_rows, discover_record_paths,
    load_record_file,
)

__all__ = [
    "Calibration", "Residual", "fit", "load_calibration",
    "save_calibration", "PinResult", "check_pins", "load_pins", "make_pin",
    "price_pin", "Collective", "CostModel", "FabricParams", "TwinPoint",
    "UncalibratedFabricError", "predict_step_ms", "schedule_for_point",
    "CalibRow", "RecordFile", "calibration_rows", "discover_record_paths",
    "load_record_file",
]
