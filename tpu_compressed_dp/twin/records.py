"""BENCH/MULTICHIP record loader: schema-validate the hand-shaped
``BENCH_r*.json`` / ``MULTICHIP_r*.json`` acceptance artifacts and
normalize them into the twin's calibration rows.

The files come in five shapes, all produced by the repo's own tooling:

  * **headline** (r01-r05): ``parsed`` is a single benchmark headline
    (``{metric, value, unit, ...}``) — validated, zero calibration rows
    (no step/payload decomposition to fit against)
  * **step** (r06): ``parsed`` is one full ``bench/sweep.py`` step record
  * **sweep** (r07/r08/r10/r11): ``records`` is a list of step records,
    optionally with ``phase_<name>_ms`` columns (``--phase_breakdown``)
  * **adaptive** (r09): ``records`` carry ``static_rungs`` /
    ``window_trace`` from the closed-loop controller runs — the timed
    ``static_rungs`` become step rows; ``window_trace`` rows are
    validated only (they mix compile/warmup walls into step_ms)
  * **stream** (r12): delta-stream segment records — validated only
    (byte accounting, no step times)

MULTICHIP files record dry-run verdicts (``{n_devices, rc, ok, ...}``)
with no timings: validated, zero calibration rows.

A **step row** carries the record's wall ``step_ms`` as target plus
per-fabric ``(count, per_chip_mb, hops)`` comm features derived from the
billed payload columns through the same schedule arithmetic the engines
use; its *context key* (model x method x knob x transport x topology x
pallas mode) gives the fitter a per-context compute term so rows that
differ only in repeat noise share one.  A **phase row** is a pure comm
equation — a ``--phase_breakdown`` comm phase's wall time against that
one collective's features, no compute term — and is what actually
identifies alpha/beta/gamma per fabric (``pallas off`` rows only: the
``force`` column times the Pallas interpreter, not the wire).

Pure functions of file contents — no clocks (hostlint TCDP101).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_compressed_dp.twin.model import (
    Collective, flat_schedule, hier_schedule, schedule_features,
)

__all__ = [
    "CalibRow", "RecordFile", "load_record_file", "discover_record_paths",
    "calibration_rows", "context_key", "step_row",
]

_STEP_REQUIRED = ("model", "method", "granularity", "mode", "devices",
                  "batch", "step_ms", "payload_mb_per_step", "transport")
_PAYLOAD_COLS = ("payload_mb_psum", "payload_mb_allgather",
                 "payload_mb_alltoall", "payload_mb_ici", "payload_mb_dcn")
_ADAPTIVE_REQUIRED = ("model", "method", "granularity", "mode", "knob",
                      "rungs", "window", "windows", "devices", "batch",
                      "static_rungs", "window_trace")
_RUNG_REQUIRED = ("rung", "value", "step_ms", "bits_per_update")
_STREAM_SEG_REQUIRED = ("seq", "kind", "step", "bytes", "nnz")
_MULTICHIP_REQUIRED = ("n_devices", "rc", "ok", "skipped")


@dataclasses.dataclass(frozen=True)
class CalibRow:
    """One calibration equation: per-fabric comm features against a wall
    target.  ``kind='step'`` rows add a per-context compute unknown keyed
    by ``context``; ``kind='phase'`` rows are comm-only."""

    source: str    # file basename
    index: int     # record position inside the file
    kind: str      # 'step' | 'phase'
    label: str     # human-readable row id for residual tables
    context: Optional[str]  # canonical context key (step rows)
    features: Dict[str, Tuple[float, float, float]]  # fabric -> (cnt,mb,hops)
    target_ms: float


@dataclasses.dataclass(frozen=True)
class RecordFile:
    """One validated artifact file."""

    source: str
    shape: str           # headline|step|sweep|adaptive|stream|multichip
    raw: dict
    rows: Tuple[CalibRow, ...]


def _err(source: str, msg: str) -> ValueError:
    return ValueError(f"{source}: {msg}")


def _require(d: dict, keys: Sequence[str], source: str, what: str) -> None:
    missing = [k for k in keys if k not in d]
    if missing:
        raise _err(source, f"{what} missing keys {missing}")


def _num(d: dict, key: str, source: str, minimum: float = None) -> float:
    v = d.get(key)
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        raise _err(source, f"{key} must be numeric, got {v!r}")
    if minimum is not None and v < minimum:
        raise _err(source, f"{key} must be >= {minimum}, got {v}")
    return float(v)


# ------------------------------------------------------------ step rows


def context_key(rec: dict) -> str:
    """Canonical compute-context key for a step record: everything that
    pins the non-comm step time.  Rows sharing a key share one fitted
    compute term, so repeat runs of the same config interpolate instead
    of each demanding its own unknown."""
    method = str(rec.get("method", "none"))
    knob = rec.get("rank") if method == "powersgd" else rec.get("ratio")
    parts = [
        f"model={rec.get('model')}",
        f"method={method}",
        f"gran={rec.get('granularity')}",
        f"mode={rec.get('mode')}",
        f"transport={rec.get('transport', 'psum')}",
        f"knob={knob}",
        f"devices={rec.get('devices')}",
        f"pods={rec.get('dp_pods', 1)}",
        f"batch={rec.get('batch')}",
        f"cs={rec.get('channels_scale', 1.0)}",
        f"pallas={rec.get('pallas_mode', 'off')}",
    ]
    return "|".join(parts)


def _hier_dcn_split(rec: dict, source: str) -> Tuple[float, float]:
    """Split a hierarchical record's billed ``payload_mb_dcn`` into
    (route_mb, return_mb) using the engine's own analytic payload ratio
    (``hier_payload_bits``), so the twin's route/return features match
    what actually rode the all_to_all vs the all_gather."""
    from tpu_compressed_dp.ops.compressors import topk_keep_count
    from tpu_compressed_dp.ops.wire_sharded import hier_payload_bits

    dcn_mb = float(rec.get("payload_mb_dcn", 0.0))
    if dcn_mb <= 0.0:
        return 0.0, 0.0
    dense_mb = rec.get("dense_mb_per_step")
    ratio = rec.get("ratio")
    if not dense_mb or not ratio:
        return dcn_mb, 0.0
    n = int(round(float(dense_mb) * 1e6 / 4.0))
    keep = topk_keep_count(n, float(ratio))
    _, route_bits, ret_bits = hier_payload_bits(
        n, keep, int(rec["devices"]), int(rec.get("dp_pods", 1)),
        1.25, 1.25)
    tot = route_bits + ret_bits
    if tot <= 0.0:
        return dcn_mb, 0.0
    return dcn_mb * route_bits / tot, dcn_mb * ret_bits / tot


def _step_schedule(rec: dict, source: str) -> List[Collective]:
    world = int(_num(rec, "devices", source, minimum=1))
    pods = int(rec.get("dp_pods", 1) or 1)
    count = float(rec.get("num_collectives", 1.0) or 1.0)
    if str(rec.get("transport")) == "hierarchical":
        route_mb, ret_mb = _hier_dcn_split(rec, source)
        return hier_schedule(
            world=world, pods=pods, count=count,
            ici_mb=float(rec.get("payload_mb_ici", 0.0)),
            dcn_route_mb=route_mb, dcn_return_mb=ret_mb)
    return flat_schedule(
        world=world, pods=pods, count=count,
        psum_mb=float(rec.get("payload_mb_psum", 0.0)),
        allgather_mb=float(rec.get("payload_mb_allgather", 0.0)),
        alltoall_mb=float(rec.get("payload_mb_alltoall", 0.0)))


def step_row(rec: dict, *, source: str, index: int) -> CalibRow:
    """Normalize one sweep step record into a calibration row."""
    _require(rec, _STEP_REQUIRED, source, f"step record {index}")
    for col in _PAYLOAD_COLS:
        if col in rec:
            _num(rec, col, source, minimum=0.0)
    target = _num(rec, "step_ms", source, minimum=0.0)
    label = "{}[{}] {} {} W={} pods={}".format(
        source, index, rec.get("transport"), rec.get("method"),
        rec.get("devices"), rec.get("dp_pods", 1))
    return CalibRow(
        source=source, index=index, kind="step", label=label,
        context=context_key(rec),
        features=schedule_features(_step_schedule(rec, source)),
        target_ms=target)


#: which ``phase_<name>_ms`` columns time a wire collective, per
#: transport — everything else (compress, ef, recompress, update, and the
#: sharded transport's local segment-sum 'reduce') is compute
_COMM_PHASES = {
    "all_gather": ("reduce",),
    "sharded": ("route", "return"),
    "hierarchical": ("ici_reduce", "route", "return"),
}


def _phase_rows(rec: dict, *, source: str, index: int) -> List[CalibRow]:
    if str(rec.get("pallas_mode", "off")) != "off":
        return []   # force rows time the Pallas interpreter, not the wire
    transport = str(rec.get("transport"))
    names = _COMM_PHASES.get(transport, ())
    world = int(rec["devices"])
    pods = int(rec.get("dp_pods", 1) or 1)
    count = float(rec.get("num_collectives", 1.0) or 1.0)
    route_mb, ret_mb = (0.0, 0.0)
    if transport == "hierarchical":
        route_mb, ret_mb = _hier_dcn_split(rec, source)
    out: List[CalibRow] = []
    for name in names:
        col = f"phase_{name}_ms"
        if col not in rec:
            continue
        target = _num(rec, col, source, minimum=0.0)
        if transport == "all_gather" and name == "reduce":
            sched = flat_schedule(
                world=world, pods=pods, count=count,
                allgather_mb=float(rec.get("payload_mb_allgather", 0.0)))
        elif transport == "sharded" and name == "route":
            sched = flat_schedule(
                world=world, pods=pods, count=count,
                alltoall_mb=float(rec.get("payload_mb_alltoall", 0.0)))
        elif transport == "sharded" and name == "return":
            sched = flat_schedule(
                world=world, pods=pods, count=count,
                allgather_mb=float(rec.get("payload_mb_allgather", 0.0)))
        elif name == "ici_reduce":
            sched = hier_schedule(
                world=world, pods=pods, count=count,
                ici_mb=float(rec.get("payload_mb_ici", 0.0)))
            sched = [c for c in sched if c.fabric == "ici"]
        elif name == "route":
            sched = [Collective(
                fabric="dcn", count=count,
                per_chip_mb=(pods - 1) / pods * route_mb,
                hops=count * 1.0)] if pods > 1 else []
        else:   # hierarchical return
            sched = [Collective(
                fabric="dcn", count=count,
                per_chip_mb=(pods - 1) * ret_mb,
                hops=count * (pods - 1))] if pods > 1 else []
        if not sched:
            continue
        out.append(CalibRow(
            source=source, index=index, kind="phase",
            label=f"{source}[{index}] {transport} phase:{name}",
            context=None, features=schedule_features(sched),
            target_ms=target))
    return out


def _rung_row(rec: dict, rung: dict, *, source: str, index: int,
              rung_i: int) -> CalibRow:
    """A timed static rung from an adaptive record: the billed bits ride
    the simulate path's psum bucket (compressed payload, dense transport
    — exactly how ``bench/sweep.py --adaptive`` bills them)."""
    _require(rung, _RUNG_REQUIRED, source,
             f"record {index} static_rungs[{rung_i}]")
    world = int(rec["devices"])
    mb = _num(rung, "bits_per_update", source, minimum=0.0) / 8.0 / 1e6
    sched = flat_schedule(world=world, pods=int(rec.get("dp_pods", 1) or 1),
                          count=1.0, psum_mb=mb)
    knobbed = dict(rec)
    knobbed["transport"] = "psum"
    key = "rank" if rec.get("method") == "powersgd" else "ratio"
    knobbed[key] = rung["value"]
    return CalibRow(
        source=source, index=index, kind="step",
        label=f"{source}[{index}] static_rung{rung_i} "
              f"{rec.get('method')}={rung['value']}",
        context=context_key(knobbed),
        features=schedule_features(sched),
        target_ms=_num(rung, "step_ms", source, minimum=0.0))


# ------------------------------------------------------------ file level


def _classify(raw: dict, source: str) -> str:
    if source.startswith("MULTICHIP"):
        _require(raw, _MULTICHIP_REQUIRED, source, "multichip record")
        return "multichip"
    _require(raw, ("n", "cmd", "rc"), source, "bench artifact")
    recs = raw.get("records")
    if isinstance(recs, list) and recs:
        first = recs[0]
        if "static_rungs" in first:
            return "adaptive"
        if "seq" in first and "bytes" in first:
            return "stream"
        return "sweep"
    parsed = raw.get("parsed")
    if isinstance(parsed, dict) and "step_ms" in parsed:
        return "step"
    if isinstance(parsed, dict) and "metric" in parsed:
        return "headline"
    raise _err(source, "unrecognized artifact shape (no records list, no "
                       "parsed step record, no parsed headline)")


def load_record_file(path: str) -> RecordFile:
    """Load + schema-validate one artifact file; normalize whatever it
    contains into calibration rows (possibly none)."""
    source = os.path.basename(path)
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise _err(source, "top level must be a JSON object")
    shape = _classify(raw, source)
    rows: List[CalibRow] = []
    if shape == "headline":
        parsed = raw["parsed"]
        _require(parsed, ("metric", "value", "unit"), source, "headline")
        _num(parsed, "value", source)
    elif shape == "step":
        rows.append(step_row(raw["parsed"], source=source, index=0))
    elif shape == "sweep":
        for i, rec in enumerate(raw["records"]):
            rows.append(step_row(rec, source=source, index=i))
            rows.extend(_phase_rows(rec, source=source, index=i))
    elif shape == "adaptive":
        for i, rec in enumerate(raw["records"]):
            _require(rec, _ADAPTIVE_REQUIRED, source, f"adaptive record {i}")
            for j, rung in enumerate(rec["static_rungs"]):
                rows.append(_rung_row(rec, rung, source=source, index=i,
                                      rung_i=j))
            for j, w in enumerate(rec["window_trace"]):
                _require(w, ("window", "rung", "step_ms"), source,
                         f"record {i} window_trace[{j}]")
    elif shape == "stream":
        for i, seg in enumerate(raw["records"]):
            _require(seg, _STREAM_SEG_REQUIRED, source, f"segment {i}")
            _num(seg, "bytes", source, minimum=0.0)
    elif shape == "multichip":
        if not isinstance(raw.get("ok"), bool):
            raise _err(source, f"ok must be bool, got {raw.get('ok')!r}")
        _num(raw, "n_devices", source, minimum=1)
    return RecordFile(source=source, shape=shape, raw=raw, rows=tuple(rows))


def discover_record_paths(root: str) -> List[str]:
    """Every BENCH/MULTICHIP artifact under ``root``, sorted."""
    out = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    out += sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    return out


def scaled_schedule(rec: dict, *, world: int, pods: int
                    ) -> List[Collective]:
    """The collective schedule one step record's config would emit at a
    DIFFERENT (world, pods) topology — the W-projection tables' engine.

    Sparse wire transports re-derive their payloads analytically (the
    sharded route/return and hierarchical splits genuinely depend on W
    and pods); dense psum and simulate rows keep their billed per-update
    payload (it is W-independent) and re-lay it on the new topology.
    """
    from tpu_compressed_dp.twin.model import TwinPoint, schedule_for_point

    transport = str(rec.get("transport"))
    method = str(rec.get("method", "none"))
    sparse_wire = (rec.get("mode") == "wire" and method == "topk"
                   and transport in ("all_gather", "sharded",
                                     "hierarchical"))
    if sparse_wire and rec.get("dense_mb_per_step") and rec.get("ratio"):
        n = int(round(float(rec["dense_mb_per_step"]) * 1e6 / 4.0))
        return schedule_for_point(TwinPoint(
            world=world, transport=transport, n_params=n, dp_pods=pods,
            method=method, ratio=float(rec["ratio"]),
            num_collectives=float(rec.get("num_collectives", 1.0) or 1.0)))
    scaled = dict(rec)
    scaled["devices"] = world
    scaled["dp_pods"] = pods
    return _step_schedule(scaled, "scaled")


def calibration_rows(root_or_paths) -> List[CalibRow]:
    """All calibration rows from a records root dir (or explicit path
    list), in deterministic file-then-record order."""
    if isinstance(root_or_paths, str):
        paths = discover_record_paths(root_or_paths)
    else:
        paths = list(root_or_paths)
    rows: List[CalibRow] = []
    for p in paths:
        rows.extend(load_record_file(p).rows)
    return rows
