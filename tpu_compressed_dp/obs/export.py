"""Exportable telemetry: JSONL event stream + Prometheus textfile.

  * :class:`EventStream` — one JSON record per run/step/epoch/guard event,
    schema-versioned (``v``), append-only, flushed per record so a watchdog
    or tail -f sees events as they happen.  ``tools/trace_report.py``
    consumes this stream offline.
  * :func:`write_prometheus` — node-exporter-textfile-style exposition of
    the latest metric values, with ``# TYPE`` / ``# HELP`` lines sourced
    from the metric registry (:mod:`tpu_compressed_dp.obs.registry`).
    Atomic replace, so a scraper never reads a partial file.
  * :func:`telemetry_snapshot` — the compact health payload the heartbeat
    carries (step rate, p95 latency, ``last_good_step``), consumed by
    ``tools/watchdog.py --check``.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from tpu_compressed_dp.obs import registry

__all__ = ["SCHEMA_VERSION", "EventStream", "read_events", "read_all_events",
           "list_segments", "write_prometheus", "telemetry_snapshot",
           "job_scoped_path"]

#: Bump when a record's field meaning changes incompatibly; consumers
#: (trace_report, watchdog, tests) check it before interpreting fields.
SCHEMA_VERSION = 1


class EventStream:
    """Append-only JSONL event writer.

    Every record carries ``v`` (schema version), ``kind`` and ``ts``
    (host epoch seconds); the constructor writes a ``run_start`` record
    with the caller's metadata, ``close()`` a ``run_end``.  Values must be
    JSON-serialisable — pass plain floats, not device arrays.

    Thread-safe: the async checkpointer's background writer emits
    ``ckpt_save`` records concurrently with the step loop's own events, so
    write+flush is serialised under a lock and records stay whole-line.  An
    ``emit`` racing (or after) ``close`` is dropped silently — a late
    background commit must not crash the run epilogue.

    ``max_bytes`` bounds the LIVE file: when appending the next record
    would cross it, the file rotates to ``<path>.<seg:04d>`` via an atomic
    ``os.replace`` (a tailing reader sees either the old whole file or the
    fresh one, never a truncation) and the stream reopens empty.  Every
    record carries its segment index as ``seg``, so consumers can stitch
    rotated segments back into one ordered stream
    (:func:`read_all_events`); on resume, numbering continues after the
    segments already on disk.  ``max_bytes=None`` (the default) keeps the
    historic unbounded single-file behaviour.
    """

    def __init__(self, path: str, meta: Optional[Dict[str, Any]] = None,
                 *, max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = max_bytes
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._seg = len(list_segments(path))
        self._f = open(path, "a")
        self._closed = False
        self.emit("run_start", **(meta or {}))

    def _rotate_locked(self) -> None:
        # caller holds self._lock
        self._f.close()
        os.replace(self.path, f"{self.path}.{self._seg:04d}")
        self._seg += 1
        self._f = open(self.path, "a")

    def emit(self, kind: str, **fields: Any) -> None:
        rec = {"v": SCHEMA_VERSION, "kind": kind, "ts": time.time(), **fields}
        with self._lock:
            if self._closed:
                return
            rec["seg"] = self._seg
            line = json.dumps(rec) + "\n"
            if (self.max_bytes is not None and self._f.tell() > 0
                    and self._f.tell() + len(line) > self.max_bytes):
                self._rotate_locked()
                rec["seg"] = self._seg
                line = json.dumps(rec) + "\n"
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        if self._closed:
            return
        self.emit("run_end")
        with self._lock:
            self._closed = True
            self._f.close()

    def __enter__(self) -> "EventStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def job_scoped_path(path: Optional[str], job_id: Optional[str]) -> Optional[str]:
    """Namespace a telemetry file path per job: ``dir/file`` becomes
    ``dir/<job_id>.file``.

    Two jobs sharing one device pool typically also share one textfile
    collector / heartbeat directory; without a per-job prefix the second
    job's atomic ``os.replace`` silently clobbers the first's export.  The
    prefix keeps the atomic-replace semantics (same directory, same
    filesystem) and leaves the file's registry HELP/TYPE content
    untouched — only the NAME is scoped; the job identity inside the
    exposition rides a ``job="<id>"`` label instead.  No-op when either
    argument is falsy, so single-job runs keep their exact paths."""
    if not path or not job_id:
        return path
    d, base = os.path.split(path)
    return os.path.join(d, f"{job_id}.{base}")


def read_events(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL event stream (strict: a malformed line raises — a
    partial tail line is a bug, the writer flushes whole records)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def list_segments(path: str) -> List[str]:
    """Rotated segment files for a stream (``<path>.0000``, ...),
    ascending by segment index."""
    d, base = os.path.split(path)
    seg_re = re.compile(re.escape(base) + r"\.(\d{4})$")
    try:
        names = os.listdir(d or ".")
    except OSError:
        return []
    found = []
    for name in names:
        m = seg_re.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(d, name)))
    return [p for _, p in sorted(found)]


def read_all_events(path: str) -> List[Dict[str, Any]]:
    """Events across every rotated segment plus the live file, stitched in
    segment order — the reader-side pair of ``EventStream(max_bytes=...)``."""
    out: List[Dict[str, Any]] = []
    for p in list_segments(path) + [path]:
        if os.path.exists(p):
            out.extend(read_events(p))
    return out


def write_prometheus(metrics: Dict[str, float], path: str,
                     labels: Optional[Dict[str, str]] = None) -> str:
    """Write ``metrics`` in Prometheus text exposition format to ``path``.

    Keys may be registry-canonical (``comm/sent_bits``) or ad-hoc; declared
    metrics get a ``# HELP`` line from their spec.  Everything is exposed
    as ``gauge``: the harnesses write per-step/per-window aggregates
    (epoch means, the latest window's value), not process-lifetime running
    totals — exposing those as Prometheus counters would make ``rate()``
    treat every dip as a counter reset.  (The registry's ``counter`` kind
    describes the metric's additive nature across workers/steps, not its
    exposition form here.)  Non-numeric values are skipped.  Atomic
    tmp+replace so scrapers never see a torn file."""
    label_str = ""
    if labels:
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        label_str = "{" + inner + "}"
    lines = []
    for key in sorted(metrics):
        val = metrics[key]
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        pname = registry.prometheus_name(key)
        if registry.is_declared(key):
            ms = registry.spec(key)
            if ms.help:
                lines.append(f"# HELP {pname} {ms.help}")
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname}{label_str} {float(val):g}")
    body = "\n".join(lines) + "\n"
    tmp = path + ".tmp"
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, path)
    return body


def telemetry_snapshot(timeline=None, *, step: Optional[int] = None,
                       last_good_step: Optional[int] = None
                       ) -> Dict[str, float]:
    """The heartbeat's health payload: step rate + p95 latency from the
    :class:`~tpu_compressed_dp.obs.trace.StepTimeline` window, plus the
    progress watermarks the watchdog's wedge check reads."""
    out: Dict[str, float] = {}
    if step is not None:
        out["step"] = int(step)
    if last_good_step is not None:
        out["last_good_step"] = int(last_good_step)
    if timeline is not None:
        snap = timeline.snapshot()
        out["steps_per_sec"] = snap["time/steps_per_sec"]
        out["step_p95_ms"] = snap["time/step_p95_ms"]
        out["data_wait_frac"] = snap["time/data_wait_frac"]
    return out
