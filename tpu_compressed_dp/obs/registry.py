"""Typed metric registry — the single source of truth for every stat key.

The reference (and this repo until now) accreted stringly-typed stats keys
across two sync engines, three step factories and three harnesses; nothing
enforced that a new ``comm/*`` key carried a sane cross-worker reduction or
that the harness epilogues even knew it existed.  Here every metric the
system emits is declared ONCE, with:

  * ``kind`` — ``counter`` (monotone / additive volume), ``gauge``
    (point-in-time value) or ``timing`` (latency/duration);
  * ``unit`` — what one unit of the value means (``bits``, ``elems``,
    ``examples``, ``seconds``...), so exporters never guess;
  * ``reduction`` — how the value combines ACROSS WORKERS: ``mean`` /
    ``sum`` for volumes, ``min`` / ``max`` for 0/1 diagnostics and
    monotone watermarks (``sync_agree`` is a unanimity verdict — pmin;
    ``guard/nonfinite`` is an any-worker alarm — pmax).  The partitioned
    sync engine (:mod:`tpu_compressed_dp.parallel.dp`) derives its
    diagnostic-reduction table from these declarations, so a reduction can
    never silently disagree between the registry and the engine;
  * ``emitter`` — which layer produces it: ``engine`` (inside
    ``sync(...)``, raw key later prefixed ``comm/`` by the step factories),
    ``step`` (the jitted train step), ``eval`` (the eval step), or
    ``host`` (harness-side derived telemetry: throughput, MFU, latency
    percentiles).

The conformance test (tests/test_observability.py) traces both sync engines
across the full method x transport x granularity matrix and fails on any
emitted key that is not declared here — adding a stat without declaring it
is a test failure, not a silent new string.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List

__all__ = [
    "MetricSpec", "REGISTRY", "declare", "canonical", "spec", "is_declared",
    "undeclared", "engine_diag_reductions", "prometheus_name",
    "COUNTER", "GAUGE", "TIMING",
]

COUNTER = "counter"
GAUGE = "gauge"
TIMING = "timing"
_KINDS = (COUNTER, GAUGE, TIMING)
_REDUCTIONS = ("mean", "sum", "min", "max")
_EMITTERS = ("engine", "step", "eval", "host")


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    name: str       # canonical full name (engines emit it without "comm/")
    kind: str       # counter | gauge | timing
    unit: str       # bits, elems, examples, tokens, seconds, ratio, ...
    reduction: str  # cross-worker combine: mean | sum | min | max
    emitter: str    # engine | step | eval | host
    help: str = ""


REGISTRY: Dict[str, MetricSpec] = {}


def declare(name: str, kind: str, unit: str, reduction: str, emitter: str,
            help: str = "") -> MetricSpec:
    """Register one metric; redeclaring with a different spec is an error
    (two subsystems fighting over one key is exactly the bug class the
    registry exists to kill)."""
    if kind not in _KINDS:
        raise ValueError(f"kind must be one of {_KINDS}, got {kind!r}")
    if reduction not in _REDUCTIONS:
        raise ValueError(
            f"reduction must be one of {_REDUCTIONS}, got {reduction!r}")
    if emitter not in _EMITTERS:
        raise ValueError(f"emitter must be one of {_EMITTERS}, got {emitter!r}")
    ms = MetricSpec(name, kind, unit, reduction, emitter, help)
    prev = REGISTRY.get(name)
    if prev is not None and prev != ms:
        raise ValueError(f"metric {name!r} already declared as {prev}")
    REGISTRY[name] = ms
    return ms


# --- engine-emitted (sync stats; step factories prefix raw keys "comm/",
#     except guard/* which the guard wrapper emits pre-prefixed) ----------
declare("comm/sent_elems", COUNTER, "elems", "mean", "engine",
        "elements the wire representation carries this step")
declare("comm/sent_bits", COUNTER, "bits", "mean", "engine",
        "payload bits on the wire this step (analytic in simulate mode, "
        "measured in wire mode)")
declare("comm/sent_bits_psum", COUNTER, "bits", "mean", "engine",
        "payload bits riding the psum ring (2(W-1)/W per-chip traffic)")
declare("comm/sent_bits_allgather", COUNTER, "bits", "mean", "engine",
        "payload bits riding an all_gather ((W-1)x per-chip traffic)")
declare("comm/sent_bits_alltoall", COUNTER, "bits", "mean", "engine",
        "payload bits riding the sharded transport's all_to_all route "
        "((W-1)/W per-chip traffic)")
declare("comm/sent_bits_ici", COUNTER, "bits", "mean", "engine",
        "hierarchical transport: bits on the fast intra-pod ICI fabric "
        "(the dense pod psums; 2(C-1)/C per-chip traffic within a pod)")
declare("comm/sent_bits_dcn", COUNTER, "bits", "mean", "engine",
        "hierarchical transport: bits crossing the slow inter-pod DCN "
        "fabric (sparse route + shard return; the binding constraint)")
declare("comm/sent_bits_dcn_route", COUNTER, "bits", "mean", "engine",
        "the all_to_all route share of sent_bits_dcn ((P-1)/P per-chip; "
        "the remainder is the (P-1)x shard-return all_gather)")
declare("comm/dense_elems", GAUGE, "elems", "mean", "engine",
        "uncompressed gradient size (the compression denominator)")
declare("comm/num_collectives", GAUGE, "collectives", "mean", "engine",
        "collectives issued per sync (granularity-dependent)")
declare("comm/sync_agree", GAUGE, "bool", "min", "engine",
        "check_sync verdict: 1.0 = every worker selected identical "
        "indices / holds an identical warm start (unanimity -> pmin)")
declare("comm/threshold_overflow", COUNTER, "elems", "mean", "engine",
        "threshold-method survivors clipped by the fixed wire capacity")
declare("comm/topk_surplus_dropped", COUNTER, "elems", "mean", "engine",
        "above-threshold tie survivors beyond keep, truncated (EF off)")
declare("comm/shard_overflow", COUNTER, "elems", "mean", "engine",
        "coordinates clipped by the sharded transport's route/return caps")
declare("guard/nonfinite", GAUGE, "bool", "max", "engine",
        "1.0 = this step was vetoed by the finiteness vote "
        "(any-worker alarm -> pmax)")

# --- step-emitted (jitted train step, already globally reduced) ---------
declare("loss", GAUGE, "nats", "mean", "step", "global mean train loss")
declare("lr", GAUGE, "lr", "mean", "step", "learning rate at this step")
declare("correct", COUNTER, "examples", "sum", "step",
        "top-1 correct examples this step (global)")
declare("count", COUNTER, "examples", "sum", "step",
        "examples this step (global)")
declare("tokens", COUNTER, "tokens", "sum", "step",
        "tokens this step (global)")
declare("guard/loss_scale", GAUGE, "scale", "mean", "step",
        "live dynamic loss scale (replicated)")
declare("guard/skipped", COUNTER, "steps", "max", "step",
        "cumulative vetoed steps (monotone, replicated)")
declare("guard/skip_streak", GAUGE, "steps", "max", "step",
        "consecutive vetoed steps ending at this step")
declare("guard/last_good_step", GAUGE, "steps", "max", "step",
        "last step whose update was applied")

# --- eval-step emitted ---------------------------------------------------
declare("loss_sum", COUNTER, "nats", "sum", "eval", "summed eval loss")
declare("correct5", COUNTER, "examples", "sum", "eval",
        "top-5 correct examples (global)")

# --- host-derived telemetry (harness epilogues / exporters) -------------
declare("throughput/examples_per_sec", GAUGE, "examples/s", "mean", "host",
        "global training throughput over the window")
declare("throughput/tokens_per_sec", GAUGE, "tokens/s", "mean", "host",
        "global token throughput over the window")
declare("throughput/model_tflops_per_chip", GAUGE, "tflops", "mean", "host",
        "model (fwd+bwd) TFLOP/s per chip at the measured rate")
declare("throughput/mfu", GAUGE, "ratio", "mean", "host",
        "model FLOPs utilisation vs the chip's bf16 peak")
declare("net/comm_mb_per_sec", GAUGE, "MB/s", "mean", "host",
        "analytic per-chip gradient-sync link traffic at the measured rate")
declare("net/payload_mb_per_step", GAUGE, "MB", "mean", "host",
        "wire payload per step from comm/sent_bits (NetMeter window mean)")
declare("net/allreduce_gbps_per_chip", GAUGE, "Gb/s", "mean", "host",
        "per-chip ring-allreduce traffic rate over the NetMeter window")
declare("net/compression_frac", GAUGE, "ratio", "mean", "host",
        "wire payload / dense gradient bytes over the NetMeter window")
declare("net/dcn_mb_per_step", GAUGE, "MB", "mean", "host",
        "per-chip bytes crossing the inter-pod DCN fabric per step "
        "(hierarchical transport; 0 on a flat mesh)")
declare("net/dcn_gbps_per_chip", GAUGE, "Gb/s", "mean", "host",
        "per-chip DCN traffic rate over the NetMeter window — the number "
        "to hold under the inter-pod link budget")
declare("net/ici_gbps_per_chip", GAUGE, "Gb/s", "mean", "host",
        "per-chip intra-pod ICI traffic rate over the NetMeter window")
declare("net/recv_gbit_s", GAUGE, "Gb/s", "mean", "host",
        "received Gbit/s at the measured step rate (TB net/ tab parity "
        "with the reference's in_gb counters)")
declare("net/transmit_gbit_s", GAUGE, "Gb/s", "mean", "host",
        "transmitted Gbit/s at the measured step rate")
declare("guard/skip_rate", GAUGE, "ratio", "mean", "host",
        "vetoed-step fraction over the logging window "
        "(windowed mean of guard/nonfinite)")
declare("time/step_p50_ms", TIMING, "ms", "mean", "host",
        "median host-observed step latency over the timeline window")
declare("time/step_p95_ms", TIMING, "ms", "mean", "host",
        "p95 host-observed step latency")
declare("time/step_p99_ms", TIMING, "ms", "mean", "host",
        "p99 host-observed step latency")
declare("time/data_wait_frac", GAUGE, "ratio", "mean", "host",
        "fraction of step wall time spent waiting on the input pipeline")
declare("time/steps_per_sec", GAUGE, "steps/s", "mean", "host",
        "host-observed step rate over the timeline window")

# --- elastic runtime (train/elastic.py; every survivor derives identical
#     values from the same coordinated failure, hence max = identity) ----
declare("elastic/peer_failures", COUNTER, "workers", "max", "host",
        "workers declared dead over the run (gossip, fetch timeout, or "
        "chaos mid-collective kill)")
declare("elastic/remesh_count", COUNTER, "remeshes", "max", "host",
        "completed W -> W-1 (or readmission) remesh barriers")
declare("elastic/dropped_ef_norm", COUNTER, "l2", "max", "host",
        "L2 norm of departed workers' EF residual mass discarded under "
        "the drop policy (0 under fold)")
declare("elastic/remesh_latency_ms", TIMING, "ms", "mean", "host",
        "host latency of the latest remesh (state migration + re-place)")
declare("elastic/remesh_ms", TIMING, "ms", "max", "host",
        "cumulative training downtime spent in elastic world transitions "
        "(remesh + rendezvous re-init + readmission) over the run")

# --- checkpoint subsystem (utils/checkpoint.py; host-side) --------------
declare("ckpt/save_ms", TIMING, "ms", "mean", "host",
        "wall time of the newest committed checkpoint write (Orbax save + "
        "manifest commit + GC; runs on a background thread for save_async)")
declare("ckpt/blocked_ms", TIMING, "ms", "max", "host",
        "cumulative step-loop time spent barriered on an in-flight async "
        "checkpoint write (a save/drain overlapping the previous one)")
declare("ckpt/inflight", GAUGE, "writes", "max", "host",
        "1 while a background checkpoint write is in flight, else 0")
declare("ckpt/last_step", GAUGE, "steps", "max", "host",
        "train step of the newest committed checkpoint (-1 before the "
        "first commit)")
declare("ckpt/age_s", GAUGE, "s", "max", "host",
        "seconds since the newest committed checkpoint (since the "
        "checkpointer opened, before the first commit)")
declare("ckpt/rollback_steps", COUNTER, "steps", "max", "host",
        "steps walked back past corrupt/unreadable checkpoints to reach "
        "the newest verifiable one at restore time")

# --- delta state streaming (stream/; host-side — writer counters on the
#     training ranks, reader gauges on stream_serve consumers) -----------
declare("stream/segments", COUNTER, "segments", "max", "host",
        "delta/keyframe segments committed to the stream directory over "
        "the writer's lifetime")
declare("stream/keyframes", COUNTER, "segments", "max", "host",
        "full-keyframe segments among the committed total (window anchors "
        "plus forced re-anchors after remesh/checkpoint)")
declare("stream/bytes", COUNTER, "bytes", "max", "host",
        "cumulative payload bytes across all committed segments (the "
        "steady-state stream cost BENCH compares against full "
        "checkpoint bytes)")
declare("stream/keyframe_bytes", COUNTER, "bytes", "max", "host",
        "payload bytes spent on full keyframes (the dense fraction of "
        "stream/bytes)")
declare("stream/append_ms", TIMING, "ms", "mean", "host",
        "commit wall time of the newest segment (payload + digest + "
        "manifest + head; background thread for append_async)")
declare("stream/residual_norm", GAUGE, "norm", "mean", "host",
        "L2 norm of the writer's untransmitted drift (params minus "
        "last_streamed); exactly 0 after a keyframe or window flush")
declare("stream/last_step", GAUGE, "steps", "max", "host",
        "train step of the newest committed segment on the writer, or "
        "the newest applied segment on a reader (-1 before the first)")
declare("stream/lag_s", GAUGE, "s", "max", "host",
        "reader staleness: seconds since the newest applied segment's "
        "write timestamp (-1 before anything applied)")
declare("stream/rejoin_bytes", GAUGE, "bytes", "max", "host",
        "bytes a warm rejoin moved over the delta stream in place of the "
        "full params broadcast (0 = no warm rejoin yet)")
declare("stream/corrupt_segments", COUNTER, "segments", "max", "host",
        "segments a reader rejected at verification (each triggers a "
        "walk-back to the last keyframe)")

# --- adaptive compression control plane (control/; host-side — every
#     worker's controller consumes identical psum'd metrics, so values are
#     identical across workers) -------------------------------------------
declare("control/rung", GAUGE, "index", "mean", "host",
        "current compression-ladder position (0 = least compressed)")
declare("control/value", GAUGE, "knob", "mean", "host",
        "active rung's knob value (keep ratio, or PowerSGD rank)")
declare("control/decisions", COUNTER, "windows", "max", "host",
        "decision windows closed so far (the control_decision event cursor)")
declare("control/window_updates", GAUGE, "updates", "mean", "host",
        "applied updates accumulated in the open decision window")
declare("control/comm_ms", TIMING, "ms", "mean", "host",
        "open window's mean per-update comm-time signal (modeled: billed "
        "bits over configured bandwidth; measured: timeline)")
declare("control/budget_ms", TIMING, "ms", "mean", "host",
        "open window's mean per-update hideable-compute budget")

# --- scale-out digital twin (twin/; host-side — the calibrated
#     alpha/beta/gamma cost model pricing the run's comm, exported when
#     the controller runs with --adaptive_model twin) --------------------
declare("twin/pred_step_ms", TIMING, "ms", "mean", "host",
        "twin-modeled step time at the open window's mean billed bits: "
        "the calibrated context's compute anchor plus the priced comm")
declare("twin/pred_err_frac", GAUGE, "frac", "mean", "host",
        "relative discrepancy between the twin's comm price and the flat "
        "--adaptive_bw_mbps price for the same billed bits (the audit "
        "signal tools/control_report.py tabulates)")
declare("twin/calib_rows", GAUGE, "rows", "max", "host",
        "calibration rows behind the twin's fitted fabric parameters")


# --- fleet control plane (fleet/scheduler.py; host-side — the scheduler
#     process is the single writer, per-job values carry a job="<id>"
#     label in the textfile exposition) ----------------------------------
declare("fleet/world", GAUGE, "devices", "max", "host",
        "devices currently assigned to this job (0 while waiting)")
declare("fleet/priority", GAUGE, "priority", "max", "host",
        "the job spec's admission/preemption priority")
declare("fleet/applied_updates", COUNTER, "updates", "max", "host",
        "the job's applied-update watermark as last reported by its "
        "controller poll")
declare("fleet/restarts", COUNTER, "restarts", "max", "host",
        "crash restarts burned from the job's budget (preemptions and "
        "evictions are free, like the watchdog's preempt accounting)")
declare("fleet/jobs_running", GAUGE, "jobs", "max", "host",
        "jobs currently holding devices")
declare("fleet/jobs_waiting", GAUGE, "jobs", "max", "host",
        "admitted jobs waiting for capacity (incl. evicted jobs queued "
        "for resume)")
declare("fleet/devices_free", GAUGE, "devices", "max", "host",
        "unassigned devices in the pool")
declare("fleet/evictions", COUNTER, "jobs", "max", "host",
        "priority preemptions executed over the fleet's lifetime "
        "(SIGTERM -> emergency save -> exit 75)")
declare("fleet/shrinks", COUNTER, "jobs", "max", "host",
        "elastic shrinks executed to fund higher-priority placements")
declare("fleet/readmits", COUNTER, "jobs", "max", "host",
        "growth actions readmitting freed capacity into shrunken jobs "
        "through the elastic readmit barrier")


# --- flight recorder + live straggler detection (obs/flight.py;
#     host-side, observation-only — per-rank values) ---------------------
declare("flight/records", COUNTER, "records", "max", "host",
        "records accepted into the flight recorder's ring buffers over "
        "the process lifetime")
declare("flight/dumps", COUNTER, "bundles", "max", "host",
        "blackbox bundle dumps committed to the shared dir (>0 means a "
        "failure path fired)")
declare("flight/last_dump_step", GAUGE, "step", "max", "host",
        "global step of the most recent blackbox dump (-1 = none)")
declare("straggler/skew_s", GAUGE, "s", "max", "host",
        "cross-rank skew of the mean host step time (slowest minus "
        "fastest rank, from the shared flight phase profiles)")
declare("straggler/rank", GAUGE, "rank", "max", "host",
        "the slowest rank by mean host step time (-1 when fewer than "
        "two ranks report)")
declare("straggler/frac", GAUGE, "frac", "max", "host",
        "straggler skew relative to the fastest rank's mean step time")


def canonical(key: str) -> str:
    """Map a raw engine stat key to its canonical registry name.

    The step factories prefix engine stats with ``comm/`` (guard/* keys
    pass through); this applies the same mapping so conformance checks can
    consume either form."""
    if key in REGISTRY or "/" in key:
        return key
    prefixed = f"comm/{key}"
    return prefixed if prefixed in REGISTRY else key


def is_declared(key: str) -> bool:
    return canonical(key) in REGISTRY


def spec(key: str) -> MetricSpec:
    return REGISTRY[canonical(key)]


def undeclared(keys: Iterable[str]) -> List[str]:
    """The subset of ``keys`` (raw or canonical) missing from the registry."""
    return sorted(k for k in keys if not is_declared(k))


def engine_diag_reductions() -> Dict[str, str]:
    """Raw engine keys whose cross-worker reduction is min/max — the 0/1
    diagnostics the partitioned sync must NOT psum over model axes.  Keyed
    by the raw (un-prefixed) name the engines emit; the single source the
    engine's diagnostic table is built from."""
    out = {}
    for name, ms in REGISTRY.items():
        if ms.emitter != "engine" or ms.reduction not in ("min", "max"):
            continue
        raw = name[len("comm/"):] if name.startswith("comm/") else name
        out[raw] = ms.reduction
    return out


def prometheus_name(key: str) -> str:
    """``comm/sent_bits`` -> ``tcdp_comm_sent_bits`` (exposition-safe)."""
    return "tcdp_" + re.sub(r"[^a-zA-Z0-9_]", "_", canonical(key))
