"""Unified telemetry subsystem.

  * :mod:`~tpu_compressed_dp.obs.registry` — typed metric registry: every
    stat key the system emits, declared once with kind/unit/cross-worker
    reduction; the conformance test fails on undeclared keys.
  * :mod:`~tpu_compressed_dp.obs.trace` — phase-level step tracing:
    ``jax.named_scope`` phase annotations through both sync engines, the
    sharded wire path and all three step factories, plus the host-side
    :class:`~tpu_compressed_dp.obs.trace.StepTimeline` ring buffer
    (p50/p95/p99 step latency, data-wait fraction, step rate).
  * :mod:`~tpu_compressed_dp.obs.export` — schema-versioned JSONL event
    stream, Prometheus textfile exporter, and the heartbeat telemetry
    snapshot consumed by ``tools/watchdog.py --check``.
"""

from tpu_compressed_dp.obs import export, registry, trace
from tpu_compressed_dp.obs.export import (EventStream, SCHEMA_VERSION,
                                          read_events, telemetry_snapshot,
                                          write_prometheus)
from tpu_compressed_dp.obs.registry import MetricSpec
from tpu_compressed_dp.obs.trace import PHASES, StepTimeline, phase

__all__ = [
    "registry", "trace", "export",
    "MetricSpec", "PHASES", "StepTimeline", "phase",
    "EventStream", "SCHEMA_VERSION", "read_events", "telemetry_snapshot",
    "write_prometheus",
]
