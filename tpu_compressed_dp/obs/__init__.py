"""Unified telemetry subsystem.

  * :mod:`~tpu_compressed_dp.obs.registry` — typed metric registry: every
    stat key the system emits, declared once with kind/unit/cross-worker
    reduction; the conformance test fails on undeclared keys.
  * :mod:`~tpu_compressed_dp.obs.trace` — phase-level step tracing:
    ``jax.named_scope`` phase annotations through both sync engines, the
    sharded wire path and all three step factories, plus the host-side
    :class:`~tpu_compressed_dp.obs.trace.StepTimeline` ring buffer
    (p50/p95/p99 step latency, data-wait fraction, step rate).
  * :mod:`~tpu_compressed_dp.obs.export` — schema-versioned JSONL event
    stream, Prometheus textfile exporter, and the heartbeat telemetry
    snapshot consumed by ``tools/watchdog.py --check``.
  * :mod:`~tpu_compressed_dp.obs.flight` — per-rank flight recorder:
    bounded ring buffers over every telemetry stream, atomic
    ``blackbox.rank<R>.json`` dumps on the failure paths, and the live
    cross-rank ``straggler/*`` gauges; ``tools/postmortem.py`` merges the
    bundles offline into a root-cause verdict.
"""

from tpu_compressed_dp.obs import export, flight, registry, trace
from tpu_compressed_dp.obs.export import (EventStream, SCHEMA_VERSION,
                                          read_events, telemetry_snapshot,
                                          write_prometheus)
from tpu_compressed_dp.obs.flight import (FLIGHT_SCHEMA, FlightRecorder,
                                          classify_failure, read_bundles,
                                          straggler_gauges, validate_bundle)
from tpu_compressed_dp.obs.registry import MetricSpec
from tpu_compressed_dp.obs.trace import PHASES, StepTimeline, phase

__all__ = [
    "registry", "trace", "export", "flight",
    "MetricSpec", "PHASES", "StepTimeline", "phase",
    "EventStream", "SCHEMA_VERSION", "read_events", "telemetry_snapshot",
    "write_prometheus",
    "FLIGHT_SCHEMA", "FlightRecorder", "classify_failure", "read_bundles",
    "straggler_gauges", "validate_bundle",
]
