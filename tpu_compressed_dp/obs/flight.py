"""Per-rank flight recorder: bounded rings + atomic blackbox bundles.

When one of the stack's five failure planes fires (guard, elastic,
preemption, checkpoint corruption, fleet eviction) the evidence of *why*
— which rank saw the NaN first, whose heartbeat went stale, what the
controller decided two windows ago — normally evaporates with the
process.  The :class:`FlightRecorder` keeps the last N records of every
stream already flowing through the system in O(capacity) ring buffers
(one per :data:`CHANNELS` entry, ``deque(maxlen=...)`` like
:class:`~tpu_compressed_dp.obs.trace.StepTimeline`), and every failure
path dumps them as one atomic, schema-versioned
``blackbox.rank<R>.json`` bundle into the shared dir before dying.
``tools/postmortem.py`` merges the per-rank bundles offline into a
cross-rank timeline and names the root cause.

Straggler detection also runs *live*: :meth:`FlightRecorder.publish`
writes this rank's per-phase host-timing profile
(``flight.rank<R>.phases.json``, atomic) next to its peers', reads them
all back and returns the ``straggler/*`` gauges — cross-rank skew of the
mean host step time — which the harnesses feed to heartbeat and
Prometheus so ``watchdog --check --max_straggler_skew`` and the fleet
scheduler can act on a slow rank *before* it wedges a collective.

House rules (enforced by tcdp-lint): the recorder is wall-clock-free —
timestamps come from an injectable ``clock`` (monotonic by default) so
replay-deterministic callers stay deterministic (TCDP101); all ring and
counter mutation is lock-guarded because the async checkpointer's
background writer tees ``ckpt_save`` records in from its own thread
(TCDP105); and both the bundle dump and the phase profile commit via
``<path>.<pid>.tmp`` + ``os.replace`` so a concurrently-reading
postmortem or scraper never sees a torn file (TCDP102).  Recording is
observation-only: no device collectives, no effect on the training
trajectory.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "FLIGHT_SCHEMA", "CHANNELS", "FlightRecorder", "classify_failure",
    "bundle_path", "read_bundles", "validate_bundle", "describe_error",
    "profile_path", "profile_from_spans", "write_phase_profile",
    "read_phase_profiles", "straggler_gauges",
]

#: Bump when a bundle field's meaning changes incompatibly; consumers
#: (tools/postmortem.py, the forensics drill) check it before interpreting.
FLIGHT_SCHEMA = 1

#: One bounded ring per channel:
#:   step     per-step scalar metrics (epoch-end fetched, host floats)
#:   guard    guard counters split out of the step metrics (skip streaks)
#:   control  adaptive-compression ``control_decision`` payloads
#:   elastic  gossip / remesh / readmit transitions
#:   ckpt     checkpoint lifecycle (save / rollback / prune)
#:   chaos    armed fault-injection specs (what WAS configured to misfire)
#:   timing   per-phase host spans drained from the StepTimeline
#:   fault    observed exceptions (the dump trigger trail)
#:   stream   delta-stream lifecycle (keyframe / flush / warm rejoin)
CHANNELS = ("step", "guard", "control", "elastic", "ckpt", "chaos",
            "timing", "fault", "stream")

#: exception class name (anywhere in the MRO) -> bundle ``reason``;
#: matched by NAME so this module imports none of the failure planes
#: (guard/elastic/resilience/checkpoint all import freely from obs).
_FAILURE_KINDS = (
    ("GuardExceeded", "guard_exceeded"),
    ("PeerFailed", "peer_failed"),
    ("Preempted", "preempt"),
    ("CheckpointCorrupt", "ckpt_corrupt"),
    ("ChaosCrash", "chaos_crash"),
)

#: attributes lifted verbatim off an exception into the bundle's error
#: record when present — the union of what the five failure planes carry.
_ERROR_ATTRS = ("step", "worker", "failed", "signum", "mode", "reason",
                "phase")

_BUNDLE_RE = re.compile(r"^blackbox\.rank(\d+)\.json$")
_PROFILE_RE = re.compile(r"^flight\.rank(\d+)\.phases\.json$")


def classify_failure(exc: BaseException) -> str:
    """Map an exception to a bundle ``reason`` by MRO class name (see
    :data:`_FAILURE_KINDS`); anything unrecognised is ``"error"``."""
    names = {c.__name__ for c in type(exc).__mro__}
    for cls_name, reason in _FAILURE_KINDS:
        if cls_name in names:
            return reason
    return "error"


def describe_error(exc: BaseException) -> Dict[str, Any]:
    """JSON-safe error record: type, truncated message, and whichever of
    the failure planes' well-known attributes the exception carries."""
    rec: Dict[str, Any] = {
        "type": type(exc).__name__,
        "message": str(exc)[:500],
    }
    for attr in _ERROR_ATTRS:
        val = getattr(exc, attr, None)
        if val is None:
            continue
        if isinstance(val, tuple):
            val = list(val)
        if isinstance(val, (int, float, str, bool, list)):
            rec[attr] = val
    return rec


def _jsonable(val: Any) -> Any:
    """Coerce a record field to something json.dumps accepts (device
    scalars arrive via ``float()``-able duck types; everything else is
    stringified rather than dropped — forensics wants lossy over silent)."""
    if val is None or isinstance(val, (bool, int, float, str)):
        return val
    if isinstance(val, (list, tuple)):
        return [_jsonable(v) for v in val]
    if isinstance(val, dict):
        return {str(k): _jsonable(v) for k, v in val.items()}
    try:
        return float(val)
    except (TypeError, ValueError):
        return str(val)[:200]


def bundle_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"blackbox.rank{int(rank)}.json")


def profile_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"flight.rank{int(rank)}.phases.json")


class FlightRecorder:
    """Bounded multi-channel ring recorder for one rank.

    ``capacity`` bounds EVERY channel ring, so memory is O(channels x
    capacity) regardless of run length.  ``clock`` is the injection seam
    (monotonic by default — bundle timestamps are relative, merge order
    across ranks comes from per-record ``seq`` plus the trigger step).
    ``directory=None`` disables dumping (records still accumulate, and
    :meth:`metrics` still exports) so tests and dry runs need no shared
    dir.
    """

    def __init__(self, rank: int = 0, capacity: int = 256,
                 directory: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 meta: Optional[Dict[str, Any]] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.rank = int(rank)
        self.capacity = int(capacity)
        self.directory = directory
        self.meta = dict(meta or {})
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._rings: Dict[str, collections.deque] = {
            ch: collections.deque(maxlen=capacity) for ch in CHANNELS}
        self._seq = 0
        self._records = 0
        self._dumps = 0
        self._last_dump_step = -1

    # ------------------------------------------------------------ recording

    def record(self, channel: str, kind: str, **fields: Any) -> None:
        """Append one record to ``channel``'s ring.  Unknown channels
        raise — a typo here would silently lose forensic evidence."""
        if channel not in self._rings:
            raise ValueError(f"unknown flight channel {channel!r}; "
                             f"expected one of {CHANNELS}")
        rec = {"kind": kind, "t": self._clock() - self._t0}
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            self._records += 1
            self._rings[channel].append(rec)

    def note_step(self, step: int, metrics: Optional[Dict[str, Any]] = None
                  ) -> None:
        """One fetched per-step metrics dict; guard counters split into
        the ``guard`` ring so the postmortem NaN-origin scan stays O(N)."""
        metrics = metrics or {}
        guard = {k: metrics[k] for k in metrics if k.startswith("guard/")}
        rest = {k: metrics[k] for k in metrics if not k.startswith("guard/")}
        self.record("step", "metrics", step=int(step), metrics=rest)
        if guard:
            self.record("guard", "counters", step=int(step), metrics=guard)

    def note_spans(self, spans: List[Dict[str, float]]) -> None:
        """Per-step host spans drained from the StepTimeline (data /
        dispatch / total splits) — the straggler evidence."""
        for span in spans:
            self.record("timing", "span",
                        **{k: span[k] for k in span if k != "t0"})

    def note_chaos(self, cfg: Any) -> None:
        """The armed fault-injection scenario (a ChaosConfig, its spec
        string, or None).  Recording what was CONFIGURED to misfire is
        what lets postmortem name the injected worker exactly."""
        if cfg is None:
            return
        if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
            fields = {k: v for k, v in dataclasses.asdict(cfg).items()
                      if v is not None}
            # the fault kind ('nan'/'inf') becomes the record kind — the
            # postmortem NaN-origin scan matches on it directly
            self.record("chaos", str(fields.pop("kind", "armed")), **fields)
        else:
            self.record("chaos", "armed", spec=str(cfg))

    def note_control(self, decision: Dict[str, Any]) -> None:
        self.record("control", "decision", **decision)

    # ------------------------------------------------------------- dumping

    def observe(self, exc: BaseException, step: Optional[int] = None,
                **extra: Any) -> Optional[str]:
        """Record a failure-plane exception into the ``fault`` ring and
        dump the blackbox bundle.  Returns the bundle path (None when no
        directory is configured).  Never raises: forensics must not mask
        the failure it is documenting."""
        reason = classify_failure(exc)
        err = describe_error(exc)
        if step is None:
            step = err.get("step")
        try:
            self.record("fault", reason, step=step, error=err, **extra)
        except Exception:
            pass
        return self.dump(reason, error=err, step=step, extra=extra or None)

    def dump(self, reason: str, *, error: Optional[Dict[str, Any]] = None,
             step: Optional[int] = None,
             extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Atomically write ``blackbox.rank<R>.json`` (tmp + os.replace).
        Best-effort by design: returns None on any I/O error — the
        process is usually dying and the original exception must win."""
        if not self.directory:
            return None
        with self._lock:
            body = {
                "v": FLIGHT_SCHEMA,
                "kind": "blackbox",
                "rank": self.rank,
                "reason": reason,
                "step": None if step is None else int(step),
                "seq": self._seq,
                "capacity": self.capacity,
                "meta": _jsonable(self.meta),
                "error": error,
                "extra": _jsonable(extra) if extra else None,
                "counts": {"records": self._records,
                           "dumps": self._dumps + 1},
                "rings": {ch: list(ring)
                          for ch, ring in self._rings.items()},
            }
            self._dumps += 1
            if step is not None:
                self._last_dump_step = int(step)
        path = bundle_path(self.directory, self.rank)
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(body, f)
            os.replace(tmp, path)
        except OSError:
            return None
        return path

    # ------------------------------------------------------------ exports

    def snapshot(self) -> Dict[str, Any]:
        """A consistent copy of every ring plus the counters (test /
        debug surface; the dump is this plus the trigger context)."""
        with self._lock:
            return {
                "rank": self.rank,
                "seq": self._seq,
                "records": self._records,
                "dumps": self._dumps,
                "rings": {ch: list(ring)
                          for ch, ring in self._rings.items()},
            }

    def metrics(self) -> Dict[str, float]:
        """Registry-declared gauges for heartbeat / Prometheus."""
        with self._lock:
            return {
                "flight/records": float(self._records),
                "flight/dumps": float(self._dumps),
                "flight/last_dump_step": float(self._last_dump_step),
            }

    # --------------------------------------------------------- stragglers

    def phase_profile(self) -> Dict[str, Any]:
        """This rank's per-phase host-timing totals over the ``timing``
        ring window — the unit the cross-rank skew is computed from."""
        with self._lock:
            spans = list(self._rings["timing"])
        return profile_from_spans(self.rank, spans)

    def publish(self) -> Dict[str, float]:
        """Write this rank's phase profile into the shared dir, read every
        peer's, and return the live ``straggler/*`` gauges.  With no
        directory (or alone in it) the gauges degrade to zero skew."""
        if not self.directory:
            return straggler_gauges({self.rank: self.phase_profile()})
        write_phase_profile(self.directory, self.rank, self.phase_profile())
        return straggler_gauges(read_phase_profiles(self.directory))


# ------------------------------------------------------------------ bundles

def read_bundles(directory: str) -> Dict[int, Dict[str, Any]]:
    """All parseable ``blackbox.rank<R>.json`` bundles in ``directory``,
    keyed by rank.  Unreadable/corrupt files are skipped (a half-written
    bundle from a rank that died mid-replace is expected, not fatal)."""
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        m = _BUNDLE_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                out[int(m.group(1))] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


def validate_bundle(rec: Dict[str, Any]) -> List[str]:
    """Schema check for one bundle; returns problem strings (empty =
    valid).  The forensics drill runs every dumped bundle through this."""
    problems: List[str] = []
    if rec.get("v") != FLIGHT_SCHEMA:
        problems.append(f"schema version {rec.get('v')!r} != {FLIGHT_SCHEMA}")
    if rec.get("kind") != "blackbox":
        problems.append(f"kind {rec.get('kind')!r} != 'blackbox'")
    if not isinstance(rec.get("rank"), int) or rec["rank"] < 0:
        problems.append(f"bad rank {rec.get('rank')!r}")
    if not isinstance(rec.get("reason"), str) or not rec.get("reason"):
        problems.append("missing reason")
    rings = rec.get("rings")
    if not isinstance(rings, dict):
        problems.append("missing rings")
        return problems
    for ch, ring in rings.items():
        if ch not in CHANNELS:
            problems.append(f"unknown channel {ch!r}")
            continue
        if not isinstance(ring, list):
            problems.append(f"channel {ch!r} is not a list")
            continue
        cap = rec.get("capacity")
        if isinstance(cap, int) and len(ring) > cap:
            problems.append(f"channel {ch!r} overflows capacity {cap}")
        for i, r in enumerate(ring):
            if not isinstance(r, dict) or "kind" not in r or "seq" not in r:
                problems.append(f"channel {ch!r} record {i} malformed")
                break
    return problems


# ----------------------------------------------------------- phase profiles

def profile_from_spans(rank: int, spans: List[Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """Aggregate per-step span records (live ``timing``-ring entries or a
    dumped bundle's ring) into one rank's phase profile — every numeric
    field summed, ``steps`` counted.  Shared with ``tools/postmortem.py``
    so the live gauges and the offline verdict use one definition."""
    phases: Dict[str, float] = {}
    for rec in spans:
        for k, v in rec.items():
            if k in ("kind", "t", "seq") or not isinstance(
                    v, (int, float)) or isinstance(v, bool):
                continue
            phases[k] = phases.get(k, 0.0) + float(v)
    return {"v": FLIGHT_SCHEMA, "rank": int(rank),
            "steps": len(spans), "phases": phases}


def write_phase_profile(directory: str, rank: int,
                        profile: Dict[str, Any]) -> str:
    """Atomic (tmp + replace) per-rank profile write; peers and the
    postmortem read these concurrently."""
    os.makedirs(directory, exist_ok=True)
    path = profile_path(directory, rank)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(profile, f)
    os.replace(tmp, path)
    return path


def read_phase_profiles(directory: str) -> Dict[int, Dict[str, Any]]:
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        m = _PROFILE_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                out[int(m.group(1))] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


def _mean_step_total(profile: Dict[str, Any]) -> Optional[float]:
    steps = profile.get("steps") or 0
    phases = profile.get("phases") or {}
    total = phases.get("total")
    if not steps or not isinstance(total, (int, float)):
        return None
    return float(total) / float(steps)


def straggler_gauges(profiles: Dict[int, Dict[str, Any]]
                     ) -> Dict[str, float]:
    """Cross-rank skew of the mean host step time.

    ``straggler/skew_s``  max - min mean step seconds across ranks
    ``straggler/rank``    the slowest rank (-1 when < 2 ranks report)
    ``straggler/frac``    skew relative to the fastest rank's mean

    Single-rank (or empty) input degrades to zero skew / rank -1, so the
    gauges are always exportable.
    """
    means = {r: m for r, m in
             ((r, _mean_step_total(p)) for r, p in profiles.items())
             if m is not None}
    if len(means) < 2:
        return {"straggler/skew_s": 0.0, "straggler/rank": -1.0,
                "straggler/frac": 0.0}
    slow = max(means, key=lambda r: means[r])
    fast = min(means, key=lambda r: means[r])
    skew = means[slow] - means[fast]
    frac = skew / means[fast] if means[fast] > 0 else 0.0
    return {"straggler/skew_s": skew, "straggler/rank": float(slow),
            "straggler/frac": frac}
