"""Phase-level step tracing: in-graph annotations + a host-side timeline.

Two complementary views of where a step's time goes:

  * **Device view** — :func:`phase` wraps each pipeline phase (compress /
    ef / route / reduce / return / update, :data:`PHASES`) in a
    ``jax.named_scope``, so XLA op names — and therefore xprof/tensorboard
    traces — attribute device time to named phases instead of a soup of
    fused ops.  Zero runtime cost: named scopes exist only at trace time.
  * **Host view** — :class:`StepTimeline` is a ring buffer of per-step
    host timings that JAX's async dispatch CAN honestly observe without
    stalling the pipeline: input-pipeline wait and dispatch time every
    step, plus an optional sampled device-drain measurement
    (``device_sync_every``) that closes the async gap at a chosen cadence.
    It yields p50/p95/p99 step latency, the data-wait fraction, and the
    step rate — the numbers the heartbeat telemetry snapshot and the JSONL
    event stream carry.

This is the measurement layer the paper's thesis needs: compression claims
are stated in bits, but they live or die on *seconds per phase*
(Near-Optimal Sparse Allreduce, arXiv:2201.07598, makes the same move).
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Dict, List, Optional

import jax

__all__ = ["PHASES", "phase", "chunk", "host_span", "StepTimeline",
           "percentile"]

#: The phase taxonomy — every named scope the engines and step factories
#: emit uses one of these (xprof filters on the ``tcdp.`` prefix):
#:   grad      forward + backward of the model
#:   ef        error-feedback residual accumulation
#:   compress  compression operator (top-k / quantize / low-rank factor)
#:   route     sharded transport: per-destination bucketing + all_to_all
#:   reduce    the reduction collective (psum / owner scatter-add)
#:   return    un-flatten / shard-return all_gather back to leaf shapes
#:   update    optimizer apply
#:   ici_reduce  hierarchical transport: dense intra-pod psum (both the
#:             contribution-in and combined-partial-out hops)
#:   recompress  hierarchical transport: pack + slice the pod-reduced
#:             gradient's nonzero union for the inter-pod exchange
PHASES = ("grad", "ef", "compress", "route", "reduce", "return", "update",
          "ici_reduce", "recompress")


def phase(name: str):
    """In-graph phase annotation: ``with phase('compress'): ...`` inside
    traced code names the enclosed ops ``tcdp.<name>/...`` in XLA dumps and
    xprof traces.  Usable anywhere (jit, shard_map, host code)."""
    return jax.named_scope(f"tcdp.{name}")


def chunk(index: int):
    """Per-chunk scope for the overlap subsystem
    (:mod:`tpu_compressed_dp.parallel.overlap`): chunk ``index``'s
    compress→route→reduce→return pipeline (and, in the fused train-step
    path, its optimizer-update slice) nests the :data:`PHASES` scopes under
    ``tcdp.chunk<ii>/``, so xprof — and the AOT schedule evidence
    (``tools/overlap_evidence.py``) — attribute each collective and each
    per-chunk ``tcdp.reduce`` / ``tcdp.update`` span to its chunk.  The
    index is the ISSUE order (0 = first dispatched = the reverse-topological
    head, i.e. the last parameters' gradients)."""
    return jax.named_scope(f"tcdp.chunk{index:02d}")


def host_span(name: str):
    """Host-side profiler annotation (``jax.profiler.TraceAnnotation``):
    marks a wall-clock span on the host timeline of a captured trace —
    for the parts of the loop that are NOT traced computation (input
    pipeline, checkpoint saves)."""
    return jax.profiler.TraceAnnotation(f"tcdp.{name}")


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an ascending list (empty -> 0.0) — the
    one percentile definition the live snapshot and the offline
    trace_report share."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class StepTimeline:
    """Ring buffer of per-step host timings.

    Protocol (driven by the epoch loop):

    >>> tl = StepTimeline()
    >>> for batch in batches:        # `next()` runs the input pipeline
    ...     tl.batch_ready()         # end of data wait
    ...     state, m = train_step(state, batch)
    ...     tl.step_dispatched()     # end of dispatch (async: device runs on)

    Each record splits the step into ``data`` (input-pipeline wait),
    ``dispatch`` (host time to trace-cache-hit + enqueue) and — on sampled
    steps when ``device_sync_every > 0`` — ``device`` (the drain measured
    by :func:`tpu_compressed_dp.utils.timer.device_sync`, which bounds the
    device work outstanding behind the dispatch).  Un-sampled steps carry
    ``device=None``; their ``total`` is the honest host-visible latency
    (under async dispatch the device cost surfaces as the NEXT dispatch
    blocking, so window-level aggregates stay truthful either way).

    Memory is O(``capacity``): the buffer holds the most recent steps only
    (the Timer-unbounded-append lesson, applied from day one).
    """

    def __init__(self, capacity: int = 1024, device_sync_every: int = 0,
                 clock: Callable[[], float] = time.perf_counter,
                 sync: Optional[Callable[[], None]] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.device_sync_every = device_sync_every
        self._clock = clock
        if sync is None:
            from tpu_compressed_dp.utils.timer import device_sync

            sync = device_sync
        self._sync = sync
        self.records: collections.deque = collections.deque(maxlen=capacity)
        # since last drain(); a ring like `records`, so on overflow both
        # keep the NEWEST spans and drained step_spans stay consistent
        # with the snapshot() computed over the same window
        self._pending: collections.deque = collections.deque(maxlen=capacity)
        self.steps = 0
        self._t = clock()   # step start = end of previous dispatch
        self._mark = self._t

    def resume(self) -> None:
        """Re-stamp the step-start mark, excluding everything since the
        last dispatch from the next step's ``data`` split.  Call on entry
        to a train loop/epoch and after any blocking between-step work
        (eval, checkpointing, a log-cadence ``device_get`` drain) —
        otherwise that wall time is billed as input-pipeline wait and
        corrupts ``data_wait_frac`` / the latency percentiles."""
        self._t = self._clock()
        self._mark = self._t
        self._data = 0.0

    def batch_ready(self) -> None:
        now = self._clock()
        self._data = now - self._t
        self._mark = now

    def step_dispatched(self) -> None:
        now = self._clock()
        rec: Dict[str, float] = {
            "t0": self._t,
            "data": getattr(self, "_data", now - self._t),
            "dispatch": now - self._mark,
        }
        self.steps += 1
        if self.device_sync_every and self.steps % self.device_sync_every == 0:
            self._sync()
            now2 = self._clock()
            rec["device"] = now2 - now
            now = now2
        rec["total"] = now - rec["t0"]
        self._t = now
        self._data = 0.0
        self.records.append(rec)
        self._pending.append(rec)

    # --- aggregates over the ring window --------------------------------

    def percentiles(self) -> Dict[str, float]:
        totals = sorted(r["total"] for r in self.records)
        return {"p50": percentile(totals, 0.50),
                "p95": percentile(totals, 0.95),
                "p99": percentile(totals, 0.99)}

    def data_wait_frac(self) -> float:
        tot = sum(r["total"] for r in self.records)
        if tot <= 0:
            return 0.0
        return sum(r["data"] for r in self.records) / tot

    def steps_per_sec(self) -> float:
        if len(self.records) < 1:
            return 0.0
        span = sum(r["total"] for r in self.records)
        return len(self.records) / span if span > 0 else 0.0

    def snapshot(self) -> Dict[str, float]:
        """The registry-named telemetry summary (heartbeat / event stream /
        Prometheus payload)."""
        p = self.percentiles()
        return {
            "time/step_p50_ms": p["p50"] * 1e3,
            "time/step_p95_ms": p["p95"] * 1e3,
            "time/step_p99_ms": p["p99"] * 1e3,
            "time/data_wait_frac": self.data_wait_frac(),
            "time/steps_per_sec": self.steps_per_sec(),
        }

    def drain(self) -> List[Dict[str, float]]:
        """Per-step records accumulated since the previous drain — the
        event stream attaches these to epoch/window records so
        tools/trace_report.py can rebuild the host timeline.  Ring-bounded
        at ``capacity``: a longer window keeps its NEWEST spans (the same
        window :meth:`snapshot` summarizes), dropping the head."""
        out = list(self._pending)
        self._pending.clear()
        return out
