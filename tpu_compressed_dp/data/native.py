"""ctypes bridge to the native image-geometry kernel.

Builds ``tpu_compressed_dp/native/image_ops.cpp`` on first use (g++ is part
of the toolchain; the .so is cached next to the source, keyed by a source
hash) and exposes :func:`crop_resize` — fused crop + PIL-BILINEAR resize +
horizontal flip on uint8 RGB arrays.  ctypes releases the GIL for the call,
so the loaders' thread pools parallelise across images.

Falls back cleanly: :func:`available` is False when no compiler exists or
the build fails, and the loaders keep their pure-PIL path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["available", "crop_resize", "build", "lib_path"]

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "image_ops.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_FAILED = False


def lib_path() -> str:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(os.path.dirname(_SRC), f"libimageops_{tag}.so")


def build(verbose: bool = False) -> str:
    """Compile the kernel if the cached .so is stale; returns the .so path."""
    out = lib_path()
    if not os.path.exists(out):
        cmd = ["g++", "-O3", "-fPIC", "-shared", "-pthread", _SRC, "-o", out]
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if res.returncode != 0:
            raise RuntimeError(f"native build failed: {res.stderr[-500:]}")
        if verbose:
            print(f"built {out}")
    return out


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _FAILED
    if _LIB is not None or _FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _FAILED:
            return _LIB
        try:
            lib = ctypes.CDLL(build())
        except Exception:
            _FAILED = True
            return None
        lib.crop_resize_bilinear.restype = ctypes.c_int
        lib.crop_resize_bilinear.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        _LIB = lib
    return _LIB


def available() -> bool:
    return _load() is not None


def crop_resize(src: np.ndarray, box: Tuple[float, float, float, float],
                out_h: int, out_w: int, flip: bool = False) -> np.ndarray:
    """Crop ``box`` (x0, y0, x1, y1) from an HWC uint8 RGB array, resize to
    (out_h, out_w) with PIL-BILINEAR semantics, optionally mirror."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native image ops unavailable (build failed?)")
    src = np.ascontiguousarray(src, dtype=np.uint8)
    if src.ndim != 3 or src.shape[2] != 3:
        raise ValueError(f"expected HWC RGB uint8, got {src.shape}")
    dst = np.empty((out_h, out_w, 3), np.uint8)
    rc = lib.crop_resize_bilinear(
        src.ctypes.data, src.shape[0], src.shape[1],
        float(box[0]), float(box[1]), float(box[2]), float(box[3]),
        dst.ctypes.data, out_h, out_w, int(bool(flip)),
    )
    if rc != 0:
        raise RuntimeError(f"crop_resize_bilinear failed with code {rc}")
    return dst
