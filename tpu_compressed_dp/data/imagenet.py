"""ImageNet-style data pipeline: datasets, train/val loaders, rect-val.

Re-implements the behaviors of `IMAGENET/training/dataloader.py` the TPU way:

  * ``TrainLoader`` = RandomResizedCrop + horizontal flip + ``fast_collate``
    (`dataloader.py:26-57,101-115`): batches are raw uint8 NHWC; the
    mean/std normalisation happens *inside* the compiled step (see
    ``make_normalizing_apply_fn`` in the ImageNet harness), so only 1 byte per
    pixel crosses the host->device wire, like the reference's GPU-side
    ``BatchTransformDataLoader`` (`dataloader.py:76-99`).
  * ``ValLoader`` = ``DistValSampler`` semantics (`dataloader.py:133-161`):
    every process yields exactly ``expected_num_batches`` batches, padding
    with *empty* batches when it runs out of images, so the per-batch global
    collective in the eval step never deadlocks and every image is seen
    exactly once.
  * ``rect_val=True`` = aspect-ratio-sorted rectangular validation
    (`sort_ar` `dataloader.py:178-188`, ``CropArTfm`` `:164-175`) — but with
    the batch aspect ratios quantised into ``ar_buckets`` distinct shapes, so
    the number of XLA compilations stays bounded (the reference paid a cudnn
    re-benchmark per shape instead).
  * sharding across hosts = ``DistributedSampler`` semantics
    (`dataloader.py:33`): per-epoch seeded global permutation, strided split.

Datasets expose ``__len__`` / ``size(i)->(w,h)`` / ``load(i)->PIL RGB`` /
``label(i)``.  ``ImageFolder`` reads a torchvision-layout directory tree;
``SyntheticImages`` is the zero-egress stand-in (deterministic, class-colored
so smoke models actually learn).
"""

from __future__ import annotations

import math
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

try:
    from PIL import Image
except ImportError:  # pragma: no cover - PIL is baked into the image
    Image = None

__all__ = [
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "SyntheticImages",
    "ImageFolder",
    "TrainLoader",
    "ValLoader",
    "val_batch_size",
]

# 0-255 scale: loaders produce uint8, the step normalises on device
# (`dataloader.py:90-99` keeps mean/std on GPU the same way).
IMAGENET_MEAN = (0.485 * 255, 0.456 * 255, 0.406 * 255)
IMAGENET_STD = (0.229 * 255, 0.224 * 255, 0.225 * 255)

_IMG_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}


class SyntheticImages:
    """Deterministic fake ImageFolder: varied sizes/aspect ratios (so rect-val
    paths are exercised), class-dependent color (so smoke training converges).
    """

    def __init__(self, n: int, num_classes: int = 1000, seed: int = 0,
                 base_size: int = 48):
        self.n = int(n)
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        self.base_size = int(base_size)
        rng = np.random.default_rng([seed, 0x5E7])
        self._labels = rng.integers(0, num_classes, size=self.n).astype(np.int64)
        # per-image (w, h): aspect ratios in [1/2, 2]
        ar = np.exp(rng.uniform(-math.log(2), math.log(2), size=self.n))
        scale = rng.uniform(0.8, 1.6, size=self.n)
        self._w = np.maximum((base_size * scale * np.sqrt(ar)).astype(int), 8)
        self._h = np.maximum((base_size * scale / np.sqrt(ar)).astype(int), 8)
        # one base color per class, spread over the hue-ish cube
        crng = np.random.default_rng([seed, 0xC01])
        self._colors = crng.integers(32, 224, size=(num_classes, 3))

    def __len__(self) -> int:
        return self.n

    def size(self, i: int) -> Tuple[int, int]:
        return int(self._w[i]), int(self._h[i])

    def label(self, i: int) -> int:
        return int(self._labels[i])

    def load(self, i: int):
        w, h = self.size(i)
        rng = np.random.default_rng([self.seed, 0x1A6, i])
        noise = rng.integers(-32, 32, size=(h, w, 3))
        img = np.clip(self._colors[self._labels[i]] + noise, 0, 255).astype(np.uint8)
        return Image.fromarray(img, "RGB")


class ImageFolder:
    """torchvision-layout tree: ``root/<class>/<image>``; labels are the sorted
    class-directory index (matches the reference's ``datasets.ImageFolder``,
    `dataloader.py:30,44`).

    Image sizes (the rect-val AR index) are scanned with a thread pool —
    header-only reads, IO-bound — and persisted to ``.tpu_cdp_sizes.npz``
    under ``root`` (falling back to ``~/.cache/tpu_compressed_dp`` for
    read-only datasets), the role of the reference's ``sort_ar`` pickle
    (`dataloader.py:178-188`): cold scan O(seconds) parallel, warm loads
    O(ms), instead of 50k serial PIL opens per run (VERDICT r2 #7).
    """

    SIZE_CACHE = ".tpu_cdp_sizes.npz"

    def __init__(self, root: str, *, size_cache: bool = True,
                 scan_workers: int = 16):
        self.root = root
        classes = sorted(
            e.name for e in os.scandir(root) if e.is_dir()
        )
        if not classes:
            raise FileNotFoundError(f"no class directories under {root}")
        self.classes = classes
        self.samples: List[Tuple[str, int]] = []
        for ci, cname in enumerate(classes):
            cdir = os.path.join(root, cname)
            for e in sorted(os.scandir(cdir), key=lambda e: e.name):
                if os.path.splitext(e.name)[1].lower() in _IMG_EXTS:
                    self.samples.append((e.path, ci))
        self._sizes: Dict[int, Tuple[int, int]] = {}
        self._bulk: Optional[np.ndarray] = None
        self._use_cache = bool(size_cache)
        self._scan_workers = max(int(scan_workers), 1)

    def __len__(self) -> int:
        return len(self.samples)

    def _cache_paths(self) -> List[str]:
        """Candidate cache locations: in-tree first (travels with the data,
        like the reference's pickle next to the val dir), then a per-root
        user-cache fallback for read-only mounts."""
        import hashlib

        in_tree = os.path.join(self.root, self.SIZE_CACHE)
        key = hashlib.md5(os.path.abspath(self.root).encode()).hexdigest()[:16]
        home = os.path.join(os.path.expanduser("~"), ".cache",
                            "tpu_compressed_dp", f"sizes-{key}.npz")
        return [in_tree, home]

    def _rel_paths(self) -> np.ndarray:
        return np.asarray(
            [os.path.relpath(p, self.root) for p, _ in self.samples])

    def _file_bytes(self) -> np.ndarray:
        # part of the staleness fingerprint: an image re-encoded IN PLACE
        # (same name, different resolution) almost surely changes its byte
        # size — without this, rect-val would plan crops from stale ARs
        return np.asarray([os.path.getsize(p) for p, _ in self.samples],
                          np.int64)

    def _load_size_cache(self) -> Optional[np.ndarray]:
        for path in self._cache_paths():
            if not os.path.exists(path):
                continue
            try:
                with np.load(path, allow_pickle=False) as z:
                    paths, wh, nbytes = z["paths"], z["wh"], z["bytes"]
            except Exception:
                continue  # corrupt/old-format cache: rescan
            # exact sample-list + byte-size match or the cache is stale
            # (files added, removed, renamed, or replaced since the scan)
            if (paths.shape[0] == len(self.samples)
                    and np.array_equal(paths, self._rel_paths())
                    and np.array_equal(nbytes, self._file_bytes())):
                return wh.astype(np.int64)
        return None

    def _save_size_cache(self, wh: np.ndarray) -> None:
        for path in self._cache_paths():
            tmp = f"{path}.{os.getpid()}.tmp.npz"
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                # NB np.savez appends '.npz' unless the name already ends
                # with it — keep the suffix so os.replace finds the file
                np.savez_compressed(tmp, paths=self._rel_paths(), wh=wh,
                                    bytes=self._file_bytes())
                os.replace(tmp, path)  # atomic vs concurrent processes
                return
            except OSError:
                # read-only location or partial write: drop any half-written
                # temp before trying the next candidate
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                continue

    def sizes_bulk(self) -> np.ndarray:
        """All image sizes as ``[n, 2] (w, h)`` — cached on disk, scanned in
        parallel on a cold start."""
        if self._bulk is not None:
            return self._bulk
        cached = self._load_size_cache() if self._use_cache else None
        if cached is None:
            def header_size(sample: Tuple[str, int]) -> Tuple[int, int]:
                with Image.open(sample[0]) as im:  # header-only read
                    return im.size

            with ThreadPoolExecutor(max_workers=self._scan_workers) as pool:
                sizes = list(pool.map(header_size, self.samples))
            cached = np.asarray(sizes, np.int64)
            if self._use_cache:
                self._save_size_cache(cached)
        self._bulk = cached
        return self._bulk

    def size(self, i: int) -> Tuple[int, int]:
        if self._bulk is not None:
            return int(self._bulk[i, 0]), int(self._bulk[i, 1])
        if i not in self._sizes:
            with Image.open(self.samples[i][0]) as im:
                self._sizes[i] = im.size
        return self._sizes[i]

    def label(self, i: int) -> int:
        return self.samples[i][1]

    def load(self, i: int):
        with Image.open(self.samples[i][0]) as im:
            return im.convert("RGB")


def val_batch_size(sz: int, bs: int) -> int:
    """Validation batch floor per image size (`train_imagenet_nv.py:592-597`):
    small images leave memory headroom for bigger eval batches."""
    floor = 512 if sz <= 128 else (256 if sz <= 224 else 128)
    return max(bs, floor)


def _rrc_box(w: int, h: int, min_scale: float, rng: np.random.Generator):
    """torchvision ``RandomResizedCrop(scale=(min_scale, 1.0))`` box sampling
    (`dataloader.py:36-39`); returns (x0, y0, x1, y1)."""
    area = w * h
    for _ in range(10):
        target_area = area * rng.uniform(min_scale, 1.0)
        log_ratio = (math.log(3 / 4), math.log(4 / 3))
        ar = math.exp(rng.uniform(*log_ratio))
        cw = int(round(math.sqrt(target_area * ar)))
        ch = int(round(math.sqrt(target_area / ar)))
        if 0 < cw <= w and 0 < ch <= h:
            x0 = int(rng.integers(0, w - cw + 1))
            y0 = int(rng.integers(0, h - ch + 1))
            return (x0, y0, x0 + cw, y0 + ch)
    # fallback: center crop of the largest square
    side = min(w, h)
    x0, y0 = (w - side) // 2, (h - side) // 2
    return (x0, y0, x0 + side, y0 + side)


def _use_native(backend: str) -> bool:
    if backend == "pil":
        return False
    from tpu_compressed_dp.data import native

    if backend == "native":
        if not native.available():
            raise RuntimeError("backend='native' requested but the native "
                               "image kernel failed to build")
        return True
    return native.available()  # auto


def _center_crop_resize(img, out_w: int, out_h: int, enlarge: float = 1.0):
    """Proportional resize (shorter relative side scaled by ``enlarge``) then
    center crop to exactly (out_h, out_w) — ``Resize + CenterCrop`` for square
    val, ``CropArTfm`` (`dataloader.py:164-175`) for rect val."""
    w, h = img.size
    scale = max(out_w * enlarge / w, out_h * enlarge / h)
    rw, rh = max(int(round(w * scale)), out_w), max(int(round(h * scale)), out_h)
    img = img.resize((rw, rh), Image.BILINEAR)
    x0, y0 = (rw - out_w) // 2, (rh - out_h) // 2
    return img.crop((x0, y0, x0 + out_w, y0 + out_h))


def _collate(arrays: Sequence[np.ndarray], labels: Sequence[int],
             h: int, w: int) -> Dict[str, np.ndarray]:
    """``fast_collate`` (`dataloader.py:101-115`): stack to uint8 NHWC."""
    x = np.zeros((len(arrays), h, w, 3), np.uint8)
    for i, a in enumerate(arrays):
        x[i] = a
    return {"input": x, "target": np.asarray(labels, np.int64)}


class TrainLoader:
    """Sharded, seeded, augmenting train loader.

    Determinism contract: batches are a pure function of
    ``(seed, epoch, process_index)`` — iterating twice without ``set_epoch``
    replays the identical epoch (augmentation included), matching the
    reference's per-epoch ``sampler.set_epoch`` reshuffle (`dataloader.py:33`,
    `train_imagenet_nv.py:554`).
    """

    def __init__(self, dataset, batch_size: int, sz: int, *,
                 min_scale: float = 0.08, seed: int = 0, workers: int = 4,
                 process_index: int = 0, process_count: int = 1,
                 backend: str = "auto"):
        self.ds = dataset
        self.batch_size = int(batch_size)
        self.sz = int(sz)
        self.min_scale = float(min_scale)
        self.seed = int(seed)
        self.workers = max(int(workers), 1)
        self.pi, self.pc = int(process_index), int(process_count)
        self.epoch = 0
        self.native = _use_native(backend)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def __len__(self) -> int:
        return (len(self.ds) // self.pc) // self.batch_size

    def _decode(self, job: Tuple[int, int]) -> np.ndarray:
        idx, aug_seed = job
        rng = np.random.default_rng([self.seed, self.epoch, aug_seed])
        img = self.ds.load(idx)
        w, h = img.size
        box = _rrc_box(w, h, self.min_scale, rng)
        flip = rng.random() < 0.5  # RandomHorizontalFlip (`dataloader.py:38`)
        if self.native:
            from tpu_compressed_dp.data import native

            return native.crop_resize(np.asarray(img, np.uint8), box,
                                      self.sz, self.sz, flip)
        arr = np.asarray(img.resize((self.sz, self.sz), Image.BILINEAR, box=box),
                         np.uint8)
        return arr[:, ::-1] if flip else arr

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        rng = np.random.default_rng([self.seed, self.epoch, 0xE90C])
        order = rng.permutation(len(self.ds))[self.pi::self.pc]
        nb = len(self)
        with ThreadPoolExecutor(max_workers=self.workers) as pool:
            for b in range(nb):
                idxs = order[b * self.batch_size:(b + 1) * self.batch_size]
                # aug seed keyed on *global* sample position so worker count
                # and process layout never change the pixels
                jobs = [(int(i), int(i)) for i in idxs]
                arrays = list(pool.map(self._decode, jobs))
                labels = [self.ds.label(int(i)) for i in idxs]
                yield _collate(arrays, labels, self.sz, self.sz)


class ValLoader:
    """Equal-batch-count validation loader (``DistValSampler``,
    `dataloader.py:133-161`): batch ``j`` of process ``i`` holds global images
    ``[j*B*P + i*B, j*B*P + (i+1)*B)`` clipped to the dataset — trailing
    batches may be short or empty, but every process yields
    ``expected_num_batches`` batches and the union covers each image once.
    """

    def __init__(self, dataset, batch_size: int, sz: int, *,
                 rect_val: bool = False, ar_buckets: int = 8, workers: int = 4,
                 process_index: int = 0, process_count: int = 1,
                 backend: str = "auto"):
        self.ds = dataset
        self.batch_size = int(batch_size)
        self.sz = int(sz)
        self.rect_val = bool(rect_val)
        self.ar_buckets = max(int(ar_buckets), 1)
        self.workers = max(int(workers), 1)
        self.pi, self.pc = int(process_index), int(process_count)
        self.native = _use_native(backend)
        n = len(dataset)
        self.expected_num_batches = max(
            -(-n // (self.batch_size * self.pc)), 1
        )
        self._shapes: Optional[List[Tuple[int, int]]] = None
        self._order: Optional[np.ndarray] = None

    def _plan_rect(self) -> None:
        """AR-ascending order + one quantised (h, w) per batch, at most
        ``ar_buckets`` distinct shapes (``sort_ar`` + ``CropArTfm``)."""
        n = len(self.ds)
        if hasattr(self.ds, "sizes_bulk"):
            wh = np.asarray(self.ds.sizes_bulk(), np.float64)
            ars = wh[:, 0] / wh[:, 1]  # parallel scan + disk cache
        else:
            ars = np.asarray(
                [self.ds.size(i)[0] / self.ds.size(i)[1] for i in range(n)])
        self._order = np.argsort(ars, kind="stable")
        gb = self.batch_size * self.pc
        nb = self.expected_num_batches
        shapes: List[Tuple[int, int]] = []
        prev_ar = 0.0
        for b in range(nb):
            bucket = b * self.ar_buckets // nb
            # all batches in a bucket share the bucket's last-batch median AR;
            # compute from member batches to keep the palette stable
            b_lo = -(-bucket * nb // self.ar_buckets)
            b_hi = -(-(bucket + 1) * nb // self.ar_buckets)
            members = self._order[b_lo * gb:min(b_hi * gb, n)]
            ar = float(np.median(ars[members])) if len(members) else 1.0
            ar = min(max(ar, 0.5), 2.0)  # reference clamps implicitly via crops
            if ar >= 1.0:
                h, w = self.sz, int(round(self.sz * ar))
            else:
                h, w = int(round(self.sz / ar)), self.sz
            # monotone non-decreasing w/h so batch order matches sort_ar
            if shapes and w / h < prev_ar:
                h, w = shapes[-1]
            prev_ar = w / h
            shapes.append((h, w))
        self._shapes = shapes

    def _decode(self, job: Tuple[int, int, int]) -> np.ndarray:
        idx, h, w = job
        img = self.ds.load(idx)
        enlarge = 1.14 if not self.rect_val else 1.0  # Resize(int(sz*1.14))
        if self.native:
            from tpu_compressed_dp.data import native

            # reproduce the two-step resize+integer-crop as one source box:
            # the crop rectangle in resized coords maps back through the
            # exact (rounded) resize dimensions
            sw, sh = img.size
            scale = max(w * enlarge / sw, h * enlarge / sh)
            rw = max(int(round(sw * scale)), w)
            rh = max(int(round(sh * scale)), h)
            cx0, cy0 = (rw - w) // 2, (rh - h) // 2
            box = (cx0 * sw / rw, cy0 * sh / rh,
                   (cx0 + w) * sw / rw, (cy0 + h) * sh / rh)
            return native.crop_resize(np.asarray(img, np.uint8), box, h, w)
        return np.asarray(_center_crop_resize(img, w, h, enlarge), np.uint8)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self.ds)
        if self.rect_val and self._shapes is None:
            self._plan_rect()
        order = self._order if self.rect_val else np.arange(n)
        gb = self.batch_size * self.pc
        with ThreadPoolExecutor(max_workers=self.workers) as pool:
            for b in range(self.expected_num_batches):
                h, w = self._shapes[b] if self.rect_val else (self.sz, self.sz)
                lo = b * gb + self.pi * self.batch_size
                hi = min(lo + self.batch_size, n)
                idxs = [int(order[i]) for i in range(lo, min(hi, n)) if i < n] if lo < n else []
                arrays = list(pool.map(self._decode, [(i, h, w) for i in idxs]))
                labels = [self.ds.label(i) for i in idxs]
                yield _collate(arrays, labels, h, w)
