"""CIFAR-10 in-RAM pipeline: load, preprocess, augment, batch.

Re-implements the reference's numpy-side preprocessing
(`CIFAR10/core.py:43-56`: normalise / reflect-pad 4) and per-epoch-sampled
augmentation (`core.py:62-114`: Crop(32,32), FlipLR, Cutout(8,8) with random
choices drawn once per epoch in ``Transform.set_random_choices``) — but
vectorised over the whole epoch instead of per-sample ``__getitem__``, and in
NHWC (TPU-native) instead of the reference's NCHW ``transpose``
(`core.py:55-56`).

Loading uses torchvision files when present (`torch_backend.py:36-42`); a
deterministic synthetic fallback keeps tests and zero-egress environments
working (the reference had no offline story).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

__all__ = [
    "CIFAR10_MEAN",
    "CIFAR10_STD",
    "load_cifar10",
    "synthetic_cifar10",
    "normalise",
    "pad",
    "augment_epoch",
    "Batches",
]

CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)  # core.py:43
CIFAR10_STD = (0.2471, 0.2435, 0.2616)  # core.py:44


def normalise(x: np.ndarray, mean=CIFAR10_MEAN, std=CIFAR10_STD) -> np.ndarray:
    """(x - 255*mean) / (255*std) on uint8 NHWC input (`core.py:46-50`)."""
    x = np.asarray(x, np.float32)
    x -= np.asarray(mean, np.float32) * 255.0
    x *= 1.0 / (255.0 * np.asarray(std, np.float32))
    return x


def pad(x: np.ndarray, border: int = 4) -> np.ndarray:
    """Reflect-pad H and W of NHWC (`core.py:52-53`)."""
    return np.pad(x, [(0, 0), (border, border), (border, border), (0, 0)], mode="reflect")


def load_cifar10(data_dir: str) -> Dict[str, Dict[str, np.ndarray]]:
    """Raw uint8 NHWC CIFAR-10 from torchvision files (`torch_backend.py:36-42`).

    Raises FileNotFoundError (with a pointer to ``synthetic_cifar10``) when the
    dataset is absent and cannot be downloaded.
    """
    try:
        import torchvision

        train = torchvision.datasets.CIFAR10(root=data_dir, train=True, download=False)
        test = torchvision.datasets.CIFAR10(root=data_dir, train=False, download=False)
    except (ImportError, RuntimeError) as e:
        raise FileNotFoundError(
            f"CIFAR-10 not found under {data_dir!r} ({e}); download it there or use "
            "synthetic_cifar10() for smoke runs"
        ) from e
    return {
        "train": {"data": np.asarray(train.data), "labels": np.asarray(train.targets, np.int32)},
        "test": {"data": np.asarray(test.data), "labels": np.asarray(test.targets, np.int32)},
    }


def synthetic_cifar10(
    n_train: int = 2048, n_test: int = 512, num_classes: int = 10, seed: int = 0
) -> Dict[str, Dict[str, np.ndarray]]:
    """Deterministic learnable stand-in: class-dependent colour blobs + noise."""
    rng = np.random.RandomState(seed)

    def make(n):
        labels = rng.randint(0, num_classes, n).astype(np.int32)
        protos = np.random.RandomState(1234).randint(0, 255, (num_classes, 4, 4, 3))
        imgs = protos[labels]
        imgs = np.repeat(np.repeat(imgs, 8, axis=1), 8, axis=2).astype(np.float32)
        imgs += rng.randn(n, 32, 32, 3) * 25.0
        return {"data": np.clip(imgs, 0, 255).astype(np.uint8), "labels": labels}

    return {"train": make(n_train), "test": make(n_test)}


def augment_epoch(
    x: np.ndarray,
    rng: np.random.RandomState,
    crop: Tuple[int, int] = (32, 32),
    cutout: Optional[Tuple[int, int]] = (8, 8),
    flip: bool = True,
) -> np.ndarray:
    """One epoch's worth of Crop + FlipLR + Cutout, choices pre-sampled per
    sample exactly like ``Transform.set_random_choices`` (`core.py:107-114`),
    applied vectorised.  ``x`` is padded NHWC, uint8 or float32 (uint8 stays
    uint8 — normalisation belongs on device)."""
    n, h, w, c = x.shape
    ch, cw = crop
    y0 = rng.randint(0, h - ch + 1, n)
    x0 = rng.randint(0, w - cw + 1, n)
    windows = np.lib.stride_tricks.sliding_window_view(x, (ch, cw), axis=(1, 2))
    out = windows[np.arange(n), y0, x0]  # (N, C, ch, cw)
    out = np.ascontiguousarray(out.transpose(0, 2, 3, 1))  # back to NHWC

    if flip:
        f = rng.rand(n) < 0.5
        out[f] = out[f, :, ::-1, :]

    if cutout is not None:
        kh, kw = cutout
        cy = rng.randint(0, ch - kh + 1, n)
        cx = rng.randint(0, cw - kw + 1, n)
        rows = np.arange(ch)[None, :]
        cols = np.arange(cw)[None, :]
        rmask = (rows >= cy[:, None]) & (rows < (cy + kh)[:, None])  # (N, H)
        cmask = (cols >= cx[:, None]) & (cols < (cx + kw)[:, None])  # (N, W)
        mask = rmask[:, :, None] & cmask[:, None, :]  # (N, H, W)
        out *= ~mask[..., None]
    return out


class Batches:
    """Epoch iterator yielding ``{'input', 'target'}`` numpy batches
    (`torch_backend.py:48-63` equivalent; augmentation happens per epoch when
    ``augment=True``, mirroring ``set_random_choices=True``)."""

    def __init__(
        self,
        data: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        *,
        shuffle: bool,
        augment: bool = False,
        drop_last: bool = False,
        seed: int = 0,
    ):
        self.data = data
        self.labels = np.asarray(labels, np.int32)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.augment = augment
        self.drop_last = drop_last
        self.rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        n = len(self.labels)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self.labels)
        x = augment_epoch(self.data, self.rng) if self.augment else self.data
        idx = self.rng.permutation(n) if self.shuffle else np.arange(n)
        stop = len(self) * self.batch_size if self.drop_last else n
        for lo in range(0, stop, self.batch_size):
            sel = idx[lo : lo + self.batch_size]
            yield {"input": x[sel], "target": self.labels[sel]}
