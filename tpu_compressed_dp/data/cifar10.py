"""CIFAR-10 in-RAM pipeline: load, preprocess, augment, batch.

Re-implements the reference's numpy-side preprocessing
(`CIFAR10/core.py:43-56`: normalise / reflect-pad 4) and per-epoch-sampled
augmentation (`core.py:62-114`: Crop(32,32), FlipLR, Cutout(8,8) with random
choices drawn once per epoch in ``Transform.set_random_choices``) — but
vectorised over the whole epoch instead of per-sample ``__getitem__``, and in
NHWC (TPU-native) instead of the reference's NCHW ``transpose``
(`core.py:55-56`).

Loading uses torchvision files when present (`torch_backend.py:36-42`); a
deterministic synthetic fallback keeps tests and zero-egress environments
working (the reference had no offline story).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

__all__ = [
    "CIFAR10_MEAN",
    "CIFAR10_STD",
    "load_cifar10",
    "synthetic_cifar10",
    "normalise",
    "pad",
    "augment_epoch",
    "Batches",
]

CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)  # core.py:43
CIFAR10_STD = (0.2471, 0.2435, 0.2616)  # core.py:44


def normalise(x: np.ndarray, mean=CIFAR10_MEAN, std=CIFAR10_STD) -> np.ndarray:
    """(x - 255*mean) / (255*std) on uint8 NHWC input (`core.py:46-50`)."""
    x = np.asarray(x, np.float32)
    x -= np.asarray(mean, np.float32) * 255.0
    x *= 1.0 / (255.0 * np.asarray(std, np.float32))
    return x


def pad(x: np.ndarray, border: int = 4) -> np.ndarray:
    """Reflect-pad H and W of NHWC (`core.py:52-53`)."""
    return np.pad(x, [(0, 0), (border, border), (border, border), (0, 0)], mode="reflect")


def load_cifar10(data_dir: str) -> Dict[str, Dict[str, np.ndarray]]:
    """Raw uint8 NHWC CIFAR-10 from torchvision files (`torch_backend.py:36-42`).

    Raises FileNotFoundError (with a pointer to ``synthetic_cifar10``) when the
    dataset is absent and cannot be downloaded.
    """
    try:
        import torchvision

        train = torchvision.datasets.CIFAR10(root=data_dir, train=True, download=False)
        test = torchvision.datasets.CIFAR10(root=data_dir, train=False, download=False)
    except (ImportError, RuntimeError) as e:
        raise FileNotFoundError(
            f"CIFAR-10 not found under {data_dir!r} ({e}); download it there or use "
            "synthetic_cifar10() for smoke runs"
        ) from e
    return {
        "train": {"data": np.asarray(train.data), "labels": np.asarray(train.targets, np.int32)},
        "test": {"data": np.asarray(test.data), "labels": np.asarray(test.targets, np.int32)},
    }


def synthetic_cifar10(
    n_train: int = 2048, n_test: int = 512, num_classes: int = 10, seed: int = 0
) -> Dict[str, Dict[str, np.ndarray]]:
    """Deterministic learnable stand-in: class-dependent colour blobs + noise."""
    rng = np.random.RandomState(seed)

    def make(n):
        labels = rng.randint(0, num_classes, n).astype(np.int32)
        protos = np.random.RandomState(1234).randint(0, 255, (num_classes, 4, 4, 3))
        imgs = protos[labels]
        imgs = np.repeat(np.repeat(imgs, 8, axis=1), 8, axis=2).astype(np.float32)
        imgs += rng.randn(n, 32, 32, 3) * 25.0
        return {"data": np.clip(imgs, 0, 255).astype(np.uint8), "labels": labels}

    return {"train": make(n_train), "test": make(n_test)}


def synthetic_cifar10_hard(
    n_train: int = 16384,
    n_test: int = 4096,
    num_classes: int = 10,
    seed: int = 0,
    protos_per_class: int = 8,
    noise: float = 80.0,
    label_noise: float = 0.04,
    max_shift: int = 8,
) -> Dict[str, Dict[str, np.ndarray]]:
    """Non-saturating synthetic benchmark (VERDICT r1 #2).

    The plain :func:`synthetic_cifar10` blobs saturate at 100% accuracy, which
    cannot separate good compressors from bad ones.  This variant is tuned so
    the 24-epoch DAWNBench protocol lands dense test accuracy ~0.9 (the
    regime of the reference's real-CIFAR claims, `CIFAR10/README.md:3`), with
    headroom for the method x ratio grid to order the way AAAI'20 Fig. 3
    does:

      * several low-frequency texture prototypes per class (intra-class
        variability — a linear classifier can't memorise one template);
      * random per-image contrast and circular shifts (needs the conv net's
        translation handling and the Crop augmentation to matter);
      * heavy pixel noise (optimisation quality shows in the margin);
      * irreducible label noise capping attainable accuracy below 1.
    """
    rng = np.random.RandomState(seed)
    prng = np.random.RandomState(4321)
    # smooth textures: 8x8 gaussian fields, bilinear-ish upsample x4 via repeat
    # + two box-blur passes
    protos = prng.randn(num_classes * protos_per_class, 8, 8, 3).astype(np.float32)
    up = np.repeat(np.repeat(protos, 4, axis=1), 4, axis=2)
    k = np.ones(5, np.float32) / 5.0
    for axis in (1, 2):
        up = np.apply_along_axis(
            lambda v: np.convolve(v, k, mode="same"), axis, up)
    up /= up.std(axis=(1, 2, 3), keepdims=True) + 1e-8
    up = up.reshape(num_classes, protos_per_class, 32, 32, 3)

    def make(n):
        labels = rng.randint(0, num_classes, n).astype(np.int32)
        pidx = rng.randint(0, protos_per_class, n)
        base = up[labels, pidx]
        contrast = rng.uniform(0.6, 1.4, (n, 1, 1, 1)).astype(np.float32)
        img = base * contrast * 42.0 + 128.0
        # per-image circular shift (translation nuisance)
        sy = rng.randint(-max_shift, max_shift + 1, n)
        sx = rng.randint(-max_shift, max_shift + 1, n)
        row = (np.arange(32)[None, :] - sy[:, None]) % 32     # [n, 32]
        col = (np.arange(32)[None, :] - sx[:, None]) % 32
        img = img[np.arange(n)[:, None, None], row[:, :, None], col[:, None, :]]
        img += rng.randn(n, 32, 32, 3).astype(np.float32) * noise
        flip = rng.rand(n) < label_noise
        labels[flip] = rng.randint(0, num_classes, int(flip.sum()))
        return {"data": np.clip(img, 0, 255).astype(np.uint8), "labels": labels}

    return {"train": make(n_train), "test": make(n_test)}


def draw_augment_choices(
    n: int,
    shape: Tuple[int, int],
    rng: np.random.RandomState,
    crop: Tuple[int, int] = (32, 32),
    cutout: Optional[Tuple[int, int]] = (8, 8),
    flip: bool = True,
) -> dict:
    """Pre-sample one epoch's augmentation choices for all ``n`` images
    (``Transform.set_random_choices``, `core.py:107-114`).  Drawing is split
    from application so multi-process ranks can keep an identical RNG stream
    while transforming only their own shard (choices are a few ints per
    image; the pixel work is the expensive part)."""
    h, w = shape
    ch, cw = crop
    out = {"crop": crop, "cutout": cutout,
           "y0": rng.randint(0, h - ch + 1, n), "x0": rng.randint(0, w - cw + 1, n)}
    out["flip"] = rng.rand(n) < 0.5 if flip else None
    if cutout is not None:
        kh, kw = cutout
        out["cy"] = rng.randint(0, ch - kh + 1, n)
        out["cx"] = rng.randint(0, cw - kw + 1, n)
    return out


def apply_augment(x: np.ndarray, choices: dict,
                  rows: Optional[np.ndarray] = None) -> np.ndarray:
    """Apply pre-drawn Crop + FlipLR + Cutout, vectorised; ``rows`` selects a
    subset of images (output in ``rows`` order).  uint8 stays uint8 —
    normalisation belongs on device."""
    ch, cw = choices["crop"]
    y0, x0, f = choices["y0"], choices["x0"], choices["flip"]
    if rows is not None:
        x = x[rows]
        y0, x0 = y0[rows], x0[rows]
        f = f[rows] if f is not None else None
    n = x.shape[0]
    windows = np.lib.stride_tricks.sliding_window_view(x, (ch, cw), axis=(1, 2))
    out = windows[np.arange(n), y0, x0]  # (N, C, ch, cw)
    out = np.ascontiguousarray(out.transpose(0, 2, 3, 1))  # back to NHWC

    if f is not None:
        out[f] = out[f, :, ::-1, :]

    if choices["cutout"] is not None:
        kh, kw = choices["cutout"]
        cy, cx = choices["cy"], choices["cx"]
        if rows is not None:
            cy, cx = cy[rows], cx[rows]
        rr = np.arange(ch)[None, :]
        cc = np.arange(cw)[None, :]
        rmask = (rr >= cy[:, None]) & (rr < (cy + kh)[:, None])  # (N, H)
        cmask = (cc >= cx[:, None]) & (cc < (cx + kw)[:, None])  # (N, W)
        mask = rmask[:, :, None] & cmask[:, None, :]  # (N, H, W)
        out *= ~mask[..., None]
    return out


def augment_epoch(
    x: np.ndarray,
    rng: np.random.RandomState,
    crop: Tuple[int, int] = (32, 32),
    cutout: Optional[Tuple[int, int]] = (8, 8),
    flip: bool = True,
) -> np.ndarray:
    """One epoch's worth of Crop + FlipLR + Cutout over every image (the
    single-process path: draw + apply in one call)."""
    choices = draw_augment_choices(x.shape[0], x.shape[1:3], rng, crop, cutout, flip)
    return apply_augment(x, choices)


class Batches:
    """Epoch iterator yielding ``{'input', 'target'}`` numpy batches
    (`torch_backend.py:48-63` equivalent; augmentation happens per epoch when
    ``augment=True``, mirroring ``set_random_choices=True``)."""

    def __init__(
        self,
        data: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        *,
        shuffle: bool,
        augment: bool = False,
        drop_last: bool = False,
        seed: int = 0,
        shard: Optional[Tuple[int, int]] = None,
    ):
        """``shard=(rank, procs)`` makes iteration yield this rank's
        ``batch_size/procs``-row slice of every global batch, with the RNG
        stream (augmentation choices + shuffle) identical to the unsharded
        iterator's — but the pixel-level augmentation work done only for the
        rank's own rows (the multi-host ``DistributedSampler`` role,
        `dataloader.py:33`, without P-fold redundant host work)."""
        self.data = data
        self.labels = np.asarray(labels, np.int32)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.augment = augment
        self.drop_last = drop_last
        self.rng = np.random.RandomState(seed)
        if shard is not None:
            rank, procs = shard
            if batch_size % procs:
                raise ValueError(f"batch_size {batch_size} not divisible by "
                                 f"{procs} processes")
            if not drop_last:
                raise ValueError("sharded iteration requires drop_last=True "
                                 "(pad + slice short batches at the caller)")
        self.shard = shard

    def __len__(self) -> int:
        n = len(self.labels)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self.labels)
        choices = (draw_augment_choices(n, self.data.shape[1:3], self.rng)
                   if self.augment else None)
        idx = self.rng.permutation(n) if self.shuffle else np.arange(n)
        if self.shard is None:
            x = apply_augment(self.data, choices) if self.augment else self.data
            stop = len(self) * self.batch_size if self.drop_last else n
            for lo in range(0, stop, self.batch_size):
                sel = idx[lo : lo + self.batch_size]
                yield {"input": x[sel], "target": self.labels[sel]}
            return
        rank, procs = self.shard
        per = self.batch_size // procs
        nb = len(self)
        # this rank's rows of every batch, in batch order
        sel = idx[: nb * self.batch_size].reshape(nb, procs, per)[:, rank, :]
        sel = sel.reshape(-1)
        x = (apply_augment(self.data, choices, rows=sel)
             if self.augment else self.data[sel])
        y = self.labels[sel]
        for b in range(nb):
            lo = b * per
            yield {"input": x[lo:lo + per], "target": y[lo:lo + per]}
