"""LM token pipelines: synthetic streams and byte-level text files.

Net-new relative to the reference (its pipelines are image-only, SURVEY.md
§2); feeds the Llama pretrain harness.  Batches are ``{'input': [B, T] int32,
'target': [B, T] int32}`` next-token pairs, deterministic in
``(seed, step, process_index)`` so multi-host runs shard the stream without
coordination — the LM analog of the seeded ``DistributedSampler`` semantics
(`dataloader.py:33`).
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

__all__ = ["SyntheticTokens", "ByteCorpus"]


class SyntheticTokens:
    """Deterministic synthetic stream with learnable structure.

    Sequences interleave (a) fixed-period repeating motifs drawn from a
    per-stream PRNG and (b) uniform noise tokens — so a model that learns
    the motifs drops well below the uniform-entropy loss floor, giving smoke
    tests a real convergence signal (loss < log(vocab)).
    """

    def __init__(self, vocab: int, seq_len: int, batch_size: int, *,
                 seed: int = 0, motif_len: int = 8, noise: float = 0.1,
                 process_index: int = 0, process_count: int = 1):
        if vocab < 4:
            raise ValueError("vocab must be >= 4")
        self.vocab, self.seq_len, self.batch_size = vocab, seq_len, batch_size
        self.seed, self.motif_len, self.noise = seed, motif_len, noise
        self.pi, self.pc = process_index, process_count
        rng = np.random.default_rng([seed, 0x70C])
        self.motifs = rng.integers(0, vocab, size=(16, motif_len))

    def batch(self, step: int) -> Dict[str, np.ndarray]:
        rng = np.random.default_rng([self.seed, step, self.pi])
        b, t = self.batch_size, self.seq_len + 1
        motif_ids = rng.integers(0, len(self.motifs), size=(b,))
        reps = -(-t // self.motif_len)
        seqs = np.tile(self.motifs[motif_ids], (1, reps))[:, :t]
        noise_mask = rng.random((b, t)) < self.noise
        seqs = np.where(noise_mask, rng.integers(0, self.vocab, size=(b, t)), seqs)
        seqs = seqs.astype(np.int32)
        return {"input": seqs[:, :-1], "target": seqs[:, 1:]}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        step = 0
        while True:
            yield self.batch(step)
            step += 1


class ByteCorpus:
    """Byte-level tokens from a text/binary file (vocab 256), random crops.

    The zero-dependency real-data path: no tokenizer to ship, every file is
    a corpus.
    """

    def __init__(self, path: str, seq_len: int, batch_size: int, *,
                 seed: int = 0, process_index: int = 0, process_count: int = 1):
        self.data = np.fromfile(path, dtype=np.uint8)
        if len(self.data) < seq_len + 2:
            raise ValueError(f"corpus {path!r} shorter than seq_len")
        self.vocab = 256
        self.seq_len, self.batch_size = seq_len, batch_size
        self.seed, self.pi, self.pc = seed, process_index, process_count

    def batch(self, step: int) -> Dict[str, np.ndarray]:
        rng = np.random.default_rng([self.seed, step, self.pi])
        starts = rng.integers(0, len(self.data) - self.seq_len - 1,
                              size=(self.batch_size,))
        idx = starts[:, None] + np.arange(self.seq_len + 1)[None, :]
        seqs = self.data[idx].astype(np.int32)
        return {"input": seqs[:, :-1], "target": seqs[:, 1:]}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        step = 0
        while True:
            yield self.batch(step)
            step += 1
