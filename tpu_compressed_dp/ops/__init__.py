from tpu_compressed_dp.ops import compressors  # noqa: F401
