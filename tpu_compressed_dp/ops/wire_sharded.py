"""Owner-sharded sparse-allreduce transport for index-carrying wire payloads.

The flat ``all_gather`` combine (`ops/wire.py`) ships every worker's
``(value, index)`` pairs to every chip: per-chip wire volume and decode work
both scale as ``O(W*k)`` — the non-scalable allgather regime that
"Near-Optimal Sparse Allreduce for Distributed Deep Learning" (OKTopk,
PAPERS.md) identifies, and that "Understanding Top-k Sparsification" shows
dominating Top-K's end-to-end cost at scale.  ``transport='sharded'``
(:class:`~tpu_compressed_dp.parallel.dp.CompressionConfig`) replaces it with
a sparse reduce-scatter-then-allgather:

  1. **route** — the group's flat unit space (elements, or whole blocks for
     Block-Top-K) is partitioned into ``W`` contiguous owner shards of
     ``ceil(n/W)`` units; each worker drops its pairs into fixed-capacity
     per-destination buckets (``cap_dest`` slots each, zero-value
     scatter-add-identity padding — the Threshold-V cap-buffer trick) and
     one ``lax.all_to_all`` delivers bucket ``j`` to owner ``j``.  Pairs
     beyond a bucket's capacity are *clipped*: they stay in the error
     feedback residual when EF is on and are dropped (and counted in
     ``comm/shard_overflow``) when it is off.
  2. **reduce** — the owner scatter-adds the ``W*cap_dest`` received pairs
     into its dense shard: cross-worker duplicates collapse *here*, once,
     instead of ``W`` times on every chip.
  3. **return** — the reduced shard travels back through one ``all_gather``.
     Two forms, chosen statically per group by billed size: the compacted
     *sparse union* of touched units in a ``cap_ret``-capacity buffer
     (``O(k/W)`` per owner in the high-overlap regime sparsified DP training
     lives in), or the *dense shard* (``n*32/W`` bits, always lossless)
     whenever that is no bigger.  Units clipped by ``cap_ret`` are refunded
     to every contributor's EF residual (each worker checks its accepted
     coordinates against the returned index set), so return clipping defers
     gradient mass exactly like any other EF'd drop.

Per-chip wire volume falls from ``(W-1) * k * 64`` bits to
``~(W-1)/W * route + (W-1) * return`` — ``O(k + min(k, n/W))`` instead of
``O(W*k)`` — and decode falls from ``W*k`` scatter-adds to ``k`` plus one
dense concat (dense return) or ``~k`` (sparse return).

Capacity sizing is static config (``shard_route_factor`` /
``shard_return_factor`` x ``k/W``), so billed bits are static too —
fixed-size transport is the honest wire cost, exactly as for the
Threshold-V cap buffer.  ``comm/shard_overflow`` reports how many
coordinates the caps clipped so they can be sized; the equivalence tests
(tests/test_wire_sharded.py) run with lossless capacities
(``cap_dest = shard_n`` forces the dense return) and match the allgather
combine bit-for-bit up to fp32 summation order.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["ShardPlan", "make_shard_plan", "sharded_payload_bits",
           "sharded_combine", "owner_of_unit", "owner_bounds",
           "SHARDED_METHODS", "HierPlan", "make_hier_plan",
           "hier_axis_groups", "hier_payload_bits"]

# The wire methods whose payloads carry explicit indices and therefore have
# a sharded form.  Quantizers (terngrad/qsgd) ship dense per-worker codes
# with per-worker scales — there is no (value, index) stream to route — and
# psum-riding methods (shared-seed randomk, powersgd, keep-all blocktopk)
# already reduce on the ring.
SHARDED_METHODS = ("topk", "blocktopk", "thresholdv", "adaptive_threshold")


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Static geometry of one group's sharded combine.

    ``n_units``/``keep`` count *units*: elements for the element-granular
    methods, whole blocks for Block-Top-K (``unit_size > 1``).
    """

    n_units: int       # units in the group's flat space
    keep: int          # payload slots per worker (k, kb, or the cap)
    world: int         # W (static mesh size)
    unit_size: int     # elements per unit (1, or block_size)
    shard_n: int       # units per owner shard (ceil(n_units / W))
    cap_dest: int      # route: slots per destination bucket
    cap_ret: int       # return: sparse-union buffer capacity per owner
    dense_return: bool # return the dense shard instead of the sparse union


def make_shard_plan(n_units: int, keep: int, world: int, unit_size: int,
                    route_factor: float, return_factor: float) -> ShardPlan:
    """Size the fixed-capacity buffers for one group, statically.

    ``cap_dest = route_factor * keep / W`` assumes a worker's selections
    spread roughly uniformly over the owner shards; ``cap_ret =
    return_factor * keep / W`` assumes worker selections overlap (the
    premise sparsified DP training rests on — correlated gradients select
    correlated coordinates; OKTopk makes the same bet).  Both caps are
    clamped to their lossless bounds (a worker holds at most ``shard_n``
    distinct units per shard; the union at an owner holds at most
    ``W * cap_dest``), and the sparse return is swapped for the dense shard
    whenever the dense form is no bigger — which is also how generous
    factors (tests) force the always-lossless dense path.
    """
    shard_n = -(-n_units // world)
    cap_dest = max(1, -(-int(round(route_factor * keep)) // world))
    cap_dest = min(cap_dest, shard_n, max(keep, 1))
    cap_ret = max(1, -(-int(round(return_factor * keep)) // world))
    cap_ret = min(cap_ret, world * cap_dest, shard_n)
    # sparse unit = unit_size values + 1 index word; dense unit = unit_size
    # values.  Prefer dense at equality: it is lossless.
    sparse_bits = cap_ret * 32 * (unit_size + 1)
    dense_bits = shard_n * 32 * unit_size
    return ShardPlan(n_units, keep, world, unit_size, shard_n, cap_dest,
                     cap_ret, dense_bits <= sparse_bits)


def owner_of_unit(unit: int, plan: ShardPlan) -> int:
    """Which worker owns flat unit ``unit`` — the host-side mirror of the
    routing rule inside :func:`sharded_combine` (``min(u // shard_n, W-1)``,
    the clamp absorbing the ragged last shard when ``W*shard_n > n_units``).
    Pure arithmetic on the plan: an elastic remesh that rebuilds the step
    over W-1 workers gets a new plan and with it a new partition, and the
    tests (tests/test_wire_sharded.py) check the two stay consistent."""
    if not 0 <= unit < plan.n_units:
        raise ValueError(f"unit {unit} outside [0, {plan.n_units})")
    return min(unit // plan.shard_n, plan.world - 1)


def owner_bounds(plan: ShardPlan) -> Tuple[Tuple[int, int], ...]:
    """Per-owner ``(lo, hi)`` half-open unit ranges, in owner order.

    Concatenated they tile ``[0, n_units)`` exactly — no unit unowned, none
    doubly-owned — for EVERY world size, including the ragged tails where
    the last owners hold short or empty shards (e.g. ``n_units=10, W=4``:
    shard_n=3, ranges (0,3)(3,6)(6,9)(9,10)).  This is the invariant a
    W -> W-1 remesh must re-establish and the partition-coverage tests
    assert directly."""
    bounds = []
    for w in range(plan.world):
        lo = min(w * plan.shard_n, plan.n_units)
        hi = plan.n_units if w == plan.world - 1 else min(
            (w + 1) * plan.shard_n, plan.n_units)
        bounds.append((lo, hi))
    return tuple(bounds)


def sharded_payload_bits(n_units: int, keep: int, world: int, unit_size: int,
                         route_factor: float, return_factor: float
                         ) -> Tuple[float, float]:
    """Analytic ``(route_bits, return_bits)`` per chip for one group —
    the same arithmetic the wire engine measures off its actual buffers
    (fp32 values assumed, matching the analytic convention everywhere
    else).  Route bits ride ``all_to_all`` (per-chip link traffic
    ``(W-1)/W x``); return bits ride ``all_gather`` (``(W-1) x``)."""
    p = make_shard_plan(n_units, keep, world, unit_size, route_factor,
                        return_factor)
    route = float(p.world * p.cap_dest * 32 * (unit_size + 1))
    if p.dense_return:
        ret = float(p.shard_n * 32 * unit_size)
    else:
        ret = float(p.cap_ret * 32 * (unit_size + 1))
    return route, ret


@dataclasses.dataclass(frozen=True)
class HierPlan:
    """Static geometry of one group's two-level hierarchical combine
    (``transport='hierarchical'``): a ``pods x chips`` virtual 2-axis view
    of the flat dp mesh, with dense ICI psums inside each pod and the
    owner-sharded sparse exchange (:class:`ShardPlan` over ``pods``
    senders) across the DCN axis.
    """

    n: int          # elements in the group's flat space
    keep: int       # per-worker selection size (elements)
    world: int      # W = pods * chips (the flat dp axis size)
    pods: int       # P: DCN-connected pod count
    chips: int      # C: ICI-connected chips per pod
    cap_union: int  # recompress: pod-union buffer capacity (multiple of C)
    slab: int       # cap_union // chips — one chip's slice of the union
    dcn: ShardPlan  # the inter-pod exchange (world=pods, keep=slab)


def hier_axis_groups(world: int, pods: int):
    """The two ``axis_index_groups`` partitions of the flat dp axis.

    ICI groups — one per pod, ``chips`` contiguous ranks each (rank ``g``
    lives in pod ``g // chips`` at chip-rank ``g % chips``); DCN groups —
    one per chip-rank, ``pods`` ranks each (the rank-``c`` column across
    pods), so the ``chips`` inter-pod exchanges run in parallel over
    disjoint slabs of each pod's union buffer."""
    if world % pods:
        raise ValueError(
            f"dp_pods={pods} must divide the dp world size {world} "
            "(the virtual mesh is pods x chips with no ragged pod)")
    chips = world // pods
    ici = [[p * chips + c for c in range(chips)] for p in range(pods)]
    dcn = [[p * chips + c for p in range(pods)] for c in range(chips)]
    return ici, dcn


def make_hier_plan(n: int, keep: int, world: int, pods: int,
                   route_factor_ici: float, route_factor_dcn: float
                   ) -> HierPlan:
    """Size the hierarchical transport's buffers for one group, statically.

    ``cap_union = route_factor_ici * keep`` is the recompress capacity for
    the pod-reduced gradient's nonzero union: with the worker-overlap
    premise the union is ~``keep`` (factor 1 would already hold it), and a
    disjoint-selection worst case needs ``chips * keep`` — the factor is
    the knob between them, clamped to the (chip-rounded) group size and
    rounded up to a multiple of ``chips`` so the buffer slices evenly into
    per-chip slabs.  The DCN exchange is an ordinary :class:`ShardPlan`
    over ``pods`` senders whose per-sender payload is one ``slab``.
    """
    if world % pods:
        raise ValueError(
            f"dp_pods={pods} must divide the dp world size {world}")
    chips = world // pods
    cap = max(chips, int(round(route_factor_ici * max(keep, 1))))
    cap = -(-cap // chips) * chips
    cap = min(cap, -(-n // chips) * chips)
    slab = cap // chips
    dcn = make_shard_plan(n, slab, pods, 1, route_factor_dcn,
                          route_factor_dcn)
    return HierPlan(n, keep, world, pods, chips, cap, slab, dcn)


def hier_payload_bits(n: int, keep: int, world: int, pods: int,
                      route_factor_ici: float, route_factor_dcn: float
                      ) -> Tuple[float, float, float]:
    """Analytic ``(ici_bits, dcn_route_bits, dcn_return_bits)`` per chip
    for one hierarchical group — the same arithmetic the wire engine
    measures off its actual buffers, so simulate and wire accounting agree
    for this transport too.

    ICI carries the two dense pod psums (the compressed-dense contribution
    in, the combined partial back out: ``2 * n * 32`` bits; zero when each
    pod is a single chip, one psum when there is a single pod — that lone
    psum already reduces the whole world).  DCN carries the per-chip slab's
    route ``all_to_all`` and shard-return ``all_gather`` exactly as billed
    by :func:`sharded_payload_bits` over ``pods`` senders."""
    p = make_hier_plan(n, keep, world, pods, route_factor_ici,
                       route_factor_dcn)
    if p.pods == 1:
        return (float(n * 32) if p.chips > 1 else 0.0), 0.0, 0.0
    ici = float(2 * n * 32) if p.chips > 1 else 0.0
    route = float(p.dcn.world * p.dcn.cap_dest * 32 * 2)
    if p.dcn.dense_return:
        ret = float(p.dcn.shard_n * 32)
    else:
        ret = float(p.dcn.cap_ret * 32 * 2)
    return ici, route, ret


def _per_dest_slots(idx: Array, valid: Optional[Array], plan: ShardPlan
                    ) -> Tuple[Array, Array, Array]:
    """Assign each payload slot its route bucket position.

    ``idx`` is ascending (``packed_indices_from_mask`` order), so
    destinations are ascending too and the within-destination rank is just
    ``position - first position of that destination``.  Returns
    ``(slot, accepted, dest)``: ``slot`` indexes the flat
    ``[W*cap_dest]`` bucket buffer (clipped/invalid slots point at the
    dump slot ``W*cap_dest``, sliced off before the collective).
    """
    k = idx.shape[0]
    W, cap = plan.world, plan.cap_dest
    dest = jnp.minimum(idx // plan.shard_n, W - 1).astype(jnp.int32)
    if valid is not None:
        # invalid (zero-padded cap-buffer) slots must not consume shard-0
        # bucket capacity: park them in the dump destination W.  Valid slots
        # are a prefix (fixed-capacity packing), so dest stays ascending.
        dest = jnp.where(valid, dest, W)
    counts = jnp.zeros((W + 1,), jnp.int32).at[dest].add(
        1, indices_are_sorted=True, mode="promise_in_bounds")
    starts = jnp.cumsum(counts) - counts              # exclusive prefix
    rank = jnp.arange(k, dtype=jnp.int32) - starts[dest]
    accepted = rank < cap
    if valid is not None:
        accepted = accepted & valid
    slot = jnp.where(accepted, dest * cap + rank, W * cap)
    return slot, accepted, dest


def sharded_combine(vals: Array, idx: Array, plan: ShardPlan,
                    axis_name: str, valid: Optional[Array] = None,
                    axis_index_groups=None):
    """Route -> owner-reduce -> return one group's ``(values, indices)``
    payload; must run inside ``shard_map`` over ``axis_name``.

    ``axis_index_groups`` restricts the exchange to disjoint subgroups of
    the axis (the hierarchical transport's DCN columns): ``plan.world``
    must then equal the group size, and the returned ``dense_units`` is the
    sum over THIS group's members only.  Grouped gathers use plain
    ``jax.lax.all_gather`` — the result genuinely differs across groups, so
    the replication-carrying invariant gather would be a lie.

    ``vals``: ``[keep]`` (element units) or ``[keep, unit_size]`` (block
    units); ``idx``: ``[keep]`` ascending int32 unit indices; ``valid``:
    optional ``[keep]`` bool marking real (non-padding) slots — a *prefix*
    of the buffer, as the fixed-capacity packing produces.

    Returns ``(dense_units, sent, route_bits, return_bits, overflow)``:

    * ``dense_units`` — the cross-worker **sum** over the padded unit space
      ``[W*shard_n(, unit_size)]`` (caller divides by world and slices);
    * ``sent`` — ``[keep]`` bool: slots that were routed AND returned, i.e.
      exactly the coordinates the synced gradient contains — ``~sent``
      survivors belong in the EF residual;
    * ``route_bits``/``return_bits`` — measured payload bits of the arrays
      handed to ``all_to_all`` / ``all_gather`` (one worker's share);
    * ``overflow`` — this worker's route-clipped count plus this owner's
      return-clipped union count (psum for the global figure).
    """
    from tpu_compressed_dp.obs import trace as obs_trace
    from tpu_compressed_dp.ops import kernels
    from tpu_compressed_dp.ops.wire import (_all_gather, _payload_bits,
                                            packed_indices_from_mask)

    W, cap, shard_n = plan.world, plan.cap_dest, plan.shard_n
    blocky = vals.ndim == 2
    if axis_index_groups is None:
        def gather(a):
            return _all_gather(a, axis_name)
    else:
        def gather(a):
            return jax.lax.all_gather(a, axis_name,
                                      axis_index_groups=axis_index_groups)
    slot, accepted, dest = _per_dest_slots(idx, valid, plan)
    local = (idx - dest * shard_n).astype(jnp.int32)

    # --- route: fixed [W, cap_dest] buckets, one all_to_all -------------
    # Empty slots carry (value 0, local index shard_n): shard_n is one past
    # the owner's unit range, so the owner's accumulators get one guard row
    # that is sliced off — padding can neither perturb a real unit nor
    # inflate the occupancy counts the return union and overflow counter
    # are built from — and the constant tail keeps every bucket row
    # monotone (filled ascending prefix) so the owner's per-row scatter
    # keeps its sorted hint.  Clipped/invalid payload slots all target the
    # dump slot W*cap, sliced off before the collective, so their values
    # need no masking.
    with obs_trace.phase("route"):
        if not blocky and kernels.use_bucket_route(idx.shape[0], W, cap):
            # fused bucket build: each destination's accepted slots are a
            # contiguous window of the ascending payload, DMA'd and masked
            # in one kernel pass (bitwise-identical buckets, monotone rows)
            bvals, bidx = kernels.fused_bucket_route(
                vals, idx, dest, W, cap, shard_n)
        else:
            bvals = jnp.zeros((W * cap + 1,) + vals.shape[1:], vals.dtype
                              ).at[slot].add(vals)[:-1]
            bidx = jnp.full((W * cap + 1,), shard_n, jnp.int32
                            ).at[slot].set(local)[:-1]
            bvals = bvals.reshape((W, cap) + vals.shape[1:])
            bidx = bidx.reshape(W, cap)
        route_bits = _payload_bits(bvals, bidx)
        rvals = jax.lax.all_to_all(
            bvals, axis_name, 0, 0,
            axis_index_groups=axis_index_groups)             # [W, cap(, bs)]
        ridx = jax.lax.all_to_all(bidx, axis_name, 0, 0,
                                  axis_index_groups=axis_index_groups)

    # --- owner reduce: W*cap scatter-adds into the dense shard ----------
    # shard_n + 1 rows: the last is the padding guard row, sliced off
    with obs_trace.phase("reduce"):
        shard = jnp.zeros((shard_n + 1,) + vals.shape[1:], vals.dtype)
        occ = jnp.zeros((shard_n + 1,), jnp.int32)
        if W <= 16:
            # per-row scatters keep the sorted hint alive (rows are monotone
            # by construction); same compile-size guard as
            # wire._scatter_combine
            for w in range(W):
                shard = shard.at[ridx[w]].add(
                    rvals[w], indices_are_sorted=True,
                    mode="promise_in_bounds")
                occ = occ.at[ridx[w]].add(
                    1, indices_are_sorted=True, mode="promise_in_bounds")
        else:
            flat_i = ridx.reshape(-1)
            shard = shard.at[flat_i].add(
                rvals.reshape((-1,) + vals.shape[1:]))
            occ = occ.at[flat_i].add(1)
        shard, occ = shard[:shard_n], occ[:shard_n]

    route_overflow = (jnp.sum(valid, dtype=jnp.int32) if valid is not None
                      else jnp.int32(idx.shape[0])
                      ) - jnp.sum(accepted, dtype=jnp.int32)

    # --- return ---------------------------------------------------------
    if plan.dense_return:
        with obs_trace.phase("return"):
            g = gather(shard)                            # [W, shard_n(, bs)]
            dense = g.reshape((W * shard_n,) + vals.shape[1:])
        return_bits = _payload_bits(shard)
        sent = accepted
        overflow = route_overflow
        return dense, sent, route_bits, return_bits, overflow

    with obs_trace.phase("return"):
        cap_ret = plan.cap_ret
        mask = occ > 0
        nnz = jnp.sum(mask, dtype=jnp.int32)
        rix = packed_indices_from_mask(mask, cap_ret)
        rvalid = jnp.arange(1, cap_ret + 1, dtype=jnp.int32) <= jnp.minimum(
            nnz, cap_ret)
        # no sorted hint: when the union underfills cap_ret the pack pads
        # trailing ranks with index 0, breaking monotonicity
        sel = shard.at[rix].get(mode="promise_in_bounds")
        sel = jnp.where(rvalid[(...,) + (None,) * (vals.ndim - 1)], sel, 0)
        rix = jnp.where(rvalid, rix, 0)
        return_bits = _payload_bits(sel, rix)
        g_vals = gather(sel)                             # [W, cap_ret(, bs)]
        g_rix = gather(rix)                              # [W, cap_ret]
        offs = jnp.arange(W, dtype=jnp.int32)[:, None] * shard_n
        gidx = (g_rix + offs).reshape(-1)
        dense = jnp.zeros((W * shard_n,) + vals.shape[1:], vals.dtype
                          ).at[gidx].add(
                              g_vals.reshape((-1,) + vals.shape[1:]))
        # Which of MY accepted coordinates actually came back: units the
        # owner clipped must return to the EF residual (their contributors
        # zeroed them locally but the synced gradient does not contain
        # them).  No sorted hint here: zero-padded cap buffers (thresholdv)
        # have index 0 in their tail slots, so ``idx`` is only ascending
        # over its valid prefix.
        returned = jnp.zeros((W * shard_n,), jnp.uint8).at[gidx].set(1)
        sent = accepted & (returned.at[idx].get(mode="promise_in_bounds") > 0)
    overflow = route_overflow + jnp.maximum(nnz - cap_ret, 0)
    return dense, sent, route_bits, return_bits, overflow
