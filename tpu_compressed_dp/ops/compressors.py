"""Gradient compression operators (TPU-native, pure JAX).

Re-implements the six compression methods of the reference harness
(`/root/reference/CIFAR10/core.py:175-213`, duplicated at
`IMAGENET/training/train_imagenet_nv.py:255-305`) as pure functions on flat
gradient vectors.  Each operator maps ``(flat_grad, key) -> flat_compressed``
where ``flat_compressed`` has the same shape as the input and zeros in the
dropped positions.  This is the dense ("simulate") representation used by the
paper's convergence-study protocol; the genuinely bandwidth-reducing packed
representations live in :mod:`tpu_compressed_dp.ops.wire`.

Design notes (TPU-first):
  * Everything is shape-static: Top-K materialises a threshold via
    ``jax.lax.top_k`` rather than a dynamically-sized index set, so the ops
    compile cleanly under ``jit`` / ``shard_map``.
  * Randomness is explicit (``jax.random`` keys) rather than global RNG state;
    the caller decides whether keys are shared across data-parallel workers
    (identical masks, as in the reference's shared-seed sparsified DDP,
    `sparsified_ddp.py:164`) or per-worker (as in the CIFAR harness, which
    never seeds and therefore draws independent masks per rank).
  * Intended behaviour is implemented where the reference has defects
    (SURVEY.md §2.3): division-by-zero in TernGrad / QSGD is guarded to
    produce zeros instead of NaN/Inf (the reference maps Inf -> 0 for QSGD
    only, `core.py:213`).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = [
    "CompressorFn",
    "identity",
    "top_k",
    "random_k",
    "randomk_mask",
    "threshold_v",
    "adaptive_threshold",
    "terngrad",
    "random_dithering",
    "get_compressor",
    "payload_bits_per_elem",
    "REGISTRY",
    "topk_keep_count",
    "randomk_keep_count",
    "block_top_k",
    "blocktopk_blocks",
    "blocktopk_scores",
    "blocktopk_num_blocks",
    "blocktopk_keep_blocks",
    "terngrad_levels",
    "terngrad_dense",
    "terngrad_num_chunks",
    "qsgd_levels",
    "leaf_key",
]

# A compressor maps a flat fp32 gradient and a PRNG key to a same-shaped
# dense vector with zeros at dropped coordinates.
CompressorFn = Callable[[Array, Array], Array]


def _flat(g: Array) -> Array:
    if g.ndim != 1:
        raise ValueError(f"compressors operate on flat vectors, got shape {g.shape}")
    return g


def topk_keep_count(n: int, ratio: float) -> int:
    """Number of elements Top-K keeps.

    The reference thresholds at ``kthvalue(ceil(n*(1-K)))`` of ``|g|`` and keeps
    elements ``>=`` that value (`core.py:181-183`), i.e. ``n - ceil(n*(1-K)) + 1``
    elements (plus ties).  We reproduce that count exactly.
    """
    import math

    m = max(1, math.ceil(n * (1.0 - ratio)))  # index (1-based) of the threshold
    return max(1, n - m + 1)


def randomk_keep_count(n: int, ratio: float) -> int:
    """Number of elements Random-K keeps: ``ceil(n*ratio)``, clamped to ``[0, n]``.

    The reference's ``randperm(n).lt(n*K)`` (`core.py:186`) keeps
    ``ceil(n*K)`` elements for fractional ``n*K`` and exactly ``n*K`` when
    integral; we compute the count statically (with a small epsilon absorbing
    binary float dust in ``n*K``) so the mask has a trace-time-known size.
    """
    import math

    return max(0, min(n, int(math.ceil(n * ratio - 1e-9))))


def blocktopk_num_blocks(n: int, block_size: int) -> int:
    return -(-n // block_size)


def blocktopk_keep_blocks(n: int, ratio: float, block_size: int) -> int:
    """Blocks Block-Top-K keeps: ``ceil(num_blocks * ratio)``, at least 1."""
    import math

    nb = blocktopk_num_blocks(n, block_size)
    return max(1, min(nb, int(math.ceil(nb * ratio - 1e-9))))


def blocktopk_blocks(g: Array, block_size: int) -> Array:
    """Zero-padded ``[num_blocks, block_size]`` view of a flat vector."""
    g = _flat(g)
    pad = (-g.shape[0]) % block_size
    return jnp.pad(g, (0, pad)).reshape(-1, block_size)


def blocktopk_scores(g: Array, block_size: int) -> Array:
    """Per-block squared-L2 scores of a flat vector (zero-padded to blocks).

    Squared norms — sqrt is monotone, so the selected set is identical and
    the threshold kernel's fp32 compare stays exact on nonnegative input.
    The single source of truth for block selection: the wire path
    (:func:`tpu_compressed_dp.ops.wire._leaf_sync_blocktopk`) calls this
    same function, so wire and simulate modes can never diverge on scoring.
    """
    flat = _flat(g).astype(jnp.float32)
    if block_size < 128 and 128 % block_size == 0:
        # small blocks: a [nb, block_size] view leaves the minor dim far
        # below the 128-lane register width — XLA pads each row to 128 lanes
        # and the reduction runs at ~1/(128/bs) efficiency (measured 32.5 ms
        # at bs=8 on a 125M vector vs ~6 ms for this path, round 5).  Keep
        # the natural [m, 128] layout and fold each row's 128/bs sub-blocks
        # with one 0/1 matmul on the MXU; zero-padding contributes zero
        # score, and phantom rows beyond nb are sliced off.
        per = 128 // block_size
        pad = (-flat.shape[0]) % 128
        x = jnp.pad(flat, (0, pad)).reshape(-1, 128)
        fold = (jnp.arange(128)[:, None] // block_size
                == jnp.arange(per)[None, :]).astype(jnp.float32)
        # HIGHEST: default matmul precision lowers fp32 operands to bf16 and
        # perturbs scores ~0.4% relative — enough to swap near-threshold
        # block selections vs the exact path (caught in r5 review)
        s = jax.lax.dot(x * x, fold, preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)
        nb = blocktopk_num_blocks(flat.shape[0], block_size)
        return s.reshape(-1)[:nb]
    x = blocktopk_blocks(flat, block_size)
    return jnp.sum(x * x, axis=1)


def block_top_k(g: Array, key: Optional[Array] = None, *, ratio: float,
                block_size: int = 256) -> Array:
    """Keep the ``~ratio`` fraction of contiguous ``block_size``-element blocks
    with the largest L2 norm; zero the rest.

    No reference equivalent — a TPU-native operator added because element-wise
    Top-K's wire form needs per-element stream compaction, while whole blocks
    gather/scatter as contiguous lane-aligned rows (no packing problem) and
    their indices cost 32/block_size bits per element.  Same contraction-style
    guarantees as Top-K for error feedback: it keeps at least as much mass as
    Random-K at equal ratio, and EF reabsorbs what the block granularity drops.
    """
    g = _flat(g)
    n = g.shape[0]
    keep = blocktopk_keep_blocks(n, ratio, block_size)
    scores = blocktopk_scores(g, block_size)
    from tpu_compressed_dp.ops import kernels

    thresh = kernels.topk_threshold(scores, keep)
    mask = jnp.repeat(scores >= thresh, block_size)[:n]
    return jnp.where(mask, g, 0.0)


def identity(g: Array, key: Optional[Array] = None) -> Array:
    """No compression (the reference's dense fallback, `core.py:215`)."""
    return _flat(g)


def leaf_key(key: Array, index: int, per_worker: bool, axis_name: str) -> Array:
    """Per-leaf (and optionally per-worker) PRNG key derivation.

    Shared by the simulate and wire sync engines so that the two modes draw
    identical randomness for identical configs: fold in the leaf index always,
    and the worker's mesh position only when masks/dither must *differ* across
    workers (``per_worker=True``; must then be called inside ``shard_map``).
    """
    k = jax.random.fold_in(key, index)
    if per_worker:
        k = jax.random.fold_in(k, jax.lax.axis_index(axis_name))
    return k


def top_k(g: Array, key: Optional[Array] = None, *, ratio: float) -> Array:
    """Keep the ``~ratio*n`` largest-magnitude coordinates (`core.py:178-183`).

    Threshold semantics match the reference: the threshold is the
    ``ceil(n*(1-ratio))``-th smallest ``|g|`` and everything ``>=`` it is kept,
    so ties at the threshold are all kept.
    """
    g = _flat(g)
    n = g.shape[0]
    keep = topk_keep_count(n, ratio)
    mag = jnp.abs(g).astype(jnp.float32)  # threshold compare in fp32 always
    # Threshold = smallest of the `keep` largest magnitudes.  Dispatches to
    # the Pallas histogram-select kernel at gradient scale on TPU (avoids
    # lax.top_k's full sort); exact top_k otherwise.
    from tpu_compressed_dp.ops import kernels

    thresh = kernels.topk_threshold(mag, keep)
    return jnp.where(mag >= thresh, g, 0.0)


def randomk_mask(key: Array, n: int, keep: int) -> Array:
    """Boolean mask selecting a uniformly-random ``keep``-subset of ``[0, n)``.

    The reference draws ``randperm(n).lt(k)`` (`core.py:186`) — a full sort.
    TPU-native formulation: the ``keep`` *largest* of ``n`` iid uniforms are a
    uniform random subset, and their threshold comes from the O(n)-streaming
    histogram-select kernel (:func:`ops.kernels.topk_threshold`) instead of a
    sort.  Uniform draws collide at fp32 resolution for large ``n``, so the
    boundary value's ties are broken deterministically by index (one cumsum),
    keeping the subset size exact.
    """
    if keep <= 0:
        return jnp.zeros((n,), bool)
    if keep >= n:
        return jnp.ones((n,), bool)
    from tpu_compressed_dp.ops import kernels

    w = kernels.uniform(key, n)
    t = kernels.topk_threshold(w, keep)
    over = w >= t
    # the smallest selected value may be duplicated; keep exactly `keep`
    boundary = jnp.min(jnp.where(over, w, jnp.inf))
    above = w > boundary
    n_above = jnp.sum(above)
    tie = w == boundary
    tie_sel = tie & (jnp.cumsum(tie) <= keep - n_above)
    return above | tie_sel


def random_k(g: Array, key: Array, *, ratio: float) -> Array:
    """Keep a uniformly-random subset of ``~ratio*n`` coordinates (`core.py:184-188`).

    The caller controls mask agreement across workers through the key: a
    replicated key reproduces the shared-seed trick of the sparsified DDP
    (`sparsified_ddp.py:164`); folding in the worker index reproduces the
    unseeded per-rank masks of the CIFAR harness.
    """
    g = _flat(g)
    n = g.shape[0]
    mask = randomk_mask(key, n, randomk_keep_count(n, ratio))
    return jnp.where(mask, g, 0.0)


def threshold_v(g: Array, key: Optional[Array] = None, *, threshold: float) -> Array:
    """Keep coordinates with ``|g| >= V`` (`core.py:189-193`)."""
    g = _flat(g)
    return jnp.where(jnp.abs(g) >= threshold, g, 0.0)


def adaptive_threshold(g: Array, key: Optional[Array] = None) -> Array:
    """Keep coordinates with ``2|g| >= max|g|`` (`core.py:194-199`)."""
    g = _flat(g)
    gmax = jnp.max(jnp.abs(g))
    return jnp.where(2.0 * jnp.abs(g) >= gmax, g, 0.0)


def terngrad_num_chunks(n: int, chunk: int) -> int:
    """Scale chunks TernGrad uses: 1 (scalar global max) when chunking is off
    or the vector fits in one chunk, else ``ceil(n / chunk)``."""
    if chunk <= 0 or n <= chunk:
        return 1
    return -(-n // chunk)


def terngrad_prescale(g: Array, chunk: int) -> tuple[Array, Array]:
    """Chunked TernGrad prescale: divide each ``chunk``-sized slice by its
    own ``max|g|`` so the quantiser sees a unit-scale vector
    (``|scaled| <= 1``).  Returns ``(scaled f32[n], gmax f32[num_chunks])``.
    Factored out of :func:`terngrad_levels` so the fused quantize+pack
    kernel path (:func:`tpu_compressed_dp.ops.kernels.terngrad_pack_prescaled`)
    can consume the prescaled vector without round-tripping int8 levels."""
    g = _flat(g)
    n = g.shape[0]
    nc = terngrad_num_chunks(n, chunk)
    pad = nc * chunk - n
    g2 = jnp.pad(g.astype(jnp.float32), (0, pad)).reshape(nc, chunk)
    gmax = jnp.max(jnp.abs(g2), axis=1)                      # [nc]
    inv = jnp.where(gmax > 0, 1.0 / jnp.where(gmax > 0, gmax, 1.0), 0.0)
    scaled = (g2 * inv[:, None]).reshape(-1)[:n]             # |scaled| <= 1
    return scaled, gmax


def terngrad_levels(g: Array, key: Array, *, chunk: int = 0) -> tuple[Array, Array]:
    """TernGrad's integer representation: ``(levels int8 in {-1,0,1}, scale)``.

    The dense estimator is ``scale * levels``; the wire path transmits the
    int8 levels + the scale(s) instead.  A zero gradient maps to zero levels
    (the reference would produce NaN via 0/0; SURVEY.md §2.3).

    ``chunk > 0`` bounds the scale granularity: one max per ``chunk``
    elements (``scale`` comes back as a ``[num_chunks]`` vector).  This is
    the resolution of the entire-model blow-up (VERDICT r2): a single
    ``max|g|`` over millions of parameters drives every keep-probability
    ``|g_i|/max|g|`` toward zero and the estimator variance unbounded — the
    reference's entire-model path was dead code (SURVEY.md §2.3.2), so there
    is no working behavior to match; chunked scales give the entire-model
    granularity layer-wise-like statistics while still transporting int8
    levels + a negligible ``32*num_chunks`` bits of scales.
    """
    g = _flat(g)
    n = g.shape[0]
    from tpu_compressed_dp.ops import kernels

    if terngrad_num_chunks(n, chunk) == 1:
        if kernels.use_quant_kernels(n):
            return kernels.terngrad_quantize(g, key)
        mag = jnp.abs(g)
        gmax = jnp.max(mag)
        prob = jnp.where(gmax > 0, mag / jnp.where(gmax > 0, gmax, 1.0), 0.0)
        coin = jax.random.uniform(key, g.shape, dtype=g.dtype)
        levels = (jnp.sign(g) * (coin < prob)).astype(jnp.int8)
        return levels, gmax
    # chunked: normalise each chunk by its own max, then ternarise the
    # prescaled vector with unit scale (one extra elementwise pass; the
    # quantisation pass itself is unchanged)
    scaled, gmax = terngrad_prescale(g, chunk)
    if kernels.use_quant_kernels(n):
        levels = kernels.terngrad_quantize_prescaled(scaled, key)
    else:
        coin = jax.random.uniform(key, (n,), dtype=jnp.float32)
        levels = (jnp.sign(scaled) * (coin < jnp.abs(scaled))).astype(jnp.int8)
    return levels, gmax


def terngrad_dense(levels: Array, scale: Array, chunk: int,
                   dtype=jnp.float32) -> Array:
    """Reassemble the dense estimator from ``terngrad_levels`` output
    (broadcasting per-chunk scales when ``scale`` is a vector)."""
    if scale.ndim == 0:
        return scale.astype(dtype) * levels.astype(dtype)
    n = levels.shape[0]
    nc = scale.shape[0]
    pad = nc * chunk - n
    lv = jnp.pad(levels, (0, pad)).reshape(nc, chunk).astype(dtype)
    return (scale.astype(dtype)[:, None] * lv).reshape(-1)[:n]


def terngrad(g: Array, key: Array, *, chunk: int = 0) -> Array:
    """TernGrad ternarisation (`core.py:200-206`).

    ``out_i = max|g| * sign(g_i) * Bernoulli(|g_i| / max|g|)`` — an unbiased
    estimator of ``g``; the max is per ``chunk`` elements when chunking is on
    (see :func:`terngrad_levels`).
    """
    levels, scale = terngrad_levels(g, key, chunk=chunk)
    return terngrad_dense(levels, scale, chunk, dtype=g.dtype)


def qsgd_levels(g: Array, key: Array, *, qstates: int = 255) -> tuple[Array, Array]:
    """QSGD's integer representation: ``(sign⊗level int16 in [-s, s], scale)``.

    The dense estimator is ``scale * levels``; the wire path transmits the
    int16 levels + one scale.  ``scale = ||g||/s`` with the reference's
    zero-norm → zero-output guard (`core.py:213`) folded into the scale.
    """
    g = _flat(g)
    from tpu_compressed_dp.ops import kernels

    if kernels.use_quant_kernels(g.shape[0]):
        return kernels.qsgd_quantize(g, key, qstates=qstates)
    norm = jnp.linalg.norm(g)
    safe_norm = jnp.where(norm > 0, norm, 1.0)
    u = jax.random.uniform(key, g.shape, dtype=g.dtype)
    levels = jnp.floor(jnp.abs(g) / safe_norm * qstates + u)  # in [0, qstates]
    levels = (jnp.sign(g) * levels).astype(jnp.int16)
    scale = jnp.where(norm > 0, norm, 0.0) / qstates
    return levels, scale


def random_dithering(g: Array, key: Array, *, qstates: int = 255) -> Array:
    """Random dithering / QSGD quantisation (`core.py:207-213`).

    ``out_i = ||g||_2 * sign(g_i) * floor(|g_i|/||g|| * s + u_i) / s`` with
    ``u_i ~ U[0,1)`` — unbiased stochastic rounding onto ``s`` levels.
    """
    levels, scale = qsgd_levels(g, key, qstates=qstates)
    return scale * levels.astype(g.dtype)


@dataclasses.dataclass(frozen=True)
class _Bound:
    """A compressor with its hyper-parameters bound, keyed by canonical name."""

    name: str
    fn: CompressorFn
    needs_rng: bool

    @property
    def is_sparsifier(self) -> bool:
        """Sparsifiers send only the surviving coordinates; quantizers
        (terngrad/qsgd) and identity send every coordinate at reduced width."""
        return self.name in ("topk", "randomk", "thresholdv",
                             "adaptive_threshold", "blocktopk")

    @property
    def is_stateful(self) -> bool:
        """Stateful compressors carry a persistent warm-start pytree through
        the sync (``TrainState.comp``); the sync engines special-case them —
        ``fn`` here is the stateless single-shot form."""
        return self.name == "powersgd"


def payload_bits_per_elem(
    name: str, *, qstates: int = 255, shared_mask: bool = False,
    block_size: int = 256, rank: int = 4, n: Optional[int] = None
) -> float:
    """Analytic wire width of one transmitted element, in bits.

    This is the accounting the reference measured empirically from
    /proc/net/dev (`meter.py:24-47`); on TPU the payload layout is known —
    and, as of round 4, every width below is the layout the wire engine
    *actually transports* (``ops.wire`` bit-packs the quantizers and bills
    measured payload bytes; ``tests/test_wire.py::TestMeasuredTransport``
    asserts the wire bill equals the collective's bytes).  This analytic
    model amortises the fp32 scales and the pad-to-4/pad-to-8 packing slack
    away, so wire-mode ``sent_bits`` runs a hair above ``n × width`` (e.g.
    ~2.02 vs 2.0 bits/elem for TernGrad at small leaves):
      * dense fp32 value: 32;
      * sparsifier: 32-bit value + 32-bit index, except shared-seed Random-K
        whose indices are implied by the common PRNG key
        (`sparsified_ddp.py:164` — only k values travel, `:412`);
      * Block-Top-K: 32-bit value + one 32-bit block index per block_size
        elements;
      * TernGrad: 2 bits per element — four ternary codes bit-packed per
        byte (:func:`ops.wire.pack_ternary`) + fp32 scale(s) (amortised);
      * QSGD/random dithering: narrowest fixed-width layout that fits
        ``qstates`` (:func:`ops.wire.qsgd_wire_pack`): int8 sign⊗level for
        ``qstates <= 127`` (8), uint8 magnitude + 1 packed sign bit for
        ``qstates <= 255`` (9), int16 beyond (16); + one fp32 norm
        (amortised).  The QSGD paper's variable-length bound is tighter but
        these are the fixed-width layouts the TPU collective moves;
      * PowerSGD: the two fp32 factors, ``32·r·(m + n/m) / n`` bits per
        element — shape-dependent, so ``n`` (the group's element count) is
        required; dense-fallback groups (factors >= dense) bill 32.  Unlike
        every sparsifier payload, the factors psum-reduce on the ring.
    """
    if name == "powersgd":
        if n is None:
            raise ValueError(
                "powersgd wire width is shape-dependent; pass n (the flat "
                "group's element count)")
        from tpu_compressed_dp.ops import lowrank

        return lowrank.powersgd_group_bits(n, rank) / n
    if name in ("none", "thresholdv", "adaptive_threshold", "topk"):
        return 32.0 if name == "none" else 64.0
    if name == "randomk":
        return 32.0 if shared_mask else 64.0
    if name == "blocktopk":
        return 32.0 + 32.0 / block_size
    if name == "terngrad":
        return 2.0
    if name == "qsgd":
        return 8.0 if qstates <= 127 else (9.0 if qstates <= 255 else 16.0)
    raise ValueError(f"unknown compressor {name!r}")


# Canonical names plus the reference CLI spellings (`dawn.py:16`,
# `train_imagenet_nv.py`): Topk / Randomk / Thresholdv / AdaptiveThreshold /
# TernGrad / RandomDithering.
_ALIASES = {
    "topk": "topk",
    "blocktopk": "blocktopk",
    "block_topk": "blocktopk",
    "blocktop_k": "blocktopk",
    "randomk": "randomk",
    "thresholdv": "thresholdv",
    "adaptivethreshold": "adaptive_threshold",
    "adaptive_threshold": "adaptive_threshold",
    "terngrad": "terngrad",
    "randomdithering": "qsgd",
    "random_dithering": "qsgd",
    "qsgd": "qsgd",
    "powersgd": "powersgd",
    "power_sgd": "powersgd",
    "lowrank": "powersgd",
    "none": "none",
    "dense": "none",
}

REGISTRY = ("none", "topk", "blocktopk", "randomk", "thresholdv",
            "adaptive_threshold", "terngrad", "qsgd", "powersgd")


def canonical_name(method: Optional[str]) -> str:
    """Resolve a method spelling (canonical or reference CLI alias) to its
    canonical name; raises on unknown spellings like :func:`get_compressor`."""
    if method is None:
        return "none"
    canon = _ALIASES.get(method.lower().replace("-", "_"))
    if canon is None:
        raise ValueError(f"unknown compression method {method!r}; known: {REGISTRY}")
    return canon


def get_compressor(
    method: Optional[str],
    *,
    ratio: float = 0.5,
    threshold: float = 1e-3,
    qstates: int = 255,
    block_size: int = 256,
    terngrad_chunk: int = 1 << 21,
    rank: int = 4,
) -> _Bound:
    """Resolve a method name (canonical or reference spelling) to a bound op.

    Mirrors the dispatch in `core.py:178-215` — unknown methods fall through to
    dense there; here they raise, since silent fallthrough hid the reference's
    'enitremodel' bug (SURVEY.md §2.3).
    """
    canon = canonical_name(method)
    if canon == "none":
        return _Bound("none", lambda g, key=None: identity(g), needs_rng=False)
    if canon == "topk":
        return _Bound("topk", lambda g, key=None: top_k(g, key, ratio=ratio), needs_rng=False)
    if canon == "blocktopk":
        return _Bound(
            "blocktopk",
            lambda g, key=None: block_top_k(g, key, ratio=ratio, block_size=block_size),
            needs_rng=False,
        )
    if canon == "randomk":
        return _Bound("randomk", lambda g, key: random_k(g, key, ratio=ratio), needs_rng=True)
    if canon == "thresholdv":
        return _Bound(
            "thresholdv", lambda g, key=None: threshold_v(g, key, threshold=threshold), needs_rng=False
        )
    if canon == "adaptive_threshold":
        return _Bound("adaptive_threshold", lambda g, key=None: adaptive_threshold(g), needs_rng=False)
    if canon == "terngrad":
        return _Bound(
            "terngrad",
            lambda g, key: terngrad(g, key, chunk=terngrad_chunk),
            needs_rng=True,
        )
    if canon == "qsgd":
        return _Bound("qsgd", lambda g, key: random_dithering(g, key, qstates=qstates), needs_rng=True)
    if canon == "powersgd":
        # the stateless single-shot form (one power iteration from a
        # key-derived Q0); the sync engines special-case the warm-started
        # stateful path — see ops/lowrank.py and parallel/dp.py
        from tpu_compressed_dp.ops import lowrank

        return _Bound(
            "powersgd",
            lambda g, key: lowrank.powersgd_approx(g, key, rank=rank),
            needs_rng=True,
        )
    raise AssertionError(canon)
