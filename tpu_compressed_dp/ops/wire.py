"""Wire-sparse gradient sync: genuinely bandwidth-reducing payloads.

The reference's simulated compression allreduces a full-size zero-filled dense
tensor (`CIFAR10/core.py:218,278`) — only `RandomKSparsifiedDDP` actually
shrinks the payload, by `masked_select`-ing k elements per parameter into the
reduction bucket (`IMAGENET/training/sparsified_ddp.py:412,460-462`) and
relying on a shared RNG seed so every rank picks the same indices
(`sparsified_ddp.py:164`).  This module is the TPU-native generalisation of
that path (``mode='wire'`` of :class:`~tpu_compressed_dp.parallel.dp.CompressionConfig`),
covering all six reference operators plus the net-new Block-Top-K:

  * **Random-K** (the `RandomKSparsifiedDDP` equivalent): a PRNG key shared by
    all workers selects identical coordinates; only the k surviving *values*
    travel, packed into a ``[k]`` buffer that is ``lax.psum``-reduced.  Indices
    never travel — they are implied by the common key.  Unlike the reference
    (which returns the **sum**, `sparsified_ddp.py:481-483` + §3.3 note), the
    reduced values are divided by world size, consistent with every other path
    here.
  * **Top-K**: worker-local index sets differ, so values *and* indices travel:
    fixed-size ``([k] values, [k] int32 indices)`` pairs are ``all_gather``-ed
    and scatter-added into a dense vector.  Exactly ``k = topk_keep_count(n)``
    elements are kept per worker (fixed-size for XLA); the simulate path's
    keep-all-ties semantics (`core.py:181-183`) can keep a few more — the two
    modes agree whenever ``|g|`` has no ties at the threshold.
  * **Block-Top-K** (net-new, no reference equivalent): element Top-K's wire
    form needs per-element stream compaction of the full gradient; selecting
    whole contiguous blocks by L2 norm instead moves the compaction onto the
    ~n/block_size block *scores*, and the payload — ``[kb, block_size]``
    value rows + ``[kb]`` block indices — gathers/scatters as contiguous
    lane-aligned rows.  The TPU-native fast path among the sparsifiers.
  * **TernGrad**: per-worker ternary levels bit-packed four-per-byte
    (codes ``level+1 ∈ {0,1,2}`` → 2 bits each) plus the fp32 scale(s),
    combined via ``all_gather`` — the collective moves the 2 bits/elem the
    analytic accounting bills (round 4; previously int8 shipped while 2 bits
    were billed, a 4× understatement).
  * **QSGD / random dithering**: narrowest layout that fits ``qstates``:
    ``sign ⊗ level`` int8 for ``qstates ≤ 127`` (8 bits/elem), uint8
    magnitudes + a bit-packed sign bitmap for ``qstates ≤ 255`` (9 bits/elem),
    int16 beyond; plus one fp32 norm, combined via ``all_gather``.
  * **Threshold-V / Adaptive-Threshold** (`core.py:189-199`): survivor
    counts are data-dependent — hostile to XLA's static shapes — so the wire
    form is a **fixed-capacity buffer**: each worker packs its first
    ``cap = wire_cap_ratio * n`` surviving coordinates (ascending index)
    into ``([cap] values, [cap] int32 indices)``, zero-padding unused slots
    (padded slots carry idx 0 / value 0 — additive identities under the
    scatter-add combine).  Survivors beyond ``cap`` stay in the error
    feedback residual when EF is on, and are *dropped* (exactly as if below
    threshold) when it is off; ``comm/threshold_overflow`` reports the
    clipped count so capacity can be sized.  Transport is the full
    cap-sized buffer, and the analytic accounting bills it as such
    (``sent_bits = cap * 64`` even when half-empty — fixed-size transport
    is the honest wire cost).

The index-carrying sparsifiers (Top-K, Block-Top-K, Threshold-V/Adaptive)
support two combines, selected by ``CompressionConfig.transport``: the flat
``all_gather`` described above (per-chip volume and decode ``O(W*k)``), or
the owner-sharded sparse reduce (``transport='sharded'``,
:mod:`tpu_compressed_dp.ops.wire_sharded`): pairs route to contiguous shard
owners over one ``lax.all_to_all``, owners scatter-add their dense ``n/W``
shard, and the reduced shards return via one ``all_gather`` — per-chip
``O(k + n/W)``, the scalable regime at large worker counts (OKTopk,
PAPERS.md).  ``transport='hierarchical'`` adds a two-level reduce over a
``dp_pods x dp_chips`` virtual mesh: dense psum along the fast intra-pod
ICI axis, re-compress the pod union, and exchange only (value, index)
pairs across the slow DCN axis via the sharded bucket-route machinery —
per-chip DCN volume ``O(k + n/W_pods)``, billed per fabric.
``parallel.dp.wire_transport`` is the classifier (psum / allgather /
sharded / hierarchical) behind the ``sent_bits_psum`` /
``sent_bits_allgather`` / ``sent_bits_alltoall`` — and, hierarchical,
``sent_bits_ici`` / ``sent_bits_dcn`` — accounting split.

All wire methods bill **measured transport**: ``sent_bits`` is computed from
the actual byte sizes of the arrays handed to the collective (including
scales/norms), the TPU-static analog of the reference's NIC byte meter
(`IMAGENET/training/meter.py:24-47,66-86`).

Error feedback composes with the sparsifiers exactly as in
`sparsified_ddp.py:408-413`: the residual (dropped coordinates) is returned
for the caller to re-add next step.  Quantizers are unbiased estimators and
get a zero residual.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from tpu_compressed_dp.ops import compressors

Array = jax.Array

__all__ = ["make_wire_grad_sync", "WIRE_METHODS", "pack_ternary",
           "unpack_ternary", "pack_bits", "unpack_bits", "qsgd_wire_pack",
           "qsgd_wire_unpack", "packed_indices_monotone", "select_pack_topk"]

WIRE_METHODS = ("randomk", "topk", "blocktopk", "terngrad", "qsgd",
                "thresholdv", "adaptive_threshold")

try:
    # The gathered payload is identical on every worker; the *_invariant
    # variant carries that fact in the type so shard_map's replication
    # checker accepts replicated out_specs downstream (plain all_gather
    # keeps the device-varying tag).
    from jax._src.lax.parallel import all_gather_invariant as _all_gather
except ImportError:  # pragma: no cover - older/newer jax layouts
    _all_gather = jax.lax.all_gather


def pack_ternary(levels: Array) -> Array:
    """Bit-pack ternary levels (int8 in {-1,0,1}) four-per-byte.

    Codes ``level+1 ∈ {0,1,2}`` occupy 2 bits; byte layout is little-endian
    within the byte (element i sits at bits ``2*(i%4)``).  Output is
    ``uint8[ceil(n/4)]`` — the actual wire form TernGrad's all_gather moves.
    Arithmetic runs in int32 (TPU-native lane width); only the final cast is
    uint8, so no sub-word shift ops are required of Mosaic/XLA.
    """
    n = levels.shape[0]
    pad = (-n) % 4
    c = jnp.pad(levels, (0, pad)).astype(jnp.int32) + 1  # {0,1,2}
    c = c.reshape(-1, 4)
    packed = c[:, 0] + (c[:, 1] << 2) + (c[:, 2] << 4) + (c[:, 3] << 6)
    return packed.astype(jnp.uint8)


def unpack_ternary(packed: Array, n: int) -> Array:
    """Inverse of :func:`pack_ternary`: ``uint8[ceil(n/4)] -> int8[n]``."""
    p = packed.astype(jnp.int32)
    codes = jnp.stack(
        [p & 3, (p >> 2) & 3, (p >> 4) & 3, (p >> 6) & 3], axis=-1)
    return (codes.reshape(*packed.shape[:-1], -1)[..., :n] - 1).astype(jnp.int8)


def pack_bits(bits: Array) -> Array:
    """Pack a boolean vector eight-per-byte (little-endian within the byte)."""
    n = bits.shape[0]
    pad = (-n) % 8
    b = jnp.pad(bits, (0, pad)).astype(jnp.int32).reshape(-1, 8)
    w = (1 << jnp.arange(8, dtype=jnp.int32))
    return jnp.sum(b * w, axis=1).astype(jnp.uint8)


def unpack_bits(packed: Array, n: int) -> Array:
    """Inverse of :func:`pack_bits`: ``uint8[ceil(n/8)] -> bool[n]``."""
    p = packed.astype(jnp.int32)
    bits = jnp.stack([(p >> i) & 1 for i in range(8)], axis=-1)
    return bits.reshape(*packed.shape[:-1], -1)[..., :n].astype(bool)


def qsgd_wire_pack(levels: Array, qstates: int) -> tuple[Array, ...]:
    """Narrowest wire layout for QSGD ``sign ⊗ level`` int16 levels.

    * ``qstates <= 127``: one int8 array (sign and magnitude share the byte);
    * ``qstates <= 255``: uint8 magnitudes + a bit-packed sign bitmap
      (9 bits/elem — the fixed-width layout `payload_bits_per_elem` bills);
    * beyond: the int16 levels unchanged (16 bits/elem).
    """
    if qstates <= 127:
        return (levels.astype(jnp.int8),)
    if qstates <= 255:
        mags = jnp.abs(levels.astype(jnp.int32)).astype(jnp.uint8)
        signs = pack_bits(levels < 0)
        return (mags, signs)
    return (levels,)


def qsgd_wire_unpack(payload: tuple[Array, ...], n: int, qstates: int,
                     dtype=jnp.float32) -> Array:
    """Inverse of :func:`qsgd_wire_pack`, returning ``sign ⊗ level`` in
    ``dtype`` (ready to scale); accepts a leading gather axis."""
    if qstates <= 127 or qstates > 255:
        return payload[0].astype(dtype)
    mags, signs = payload
    neg = unpack_bits(signs, n)
    return jnp.where(neg, -mags.astype(dtype), mags.astype(dtype))


def _sorted_gather(a: Array, idx: Array) -> Array:
    """``a[idx]`` where ``idx`` is known ascending (not necessarily unique)
    and in bounds.  The hints matter at wire scale: XLA's general gather
    assumes arbitrary indices; sorted+in-bounds lowers to a cheaper sequence
    on TPU for the k~1M element-granular loads this path lives on."""
    return a.at[idx].get(indices_are_sorted=True, mode="promise_in_bounds")


def _select_pack(flat: Array, mag: Array, t, keep: int):
    """``(payload [keep], idx [keep], survivor count)``: the coordinates
    with ``mag >= t`` by ascending index — the wire select+pack step.

    One fused Pallas pass (`kernels.fused_select_pack`) when dispatched;
    otherwise the XLA mask -> `packed_indices_from_mask` -> `_sorted_gather`
    chain.  Payloads are bitwise identical across the two paths whenever
    ``count >= keep`` (`topk_threshold`'s guarantee; parity-gated in
    tests/test_kernels.py) — underfull masks differ only in the padding
    slots, which every caller re-masks or treats as scatter identities."""
    from tpu_compressed_dp.ops import kernels

    if kernels.use_select_pack(flat.shape[0], keep):
        return kernels.fused_select_pack(flat, t, keep)
    mask = mag >= t
    idx = packed_indices_from_mask(mask, keep)
    return _sorted_gather(flat, idx), idx, jnp.sum(mask, dtype=jnp.int32)


def select_pack_topk(flat: Array, keep: int):
    """Top-``keep``-by-magnitude select+pack of a flat vector: the wire
    compress step (threshold + select + pack, Pallas-fused when
    dispatched) exposed for non-gradient payloads — the delta stream in
    :mod:`tpu_compressed_dp.stream` runs it on parameter drift.

    Returns ``(payload [keep], idx [keep] ascending, survivor count)``;
    when ``count < keep`` (underfull mask — e.g. non-finite inputs)
    trailing ranks pad with index 0 and callers must trim to
    ``min(count, keep)``.  Magnitudes are computed internally (``|flat|``
    in fp32) because the fused kernel recomputes them from ``flat``."""
    from tpu_compressed_dp.ops import kernels

    mag = jnp.abs(flat).astype(jnp.float32)
    t = kernels.topk_threshold(mag, keep)
    return _select_pack(flat, mag, t, keep)


def _scatter_combine(shape, dtype, g_idx: Array, g_vals: Array, world,
                     block_size: int = 0) -> Array:
    """Gathered ``[W, k]`` (indices, values) payload -> dense sum / world.

    Each worker's index row is ascending and unique by construction
    (`packed_indices_from_mask`), but a flattened ``[W*k]`` scatter-add
    forfeits that: XLA must assume arbitrary duplicate order.  Per-row
    scatters keep the ``indices_are_sorted`` / ``unique_indices`` hints
    alive; ``W`` is a static mesh size so the loop unrolls at trace time.
    Beyond 16 rows fall back to the single fused scatter (compile-size
    guard — the hint's win is per-element dispatch, already amortised at
    large ``W``).  ``block_size > 0`` scatters contiguous value rows
    (Block-Top-K payloads, ``g_vals: [W, kb, bs]``).
    """
    W = g_idx.shape[0]
    dense = jnp.zeros(shape, dtype)
    if W <= 16:
        for w in range(W):
            dense = dense.at[g_idx[w]].add(
                g_vals[w], indices_are_sorted=True, unique_indices=True,
                mode="promise_in_bounds")
    else:
        vals = (g_vals.reshape(-1, block_size) if block_size
                else g_vals.reshape(-1))
        dense = dense.at[g_idx.reshape(-1)].add(vals)
    return dense / world


def packed_indices_from_mask(mask: Array, keep: int) -> Array:
    """Ascending indices of the first ``keep`` True positions of ``mask``.

    Precondition: the mask should have at least ``keep`` set bits; ranks
    beyond the actual count degrade benignly to index 0 (the same fill
    ``jnp.nonzero(size=keep, fill_value=0)`` used).

    ``jnp.nonzero(size=)`` and a flat 1-D cumsum both lower poorly on TPU at
    gradient scale (~400ms / ~190ms at 42M elements).  Hierarchical stream
    compaction instead: per-128-lane-row counts (one linear reduce), a small
    cumsum over row totals, then a rank->row map via bucketing each row's
    inclusive end and prefix-summing — ``row_of[r-1] = #{i : row_ends[i] < r}``
    (== searchsorted(row_ends, r, left)) — which replaced a binary search's
    serialized gather chain (258ms -> ~25ms at 170M, round 2).

    The per-rank stage is TWO gathers per rank (round 5; was three + an
    fp32 tri-matmul): per-rank costs are billed per random ACCESS, and the
    round-5 bisect (tools/wire_profile.py --subs and the scratch bisect in
    benchmarks/wire_wall_r5.txt) measured ~7 ms per [keep]-sized gather at
    keep=1.25M — so gathering ``row_ends`` and ``row_counts`` separately
    just to subtract them was a wasted 8 ms: one precomputed ``row_starts``
    array halves that stage.  The in-row prefix matmul runs in bf16 (row
    prefix counts are <= 128, exactly representable), halving the gathered
    rows' materialisation traffic vs fp32.  Two rejected redesigns, both
    measured slower: bit-packing rows into uint32 words for a single
    32-byte-row gather (the uint32 pack pass itself costs ~30 ms — integer
    multiply-reduce over the full tensor does not vectorise well on the
    VPU), and a full-tensor scatter formulation emitting (idx, val) pairs
    elementwise (XLA does not stream sorted 125M-update scatters: 2.2 s).
    """
    lanes = 128
    n = mask.shape[0]
    pad = (-n) % lanes
    m2 = jnp.pad(mask, (0, pad)).reshape(-1, lanes)
    nrows = m2.shape[0]
    row_counts = jnp.sum(m2, axis=1, dtype=jnp.int32)
    # NB: plain 1-D cumsum here — at the ~n/128 and ~keep sizes these run at,
    # XLA's native scan beats a hand-rolled two-level decomposition (measured
    # +18ms/step at LM scale from a hier_cumsum variant, round 2)
    row_ends = jnp.cumsum(row_counts)                      # inclusive offsets
    ranks = jnp.arange(1, keep + 1, dtype=jnp.int32)
    # row_ends is a cumsum — monotone — so the histogram scatter and the
    # gathers below ride the sorted-indices fast path
    ends_hist = jnp.zeros((keep + 1,), jnp.int32).at[
        jnp.minimum(row_ends, keep)].add(
            1, indices_are_sorted=True, mode="promise_in_bounds")
    row_of = jnp.cumsum(ends_hist)[:keep]
    valid = row_of < nrows                                 # rank <= total count
    # pad invalid ranks with the LAST row (not row 0): keeps row_of monotone
    # so the sorted-gather hints stay truthful; the final jnp.where still
    # returns index 0 for invalid ranks
    row_of = jnp.where(valid, row_of, nrows - 1)
    # rank within the row: global rank minus everything before the row —
    # ONE gather of the precomputed starts, not two of ends and counts
    row_starts = _sorted_gather(row_ends - row_counts, row_of)
    within = ranks - row_starts                            # 1-based in-row rank
    rows = _sorted_gather(m2, row_of).astype(jnp.bfloat16)  # [keep, 128]
    tri = jnp.tril(jnp.ones((lanes, lanes), jnp.bfloat16))
    # inclusive in-row prefix on the MXU; counts <= 128 are bf16-exact
    prefix = jax.lax.dot(rows, tri.T,
                         preferred_element_type=jnp.float32)
    hit = (prefix >= within[:, None].astype(jnp.float32)) & (rows > 0)
    col = jnp.argmax(hit, axis=1).astype(jnp.int32)
    return jnp.where(valid, row_of * lanes + col, 0)


def packed_indices_monotone(idx: Array) -> Array:
    """Debug predicate for the ``indices_are_sorted``/``unique_indices``
    scatter hints downstream of :func:`packed_indices_from_mask`: True iff
    ``idx`` is strictly ascending (ascending AND unique), which holds
    exactly when the source mask had at least ``keep`` set bits.

    The known violation is a non-finite gradient: NaNs compare false
    against the Top-K threshold, the mask underfills, and the pack pads
    trailing ranks with duplicate index 0 — at which point the hinted
    scatters in `_scatter_combine` and the EF zeroing are undefined rather
    than benignly degraded.  Run with this check (outside the hot path —
    it is a debug aid, not a runtime guard) when chasing corruption under
    suspected overflow/NaN gradients; tests/test_wire_sharded.py pins both
    directions of the predicate.
    """
    return jnp.all(idx[1:] > idx[:-1]) if idx.shape[0] > 1 else jnp.asarray(True)


def _randomk_indices(key: Array, n: int, keep: int) -> Array:
    """The coordinates Random-K keeps, bit-identical to the simulate mask
    (same ``randomk_mask`` call, so wire and simulate modes always agree)."""
    mask = compressors.randomk_mask(key, n, keep)
    return packed_indices_from_mask(mask, keep)


def _leaf_sync_randomk(flat: Array, key: Array, keep: int, axis_name: str, world,
                       check: bool = False):
    idx = _randomk_indices(key, flat.shape[0], keep)
    payload = _sorted_gather(flat, idx)                   # [k] — all that travels
    bits = _payload_bits(payload)
    reduced = jax.lax.psum(payload, axis_name) / world
    # NB: fresh zeros, not zeros_like(flat) — the latter would inherit the
    # device-varying manifest-axes tag of the local gradient and defeat
    # shard_map's replication inference for the psum-reduced result.
    dense = jnp.zeros(flat.shape, flat.dtype).at[idx].set(
        reduced, indices_are_sorted=True, unique_indices=True,
        mode="promise_in_bounds")
    agree = None
    if check:
        # `check_reduction` analog: all workers must have selected the SAME
        # indices or the packed psum silently mixes coordinates
        h = jnp.sum(idx.astype(jnp.float64 if jax.config.jax_enable_x64
                               else jnp.float32) * (1.0 + jnp.arange(keep) % 7))
        agree = (jax.lax.pmax(h, axis_name) == jax.lax.pmin(h, axis_name)
                 ).astype(jnp.float32)
    return dense, idx, agree, bits


def _leaf_sync_topk(flat: Array, keep: int, axis_name: str, world,
                    want_surplus: bool = False):
    # threshold-select + hierarchical pack instead of lax.top_k's full sort;
    # near-threshold membership can differ from exact top-k by a few elements
    # at the histogram's final-bin resolution (error feedback reabsorbs the
    # difference).  fp32 magnitudes keep the count >= keep guarantee that
    # packed_indices_from_mask requires.
    from tpu_compressed_dp.ops import kernels

    mag = jnp.abs(flat).astype(jnp.float32)
    t = kernels.topk_threshold(mag, keep)
    payload, idx, count = _select_pack(flat, mag, t, keep)
    bits = _payload_bits(payload, idx)
    g_vals = _all_gather(payload, axis_name)       # [W, k]
    g_idx = _all_gather(idx, axis_name)            # [W, k]
    dense = _scatter_combine(flat.shape, flat.dtype, g_idx, g_vals, world)
    # above-threshold survivors beyond `keep` (histogram bin-resolution ties/
    # surplus) are truncated by ascending index; with EF off they are silently
    # dropped — surface the count so callers can see it (ADVICE r2)
    surplus = jnp.maximum(count - keep, 0) if want_surplus else None
    return dense, idx, surplus, bits


def _leaf_sync_topk_seg(flat: Array, keep: int, axis_name: str, world,
                        want_ef: bool):
    """Element Top-K wire sync via the segmented shift-network pack kernel
    (`kernels.seg_pack_by_threshold`): one fused pass computes per-segment
    compacted (values, indices) AND the EF residual elementwise — replacing
    the mask->rank->gather chain plus the k-sized EF scatter.

    Selection diverges from `_leaf_sync_topk` only when a 4096-element
    segment holds >128 survivors: the overflow stays in the residual and the
    freed payload slots go to later survivors (capacity discipline like the
    wire thresholdv path).  Returns ``(dense, new_ef, sent_count, bits,
    dropped)``; ``dropped`` counts cap-overflow + beyond-keep survivors
    (reported when EF is off, reabsorbed by the residual otherwise).
    """
    from tpu_compressed_dp.ops import kernels

    mag = jnp.abs(flat).astype(jnp.float32)
    t = kernels.topk_threshold(mag, keep)
    vals, idx2, new_ef, elig, counts = kernels.seg_pack_by_threshold(
        flat, t, keep, want_ef=want_ef)
    pvals, pidx = kernels.seg_pack_payload(vals, idx2, elig, keep)
    pvals = pvals.astype(flat.dtype)
    bits = _payload_bits(pvals, pidx)
    g_vals = _all_gather(pvals, axis_name)         # [W, k]
    g_idx = _all_gather(pidx, axis_name)           # [W, k]
    dense = (
        jnp.zeros(flat.shape, flat.dtype)
        .at[g_idx.reshape(-1)]
        .add(g_vals.reshape(-1))
        / world
    )
    total_elig = jnp.sum(elig, dtype=jnp.int32)
    sent_count = jnp.minimum(total_elig, keep)
    dropped = jnp.sum(counts, dtype=jnp.int32) - sent_count
    return dense, new_ef, sent_count, bits, dropped


def _leaf_sync_blocktopk(flat: Array, keep_blocks: int, block_size: int,
                         axis_name: str, world, want_ef: bool):
    """Block-granular Top-K: whole contiguous blocks travel.

    The TPU-native fast path — selected blocks gather/scatter as contiguous
    lane-aligned rows, so there is no per-element stream compaction at all:
    the pack runs on the ~n/block_size block scores instead of n elements.
    Payload per worker: ``[keep_blocks, block_size]`` values +
    ``[keep_blocks]`` int32 block indices, all_gather-combined (worker-local
    block sets differ, as with element Top-K).
    """
    from tpu_compressed_dp.ops import kernels

    n = flat.shape[0]
    scores = compressors.blocktopk_scores(flat, block_size)
    t = kernels.topk_threshold(scores, keep_blocks)
    # scores are non-negative, so they serve as their own magnitudes for the
    # fused select+pack dispatch; only the index stream is consumed here
    bidx = _select_pack(scores, scores, t, keep_blocks)[1]
    if block_size < 128 and 128 % block_size == 0:
        return _blocktopk_small_bs(flat, bidx, block_size, axis_name, world,
                                   want_ef)
    g2 = compressors.blocktopk_blocks(flat, block_size)
    payload = _sorted_gather(g2, bidx)         # [kb, bs] contiguous rows
    bits = _payload_bits(payload, bidx)
    g_vals = _all_gather(payload, axis_name)   # [W, kb, bs]
    g_idx = _all_gather(bidx, axis_name)       # [W, kb]
    dense2 = _scatter_combine(g2.shape, flat.dtype, g_idx, g_vals, world,
                              block_size=block_size)
    dense = dense2.reshape(-1)[:n]
    new_ef = (g2.at[bidx].set(0.0, indices_are_sorted=True,
                              unique_indices=True, mode="promise_in_bounds")
              .reshape(-1)[:n] if want_ef else None)
    return dense, new_ef, bits


def _blocktopk_small_bs(flat: Array, bidx: Array, block_size: int,
                        axis_name: str, world, want_ef: bool):
    """Block-Top-K wire sync for sub-128-lane blocks via COVERING rows.

    A ``[nb, block_size]`` view pads every row to the 128-lane register
    width, so gathering/scattering ``block_size``-wide rows at bs=8 wastes
    16x the memory machinery (measured 36 ms of "rest" at the 125M/1%
    config, benchmarks/wire_wall_r5.txt).  Instead keep the natural
    ``[m, 128]`` layout and touch only full cache-line rows:

      * payload gather: fetch each selected block's COVERING 128-lane row
        (one full-line access), then select its ``128/bs`` sub-block in
        registers (jnp.where + sum over the sub-block axis — `where`, not
        multiply-by-mask, so inf/nan gradients in unselected blocks cannot
        poison the selection);
      * scatter-add reconstruction: expand each worker's ``[kb, bs]``
        payload into zeros-padded covering rows and scatter-add full rows
        (duplicate row ids — two selected blocks sharing a row — are
        legal for add);
      * EF: scatter-MULTIPLY the covering rows by a keep-mask (commutative,
        so duplicate rows compose correctly).

    The wire format and billing are unchanged: ``[kb, bs]`` values +
    ``[kb]`` indices travel, exactly like the wide-block path.
    """
    n = flat.shape[0]
    per = 128 // block_size
    pad = (-n) % 128
    g128 = jnp.pad(flat, (0, pad)).reshape(-1, 128)       # [m, 128]
    kb = bidx.shape[0]
    rowid = bidx // per                                   # sorted, not unique
    sub = bidx % per
    rows = _sorted_gather(g128, rowid)                    # [kb, 128] full lines
    sel = (jnp.arange(per, dtype=jnp.int32)[None, :] == sub[:, None])
    payload = jnp.sum(
        jnp.where(sel[:, :, None], rows.reshape(kb, per, block_size), 0.0),
        axis=1)                                           # [kb, bs]
    bits = _payload_bits(payload, bidx)
    g_vals = _all_gather(payload, axis_name)              # [W, kb, bs]
    g_idx = _all_gather(bidx, axis_name)                  # [W, kb]
    W = g_idx.shape[0]

    def expand(idx_row, vals_row):
        s = (jnp.arange(per, dtype=jnp.int32)[None, :]
             == (idx_row % per)[:, None])
        return jnp.where(s[:, :, None], vals_row[:, None, :],
                         0.0).reshape(-1, 128)

    dense128 = jnp.zeros(g128.shape, flat.dtype)
    if W <= 16:
        for w in range(W):
            dense128 = dense128.at[g_idx[w] // per].add(
                expand(g_idx[w], g_vals[w]), indices_are_sorted=True,
                mode="promise_in_bounds")
    else:
        # compile-size guard (same rationale as _scatter_combine): one fused
        # unhinted scatter over all workers' expanded rows
        dense128 = dense128.at[(g_idx // per).reshape(-1)].add(
            expand(g_idx.reshape(-1), g_vals.reshape(-1, block_size)))
    dense = (dense128 / world).reshape(-1)[:n]
    new_ef = None
    if want_ef:
        # EF = zero exactly the sent sub-blocks.  A direct scatter-multiply
        # of g128 by a 0/1 mask would turn a sent inf into inf*0 = NaN and
        # poison the residual (the wide path's set(0.0) is immune) — so
        # accumulate the mask separately (finite 0/1 values compose under
        # duplicate covering rows) and apply it with where.
        keep_mask = jnp.broadcast_to(
            ~sel[:, :, None], (kb, per, block_size)).astype(
                jnp.uint8).reshape(kb, 128)
        maskarr = jnp.ones(g128.shape, jnp.uint8).at[rowid].multiply(
            keep_mask, indices_are_sorted=True, mode="promise_in_bounds")
        new_ef = jnp.where(maskarr.astype(bool), g128,
                           0.0).reshape(-1)[:n]
    return dense, new_ef, bits


def _leaf_sync_threshold(flat: Array, v, cap: int, axis_name: str, world,
                         want_ef: bool):
    """Fixed-capacity wire form of the data-dependent-count threshold
    operators (`core.py:189-199`): pack the first ``cap`` survivors by
    ascending index, zero-pad the rest, all_gather, scatter-add.

    Returns ``(dense, new_ef, sent_count, overflow)`` where ``sent_count``
    is the (dynamic) number of coordinates that actually travelled and
    ``overflow`` how many survivors were clipped by the capacity.
    """
    mag = jnp.abs(flat)
    vals, idx, count = _select_pack(flat, mag, v, cap)
    sent_count = jnp.minimum(count, cap)
    rank = jnp.arange(1, cap + 1, dtype=jnp.int32)
    valid = rank <= sent_count
    vals = jnp.where(valid, vals, 0.0)
    idx = jnp.where(valid, idx, 0)
    bits = _payload_bits(vals, idx)                  # the full cap-sized buffer
    g_vals = _all_gather(vals, axis_name)            # [W, cap]
    g_idx = _all_gather(idx, axis_name)              # [W, cap]
    dense = (
        jnp.zeros(flat.shape, flat.dtype)
        .at[g_idx.reshape(-1)]
        .add(g_vals.reshape(-1))
        / world
    )
    new_ef = None
    if want_ef:
        # zero exactly the sent coordinates; padded slots multiply coord 0
        # by 1 (scatter-mul identity)
        new_ef = flat.at[idx].mul(jnp.where(valid, 0.0, 1.0))
    overflow = jnp.maximum(count - cap, 0)
    return dense, new_ef, sent_count, overflow, bits


def _payload_bits(*arrays: Array) -> float:
    """Measured transport: total bits of the arrays handed to the collective
    (one worker's payload — the per-chip quantity the traffic model scales)."""
    return float(sum(a.size * a.dtype.itemsize * 8 for a in arrays))


def _shard_plan(cfg, n_units: int, keep: int, world: int, unit_size: int):
    from tpu_compressed_dp.ops import wire_sharded

    return wire_sharded.make_shard_plan(
        n_units, keep, world, unit_size,
        cfg.shard_route_factor, cfg.shard_return_factor)


def _hier_combine(contrib: Array, keep: int, axis_name: str, world, cfg):
    """Two-level (ICI x DCN) exchange of one group's compressed-dense
    contribution (``transport='hierarchical'``).

    ``contrib`` is this worker's selection scattered dense (``[n]``, zeros
    at unselected coordinates) — the SAME selection the flat transports
    ship, so hierarchical stays coordinate-equivalent to them.  The flat dp
    axis is viewed as ``dp_pods x chips`` (:func:`~tpu_compressed_dp.ops.
    wire_sharded.hier_axis_groups`):

      1. **ici-reduce** — one dense psum of ``contrib`` inside the pod:
         cheap fabric, and cross-worker duplicates collapse here so only
         the pod UNION crosses the DCN.
      2. **recompress** — pack the pod sum's nonzero union (ascending, the
         Threshold-V prefix-validity discipline) into a ``cap_union``
         buffer sized by ``hier_route_factor_ici x keep``, then slice it
         into per-chip slabs: chip ``c`` of every pod carries slab ``c``,
         so each DCN column moves ``1/chips`` of the pod payload.
      3. **dcn route/reduce/return** — the slabs ride the ordinary
         owner-sharded exchange (:func:`~tpu_compressed_dp.ops.
         wire_sharded.sharded_combine`) restricted to the chip-rank column
         across pods (``axis_index_groups``, ``pods`` senders).
      4. **ici-reduce (back)** — a second dense pod psum sums the chips'
         disjoint-slab partials into the full inter-pod total.

    Returns ``(total, ef_extra, bits_ici, bits_dcn_route, bits_dcn_ret,
    overflow)``: ``total`` is the sum over ALL workers of their transmitted
    contributions (caller divides by world); ``ef_extra`` is this worker's
    exact refund of everything clipped after its pod reduce — recompress
    clips refund ``pod_sum / chips`` on every pod chip (the clip is
    pod-replicated), DCN route/return clips refund the full pod value on
    the one chip whose slab carried them — so summed across workers,
    ``transmitted + refunds == sum of contributions`` (the
    ``comm/shard_overflow`` EF invariant).  ``overflow`` counts recompress
    clips (chip-rank 0 only, so the psum'd figure counts each pod once)
    plus the DCN exchange's route/return clips.
    """
    from tpu_compressed_dp.obs import trace as obs_trace
    from tpu_compressed_dp.ops import wire_sharded

    n = contrib.shape[0]
    plan = wire_sharded.make_hier_plan(
        n, keep, world, cfg.dp_pods, cfg.hier_route_factor_ici,
        cfg.hier_route_factor_dcn)
    P, C = plan.pods, plan.chips
    ici_groups, dcn_groups = wire_sharded.hier_axis_groups(world, P)
    zero_ovf = jnp.zeros((), jnp.int32)

    with obs_trace.phase("ici_reduce"):
        if C > 1:
            pod_sum = jax.lax.psum(contrib, axis_name,
                                   axis_index_groups=ici_groups)
            bits_ici = _payload_bits(contrib)
        else:
            pod_sum = contrib
            bits_ici = 0.0
    if P == 1:
        # one pod: the ICI psum above already reduced the whole world and
        # nothing crosses a DCN — transmitted == sum of contributions
        return pod_sum, jnp.zeros_like(contrib), bits_ici, 0.0, 0.0, zero_ovf

    with obs_trace.phase("recompress"):
        cap = plan.cap_union
        mask = pod_sum != 0
        nnz = jnp.sum(mask, dtype=jnp.int32)
        uidx = packed_indices_from_mask(mask, cap)
        uvalid = (jnp.arange(1, cap + 1, dtype=jnp.int32)
                  <= jnp.minimum(nnz, cap))
        uvals = jnp.where(
            uvalid, pod_sum.at[uidx].get(mode="promise_in_bounds"), 0.0)
        uidx = jnp.where(uvalid, uidx, 0)
        # union coordinates clipped by cap_union: the clip is identical on
        # every pod chip (pod_sum is), so each chip refunds 1/C of the pod
        # value and the pod as a whole refunds it exactly once
        taken = jnp.zeros((n,), jnp.uint8).at[uidx].max(
            uvalid.astype(jnp.uint8))
        union_clip = jnp.where(mask & (taken == 0), pod_sum, 0.0) / C
        c_rank = jax.lax.axis_index(axis_name) % C
        slab = plan.slab
        s_vals = jax.lax.dynamic_slice_in_dim(uvals, c_rank * slab, slab)
        s_idx = jax.lax.dynamic_slice_in_dim(uidx, c_rank * slab, slab)
        s_valid = jax.lax.dynamic_slice_in_dim(uvalid, c_rank * slab, slab)

    dense_u, sent, route_bits, ret_bits, dcn_overflow = (
        wire_sharded.sharded_combine(s_vals, s_idx, plan.dcn, axis_name,
                                     valid=s_valid,
                                     axis_index_groups=dcn_groups))
    partial = dense_u[:n]
    with obs_trace.phase("ici_reduce"):
        if C > 1:
            total = jax.lax.psum(partial, axis_name,
                                 axis_index_groups=ici_groups)
            bits_ici += _payload_bits(partial)
        else:
            total = partial
    # DCN clips: only this chip's slab carried these units for its pod, so
    # the full pod value is refunded here and nowhere else in the pod
    slice_refund = jnp.zeros((n,), contrib.dtype).at[s_idx].add(
        jnp.where(s_valid & ~sent, s_vals, 0.0))
    ef_extra = union_clip + slice_refund
    union_clipped = jnp.where(c_rank == 0, jnp.maximum(nnz - cap, 0), 0)
    return (total, ef_extra, bits_ici, route_bits, ret_bits,
            dcn_overflow + union_clipped)


def _leaf_sync_topk_sharded(flat: Array, keep: int, axis_name: str, world,
                            cfg, want_ef: bool):
    """Element Top-K over the owner-sharded transport
    (:mod:`~tpu_compressed_dp.ops.wire_sharded`): same selection as
    `_leaf_sync_topk`, but the (value, index) pairs route to shard owners
    instead of visiting every chip.  Coordinates clipped by the route or
    return capacities stay in the EF residual (EF on) or are dropped and
    counted (EF off) — ``comm/shard_overflow`` sizes the caps either way.
    """
    from tpu_compressed_dp.ops import kernels, wire_sharded

    mag = jnp.abs(flat).astype(jnp.float32)
    t = kernels.topk_threshold(mag, keep)
    vals, idx, count = _select_pack(flat, mag, t, keep)
    plan = _shard_plan(cfg, flat.shape[0], keep, world, 1)
    dense_u, sent, route_bits, ret_bits, overflow = (
        wire_sharded.sharded_combine(vals, idx, plan, axis_name))
    dense = (dense_u[:flat.shape[0]] / world).astype(flat.dtype)
    new_ef = None
    if want_ef:
        # zero exactly the coordinates the synced gradient contains; routed-
        # but-return-clipped survivors keep their value (set, not mul: a
        # sent inf must not become inf*0 = NaN in the residual)
        new_ef = flat.at[idx].set(
            jnp.where(sent, 0.0, vals), indices_are_sorted=True,
            unique_indices=True, mode="promise_in_bounds")
    # the allgather path's EF-off surplus accounting (ADVICE r2): above-
    # threshold survivors beyond `keep` are a selection-stage drop, reported
    # under its own key — folding it into shard_overflow would pollute the
    # capacity-sizing signal (the factors cannot drive a tie surplus to 0)
    surplus = None if want_ef else jnp.maximum(count - keep, 0)
    # sent_elems = coordinates the synced gradient actually contains
    # (route-accepted AND returned) — same semantics as threshold-sharded,
    # dynamic when the capacity factors clip
    sent_count = jnp.sum(sent, dtype=jnp.int32)
    return (dense, new_ef, sent_count, route_bits + ret_bits, route_bits,
            overflow, surplus)


def _leaf_sync_blocktopk_sharded(flat: Array, keep_blocks: int,
                                 block_size: int, axis_name: str, world,
                                 cfg, want_ef: bool):
    """Block-Top-K over the owner-sharded transport: whole ``[block_size]``
    value rows route to the owners of their block-index shard.  The
    sub-128-lane covering-row trick stays an allgather-path optimisation —
    this path moves ``[kb, bs]`` rows directly at any block size."""
    from tpu_compressed_dp.ops import kernels, wire_sharded

    n = flat.shape[0]
    scores = compressors.blocktopk_scores(flat, block_size)
    t = kernels.topk_threshold(scores, keep_blocks)
    # scores are non-negative, so they serve as their own magnitudes
    bidx = _select_pack(scores, scores, t, keep_blocks)[1]
    g2 = compressors.blocktopk_blocks(flat, block_size)     # [nb, bs]
    payload = _sorted_gather(g2, bidx)                      # [kb, bs]
    plan = _shard_plan(cfg, g2.shape[0], keep_blocks, world, block_size)
    dense_u, sent, route_bits, ret_bits, overflow = (
        wire_sharded.sharded_combine(payload, bidx, plan, axis_name))
    dense = (dense_u / world).astype(flat.dtype).reshape(-1)[:n]
    new_ef = None
    if want_ef:
        new_ef = (g2.at[bidx].set(
            jnp.where(sent[:, None], 0.0, payload), indices_are_sorted=True,
            unique_indices=True, mode="promise_in_bounds")
            .reshape(-1)[:n])
    # sent blocks that actually reached the synced gradient, in ELEMENTS
    # (whole zero-padded block rows travel — same convention as the
    # allgather path's keep accounting)
    sent_count = jnp.sum(sent, dtype=jnp.int32) * block_size
    return dense, new_ef, sent_count, route_bits + ret_bits, route_bits, overflow


def _leaf_sync_threshold_sharded(flat: Array, v, cap: int, axis_name: str,
                                 world, cfg, want_ef: bool):
    """Threshold-V fixed-capacity buffer over the owner-sharded transport:
    the zero-padded tail slots route to the dump destination (they must not
    consume shard-0 bucket capacity).  Returns the threshold cap overflow
    and the transport overflow separately — they size different knobs
    (``wire_cap_ratio`` vs ``shard_route_factor``/``shard_return_factor``).
    """
    from tpu_compressed_dp.ops import wire_sharded

    mag = jnp.abs(flat)
    vals, idx, count = _select_pack(flat, mag, v, cap)
    sent_count = jnp.minimum(count, cap)
    rank = jnp.arange(1, cap + 1, dtype=jnp.int32)
    valid = rank <= sent_count
    vals = jnp.where(valid, vals, 0.0)
    plan = _shard_plan(cfg, flat.shape[0], cap, world, 1)
    dense_u, sent, route_bits, ret_bits, overflow = (
        wire_sharded.sharded_combine(vals, idx, plan, axis_name, valid=valid))
    dense = (dense_u[:flat.shape[0]] / world).astype(flat.dtype)
    new_ef = None
    if want_ef:
        # mul keeps the padded tail slots (idx 0, factor 1) identities,
        # exactly like the allgather path's EF
        new_ef = flat.at[idx].mul(jnp.where(sent, 0.0, 1.0))
    cap_overflow = jnp.maximum(count - cap, 0)
    sent_transported = jnp.sum(sent, dtype=jnp.int32)
    return (dense, new_ef, sent_transported, route_bits + ret_bits,
            route_bits, cap_overflow, overflow)


def _leaf_sync_topk_hier(flat: Array, keep: int, axis_name: str, world,
                         cfg, want_ef: bool):
    """Element Top-K over the hierarchical transport: the flat transports'
    exact selection, scattered dense and handed to :func:`_hier_combine`.
    EF is the base residual (everything unselected) plus the combine's
    exact clip refunds."""
    from tpu_compressed_dp.ops import kernels

    mag = jnp.abs(flat).astype(jnp.float32)
    t = kernels.topk_threshold(mag, keep)
    vals, idx, count = _select_pack(flat, mag, t, keep)
    contrib = jnp.zeros(flat.shape, flat.dtype).at[idx].set(
        vals, indices_are_sorted=True, unique_indices=True,
        mode="promise_in_bounds")
    total, ef_extra, b_ici, b_rt, b_ret, overflow = _hier_combine(
        contrib, keep, axis_name, world, cfg)
    dense = (total / world).astype(flat.dtype)
    new_ef = (flat - contrib + ef_extra) if want_ef else None
    surplus = None if want_ef else jnp.maximum(count - keep, 0)
    return dense, new_ef, (b_ici, b_rt, b_ret), overflow, surplus


def _leaf_sync_blocktopk_hier(flat: Array, keep_blocks: int, block_size: int,
                              axis_name: str, world, cfg, want_ef: bool):
    """Block-Top-K over the hierarchical transport: selected blocks scatter
    dense, and the pod-reduced gradient recompresses element-granular (the
    inter-pod exchange is the pod UNION's nonzeros, not block rows)."""
    from tpu_compressed_dp.ops import kernels

    n = flat.shape[0]
    scores = compressors.blocktopk_scores(flat, block_size)
    t = kernels.topk_threshold(scores, keep_blocks)
    # scores are non-negative, so they serve as their own magnitudes
    bidx = _select_pack(scores, scores, t, keep_blocks)[1]
    g2 = compressors.blocktopk_blocks(flat, block_size)     # [nb, bs]
    payload = _sorted_gather(g2, bidx)                      # [kb, bs]
    contrib = jnp.zeros(g2.shape, flat.dtype).at[bidx].set(
        payload, indices_are_sorted=True, unique_indices=True,
        mode="promise_in_bounds").reshape(-1)[:n]
    total, ef_extra, b_ici, b_rt, b_ret, overflow = _hier_combine(
        contrib, min(keep_blocks * block_size, n), axis_name, world, cfg)
    dense = (total / world).astype(flat.dtype)
    new_ef = (flat - contrib + ef_extra) if want_ef else None
    return dense, new_ef, (b_ici, b_rt, b_ret), overflow


def _leaf_sync_threshold_hier(flat: Array, v, cap: int, axis_name: str,
                              world, cfg, want_ef: bool):
    """Threshold-V fixed-capacity buffer over the hierarchical transport.
    The cap clip (survivors beyond ``wire_cap_ratio``) stays a selection
    matter — it never enters ``contrib`` so it lands in the base residual;
    transport clips refund through :func:`_hier_combine`."""
    mag = jnp.abs(flat)
    vals, idx, count = _select_pack(flat, mag, v, cap)
    sent_count = jnp.minimum(count, cap)
    rank = jnp.arange(1, cap + 1, dtype=jnp.int32)
    valid = rank <= sent_count
    vals = jnp.where(valid, vals, 0.0)
    idx = jnp.where(valid, idx, 0)
    # add, not set: the zero-padded tail slots all alias coordinate 0 and
    # must not clobber a genuinely selected value there
    contrib = jnp.zeros(flat.shape, flat.dtype).at[idx].add(vals)
    total, ef_extra, b_ici, b_rt, b_ret, overflow = _hier_combine(
        contrib, cap, axis_name, world, cfg)
    dense = (total / world).astype(flat.dtype)
    new_ef = (flat - contrib + ef_extra) if want_ef else None
    cap_overflow = jnp.maximum(count - cap, 0)
    return (dense, new_ef, sent_count, (b_ici, b_rt, b_ret), cap_overflow,
            overflow)


def _leaf_sync_terngrad(flat: Array, key: Array, chunk: int, axis_name: str,
                        world):
    from tpu_compressed_dp.ops import kernels

    n = flat.shape[0]
    if kernels.use_quant_pack(n):
        # fused quantize+pack: dither and 2-bit wire bytes in one kernel
        # pass, no materialised int8 level vector (bitwise-identical bytes)
        if compressors.terngrad_num_chunks(n, chunk) == 1:
            packed, scale = kernels.terngrad_pack(flat, key)
        else:
            scaled, scale = compressors.terngrad_prescale(flat, chunk)
            packed = kernels.terngrad_pack_prescaled(scaled, key)
    else:
        levels, scale = compressors.terngrad_levels(flat, key, chunk=chunk)
        packed = pack_ternary(levels)                     # uint8[ceil(n/4)]
    bits = _payload_bits(packed, scale)
    g_packed = _all_gather(packed, axis_name)             # [W, ceil(n/4)]
    g_scale = _all_gather(scale, axis_name)               # [W] or [W, nc]
    g_levels = unpack_ternary(g_packed, n)                # [W, n] int8
    if scale.ndim == 0:
        dense = jnp.sum(
            g_scale[:, None] * g_levels.astype(flat.dtype), axis=0) / world
        return dense, bits
    # chunked scales: broadcast each worker's [nc] scales over its chunks
    nc = scale.shape[0]
    pad = nc * chunk - n
    lv = jnp.pad(g_levels, ((0, 0), (0, pad))).reshape(-1, nc, chunk)
    dense = jnp.sum(
        g_scale[:, :, None] * lv.astype(flat.dtype), axis=0
    ).reshape(-1)[:n] / world
    return dense, bits


def _leaf_sync_qsgd(flat: Array, key: Array, qstates: int, axis_name: str, world):
    from tpu_compressed_dp.ops import kernels

    n = flat.shape[0]
    if 127 < qstates <= 255 and kernels.use_quant_pack(n):
        # fused quantize+pack emits the byte-magnitude + packed-sign wire
        # format directly (the qstates <= 255 branch of qsgd_wire_pack)
        mags, signs, scale = kernels.qsgd_pack(flat, key, qstates=qstates)
        payload = (mags, signs)
    else:
        levels, scale = compressors.qsgd_levels(flat, key, qstates=qstates)
        payload = qsgd_wire_pack(levels, qstates)
    bits = _payload_bits(*payload, scale)
    g_payload = tuple(_all_gather(p, axis_name) for p in payload)
    g_scale = _all_gather(scale, axis_name)               # [W]
    g_levels = qsgd_wire_unpack(g_payload, n, qstates, dtype=flat.dtype)
    dense = jnp.sum(g_scale[:, None] * g_levels, axis=0) / world
    return dense, bits


def make_wire_grad_sync(cfg, axis_name: str = "data", *,
                        group_offset: int = 0):
    """Build ``sync(grads, ef, key) -> (synced, new_ef, comm_stats)``.

    Same contract as the simulate-mode sync in
    :func:`tpu_compressed_dp.parallel.dp.make_grad_sync` (which dispatches
    here for ``mode='wire'`` and adapts this 3-tuple to its stateful
    4-tuple — every wire method is stateless, so the compressor state
    passes through untouched); must run inside ``shard_map`` over
    ``axis_name``.

    ``group_offset`` shifts the per-group RNG derivation to the chunk's
    global group indices when the overlap driver
    (:mod:`tpu_compressed_dp.parallel.overlap`) syncs a slice of the tree,
    so chunked and whole-tree syncs draw identical randomness per group.
    """
    from tpu_compressed_dp.parallel.dp import wire_transport

    comp = compressors.get_compressor(
        cfg.method, ratio=cfg.ratio, threshold=cfg.threshold,
        qstates=cfg.qstates, block_size=cfg.block_size,
        terngrad_chunk=cfg.resolved_terngrad_chunk,
    )
    if comp.name not in WIRE_METHODS:
        raise NotImplementedError(
            f"mode='wire' supports {WIRE_METHODS}, got {comp.name!r}"
        )
    if comp.name == "randomk" and not cfg.resolved_shared_mask:
        raise ValueError(
            "wire randomk needs shared_mask=True so worker index sets line up "
            "(the shared-seed trick, sparsified_ddp.py:164)"
        )
    if cfg.error_feedback and comp.name in ("terngrad", "qsgd"):
        raise ValueError(
            "error feedback composes with sparsifiers (topk/randomk); "
            "terngrad/qsgd are unbiased quantizers with no dropped coordinates"
        )

    # Quantizer dither may (and, for variance reduction, should) differ across
    # workers: honour shared_mask=False the same way simulate mode does.
    # Random-K requires a shared key (checked above); Top-K uses no RNG.
    per_worker_rng = (not cfg.resolved_shared_mask) and comp.needs_rng

    def leaf_keep(n: int) -> int:
        if comp.name == "topk":
            return compressors.topk_keep_count(n, cfg.ratio)
        if comp.name == "randomk":
            return compressors.randomk_keep_count(n, cfg.ratio)
        if comp.name in ("thresholdv", "adaptive_threshold"):
            # fixed transport capacity for the data-dependent survivor count
            return max(1, int(round(cfg.wire_cap_ratio * n)))
        if comp.name == "blocktopk":
            # whole blocks travel, pad zeros included — honest wire size;
            # capped at n: when every block is kept (small leaves round up
            # to >= 1 block) the leaf psums dense instead, with no payload
            # inflation from block padding
            kb = compressors.blocktopk_keep_blocks(n, cfg.ratio, cfg.block_size)
            return min(kb * cfg.block_size, n)
        return n  # quantizers transmit every coordinate (at reduced width)

    check = getattr(cfg, "check_sync", False)

    def sync_flat(flat: Array, ef_flat, key: Array, world):
        """Returns ``(dense, new_ef, sent, bits, bits_route, agree,
        overflows, fabric)``; ``sent`` may be dynamic (threshold methods),
        the rest of the accounting is static.  ``bits`` is MEASURED from
        the payload arrays each leaf sync actually hands its collective —
        never an analytic per-element model; ``bits_route`` is the
        all_to_all share of ``bits`` (sharded transport only, else 0).
        ``overflows`` maps comm-stat keys to clip counts.  ``fabric`` is
        None except for hierarchical groups, where it is the per-fabric
        split ``(ici_bits, dcn_route_bits, dcn_return_bits)`` summing to
        ``bits`` (the flat collective-kind buckets stay whole-world-only —
        hierarchical bits bill per fabric instead)."""
        acc = flat + ef_flat if ef_flat is not None else flat
        n = flat.shape[0]
        if n > (1 << 31) - 1 and comp.name not in ("terngrad", "qsgd"):
            # the packed index pipeline is int32 throughout (32-bit indices
            # ARE the wire format); groups beyond int32 must be cut smaller
            raise ValueError(
                f"wire-mode {comp.name} group of {n} elements exceeds int32 "
                "index range; use granularity='bucketed' (25 MB buckets) or "
                "'layerwise' for models this large")
        keep = leaf_keep(n)
        agree = None
        idx = None
        # W=1 has no cross-worker duplicates to owner-reduce (and the route
        # collective would be a copy): the allgather combine is the same
        # arithmetic with less machinery, so sharded AND hierarchical
        # degrade to it.
        transport = wire_transport(comp.name, n, cfg)
        sharded = transport == "sharded" and world > 1
        hier = transport == "hierarchical" and world > 1
        if comp.name in ("thresholdv", "adaptive_threshold"):
            v = (cfg.threshold if comp.name == "thresholdv"
                 else jnp.max(jnp.abs(acc)) * 0.5)
            if hier:
                (dense, new_ef, sent_count, fabric, cap_overflow,
                 shard_overflow) = _leaf_sync_threshold_hier(
                    acc, v, keep, axis_name, world, cfg, ef_flat is not None)
                return (dense, new_ef, sent_count.astype(jnp.float32),
                        sum(fabric), 0.0, agree,
                        {"threshold_overflow": cap_overflow,
                         "shard_overflow": shard_overflow}, fabric)
            if sharded:
                (dense, new_ef, sent_count, bits, bits_route, cap_overflow,
                 shard_overflow) = _leaf_sync_threshold_sharded(
                    acc, v, keep, axis_name, world, cfg, ef_flat is not None)
                return (dense, new_ef, sent_count.astype(jnp.float32), bits,
                        bits_route, agree,
                        {"threshold_overflow": cap_overflow,
                         "shard_overflow": shard_overflow}, None)
            dense, new_ef, sent_count, overflow, bits = _leaf_sync_threshold(
                acc, v, keep, axis_name, world, ef_flat is not None)
            # transport is the full cap-sized buffer even when half-empty
            return (dense, new_ef, sent_count.astype(jnp.float32),
                    bits, 0.0, agree, {"threshold_overflow": overflow}, None)
        if comp.name == "randomk":
            dense, idx, agree, bits = _leaf_sync_randomk(
                acc, key, keep, axis_name, world, check)
        elif comp.name == "topk":
            from tpu_compressed_dp.ops import kernels

            if hier:
                dense, new_ef, fabric, overflow, surplus = (
                    _leaf_sync_topk_hier(acc, keep, axis_name, world, cfg,
                                         ef_flat is not None))
                ovf = {"shard_overflow": overflow}
                if surplus is not None:
                    ovf["topk_surplus_dropped"] = surplus
                return (dense, new_ef, float(keep), sum(fabric), 0.0, agree,
                        ovf, fabric)
            if sharded:
                (dense, new_ef, sent_count, bits, bits_route, overflow,
                 surplus) = _leaf_sync_topk_sharded(
                    acc, keep, axis_name, world, cfg, ef_flat is not None)
                ovf = {"shard_overflow": overflow}
                if surplus is not None:
                    ovf["topk_surplus_dropped"] = surplus
                return (dense, new_ef, sent_count.astype(jnp.float32), bits,
                        bits_route, agree, ovf, None)
            if kernels.use_seg_pack(n, keep):
                # the seg-pack fused EF/pack kernel assumes every packed slot
                # travels — an allgather-path contract; sharded groups take
                # the mask->rank->gather chain above instead
                dense, new_ef, sent_count, bits, dropped = _leaf_sync_topk_seg(
                    acc, keep, axis_name, world, ef_flat is not None)
                return (dense, new_ef, sent_count.astype(jnp.float32), bits,
                        0.0, agree,
                        {} if ef_flat is not None
                        else {"topk_surplus_dropped": dropped}, None)
            # with EF on the surplus is reabsorbed by the residual; with EF
            # off it is a real (silent) drop — count and report it
            dense, idx, surplus, bits = _leaf_sync_topk(
                acc, keep, axis_name, world, want_surplus=ef_flat is None)
            if surplus is not None:
                new_ef = None
                return (dense, new_ef, float(keep), bits, 0.0, agree,
                        {"topk_surplus_dropped": surplus}, None)
        elif comp.name == "blocktopk":
            if keep >= flat.shape[0]:
                # every block selected (leaves <= block_size always are, and
                # ratio~1 configs): identical to simulate mode's keep-all
                # result, and a dense psum is strictly cheaper than padded
                # block rows — matches the reference protocol of never
                # sending more than the dense tensor
                dense = jax.lax.psum(acc, axis_name) / world
                bits = _payload_bits(acc)
                new_ef = jnp.zeros_like(acc) if ef_flat is not None else None
            elif hier:
                dense, new_ef, fabric, overflow = _leaf_sync_blocktopk_hier(
                    acc, keep // cfg.block_size, cfg.block_size, axis_name,
                    world, cfg, ef_flat is not None)
                return (dense, new_ef, float(keep), sum(fabric), 0.0, agree,
                        {"shard_overflow": overflow}, fabric)
            elif sharded:
                dense, new_ef, sent_count, bits, bits_route, overflow = (
                    _leaf_sync_blocktopk_sharded(
                        acc, keep // cfg.block_size, cfg.block_size,
                        axis_name, world, cfg, ef_flat is not None))
                return (dense, new_ef, sent_count.astype(jnp.float32), bits,
                        bits_route, agree, {"shard_overflow": overflow}, None)
            else:
                dense, new_ef, bits = _leaf_sync_blocktopk(
                    acc, keep // cfg.block_size, cfg.block_size, axis_name,
                    world, ef_flat is not None)
            return dense, new_ef, float(keep), bits, 0.0, agree, {}, None
        elif comp.name == "terngrad":
            dense, bits = _leaf_sync_terngrad(
                acc, key, cfg.resolved_terngrad_chunk, axis_name, world)
        else:  # qsgd
            dense, bits = _leaf_sync_qsgd(acc, key, cfg.qstates, axis_name, world)
        # EF residual = the coordinates that did NOT travel; zeroing the sent
        # ones in place of building a dense local reconstruction saves a full
        # scatter + elementwise pass at model scale.  EF with quantizers is
        # rejected at build time, so ef_flat != None implies a sparsifier —
        # and sparsifier idx is ascending-unique (packed_indices_from_mask).
        # PRECONDITION (ADVICE r5): ascending-unique holds only for FINITE
        # gradients — the hints here and in _scatter_combine assume
        # count(mag >= t) >= keep, and NaNs compare false against every
        # threshold, starving the mask below keep so the pack pads trailing
        # ranks with duplicate index 0.  The sorted/unique hints then
        # mis-describe the scatter and its result is undefined rather than
        # benignly degraded (tests/test_wire_sharded.py pins the predicate
        # via packed_indices_monotone).  A NaN gradient has already
        # destroyed the step; the contract here is only that we never
        # promise XLA an invariant a NaN can silently break without the
        # debug predicate being able to see it.
        new_ef = (acc.at[idx].set(0, indices_are_sorted=True,
                                  unique_indices=True,
                                  mode="promise_in_bounds")
                  if ef_flat is not None else None)
        return dense, new_ef, float(keep), bits, 0.0, agree, {}, None

    def sync(grads: Any, ef: Any, key: Array) -> Tuple[Any, Any, Dict[str, Array]]:
        from tpu_compressed_dp.parallel.dp import (
            BUCKET_MB, group_concat, group_split, make_leaf_groups,
        )

        world = jax.lax.psum(1, axis_name)
        use_ef = cfg.error_feedback
        leaves, treedef = jax.tree.flatten(grads)
        ef_leaves = jax.tree.leaves(ef) if use_ef else [None] * len(leaves)

        # One packed payload + one collective per group (layerwise /
        # entiremodel / 25MB-bucketed — the same static grouping as
        # simulate mode, parallel/dp.py:make_leaf_groups).
        groups = make_leaf_groups(
            [g.size * g.dtype.itemsize for g in leaves],
            cfg.granularity, cfg.bucket_mb * BUCKET_MB)
        out_leaves = [None] * len(leaves)
        new_ef_leaves = [None] * len(leaves)
        agrees = []
        # per-kind clip counters: threshold_overflow (capacity vs survivor
        # count), topk_surplus_dropped (EF-off tie surplus), shard_overflow
        # (sharded-transport route/return clips) — a leaf may report several
        overflows: Dict[str, list] = {}
        sent = 0.0
        bits = 0.0
        bits_psum = 0.0
        bits_ag = 0.0
        bits_a2a = 0.0
        bits_ici = 0.0
        bits_dcn = 0.0
        bits_dcn_route = 0.0
        dense_total = 0.0
        from tpu_compressed_dp.obs import trace as obs_trace

        for gi, idxs in enumerate(groups):
            flat = group_concat(leaves, idxs)
            with obs_trace.phase("ef"):
                ef_flat = group_concat(ef_leaves, idxs) if use_ef else None
            ki = compressors.leaf_key(key, gi + group_offset, per_worker_rng,
                                      axis_name)
            # one scope over the whole wire leaf sync (select + pack +
            # combine): the sharded transport's route/reduce/return scopes
            # nest inside (xprof shows tcdp.compress/tcdp.route etc.), and
            # the allgather combine's collectives split out by op name
            with obs_trace.phase("compress"):
                (dense, new_ef_flat, sent_leaf, bits_leaf, bits_route, agree,
                 leaf_overflows, fabric) = sync_flat(flat, ef_flat, ki, world)
            # which collective(s) this group's payload actually rode
            # (VERDICT r2 #2) — shared classifier with the simulate engine.
            # A sharded group splits: route bits ride the all_to_all, the
            # shard return rides an all_gather.  A hierarchical group bills
            # per FABRIC instead — the flat collective-kind buckets stay
            # whole-world-only so their traffic arithmetic needs no
            # topology caveats.
            transport = wire_transport(comp.name, flat.shape[0], cfg)
            if fabric is not None:
                f_ici, f_rt, f_ret = fabric
                bits_ici += f_ici
                bits_dcn += f_rt + f_ret
                bits_dcn_route += f_rt
            elif transport == "psum":
                bits_psum += bits_leaf
            elif transport == "sharded" and world > 1:
                bits_a2a += bits_route
                bits_ag += bits_leaf - bits_route
            else:
                bits_ag += bits_leaf
            with obs_trace.phase("return"):
                group_split(dense, leaves, idxs, out_leaves)
                if use_ef:
                    # EF residual is fp32 by design (see group_split
                    # docstring)
                    group_split(new_ef_flat, leaves, idxs, new_ef_leaves,
                                dtype=jnp.float32)
            if agree is not None:
                agrees.append(agree)
            for k, v in leaf_overflows.items():
                overflows.setdefault(k, []).append(v)
            sent = sent + sent_leaf            # dynamic for threshold methods
            bits += bits_leaf
            dense_total += float(flat.shape[0])

        stats = {
            "sent_elems": jnp.asarray(sent, jnp.float32),
            "sent_bits": jnp.asarray(bits, jnp.float32),
            "sent_bits_psum": jnp.asarray(bits_psum, jnp.float32),
            "sent_bits_allgather": jnp.asarray(bits_ag, jnp.float32),
            "sent_bits_alltoall": jnp.asarray(bits_a2a, jnp.float32),
            "sent_bits_ici": jnp.asarray(bits_ici, jnp.float32),
            "sent_bits_dcn": jnp.asarray(bits_dcn, jnp.float32),
            "sent_bits_dcn_route": jnp.asarray(bits_dcn_route, jnp.float32),
            "dense_elems": jnp.asarray(dense_total, jnp.float32),
            "num_collectives": jnp.asarray(float(len(groups)), jnp.float32),
        }
        if agrees:
            stats["sync_agree"] = jnp.min(jnp.stack(agrees))
        for k, vs in overflows.items():
            # threshold_overflow: survivors clipped by the fixed capacity
            # (0 = cap was enough).  topk_surplus_dropped: above-threshold
            # survivors beyond keep, truncated by ascending index (ADVICE
            # r2).  shard_overflow: coordinates clipped by the sharded
            # transport's route/return capacities (EF reabsorbs them when
            # on; this worker's route clips + this owner's return clips).
            stats[k] = jnp.sum(jnp.stack(vs)).astype(jnp.float32)
        out = jax.tree.unflatten(treedef, out_leaves)
        new_ef = jax.tree.unflatten(treedef, new_ef_leaves) if use_ef else ()
        return out, new_ef, stats

    return sync
