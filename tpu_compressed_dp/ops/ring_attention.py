"""Ring attention: causal attention over a sequence-sharded axis.

Net-new capability relative to the reference (SURVEY.md §5: long-context /
sequence parallelism is **absent** there — its workloads are CNNs), required
for the Llama pretrain stretch config (BASELINE.json) and demanded by the
framework goal: long sequences scale by sharding the *sequence* dimension
over a mesh axis, with K/V blocks rotating around the ring via
``jax.lax.ppermute`` while each device accumulates its queries' attention
online (flash-attention style running softmax).  Compute overlaps the
neighbor exchange because XLA schedules the ppermute alongside the block
matmuls — the same latency-hiding the reference hand-built with NCCL side
streams (`ddp.py:429-456`), applied to sequence parallelism.

Semantics: exact causal attention — bitwise-equivalent (up to fp reassociation)
to dense softmax attention over the full sequence, verified in
tests/test_transformer.py.  Rotation count is the ring size (static), so the
whole loop unrolls into XLA with static shapes.

Layout: ``(batch, heads, seq_block, head_dim)`` per device; the global
sequence position of a block is recovered from the device's ring index, so
causal masking is correct without materialising a [T, T] mask.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["ring_attention", "dense_causal_attention", "use_fused_attention"]

_NEG_INF = -1e30

# Fused flash-attention for the single-block (ring size 1) case: the tiled
# Pallas kernel never materialises the [T, T] probability matrix in HBM —
# at T=1024 the unfused chain round-trips ~400 MB of fp32 scores per layer
# pass, the dominant non-matmul HBM traffic of the LM step (VERDICT r3 weak
# #5).  The multi-block ring path keeps the exact online-softmax: its
# per-step K/V blocks already bound the score working set to [T_loc, T_loc],
# and block outputs merge through the (o, m, l) carry that a fused kernel
# would have to export anyway.
#
# Operand-precision note (ADVICE r4): for bf16 models the kernel feeds bf16
# q/k straight to the MXU (fp32 accumulation), while the unfused path
# upcast q/k to fp32 before the score matmul — so enabling the default-on
# kernel shifts bf16 loss curves at the last-ulp level.  This matches
# standard XLA attention practice; set TPU_CDP_FUSED_ATTN=0 to recover the
# old operand precision when diffing curves against pre-round-4 runs.
_FUSED_ATTN = os.environ.get("TPU_CDP_FUSED_ATTN", "1") != "0"


def use_fused_attention(q_shape, k_shape, itemsize: int = 2) -> bool:
    """True when the single-block causal path should hit the fused kernel
    (:mod:`tpu_compressed_dp.ops.flash_attention`): TPU backend, seq a lane
    multiple, head_dim MXU-friendly, K/V small enough to stream through
    VMEM whole."""
    if not _FUSED_ATTN:
        return False
    try:
        if jax.default_backend() != "tpu":
            return False
    except RuntimeError:  # pragma: no cover - backend not initialised
        return False
    b, h, t, d = q_shape
    d_pad = d + (-d) % 128
    # Binding constraint since the r5 streamed dkv backward (which keeps its
    # full-T operands in HBM): the fwd/dq kernels' Mosaic-managed full-T
    # K + V blocks, held at input dtype (bf16 in practice) and
    # double-buffered, must fit the TPU's ~16 MB scoped-vmem ceiling with
    # room for the streamed q/do blocks.  Cap the single-buffered K+V set
    # at 4 MB (= 8 MB doubled + block buffers, comfortably under 16 MB):
    # admits the chip-verified T=8192 at d=128 exactly; T=16384 (8 MB
    # single, ~18+ doubled) would hit the same scoped-vmem wall the r5 dkv
    # fix removed — long-context's designed path is the seq-axis ring
    # sharding T_local below this gate.
    resident = t * 2 * d_pad * itemsize   # K + V at input dtype
    return (t == k_shape[2] and t >= 128 and t % 128 == 0 and d % 64 == 0
            and resident <= 4 * 1024 * 1024)


def _fused_causal(q: Array, k: Array, v: Array, scale: float) -> Array:
    from tpu_compressed_dp.ops.flash_attention import flash_causal_attention

    return flash_causal_attention(q, k, v, scale)


def _block_attend(q, k, v, q_pos, k_pos, scale, o, m, l):
    """One online-softmax accumulation step against a K/V block.

    q: [B, H, Tq, D]; k/v: [B, H, Tk, D]; *_pos: [Tq]/[Tk] global positions.
    o/m/l: running output [B,H,Tq,D], row max [B,H,Tq], row sum [B,H,Tq].
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    causal = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
    s = jnp.where(causal[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # fully-masked rows keep m == -inf sentinel; exp(-inf - -inf) guarded to 0
    corr = jnp.where(m > _NEG_INF / 2, jnp.exp(m - m_new), 0.0)
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(causal[None, None], p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    o_new = o * corr[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    axis_name: Optional[str] = None,
    scale: Optional[float] = None,
) -> Array:
    """Causal attention; ``q/k/v``: [B, H, T_local, D] (local sequence block).

    With ``axis_name`` set (inside shard_map over a sequence mesh axis), the
    full sequence is ``ring_size * T_local`` long and device ``i`` holds
    positions ``[i*T_local, (i+1)*T_local)``.  Without it, plain single-block
    causal attention (the ring degenerates to one step).

    GQA: pass K/V with fewer heads than Q as long as ``H_q % H_kv == 0``
    (heads are repeated locally — no extra wire traffic).
    """
    if q.shape[1] != k.shape[1]:
        if q.shape[1] % k.shape[1]:
            raise ValueError(f"H_q={q.shape[1]} not a multiple of H_kv={k.shape[1]}")
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    t_local = q.shape[2]
    d = q.shape[3]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    if axis_name is None:
        ring, my = 1, 0
    else:
        # the axis size is static at trace time — a size-1 seq axis (the LM
        # harness always names the axis, sp=1 or not) degenerates to the
        # single-block case and must hit the same fused path
        ring = jax.lax.psum(1, axis_name)
        my = jax.lax.axis_index(axis_name)
    if ring == 1 and use_fused_attention(q.shape, k.shape, q.dtype.itemsize):
        return _fused_causal(q, k, v, scale)

    q_pos = my * t_local + jnp.arange(t_local)
    qf = q.astype(jnp.float32)
    o = jnp.zeros(q.shape[:3] + (d,), jnp.float32)
    m = jnp.full(q.shape[:3], _NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)

    perm = None
    if ring > 1:
        # block i travels i -> i+1 each step, so after s steps device `my`
        # holds block (my - s) mod ring
        perm = [(i, (i + 1) % ring) for i in range(ring)]

    def step(s, carry):
        o, m, l, kb, vb = carry
        src = (my - s) % ring if axis_name is not None else 0
        k_pos = src * t_local + jnp.arange(t_local)
        o, m, l = _block_attend(qf, kb.astype(jnp.float32), vb, q_pos, k_pos,
                                scale, o, m, l)
        if perm is not None:
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
        return o, m, l, kb, vb

    carry = (o, m, l, k, v)
    # static ring size -> unrolled python loop (each iteration's ppermute can
    # overlap the next block's compute in XLA's schedule)
    for s in range(ring):
        carry = step(s, carry)
    o, m, l = carry[:3]

    # every causal query row attends to itself, so l > 0
    return (o / l[..., None]).astype(q.dtype)


def dense_causal_attention(q: Array, k: Array, v: Array,
                           scale: Optional[float] = None) -> Array:
    """Reference implementation (full [T, T] scores) for tests."""
    return ring_attention(q, k, v, axis_name=None, scale=scale)
