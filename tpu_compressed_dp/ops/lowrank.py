"""Rank-r PowerSGD low-rank gradient compression (Vogels et al., PAPERS.md).

The six reference operators (:mod:`tpu_compressed_dp.ops.compressors`) are all
element-wise sparsifiers/quantizers whose wire payloads carry worker-dependent
supports (indices, scales) — every one of them except dense and shared-seed
Random-K pays the all_gather penalty :func:`parallel.dp.wire_rides_psum`
documents.  PowerSGD is the compressor family whose payload is *linear in the
gradient*: each worker's factor ``P = M Q`` (and ``Q' = Mᵀ P̂``) can be
psum-averaged directly, so the compressed sync always rides the cheap ring
collective, at ``r·(m + n/m)`` fp32 words per ``n``-element group.

Per leaf group (layerwise / bucketed / entiremodel — the same static grouping
as the other engines):

  1. reshape the flat accumulated gradient (grad + EF residual) to the
     near-square ``[m, n2]`` matrix ``M`` (zero-padded; ``m ~ sqrt(n)``
     minimises the factor payload ``m + n2``),
  2. one power-iteration step against the persistent warm-start ``Q``:
     ``P = M Q`` — psum-mean — Gram–Schmidt → ``P̂``,
  3. ``Q' = Mᵀ P̂`` — psum-mean,
  4. reconstruct ``Ĝ = P̂ Q'ᵀ`` (identical on every worker: both factors are
     already averaged) and fold ``M − Ĝ`` into the error-feedback residual —
     Sparsified SGD with Memory (Stich et al., PAPERS.md) applied to the
     low-rank case.

The warm start is what makes one iteration per step enough: ``Q`` persists in
``TrainState.comp`` across steps (and through Orbax checkpoints), so the power
iteration keeps refining the same dominant subspace the gradient stream
actually occupies.  Because every nonlinear step (orthogonalisation) happens
*after* a psum, the whole sync is linear in the per-worker inputs: the result
equals running the same compression on the worker-mean gradient — the
psum-linearity property ``tests/test_lowrank.py`` pins down.

Groups too small for the factors to pay for themselves (``r·(m+n2) >= n``:
biases, norm scales) psum dense instead — exact, and strictly cheaper.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["powersgd_dims", "gram_schmidt", "powersgd_approx",
           "init_group_state", "powersgd_group_sync", "powersgd_group_bits"]


def powersgd_dims(n: int, rank: int) -> Optional[Tuple[int, int, int]]:
    """``(m, n2, r_eff)`` for compressing a flat ``n``-vector, or ``None``
    when the factors would cost at least the dense vector (send dense).

    ``m = round(sqrt(n))`` and ``n2 = ceil(n/m)`` minimise the per-rank
    payload ``m + n2``; the effective rank is clamped to ``min(rank, m, n2)``
    (a taller rank cannot add information).
    """
    if n <= 0:
        return None
    m = max(1, int(round(math.sqrt(n))))
    n2 = -(-n // m)
    r = max(1, min(rank, m, n2))
    if r * (m + n2) >= n:
        return None
    return m, n2, r


def powersgd_group_bits(n: int, rank: int) -> float:
    """Analytic wire bits for one ``n``-element group: both fp32 factors
    (``P`` then ``Q``) ride the psum ring; dense-fallback groups bill 32/elem."""
    dims = powersgd_dims(n, rank)
    if dims is None:
        return 32.0 * n
    m, n2, r = dims
    return 32.0 * r * (m + n2)


def gram_schmidt(p: Array, eps: float = 1e-8) -> Array:
    """Orthonormalise the columns of ``p`` ([..., m, r]) by modified
    Gram–Schmidt, batched over leading dims.

    ``r`` is static and small (1–4), so the column loop unrolls at trace
    time into ``r²/2`` fused dot/axpy passes — no iterative QR machinery.
    Near-zero columns (zero gradient, or rank deficiency after projection)
    normalise against ``eps`` and come back ~0 instead of NaN; the
    reconstruction then simply spans fewer directions that step.
    """
    cols = []
    for i in range(p.shape[-1]):
        v = p[..., i]
        for u in cols:
            v = v - jnp.sum(u * v, axis=-1, keepdims=True) * u
        norm = jnp.sqrt(jnp.sum(v * v, axis=-1, keepdims=True))
        cols.append(v / jnp.maximum(norm, eps))
    return jnp.stack(cols, axis=-1)


def _as_matrix(flat: Array, m: int, n2: int) -> Array:
    pad = m * n2 - flat.shape[0]
    return jnp.pad(flat.astype(jnp.float32), (0, pad)).reshape(m, n2)


def _dot(a: Array, b: Array) -> Array:
    # HIGHEST: default matmul precision lowers fp32 operands to bf16 on TPU;
    # the factor products ARE the payload, so precision loss here is wire
    # noise that EF then has to re-absorb (same rationale as blocktopk_scores)
    return jax.lax.dot(a, b, precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)


def powersgd_approx(flat: Array, key: Array, *, rank: int) -> Array:
    """Stateless single-shot rank-``r`` approximation of a flat vector (one
    power iteration from a key-derived random ``Q0``).

    This is the :func:`compressors.get_compressor`-registered form — same
    math as one warm-started engine step, minus the persistent state and the
    collectives — used for registry uniformity and local experimentation;
    training syncs go through :func:`powersgd_group_sync`.
    """
    n = flat.shape[0]
    dims = powersgd_dims(n, rank)
    if dims is None:
        return flat
    m, n2, r = dims
    mat = _as_matrix(flat, m, n2)
    q0 = jax.random.normal(key, (n2, r), jnp.float32)
    p_hat = gram_schmidt(_dot(mat, q0))
    q = _dot(mat.T, p_hat)
    return _dot(p_hat, q.T).reshape(-1)[:n].astype(flat.dtype)


def init_group_state(n: int, rank: int, key: Array) -> Optional[Array]:
    """Warm-start ``Q0 ~ N(0, 1)`` ([n2, r] fp32) for an ``n``-element group,
    or ``None`` for dense-fallback groups.  Deterministic in ``key`` — every
    worker must draw the IDENTICAL warm start or the very first P-psum would
    average factors living in different bases."""
    dims = powersgd_dims(n, rank)
    if dims is None:
        return None
    _, n2, r = dims
    return jax.random.normal(key, (n2, r), jnp.float32)


def powersgd_group_sync(acc: Array, q: Array, rank: int, axis_name,
                        world) -> Tuple[Array, Array, float, float]:
    """One warm-started PowerSGD sync of a group's accumulated gradient.

    ``acc``: the flat fp32 local gradient (+ EF residual); ``q``: this
    group's persistent ``[n2, r]`` warm start.  Must run inside
    ``shard_map`` over ``axis_name``.  Returns ``(recon, q_new, sent_elems,
    sent_bits)`` — ``recon`` is the rank-r approximation of the WORKER-MEAN
    gradient (both factors are psum-averaged before reconstruction), and
    the caller folds ``acc - recon`` into the EF residual.
    """
    n = acc.shape[0]
    dims = powersgd_dims(n, rank)
    assert dims is not None, "dense-fallback groups never reach group_sync"
    m, n2, r = dims
    if q.shape != (n2, r):
        raise ValueError(
            f"warm-start Q shape {q.shape} does not match group dims "
            f"({n2}, {r}) — was the compressor state built by init_comp_state "
            "for this config and gradient tree?")
    mat = _as_matrix(acc, m, n2)
    p = jax.lax.psum(_dot(mat, q), axis_name) / world          # [m, r]
    p_hat = gram_schmidt(p)
    q_new = jax.lax.psum(_dot(mat.T, p_hat), axis_name) / world  # [n2, r]
    recon = _dot(p_hat, q_new.T).reshape(-1)[:n]
    sent = float(r * (m + n2))
    return recon, q_new, sent, 32.0 * sent
