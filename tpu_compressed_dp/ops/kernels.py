"""Pallas TPU kernels for the hot compression ops.

The pure-JAX operators in :mod:`tpu_compressed_dp.ops.compressors` are the
reference semantics; these kernels are drop-in accelerations for the pieces
that map badly onto stock XLA at gradient scale (SURVEY.md §7 "hard parts"):

  * **Top-K threshold select** — the reference thresholds at
    ``kthvalue(|g|)`` (`CIFAR10/core.py:181-183`).  ``jax.lax.top_k`` at
    ResNet-50 scale (25M elements) pays for a full sort; the kernel instead
    finds the threshold by *iterative histogram refinement*: each round makes
    one streaming pass over ``|g|``, counting elements at or above 16
    equispaced bin edges (per-edge compare + sum, pure VPU work), then
    narrows the candidate range to the bin containing the k-th magnitude.
    Seven rounds resolve the threshold to ~``max|g| / 16^7`` = ``max|g| /
    2^28`` — below fp32 tie resolution for real gradients — in O(rounds·n)
    streamed bytes and O(1) memory, with tie semantics identical to the
    reference (everything ``>= threshold`` is kept).  (16 bins x 7 rounds
    replaced 128 x 4: same resolution, ~4x less compare work on the
    compute-bound counting pass.)
  * **Fused stochastic quantisation** (QSGD / TernGrad,
    `core.py:200-213`) — one pass that draws hardware PRNG bits
    (``pltpu.prng_random_bits``), dithers, and emits packed integer levels
    (int16 / int8), instead of XLA materialising a full fp32 uniform tensor.
    The integer levels are exactly what the wire path transmits.
  * **Fused select+pack** (``fused_select_pack``) — one pass from the
    histogram threshold to the compacted ascending ``(value, index)`` wire
    payload (per-segment shift-network compaction + an nseg-sized rank
    bucketing epilogue), replacing the wire path's dense mask ->
    `packed_indices_from_mask` -> `_sorted_gather` chain.  Bitwise-parity
    with the XLA chain, gated in tier-1 under the interpreter.
  * **Fused quantize+pack** (``terngrad_pack`` / ``qsgd_pack``) — dither
    AND bit-pack in the same pass: 2-bit TernGrad codes or QSGD uint8
    magnitudes + sign bitmap come out as wire bytes directly (matmul-based
    lane packing; the byte layout is bitwise `wire.pack_ternary` /
    `wire.pack_bits`).
  * **Fused bucket route** (``fused_bucket_route``) — the sharded
    transport's per-destination fixed-capacity bucket build as W windowed
    DMA copies instead of a [W*cap+1] scatter pair, preserving the
    monotone-row invariant the owner-side sorted-scatter hints rely on.

Dispatch: ``auto`` (default) uses the kernels on TPU backends for tensors
of at least ``MIN_PALLAS_ELEMS`` elements and falls back to pure JAX
elsewhere; ``off`` / ``force`` override.  Off-TPU, ``force`` runs the
non-PRNG kernels under the Pallas interpreter — slow, but it executes the
fused dispatch call sites end to end in CPU CI (PRNG kernels additionally
need the TPU-semantics interpreter, `compat.HAS_TPU_INTERPRET`).  The
quantizer kernels draw from the TPU hardware PRNG, a *different stream* than
``jax.random`` — same distribution, so estimators stay unbiased, but
bitwise results differ from the pure path (the dispatch seed is derived from
the caller's key, so runs remain reproducible for a fixed config).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from tpu_compressed_dp import compat

try:  # Pallas TPU lowering is unavailable on some CPU-only builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False

Array = jax.Array

__all__ = [
    "set_pallas_mode",
    "pallas_mode",
    "topk_threshold",
    "fused_sparsify",
    "use_fused_sparsify",
    "pack_by_threshold",
    "seg_pack_by_threshold",
    "seg_pack_payload",
    "use_seg_pack",
    "fused_select_pack",
    "use_select_pack",
    "pack_ternary_pallas",
    "qsgd_pack_pallas",
    "terngrad_pack",
    "terngrad_pack_prescaled",
    "qsgd_pack",
    "use_quant_pack",
    "fused_bucket_route",
    "use_bucket_route",
    "qsgd_quantize",
    "terngrad_quantize",
    "terngrad_quantize_prescaled",
    "MIN_PALLAS_ELEMS",
]

_MODE = "auto"  # auto | off | force
MIN_PALLAS_ELEMS = 1 << 16
_LANES = 128
_ROWS = 64  # rows per grid step -> 8192-element chunks, int8-tile aligned


def set_pallas_mode(mode: str) -> None:
    global _MODE
    if mode not in ("auto", "off", "force"):
        raise ValueError(f"pallas mode must be auto|off|force, got {mode!r}")
    _MODE = mode


def pallas_mode() -> str:
    return _MODE


def _dispatch_to_pallas(n: int) -> bool:
    if not _HAVE_PALLAS or _MODE == "off":
        return False
    if _MODE == "force":
        return True
    return jax.default_backend() == "tpu" and n >= MIN_PALLAS_ELEMS


def _auto_interpret() -> bool:
    """``force`` off-TPU runs the kernels under the Pallas interpreter, so
    the fused dispatch *paths* (wire/sharded call sites included) execute end
    to end in CPU CI instead of dying in Mosaic lowering.  PRNG kernels stay
    on the TPU-semantics interpreter gate (`compat.HAS_TPU_INTERPRET`) — the
    stock HLO interpreter's PRNG is a zero stub."""
    return _MODE == "force" and jax.default_backend() != "tpu"


def _pad_chunks(flat: Array, fill: float, rows: int = _ROWS) -> Tuple[Array, int]:
    """Pad a flat vector to whole (rows, 128) chunks, reshaped 2D.

    Fill discipline (audited): padding lanes must be invisible to every
    consumer even when the DATA is poisoned (NaN/Inf guard-vetoed steps).
    The histogram kernels use ``fill=-1.0`` — strictly below every bin edge
    (edges are ``>= lo >= 0`` and stay finite via the non-finite ``hi``
    clamp in the threshold paths) — while the pack/quantize/select kernels
    use ``fill=0`` and mask by global position (``pos < n``) instead, which
    holds for any fill.  New kernels must pick one of those two disciplines;
    a fill that merely compares below *typical* data is not enough.
    """
    n = flat.shape[0]
    chunk = rows * _LANES
    padded_n = -(-n // chunk) * chunk
    if padded_n != n:
        flat = jnp.concatenate(
            [flat, jnp.full((padded_n - n,), fill, flat.dtype)]
        )
    return flat.reshape(padded_n // _LANES, _LANES), padded_n // chunk


# ---------------------------------------------------------------------------
# Top-K threshold select
# ---------------------------------------------------------------------------


# big blocks for the streaming histogram: the per-bin compare loop keeps the
# block in vector registers (no 128-wide broadcast materialised), so the
# limits are grid-step overhead and VPU compare throughput
_HIST_ROWS = 1024

# 16 bins x 7 rounds resolves the threshold to max|g| / 16^7 — identical to
# the original 128 bins x 4 rounds (16^7 == 128^4 == 2^28, below fp32 tie
# resolution for real gradients) — but costs 7*16 = 112 compare-ops per
# element instead of 4*128 = 512 on the compute-bound counting pass (~4x
# less VPU work for ~1.75x more streamed bytes, a net ~3x at 170M elements).
_HIST_BINS = 16


def _count_ge_kernel(lo_ref, hi_ref, x_ref, counts_ref):
    """counts[b] += #{x : edge_b <= x < hi} for _HIST_BINS equispaced edges
    in [lo, hi).  Grid walks chunks of the flattened magnitudes; TPU grid
    steps run sequentially, so accumulating into the single output block is
    safe.

    The per-bin unrolled loop compares the block against each scalar edge —
    faster than a broadcast compare (which round-trips bins-times the data
    through VMEM), and the ``lo + width*b`` edge values are bit-identical to
    the thresholds the refine loop narrows to, keeping count/threshold
    consistency exact.  The output block stays one 128-lane row; lanes
    beyond _HIST_BINS are unused.
    """

    @pl.when(pl.program_id(0) == 0)
    def _():
        counts_ref[:] = jnp.zeros_like(counts_ref)

    lo = lo_ref[0, 0]
    hi = hi_ref[0, 0]
    width = (hi - lo) / _HIST_BINS
    x = x_ref[:]
    valid = x < hi
    counts = []
    for b in range(_HIST_BINS):
        edge = lo + width * b
        counts.append(
            jnp.sum(jnp.logical_and(x >= edge, valid).astype(jnp.float32)))
    # full 128-lane row write (lane-partial stores lower poorly on TPU)
    counts += [jnp.float32(0.0)] * (_LANES - _HIST_BINS)
    counts_ref[0, :] += jnp.stack(counts)


def _count_edges_kernel(edges_ref, x_ref, counts_ref):
    """CUMULATIVE counts at arbitrary ascending edges: counts[b] +=
    #{x : edges[b] <= x < edges[_HIST_BINS]} — i.e. count(>= edges[b]) since
    the top edge exceeds max(x).  The data-adapted first round of the
    sampled threshold (equispaced bins can't exploit the sample without a
    branch; quantile edges can).  17 SMEM edges = 16 bins; the selection
    compares these cumulative counts directly against keep."""

    @pl.when(pl.program_id(0) == 0)
    def _():
        counts_ref[:] = jnp.zeros_like(counts_ref)

    x = x_ref[:]
    hi = edges_ref[0, _HIST_BINS]
    valid = x < hi
    counts = []
    for b in range(_HIST_BINS):
        counts.append(jnp.sum(
            jnp.logical_and(x >= edges_ref[0, b], valid).astype(jnp.float32)))
    counts += [jnp.float32(0.0)] * (_LANES - _HIST_BINS)
    counts_ref[0, :] += jnp.stack(counts)


def _vma(x: Array):
    """Varying-mesh-axes of ``x`` — must be propagated onto pallas_call
    out_shapes when the kernel runs on device-varying data inside shard_map."""
    return getattr(compat.typeof(x), "vma", frozenset())


def _topk_threshold_pallas(
    mag: Array, keep: int, *, rounds: int = 7, interpret: bool = False,
    sample_init: bool = True,
) -> Array:
    n = mag.shape[0]
    # clamp BEFORE the sampled-init rank arithmetic: keep > n would give
    # lo_rank > hi_rank and an IndexError at trace time in sv[rk] (the exact
    # path already clamps via keep_f; mirror it here)
    keep = min(keep, n)
    x2d, num_chunks = _pad_chunks(mag.astype(jnp.float32), fill=-1.0,
                                  rows=_HIST_ROWS)

    count_ge = pl.pallas_call(
        _count_ge_kernel,
        grid=(num_chunks,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((_HIST_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct((1, _LANES), jnp.float32, vma=_vma(mag)),
        interpret=interpret,
    )

    keep_f = jnp.float32(min(keep, n))

    def narrow(lo, hi, above, counts):
        total_ge = above + counts  # monotone nonincreasing over bins
        b = jnp.sum((total_ge >= keep_f).astype(jnp.int32)) - 1
        b = jnp.clip(b, 0, _HIST_BINS - 1)
        width = (hi - lo) / _HIST_BINS
        new_lo = lo + width * b.astype(jnp.float32)
        new_hi = jnp.where(b == _HIST_BINS - 1, hi, lo + width * (b + 1).astype(jnp.float32))
        counts_next = jnp.concatenate([counts, jnp.zeros((1,), jnp.float32)])
        new_above = above + jnp.where(
            b == _HIST_BINS - 1, 0.0, counts_next[jnp.clip(b + 1, 0, _HIST_BINS)]
        )
        return new_lo, new_hi, new_above

    def round_body(_, carry):
        lo, hi, above = carry
        counts = count_ge(
            lo.reshape(1, 1).astype(jnp.float32),
            hi.reshape(1, 1).astype(jnp.float32),
            x2d,
        )[0][:_HIST_BINS]
        return narrow(lo, hi, above, counts)

    def pcast(vals):
        # carries become device-varying after a count round (counts derive
        # from the varying magnitudes) — pcast replicated values so loop /
        # cond branch types match
        vma = tuple(_vma(mag))
        if not vma:
            return vals
        return tuple(
            compat.pcast(v, vma, to="varying") if not _vma(v) else v for v in vals
        )

    # max|g| strictly below hi so the top element always lands in a bin.
    # A non-finite max (guard-vetoed NaN/Inf gradient, or fp32 overflow of
    # the eps bump) would poison every bin edge — counts degenerate and the
    # refinement collapses to t = 0, selecting *everything*.  Clamping hi to
    # FP32_MAX keeps the histogram ranking the finite magnitudes: padding
    # lanes (fill -1.0, strictly below every edge >= lo >= 0) still never
    # count, NaNs compare-false out of every bin, and +-Inf sits above every
    # edge exactly like the true max used to.
    hi_raw = jnp.max(mag).astype(jnp.float32) * 1.0000002 + 1e-30
    full_init = pcast(
        (jnp.float32(0.0),
         jnp.where(jnp.isfinite(hi_raw), hi_raw, jnp.float32(3.4028235e38)),
         jnp.float32(0.0)))

    if not sample_init or keep < 1 or n < (1 << 18):
        lo, _, _ = jax.lax.fori_loop(0, rounds, round_body, full_init)
        return lo

    # Sampled init, BRANCHLESS (a lax.cond fallback would run BOTH branches
    # under shard_map — the predicate is device-varying — costing more than
    # the full histogram).  Round 1 counts at data-adapted edges: quantiles
    # of a subsample around the expected k-th rank, bracketed by 0 below and
    # (global max)*(1+eps) above, so the k-th magnitude ALWAYS falls in some
    # bin — no validity branch, and when the sample is representative
    # (always, in practice) the selected bin is already ~delta ranks wide.
    # Four equispaced rounds then refine the selected bin by 16^4.
    #   * sample size targets ~1024 expected survivors so the top_k on the
    #     sample stays cheap at every keep;
    #   * the sample is the first 128 lanes of every C-element block — 512 B
    #     contiguous reads spread across the whole tensor (a fine-strided
    #     slice costs ~a full pass in gathers; slab reads are ~free);
    #   * worst case (adversarial layout hiding all mass from the sample)
    #     degrades RESOLUTION only — the count(mag >= t) >= keep guarantee
    #     is structural (narrow() keeps the k-th inside [lo, hi)), with
    #     surplus up to the selected bin's population instead of tie-level.
    m_target = int(min(max(1024 * n / keep, 1 << 16), 1 << 21))
    C = 128
    while C < (1 << 17) and n * 128 // (C * 2) >= m_target and C * 2 <= n:
        C *= 2
    nb = n // C
    m = nb * 128
    if m > n // 16:
        # mid-size tensors where the sample can't be much smaller than the
        # data: the sample top_k would rival the full histogram — use the
        # exact full-range rounds instead
        lo, _, _ = jax.lax.fori_loop(0, rounds, round_body, full_init)
        return lo
    sample = jax.lax.slice(
        mag[: nb * C].reshape(nb, C).astype(jnp.float32), (0, 0), (nb, 128)
    ).reshape(-1)
    r = keep * m / n
    delta = 4.0 * float(r) ** 0.5 + 8.0
    hi_rank = int(min(m - 1, r + delta))
    lo_rank = int(max(0, r - delta))
    sv = jax.lax.top_k(sample, hi_rank + 1)[0]
    # 15 interior quantile edges spanning [rank r+delta, rank r-delta],
    # ascending in value (17 edges = 16 bins with the 0 and max*(1+eps)
    # brackets); duplicate edges (sample ties) just yield empty bins.
    # A NaN slab sample (guard-vetoed gradient) poisons its top_k quantiles
    # — a NaN edge survives jnp.minimum, zeroes that bin's count, and the
    # bin selection then violates the count >= keep guarantee (underfull
    # pack -> duplicate-index payload).  Clamp non-finite edges to the hi
    # bracket: an empty top bin, exactly like a duplicate edge.
    qranks = [int(round(lo_rank + (hi_rank - lo_rank) * i / 14.0))
              for i in range(15)]
    interior = [sv[rk] for rk in reversed(qranks)]           # ascending
    hi0 = full_init[1]                                       # max*(1+eps)
    edges = jnp.stack(
        [jnp.float32(0.0) if not _vma(mag)
         else compat.pcast(jnp.float32(0.0), tuple(_vma(mag)), to="varying")]
        + [jnp.where(jnp.isfinite(e), jnp.minimum(e, hi0), hi0)
           for e in interior] + [hi0]
    )

    count_edges = pl.pallas_call(
        _count_edges_kernel,
        grid=(num_chunks,),
        in_specs=[
            pl.BlockSpec((1, _HIST_BINS + 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((_HIST_ROWS, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, _LANES), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct((1, _LANES), jnp.float32, vma=_vma(mag)),
        interpret=interpret,
    )
    counts = count_edges(edges.reshape(1, -1), x2d)[0][:_HIST_BINS]
    # bin selection against the edge ARRAY (narrow()'s arithmetic edges
    # don't apply to the quantile round)
    total_ge = counts  # counts[b] already counts >= edges[b] (above == 0)
    b = jnp.clip(jnp.sum((total_ge >= keep_f).astype(jnp.int32)) - 1,
                 0, _HIST_BINS - 1)
    new_lo = edges[b]
    new_hi = edges[b + 1]
    counts_ext = jnp.concatenate([counts, jnp.zeros((1,), jnp.float32)])
    new_above = counts_ext[jnp.clip(b + 1, 0, _HIST_BINS)]
    carry = (new_lo, new_hi, new_above)
    # 4 equispaced rounds refine the selected bin by 16^4: tie-level surplus
    # for representative samples, and a few percent even when the whole
    # top-k mass hides from the sample (the degraded worst case — see
    # tests/test_kernels.py adversarial-layout case)
    lo, _, _ = jax.lax.fori_loop(0, 4, round_body, carry)
    return lo


_INT32_MAX = (1 << 31) - 1


def _topk_threshold_jnp(mag: Array, keep: int, rounds: int = 7) -> Array:
    """Pure-jnp histogram refinement — the Pallas kernel's algorithm without
    the kernel: 16 bins per round via one bucketize + scatter-add pass (not
    16 per-edge compare passes), 7 rounds -> threshold resolved to
    ``max|g| / 2^28``.  The fallback for sizes where ``lax.top_k`` would
    overflow its int32 indices (> 2^31 elements: the 8B entire-model
    groups), and for abstract evaluation of those configs off-TPU.

    Counts accumulate in float32, whose ulp at 2^32 is 512 — the bin
    selection therefore targets ``keep + margin`` with ``margin`` a few
    float32 ulps of n, so cumulative-count rounding can only ADD surplus
    (threshold a hair low), never break ``count(mag >= t) >= keep``.
    """
    n = mag.shape[0]
    mag = mag.astype(jnp.float32)
    # conservative target: fp32 summation error is bounded by a few ulps of
    # the running total; 8 ulps of n keeps the guarantee one-sided
    margin = 8.0 * n / float(1 << 23) if n > (1 << 23) else 0.0
    keep_f = jnp.float32(min(keep + margin, n))
    lo = jnp.float32(0.0)
    # same non-finite clamp as the kernel path: a NaN/Inf max must not
    # poison the bin edges (see _topk_threshold_pallas)
    hi_raw = (jnp.max(mag) * 1.0000002 + 1e-30).astype(jnp.float32)
    hi = jnp.where(jnp.isfinite(hi_raw), hi_raw, jnp.float32(3.4028235e38))
    above = jnp.float32(0.0)
    for _ in range(rounds):
        width = (hi - lo) / _HIST_BINS
        idx = jnp.clip(((mag - lo) / width).astype(jnp.int32),
                       0, _HIST_BINS - 1)
        valid = (mag >= lo) & (mag < hi)
        hist = jnp.zeros((_HIST_BINS,), jnp.float32).at[
            jnp.where(valid, idx, 0)].add(valid.astype(jnp.float32))
        # counts[b] = #{x : x >= edge_b, x < hi} = suffix sum of the hist
        counts = jnp.cumsum(hist[::-1])[::-1]
        total_ge = above + counts
        b = jnp.clip(jnp.sum((total_ge >= keep_f).astype(jnp.int32)) - 1,
                     0, _HIST_BINS - 1)
        new_lo = lo + width * b.astype(jnp.float32)
        new_hi = jnp.where(b == _HIST_BINS - 1, hi,
                           lo + width * (b + 1).astype(jnp.float32))
        counts_next = jnp.concatenate([counts, jnp.zeros((1,), jnp.float32)])
        above = above + jnp.where(
            b == _HIST_BINS - 1, 0.0,
            counts_next[jnp.clip(b + 1, 0, _HIST_BINS)])
        lo, hi = new_lo, new_hi
    return lo


def topk_threshold(mag: Array, keep: int) -> Array:
    """Magnitude threshold keeping ``>= keep`` elements (ties included).

    Exact (``lax.top_k``) below the dispatch cutoff or off-TPU; histogram
    kernel above it; pure-jnp histogram beyond int32 sizes.  Either way
    ``count(mag >= t) >= keep`` with surplus only from ties at the returned
    threshold's resolution.
    """
    n = mag.shape[0]
    if keep >= n:
        return jnp.zeros((), jnp.float32)
    if _dispatch_to_pallas(n):
        # fp32 always: downcasting the bin edge to a lower-precision input
        # dtype could round UP past the true k-th magnitude and break the
        # count(mag >= t) >= keep guarantee
        return _topk_threshold_pallas(mag, keep, interpret=_auto_interpret())
    if n > _INT32_MAX:
        return _topk_threshold_jnp(mag, keep)
    # NaN sorts as LARGEST under lax.top_k: each guard-vetoed NaN would
    # steal a top-k slot, land the threshold one rank too high, and
    # underfill the pack (duplicate-index payload, voided scatter hints —
    # the poisoned-tail leak).  Demote NaN below every magnitude so the
    # threshold ranks the finite values; NaN still never travels (it
    # compares false against any threshold).
    m32 = mag.astype(jnp.float32)
    m32 = jnp.where(jnp.isnan(m32), -1.0, m32)
    return jax.lax.top_k(m32, keep)[0][-1]


# ---------------------------------------------------------------------------
# Fused sparsify (simulate-mode Top-K / threshold epilogue)
# ---------------------------------------------------------------------------


def _fused_sparsify_kernel(want_ef: bool, n: int, t_ref, x_ref, *refs):
    """One streaming pass over the accumulated gradient: apply the magnitude
    threshold and emit the compressed tensor, (optionally) the new EF
    residual, and the nonzero-survivor count — replacing the where/subtract/
    count_nonzero pass chain XLA would otherwise run as separate kernels
    around the pallas threshold call (pallas_call boundaries block fusion).
    Padding beyond ``n`` is excluded from the count via a global-position
    mask, and exact zeros never count as sent (matching ``count_nonzero`` on
    the unfused path even at threshold 0)."""
    if want_ef:
        comp_ref, ef_ref, count_ref = refs
    else:
        comp_ref, count_ref = refs
        ef_ref = None

    @pl.when(pl.program_id(0) == 0)
    def _():
        count_ref[:] = jnp.zeros_like(count_ref)

    rows, lanes = comp_ref.shape
    acc = x_ref[:]
    base = pl.program_id(0) * rows * lanes
    pos = (base
           + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0) * lanes
           + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1))
    keep = jnp.logical_and(jnp.abs(acc) >= t_ref[0, 0], pos < n)
    comp = jnp.where(keep, acc, 0.0)
    comp_ref[:] = comp
    if ef_ref is not None:
        ef_ref[:] = acc - comp
    sent = jnp.logical_and(keep, acc != 0.0)
    # int32 accumulation: fp32 partial sums round past 2^24 sent elements,
    # drifting from the unfused path's integer-exact count_nonzero
    row = [jnp.sum(sent.astype(jnp.int32))]
    row += [jnp.int32(0)] * (_LANES - 1)
    count_ref[0, :] += jnp.stack(row)


# fat blocks: <=3 streams x 512 rows x 128 lanes x 4 B = <=0.8 MB live VMEM
# per grid step; fewer grid steps matter — 64-row blocks measured ~8 ms
# SLOWER at a 100M-element leaf from per-step overhead alone
_SPARSIFY_ROWS = 512


def fused_sparsify(acc: Array, t: Array, *, want_ef: bool = True,
                   interpret: bool | None = None):
    """``(comp, new_ef | None, count)`` keeping coordinates ``|acc| >= t`` —
    the simulate-mode epilogue fused into one pass over the (already
    EF-accumulated) gradient.  fp32 in/out: the caller gates dispatch on
    fp32 inputs so the psum payload dtype matches the unfused path."""
    if interpret is None:
        interpret = _auto_interpret()
    n = acc.shape[0]
    rows = _SPARSIFY_ROWS
    x2d, num_chunks = _pad_chunks(acc.astype(jnp.float32), fill=0.0, rows=rows)
    vma = _vma(acc)
    big = pl.BlockSpec((rows, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    out_specs = [big] + ([big] if want_ef else []) + [
        pl.BlockSpec((1, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM)]
    out_shape = [compat.shape_dtype_struct(x2d.shape, jnp.float32, vma=vma)]
    if want_ef:
        out_shape.append(compat.shape_dtype_struct(x2d.shape, jnp.float32, vma=vma))
    out_shape.append(compat.shape_dtype_struct((1, _LANES), jnp.int32, vma=vma))
    outs = pl.pallas_call(
        functools.partial(_fused_sparsify_kernel, want_ef, n),
        grid=(num_chunks,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            big,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(t.reshape(1, 1).astype(jnp.float32), x2d)
    comp = outs[0].reshape(-1)[:n]
    new_ef = outs[1].reshape(-1)[:n] if want_ef else None
    return comp, new_ef, outs[-1][0, 0].astype(jnp.float32)


def use_fused_sparsify(n: int) -> bool:
    """Whether the fused simulate-mode epilogue should serve this tensor.

    Above int32 sizes the kernel's global-position iota would wrap — the
    unfused path (threshold + where) handles those (XLA indexes with s64
    where needed)."""
    return _dispatch_to_pallas(n) and n <= _INT32_MAX


# ---------------------------------------------------------------------------
# Fused threshold-pack (wire-mode Top-K stream compaction)
# ---------------------------------------------------------------------------

# block = _PACK_ROWS x 128 elements assembled in a VMEM scratch then DMA'd
# to the HBM output at the running ROW offset.  Inner compaction vectorises
# _PACK_SUB rows at a time ([_PACK_SUB, 128, 128] one-hot reduce).
_PACK_ROWS = 512
_PACK_SUB = 8


def pack_payload_slots(n: int, keep: int) -> int:
    """Payload capacity of the packed (vals, idx) buffers: survivors pack
    tightly WITHIN a block, but block bases are 128-aligned in the output
    (Mosaic supports dynamic addressing at row granularity only), wasting
    <128 zero slots per 64k-element block — zeros with idx 0, scatter-add
    identities.  Transport must be billed at this size."""
    blocks = -(-max(n, 1) // (_PACK_ROWS * _LANES))
    return -(-keep // _LANES) * _LANES + blocks * _LANES


def _pack_kernel(n: int, cap_rows: int, want_ef: bool, t_ref, x_ref, *refs):
    """One streaming pass over |acc| >= t: emits the packed (values,
    indices) payload — ascending index, zero-padded at row-alignment gaps —
    plus (optionally) the EF residual and the survivor count.

    Replaces the r2 chain threshold-mask -> hierarchical rank -> gather ->
    EF scatter (4+ passes with element-granular gathers at ~25-50 M/s,
    benchmarks/lm_throughput_r2.txt) with: per-row inclusive prefix via a
    lower-triangular matmul, in-row one-hot compaction with the row's
    lane-rotation folded into the one-hot destination (Mosaic has no
    dynamic element-granular stores OR dynamic 1-D rotates), two
    dynamic-ROW read-modify-write stores per source row into a zeroed
    scratch, one fixed-size DMA per block at the block's base row.
    """
    if want_ef:
        vals_ref, idx_ref, ef_ref, count_ref = refs[:4]
        scratch_v, scratch_i, off_ref, sem_v, sem_i = refs[4:]
    else:
        vals_ref, idx_ref, count_ref = refs[:3]
        scratch_v, scratch_i, off_ref, sem_v, sem_i = refs[3:]

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        off_ref[0] = 0   # rows emitted (all blocks)
        off_ref[1] = 0   # survivors seen (all blocks)
        off_ref[2] = 0   # survivors SHIPPED
        off_ref[3] = 0   # shipped rows end (zero-mask boundary)

    t = t_ref[0, 0]
    x = x_ref[:]                                   # [_PACK_ROWS, 128]
    base_pos = i * _PACK_ROWS * _LANES
    pos = (base_pos
           + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) * _LANES
           + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1))
    mask = jnp.logical_and(jnp.abs(x) >= t, pos < n)
    maskf = mask.astype(jnp.float32)
    tri = jnp.tril(jnp.ones((_LANES, _LANES), jnp.float32))
    prefix = maskf @ tri.T                          # [R,128] inclusive rank
    c_row_f = prefix[:, _LANES - 1]                  # survivors/row (fp32 —
    # exact: block totals <= 65536 << 2^24).  Mosaic has no cumsum; the
    # exclusive running offsets come from another triangular matmul.
    tri_r = jnp.tril(jnp.ones((_PACK_ROWS, _PACK_ROWS), jnp.float32))
    incl = tri_r @ c_row_f                           # [R] inclusive
    excl_f = incl - c_row_f                          # [R] exclusive (fp32)
    row_off = excl_f.astype(jnp.int32)

    blk_count = incl[_PACK_ROWS - 1].astype(jnp.int32)
    base_row = off_ref[0]
    rows_used = (blk_count + _LANES - 1) // _LANES
    # a block ships only if it fits WHOLE below the capacity (a spilling
    # block keeps ALL its survivors in the residual — shipping half while
    # zeroing the residual for all would lose gradient mass); base always
    # advances, so truncation is sticky and the payload stays ascending
    shipped = base_row + rows_used <= cap_rows
    off_ref[0] = base_row + rows_used
    off_ref[1] = off_ref[1] + blk_count
    off_ref[2] = off_ref[2] + jnp.where(shipped, blk_count, 0)
    off_ref[3] = jnp.where(shipped, base_row + rows_used, off_ref[3])
    count_ref[0, 0] = off_ref[2]   # survivors actually in the payload
    count_ref[0, 1] = off_ref[1]   # survivors seen (incl. truncated)
    count_ref[0, 2] = off_ref[3]   # valid payload rows (zero-mask bound)

    if want_ef:
        # residual = unshipped coordinates
        ef_ref[:] = jnp.where(jnp.logical_and(mask, shipped), 0.0, x)

    # ---- in-row compaction with the lane rotation folded in -------------
    # dest lane of a survivor = (rank-1 + row_off%128) mod 128.  Channels
    # kept f32-exact for the MXU: values, source LANE (< 128), and the
    # absolute source ROW id (< n/128 <= 2^24) — idx = row*128 + lane is
    # reassembled in int32 at the end (a single f32 position channel would
    # round above 2^24).
    lane_d = jax.lax.broadcasted_iota(
        jnp.int32, (_PACK_SUB, _LANES, _LANES), 2
    ).astype(jnp.float32)  # dest lane iota (tpu.iota is integer-only)
    lane_src = jax.lax.broadcasted_iota(
        jnp.int32, (_PACK_SUB, _LANES), 1).astype(jnp.float32)
    q_all = row_off // _LANES                             # [R] int32
    rem_all_f = (row_off - q_all * _LANES).astype(jnp.float32)
    comp_v_parts = []
    comp_l_parts = []
    comp_ok_parts = []
    for s in range(_PACK_ROWS // _PACK_SUB):
        sl = slice(s * _PACK_SUB, (s + 1) * _PACK_SUB)
        dest = prefix[sl][:, :, None] - 1.0 + rem_all_f[sl][:, None, None]
        dest = dest - jnp.where(dest >= _LANES, float(_LANES), 0.0)
        hitf = (jnp.where(dest == lane_d, 1.0, 0.0)
                * maskf[sl][:, :, None])                  # [S,src,dst]
        # batched matvec (einsum rsd,rs->rd) crashes Mosaic — VPU
        # multiply-sum instead; the MXU work is the 2-D placement matmuls
        comp_v_parts.append(jnp.sum(hitf * x[sl][:, :, None], axis=1))
        comp_l_parts.append(jnp.sum(hitf * lane_src[:, :, None], axis=1))
        comp_ok_parts.append(jnp.sum(hitf, axis=1))
    comp_v = jnp.concatenate(comp_v_parts)                # [R,128]
    comp_l = jnp.concatenate(comp_l_parts)
    comp_ok = jnp.concatenate(comp_ok_parts)              # 1.0 at payload

    # ---- block-level row placement as two MXU matmuls -------------------
    # Row r's (pre-rotated) payload splits into dst rows q_r (lanes >= rem)
    # and q_r + 1 (lanes < rem); the placement matrices are one-hots over
    # dst rows, so stage = Q1 @ hi-part + Q2 @ lo-part — no dynamic stores,
    # no serialized read-modify-write chains (the v1 kernel's 3x loss).
    rows_d = jax.lax.broadcasted_iota(
        jnp.int32, (_PACK_ROWS + 8, _PACK_ROWS), 0)
    q_f = q_all.astype(jnp.float32)
    rows_d_f = rows_d.astype(jnp.float32)
    Q1 = jnp.where(rows_d_f == q_f[None, :], 1.0, 0.0)
    Q2 = jnp.where(rows_d_f == q_f[None, :] + 1.0, 1.0, 0.0)
    lanes_f = jax.lax.broadcasted_iota(
        jnp.int32, (_PACK_ROWS, _LANES), 1).astype(jnp.float32)
    hi = jnp.where(lanes_f >= rem_all_f[:, None], 1.0, 0.0)
    lo = 1.0 - hi

    def place(c):
        # HIGHEST precision: the MXU's default rounds operands to bf16 —
        # fatal for the value channel and for row ids above 256 (the 0/1
        # COUNT matmuls above are safe: exact operands, f32 accumulation)
        hi_part = jnp.matmul(Q1, c * hi,
                             precision=jax.lax.Precision.HIGHEST)
        lo_part = jnp.matmul(Q2, c * lo,
                             precision=jax.lax.Precision.HIGHEST)
        return hi_part + lo_part                          # [R+8, 128]

    row_abs_f = (jnp.float32(i) * _PACK_ROWS
                 + jax.lax.broadcasted_iota(
                     jnp.int32, (_PACK_ROWS, _LANES), 0).astype(jnp.float32))
    stage_v = place(comp_v)
    stage_l = place(comp_l)
    stage_row = place(comp_ok * row_abs_f)
    stage_ok = place(comp_ok)
    stage_i = jnp.where(
        stage_ok > 0.0,
        stage_row.astype(jnp.int32) * _LANES + stage_l.astype(jnp.int32),
        0)
    scratch_v[:] = stage_v
    scratch_i[:] = stage_i

    @pl.when(shipped)
    def _():
        dv = pltpu.make_async_copy(
            scratch_v.at[pl.ds(0, _PACK_ROWS), :],
            vals_ref.at[pl.ds(base_row, _PACK_ROWS), :], sem_v)
        di = pltpu.make_async_copy(
            scratch_i.at[pl.ds(0, _PACK_ROWS), :],
            idx_ref.at[pl.ds(base_row, _PACK_ROWS), :], sem_i)
        dv.start()
        di.start()
        dv.wait()
        di.wait()


def pack_by_threshold(acc: Array, t: Array, keep: int, *, want_ef: bool = True,
                      interpret: bool = False):
    """``(vals [P], idx [P], new_ef|None, count)`` with ``P =
    pack_payload_slots(n, keep)``: the coordinates with ``|acc| >= t`` by
    ascending index (the wire-mode Top-K payload), zero-padded at the
    row-alignment gaps (identities under scatter-add), their values, and
    the residual, in one fused pass.

    Caller guarantees ``count(|acc| >= t) >= keep`` (the `topk_threshold`
    structural guarantee); capacity-truncated survivors stay in the
    residual (whole-block granularity), and the returned ``count`` is the
    survivors actually in the payload.

    STATUS: correct and tested, but MEASURED SLOWER than the unfused
    pack chain on TPU v5e (0.32-0.45x; benchmarks/pack_kernel_r3.txt) —
    deliberately NOT dispatched by the wire path.  Kept as the measured
    negative result VERDICT r2 #4 asked for, and as the base for the
    shift-network follow-up sketched in the benchmark notes.
    """
    n = acc.shape[0]
    if n > _INT32_MAX:
        raise ValueError(f"pack_by_threshold indexes int32; got n={n}")
    x2d, num_blocks = _pad_chunks(acc.astype(jnp.float32), fill=0.0,
                                  rows=_PACK_ROWS)
    vma = _vma(acc)
    cap_rows = pack_payload_slots(n, keep) // _LANES
    out_rows = cap_rows + _PACK_ROWS          # slack for the last DMA window
    out_shape = [
        compat.shape_dtype_struct((out_rows, _LANES), jnp.float32, vma=vma),
        compat.shape_dtype_struct((out_rows, _LANES), jnp.int32, vma=vma),
    ]
    out_specs = [
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    if want_ef:
        out_shape.append(compat.shape_dtype_struct(x2d.shape, jnp.float32, vma=vma))
        out_specs.append(pl.BlockSpec((_PACK_ROWS, _LANES), lambda i: (i, 0),
                                      memory_space=pltpu.VMEM))
    out_shape.append(compat.shape_dtype_struct((1, 3), jnp.int32, vma=vma))
    out_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    outs = pl.pallas_call(
        functools.partial(_pack_kernel, n, cap_rows, want_ef),
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((_PACK_ROWS, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            # 8 spare rows (sublane-aligned staging): the last source row's
            # wrapped placement lands in row R; the DMA copies rows [0, R)
            pltpu.VMEM((_PACK_ROWS + 8, _LANES), jnp.float32),
            pltpu.VMEM((_PACK_ROWS + 8, _LANES), jnp.int32),
            pltpu.SMEM((4,), jnp.int32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=compat.pallas_interpret_params() if interpret else False,
        compiler_params=compat.pallas_compiler_params(
            has_side_effects=True,
            # the unrolled one-hot sub-blocks keep several [S,128,128]
            # temporaries live; the default 16M scoped-vmem limit is too
            # tight for the block size (v5e has 128M physical VMEM)
            vmem_limit_bytes=96 * 1024 * 1024,
        ),
    )(t.reshape(1, 1).astype(jnp.float32), x2d)
    P = cap_rows * _LANES
    counts = outs[-1]
    # rows past the last SHIPPED block are uninitialised HBM — zero them
    # (zeros/idx-0 are scatter-add identities, like the alignment gaps)
    valid = jnp.arange(P, dtype=jnp.int32) < counts[0, 2] * _LANES
    vals = jnp.where(valid, outs[0].reshape(-1)[:P], 0.0)
    idx = jnp.where(valid, outs[1].reshape(-1)[:P], 0)
    new_ef = outs[2].reshape(-1)[:n] if want_ef else None
    count = counts[0, 0]   # survivors actually in the payload
    return vals, idx, new_ef, count


# ---------------------------------------------------------------------------
# Segmented shift-network pack (the r3 follow-up: log-round static rolls)
# ---------------------------------------------------------------------------

# Segment = _SEG_ROWS x 128 elements compacted independently; _SEG_PER_BLOCK
# segments per grid step amortise grid overhead.  Per segment the kernel
# computes in-segment survivor ranks (one tri-matmul in-row prefix + a
# Hillis-Steele row scan), then routes each survivor LEFT by its compaction
# distance d = pos - (rank-1) in log2(SEG) rounds of STATIC flattened rolls
# (round b moves every element whose remaining distance has bit b set by
# 2^b).  Distances are monotone non-decreasing in position, which makes the
# LSB->MSB schedule collision-free: an arrival can only land on a dead slot
# or a slot simultaneously vacated (fuzz-verified; tests).  No per-element
# dynamic stores, no one-hot materialisation — exactly the two walls the r3
# kernel measured (benchmarks/pack_kernel_r3.txt).
_SEG_ROWS = 32                    # 4096 elements per segment
_SEG = _SEG_ROWS * _LANES
_SEG_PER_BLOCK = 16               # 512 rows / grid step
_SEG_CAP = _LANES                 # payload slots per segment (one lane row)


def seg_pack_slots(n: int) -> int:
    """Payload capacity of the segmented layout: cap slots per segment."""
    nseg = -(-n // _SEG)
    return nseg * _SEG_CAP


def _roll_flat(a: Array, s: int, seg_rows: int):
    """Flattened-order left roll by static ``s`` on a [R, 128] block, with
    row wrap INSIDE the block (callers mask cross-segment wraps).

    NB roll-by-0 must short-circuit: Mosaic lowers jnp.roll to a slice pair
    and rejects the zero-size half."""
    row_part, lane_part = divmod(s, _LANES)
    a0 = a if row_part == 0 else jnp.roll(a, -row_part, axis=0)
    if lane_part == 0:
        return a0
    a1 = jnp.roll(a, -(row_part + 1), axis=0)
    lane = jax.lax.broadcasted_iota(jnp.int32, a.shape, 1)
    return jnp.where(lane < _LANES - lane_part,
                     jnp.roll(a0, -lane_part, axis=1),
                     jnp.roll(a1, -lane_part, axis=1))


def _seg_pack_kernel(n: int, keep: int, want_ef: bool, t_ref, x_ref,
                     start_ref, cnt_ref, *out_refs):
    if want_ef:
        vals_ref, idx_ref, ef_ref = out_refs
    else:
        vals_ref, idx_ref = out_refs
        ef_ref = None
    rows = x_ref.shape[0]                        # _SEG_PER_BLOCK * _SEG_ROWS
    x = x_ref[:]
    base = pl.program_id(0) * rows * _LANES
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, _LANES), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (rows, _LANES), 0)
    gpos = base + row * _LANES + lane
    seg_row = row % _SEG_ROWS                    # row index within the segment
    spos = seg_row * _LANES + lane               # flat position within segment
    m = jnp.logical_and(jnp.abs(x) >= t_ref[0, 0], gpos < n)

    # in-segment 1-based survivor rank: in-row inclusive prefix (tri matmul,
    # rows are segment-local by construction) + exclusive row prefix within
    # the segment (Hillis-Steele over sublanes, masked at segment boundaries)
    mf = m.astype(jnp.float32)
    tri = (jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 0)
           <= jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 1)
           ).astype(jnp.float32)
    inrow = jax.lax.dot_general(mf, tri, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    rowcnt = jnp.broadcast_to(inrow[:, _LANES - 1:], (rows, _LANES))
    rowpfx = rowcnt                              # inclusive over segment rows
    s = 1
    while s < _SEG_ROWS:
        shifted = jnp.roll(rowpfx, s, axis=0)
        rowpfx = jnp.where(seg_row >= s, rowpfx + shifted, rowpfx)
        s *= 2
    rank = (rowpfx - rowcnt + inrow).astype(jnp.int32)   # 1-based, survivors

    eligible = jnp.logical_and(m, rank <= _SEG_CAP)
    if ef_ref is not None:
        # start_ref: [rows, 1] per-ROW copy of the segment's exclusive
        # eligible-prefix — [., 1] so the in-kernel broadcast is lane-only
        # (Mosaic has no sublane+lane broadcast)
        sent = jnp.logical_and(eligible, start_ref[:] + rank <= keep)
        ef_ref[:] = jnp.where(sent, 0.0, x)

    # route eligible survivors left by d = spos - (rank-1); d == 0 is dead
    d = jnp.where(eligible, spos - (rank - 1), 0)
    vals = x
    gidx = gpos
    b = 0
    while (1 << b) < _SEG:
        sft = 1 << b
        rd = _roll_flat(d, sft, _SEG_ROWS)
        rv = _roll_flat(vals, sft, _SEG_ROWS)
        ri = _roll_flat(gidx, sft, _SEG_ROWS)
        # arrivals: source element (at spos+sft, same segment) moving now
        move_in = jnp.logical_and(((rd >> b) & 1) == 1, spos < _SEG - sft)
        my_move = ((d >> b) & 1) == 1
        vals = jnp.where(move_in, rv, vals)
        gidx = jnp.where(move_in, ri, gidx)
        d = jnp.where(move_in, rd - sft, jnp.where(my_move, 0, d))
        b += 1

    # segment s_local's compacted payload = its first _SEG_CAP slots (row 0)
    v3 = vals.reshape(rows // _SEG_ROWS, _SEG_ROWS, _LANES)
    i3 = gidx.reshape(rows // _SEG_ROWS, _SEG_ROWS, _LANES)
    # mask dead tail slots (rank beyond count): their lanes carry stale
    # values — zero value / index 0 are scatter-add identities.  cnt_ref is
    # the per-segment survivor count [_SEG_PER_BLOCK, 1] (computed outside;
    # [., 1] keeps the comparison's broadcast lane-only)
    live = (jax.lax.broadcasted_iota(
        jnp.int32, (rows // _SEG_ROWS, _LANES), 1) < cnt_ref[:])
    vals_ref[:] = jnp.where(live, v3[:, 0, :], 0.0)
    idx_ref[:] = jnp.where(live, i3[:, 0, :], 0)


def seg_pack_by_threshold(acc: Array, t: Array, keep: int, *,
                          want_ef: bool = True, interpret: bool = False):
    """``(vals [nseg, 128], idx [nseg, 128], new_ef [n] | None,
    elig [nseg], counts [nseg])``: per-segment left-compacted survivors
    (``|acc| >= t``), their global indices, and the EF residual, in one
    fused pass per element.

    Wire semantics: each 4096-element segment contributes at most 128
    survivors (ascending index); the epilogue (:func:`seg_pack_payload`)
    concatenates the per-segment prefixes and truncates to ``keep`` — when a
    segment overflows its cap, the overflow stays in the residual and later
    survivors take the freed payload slots (same capacity discipline as the
    wire thresholdv path, segment-granular instead of global).  ``counts``
    is the raw per-segment survivor count (for overflow reporting),
    ``elig = min(counts, 128)``.
    """
    n = acc.shape[0]
    if n > _INT32_MAX:
        raise ValueError(f"seg_pack_by_threshold indexes int32; got n={n}")
    rows_blk = _SEG_PER_BLOCK * _SEG_ROWS
    x2d, num_blocks = _pad_chunks(acc.astype(jnp.float32), fill=0.0,
                                  rows=rows_blk)
    nseg = x2d.shape[0] // _SEG_ROWS
    vma = _vma(acc)
    # per-segment eligible-prefix (exclusive): counts need one cheap mask
    # pass (the kernel recomputes the mask in-VMEM; this pass is linear and
    # XLA-fused, ~1 read of n)
    tf = jnp.asarray(t, jnp.float32)
    m2 = jnp.logical_and(jnp.abs(x2d) >= tf,
                         jnp.arange(x2d.size, dtype=jnp.int32)
                         .reshape(x2d.shape) < n)
    counts = jnp.sum(m2.reshape(nseg, _SEG_ROWS * _LANES), axis=1,
                     dtype=jnp.int32)
    elig = jnp.minimum(counts, _SEG_CAP)
    starts = (jnp.cumsum(elig) - elig).astype(jnp.int32)   # exclusive
    start_rows = jnp.repeat(starts, _SEG_ROWS)[:, None]    # [rows, 1]
    blk = pl.BlockSpec((rows_blk, _LANES), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    seg_out = pl.BlockSpec((_SEG_PER_BLOCK, _LANES), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    out_specs = [seg_out, seg_out] + ([blk] if want_ef else [])
    out_shape = [
        compat.shape_dtype_struct((nseg, _LANES), jnp.float32, vma=vma),
        compat.shape_dtype_struct((nseg, _LANES), jnp.int32, vma=vma),
    ] + ([compat.shape_dtype_struct(x2d.shape, jnp.float32, vma=vma)]
         if want_ef else [])
    outs = pl.pallas_call(
        functools.partial(_seg_pack_kernel, n, int(keep), want_ef),
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            blk,
            pl.BlockSpec((rows_blk, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_SEG_PER_BLOCK, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(jnp.asarray(t).reshape(1, 1).astype(jnp.float32), x2d, start_rows,
      counts[:, None])
    new_ef = outs[2].reshape(-1)[:n] if want_ef else None
    return outs[0], outs[1], new_ef, elig, counts


def seg_pack_payload(vals: Array, idx: Array, elig: Array, keep: int):
    """Concatenate per-segment compacted prefixes into the exact ``keep``-slot
    wire payload: slot ``j`` holds eligible survivor ``j+1`` in ascending
    global order (rank bucketing over segment ends — the
    `packed_indices_from_mask` trick at segment granularity, ~32x fewer
    buckets than per-128-lane rows).  Slots past the eligible total are
    zero/index-0 (scatter-add identities)."""
    nseg = vals.shape[0]
    ends = jnp.cumsum(elig)                                # inclusive
    ranks = jnp.arange(1, keep + 1, dtype=jnp.int32)
    hist = jnp.zeros((keep + 1,), jnp.int32).at[
        jnp.minimum(ends, keep)].add(1)
    seg_of = jnp.cumsum(hist)[:keep]
    valid = seg_of < nseg
    seg_of = jnp.where(valid, seg_of, 0)
    within = ranks - (ends[seg_of] - elig[seg_of]) - 1     # 0-based slot
    flat_pos = seg_of * _LANES + within
    pvals = jnp.where(valid, vals.reshape(-1)[flat_pos], 0.0)
    pidx = jnp.where(valid, idx.reshape(-1)[flat_pos], 0)
    return pvals, pidx


_SEG_PACK_DISPATCH = False


def use_seg_pack(n: int, keep: int) -> bool:
    """Whether the wire Top-K path should take the segmented shift-network
    kernel.  OFF by default (round-4 measured result: at the 125M-param LM
    config the kernel ties the unfused chain end-to-end — 45.0k vs 45.9k
    tok/s — while segment-cap overflow on concentrated LM gradients drops
    the effective sent fraction to ~0.5%; benchmarks/pack_kernel_r4.txt).
    The structural gates remain for forced/experimental use: TPU,
    int32-indexable, keep density comfortably under the per-segment cap
    (128/4096 = 3.125%)."""
    return (_SEG_PACK_DISPATCH and _dispatch_to_pallas(n)
            and n <= _INT32_MAX and keep * 2 * _SEG <= n * _SEG_CAP)


# ---------------------------------------------------------------------------
# Fused select+pack (wire-mode Top-K: one-pass threshold select -> payload)
# ---------------------------------------------------------------------------
#
# The r4 seg-pack postmortem identified the per-segment CAP as the killer
# (concentrated LM gradients overflow 128 slots/4096 elements and drop sent
# mass), not the shift network itself.  This kernel removes the cap: each
# 4096-element segment is FULLY left-compacted (capacity = segment size, so
# no survivor is ever clipped), staging compacted (value, global-index)
# pairs plus a per-segment survivor count in ONE pass over the gradient.  A
# small XLA epilogue (cumsum over nseg counts + one rank-bucketed gather of
# exactly `keep` slots) then assembles the wire payload — the
# `packed_indices_from_mask` trick at segment granularity, ~32x fewer
# buckets than the per-128-lane-row XLA chain, and without the chain's
# full-width mask materialisation, row-count pass, and element gather over n.
# Within-segment compaction preserves ascending order and segments are
# ascending, so the payload is bitwise identical to the unfused
# mask -> packed_indices_from_mask -> _sorted_gather pipeline (parity-gated
# in tier-1 under the interpreter).


def _select_pack_kernel(n: int, t_ref, x_ref, vals_ref, idx_ref, cnt_ref):
    rows = x_ref.shape[0]                        # _SEG_PER_BLOCK * _SEG_ROWS
    x = x_ref[:]
    base = pl.program_id(0) * rows * _LANES
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, _LANES), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (rows, _LANES), 0)
    gpos = base + row * _LANES + lane
    seg_row = row % _SEG_ROWS
    spos = seg_row * _LANES + lane
    # fp32 magnitude compare regardless of input dtype — matches the wire
    # path's `jnp.abs(flat).astype(f32) >= t` bit for bit (abs is exact, and
    # upcast-then-compare equals compare-after-promotion for bf16 inputs)
    m = jnp.logical_and(jnp.abs(x.astype(jnp.float32)) >= t_ref[0, 0],
                        gpos < n)

    # in-segment 1-based survivor rank: same tri-matmul in-row prefix +
    # Hillis-Steele row scan as _seg_pack_kernel
    mf = m.astype(jnp.float32)
    tri = (jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 0)
           <= jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 1)
           ).astype(jnp.float32)
    inrow = jax.lax.dot_general(mf, tri, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    rowcnt = jnp.broadcast_to(inrow[:, _LANES - 1:], (rows, _LANES))
    rowpfx = rowcnt
    s = 1
    while s < _SEG_ROWS:
        shifted = jnp.roll(rowpfx, s, axis=0)
        rowpfx = jnp.where(seg_row >= s, rowpfx + shifted, rowpfx)
        s *= 2
    rank = (rowpfx - rowcnt + inrow).astype(jnp.int32)   # 1-based, survivors

    # route EVERY survivor left by d = spos - (rank-1); no eligibility cap,
    # so distances stay monotone non-decreasing in position and the LSB->MSB
    # schedule stays collision-free for the full log2(_SEG) rounds
    d = jnp.where(m, spos - (rank - 1), 0)
    vals = x
    gidx = gpos
    b = 0
    while (1 << b) < _SEG:
        sft = 1 << b
        rd = _roll_flat(d, sft, _SEG_ROWS)
        rv = _roll_flat(vals, sft, _SEG_ROWS)
        ri = _roll_flat(gidx, sft, _SEG_ROWS)
        move_in = jnp.logical_and(((rd >> b) & 1) == 1, spos < _SEG - sft)
        my_move = ((d >> b) & 1) == 1
        vals = jnp.where(move_in, rv, vals)
        gidx = jnp.where(move_in, ri, gidx)
        d = jnp.where(move_in, rd - sft, jnp.where(my_move, 0, d))
        b += 1

    vals_ref[:] = vals
    idx_ref[:] = gidx
    # per-segment survivor totals: rowpfx at each segment's last row is the
    # inclusive count (identical across lanes) — full 128-lane row writes,
    # the reader takes lane 0
    r3 = rowpfx.reshape(rows // _SEG_ROWS, _SEG_ROWS, _LANES)
    cnt_ref[:] = r3[:, _SEG_ROWS - 1, :].astype(jnp.int32)


def _select_pack_payload(vals_st: Array, idx_st: Array, counts: Array,
                         keep: int):
    """Rank-bucket the per-segment compacted prefixes into the exact
    ``keep``-slot payload (ascending global index).  Segment-granular
    `packed_indices_from_mask`: find each payload rank's segment via a
    histogram over segment end-counts, then one sorted gather from the
    staging buffer.  Underfull masks (count < keep — only reachable on
    poisoned gradients; `topk_threshold` guarantees count >= keep otherwise)
    pad with value 0 / index 0, scatter-add identities."""
    nseg = counts.shape[0]
    v = vals_st.reshape(nseg, _SEG)
    ix = idx_st.reshape(nseg, _SEG)
    ends = jnp.cumsum(counts)                              # inclusive
    total = ends[nseg - 1]
    ranks = jnp.arange(1, keep + 1, dtype=jnp.int32)
    hist = jnp.zeros((keep + 1,), jnp.int32).at[
        jnp.minimum(ends, keep)].add(1, indices_are_sorted=True)
    seg_of = jnp.cumsum(hist)[:keep]
    valid = seg_of < nseg
    # clamp to the last segment (not 0) so flat_pos stays monotone and the
    # gather can keep its sorted hint
    seg_of = jnp.where(valid, seg_of, nseg - 1)
    # one gather of precomputed exclusive starts (the packed_indices_from_mask
    # trick), not two of ends and counts
    starts = (ends - counts).at[seg_of].get(indices_are_sorted=True,
                                            mode="promise_in_bounds")
    within = jnp.clip(ranks - starts - 1, 0, _SEG - 1)
    flat_pos = seg_of * _SEG + within
    gv = v.reshape(-1).at[flat_pos].get(indices_are_sorted=True,
                                        mode="promise_in_bounds")
    gi = ix.reshape(-1).at[flat_pos].get(indices_are_sorted=True,
                                         mode="promise_in_bounds")
    pvals = jnp.where(valid, gv, jnp.zeros((), vals_st.dtype))
    pidx = jnp.where(valid, gi, 0)
    return pvals, pidx, total


def fused_select_pack(flat: Array, t: Array, keep: int, *,
                      interpret: bool | None = None):
    """``(vals [keep], idx [keep], count)``: the wire-mode Top-K payload —
    coordinates with ``|flat| >= t`` by ascending index, their values in
    ``flat.dtype`` — in one Pallas pass plus an nseg-sized epilogue.

    Bitwise-identical to ``mask -> packed_indices_from_mask -> _sorted_gather``
    whenever the `topk_threshold` contract ``count(|flat| >= t) >= keep``
    holds (the one difference is deliberate: an underfull mask pads value 0 /
    index 0 instead of replicating ``flat[0]``).  ``count`` is the total
    survivor count (int32) for surplus accounting.
    """
    n = flat.shape[0]
    if n > _INT32_MAX:
        raise ValueError(f"fused_select_pack indexes int32; got n={n}")
    if interpret is None:
        interpret = _auto_interpret()
    rows_blk = _SEG_PER_BLOCK * _SEG_ROWS
    x2d, num_blocks = _pad_chunks(flat, fill=0.0, rows=rows_blk)
    nseg = x2d.shape[0] // _SEG_ROWS
    vma = _vma(flat)
    blk = pl.BlockSpec((rows_blk, _LANES), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    seg_out = pl.BlockSpec((_SEG_PER_BLOCK, _LANES), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        functools.partial(_select_pack_kernel, n),
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            blk,
        ],
        out_specs=[blk, blk, seg_out],
        out_shape=[
            compat.shape_dtype_struct(x2d.shape, flat.dtype, vma=vma),
            compat.shape_dtype_struct(x2d.shape, jnp.int32, vma=vma),
            compat.shape_dtype_struct((nseg, _LANES), jnp.int32, vma=vma),
        ],
        interpret=interpret,
    )(jnp.asarray(t).reshape(1, 1).astype(jnp.float32), x2d)
    return _select_pack_payload(outs[0], outs[1], outs[2][:, 0], int(keep))


def use_select_pack(n: int, keep: int) -> bool:
    """Whether the wire Top-K select+pack should take the fused kernel.
    Unlike the capped seg-pack (measured tie, off), full per-segment
    compaction has no overflow pathology, so it dispatches on the standard
    gates; the epilogue gather is O(keep)."""
    return _dispatch_to_pallas(n) and n <= _INT32_MAX and keep >= 1


# ---------------------------------------------------------------------------
# Fused stochastic quantisation
# ---------------------------------------------------------------------------


def _uniform_from_bits(shape) -> Array:
    # random bits come back as signed i32 on TPU — bitcast before shifting so
    # the shift is logical, then use the top 24 bits -> exact fp32 in [0, 1)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    # (Mosaic has no u32->f32 cast; the 24-bit value is sign-safe as i32.)
    top24 = pltpu.bitcast(bits >> 8, jnp.int32)
    return top24.astype(jnp.float32) * (1.0 / (1 << 24))


def _sign(x: Array) -> Array:
    # jnp.sign's Mosaic lowering emits an unsupported `pvary` when traced
    # under shard_map's varying-axes tracking; select-based sign lowers clean
    return jnp.where(x > 0, 1.0, 0.0) - jnp.where(x < 0, 1.0, 0.0)


def _qsgd_kernel(qstates: int, seed_ref, inv_norm_ref, x_ref, out_ref):
    pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
    x = x_ref[:]
    u = _uniform_from_bits(x.shape)
    levels = jnp.floor(jnp.abs(x) * inv_norm_ref[0, 0] * qstates + u)
    out_ref[:] = (_sign(x) * levels).astype(jnp.int16)


def _terngrad_kernel(seed_ref, inv_max_ref, x_ref, out_ref):
    pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
    x = x_ref[:]
    u = _uniform_from_bits(x.shape)
    keep = (u < jnp.abs(x) * inv_max_ref[0, 0]).astype(jnp.float32)
    out_ref[:] = (_sign(x) * keep).astype(jnp.int8)


def _run_quant(kernel, out_dtype, flat: Array, inv_scale: Array, seed: Array,
               interpret: bool) -> Array:
    n = flat.shape[0]
    x2d, num_chunks = _pad_chunks(flat.astype(jnp.float32), fill=0.0)
    out = pl.pallas_call(
        kernel,
        grid=(num_chunks,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct(x2d.shape, out_dtype, vma=_vma(flat)),
        # TPU-semantics interpreter: the stock HLO interpreter has no
        # prng_seed/prng_random_bits (NB: its PRNG is a zero stub — dither
        # u == 0 under interpretation; see tests/test_kernels.py)
        interpret=compat.pallas_interpret_params() if interpret else False,
    )(
        seed.reshape(1, 1).astype(jnp.int32),
        inv_scale.reshape(1, 1).astype(jnp.float32),
        x2d,
    )
    return out.reshape(-1)[:n]


def _seed_from_key(key: Array) -> Array:
    return jax.random.bits(key, dtype=jnp.uint32).astype(jnp.int32)


def qsgd_quantize(flat: Array, key: Array, *, qstates: int = 255,
                  interpret: bool | None = None) -> Tuple[Array, Array]:
    """Fused QSGD levels: ``(int16 levels in [-s, s], fp32 scale)``.

    Same estimator as :func:`compressors.qsgd_levels` (`core.py:207-213`),
    dither drawn from the TPU hardware PRNG seeded off ``key``.
    """
    if interpret is None:
        interpret = _auto_interpret() and compat.HAS_TPU_INTERPRET
    norm = jnp.linalg.norm(flat.astype(jnp.float32))
    inv = jnp.where(norm > 0, 1.0 / jnp.where(norm > 0, norm, 1.0), 0.0)
    levels = _run_quant(
        functools.partial(_qsgd_kernel, qstates), jnp.int16,
        flat, inv, _seed_from_key(key), interpret,
    )
    scale = jnp.where(norm > 0, norm, 0.0) / qstates
    return levels, scale


def terngrad_quantize(flat: Array, key: Array, *,
                      interpret: bool | None = None) -> Tuple[Array, Array]:
    """Fused TernGrad levels: ``(int8 levels in {-1,0,1}, fp32 scale)``
    (`core.py:200-206`), dither from the TPU hardware PRNG."""
    if interpret is None:
        interpret = _auto_interpret() and compat.HAS_TPU_INTERPRET
    gmax = jnp.max(jnp.abs(flat.astype(jnp.float32)))
    inv = jnp.where(gmax > 0, 1.0 / jnp.where(gmax > 0, gmax, 1.0), 0.0)
    levels = _run_quant(
        _terngrad_kernel, jnp.int8, flat, inv, _seed_from_key(key), interpret,
    )
    return levels, gmax


def terngrad_quantize_prescaled(scaled: Array, key: Array, *,
                                interpret: bool | None = None) -> Array:
    """TernGrad levels for an already chunk-normalised input (``|x| <= 1``,
    unit scale) — the chunked-scale path's quantisation pass."""
    if interpret is None:
        interpret = _auto_interpret() and compat.HAS_TPU_INTERPRET
    return _run_quant(
        _terngrad_kernel, jnp.int8, scaled,
        jnp.asarray(1.0, jnp.float32), _seed_from_key(key), interpret,
    )


def use_quant_kernels(n: int) -> bool:
    """Whether the fused quantizer kernels should serve this tensor.

    Forced off-TPU the PRNG kernels need the TPU-semantics interpreter
    (the stock HLO interpreter's PRNG is a zero stub) — without it the
    jnp paths serve instead of crashing the lowering."""
    if not _dispatch_to_pallas(n):
        return False
    return not _auto_interpret() or compat.HAS_TPU_INTERPRET


# ---------------------------------------------------------------------------
# Fused quantize+pack (TernGrad 2-bit / QSGD mag + sign-bitmap wire bytes)
# ---------------------------------------------------------------------------
#
# The quantizer kernels above emit integer LEVELS; XLA then runs
# `wire.pack_ternary` / `wire.pack_bits` as separate shift/sum passes over
# the levels before anything hits the wire.  These kernels emit the wire
# BYTES directly.  Bit-packing on the VPU has no sub-word shuffles: packing
# is one matmul against a 0/1-weighted selector — codes [R, 128] times
# packmat [128, 128/g] where column l//g carries weight base^(l%g) — and a
# row-major reshape of the [R, 128/g] byte panel back to 128-lane rows.
# Operands are small exact integers (codes <= 2, weights <= 128, bytes <=
# 255 < 2^24), so even the MXU's bf16 default precision is exact, like the
# 0/1 count matmuls in the pack kernels.  Byte order matches the XLA
# packers bitwise: byte j of the flat output packs elements g*j .. g*j+g-1
# little-endian, which is exactly row-major order of the reshaped panel.

# 256-row element blocks: ternary bytes come out [64, 128] and sign-bitmap
# bytes [32, 128] — both at or above the uint8 (32, 128) min tile
_QPACK_ROWS = 256


def _bytepack(v: Array, g: int) -> Array:
    """[R, 128] f32 small-int codes -> [R * 128 // (g * 128), 128] f32 bytes
    packing ``g`` consecutive lanes per byte, little-endian (weight
    ``(2^(8/g))^(l%g)`` at column ``l//g``)."""
    rows = v.shape[0]
    cols = _LANES // g
    li = jax.lax.broadcasted_iota(jnp.int32, (_LANES, cols), 0)
    ci = jax.lax.broadcasted_iota(jnp.int32, (_LANES, cols), 1)
    pm = jnp.where(li // g == ci, (1 << ((li % g) * (8 // g))), 0
                   ).astype(jnp.float32)
    b = jax.lax.dot(v, pm, preferred_element_type=jnp.float32)  # [R, cols]
    # row-major reshape to full 128-lane byte rows; flat order == wire order
    return b.reshape(rows * cols // _LANES, _LANES)


def _pack2b_kernel(levels_ref, out_ref):
    c = levels_ref[:].astype(jnp.float32) + 1.0            # {0,1,2}
    out_ref[:] = _bytepack(c, 4).astype(jnp.int32).astype(jnp.uint8)


def _qsgd_pack_levels_kernel(levels_ref, mag_ref, sign_ref):
    lv = levels_ref[:].astype(jnp.int32)
    mag_ref[:] = jnp.abs(lv).astype(jnp.uint8)
    neg = (lv < 0).astype(jnp.float32)
    sign_ref[:] = _bytepack(neg, 8).astype(jnp.int32).astype(jnp.uint8)


def _pack_bytes_call(kernel, levels: Array, out_divs, out_dtypes,
                     interpret: bool):
    """Shared pallas_call plumbing for the byte packers: grid over
    _QPACK_ROWS-row level chunks, one output per (rows-divisor, dtype)."""
    n = levels.shape[0]
    x2d, num_chunks = _pad_chunks(levels, fill=0, rows=_QPACK_ROWS)
    vma = _vma(levels)
    outs = pl.pallas_call(
        kernel,
        grid=(num_chunks,),
        in_specs=[pl.BlockSpec((_QPACK_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((_QPACK_ROWS // d, _LANES), lambda i, d=d: (i, 0),
                                memory_space=pltpu.VMEM) for d in out_divs],
        out_shape=[compat.shape_dtype_struct((x2d.shape[0] // d, _LANES), dt,
                                             vma=vma)
                   for d, dt in zip(out_divs, out_dtypes)],
        interpret=interpret,
    )(x2d)
    return outs


def pack_ternary_pallas(levels: Array, *, interpret: bool | None = None) -> Array:
    """``uint8[ceil(n/4)]`` — bitwise-identical to :func:`wire.pack_ternary`
    (the XLA packer zero-pads levels to a multiple of 4; chunk padding here
    is level 0 -> code 1, the same byte content)."""
    n = levels.shape[0]
    if interpret is None:
        interpret = _auto_interpret()
    (out,) = _pack_bytes_call(_pack2b_kernel, levels.astype(jnp.int8),
                              (4,), (jnp.uint8,), interpret)
    return out.reshape(-1)[: -(-n // 4)]


def qsgd_pack_pallas(levels: Array, *, interpret: bool | None = None):
    """``(uint8 mags [n], uint8 signs [ceil(n/8)])`` — bitwise-identical to
    :func:`wire.qsgd_wire_pack` for ``qstates <= 255`` given the same int16
    levels."""
    n = levels.shape[0]
    if interpret is None:
        interpret = _auto_interpret()
    mags, signs = _pack_bytes_call(
        _qsgd_pack_levels_kernel, levels.astype(jnp.int16),
        (1, 8), (jnp.uint8, jnp.uint8), interpret)
    return mags.reshape(-1)[:n], signs.reshape(-1)[: -(-n // 8)]


def _terngrad_pack_kernel(seed_ref, inv_max_ref, x_ref, out_ref):
    pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
    x = x_ref[:]
    u = _uniform_from_bits(x.shape)
    keep = (u < jnp.abs(x) * inv_max_ref[0, 0]).astype(jnp.float32)
    codes = _sign(x) * keep + 1.0                          # {0,1,2}
    out_ref[:] = _bytepack(codes, 4).astype(jnp.int32).astype(jnp.uint8)


def _qsgd_pack_kernel(qstates: int, seed_ref, inv_norm_ref, x_ref,
                      mag_ref, sign_ref):
    pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
    x = x_ref[:]
    u = _uniform_from_bits(x.shape)
    lv = jnp.floor(jnp.abs(x) * inv_norm_ref[0, 0] * qstates + u)
    mag_ref[:] = lv.astype(jnp.int32).astype(jnp.uint8)
    # sign bit set iff the signed level is negative: x < 0 AND lv > 0
    # (jnp.sign(x) * 0 == +-0, never < 0 — matches qsgd_wire_pack)
    neg = jnp.logical_and(x < 0, lv > 0).astype(jnp.float32)
    sign_ref[:] = _bytepack(neg, 8).astype(jnp.int32).astype(jnp.uint8)


def _run_quant_pack(kernel, flat: Array, inv_scale: Array, seed: Array,
                    out_divs, interpret: bool):
    n = flat.shape[0]
    x2d, num_chunks = _pad_chunks(flat.astype(jnp.float32), fill=0.0,
                                  rows=_QPACK_ROWS)
    vma = _vma(flat)
    outs = pl.pallas_call(
        kernel,
        grid=(num_chunks,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((_QPACK_ROWS, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[pl.BlockSpec((_QPACK_ROWS // d, _LANES), lambda i, d=d: (i, 0),
                                memory_space=pltpu.VMEM) for d in out_divs],
        out_shape=[compat.shape_dtype_struct((x2d.shape[0] // d, _LANES),
                                             jnp.uint8, vma=vma)
                   for d in out_divs],
        # hardware PRNG — TPU-semantics interpreter required off-TPU
        interpret=compat.pallas_interpret_params() if interpret else False,
    )(
        seed.reshape(1, 1).astype(jnp.int32),
        inv_scale.reshape(1, 1).astype(jnp.float32),
        x2d,
    )
    return outs


def terngrad_pack(flat: Array, key: Array, *,
                  interpret: bool | None = None) -> Tuple[Array, Array]:
    """Fused TernGrad quantize+pack: ``(uint8 wire bytes [ceil(n/4)], fp32
    scale)`` — draw, dither, and 2-bit-pack in ONE pass instead of the
    levels pass + XLA `pack_ternary` pass.  Same hardware-PRNG stream caveat
    as :func:`terngrad_quantize` (unbiased, not bitwise with `jax.random`);
    chunk padding packs as code 1 exactly like the XLA packer's zero-pad."""
    if interpret is None:
        interpret = _auto_interpret() and compat.HAS_TPU_INTERPRET
    gmax = jnp.max(jnp.abs(flat.astype(jnp.float32)))
    inv = jnp.where(gmax > 0, 1.0 / jnp.where(gmax > 0, gmax, 1.0), 0.0)
    (packed,) = _run_quant_pack(
        _terngrad_pack_kernel, flat, inv, _seed_from_key(key), (4,), interpret)
    n = flat.shape[0]
    return packed.reshape(-1)[: -(-n // 4)], gmax


def terngrad_pack_prescaled(scaled: Array, key: Array, *,
                            interpret: bool | None = None) -> Array:
    """Quantize+pack for an already chunk-normalised input (``|x| <= 1``) —
    the chunked-scale TernGrad path's fused second pass."""
    if interpret is None:
        interpret = _auto_interpret() and compat.HAS_TPU_INTERPRET
    (packed,) = _run_quant_pack(
        _terngrad_pack_kernel, scaled, jnp.asarray(1.0, jnp.float32),
        _seed_from_key(key), (4,), interpret)
    n = scaled.shape[0]
    return packed.reshape(-1)[: -(-n // 4)]


def qsgd_pack(flat: Array, key: Array, *, qstates: int = 255,
              interpret: bool | None = None):
    """Fused QSGD quantize+pack for the uint8 wire layout (``qstates <=
    255``): ``(uint8 mags [n], uint8 sign bitmap [ceil(n/8)], fp32 scale)``
    in one pass — replacing levels + `qsgd_wire_pack`'s abs/compare/pack_bits
    chain.  Hardware-PRNG stream caveat as :func:`qsgd_quantize`."""
    if not 0 < qstates <= 255:
        raise ValueError(f"qsgd_pack packs uint8 magnitudes; qstates={qstates}")
    if interpret is None:
        interpret = _auto_interpret() and compat.HAS_TPU_INTERPRET
    norm = jnp.linalg.norm(flat.astype(jnp.float32))
    inv = jnp.where(norm > 0, 1.0 / jnp.where(norm > 0, norm, 1.0), 0.0)
    mags, signs = _run_quant_pack(
        functools.partial(_qsgd_pack_kernel, qstates), flat, inv,
        _seed_from_key(key), (1, 8), interpret)
    n = flat.shape[0]
    scale = jnp.where(norm > 0, norm, 0.0) / qstates
    return mags.reshape(-1)[:n], signs.reshape(-1)[: -(-n // 8)], scale


def use_quant_pack(n: int) -> bool:
    """Whether the fused quantize+pack kernels should serve this tensor.
    Off-TPU (including ``force``) they need the TPU-semantics interpreter —
    the stock interpreter's PRNG stub would silently zero the dither."""
    if not _dispatch_to_pallas(n):
        return False
    return jax.default_backend() == "tpu" or compat.HAS_TPU_INTERPRET


# ---------------------------------------------------------------------------
# Hardware-PRNG uniforms
# ---------------------------------------------------------------------------


def _uniform_kernel(seed_ref, out_ref):
    pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
    out_ref[:] = _uniform_from_bits(out_ref.shape)


# PRNG seeding has per-grid-step cost — use fat blocks (512 KB) so the fill
# is bandwidth-bound, not step-bound
_UNIFORM_ROWS = 1024


def _uniform_pallas(seed: Array, n: int, interpret: bool = False) -> Array:
    chunk = _UNIFORM_ROWS * _LANES
    padded_n = -(-n // chunk) * chunk
    out = pl.pallas_call(
        _uniform_kernel,
        grid=(padded_n // chunk,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((_UNIFORM_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct((padded_n // _LANES, _LANES), jnp.float32,
                                       vma=_vma(seed)),
        interpret=compat.pallas_interpret_params() if interpret else False,
    )(seed.reshape(1, 1).astype(jnp.int32))
    return out.reshape(-1)[:n]


def uniform(key: Array, n: int) -> Array:
    """Uniform [0, 1) draws; hardware PRNG on TPU at scale (threefry is
    ~10x slower there for multi-million element draws), ``jax.random``
    elsewhere.  Deterministic in ``key`` on both paths — a replicated key
    yields identical draws on every worker (the shared-seed contract
    Random-K masks rely on) — but the two paths draw different streams."""
    if _dispatch_to_pallas(n):
        return _uniform_pallas(_seed_from_key(key), n)
    return jax.random.uniform(key, (n,))


# ---------------------------------------------------------------------------
# Fused bucket route (sharded transport per-destination bucket build)
# ---------------------------------------------------------------------------
#
# The sharded transport's route phase turns the ascending (value, index)
# payload into per-destination fixed-capacity buckets.  The XLA build is a
# pair of [W*cap+1]-slot scatters (value add + index set with a dump slot).
# Because the indices are ascending, each destination's accepted elements
# are a CONTIGUOUS window [starts[w], starts[w] + min(count, cap)) of the
# payload — so the scatter is really W windowed copies.  The kernel grids
# over destinations, DMAs each window from HBM at its dynamic start offset,
# masks the tail, and writes full bucket rows: zero value / `shard_n` guard
# index on empty slots, identical bit-for-bit to the scatter build, and
# rows stay monotone (window order = payload order), preserving the
# owner-side sorted-scatter hints.

# per-destination window bound: 2 value+index scratch windows of cap_p
# elements must sit in VMEM alongside the output block
_ROUTE_MAX_CAPP = 1 << 15


def _bucket_route_kernel(cap: int, cap_p: int, shard_n: int,
                         starts_ref, counts_ref, vals_ref, idx_ref,
                         bv_ref, bi_ref, scratch_v, scratch_i, sem_v, sem_i):
    w = pl.program_id(0)
    start = starts_ref[w]
    cnt = jnp.minimum(counts_ref[w], cap)
    # dynamic element-offset DMA: the payload is padded by cap_p so the last
    # destination's window read stays in bounds whatever its start
    cv = pltpu.make_async_copy(vals_ref.at[pl.ds(start, cap_p)], scratch_v,
                               sem_v)
    ci = pltpu.make_async_copy(idx_ref.at[pl.ds(start, cap_p)], scratch_i,
                               sem_i)
    cv.start()
    ci.start()
    cv.wait()
    ci.wait()
    r2 = cap_p // _LANES
    v = scratch_v[:].reshape(r2, _LANES)
    ix = scratch_i[:].reshape(r2, _LANES)
    pos = (jax.lax.broadcasted_iota(jnp.int32, (r2, _LANES), 0) * _LANES
           + jax.lax.broadcasted_iota(jnp.int32, (r2, _LANES), 1))
    take = pos < cnt
    bv_ref[:] = jnp.where(take, v, jnp.zeros((), v.dtype))
    # bucket-local index; empty slots carry the shard_n guard row the owner
    # reduce scatters into
    bi_ref[:] = jnp.where(take, ix - w * shard_n, shard_n)


def fused_bucket_route(vals: Array, idx: Array, dest: Array, world: int,
                       cap: int, shard_n: int, *,
                       interpret: bool | None = None):
    """``(bvals [W, cap], bidx [W, cap])`` — the sharded transport's
    per-destination buckets, built as W windowed copies instead of a
    [W*cap+1] scatter pair.  ``dest`` is the per-element destination (dump
    value ``world`` for invalid tail slots), ascending by the payload's
    monotone-index contract."""
    k = vals.shape[0]
    if interpret is None:
        interpret = _auto_interpret()
    cap_p = -(-cap // _LANES) * _LANES
    r2 = cap_p // _LANES
    # per-destination totals and exclusive starts (tiny: W+1 buckets); the
    # dump bucket keeps invalid tail slots out of every window
    counts_all = jnp.zeros((world + 1,), jnp.int32).at[dest].add(
        1, indices_are_sorted=True, mode="promise_in_bounds")
    starts = (jnp.cumsum(counts_all) - counts_all)[:world].astype(jnp.int32)
    counts = counts_all[:world]
    vpad = jnp.concatenate([vals, jnp.zeros((cap_p,), vals.dtype)])
    ipad = jnp.concatenate([idx, jnp.zeros((cap_p,), jnp.int32)])
    vma = _vma(vals)
    outs = pl.pallas_call(
        functools.partial(_bucket_route_kernel, int(cap), cap_p, int(shard_n)),
        grid=(world,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((r2, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r2, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            compat.shape_dtype_struct((world * r2, _LANES), vals.dtype, vma=vma),
            compat.shape_dtype_struct((world * r2, _LANES), jnp.int32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((cap_p,), vals.dtype),
            pltpu.VMEM((cap_p,), jnp.int32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(starts, counts, vpad, ipad)
    bvals = outs[0].reshape(world, cap_p)[:, :cap]
    bidx = outs[1].reshape(world, cap_p)[:, :cap]
    return bvals, bidx


def use_bucket_route(k: int, world: int, cap: int) -> bool:
    """Whether the sharded route phase should take the fused window kernel.
    Element-granular payloads only (the blocky Block-Top-K row layout keeps
    the XLA scatter); the window bound keeps both scratch copies in VMEM."""
    cap_p = -(-cap // _LANES) * _LANES
    return (_dispatch_to_pallas(k) and k <= _INT32_MAX and world >= 2
            and cap_p <= _ROUTE_MAX_CAPP)
