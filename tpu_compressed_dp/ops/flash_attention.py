"""Tiled causal flash attention (forward + backward) in Pallas.

The single-block attention path of :mod:`tpu_compressed_dp.ops.ring_attention`
— the unfused XLA chain materialises the [T, T] probability matrix in HBM
(~400 MB fp32 per layer pass at T=1024, 16x that at 4096), the dominant
non-matmul HBM traffic of the LM step (VERDICT r3 weak #5).  This kernel
streams K/V blocks through VMEM with the standard online-softmax recurrence,
so only O(T·D) leaves the chip per pass.

Built in-repo rather than taken from jax.experimental's ops because the sync
engines run inside ``shard_map`` with replication checking on: every
``pallas_call`` out_shape must carry the varying-mesh-axes of its inputs
(``_vma`` plumbing, like ops/kernels.py), which stock kernels do not thread.

Backward follows the flash-attention recipe: save (o, lse) from forward,
precompute ``delta = rowsum(do * o)``, then one kernel accumulates dq over
K/V blocks and a second accumulates (dk, dv) over Q blocks — each recomputes
its score block in VMEM instead of reading a saved [T, T].

Mosaic-shaped storage: per-row scalars (lse, delta) cannot leave a kernel as
``[1, block_q]`` blocks (block last-two-dims must be 8/128-divisible), so
they ride the LANE dimension of the tensors that already flow: the forward
packs ``lse`` into lane ``d`` of the (lane-padded) output block, and the
backward wrapper packs ``delta``/``lse`` into lanes ``d``/``d+1`` of the
incoming cotangent.  At the LM head_dim of 64 the pad lanes exist anyway —
the stats travel free.

Layout: [B, H, T, D]; causal only (the framework's LM decoders); D padded to
the 128-lane tile in the wrapper (zero columns are inert through qk/pv and
sliced off).  Matmuls run on the MXU with fp32 accumulation
(``preferred_element_type``); bf16 inputs keep bf16 operands — the same
accumulation discipline as XLA's own attention lowering.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

from tpu_compressed_dp import compat

try:  # pragma: no cover - CPU-only builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False

Array = jax.Array

__all__ = ["flash_causal_attention"]

_NEG_INF = -1e30


def _vma(x: Array):
    return getattr(compat.typeof(x), "vma", frozenset())


def _causal_pos(qi, kj, blk_q, blk_k):
    q_pos = qi * blk_q + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0)
    k_pos = kj * blk_k + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 1)
    return q_pos >= k_pos


def _fwd_kernel(scale: float, blk_q: int, blk_k: int, n_k: int, d: int,
                q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
    qi = pl.program_id(1)
    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    q = q_ref[0]                                     # [blk_q, d_pad]

    def body(kj, _):
        k = k_ref[0, pl.ds(kj * blk_k, blk_k)]       # [blk_k, d_pad]
        v = v_ref[0, pl.ds(kj * blk_k, blk_k)]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [blk_q, blk_k]
        s = jnp.where(_causal_pos(qi, kj, blk_q, blk_k), s, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                       # masked lanes -> 0
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        return 0

    # causal: q block qi attends kv blocks 0..ceil((qi+1)*blk_q / blk_k)-1;
    # trailing blocks are fully masked — skipped entirely
    n_live = jnp.minimum(((qi + 1) * blk_q + blk_k - 1) // blk_k, n_k)
    jax.lax.fori_loop(0, n_live, body, 0)
    l = l_ref[:]
    o = acc_ref[:] / l                               # [blk_q, d_pad]
    lse = m_ref[:] + jnp.log(l)                      # [blk_q, 1]
    d_store = o_ref.shape[-1]
    out = jnp.concatenate(
        [o[:, :d], lse] + ([jnp.zeros((blk_q, d_store - d - 1), jnp.float32)]
                           if d_store - d - 1 else []), axis=1)
    o_ref[0] = out.astype(o_ref.dtype)


def _dq_kernel(scale: float, blk_q: int, blk_k: int, n_k: int, d: int,
               q_ref, k_ref, v_ref, dop_ref, dq_ref, acc_ref):
    qi = pl.program_id(1)
    acc_ref[:] = jnp.zeros_like(acc_ref)
    q = q_ref[0]
    d_pad = q.shape[-1]
    dop = dop_ref[0]                                 # packed: do | delta | lse
    # re-pad do to d_pad lanes so contractions align with the padded k/v
    # (zero lanes are inert through every product)
    do = jnp.concatenate(
        [dop[:, :d], jnp.zeros((blk_q, d_pad - d), dop.dtype)],
        axis=1).astype(jnp.float32) if d_pad > d else dop[:, :d].astype(jnp.float32)
    delta = dop[:, d:d + 1].astype(jnp.float32)
    lse = dop[:, d + 1:d + 2].astype(jnp.float32)

    def body(kj, _):
        k = k_ref[0, pl.ds(kj * blk_k, blk_k)]
        v = v_ref[0, pl.ds(kj * blk_k, blk_k)]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.where(_causal_pos(qi, kj, blk_q, blk_k),
                      jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return 0

    n_live = jnp.minimum(((qi + 1) * blk_q + blk_k - 1) // blk_k, n_k)
    jax.lax.fori_loop(0, n_live, body, 0)
    dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_block_math(scale, blk_q, blk_k, d, kj, qi, q, dop, k, v,
                    dk_acc, dv_acc):
    """One (q block) x (kv block) accumulation of dk/dv — shared by the
    VMEM-resident and the HBM-streamed dkv kernels."""
    d_pad = k.shape[-1]
    do = jnp.concatenate(
        [dop[:, :d], jnp.zeros((blk_q, d_pad - d), dop.dtype)],
        axis=1).astype(jnp.float32) if d_pad > d else dop[:, :d].astype(jnp.float32)
    delta = dop[:, d:d + 1].astype(jnp.float32)
    lse = dop[:, d + 1:d + 2].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    p = jnp.where(_causal_pos(qi, kj, blk_q, blk_k),
                  jnp.exp(s - lse), 0.0)
    dv_acc[:] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    dk_acc[:] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dkv_kernel(scale: float, blk_q: int, blk_k: int, n_q: int, d: int,
                q_ref, k_ref, v_ref, dop_ref, dk_ref, dv_ref,
                dk_acc, dv_acc):
    kj = pl.program_id(1)
    dk_acc[:] = jnp.zeros_like(dk_acc)
    dv_acc[:] = jnp.zeros_like(dv_acc)
    k = k_ref[0]                                     # [blk_k, d_pad]
    v = v_ref[0]

    def body(qi, _):
        q = q_ref[0, pl.ds(qi * blk_q, blk_q)]
        dop = dop_ref[0, pl.ds(qi * blk_q, blk_q)]
        _dkv_block_math(scale, blk_q, blk_k, d, kj, qi, q, dop, k, v,
                        dk_acc, dv_acc)
        return 0

    # q blocks qi >= kj*blk_k // blk_q can contain positions >= this kv block
    first = kj * blk_k // blk_q
    jax.lax.fori_loop(first, n_q, body, 0)
    dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
    dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dkv_kernel_streamed(scale: float, blk_q: int, blk_k: int, n_q: int,
                         d: int, q_hbm, k_ref, v_ref, dop_hbm,
                         dk_ref, dv_ref, dk_acc, dv_acc,
                         q_buf, dop_buf, q_sem, dop_sem):
    """dkv with the full-T operands (Q and the packed cotangent) left in
    HBM and double-buffered per q-block via explicit DMA.  At T=8192/d=128
    the VMEM-resident form's q (bf16, 2 MB) + packed f32 cotangent (8 MB),
    Mosaic-double-buffered, blow the 16 MB scoped-vmem ceiling (measured
    17.5 MB, r5); streaming keeps residency at 2 q-blocks + 2 dop-blocks
    (~1 MB) regardless of T, so long single-chip sequences are bounded by
    HBM, not scoped VMEM."""
    bh = pl.program_id(0)
    kj = pl.program_id(1)
    dk_acc[:] = jnp.zeros_like(dk_acc)
    dv_acc[:] = jnp.zeros_like(dv_acc)
    k = k_ref[0]
    v = v_ref[0]

    def q_dma(qi, slot):
        return pltpu.make_async_copy(
            q_hbm.at[bh, pl.ds(qi * blk_q, blk_q)], q_buf.at[slot],
            q_sem.at[slot])

    def dop_dma(qi, slot):
        return pltpu.make_async_copy(
            dop_hbm.at[bh, pl.ds(qi * blk_q, blk_q)], dop_buf.at[slot],
            dop_sem.at[slot])

    first = kj * blk_k // blk_q
    q_dma(first, jax.lax.rem(first, 2)).start()
    dop_dma(first, jax.lax.rem(first, 2)).start()

    def body(qi, _):
        slot = jax.lax.rem(qi, 2)
        nxt = jax.lax.rem(qi + 1, 2)

        @pl.when(qi + 1 < n_q)
        def _():
            q_dma(qi + 1, nxt).start()
            dop_dma(qi + 1, nxt).start()

        q_dma(qi, slot).wait()
        dop_dma(qi, slot).wait()
        _dkv_block_math(scale, blk_q, blk_k, d, kj, qi, q_buf[slot],
                        dop_buf[slot], k, v, dk_acc, dv_acc)
        return 0

    jax.lax.fori_loop(first, n_q, body, 0)
    dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
    dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _pick_blocks(t: int) -> tuple:
    # smaller streamed blocks at long T: the full-T resident operands (K/V in
    # the dq kernel; Q + packed cotangent in dkv) grow with T and the dkv
    # kernel sits within ~1.5 MB of the 16 MB scoped-vmem ceiling at T=8192 —
    # halving the block buffers buys that margin (r5; grid-step overhead is
    # amortised by the larger per-step loop trip count at these T)
    bq = min(256 if t >= 8192 else 512, t)
    while t % bq:
        bq //= 2
    return bq, bq


def _d_store(d: int) -> int:
    d_pad = d + (-d) % 128
    # lse/delta ride lanes d, d+1 — need two spare lanes past the data
    return d_pad if d_pad - d >= 2 else d_pad + 128


def _pad_lanes(x: Array, to: int) -> Array:
    return jnp.pad(x, ((0, 0),) * (x.ndim - 1) + ((0, to - x.shape[-1]),))


def _fwd(q, k, v, scale, blk, interpret, d):
    """q/k/v pre-padded to d_pad lanes; returns packed o (lse at lane d)."""
    b, h, t, d_pad = q.shape
    bq, bk = blk
    vma = _vma(q)
    qs, ks, vs = (x.reshape(b * h, t, d_pad) for x in (q, k, v))
    ds = _d_store(d)
    kv_spec = pl.BlockSpec((1, t, d_pad), lambda bh, qi: (bh, 0, 0),
                           memory_space=pltpu.VMEM)
    o_packed = pl.pallas_call(
        functools.partial(_fwd_kernel, scale, bq, bk, t // bk, d),
        grid=(b * h, t // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d_pad), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            kv_spec, kv_spec,
        ],
        out_specs=pl.BlockSpec((1, bq, ds), lambda bh, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct((b * h, t, ds), jnp.float32, vma=vma),
        scratch_shapes=[
            pltpu.VMEM((bq, d_pad), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qs, ks, vs)
    return o_packed.reshape(b, h, t, ds)


def _bwd(q, k, v, dop, scale, blk, interpret, out_dtype, d):
    b, h, t, d_pad = q.shape
    bq, bk = blk
    vma = _vma(q)
    ds = dop.shape[-1]
    qs, ks, vs = (x.reshape(b * h, t, d_pad) for x in (q, k, v))
    dops = dop.reshape(b * h, t, ds)
    full = lambda w: pl.BlockSpec((1, t, w), lambda bh, i: (bh, 0, 0),
                                  memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale, bq, bk, t // bk, d),
        grid=(b * h, t // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d_pad), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            full(d_pad), full(d_pad),
            pl.BlockSpec((1, bq, ds), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d_pad), lambda bh, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct((b * h, t, d_pad), out_dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((bq, d_pad), jnp.float32)],
        interpret=interpret,
    )(qs, ks, vs, dops)
    kv_block = pl.BlockSpec((1, bk, d_pad), lambda bh, kj: (bh, kj, 0),
                            memory_space=pltpu.VMEM)
    # Streamed dkv off-interpret: Q and the packed cotangent stay in HBM,
    # the kernel DMAs per-q-block slices itself (see _dkv_kernel_streamed).
    # Interpret mode (CPU tests) keeps the VMEM-resident form — identical
    # math via _dkv_block_math — unless TPU_CDP_FORCE_STREAMED_DKV=1, which
    # runs the DMA/double-buffer machinery under the Pallas interpreter so
    # the streamed path has off-chip parity coverage (ADVICE r5;
    # tests/test_flash_attention.py::test_streamed_dkv_matches_resident).
    if interpret and os.environ.get("TPU_CDP_FORCE_STREAMED_DKV") != "1":
        dkv_kernel = functools.partial(_dkv_kernel, scale, bq, bk, t // bq, d)
        qd_specs = [full(d_pad), kv_block, kv_block, full(ds)]
        extra_scratch = []
    else:
        dkv_kernel = functools.partial(
            _dkv_kernel_streamed, scale, bq, bk, t // bq, d)
        qd_specs = [pl.BlockSpec(memory_space=pltpu.ANY), kv_block, kv_block,
                    pl.BlockSpec(memory_space=pltpu.ANY)]
        extra_scratch = [
            pltpu.VMEM((2, bq, d_pad), qs.dtype),
            pltpu.VMEM((2, bq, ds), dops.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, t // bk),
        in_specs=[qd_specs[0], qd_specs[1], qd_specs[2], qd_specs[3]],
        out_specs=[
            pl.BlockSpec((1, bk, d_pad), lambda bh, kj: (bh, kj, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d_pad), lambda bh, kj: (bh, kj, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            compat.shape_dtype_struct((b * h, t, d_pad), out_dtype, vma=vma),
            compat.shape_dtype_struct((b * h, t, d_pad), out_dtype, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d_pad), jnp.float32),
            pltpu.VMEM((bk, d_pad), jnp.float32),
        ] + extra_scratch,
        interpret=interpret,
    )(qs, ks, vs, dops)
    rs = lambda x: x.reshape(b, h, t, d_pad)
    return rs(dq), rs(dk), rs(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_causal_attention(q: Array, k: Array, v: Array,
                           scale: Optional[float] = None,
                           interpret: bool = False) -> Array:
    """Exact causal attention, flash-tiled; [B, H, T, D] (equal q/kv heads —
    GQA repeat happens in the caller, ring_attention)."""
    o, _ = _fa_fwd(q, k, v, scale, interpret)
    return o


def _fa_fwd(q, k, v, scale, interpret):
    b, h, t, d = q.shape
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    d_pad = d + (-d) % 128
    qp, kp, vp = (_pad_lanes(x, d_pad) for x in (q, k, v))
    o_packed = _fwd(qp, kp, vp, s, _pick_blocks(t), interpret, d)
    o = o_packed[..., :d].astype(q.dtype)
    lse = o_packed[..., d]
    return o, (q, k, v, o, lse)


def _fa_bwd(scale, interpret, res, do):
    q, k, v, o, lse = res
    b, h, t, d = q.shape
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    d_pad = d + (-d) % 128
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    ds = _d_store(d)
    dop = _pad_lanes(
        jnp.concatenate([do.astype(jnp.float32), delta[..., None],
                         lse[..., None]], axis=-1), ds)
    qp, kp, vp = (_pad_lanes(x, d_pad) for x in (q, k, v))
    dq, dk, dv = _bwd(qp, kp, vp, dop, s, _pick_blocks(t), interpret,
                      q.dtype, d)
    return dq[..., :d], dk[..., :d], dv[..., :d]


flash_causal_attention.defvjp(_fa_fwd, _fa_bwd)
