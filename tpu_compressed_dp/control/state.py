"""The controller's cross-step carry: one more ``TrainState`` occupant.

:class:`ControlState` follows :class:`~tpu_compressed_dp.train.guard.GuardState`
exactly: replicated device scalars (every worker consumes the identical
psum'd metrics, so every worker would compute the identical state), threaded
through the jitted step untouched (the step's ``state_spec`` gives it the
replicated ``P()`` spec), serialised to Orbax as a plain dict
(``utils/checkpoint.py``), and therefore bitwise replayable through
crash/resume.  The jitted step never reads or writes it — the HOST controller
(:mod:`tpu_compressed_dp.control.controller`) replaces it between steps, which
is exactly when rung switches are legal anyway.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

from tpu_compressed_dp.control.config import ControlConfig

Array = jax.Array

__all__ = ["ControlState", "init_control_state", "control_to_dict",
           "control_from_dict"]


@struct.dataclass
class ControlState:
    """Everything a resumed run needs to continue the decision sequence
    bitwise: the ladder position, the open window's start on the
    applied-update clock, its accumulators, and the decision-log cursor."""

    rung: Array           # i32 current ladder index (0 = least compressed)
    window_start: Array   # i32 applied-update count when the window opened
    win_updates: Array    # i32 applied updates accumulated in the window
    win_bits: Array       # f32 billed bits accumulated (sum over updates)
    win_comm_ms: Array    # f32 comm-time signal accumulated, ms
    win_budget_ms: Array  # f32 hideable-compute budget accumulated, ms
    decisions: Array      # i32 windows closed so far (the event-log cursor)


def init_control_state(cfg: Optional[ControlConfig]) -> Any:
    """Fresh :class:`ControlState` (``()`` when adaptive control is off,
    mirroring ``ef``/``comp``/``guard``).

    Each field gets its OWN zero array — sharing one buffer across fields
    aliases them and breaks the donating jitted step (see
    :func:`tpu_compressed_dp.train.guard.init_guard_state`).
    """
    if cfg is None:
        return ()
    return ControlState(
        rung=jnp.asarray(cfg.start_rung, jnp.int32),
        window_start=jnp.zeros((), jnp.int32),
        win_updates=jnp.zeros((), jnp.int32),
        win_bits=jnp.zeros((), jnp.float32),
        win_comm_ms=jnp.zeros((), jnp.float32),
        win_budget_ms=jnp.zeros((), jnp.float32),
        decisions=jnp.zeros((), jnp.int32),
    )


def control_to_dict(cs: ControlState) -> Dict[str, Array]:
    """Plain-dict form for Orbax (no pytree-registration agreement needed
    between the writing and reading process; same idiom as
    :func:`~tpu_compressed_dp.train.guard.guard_to_dict`)."""
    return {f.name: getattr(cs, f.name) for f in dataclasses.fields(cs)}


def control_from_dict(d: Dict[str, Any]) -> ControlState:
    return ControlState(
        rung=jnp.asarray(d["rung"], jnp.int32),
        window_start=jnp.asarray(d["window_start"], jnp.int32),
        win_updates=jnp.asarray(d["win_updates"], jnp.int32),
        win_bits=jnp.asarray(d["win_bits"], jnp.float32),
        win_comm_ms=jnp.asarray(d["win_comm_ms"], jnp.float32),
        win_budget_ms=jnp.asarray(d["win_budget_ms"], jnp.float32),
        decisions=jnp.asarray(d["decisions"], jnp.int32),
    )
