"""Deterministic signal models feeding the controller.

The controller equalizes two per-update quantities: **comm time** (what the
sync costs) against the **hideable-compute budget** (how much of that cost
the ``sync_overlap`` chunk schedule can bury under the remaining backward
pass).  This module converts what the system already measures into those two
numbers — and nothing here reads a clock: the 'modeled' path is a pure
function of the engines' analytic billed bits, and the 'measured' path takes
wall-times the HARNESS observed (StepTimeline) as plain arguments.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

from tpu_compressed_dp.control.config import ControlConfig

__all__ = ["WindowSignals", "modeled_comm_ms", "hideable_budget_ms",
           "billed_signal_bits", "TwinPricer"]


@dataclasses.dataclass(frozen=True)
class WindowSignals:
    """One tick's per-update signals, as the harness hands them to
    :meth:`~tpu_compressed_dp.control.controller.Controller.tick`."""

    bits: float       # billed wire bits per update (``comm/sent_bits`` mean)
    comm_ms: float    # comm-time signal per update, ms
    budget_ms: float  # hideable-compute budget per update, ms


def modeled_comm_ms(bits_per_update: float, bandwidth_mbps: float) -> float:
    """Analytic per-update comm time: billed bits over the configured wire
    bandwidth.  ``bits / (Mbit/s)`` = microseconds; divide by 1e3 for ms.

    This is the replay-deterministic signal: ``comm/sent_bits`` is computed
    analytically inside the engines (``parallel/dp.py``), so the same run
    replayed — crash, resume, chaos — models the identical comm time.
    """
    return float(bits_per_update) / (float(bandwidth_mbps) * 1e3)


def billed_signal_bits(comm_means, pods: int = 1) -> float:
    """The billed-bits series the modeled signal prices: on a flat mesh the
    whole ``comm/sent_bits``; on a 2-level topology (``pods > 1``) the
    DCN-billed share (``comm/sent_bits_dcn`` plus any flat whole-world
    collectives, which span the slow fabric too) — the inter-pod link is
    the binding constraint a cross-pod bandwidth budget is set against,
    and pricing intra-pod ICI payloads at DCN bandwidth would drive the
    controller to over-compress by orders of magnitude.

    ``comm_means`` is a ``comm/*`` metrics dict of per-update means.
    Deterministic: a pure function of the engines' analytic billed bits.
    """
    total = float(comm_means.get("comm/sent_bits", 0.0))
    if pods <= 1:
        return total
    ici = float(comm_means.get("comm/sent_bits_ici", 0.0))
    return total - ici


@dataclasses.dataclass(frozen=True)
class TwinPricer:
    """Prices billed bits through the calibrated digital twin
    (``--adaptive_model twin``): the bits are laid onto the run's actual
    transport schedule at its (world, pods) topology and priced with the
    fitted per-fabric alpha/beta/gamma — so a rung's comm cost reflects
    dispatch latency and per-hop terms the flat ``bits / bandwidth``
    division cannot see.

    At ``pods > 1`` the bits handed in should already be the DCN-billed
    share (:func:`billed_signal_bits` — the convention the harnesses
    feed the controller), which is why the sharded/hierarchical route
    and return stages split ``route_frac`` / ``1 - route_frac`` over the
    same fabric here.  Deterministic: a frozen pure function of its
    inputs, like every other signal model in this module.

    model:             a fitted :class:`tpu_compressed_dp.twin.CostModel`
    world / pods:      the run's dp topology
    transport:         psum | all_gather | sharded | hierarchical
    num_collectives:   dispatches per update (reduction-group count)
    route_frac:        share of sparse bits riding the route all_to_all
                       (the rest ride the shard-return all_gather)
    calib_rows:        evidence rows behind ``model`` (exported as
                       ``twin/calib_rows``)
    compute_anchor_ms: calibrated non-comm step time for the run's
                       context (the ``twin/pred_step_ms`` baseline)
    """

    model: Any
    world: int
    pods: int = 1
    transport: str = "psum"
    num_collectives: float = 1.0
    route_frac: float = 0.888
    calib_rows: int = 0
    compute_anchor_ms: float = 0.0

    def comm_ms(self, bits_per_update: float) -> float:
        from tpu_compressed_dp.twin.model import flat_schedule
        mb = float(bits_per_update) / 8.0 / 1e6
        kw = dict(world=self.world, pods=self.pods,
                  count=self.num_collectives)
        if self.transport == "all_gather":
            sched = flat_schedule(allgather_mb=mb, **kw)
        elif self.transport in ("sharded", "hierarchical"):
            # route bits ride the all_to_all bucket, return bits the
            # all_gather; hierarchical's DCN share splits the same way
            sched = flat_schedule(alltoall_mb=mb * self.route_frac,
                                  allgather_mb=mb * (1.0 - self.route_frac),
                                  **kw)
        else:
            sched = flat_schedule(psum_mb=mb, **kw)
        return self.model.comm_ms(sched)

    def step_ms(self, bits_per_update: float) -> float:
        return self.compute_anchor_ms + self.comm_ms(bits_per_update)


def hideable_budget_ms(cfg: ControlConfig, *,
                       compute_ms: Optional[float] = None,
                       hideable_fraction: float = 1.0) -> float:
    """The per-update compute budget comm should be tuned to fit inside.

    ``cfg.budget_ms > 0`` pins it (the CPU/CI path, and any deployment that
    calibrated the budget offline).  Otherwise the budget is the measured
    per-update compute time scaled by the overlap schedule's hideable
    fraction (:func:`tpu_compressed_dp.parallel.overlap.hideable_byte_fraction`
    — the serial head chunk of the pipeline can't hide, so only that
    fraction of the sync genuinely overlaps compute).
    """
    if cfg.budget_ms > 0.0:
        return float(cfg.budget_ms)
    if compute_ms is None:
        raise ValueError(
            "budget_ms=0 needs a measured compute_ms to derive the budget "
            "from (pass --adaptive_budget_ms, or use signal='measured' with "
            "a timeline)")
    return float(compute_ms) * float(hideable_fraction)


def mean_or_zero(values: Sequence[float]) -> float:
    """Mean of a possibly-empty sequence (0.0 when empty) — tick inputs for
    epochs where every step was skipped."""
    vals = list(values)
    return sum(vals) / len(vals) if vals else 0.0
