"""The host-side decision loop: window accounting + the rung rule.

One :class:`Controller` per run.  The harness calls :meth:`Controller.tick`
at its metric-fetch cadence (per epoch in the CNN harnesses) with the
applied-update count and that span's per-update signals; the controller
accumulates them into the open window (all accumulators live in the
checkpointed :class:`~tpu_compressed_dp.control.state.ControlState`, so a
crash mid-window resumes the very same window), closes the window once it
spans ``cfg.window`` applied updates, and applies the rule:

  * comm above ``budget*(1+deadband)``       -> one rung DOWN the ladder
    (more compression);
  * comm below ``budget*(1-deadband)`` AND the projected comm at the
    cheaper rung still inside the band     -> one rung UP;
  * otherwise                               -> hold.

One rung per window (the arXiv 1911.08727 rule discretised): payloads scale
~linearly in the knob, so a single window of signals cannot justify a
multi-rung jump, and bounded motion keeps every visited rung's step variant
trace-cached instead of compiling the whole ladder up front.

Every window close — including holds — is a ``control_decision`` record on
the ``--events`` stream and increments the ``decisions`` cursor, so two
replicas (or a crash/resume replay) can be compared decision-for-decision.
Nothing here reads a clock; with the default 'modeled' signal the whole
sequence is a deterministic function of checkpointed state and the engines'
analytic comm stats.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax.numpy as jnp

from tpu_compressed_dp.control.config import ControlConfig
from tpu_compressed_dp.control.rungs import ladder_knob, rung_value
from tpu_compressed_dp.control.signals import (
    WindowSignals, hideable_budget_ms, modeled_comm_ms,
)
from tpu_compressed_dp.control.state import ControlState

__all__ = ["Controller", "Decision"]


@dataclasses.dataclass(frozen=True)
class Decision:
    """One closed window, exactly as it lands on the event stream."""

    index: int         # the decision-log cursor (ControlState.decisions)
    applied: int       # applied-update count at the window close
    window_start: int  # applied-update count when the window opened
    updates: int       # applied updates the window spanned
    rung_from: int
    rung_to: int
    value_from: float  # knob value (ratio or rank) before
    value_to: float    # knob value after
    comm_ms: float     # window-mean comm signal per update
    budget_ms: float   # window-mean hideable budget per update
    bits: float        # window-mean billed bits per update
    direction: str     # 'down' (more compression) | 'up' | 'hold'

    def event_fields(self) -> dict:
        return dataclasses.asdict(self)


class Controller:
    """Host half of the control plane.  Stateless beyond ``cfg`` — all
    run-state rides ``TrainState.control`` so resume replays decisions
    bitwise."""

    def __init__(self, cfg: ControlConfig, *, events: Any = None,
                 pricer: Any = None):
        self.cfg = cfg
        self.knob = ladder_knob(cfg.method)
        self.events = events
        # the calibrated twin's bit pricer (--adaptive_model twin): still
        # a pure function of billed bits, so decisions stay replayable
        self.pricer = pricer
        if cfg.model == "twin" and pricer is None:
            raise ValueError(
                "cfg.model='twin' needs a TwinPricer (build one from the "
                "records dir: harness.loop.build_twin_pricer)")

    # ----------------------------------------------------------- signals

    def window_signals(self, *, mean_bits: float,
                       measured_comm_ms: Optional[float] = None,
                       compute_ms: Optional[float] = None,
                       hideable_fraction: float = 1.0) -> WindowSignals:
        """Assemble one tick's per-update signals per ``cfg.signal``."""
        if self.cfg.signal == "modeled":
            if self.cfg.model == "twin":
                comm = self.pricer.comm_ms(mean_bits)
            else:
                comm = modeled_comm_ms(mean_bits, self.cfg.bandwidth_mbps)
        else:
            if measured_comm_ms is None:
                raise ValueError(
                    "signal='measured' needs measured_comm_ms from the "
                    "harness timeline")
            comm = float(measured_comm_ms)
        budget = hideable_budget_ms(
            self.cfg, compute_ms=compute_ms,
            hideable_fraction=hideable_fraction)
        return WindowSignals(bits=float(mean_bits), comm_ms=comm,
                             budget_ms=budget)

    # -------------------------------------------------------------- tick

    def tick(self, control: ControlState, *, applied: int,
             signals: WindowSignals) -> Tuple[ControlState, List[Decision]]:
        """Fold one observation span into the open window; close it when it
        spans ``cfg.window`` applied updates.

        ``applied`` is the applied-update count NOW (``guard.schedule_step``
        of the current step) — the delta since the last tick weights the
        signals.  A tick with no applied updates (an all-skipped epoch)
        leaves the window clock frozen, which is exactly what keeps chaos
        replays aligned.
        """
        applied = int(applied)
        delta = applied - (int(control.window_start)
                           + int(control.win_updates))
        if delta <= 0:
            return control, []
        rung = int(control.rung)
        window_start = int(control.window_start)
        n_dec = int(control.decisions)
        win_updates = int(control.win_updates) + delta
        win_bits = float(control.win_bits) + signals.bits * delta
        win_comm = float(control.win_comm_ms) + signals.comm_ms * delta
        win_budget = float(control.win_budget_ms) + signals.budget_ms * delta

        decisions: List[Decision] = []
        if win_updates >= self.cfg.window:
            comm = win_comm / win_updates
            budget = win_budget / win_updates
            new_rung, direction = self._decide(rung, comm, budget)
            dec = Decision(
                index=n_dec, applied=applied, window_start=window_start,
                updates=win_updates, rung_from=rung, rung_to=new_rung,
                value_from=rung_value(self.cfg, rung),
                value_to=rung_value(self.cfg, new_rung),
                comm_ms=comm, budget_ms=budget, bits=win_bits / win_updates,
                direction=direction,
            )
            decisions.append(dec)
            self._emit(dec)
            rung, window_start, n_dec = new_rung, applied, n_dec + 1
            win_updates, win_bits = 0, 0.0
            win_comm, win_budget = 0.0, 0.0

        new_control = ControlState(
            rung=jnp.asarray(rung, jnp.int32),
            window_start=jnp.asarray(window_start, jnp.int32),
            win_updates=jnp.asarray(win_updates, jnp.int32),
            win_bits=jnp.asarray(win_bits, jnp.float32),
            win_comm_ms=jnp.asarray(win_comm, jnp.float32),
            win_budget_ms=jnp.asarray(win_budget, jnp.float32),
            decisions=jnp.asarray(n_dec, jnp.int32),
        )
        return new_control, decisions

    def _decide(self, rung: int, comm_ms: float,
                budget_ms: float) -> Tuple[int, str]:
        hi = budget_ms * (1.0 + self.cfg.deadband)
        lo = budget_ms * (1.0 - self.cfg.deadband)
        last = len(self.cfg.rungs) - 1
        if comm_ms > hi and rung < last:
            return rung + 1, "down"
        if comm_ms < lo and rung > 0:
            # step up only if the cheaper rung's projected comm still fits
            # (payloads scale ~linearly in the knob); without the projection
            # the controller ping-pongs across the deadband every window
            scale = (rung_value(self.cfg, rung - 1)
                     / rung_value(self.cfg, rung))
            if comm_ms * scale <= hi:
                return rung - 1, "up"
        return rung, "hold"

    def _emit(self, dec: Decision) -> None:
        ev = self.events
        if ev is None:
            return
        try:
            ev.emit("control_decision", knob=self.knob, **dec.event_fields())
        except Exception:
            pass  # telemetry must never fail a decision

    # -------------------------------------------------------- observability

    def metrics(self, control: Any) -> dict:
        """Host-emitter gauges for heartbeat/Prometheus; keys declared in
        ``obs/registry.py``.  Derived purely from the checkpointed state so
        a resumed run exports consistent values."""
        if control == ():
            return {}
        rung = int(control.rung)
        n = max(1, int(control.win_updates))
        out = {
            "control/rung": float(rung),
            "control/value": float(rung_value(self.cfg, rung)),
            "control/decisions": float(int(control.decisions)),
            "control/window_updates": float(int(control.win_updates)),
            "control/comm_ms": float(control.win_comm_ms) / n,
            "control/budget_ms": float(control.win_budget_ms) / n,
        }
        if self.pricer is not None and int(control.win_updates) > 0:
            # twin audit gauges ride the same export path: the twin's
            # price for the open window's mean billed bits, and its
            # discrepancy against the flat-bandwidth price (declared in
            # obs/registry.py; derived from checkpointed state only)
            mean_bits = float(control.win_bits) / n
            twin_comm = self.pricer.comm_ms(mean_bits)
            flat_comm = modeled_comm_ms(mean_bits, self.cfg.bandwidth_mbps)
            out["twin/pred_step_ms"] = (
                float(self.pricer.compute_anchor_ms) + twin_comm)
            out["twin/pred_err_frac"] = ((twin_comm - flat_comm)
                                         / max(flat_comm, 1e-9))
            out["twin/calib_rows"] = float(self.pricer.calib_rows)
        return out

    def heartbeat_fields(self, control: Any) -> dict:
        if control == ():
            return {}
        return {"control_rung": int(control.rung),
                "control_value": float(rung_value(self.cfg,
                                                  int(control.rung)))}
