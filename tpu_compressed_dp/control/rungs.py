"""The discrete rung ladder: mapping ladder positions to step variants.

Jitted steps bake the compression knob at trace time (Top-K's keep count and
PowerSGD's factor shapes are static), so the controller cannot tune k
continuously — it walks a small static ladder of precompiled rungs.  Each
rung is a full :class:`~tpu_compressed_dp.parallel.dp.CompressionConfig`
(:func:`comp_for_rung`), and the harness keeps one trace-cached train step
per visited rung (the ``step_cache`` idiom ``harness/dawn.py`` already uses
for ratio warmup).

Ratio rungs (topk/blocktopk/randomk) need no state surgery: the EF residual
is dense and ratio-independent, and those methods carry no compressor state.
Rank rungs (powersgd) resize the warm-start ``Q`` factors —
:func:`migrate_comp_state` re-derives the state at the new rank and copies
the overlapping warm columns, so the power iteration keeps its converged
subspace through a rung switch instead of re-warming from random.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_compressed_dp.control.config import (
    ControlConfig, RANK_METHODS, RATIO_METHODS,
)
from tpu_compressed_dp.parallel.dp import CompressionConfig, init_comp_state

__all__ = ["ladder_knob", "build_ladder", "comp_for_rung",
           "migrate_comp_state", "rung_value"]


def ladder_knob(method: str) -> str:
    """Which :class:`CompressionConfig` field the ladder drives:
    ``'ratio'`` or ``'rank'``."""
    if method in RATIO_METHODS:
        return "ratio"
    if method in RANK_METHODS:
        return "rank"
    raise ValueError(f"no ladder knob for method {method!r}")


def build_ladder(method: str, base_ratio: float, base_rank: int,
                 *, depth: int = 5) -> Tuple[float, ...]:
    """Default descending ladder anchored at the CLI-configured knob.

    Ratio methods halve per rung (floored at 1e-3 — below that Top-K keeps
    ~nothing and the EF delay diverges); powersgd halves the rank (floored
    at 1).  Rung 0 is the configured static value, so an adaptive run that
    never needs to act behaves exactly like the static run.
    """
    if ladder_knob(method) == "ratio":
        rungs, r = [], float(base_ratio)
        for _ in range(depth):
            rungs.append(r)
            r = r / 2.0
            if r < 1e-3:
                break
        return tuple(rungs)
    rungs, rk = [], int(base_rank)
    while rk >= 1 and len(rungs) < depth:
        rungs.append(float(rk))
        if rk == 1:
            break
        rk = max(1, rk // 2)
    return tuple(rungs)


def rung_value(cfg: ControlConfig, rung: int) -> float:
    """The knob value at a ladder position (bounds-checked)."""
    if not (0 <= rung < len(cfg.rungs)):
        raise ValueError(
            f"rung {rung} out of range for ladder {cfg.rungs}")
    return cfg.rungs[rung]


def comp_for_rung(base: CompressionConfig, cfg: ControlConfig,
                  rung: int) -> CompressionConfig:
    """The compression config a given ladder position compiles to — the
    trace-cache key the harness builds step variants from."""
    val = rung_value(cfg, rung)
    if ladder_knob(cfg.method) == "ratio":
        return dataclasses.replace(base, ratio=val)
    return dataclasses.replace(base, rank=int(val))


def migrate_comp_state(comp: Any, grads_like: Any, old: CompressionConfig,
                       new: CompressionConfig,
                       num_devices: Optional[int] = None, *,
                       seed: int = 0) -> Any:
    """Carry the PowerSGD warm start across a rank rung switch.

    A rank change resizes every group's ``Q`` ([..., n2, r]) and can move
    groups across the dense-fallback boundary (``r*(m+n2) >= n``), so the
    state is re-derived with :func:`init_comp_state` at the NEW rank —
    deterministically, from the same seed every worker uses — and the first
    ``min(r_old, r_new)`` warm columns are copied where a group exists at
    both ranks (``n2`` depends only on the group size, so columns align).
    Stateless methods and no-op switches pass through unchanged.
    """
    if comp == () or old.rank == new.rank:
        return comp
    fresh = init_comp_state(grads_like, new, num_devices, seed=seed)
    if fresh == ():
        return fresh
    out = {}
    for qk, q_new in fresh.items():
        q_old = comp.get(qk) if isinstance(comp, dict) else None
        if q_old is None or q_old.shape[:-1] != q_new.shape[:-1]:
            out[qk] = q_new
            continue
        r_copy = min(q_old.shape[-1], q_new.shape[-1])
        out[qk] = jnp.concatenate(
            [q_old[..., :r_copy], q_new[..., r_copy:]], axis=-1)
    return out


def assert_ladder_traceable(cfg: ControlConfig) -> None:
    """Cheap sanity hook for harness start: every rung must build a valid
    config (``CompressionConfig.__post_init__`` validates ranges), so a bad
    ladder fails at launch, not at the first rung switch mid-run."""
    base = CompressionConfig(method=cfg.method)
    for i in range(len(cfg.rungs)):
        comp_for_rung(base, cfg, i)
    jax.tree.map(lambda x: x, cfg.rungs)  # tuples of plain floats only
