"""Closed-loop adaptive compression control plane.

The obs subsystem measures per-phase time and per-chip comm volume; this
package closes the loop: a host-side controller consumes those signals each
decision window and retunes per-group compression (Top-K/Random-K ``ratio``,
PowerSGD ``rank``) to equalize comm time against the compute it can hide
behind.  Jitted steps bake k at trace time, so tuning is a **discrete rung
ladder** — a small static set of precompiled ratio/rank rungs
(:mod:`tpu_compressed_dp.control.rungs`), with rung switches only at step
boundaries (the harness swaps trace-cached step variants between epochs).

House invariants, same as chaos/guard/elastic:

  * decisions key off APPLIED-update counts (``guard.schedule_step``
    semantics), so NaN-skip bursts don't desynchronise replay;
  * every window close is a ``control_decision`` record on the ``--events``
    stream; the default ``signal='modeled'`` derives comm time from the
    engines' analytic billed bits, making the whole decision sequence
    bitwise reproducible across crash/resume replays;
  * controller state (:class:`~tpu_compressed_dp.control.state.ControlState`)
    rides ``TrainState.control`` next to ``guard`` — replicated, donated,
    Orbax round-tripped with the established legacy-template fallback;
  * no module here reads the wall clock — signals are injected by the
    harness (``analysis/hostlint.py`` lints this package replay-deterministic).

Adaptive-k rule after "Layer-wise Adaptive Gradient Sparsification"
(PAPERS.md, arXiv 1911.08727); the accuracy-vs-k backdrop is "Understanding
Top-k Sparsification" (arXiv 1911.08772).
"""

from tpu_compressed_dp.control.config import ControlConfig
from tpu_compressed_dp.control.controller import Controller, Decision
from tpu_compressed_dp.control.rungs import (
    build_ladder, comp_for_rung, ladder_knob, migrate_comp_state,
)
from tpu_compressed_dp.control.signals import hideable_budget_ms, modeled_comm_ms
from tpu_compressed_dp.control.state import (
    ControlState, control_from_dict, control_to_dict, init_control_state,
)

__all__ = [
    "ControlConfig", "Controller", "Decision", "ControlState",
    "init_control_state", "control_to_dict", "control_from_dict",
    "build_ladder", "comp_for_rung", "ladder_knob", "migrate_comp_state",
    "modeled_comm_ms", "hideable_budget_ms",
]
