"""Controller configuration: the rung ladder and the decision rule's knobs.

A :class:`ControlConfig` is frozen at harness start (CLI ``--adaptive*``
flags, :func:`tpu_compressed_dp.harness.loop.build_control`); everything the
controller decides at runtime lives in
:class:`~tpu_compressed_dp.control.state.ControlState` so it checkpoints.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

__all__ = ["ControlConfig", "TUNABLE_METHODS", "RATIO_METHODS", "RANK_METHODS"]

#: methods whose compression knob is the keep ``ratio``
RATIO_METHODS = ("topk", "blocktopk", "randomk")
#: methods whose compression knob is the low-rank ``rank``
RANK_METHODS = ("powersgd",)
TUNABLE_METHODS = RATIO_METHODS + RANK_METHODS


@dataclasses.dataclass(frozen=True)
class ControlConfig:
    """Closed-loop compression-control knobs.

    method:         canonical compressor name the ladder tunes (one of
                    :data:`TUNABLE_METHODS`; threshold/quantizer methods have
                    no discrete payload knob a trace-cached ladder can swap)
    rungs:          descending knob values, rung 0 = least compressed.  For
                    ratio methods these are keep ratios in (0, 1]; for
                    powersgd they are integral ranks >= 1.  Small and static
                    by design: each rung is a separately-compiled step
                    variant, so the ladder size bounds the trace-cache cost.
    window:         decision-window length in APPLIED updates (the
                    ``guard.schedule_step`` clock — skipped steps never close
                    a window, so replay under chaos stays aligned)
    deadband:       relative hysteresis around the budget: comm above
                    ``budget*(1+deadband)`` steps DOWN the ladder (more
                    compression), below ``budget*(1-deadband)`` steps UP —
                    and only when the projected comm at the cheaper rung
                    still fits, so the controller doesn't oscillate across
                    the band
    signal:         'modeled' (default) — per-update comm time is the
                    engines' analytic billed bits over ``bandwidth_mbps``,
                    which makes every decision a pure function of
                    checkpointed state + deterministic metrics (bitwise
                    replayable); 'measured' — the harness feeds StepTimeline
                    wall-time signals instead (production mode; documented
                    as NOT cross-run bitwise)
    model:          how the 'modeled' signal prices bits: 'flat' (default)
                    divides by ``bandwidth_mbps``; 'twin' prices the
                    transport's collective schedule through the calibrated
                    per-fabric twin (``tpu_compressed_dp/twin/``) — the
                    harness must hand the Controller a
                    :class:`~tpu_compressed_dp.control.signals.TwinPricer`.
                    Still a pure function of billed bits, so still
                    replay-deterministic
    bandwidth_mbps: modeled per-chip wire bandwidth, Mbit/s ('modeled' only)
    budget_ms:      hideable-compute budget per update, ms.  > 0 pins the
                    budget; 0 means the harness must derive it (measured
                    compute time x the overlap schedule's hideable fraction,
                    :func:`tpu_compressed_dp.control.signals.hideable_budget_ms`)
    start_rung:     initial ladder position
    """

    method: str
    rungs: Tuple[float, ...]
    window: int = 8
    deadband: float = 0.25
    signal: str = "modeled"
    model: str = "flat"
    bandwidth_mbps: float = 100.0
    budget_ms: float = 0.0
    start_rung: int = 0

    def __post_init__(self):
        if self.method not in TUNABLE_METHODS:
            raise ValueError(
                f"adaptive control tunes {TUNABLE_METHODS}, got "
                f"{self.method!r} (threshold/quantizer methods have no "
                "discrete payload knob to ladder)")
        if len(self.rungs) < 2:
            raise ValueError(
                f"a ladder needs >= 2 rungs to control anything, got "
                f"{self.rungs}")
        if any(b >= a for a, b in zip(self.rungs, self.rungs[1:])):
            raise ValueError(
                f"rungs must strictly descend (rung 0 = least compressed), "
                f"got {self.rungs}")
        if self.method in RATIO_METHODS:
            if any(not (0.0 < r <= 1.0) for r in self.rungs):
                raise ValueError(
                    f"ratio rungs must lie in (0, 1], got {self.rungs}")
        else:
            if any(r < 1 or r != int(r) for r in self.rungs):
                raise ValueError(
                    f"rank rungs must be integers >= 1, got {self.rungs}")
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if not (0.0 <= self.deadband < 1.0):
            raise ValueError(
                f"deadband must be in [0, 1), got {self.deadband}")
        if self.signal not in ("modeled", "measured"):
            raise ValueError(
                f"signal must be modeled|measured, got {self.signal!r}")
        if self.model not in ("flat", "twin"):
            raise ValueError(
                f"model must be flat|twin, got {self.model!r}")
        if self.signal == "modeled" and self.bandwidth_mbps <= 0:
            raise ValueError(
                f"bandwidth_mbps must be positive for the modeled signal, "
                f"got {self.bandwidth_mbps}")
        if self.budget_ms < 0:
            raise ValueError(f"budget_ms must be >= 0, got {self.budget_ms}")
        if not (0 <= self.start_rung < len(self.rungs)):
            raise ValueError(
                f"start_rung {self.start_rung} out of range for "
                f"{len(self.rungs)} rungs")
