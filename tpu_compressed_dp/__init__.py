"""tpu_compressed_dp — TPU-native compressed-communication data-parallel training.

A brand-new JAX/XLA/Pallas/pjit framework with the capabilities of the AAAI'20
layer-wise compressed-communication reference (see SURVEY.md): six gradient
compression operators at layer-wise or entire-model granularity, simulated and
wire-sparse payloads, error feedback, DAWNBench CIFAR-10 and ImageNet ResNet-50
workloads, phase schedules, checkpointing, and comm observability — all over
`jax.sharding.Mesh` collectives instead of NCCL/Gloo.
"""

__version__ = "0.1.0"

from tpu_compressed_dp.compat import shard_map  # noqa: F401  (version shim)
from tpu_compressed_dp.parallel.dp import CompressionConfig  # noqa: F401
from tpu_compressed_dp.parallel.mesh import make_data_mesh, distributed_init  # noqa: F401
from tpu_compressed_dp.train.optim import SGD  # noqa: F401
from tpu_compressed_dp.train.schedules import piecewise_linear  # noqa: F401
from tpu_compressed_dp.train.state import TrainState  # noqa: F401
from tpu_compressed_dp.train.step import make_train_step, make_eval_step  # noqa: F401
