// Native image-geometry kernel for the data pipeline hot path.
//
// The reference feeds its GPUs through torch's C++ DataLoader workers and
// PIL-SIMD (`IMAGENET/setup.sh:4-8` installs pillow-simd; `dataloader.py`
// rides `torch.utils.data.DataLoader`).  This is the TPU framework's native
// equivalent for the per-image work that dominates host CPU time: fused
// crop + resize + horizontal-flip from a decoded RGB buffer straight into
// the collated uint8 NHWC batch, with no intermediate allocations beyond one
// float scratch row block.
//
// Resize semantics match PIL's BILINEAR (a separable triangle filter whose
// support scales with the downscale ratio — i.e. antialiased area-weighted
// sampling, not naive 4-tap bilinear), so swapping the Python path for this
// one changes pixels by rounding only.  Called from Python via ctypes
// (tpu_compressed_dp/data/native.py); ctypes drops the GIL for the duration,
// so the existing thread-pool loaders parallelise across images for free.
//
// Build: g++ -O3 -fPIC -shared -pthread image_ops.cpp -o libimageops.so

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Taps {
  std::vector<int> start;    // first source index per output index
  std::vector<int> count;    // taps per output index
  std::vector<float> weight; // flattened [out][tap] weights, normalised
  int max_count = 0;
};

// PIL-compatible triangle-filter taps mapping src range [lo, hi) -> out_size.
Taps make_taps(float lo, float hi, int src_size, int out_size) {
  Taps t;
  t.start.resize(out_size);
  t.count.resize(out_size);
  const double scale = (hi - lo) / out_size;
  const double filterscale = std::max(scale, 1.0);
  const double support = 1.0 * filterscale; // bilinear support = 1.0
  t.max_count = (int)std::ceil(support * 2 + 1);
  t.weight.assign((size_t)out_size * t.max_count, 0.0f);
  for (int j = 0; j < out_size; ++j) {
    const double center = lo + (j + 0.5) * scale;
    int xmin = (int)std::max(0.0, std::floor(center - support + 0.5));
    int xmax = (int)std::min((double)src_size, std::floor(center + support + 0.5));
    if (xmax <= xmin) { // degenerate box: clamp to nearest valid pixel
      xmin = std::min(std::max(xmin, 0), src_size - 1);
      xmax = xmin + 1;
    }
    double total = 0.0;
    std::vector<double> w(xmax - xmin);
    for (int x = xmin; x < xmax; ++x) {
      const double d = (x + 0.5 - center) / filterscale;
      const double tw = std::max(0.0, 1.0 - std::abs(d)); // triangle
      w[x - xmin] = tw;
      total += tw;
    }
    if (total <= 0.0) { w.assign(w.size(), 1.0); total = (double)w.size(); }
    t.start[j] = xmin;
    t.count[j] = xmax - xmin;
    for (int k = 0; k < xmax - xmin; ++k)
      t.weight[(size_t)j * t.max_count + k] = (float)(w[k] / total);
  }
  return t;
}

inline uint8_t clamp_u8(float v) {
  return (uint8_t)std::min(255.0f, std::max(0.0f, v + 0.5f));
}

} // namespace

extern "C" {

// Crop the box [x0,y0,x1,y1) out of src (sh x sw x 3 uint8), resize to
// (dh x dw) with PIL-BILINEAR semantics, optional horizontal flip, write
// into dst (dh x dw x 3 uint8).  Returns 0 on success.
int crop_resize_bilinear(const uint8_t* src, int sh, int sw,
                         float x0, float y0, float x1, float y1,
                         uint8_t* dst, int dh, int dw, int flip) {
  if (!src || !dst || sh <= 0 || sw <= 0 || dh <= 0 || dw <= 0) return 1;
  x0 = std::max(0.0f, std::min(x0, (float)sw));
  x1 = std::max(x0, std::min(x1, (float)sw));
  y0 = std::max(0.0f, std::min(y0, (float)sh));
  y1 = std::max(y0, std::min(y1, (float)sh));

  const Taps tx = make_taps(x0, x1, sw, dw);
  const Taps ty = make_taps(y0, y1, sh, dh);

  // horizontal pass: src rows [row_lo, row_hi) -> float (rows x dw x 3)
  const int row_lo = ty.start.empty() ? 0 : *std::min_element(ty.start.begin(), ty.start.end());
  int row_hi = 0;
  for (int j = 0; j < dh; ++j) row_hi = std::max(row_hi, ty.start[j] + ty.count[j]);
  const int rows = row_hi - row_lo;
  std::vector<float> mid((size_t)rows * dw * 3);
  for (int r = 0; r < rows; ++r) {
    const uint8_t* srow = src + (size_t)(r + row_lo) * sw * 3;
    float* mrow = mid.data() + (size_t)r * dw * 3;
    for (int j = 0; j < dw; ++j) {
      float acc0 = 0, acc1 = 0, acc2 = 0;
      const int s = tx.start[j], c = tx.count[j];
      const float* w = &tx.weight[(size_t)j * tx.max_count];
      for (int k = 0; k < c; ++k) {
        const uint8_t* p = srow + (size_t)(s + k) * 3;
        acc0 += w[k] * p[0];
        acc1 += w[k] * p[1];
        acc2 += w[k] * p[2];
      }
      mrow[j * 3 + 0] = acc0;
      mrow[j * 3 + 1] = acc1;
      mrow[j * 3 + 2] = acc2;
    }
  }

  // vertical pass + flip + u8 store
  for (int i = 0; i < dh; ++i) {
    const int s = ty.start[i], c = ty.count[i];
    const float* w = &ty.weight[(size_t)i * ty.max_count];
    uint8_t* drow = dst + (size_t)i * dw * 3;
    for (int j = 0; j < dw; ++j) {
      float acc0 = 0, acc1 = 0, acc2 = 0;
      for (int k = 0; k < c; ++k) {
        const float* p = mid.data() + ((size_t)(s + k - row_lo) * dw + j) * 3;
        acc0 += w[k] * p[0];
        acc1 += w[k] * p[1];
        acc2 += w[k] * p[2];
      }
      const int jj = flip ? (dw - 1 - j) : j;
      drow[jj * 3 + 0] = clamp_u8(acc0);
      drow[jj * 3 + 1] = clamp_u8(acc1);
      drow[jj * 3 + 2] = clamp_u8(acc2);
    }
  }
  return 0;
}

} // extern "C"
