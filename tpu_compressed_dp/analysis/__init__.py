"""Static analysis — tcdp-lint's two passes as an importable subsystem.

Pass 1 (:mod:`tpu_compressed_dp.analysis.spmd`) traces the sync engines and
step factories to jaxprs and checks the SPMD safety invariants every worker
relies on structurally: no collective hidden inside divergent control flow,
deterministic collective signatures across re-traces and worker-symmetric
configs, donation aliasing that actually lands, and an intact overlap
chunk chain.  Pass 2 (:mod:`tpu_compressed_dp.analysis.hostlint`) is a
rule-based ``ast`` walk over the host-side code enforcing the invariants
the runtime drills (chaos/elastic/rendezvous) depend on: injectable clocks,
atomic shared-dir writes, registry-declared stat keys, the ``tcdp.<phase>``
scope taxonomy, and lock-guarded cross-thread mutation.

``tools/tcdp_lint.py`` is the CLI; ``tests/test_lint.py`` gates tier-1 on
zero unsuppressed findings.  Import of this package must stay jax-free —
:mod:`.spmd` imports jax lazily so the AST pass runs anywhere.
"""

from tpu_compressed_dp.analysis.report import (  # noqa: F401
    CODES, Finding, filter_suppressed, findings_to_json, format_findings,
)

__all__ = ["CODES", "Finding", "filter_suppressed", "findings_to_json",
           "format_findings"]
