"""Pass 2 — rule-based AST lint over the host-side code (package + tools).

Five rules, each enforcing an invariant a runtime drill already depends on
(see ISSUE/README); the linter makes them fail in milliseconds instead of
in a chaos drill:

  * **TCDP101** — no wall-clock reads (``time.time()``, ``datetime.now()``)
    in the replay-deterministic modules (chaos, elastic, rendezvous,
    guard).  Those paths replay under chaos and across resume; they take
    injectable ``now``/``wall`` callables.  *References* like
    ``now: Callable = time.monotonic`` are the injection seam and pass;
    only direct calls are flagged.
  * **TCDP102** — every write-mode ``open()`` in a shared-dir protocol
    module (rendezvous epoch/vote/join files, gossip heartbeats,
    checkpoint manifests, prometheus textfiles) must target a ``*.tmp``
    sibling that is later ``os.replace()``d — readers on shared storage
    must never see a torn record.  Append mode is exempt (JSONL event
    streams rely on O_APPEND).
  * **TCDP103** — every ``"family/name"`` stat-key string literal in a
    registry-governed family must be declared in ``obs/registry.py``.
    This extends the runtime conformance test (tests/test_observability)
    to keys only ever touched on cold paths.
  * **TCDP104** — ``jax.named_scope`` strings outside ``obs/trace.py``
    must live in the ``tcdp.<phase>`` taxonomy, and literal
    ``obs_trace.phase(...)`` arguments must name a declared phase —
    xprof tooling (tools/trace_report.py) groups by these.
  * **TCDP105** — attributes mutated inside a ``threading.Thread`` target
    must hold the owning class's lock; unsynchronised writer threads are
    how the heartbeat false-positive bug happened (utils/resilience.py).

``lint_source`` is the per-file engine (tests feed it fixtures);
``run_host_pass`` walks the real tree and applies ``# tcdp-lint:
disable=`` suppressions.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tpu_compressed_dp.analysis.report import Finding, filter_suppressed

__all__ = [
    "REPLAY_DETERMINISTIC_MODULES", "SHARED_DIR_MODULES", "STAT_KEY_RE",
    "lint_source", "lint_file", "run_host_pass", "iter_lintable_files",
    "roles_for_path",
]

#: modules whose behaviour must replay bit-identically under chaos/resume —
#: wall-clock reads (TCDP101) are banned here, injectable clocks only
REPLAY_DETERMINISTIC_MODULES = (
    "tpu_compressed_dp/utils/chaos.py",
    "tpu_compressed_dp/train/elastic.py",
    "tpu_compressed_dp/train/rendezvous.py",
    "tpu_compressed_dp/train/guard.py",
    # the adaptive-compression control plane: decisions must replay
    # bitwise across crash/resume (the 'modeled' signal is a pure function
    # of checkpointed state + analytic comm stats — no clock reads)
    "tpu_compressed_dp/control/config.py",
    "tpu_compressed_dp/control/controller.py",
    "tpu_compressed_dp/control/rungs.py",
    "tpu_compressed_dp/control/signals.py",
    "tpu_compressed_dp/control/state.py",
    # the fleet decision loop: admission order, placement, preemption and
    # the records/events they produce must replay from the same snapshot
    # (clocks are injected, timestamps ride in via the scheduler's wall)
    "tpu_compressed_dp/fleet/spec.py",
    "tpu_compressed_dp/fleet/placement.py",
    "tpu_compressed_dp/fleet/scheduler.py",
    # the flight recorder rides inside replay-deterministic callers (guard
    # check, elastic failure handling) — its timestamps come from an
    # injected clock; postmortem replays bundles offline and must order
    # records by seq, never by wall reads of its own
    "tpu_compressed_dp/obs/flight.py",
    "tools/postmortem.py",
    # the delta state stream: segment content and window accounting must
    # replay bitwise (the lossless-window invariant) — segment timestamps
    # ride in via the writer's injected wall clock
    "tpu_compressed_dp/stream/delta.py",
    "tpu_compressed_dp/stream/writer.py",
    "tpu_compressed_dp/stream/reader.py",
    "tpu_compressed_dp/stream/rejoin.py",
    # the digital twin's fit/predict core: calibrations and pin verdicts
    # must be pure functions of the committed artifacts — same records,
    # same model, bitwise — so the perf gate is reproducible in CI
    "tpu_compressed_dp/twin/model.py",
    "tpu_compressed_dp/twin/records.py",
    "tpu_compressed_dp/twin/calibrate.py",
    "tpu_compressed_dp/twin/gate.py",
)

#: modules that write records other processes read over shared storage —
#: write-mode opens (TCDP102) must go through tmp + os.replace here
SHARED_DIR_MODULES = (
    "tpu_compressed_dp/train/rendezvous.py",
    "tpu_compressed_dp/train/elastic.py",
    "tpu_compressed_dp/utils/resilience.py",
    "tpu_compressed_dp/utils/checkpoint.py",
    "tpu_compressed_dp/obs/export.py",
    # fleet queue/job/pool records: multi-process readers (operator CLI,
    # dashboards) over the shared fleet dir
    "tpu_compressed_dp/fleet/state.py",
    # blackbox bundles + phase profiles: every rank writes, postmortem /
    # peers / the watchdog read concurrently over the shared dir
    "tpu_compressed_dp/obs/flight.py",
    "tools/postmortem.py",
    # stream segments: the training rank writes, joiners and serving
    # consumers tail the same directory concurrently
    "tpu_compressed_dp/stream/store.py",
    "tools/stream_serve.py",
)

#: registry-governed stat-key families (TCDP103); literals shaped
#: "<family>/<name>" with these families must be declared
STAT_FAMILIES = ("comm", "guard", "elastic", "ckpt", "throughput", "time",
                 "net", "control", "fleet", "flight", "straggler", "stream",
                 "twin")
STAT_KEY_RE = re.compile(r"^(?:%s)/[a-z0-9_]+$" % "|".join(STAT_FAMILIES))

_WALLCLOCK_CALLS = frozenset({
    "time.time",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})
_LOCK_FACTORIES = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
})
_ROLE_PRAGMA_RE = re.compile(r"#\s*tcdp-lint:\s*roles=([a-z_,\s]+)")
_CHUNK_SCOPE_RE = re.compile(r"^chunk\d+$")


def roles_for_path(rel_path: str) -> Set[str]:
    """Rule-applicability roles inferred from the repo-relative path."""
    p = rel_path.replace(os.sep, "/")
    roles: Set[str] = set()
    if any(p.endswith(m) for m in REPLAY_DETERMINISTIC_MODULES):
        roles.add("replay")
    if any(p.endswith(m) for m in SHARED_DIR_MODULES):
        roles.add("shared_dir")
    return roles


def _pragma_roles(source: str) -> Optional[Set[str]]:
    """Fixture files self-describe their roles via a header pragma
    (``# tcdp-lint: roles=replay,shared_dir``)."""
    for line in source.splitlines()[:5]:
        m = _ROLE_PRAGMA_RE.search(line)
        if m:
            return {r.strip() for r in m.group(1).split(",") if r.strip()}
    return None


# --------------------------------------------------------------- resolution

class _Imports(ast.NodeVisitor):
    """name -> dotted origin, so ``obs_trace.phase`` / ``from time import
    time`` call sites resolve to canonical dotted names."""

    def __init__(self) -> None:
        self.origin: Dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.origin[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0])

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        for a in node.names:
            self.origin[a.asname or a.name] = (
                f"{base}.{a.name}" if base else a.name)


def _dotted(node: ast.AST, origin: Dict[str, str]) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(origin.get(node.id, node.id))
    return ".".join(reversed(parts))


def _is_docstring_set(tree: ast.Module) -> Set[int]:
    ids: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                ids.add(id(body[0].value))
    return ids


# -------------------------------------------------------------------- rules

def _check_wallclock(tree: ast.Module, origin: Dict[str, str], rel: str,
                     out: List[Finding]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func, origin)
        if dotted in _WALLCLOCK_CALLS:
            out.append(Finding(
                code="TCDP101", file=rel, line=node.lineno,
                col=node.col_offset,
                message=f"{dotted}() read in a replay-deterministic module; "
                        "thread an injectable clock through instead"))


def _check_atomic_writes(tree: ast.Module, origin: Dict[str, str], rel: str,
                         out: List[Finding]) -> None:
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and origin.get(node.func.id, node.func.id) == "open"):
            continue
        mode = "r"
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
            mode = str(node.args[1].value)
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = str(kw.value.value)
        if not any(c in mode for c in "wx"):
            continue  # read / append modes cannot tear a committed record
        path_src = ast.unparse(node.args[0]) if node.args else ""
        if "tmp" in path_src.lower():
            continue  # tmp sibling, committed by a later os.replace
        out.append(Finding(
            code="TCDP102", file=rel, line=node.lineno, col=node.col_offset,
            message=f"open({path_src or '?'}, {mode!r}) writes a shared-dir "
                    "record in place; write '<path>.<pid>.tmp' and "
                    "os.replace() it"))


def _check_stat_keys(tree: ast.Module, rel: str, out: List[Finding]) -> None:
    from tpu_compressed_dp.obs import registry

    docstrings = _is_docstring_set(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and id(node) not in docstrings):
            continue
        key = node.value
        if STAT_KEY_RE.match(key) and not registry.is_declared(key):
            out.append(Finding(
                code="TCDP103", file=rel, line=node.lineno,
                col=node.col_offset,
                message=f"stat key {key!r} is not declared in "
                        "obs/registry.py"))


def _check_named_scopes(tree: ast.Module, origin: Dict[str, str], rel: str,
                        out: List[Finding]) -> None:
    from tpu_compressed_dp.obs import trace as obs_trace

    if rel.replace(os.sep, "/").endswith("tpu_compressed_dp/obs/trace.py"):
        return  # the taxonomy's own definition site
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func, origin) or ""
        lit = (node.args[0].value
               if node.args and isinstance(node.args[0], ast.Constant)
               and isinstance(node.args[0].value, str) else None)
        if dotted.endswith("jax.named_scope") or dotted == "jax.named_scope":
            if lit is None:
                continue
            name = lit[len("tcdp."):] if lit.startswith("tcdp.") else None
            if name is None or not (name in obs_trace.PHASES
                                    or _CHUNK_SCOPE_RE.match(name)):
                out.append(Finding(
                    code="TCDP104", file=rel, line=node.lineno,
                    col=node.col_offset,
                    message=f"named_scope({lit!r}) outside the tcdp.<phase> "
                            f"taxonomy {obs_trace.PHASES}"))
        elif dotted.endswith("obs.trace.phase") or dotted.endswith(
                "obs_trace.phase"):
            if lit is not None and lit not in obs_trace.PHASES:
                out.append(Finding(
                    code="TCDP104", file=rel, line=node.lineno,
                    col=node.col_offset,
                    message=f"phase({lit!r}) is not a declared phase "
                            f"{obs_trace.PHASES}"))


class _ThreadRule(ast.NodeVisitor):
    """TCDP105: per class, collect lock attributes and Thread targets, then
    require every ``self.<attr> = ...`` inside a target to sit under a
    ``with self.<lock>:`` block."""

    def __init__(self, origin: Dict[str, str], rel: str,
                 out: List[Finding]) -> None:
        self.origin = origin
        self.rel = rel
        self.out = out

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        locks: Set[str] = set()
        targets: List[ast.FunctionDef] = []
        methods = {m.name: m for m in node.body
                   if isinstance(m, ast.FunctionDef)}
        local_funcs: Dict[str, ast.FunctionDef] = {}
        for sub in ast.walk(node):
            if (isinstance(sub, ast.FunctionDef)
                    and sub.name not in methods):
                local_funcs[sub.name] = sub
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and isinstance(sub.value, ast.Call)
                            and _dotted(sub.value.func, self.origin)
                            in _LOCK_FACTORIES):
                        locks.add(t.attr)
            if (isinstance(sub, ast.Call)
                    and (_dotted(sub.func, self.origin) or "").endswith(
                        "threading.Thread")):
                for kw in sub.keywords:
                    if kw.arg != "target":
                        continue
                    fn = None
                    if (isinstance(kw.value, ast.Attribute)
                            and isinstance(kw.value.value, ast.Name)
                            and kw.value.value.id == "self"):
                        fn = methods.get(kw.value.attr)
                    elif isinstance(kw.value, ast.Name):
                        fn = local_funcs.get(kw.value.id)
                    if fn is not None:
                        targets.append(fn)
        for fn in targets:
            self._check_target(fn, locks, node.name)
        self.generic_visit(node)

    def _check_target(self, fn: ast.FunctionDef, locks: Set[str],
                      cls: str) -> None:
        def walk(stmts: Sequence[ast.stmt], guarded: bool) -> None:
            for st in stmts:
                if isinstance(st, ast.With):
                    holds = guarded or any(
                        isinstance(it.context_expr, ast.Attribute)
                        and isinstance(it.context_expr.value, ast.Name)
                        and it.context_expr.value.id == "self"
                        and it.context_expr.attr in locks
                        for it in st.items)
                    walk(st.body, holds)
                    continue
                if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    tgts = (st.targets if isinstance(st, ast.Assign)
                            else [st.target])
                    for t in tgts:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self" and not guarded):
                            self.out.append(Finding(
                                code="TCDP105", file=self.rel,
                                line=st.lineno, col=st.col_offset,
                                message=(
                                    f"self.{t.attr} assigned in thread "
                                    f"target {cls}.{fn.name} without "
                                    f"holding a class lock "
                                    f"({sorted(locks) or 'none declared'})")))
                    continue
                if isinstance(st, ast.Try):
                    walk(st.body, guarded)
                    walk(st.orelse, guarded)
                    walk(st.finalbody, guarded)
                    for h in st.handlers:
                        walk(h.body, guarded)
                elif isinstance(st, (ast.For, ast.While, ast.If)):
                    walk(st.body, guarded)
                    walk(st.orelse, guarded)
                # nested defs are analysed only when they are themselves
                # Thread targets (collected by visit_ClassDef)

        walk(fn.body, guarded=False)


# ------------------------------------------------------------------ drivers

def lint_source(source: str, rel_path: str,
                roles: Optional[Set[str]] = None) -> List[Finding]:
    """Run all five rules over one file's source; returns RAW findings
    (no suppression applied — see :func:`run_host_pass`)."""
    tree = ast.parse(source, filename=rel_path)
    imports = _Imports()
    imports.visit(tree)
    origin = imports.origin
    if roles is None:
        roles = _pragma_roles(source) or roles_for_path(rel_path)
    out: List[Finding] = []
    if "replay" in roles:
        _check_wallclock(tree, origin, rel_path, out)
    if "shared_dir" in roles:
        _check_atomic_writes(tree, origin, rel_path, out)
    if not rel_path.replace(os.sep, "/").endswith(
            "tpu_compressed_dp/obs/registry.py"):
        _check_stat_keys(tree, rel_path, out)
    _check_named_scopes(tree, origin, rel_path, out)
    _ThreadRule(origin, rel_path, out).visit(tree)
    out.sort(key=lambda f: (f.file, f.line, f.code))
    return out


def lint_file(path: str, repo_root: str) -> Tuple[List[Finding], str]:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    rel = os.path.relpath(path, repo_root)
    return lint_source(source, rel), source


def iter_lintable_files(repo_root: str) -> List[str]:
    """Package + tools/ .py files — the scope Pass 2 gates."""
    out: List[str] = []
    for top in ("tpu_compressed_dp", "tools"):
        base = os.path.join(repo_root, top)
        for root, dirs, names in os.walk(base):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            out.extend(os.path.join(root, n) for n in names
                       if n.endswith(".py"))
    return sorted(out)


def run_host_pass(repo_root: str, files: Optional[Iterable[str]] = None,
                  ) -> Tuple[List[Finding], List[Finding]]:
    """Lint the tree (or an explicit file list) and apply suppressions.
    Returns ``(active, suppressed)``."""
    raw: List[Finding] = []
    sources: Dict[str, str] = {}
    for path in (files if files is not None
                 else iter_lintable_files(repo_root)):
        findings, source = lint_file(path, repo_root)
        raw.extend(findings)
        sources[os.path.relpath(path, repo_root)] = source
    return filter_suppressed(raw, sources)
