"""Pass 1 — SPMD safety analysis over traced jaxprs.

The compressed-DP contract is structural: every worker must execute the
*identical* ordered collective program every step, or the fleet deadlocks
(a collective issued by some workers only) or silently diverges (stateful
compressors like PowerSGD make a one-step mismatch sticky).  Runtime
drills catch these on an 8-device mesh in minutes; this pass catches them
at trace time in seconds by walking the ClosedJaxprs of both sync engines
and all three step factories:

  * **TCDP001 — collectives under divergent control flow.**  A collective
    inside only one ``cond`` branch, or inside a ``while`` whose predicate
    derives from float data (loss values, gradient norms — anything that
    can disagree across workers), is the elastic-deadlock shape.  Loops
    with counter-only predicates (``fori_loop``) and ``scan`` (static trip
    count) are symmetric by construction and pass.
  * **TCDP002 — collective-signature determinism.**  The ordered
    (primitive, axis names, operand shapes) sequence must be identical
    across re-traces of one config, equal as a multiset between the
    chunk-pipelined and single-dispatch schedules (the bitwise-equality
    claim of tests/test_overlap.py), and identical between the simulate
    and wire engines where the equivalence tests claim it (dense psum).
  * **TCDP003 — donation that cannot alias.**  Every donated input leaf
    must find a shape/dtype-matching output to alias into; a donated
    buffer with no destination is a wasted donation and a
    read-after-donate hazard on real hardware.
  * **TCDP004 — overlap chunk plan / chain integrity.**  Chunk plans must
    partition the leaf range with strictly increasing, distinct group
    offsets (distinct RNG streams / PowerSGD warm-start keys per chunk),
    and the traced chunked sync must carry ``optimization_barrier`` links
    with a collective ancestor between consecutive chunks — the
    issue-order invariant PR 5's schedule evidence relies on.
  * **TCDP005 — per-config jaxpr size budget.**  Every traced config must
    stay under a fixed equation budget (~3x the measured quick-matrix
    maximum).  The failure this catches is *accidental unrolling*: a
    Python loop over leaves, chunks or devices that should be a
    ``scan``/``fori_loop`` multiplies the trace ~10x, blowing compile
    time and (on the fused-kernel paths) emitting one Pallas call per
    iteration instead of one per payload.

The fused compressor kernels (``ops/kernels.py``) add one more axis to
the matrix: representative fused-path configs are traced under
``pallas_mode`` off AND force, and the ordered collective signature must
be identical between the two — the kernel family is pure local compute,
so toggling it may never add, drop or reorder a collective.

Everything here is pure tracing (``jax.make_jaxpr`` / ``jax.eval_shape``)
— no compilation, no devices beyond the virtual CPU mesh — so the full
matrix runs on CPU in seconds (``tools/tcdp_lint.py``; the quick profile
gates tier-1 via tests/test_lint.py).
"""

from __future__ import annotations

import collections
import itertools
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Set, Tuple)

from tpu_compressed_dp.analysis.report import Finding

__all__ = [
    "COLLECTIVE_PRIMS", "collective_signature", "check_control_flow",
    "check_signature_match", "check_donation", "check_chunk_plan",
    "check_barrier_chain", "count_eqns", "check_jaxpr_budget",
    "EQN_BUDGET_SYNC", "EQN_BUDGET_STEP", "trace_sync", "run_spmd_pass",
    "ENGINE_METHODS",
]

#: primitives that hit the interconnect — any of these inside divergent
#: control flow is a cross-worker deadlock in waiting
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmin", "pmax", "ppermute", "pbroadcast",
    "all_gather", "all_gather_invariant", "all_to_all",
    "reduce_scatter", "psum_scatter",
})

ENGINE_METHODS = (None, "topk", "blocktopk", "randomk", "thresholdv",
                  "adaptive_threshold", "terngrad", "qsgd", "powersgd")

#: signature element: (primitive, axis names, input avals)
Sig = Tuple[str, Tuple[str, ...], Tuple[str, ...]]


# ---------------------------------------------------------- jaxpr plumbing

def _sub_jaxprs(eqn) -> Iterable[Any]:
    """Inner (open) jaxprs of one equation — pjit bodies, cond branches,
    while cond/body, scan bodies, shard_map bodies, custom_* calls."""
    for v in eqn.params.values():
        for x in (v if isinstance(v, (tuple, list)) else (v,)):
            inner = getattr(x, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner
            elif hasattr(x, "eqns"):
                yield x


def _is_var(v) -> bool:
    """True for jaxpr Vars (hashable, traceable to a producer) — excludes
    Literals, which also carry ``.aval`` but are constants."""
    from jax.core import Literal
    return hasattr(v, "aval") and not isinstance(v, Literal)


def _axes_of(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _sig_of(eqn) -> Sig:
    return (eqn.primitive.name, _axes_of(eqn),
            tuple(v.aval.str_short() for v in eqn.invars
                  if hasattr(v, "aval")))


def collective_signature(jaxpr) -> List[Sig]:
    """Ordered collective program of a (Closed)Jaxpr, recursing into every
    sub-jaxpr in equation order."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    out: List[Sig] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            out.append(_sig_of(eqn))
        for sub in _sub_jaxprs(eqn):
            out.extend(collective_signature(sub))
    return out


def _influencing_invars(jaxpr) -> Set[int]:
    """Indices of ``jaxpr.invars`` the outputs transitively depend on."""
    from jax import core  # noqa: F401  (Literal detection below)

    producers: Dict[Any, Any] = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            producers[ov] = eqn
    needed: Set[Any] = set()
    frontier = [v for v in jaxpr.outvars if _is_var(v)]
    while frontier:
        v = frontier.pop()
        if v in needed:
            continue
        needed.add(v)
        eqn = producers.get(v)
        if eqn is not None:
            frontier.extend(iv for iv in eqn.invars if _is_var(iv))
    return {i for i, iv in enumerate(jaxpr.invars) if iv in needed}


def _slice_touches_float(jaxpr, roots) -> bool:
    """True when the backward slice from ``roots`` crosses any
    floating-point value — i.e. the quantity is data-derived, not a
    counter."""
    import jax.numpy as jnp

    producers: Dict[Any, Any] = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            producers[ov] = eqn
    seen: Set[Any] = set()
    frontier = [v for v in roots if _is_var(v)]
    while frontier:
        v = frontier.pop()
        if v in seen:
            continue
        seen.add(v)
        if jnp.issubdtype(v.aval.dtype, jnp.inexact):
            return True
        eqn = producers.get(v)
        if eqn is not None:
            frontier.extend(iv for iv in eqn.invars if _is_var(iv))
    return False


def _while_predicate_data_dependent(eqn) -> bool:
    """Heuristic: a ``while`` predicate is worker-divergent when its
    backward slice (over the loop-carried values feeding it, at the init
    site and through one body application) touches float data.  A pure
    counter loop (``fori_loop``: int carry updated from literals) passes."""
    import jax.numpy as jnp
    from jax.core import Literal

    cond_closed = eqn.params["cond_jaxpr"]
    cj = getattr(cond_closed, "jaxpr", cond_closed)
    n_cc = int(eqn.params.get("cond_nconsts", 0))
    n_bc = int(eqn.params.get("body_nconsts", 0))
    needed = _influencing_invars(cj)
    carry_positions = [i - n_cc for i in needed if i >= n_cc]
    # init operands feeding the predicate
    for i in needed:
        outer_idx = i if i < n_cc else n_cc + n_bc + (i - n_cc)
        v = eqn.invars[outer_idx]
        if isinstance(v, Literal):
            continue
        aval = v.aval
        if jnp.issubdtype(aval.dtype, jnp.inexact) or aval.ndim > 0:
            return True
    # one body application: do the predicate-feeding carry outputs derive
    # from float data?
    body_closed = eqn.params["body_jaxpr"]
    bj = getattr(body_closed, "jaxpr", body_closed)
    roots = [bj.outvars[p] for p in carry_positions
             if p < len(bj.outvars) and _is_var(bj.outvars[p])]
    return _slice_touches_float(bj, roots)


# ------------------------------------------------------------------ checks

def check_control_flow(jaxpr, *, config: str = "") -> List[Finding]:
    """TCDP001 over one (Closed)Jaxpr, recursively."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    out: List[Finding] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "cond":
            branches = eqn.params.get("branches", ())
            sigs = [tuple(collective_signature(b)) for b in branches]
            if len({s for s in sigs}) > 1:
                detail = [f"branch{i}: {len(s)} collectives"
                          for i, s in enumerate(sigs)]
                out.append(Finding(
                    code="TCDP001", config=config,
                    message="collective program differs across cond "
                            f"branches ({', '.join(detail)}) — workers "
                            "taking different branches deadlock"))
        elif name == "while":
            body = eqn.params.get("body_jaxpr")
            cond = eqn.params.get("cond_jaxpr")
            n_coll = (len(collective_signature(body)) if body else 0) + (
                len(collective_signature(cond)) if cond else 0)
            if n_coll and _while_predicate_data_dependent(eqn):
                out.append(Finding(
                    code="TCDP001", config=config,
                    message=f"{n_coll} collective(s) inside a while loop "
                            "with a data-dependent predicate — trip "
                            "counts can diverge across workers"))
        for sub in _sub_jaxprs(eqn):
            out.extend(check_control_flow(sub, config=config))
    return out


def check_signature_match(sig_a: Sequence[Sig], sig_b: Sequence[Sig],
                          label_a: str, label_b: str, *, config: str = "",
                          ordered: bool = True) -> List[Finding]:
    """TCDP002: compare two collective programs, ordered (retrace / engine
    pair) or as multisets (chunked vs single dispatch, where only the
    schedule may differ)."""
    if ordered:
        same = list(sig_a) == list(sig_b)
    else:
        same = (collections.Counter(sig_a) == collections.Counter(sig_b))
    if same:
        return []
    only_a = collections.Counter(sig_a) - collections.Counter(sig_b)
    only_b = collections.Counter(sig_b) - collections.Counter(sig_a)
    detail = ""
    if only_a or only_b:
        detail = (f"; only in {label_a}: {sorted(only_a)[:3]}"
                  f"; only in {label_b}: {sorted(only_b)[:3]}")
    else:
        detail = "; same multiset, different order"
    return [Finding(
        code="TCDP002", config=config,
        message=f"collective signature of {label_a} ({len(sig_a)} colls) != "
                f"{label_b} ({len(sig_b)} colls){detail}")]


def check_donation(fn: Callable, args: Sequence[Any],
                   donate_argnums: Sequence[int], *, config: str = ""
                   ) -> List[Finding]:
    """TCDP003: every donated input leaf must have a shape/dtype-matching
    output leaf left to alias into (multiset matching, XLA's own rule)."""
    import jax

    out_shapes = jax.eval_shape(fn, *args)
    budget = collections.Counter(
        (tuple(l.shape), str(l.dtype)) for l in jax.tree.leaves(out_shapes))
    findings: List[Finding] = []
    for argnum in donate_argnums:
        for leaf in jax.tree.leaves(
                jax.eval_shape(lambda a: a, args[argnum])):
            key = (tuple(leaf.shape), str(leaf.dtype))
            if budget[key] > 0:
                budget[key] -= 1
            else:
                findings.append(Finding(
                    code="TCDP003", config=config,
                    message=f"donated arg {argnum} leaf "
                            f"{leaf.dtype}{list(leaf.shape)} has no "
                            "matching output to alias into"))
    return findings


def check_chunk_plan(plans: Sequence[Any], *, n_leaves: int, n_groups: int,
                     config: str = "") -> List[Finding]:
    """TCDP004 (plan level): chunks partition ``[0, n_leaves)`` in order,
    group offsets are distinct/strictly increasing and consistent with the
    per-chunk group counts — the invariant that gives every chunk its own
    RNG stream and PowerSGD warm-start keys."""
    out: List[Finding] = []

    def bad(msg: str) -> None:
        out.append(Finding(code="TCDP004", config=config,
                           message=f"chunk plan: {msg}"))

    if not plans:
        if n_leaves:
            bad(f"empty plan for {n_leaves} leaves")
        return out
    offs = [p.group_offset for p in plans]
    if len(set(offs)) != len(offs) or offs != sorted(offs):
        bad(f"group offsets not distinct/increasing: {offs}")
    expect = 0
    for p in plans:
        if p.group_offset != expect:
            bad(f"chunk {p.index} group_offset {p.group_offset} != "
                f"running group count {expect} — RNG/warm-start streams "
                "would collide or skip")
            break
        expect += p.n_groups
    if expect != n_groups and not out:
        bad(f"plan covers {expect} groups, tree has {n_groups}")
    lo = 0
    for p in plans:
        if p.leaf_lo != lo:
            bad(f"chunk {p.index} leaf range [{p.leaf_lo},{p.leaf_hi}) "
                f"does not continue at {lo} — chunks must partition the "
                "leaf order")
            break
        lo = p.leaf_hi
    if lo != n_leaves and not any("leaf range" in f.message for f in out):
        bad(f"chunks end at leaf {lo}, tree has {n_leaves}")
    return out


def check_barrier_chain(jaxpr, *, n_chunks: int, config: str = ""
                        ) -> List[Finding]:
    """TCDP004 (jaxpr level): a ``K``-chunk sync must carry ``K-1``
    ``optimization_barrier`` links, each with a collective ancestor — the
    dependency chain that keeps the chunk collectives K separate, ordered
    instructions (defeating XLA's all-reduce combiner)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    chained = 0

    def scan_scope(j) -> None:
        nonlocal total, chained
        producers: Dict[Any, Any] = {}
        for eqn in j.eqns:
            for ov in eqn.outvars:
                producers[ov] = eqn
        for eqn in j.eqns:
            if eqn.primitive.name == "optimization_barrier":
                total += 1
                seen: Set[Any] = set()
                frontier = [v for v in eqn.invars if _is_var(v)]
                hit = False
                while frontier and not hit:
                    v = frontier.pop()
                    if v in seen:
                        continue
                    seen.add(v)
                    p = producers.get(v)
                    if p is None:
                        continue
                    if (p.primitive.name in COLLECTIVE_PRIMS
                            or any(collective_signature(s)
                                   for s in _sub_jaxprs(p))):
                        hit = True
                        break
                    frontier.extend(iv for iv in p.invars
                                    if _is_var(iv))
                chained += bool(hit)
            for sub in _sub_jaxprs(eqn):
                scan_scope(sub)

    scan_scope(jaxpr)
    need = max(0, int(n_chunks) - 1)
    if total < need or chained < need:
        return [Finding(
            code="TCDP004", config=config,
            message=f"{n_chunks}-chunk sync carries {total} "
                    f"optimization_barrier(s), {chained} with a collective "
                    f"ancestor; need >= {need} chained barriers to pin "
                    "chunk issue order")]
    return []


#: TCDP005 budgets — measured 2026-08 quick-matrix maxima (~500 eqns for a
#: sync trace, ~1530 for the LM step) with ~3x headroom.  An unrolled
#: 11-leaf loop multiplies a trace ~10x, so it trips the budget long
#: before trace time becomes painful.  Default-mode traces only: force
#: mode off-TPU runs kernels under the Pallas interpreter, which inlines
#: kernel bodies into the jaxpr and is not what ships.
EQN_BUDGET_SYNC = 1500
EQN_BUDGET_STEP = 4500


def count_eqns(jaxpr) -> int:
    """Total equation count of a (Closed)Jaxpr, recursing into every
    sub-jaxpr — the size measure TCDP005 budgets.  Loop bodies count ONCE
    (a ``scan`` over K chunks adds its body once), which is exactly why
    the budget separates rolled from unrolled programs."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    n = 0
    for eqn in jaxpr.eqns:
        n += 1
        for sub in _sub_jaxprs(eqn):
            n += count_eqns(sub)
    return n


def check_jaxpr_budget(jaxpr, *, budget: int, config: str = ""
                       ) -> List[Finding]:
    """TCDP005: one traced config must fit its equation budget."""
    n = count_eqns(jaxpr)
    if n > budget:
        return [Finding(
            code="TCDP005", config=config,
            message=f"traced jaxpr holds {n} equations, budget {budget} — "
                    "a Python loop over leaves/chunks/devices is probably "
                    "unrolling into the trace (use scan/fori_loop, or raise "
                    "the budget with a justification if growth is real)")]
    return []


# -------------------------------------------------------- tracing the tree

def _mesh(n: int):
    from tpu_compressed_dp.parallel.mesh import make_data_mesh
    return make_data_mesh(n)


def _grads():
    import jax.numpy as jnp
    return {"w": jnp.zeros((64, 8)), "b": jnp.zeros((8,)),
            "v": jnp.zeros((32, 4))}


def trace_sync(cfg, mesh, *, chunked: bool = False):
    """Trace one engine config under shard_map to a ClosedJaxpr (returns
    ``(closed_jaxpr, n_leaves, n_groups, plans)``; plans is None unless
    ``chunked``)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_compressed_dp.compat import shard_map
    from tpu_compressed_dp.parallel import dp, overlap

    grads = _grads()
    leaves = jax.tree.leaves(grads)
    byte_sizes = [l.size * l.dtype.itemsize for l in leaves]
    groups = dp.make_leaf_groups(byte_sizes, cfg.granularity,
                                 cfg.bucket_mb * dp.BUCKET_MB)
    plans = overlap.plan_chunks(byte_sizes, cfg) if chunked else None
    sync = (overlap.make_chunked_grad_sync(cfg) if chunked
            else dp.make_grad_sync(cfg))
    ef = (jax.tree.map(lambda g: jnp.zeros(g.shape, jnp.float32), grads)
          if cfg.error_feedback else ())
    comp = dp.init_comp_state(grads, cfg)

    def f(g, e, c, k):
        return sync(g, e, c, k, ok=jnp.asarray(True))

    sm = shard_map(f, mesh=mesh, in_specs=(P(), P(), P(), P()),
                   out_specs=P())
    closed = jax.make_jaxpr(sm)(grads, ef, comp, jax.random.key(0))
    return closed, len(leaves), len(groups), plans


def _engine_configs(profile: str):
    from tpu_compressed_dp.parallel.dp import CompressionConfig

    def mk(m, mode, transport, gran, **kw):
        ef = kw.pop("error_feedback", m not in (None, "terngrad", "qsgd"))
        if transport == "hierarchical":
            # 2x2 virtual mesh on the lint pass's 4-device data axis —
            # exercises both the grouped ICI psums and the grouped DCN
            # route/return collectives
            kw.setdefault("dp_pods", 2)
        return CompressionConfig(method=m, granularity=gran, mode=mode,
                                 transport=transport, ratio=0.25,
                                 error_feedback=ef, check_sync=True, **kw)

    if profile == "full":
        return [mk(m, mode, tr, gran) for m, mode, tr, gran in
                itertools.product(ENGINE_METHODS, ("simulate", "wire"),
                                  ("allgather", "sharded", "hierarchical"),
                                  ("layerwise", "entiremodel", "bucketed"))]
    # quick: each method once on the wire path, plus transport/granularity
    # variants for the index-carrying representative
    cfgs = [mk(m, "wire", "allgather", "bucketed") for m in ENGINE_METHODS]
    cfgs += [mk("topk", "wire", "sharded", "bucketed"),
             mk("topk", "wire", "hierarchical", "bucketed"),
             mk("thresholdv", "wire", "hierarchical", "entiremodel"),
             mk("topk", "simulate", "hierarchical", "bucketed"),
             mk("topk", "wire", "allgather", "layerwise"),
             mk("topk", "wire", "allgather", "entiremodel"),
             mk("topk", "simulate", "allgather", "bucketed")]
    return cfgs


def _cfg_label(cfg, suffix: str = "") -> str:
    lab = (f"{cfg.method or 'none'}/{cfg.mode}/{cfg.transport}/"
           f"{cfg.granularity}/ef={int(cfg.error_feedback)}")
    return f"{lab}{suffix}"


def _chunk_configs(profile: str):
    from tpu_compressed_dp.parallel.dp import CompressionConfig

    methods = (ENGINE_METHODS if profile == "full"
               else (None, "topk", "powersgd"))
    return [CompressionConfig(method=m, granularity="layerwise", mode="wire",
                              transport="allgather", ratio=0.25,
                              error_feedback=m not in (None, "terngrad",
                                                       "qsgd"),
                              check_sync=True, sync_overlap=3)
            for m in methods]


def _check_engines(profile: str, mesh) -> Tuple[List[Finding], int]:
    findings: List[Finding] = []
    n = 0
    sig_cache: Dict[str, List[Sig]] = {}
    for cfg in _engine_configs(profile):
        label = _cfg_label(cfg)
        closed, _, _, _ = trace_sync(cfg, mesh)
        closed2, _, _, _ = trace_sync(cfg, mesh)
        n += 2
        findings += check_control_flow(closed, config=label)
        findings += check_jaxpr_budget(closed, budget=EQN_BUDGET_SYNC,
                                       config=label)
        sig = collective_signature(closed)
        findings += check_signature_match(
            sig, collective_signature(closed2), "trace#1", "trace#2",
            config=label)
        sig_cache[label] = sig
    # simulate == wire where the equivalence tests claim it: the dense psum
    # path (method None) is shared by construction
    for tr in ("allgather",):
        a = sig_cache.get(f"none/simulate/{tr}/bucketed/ef=0")
        b = sig_cache.get(f"none/wire/{tr}/bucketed/ef=0")
        if a is not None and b is not None:
            findings += check_signature_match(
                a, b, "simulate engine", "wire engine",
                config=f"none/{tr}/bucketed")
    # chunk-pipelined schedule: same collectives, chained issue order
    import dataclasses
    for cfg in _chunk_configs(profile):
        label = _cfg_label(cfg, suffix=f"/overlap={cfg.sync_overlap}")
        chunked, n_leaves, n_groups, plans = trace_sync(cfg, mesh,
                                                        chunked=True)
        single, _, _, _ = trace_sync(
            dataclasses.replace(cfg, sync_overlap=1), mesh)
        n += 2
        findings += check_control_flow(chunked, config=label)
        findings += check_jaxpr_budget(chunked, budget=EQN_BUDGET_SYNC,
                                       config=label)
        findings += check_chunk_plan(plans, n_leaves=n_leaves,
                                     n_groups=n_groups, config=label)
        findings += check_signature_match(
            collective_signature(chunked), collective_signature(single),
            "chunked", "single-dispatch", config=label, ordered=False)
        findings += check_barrier_chain(chunked, n_chunks=len(plans),
                                        config=label)
    findings_p, n_p = _check_pallas_variants(profile, mesh)
    return findings + findings_p, n + n_p


def _pallas_variant_configs(profile: str):
    """Fused-kernel representatives: one per kernel family (select+pack on
    allgather, bucket-route on sharded/hierarchical, quantize+pack for
    terngrad/qsgd) — the paths where ``pallas_mode`` changes the emitted
    step program."""
    from tpu_compressed_dp.parallel.dp import CompressionConfig

    def mk(m, transport, **kw):
        if transport == "hierarchical":
            kw.setdefault("dp_pods", 2)
        return CompressionConfig(method=m, granularity="entiremodel",
                                 mode="wire", transport=transport,
                                 ratio=0.25,
                                 error_feedback=m not in ("terngrad", "qsgd"),
                                 check_sync=True, **kw)

    # quick: one select+pack path and one quantize+pack path (the force
    # traces inline interpreted kernel bodies, so each pair costs ~1 s —
    # the quick gate rides tier-1's wall budget); full: every family x
    # transport representative
    cfgs = [mk("topk", "allgather"), mk("terngrad", "allgather")]
    if profile == "full":
        cfgs += [mk("topk", "sharded"), mk("qsgd", "allgather"),
                 mk("topk", "hierarchical"), mk("blocktopk", "sharded"),
                 mk("thresholdv", "hierarchical"),
                 mk("adaptive_threshold", "allgather")]
    return cfgs


def _check_pallas_variants(profile: str, mesh) -> Tuple[List[Finding], int]:
    """TCDP002 across the ``pallas_mode`` toggle: the fused kernels are
    pure local compute, so forcing them on (or off) may never add, drop
    or reorder a collective relative to the XLA fallback chain.  Traced
    only — ``make_jaxpr`` abstract-evals the pallas_call, so this pins the
    TPU dispatch shape from the CPU lint pass."""
    from tpu_compressed_dp.ops import kernels

    findings: List[Finding] = []
    n = 0
    for cfg in _pallas_variant_configs(profile):
        label = _cfg_label(cfg, suffix="/pallas")
        prev = kernels.pallas_mode()
        try:
            kernels.set_pallas_mode("off")
            off_closed, _, _, _ = trace_sync(cfg, mesh)
            kernels.set_pallas_mode("force")
            on_closed, _, _, _ = trace_sync(cfg, mesh)
        finally:
            kernels.set_pallas_mode(prev)
        n += 2
        findings += check_control_flow(on_closed, config=label)
        findings += check_signature_match(
            collective_signature(off_closed), collective_signature(on_closed),
            "pallas=off", "pallas=force", config=label)
        # budget the off trace only: force off-TPU interprets, inlining
        # kernel bodies the shipped program never holds
        findings += check_jaxpr_budget(off_closed, budget=EQN_BUDGET_SYNC,
                                       config=label)
    return findings, n


def _check_train_step(profile: str) -> Tuple[List[Finding], int]:
    """Trace the pure-DP train step factory (donation on, guard on, and an
    overlap variant) and run all four checks."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    import flax.linen as nn
    from tpu_compressed_dp.models.common import init_model, make_apply_fn
    from tpu_compressed_dp.parallel.dp import (CompressionConfig,
                                               init_comp_state,
                                               init_ef_state)
    from tpu_compressed_dp.train.guard import GuardConfig, init_guard_state
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState
    from tpu_compressed_dp.train.step import make_train_step

    class _Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(4)(nn.relu(nn.Dense(16)(x)))

    mesh = _mesh(4)
    module = _Tiny()
    params, stats = init_model(module, jax.random.key(0),
                               jnp.zeros((1, 8, 8, 3), jnp.float32))
    opt = SGD(lr=0.05, momentum=0.9)
    apply_fn = make_apply_fn(module)
    batch = {"input": jnp.zeros((8, 8, 8, 3), jnp.float32),
             "target": jnp.zeros((8,), jnp.int32)}

    cfgs = [CompressionConfig(method="topk", ratio=0.25,
                              error_feedback=True),
            CompressionConfig(method="topk", ratio=0.25, error_feedback=True,
                              sync_overlap=3, granularity="layerwise")]
    if profile == "full":
        cfgs += [CompressionConfig(method=None),
                 CompressionConfig(method="powersgd", rank=2,
                                   error_feedback=True),
                 CompressionConfig(method="qsgd", mode="wire")]

    findings: List[Finding] = []
    n = 0
    guard_cfg = GuardConfig()
    for cfg in cfgs:
        label = _cfg_label(cfg, suffix=f"/step(overlap={cfg.sync_overlap})")
        ef = init_ef_state(params, cfg, num_devices=mesh.shape["data"])
        comp = init_comp_state(params, cfg, num_devices=mesh.shape["data"])
        state = TrainState.create(params, stats, opt.init(params), ef,
                                  jax.random.key(1), comp=comp,
                                  guard=init_guard_state(guard_cfg))
        step = make_train_step(apply_fn, opt, cfg, mesh, grad_scale=1.0,
                               donate=True, guard_cfg=guard_cfg)
        closed = jax.make_jaxpr(step)(state, batch)
        n += 1
        findings += check_control_flow(closed, config=label)
        findings += check_jaxpr_budget(closed, budget=EQN_BUDGET_STEP,
                                       config=label)
        findings += check_donation(step, (state, batch), (0,), config=label)
        if profile == "full":
            closed2 = jax.make_jaxpr(step)(state, batch)
            n += 1
            findings += check_signature_match(
                collective_signature(closed), collective_signature(closed2),
                "trace#1", "trace#2", config=label)
        if cfg.sync_overlap > 1:
            from tpu_compressed_dp.parallel.dp import (BUCKET_MB,
                                                       make_leaf_groups)
            from tpu_compressed_dp.parallel.overlap import plan_chunks
            byte_sizes = [l.size * l.dtype.itemsize
                          for l in jax.tree.leaves(params)]
            plans = plan_chunks(byte_sizes, cfg)
            findings += check_chunk_plan(
                plans, n_leaves=len(byte_sizes),
                n_groups=len(make_leaf_groups(byte_sizes, cfg.granularity,
                                              cfg.bucket_mb * BUCKET_MB)),
                config=label)
            findings += check_barrier_chain(closed, n_chunks=len(plans),
                                            config=label)
    # adaptive-controller rung pair: a rung switch swaps one trace-cached
    # step variant for another, and TCDP002 pins the only thing allowed to
    # change — the k-dependent operand SHAPES.  The ordered (primitive,
    # axis) program must be identical across rungs, or a mid-run switch
    # would reorder/add collectives and desync any worker that traced the
    # other rung.
    from tpu_compressed_dp.control import (ControlConfig, comp_for_rung,
                                           init_control_state)

    ctrl = ControlConfig(method="topk", rungs=(0.25, 0.125))
    rung_sigs = {}
    for rung in (0, 1):
        rcfg = comp_for_rung(cfgs[0], ctrl, rung)
        label = _cfg_label(rcfg, suffix=f"/step(rung={rung})")
        ef = init_ef_state(params, rcfg, num_devices=mesh.shape["data"])
        comp = init_comp_state(params, rcfg, num_devices=mesh.shape["data"])
        state = TrainState.create(params, stats, opt.init(params), ef,
                                  jax.random.key(1), comp=comp,
                                  guard=init_guard_state(guard_cfg),
                                  control=init_control_state(ctrl))
        step = make_train_step(apply_fn, opt, rcfg, mesh, grad_scale=1.0,
                               donate=True, guard_cfg=guard_cfg)
        closed = jax.make_jaxpr(step)(state, batch)
        n += 1
        findings += check_control_flow(closed, config=label)
        findings += check_jaxpr_budget(closed, budget=EQN_BUDGET_STEP,
                                       config=label)
        findings += check_donation(step, (state, batch), (0,), config=label)
        rung_sigs[rung] = collective_signature(closed)
    findings += check_signature_match(
        [s[:2] for s in rung_sigs[0]], [s[:2] for s in rung_sigs[1]],
        "rung0 (prim, axes)", "rung1 (prim, axes)",
        config="topk/step(rung-pair)")
    return findings, n


def _check_lm_step(profile: str) -> Tuple[List[Finding], int]:
    import jax

    from tpu_compressed_dp.models import transformer as tf
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.lm_step import (init_lm_ef_state,
                                                 make_lm_mesh,
                                                 make_lm_train_step)
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState

    cfg = tf.LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                         n_kv_heads=2, ffn_hidden=64, dtype=jax.numpy.float32)
    mesh = make_lm_mesh(2, 2, 2)
    params = tf.init_llama(cfg, jax.random.key(0))
    opt = SGD(lr=0.1, momentum=0.9)
    comp = CompressionConfig(method="topk", granularity="entiremodel",
                             ratio=0.05, error_feedback=True)
    state = TrainState.create(params, {}, opt.init(params),
                              init_lm_ef_state(cfg, params, comp, mesh),
                              jax.random.key(1))
    step = make_lm_train_step(cfg, opt, comp, mesh, donate=True)
    batch = {"input": jax.numpy.zeros((4, 16), jax.numpy.int32),
             "target": jax.numpy.zeros((4, 16), jax.numpy.int32)}
    label = "lm_step/topk/entiremodel/ef=1"
    closed = jax.make_jaxpr(step)(state, batch)
    findings = check_control_flow(closed, config=label)
    findings += check_jaxpr_budget(closed, budget=EQN_BUDGET_STEP,
                                   config=label)
    findings += check_donation(step, (state, batch), (0,), config=label)
    n = 1
    if profile == "full":
        closed2 = jax.make_jaxpr(step)(state, batch)
        n += 1
        findings += check_signature_match(
            collective_signature(closed), collective_signature(closed2),
            "trace#1", "trace#2", config=label)
    return findings, n


def _check_pp_step(profile: str) -> Tuple[List[Finding], int]:
    import jax

    from tpu_compressed_dp.models import transformer as tf
    from tpu_compressed_dp.parallel.dp import CompressionConfig
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.pp_step import (init_pp_ef_state,
                                                 make_pp_mesh,
                                                 make_pp_train_step,
                                                 stack_layer_params)
    from tpu_compressed_dp.train.state import TrainState

    cfg = tf.LlamaConfig(vocab_size=64, dim=32, n_layers=4, n_heads=4,
                         n_kv_heads=2, ffn_hidden=64, dtype=jax.numpy.float32)
    mesh = make_pp_mesh(2, 2)
    comp = CompressionConfig(method="topk", granularity="entiremodel",
                             ratio=0.05, error_feedback=True)
    params = stack_layer_params(tf.init_llama(cfg, jax.random.key(0)))
    opt = SGD(lr=0.1, momentum=0.9)
    state = TrainState.create(params, {}, opt.init(params),
                              init_pp_ef_state(cfg, params, comp, mesh),
                              jax.random.key(3))
    step = make_pp_train_step(cfg, opt, comp, mesh, microbatches=2,
                              donate=True)
    batch = {"input": jax.numpy.zeros((8, 16), jax.numpy.int32),
             "target": jax.numpy.zeros((8, 16), jax.numpy.int32)}
    label = "pp_step/topk/entiremodel/ef=1"
    closed = jax.make_jaxpr(step)(state, batch)
    findings = check_control_flow(closed, config=label)
    findings += check_jaxpr_budget(closed, budget=EQN_BUDGET_STEP,
                                   config=label)
    findings += check_donation(step, (state, batch), (0,), config=label)
    n = 1
    if profile == "full":
        closed2 = jax.make_jaxpr(step)(state, batch)
        n += 1
        findings += check_signature_match(
            collective_signature(closed), collective_signature(closed2),
            "trace#1", "trace#2", config=label)
    return findings, n


def run_spmd_pass(profile: str = "quick") -> Tuple[List[Finding],
                                                   Dict[str, int]]:
    """Trace the real tree and run every check.  ``profile='quick'`` is the
    tier-1 gate (each method + the structural variants); ``'full'`` is the
    CLI's complete method x mode x transport x granularity matrix."""
    import jax

    if len(jax.devices()) < 4:
        raise RuntimeError(
            "tcdp-lint pass 1 needs >= 4 devices (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    mesh = _mesh(4)
    findings: List[Finding] = []
    traced = 0
    for part in (lambda: _check_engines(profile, mesh),
                 lambda: _check_train_step(profile),
                 lambda: _check_lm_step(profile),
                 lambda: _check_pp_step(profile)):
        f, n = part()
        findings += f
        traced += n
    return findings, {"configs_traced": traced}
