"""Finding record + rule-code table shared by both tcdp-lint passes.

Every rule carries a stable ``TCDPxxx`` code (0xx = jaxpr/SPMD pass, 1xx =
host AST pass) so suppressions, JSON consumers and the README rule table
never drift from the implementation: :data:`CODES` IS the table.

Suppression is per-line and must be justified::

    x = time.time()  # tcdp-lint: disable=TCDP101 -- display-only banner ts

The comment may sit on the flagged line or alone on the line above; the
``-- <why>`` justification is REQUIRED — a bare disable is itself a
finding (``TCDP100``), so the escape hatch documents the exception instead
of hiding it.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "CODES", "Finding", "parse_disables", "filter_suppressed",
    "format_findings", "findings_to_json",
]

#: rule code -> one-line description (the README table is generated from
#: this mapping; tests assert the two stay in sync)
CODES: Dict[str, str] = {
    # pass 1 — SPMD / jaxpr analyzer (analysis/spmd.py)
    "TCDP001": "collective primitive under data-dependent/divergent control "
               "flow (cond branch asymmetry or data-predicated while)",
    "TCDP002": "collective signature diverges across re-traces, chunked vs "
               "single dispatch, or claimed-equivalent engine pairs",
    "TCDP003": "donated buffer with no shape/dtype-matching output to alias "
               "(wasted donation -> read-after-donate hazard)",
    "TCDP004": "overlap chunk plan or optimization_barrier chain broken "
               "(duplicate group offsets, non-partitioning chunks, "
               "unchained chunk collectives)",
    "TCDP005": "traced config exceeds its jaxpr equation budget — a "
               "leaf/chunk/device loop is unrolling into the trace",
    # pass 2 — host-side AST linter (analysis/hostlint.py)
    "TCDP100": "tcdp-lint disable comment without '-- <justification>'",
    "TCDP101": "wall-clock read (time.time / datetime.now) in a "
               "replay-deterministic module — inject a clock instead",
    "TCDP102": "non-atomic write in a shared-dir protocol module — write a "
               "*.tmp sibling and os.replace() it",
    "TCDP103": "stat-key string literal not declared in obs/registry.py",
    "TCDP104": "named_scope / phase string outside the tcdp.<phase> "
               "taxonomy",
    "TCDP105": "attribute mutated from a spawned thread without holding "
               "the class's lock",
}

_DISABLE_RE = re.compile(
    r"#\s*tcdp-lint:\s*disable=(?P<codes>TCDP\d{3}(?:\s*,\s*TCDP\d{3})*)"
    r"(?:\s*--\s*(?P<why>\S.*))?")


@dataclasses.dataclass
class Finding:
    """One analyzer hit.  ``file``/``line`` are empty for pass-1 findings
    raised against a traced config rather than a source location — there
    ``config`` names the (method, mode, transport, ...) combination."""

    code: str
    message: str
    file: str = ""
    line: int = 0
    col: int = 0
    config: str = ""
    suppressed: bool = False
    justification: str = ""

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d["description"] = CODES.get(self.code, "")
        return d

    def location(self) -> str:
        if self.file:
            return f"{self.file}:{self.line}" if self.line else self.file
        return f"<{self.config}>" if self.config else "<traced>"


def parse_disables(source: str) -> Dict[int, Tuple[Tuple[str, ...], str]]:
    """Map 1-based line number -> (codes, justification) for every line a
    disable comment covers (its own line, plus the next line when the
    comment stands alone).  Comments with a missing justification still
    suppress — the TCDP100 finding they raise is the enforcement."""
    out: Dict[int, Tuple[Tuple[str, ...], str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        codes = tuple(c.strip() for c in m.group("codes").split(","))
        why = (m.group("why") or "").strip()
        out[i] = (codes, why)
        if text.lstrip().startswith("#"):  # own-line comment guards the next
            out.setdefault(i + 1, (codes, why))
    return out


def filter_suppressed(findings: Iterable[Finding], source_by_file: Dict[str, str],
                      ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (active, suppressed), marking suppressed ones and
    appending a TCDP100 active finding for each justification-free disable
    comment that actually suppressed something."""
    active: List[Finding] = []
    suppressed: List[Finding] = []
    disables_cache: Dict[str, Dict[int, Tuple[Tuple[str, ...], str]]] = {}
    for f in findings:
        src = source_by_file.get(f.file)
        if src is None or not f.line:
            active.append(f)
            continue
        if f.file not in disables_cache:
            disables_cache[f.file] = parse_disables(src)
        hit = disables_cache[f.file].get(f.line)
        if hit is None or f.code not in hit[0]:
            active.append(f)
            continue
        f.suppressed = True
        f.justification = hit[1]
        suppressed.append(f)
        if not hit[1]:
            active.append(Finding(
                code="TCDP100", file=f.file, line=f.line,
                message=f"disable={f.code} has no '-- <justification>'"))
    return active, suppressed


def format_findings(findings: Sequence[Finding], *, color: Optional[bool] = None
                    ) -> str:
    lines = []
    for f in findings:
        tag = " (suppressed)" if f.suppressed else ""
        lines.append(f"{f.location()}: {f.code}{tag}: {f.message}")
    return "\n".join(lines)


def findings_to_json(active: Sequence[Finding],
                     suppressed: Sequence[Finding] = ()) -> Dict[str, object]:
    """JSON-serialisable report payload (callers ``json.dump`` it)."""
    return {
        "version": 1,
        "active": [f.to_dict() for f in active],
        "suppressed": [f.to_dict() for f in suppressed],
        "counts": {
            "active": len(active),
            "suppressed": len(suppressed),
        },
    }
