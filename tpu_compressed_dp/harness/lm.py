"""Llama pretrain harness — the BASELINE.json stretch config driver.

No reference equivalent exists (the reference trains CNNs only); the flag
surface follows the CNN harnesses where concepts coincide (compression
config, checkpointing, logging) and adds the mesh/model axes.  The headline
configuration is ``--preset llama3_8b --compress entiremodel --method topk``:
entire-model Top-K gradient compression over ICI, with tensor and sequence
parallelism inside the chip mesh.

Smoke run (CPU, 8 virtual devices):
  ``python -m tpu_compressed_dp.harness.lm --preset tiny --dp 2 --sp 2
  --tp 2 --steps 20 --seq_len 64 --global_batch 8``
"""

from __future__ import annotations

import argparse
import contextlib
import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_compressed_dp.data import lm as lm_data
from tpu_compressed_dp.models import transformer as tf
from tpu_compressed_dp.parallel.dp import CompressionConfig
from tpu_compressed_dp.train.lm_step import (
    init_lm_ef_state,
    make_lm_mesh,
    make_lm_train_step,
)
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.schedules import piecewise_linear
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.utils import resilience
from tpu_compressed_dp.utils.checkpoint import Checkpointer
from tpu_compressed_dp.utils.loggers import TableLogger

PRESETS = {
    "tiny": tf.tiny_llama,
    "llama3_8b": tf.llama3_8b,
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Llama pretrain, compressed-DP over (data, seq, tensor) mesh")
    p.add_argument("--preset", default="tiny", choices=sorted(PRESETS))
    p.add_argument("--vocab", type=int, default=None)
    p.add_argument("--dim", type=int, default=None)
    p.add_argument("--layers", type=int, default=None)
    p.add_argument("--heads", type=int, default=None)
    p.add_argument("--kv_heads", type=int, default=None)
    p.add_argument("--ffn", type=int, default=None)
    p.add_argument("--experts", type=int, default=None,
                   help="MoE expert count (0/unset = dense FFN)")
    p.add_argument("--moe_every", type=int, default=None)
    p.add_argument("--capacity_factor", type=float, default=None)
    p.add_argument("--fp32", action="store_true", help="disable bf16 compute")
    p.add_argument("--remat", action="store_true",
                   help="rematerialise layers in backward (jax.checkpoint)")
    # mesh
    p.add_argument("--dp", type=int, default=None, help="data axis size (default: all devices)")
    p.add_argument("--sp", type=int, default=1, help="sequence axis size")
    p.add_argument("--tp", type=int, default=1, help="tensor axis size")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline stages (the (data, seq, pipe, tensor) "
                        "step; composes with --sp and --tp)")
    p.add_argument("--microbatches", type=int, default=4,
                   help="pipeline microbatches per step (--pp > 1 only)")
    # data/schedule
    p.add_argument("--corpus", type=str, default=None, help="byte-level text file; default synthetic")
    p.add_argument("--seq_len", type=int, default=512)
    p.add_argument("--global_batch", type=int, default=32)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--warmup_steps", type=int, default=10)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight_decay", type=float, default=0.0)
    p.add_argument("--clip_norm", type=float, default=0.0,
                   help="local-gradient L2 clip (0=off) — EF+momentum "
                        "stabiliser (see tools/ef_bisect.py)")
    p.add_argument("--clip_sent_norm", type=float, default=0.0,
                   help="post-aggregation L2 clip of the synced gradient "
                        "(bounds the EF residual spike)")
    # compression (same surface as the CNN harnesses)
    p.add_argument("--compress", "-c", default="none", choices=["none", "layerwise", "entiremodel", "bucketed"])
    p.add_argument("--method", default="none")
    p.add_argument("--ratio", "-K", type=float, default=0.01)
    p.add_argument("--threshold", "-V", type=float, default=0.001)
    p.add_argument("--qstates", "-Q", type=int, default=255)
    p.add_argument("--rank", type=int, default=4,
                   help="r for powersgd (psum-ring low-rank factors)")
    p.add_argument("--block_size", type=int, default=256,
                   help="blocktopk: elements per contiguous block")
    p.add_argument("--bucket_mb", type=float, default=25.0,
                   help="bucketed granularity: capacity per bucket")
    p.add_argument("--wire_cap_ratio", type=float, default=0.05,
                   help="wire thresholdv/adaptive_threshold transport "
                        "capacity (fraction of elements)")
    p.add_argument("--mode", default="simulate", choices=["simulate", "wire"])
    p.add_argument("--transport", default="allgather",
                   choices=["allgather", "sharded", "hierarchical"],
                   help="wire combine for index-carrying sparsifiers: flat "
                        "all_gather (O(W*k)/chip), owner-sharded reduce "
                        "(O(k + n/W)/chip, ops/wire_sharded.py; size caps "
                        "via comm/shard_overflow), or the two-level "
                        "hierarchical reduce over a --dp_pods x chips "
                        "virtual mesh (O(k + n/W_pods) DCN bytes)")
    p.add_argument("--error_feedback", action="store_true")
    p.add_argument("--overlap", type=int, default=1,
                   help="chunk-pipelined sync (parallel/overlap.py): up to "
                        "K reverse-topological chunk collectives per "
                        "replication signature, interleaved with backward "
                        "compute; numerics unchanged (1 = single dispatch)")
    # robustness: shared --guard*/--chaos/--heartbeat surface
    from tpu_compressed_dp.harness.loop import (add_adaptive_args,
                                                add_robustness_args,
                                                add_stream_args,
                                                add_telemetry_args,
                                                add_topology_args)

    add_topology_args(p)
    add_robustness_args(p, check_note="checked every --log_every")
    # delta state streaming: shared --stream* surface (stream/)
    add_stream_args(p, cadence_help="steps between delta-stream appends "
                                    "(requires --stream_dir; 0 disables "
                                    "the periodic append)")
    # adaptive compression: shared --adaptive* surface (control/); the LM
    # loop's decision cadence is the --log_every metric-fetch window
    add_adaptive_args(p)
    # telemetry: shared --events/--prom surface (obs/export.py)
    add_telemetry_args(p)
    p.add_argument("--logdir", type=str, default=None,
                   help="output dir for profiler traces")
    p.add_argument("--profile_epoch", type=int, default=None,
                   help="jax.profiler-trace the Nth --log_every window of "
                        "steps to <logdir>/profile (the LM loop's 'epoch' "
                        "is one log window)")
    # plumbing
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log_every", type=int, default=10)
    p.add_argument("--checkpoint_dir", type=str, default=None)
    p.add_argument("--ckpt_every", type=int, default=0,
                   help="steps between async checkpoint saves (requires "
                        "--checkpoint_dir; 0 = final/emergency saves only)")
    p.add_argument("--resume", type=str, default=None)
    p.add_argument("--coordinator", type=str, default=None)
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    return p


def build_config(args) -> tf.LlamaConfig:
    import dataclasses

    cfg = PRESETS[args.preset]()
    overrides = {}
    for field, arg in [("vocab_size", args.vocab), ("dim", args.dim),
                       ("n_layers", args.layers), ("n_heads", args.heads),
                       ("n_kv_heads", args.kv_heads), ("ffn_hidden", args.ffn),
                       ("n_experts", args.experts), ("moe_every", args.moe_every),
                       ("capacity_factor", args.capacity_factor)]:
        if arg is not None:
            overrides[field] = arg
    if args.fp32:
        overrides["dtype"] = jnp.float32
    if args.remat:
        overrides["remat"] = True
    return dataclasses.replace(cfg, **overrides)


def run(args) -> Dict[str, float]:
    if args.method.lower() != "none" and args.compress == "none":
        raise ValueError(f"--method {args.method} requires --compress layerwise|entiremodel")
    from tpu_compressed_dp.harness.loop import elastic_distributed_init

    rejoin = elastic_distributed_init(args)
    ndev = len(jax.devices())
    pipelined = args.pp > 1
    dp = args.dp if args.dp is not None else ndev // (args.sp * args.tp * args.pp)
    if pipelined:
        from tpu_compressed_dp.train.pp_step import make_pp_mesh

        mesh = make_pp_mesh(dp, args.pp, args.tp, args.sp)
    else:
        mesh = make_lm_mesh(dp, args.sp, args.tp)
    cfg = build_config(args)
    cfg.validate_mesh(args.tp)

    if args.global_batch % (dp * (args.microbatches if pipelined else 1)):
        raise ValueError(f"--global_batch {args.global_batch} must divide by "
                         f"dp*microbatches")
    if args.seq_len % args.sp:
        raise ValueError(f"--seq_len {args.seq_len} must divide by sp={args.sp}")

    if args.corpus:
        ds = lm_data.ByteCorpus(args.corpus, args.seq_len, args.global_batch,
                                seed=args.seed)
        if ds.vocab != cfg.vocab_size:
            import dataclasses

            cfg = dataclasses.replace(cfg, vocab_size=ds.vocab)
    else:
        ds = lm_data.SyntheticTokens(cfg.vocab_size, args.seq_len,
                                     args.global_batch, seed=args.seed)

    params = tf.init_llama(cfg, jax.random.key(args.seed))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    sched = piecewise_linear(
        [0, max(args.warmup_steps, 1), max(args.steps, args.warmup_steps + 1)],
        [0.0, args.lr, args.lr * 0.1],
    )
    opt = SGD(lr=sched, momentum=args.momentum, weight_decay=args.weight_decay)
    comp = CompressionConfig(
        method=None if args.compress == "none" or args.method.lower() == "none" else args.method,
        granularity=args.compress if args.compress != "none" else "layerwise",
        mode=args.mode, ratio=args.ratio, threshold=args.threshold,
        qstates=args.qstates, block_size=args.block_size,
        bucket_mb=args.bucket_mb,
        wire_cap_ratio=args.wire_cap_ratio,
        transport=args.transport,
        dp_pods=args.dp_pods,
        hier_route_factor_ici=args.hier_route_factor_ici,
        hier_route_factor_dcn=args.hier_route_factor_dcn,
        rank=args.rank,
        error_feedback=args.error_feedback,
        sync_overlap=args.overlap,
    )
    from tpu_compressed_dp.harness.loop import build_control, build_robustness
    from tpu_compressed_dp.train.guard import init_guard_state

    guard_cfg, chaos, crash = build_robustness(args, cfg.dtype)
    ctrl_cfg = build_control(args, comp)
    if ctrl_cfg is not None and pipelined:
        raise ValueError(
            "--adaptive supports the (data, seq, tensor) step; the pipeline "
            "step's stacked-layer layout has no rung-switch path yet")
    if ctrl_cfg is not None:
        from tpu_compressed_dp.control.rungs import ladder_knob
        if ladder_knob(ctrl_cfg.method) == "rank":
            raise ValueError(
                "--adaptive rank retuning (powersgd) is CNN-harness-only "
                "for now: the LM comp-state layout has no cross-rank "
                "migration path (use a ratio method, or static --rank)")
    from tpu_compressed_dp.control import init_control_state

    step_cache: Dict = {}

    def active_comp() -> CompressionConfig:
        """The compression config the NEXT step should trace under: the
        controller's checkpointed rung when adaptive, the static config
        otherwise."""
        if ctrl_cfg is None:
            return comp
        from tpu_compressed_dp.control import comp_for_rung
        return comp_for_rung(comp, ctrl_cfg, int(state.control.rung))

    def lm_step_for(comp_cfg: CompressionConfig):
        # keyed by the tunable knobs (the rung ladder varies exactly these);
        # cleared wholesale on remesh — entries close over the current mesh
        key = (comp_cfg.ratio, comp_cfg.rank)
        if key not in step_cache:
            step_cache[key] = make_lm_train_step(
                cfg, opt, comp_cfg, mesh,
                clip_norm=args.clip_norm,
                clip_sent_norm=args.clip_sent_norm,
                guard_cfg=guard_cfg, chaos=chaos)
        return step_cache[key]
    if pipelined:
        # NB make_pp_train_step rejects method='powersgd' (stacked-layer
        # params shard over pipe; no warm-start init exists for that layout)
        from tpu_compressed_dp.train.pp_step import (
            init_pp_ef_state, make_pp_train_step, stack_layer_params,
        )

        params = stack_layer_params(params)
        state = TrainState.create(
            params, {}, opt.init(params),
            init_pp_ef_state(cfg, params, comp, mesh),
            jax.random.key(args.seed + 1),
            guard=init_guard_state(guard_cfg),
        )
        train_step = make_pp_train_step(cfg, opt, comp, mesh,
                                        microbatches=args.microbatches,
                                        clip_norm=args.clip_norm,
                                        clip_sent_norm=args.clip_sent_norm,
                                        guard_cfg=guard_cfg, chaos=chaos)
        ckpt = Checkpointer(args.checkpoint_dir) if args.checkpoint_dir else None
        if args.resume:
            from tpu_compressed_dp.train.pp_step import place_pp_state

            restore = Checkpointer(args.resume)
            state, meta = restore.restore(state)
            restore.close()
            state = place_pp_state(state, cfg, comp, mesh)
            print(f"resumed step {int(state.step)}")
    else:
        from tpu_compressed_dp.train.lm_step import init_lm_comp_state

        state = TrainState.create(
            params, {}, opt.init(params), init_lm_ef_state(cfg, params, comp, mesh),
            jax.random.key(args.seed + 1),
            comp=init_lm_comp_state(cfg, params, comp, mesh),
            guard=init_guard_state(guard_cfg),
            control=init_control_state(ctrl_cfg),
        )
        ckpt = Checkpointer(args.checkpoint_dir) if args.checkpoint_dir else None
        if args.resume:
            from tpu_compressed_dp.train.lm_step import place_lm_state

            restore = Checkpointer(args.resume)
            state, meta = restore.restore(state)
            restore.close()
            state = place_lm_state(state, cfg, comp, mesh)
            print(f"resumed step {int(state.step)}")

        train_step = lm_step_for(active_comp())
    mesh_str = (f"dp{dp}xsp{args.sp}xpp{args.pp}xtp{args.tp}(mb{args.microbatches})" if pipelined
                else f"dp{dp}xsp{args.sp}xtp{args.tp}")
    print(f"params={n_params/1e6:.1f}M mesh={mesh_str} "
          f"seq={args.seq_len} batch={args.global_batch} "
          f"method={comp.method or 'dense'}/{comp.granularity}/{comp.mode}")

    table = TableLogger()
    from tpu_compressed_dp.utils.meters import GuardMeter, per_chip_comm_bytes

    guard_meter = GuardMeter()
    from tpu_compressed_dp.harness.loop import (flight_update, job_scoped,
                                                make_event_stream,
                                                make_flight_recorder,
                                                make_heartbeat,
                                                make_preemption, make_stream,
                                                preempt_exit, profile_trace,
                                                prom_labels,
                                                stream_rejoin_params)
    from tpu_compressed_dp.obs.export import (telemetry_snapshot,
                                              write_prometheus)
    from tpu_compressed_dp.obs.trace import StepTimeline

    hb = make_heartbeat(args)
    timeline = StepTimeline()
    events = make_event_stream(
        args, harness="lm", preset=args.preset, mesh=mesh_str,
        method=comp.method or "none", compress=args.compress, mode=args.mode,
        transport=args.transport, seq_len=args.seq_len,
        global_batch=args.global_batch, steps=args.steps)
    flight = make_flight_recorder(
        args, harness="lm", preset=args.preset, mesh=mesh_str,
        method=comp.method or "none")
    if flight is not None and chaos is not None:
        flight.note_chaos(chaos)
    if flight is not None and crash is not None:
        crash.flight = flight
    if ckpt is not None:
        ckpt.events = events   # save/rollback records on the run's stream
        ckpt.flight = flight
    stream = make_stream(args, flight=flight, events=events)
    if ckpt is not None and stream is not None:
        # tee: a committed full checkpoint re-anchors the delta window
        ckpt.stream = stream
    preempt = make_preemption()
    if getattr(args, "elastic", False) and pipelined:
        # dp x sp and dp x tp remesh by deleting the dead DATA row (the
        # model shards are replicated across data rows); a pipeline stage
        # has no replica to recover from, so pp stays a checkpoint restart
        raise ValueError(
            "--elastic supports dp/dp x sp/dp x tp meshes; losing a worker "
            "of a pp mesh orphans a pipeline stage (that is a checkpoint "
            "restart, not a remesh)")
    from tpu_compressed_dp.harness.loop import build_elastic
    from tpu_compressed_dp.train.lm_step import place_lm_state

    el = build_elastic(args, mesh, chaos=chaos, crash=crash, events=events,
                       place=lambda s, m: place_lm_state(s, cfg, comp, m),
                       flight=flight, ef_axes=("data", "seq"), stream=stream)
    if el is not None and rejoin is not None:
        # watchdog-relaunched host: adopt the running world's replicated
        # state from the re-elected coordinator's broadcast (EF rows start
        # at zero) and retrace the step on the post-join mesh; a warm
        # joiner replays the delta stream instead of shipping params
        adopted_params, adopted_info = stream_rejoin_params(
            args, state, rejoin, flight=flight)
        state = el.join_world(state, rejoin, adopted_params=adopted_params,
                              adopted_info=adopted_info)
        mesh = el.mesh
        dp = el.world
        step_cache.clear()
        train_step = lm_step_for(active_comp())
    controller = None
    hide_frac = 1.0
    if ctrl_cfg is not None:
        from tpu_compressed_dp.control import Controller
        from tpu_compressed_dp.harness.loop import build_twin_pricer
        from tpu_compressed_dp.parallel.overlap import (hideable_byte_fraction,
                                                        plan_chunks)
        from tpu_compressed_dp.train.guard import schedule_step

        controller = Controller(ctrl_cfg, events=events,
                                pricer=build_twin_pricer(args, comp,
                                                         world=dp))
        hide_frac = hideable_byte_fraction(plan_chunks(
            [leaf.size * 4 for leaf in jax.tree.leaves(params)], comp))
        print(f"adaptive: method={ctrl_cfg.method} knob={controller.knob} "
              f"rungs={ctrl_cfg.rungs} window={ctrl_cfg.window} "
              f"signal={ctrl_cfg.signal} hideable_frac={hide_frac:.3f}")
    # --profile_epoch: trace the Nth log window.  ExitStack (not a `with`)
    # because the window opens and closes mid-loop; the outer finally
    # guarantees the stop even when the loop raises inside the window —
    # the same leak-proofing profile_trace gives the CNN harnesses.
    prof = contextlib.ExitStack()
    prof_window = None
    if args.profile_epoch is not None and args.logdir:
        w0 = args.profile_epoch * args.log_every
        prof_window = (w0, w0 + args.log_every)
    t0 = time.time()
    tokens_done = 0.0
    summary: Dict[str, float] = {}
    start = int(state.step)
    timed_from = start
    world = dp * args.sp  # gradient-sync workers (transport arithmetic)
    prev_skipped = 0.0
    # finally-guarded: GuardExceeded / ChaosCrash must not leak the
    # heartbeat writer thread, the checkpoint manager, a running profiler
    # trace, or an unterminated event stream; the final save stays on the
    # clean path only
    try:
        rows = args.global_batch        # post-remesh: largest dp-divisible cut
        warm_until = start + 1          # compile-reset horizon (moves on remesh)
        step_i = start
        while step_i < args.steps:
            try:
                if prof_window is not None and step_i == prof_window[0]:
                    prof.enter_context(
                        profile_trace(os.path.join(args.logdir, "profile")))
                if crash is not None:
                    crash.check(step_i)
                # after crash.check: crash=preempt self-SIGTERMs there, and
                # the flag must be observed within the same iteration
                preempt.check(step_i)
                if el is not None:
                    el.poll(step_i)
                batch = ds.batch(step_i)
                if rows != args.global_batch:
                    batch = {k: v[:rows] for k, v in batch.items()}
                timeline.batch_ready()
                state, metrics = train_step(
                    state, {k: jnp.asarray(v) for k, v in batch.items()})
                timeline.step_dispatched()
                if crash is not None:
                    # the mid-collective plane: the step's collectives are
                    # already in flight when this one fires
                    crash.check(step_i, phase="mid_collective")
                if prof_window is not None and step_i + 1 == prof_window[1]:
                    prof.close()
                if step_i <= warm_until:
                    # steady-state tokens/sec: the jitted step compiles TWICE (the
                    # donated-buffer layouts change the arg signature on call 2), so
                    # barrier-and-reset after each of the first two steps — one
                    # excluded step would leak the second compile (18s+ at 125M
                    # params) into the timed window
                    jax.device_get(metrics)
                    t0 = time.time()
                    timed_from = step_i + 1
                    timeline.resume()  # the compile drain is not data wait
                if (step_i + 1) % args.log_every == 0 or step_i == args.steps - 1:
                    m = (el.bounded_get(metrics, step=step_i + 1)
                         if el is not None else jax.device_get(metrics))
                    # spans drain ONCE per window and fan out to every
                    # consumer; the flight rings fill BEFORE the wedge check
                    # so a GuardExceeded dump carries the streak history
                    spans = timeline.drain()
                    fgauges = flight_update(flight, step=step_i + 1,
                                            metrics=m, spans=spans)
                    if guard_cfg is not None:
                        # wedge check at log cadence (detection latency = log_every)
                        from tpu_compressed_dp.train.guard import check_guard_metrics

                        guard_meter.update(m, step_i + 1)
                        check_guard_metrics(m, guard_cfg, flight=flight)
                    if hb is not None:
                        hb.update(
                            step=step_i + 1,
                            last_good_step=(int(m["guard/last_good_step"])
                                            if guard_cfg is not None else step_i + 1),
                            telemetry=telemetry_snapshot(timeline),
                            **(ckpt.heartbeat_fields() if ckpt is not None
                               else {}),
                            **(stream.heartbeat_fields() if stream is not None
                               else {}),
                            **({"elastic": el.metrics()} if el is not None else {}),
                            **(controller.heartbeat_fields(state.control)
                               if controller is not None else {}),
                            **({"straggler_skew_s": fgauges["straggler/skew_s"],
                                "straggler_rank": fgauges["straggler/rank"]}
                               if "straggler/skew_s" in fgauges else {}),
                        )
                    steps_timed = step_i + 1 - timed_from
                    tokens_done = steps_timed * rows * args.seq_len
                    dt = time.time() - t0
                    summary = {
                        "step": step_i + 1,
                        "loss": float(m["loss"]),
                        "lr": float(m["lr"]),
                        # 0.0 until at least one post-compile step is in the window
                        "tok/s": round(tokens_done / dt, 1) if steps_timed > 0 else 0.0,
                    }
                    thr: Dict[str, float] = {}
                    if steps_timed > 0:
                        # MFU (VERDICT r2 #3): closed-form 6N + 12Lds per token
                        # (utils/flops.py), per chip, vs the chip's bf16 peak —
                        # per-chip fwd flops feed the shared throughput_record
                        # epilogue the CNN harnesses use
                        from tpu_compressed_dp.utils import flops as flops_mod

                        tok_flops = flops_mod.transformer_train_flops_per_token(
                            n_params, cfg.n_layers, cfg.dim, args.seq_len)
                        n_chips = max(int(mesh.devices.size), 1)
                        tok_s = tokens_done / dt
                        fwd_per_chip = (tok_flops / 3.0) * (
                            rows * args.seq_len) / n_chips
                        thr = flops_mod.throughput_record(
                            fwd_per_chip, steps_timed / dt, tokens_per_sec=tok_s)
                        if "throughput/mfu" in thr:
                            summary["mfu"] = round(thr["throughput/mfu"], 4)
                    comm_m = {k: float(v) for k, v in m.items()
                              if k.startswith("comm/")}
                    if "comm/sent_elems" in m:
                        summary["sent frac"] = float(m["comm/sent_elems"]) / max(
                            float(m["comm/dense_elems"]), 1.0)
                        summary["wire frac"] = float(m["comm/sent_bits"]) / (
                            32.0 * max(float(m["comm/dense_elems"]), 1.0))
                        per_chip_b = per_chip_comm_bytes(comm_m, world,
                                                         args.dp_pods)
                        if per_chip_b is not None and steps_timed > 0:
                            summary["comm MB/s"] = round(
                                per_chip_b * (steps_timed / dt) / 1e6, 3)
                    guard_last = {k: float(v) for k, v in m.items()
                                  if k.startswith("guard/")}
                    if guard_cfg is not None:
                        gsum = guard_meter.summary()
                        summary["skipped"] = gsum.get("guard/skipped", 0.0)
                        summary["loss_scale"] = gsum.get("guard/loss_scale", 1.0)
                    control_stats: Dict[str, float] = {}
                    if controller is not None:
                        # decision tick at the log-window cadence, keyed to
                        # APPLIED updates; ticks before the checkpoint-save
                        # site below so the saved ControlState carries this
                        # window's accumulation (bitwise crash/resume)
                        applied = (schedule_step(guard_cfg, state.guard,
                                                 int(state.step))
                                   if guard_cfg is not None
                                   else int(state.step))
                        wall_ms = (dt * 1e3 / steps_timed
                                   if steps_timed > 0 else None)
                        if wall_ms is not None or (
                                ctrl_cfg.signal == "modeled"
                                and ctrl_cfg.budget_ms > 0):
                            old_rung = int(state.control.rung)
                            new_control, _ = controller.tick(
                                state.control, applied=applied,
                                signals=controller.window_signals(
                                    mean_bits=float(
                                        m.get("comm/sent_bits", 0.0)),
                                    measured_comm_ms=wall_ms,
                                    compute_ms=wall_ms,
                                    hideable_fraction=hide_frac))
                            state = state.replace(control=new_control)
                            if flight is not None:
                                flight.note_control(
                                    {"step": step_i + 1,
                                     "rung": int(new_control.rung),
                                     "applied": applied})
                            if int(new_control.rung) != old_rung:
                                # trace-cached rung switch: takes effect at
                                # the next step dispatch
                                train_step = lm_step_for(active_comp())
                        control_stats = controller.metrics(state.control)
                        summary["rung"] = control_stats["control/rung"]
                        summary[controller.knob] = control_stats["control/value"]
                    if events is not None:
                        events.emit(
                            "step", step=step_i + 1,
                            metrics={k: v for k, v in summary.items()
                                     if isinstance(v, (int, float))},
                            throughput=thr, comm=comm_m, guard=guard_last,
                            control=control_stats,
                            timeline=timeline.snapshot(),
                            step_spans=spans)
                        # delta-gate on the cumulative counter: one guard event
                        # per window that actually skipped, not one per window
                        # forever after the first skip
                        skipped_now = guard_last.get("guard/skipped", 0.0)
                        if skipped_now > prev_skipped:
                            events.emit("guard", step=step_i + 1, **guard_last)
                        prev_skipped = skipped_now
                    if args.prom and jax.process_index() == 0:
                        write_prometheus(
                            {"loss": summary["loss"], "lr": summary["lr"],
                             **thr, **comm_m, **guard_last, **control_stats,
                             **timeline.snapshot(),
                             **(ckpt.metrics() if ckpt is not None else {}),
                             **(stream.metrics() if stream is not None else {}),
                             **(el.metrics() if el is not None else {}),
                             **fgauges},
                            job_scoped(args, args.prom),
                            labels=prom_labels(args, harness="lm"))
                    table.append(summary)
                    # the log window's device_get drain + export work is not the
                    # next step's input-pipeline wait
                    timeline.resume()
                if el is not None and (step_i + 1) % args.log_every == 0:
                    # log-cadence readmission: fold any watchdog-relaunched
                    # host parked in the rendezvous join barrier into a new
                    # world epoch (no-op single-process / no joins pending)
                    state, grew = el.rejoin_barrier(state)
                    if grew:
                        mesh = el.mesh
                        dp = el.world
                        world = dp * args.sp
                        rows = (args.global_batch // dp) * dp
                        step_cache.clear()
                        train_step = lm_step_for(active_comp())
                        warm_until = step_i + 2  # compile pair on the new mesh
                        t0 = time.time()
                        timed_from = step_i + 1
                        timeline.resume()
            except Exception as err:  # noqa: BLE001 - converted or re-raised
                failure = el.failure_from(err) if el is not None else None
                if failure is None:
                    if flight is not None and not isinstance(
                            err, resilience.Preempted):
                        # unconverted failure about to unwind the run: the
                        # dump here is the only evidence this rank leaves
                        flight.observe(err, step=step_i)
                    raise
                # coordinated abort + remesh.  Granularity is one step: a
                # pre-dispatch detection (gossip poll) retries the same
                # index untouched; a post-dispatch kill drains the in-flight
                # step during migration (single-process simulation — the
                # collectives do complete) and the index re-runs on the W-1
                # mesh.  Real multi-host discards in-flight work by process
                # exit instead.
                state = el.handle_failure(state, failure)
                mesh = el.mesh
                dp = el.world
                world = dp * args.sp
                rows = (args.global_batch // dp) * dp
                step_cache.clear()
                train_step = lm_step_for(active_comp())
                warm_until = step_i + 1     # fresh compile pair on the new mesh
                t0 = time.time()
                timed_from = step_i
                timeline.resume()
                continue
            if (ckpt is not None and args.ckpt_every
                    and (step_i + 1) % args.ckpt_every == 0):
                # async: snapshot to host, hand the Orbax write to the
                # background thread, keep stepping
                ckpt.save_async(state, {"step": step_i + 1})
            if (stream is not None and args.stream_every > 0
                    and (step_i + 1) % args.stream_every == 0):
                # delta stream: Top-K of (params - last streamed) on the
                # compressed wire codec; codec runs on this thread (window
                # accounting is ordered), the npz write goes to background
                stream.append_async(state.params, step=int(state.step))
            step_i += 1
        if ckpt:
            ckpt.save(state, {"step": int(state.step)})
    except resilience.Preempted as err:
        # SIGTERM/SIGINT landed: cut the emergency checkpoint (draining any
        # in-flight async write first) and exit PREEMPT_EXIT so the watchdog
        # relaunches immediately instead of burning its backoff/budget
        state = getattr(err, "elastic_state", state)
        raise preempt_exit(err, ckpt=ckpt, state=state,
                           meta={"step": int(state.step)},
                           events=events, flight=flight) from None
    finally:
        preempt.uninstall()
        prof.close()
        if stream is not None:
            stream.close()   # drain the in-flight delta append
        if ckpt:
            ckpt.close()   # drains the background writer before events close
        if events is not None:
            events.close()
        if hb is not None:
            hb.stop()
    return summary


def main(argv: Optional[list] = None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
