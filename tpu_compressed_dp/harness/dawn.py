"""CIFAR-10 DAWNBench harness — the `CIFAR10/dawn.py` equivalent.

Protocol parity (`dawn.py:98-155`): batch 512; 24 epochs (40 for Random-K /
Threshold-V, `dawn.py:105-108`); ``PiecewiseLinear([0, 5, epochs],
[0, 0.4, 0])`` evaluated at fractional epochs, divided by batch size
(`dawn.py:110,142`); weight decay ``5e-4 * batch_size``; optional Nesterov
momentum (`dawn.py:144-148`); Crop/FlipLR/Cutout augmentation; TSV + table
logging.  Gradients are compressed at summed-loss scale via
``grad_scale=batch_size`` (see train/step.py docstring).

Differences from the reference (intended behaviour, SURVEY.md §2.3):
  * ``--network resnet9`` actually selects ResNet-9 (the reference compared
    against the misspelling 'Resent9' and crashed on its own default);
  * the entire-model path works;
  * rendezvous/mesh come from JAX (no --master_address/--rank plumbing needed
    single-host; multi-host rendezvous flags exist but per-process batch
    sharding is not wired up yet — the harness refuses rather than mis-feeds).

Run: ``python -m tpu_compressed_dp.harness.dawn --synthetic --epochs 2``
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_compressed_dp.data import cifar10 as data
from tpu_compressed_dp.harness.loop import (add_adaptive_args,
                                            add_checkpoint_args,
                                            add_robustness_args,
                                            add_stream_args,
                                            add_telemetry_args,
                                            add_topology_args,
                                            build_control,
                                            build_elastic, build_robustness,
                                            control_summary,
                                            elastic_distributed_init,
                                            flight_update, job_scoped,
                                            make_event_stream,
                                            make_flight_recorder,
                                            make_heartbeat,
                                            make_preemption, make_stream,
                                            preempt_exit, profile_trace,
                                            prom_labels,
                                            stream_rejoin_params,
                                            train_epoch)
from tpu_compressed_dp.models import alexnet as alexnet_mod
from tpu_compressed_dp.models import resnet9 as resnet9_mod
from tpu_compressed_dp.models import vgg as vgg_mod
from tpu_compressed_dp.models.common import (
    init_model,
    make_apply_fn,
    make_normalizing_apply_fn,
)
from tpu_compressed_dp.parallel.dp import (CompressionConfig, init_comp_state,
                                           init_ef_state)
from tpu_compressed_dp.parallel.mesh import make_data_mesh
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.guard import init_guard_state
from tpu_compressed_dp.train.schedules import piecewise_linear
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.step import make_eval_step, make_train_step
from tpu_compressed_dp.utils import resilience
from tpu_compressed_dp.utils.loggers import TableLogger, TSVLogger
from tpu_compressed_dp.utils.timer import Timer

def _scaled(ch: dict, scale: float) -> dict:
    return {k: max(8, int(v * scale)) for k, v in ch.items()}


def _fixed_width(name: str, ctor, s: float, dtype):
    # no width/dtype knob on these: refuse a non-default instead of silently
    # building full-width fp32 (would mislabel every downstream timing)
    if s != 1.0:
        raise ValueError(f"{name} does not support channels_scale")
    if dtype != jnp.float32:
        raise ValueError(f"{name} does not support --dtype (fp32 only)")
    return ctor()


MODELS = {
    # channels_scale reproduces the width ablations of the reference's
    # experiments.ipynb (half/double width nets, SURVEY.md §6) and keeps CPU
    # smoke tests fast.  dtype=bfloat16 is the TPU-native mixed-precision
    # posture (bf16 compute / fp32 masters; the reference's fp16util.py role).
    "resnet9": lambda s=1.0, dtype=jnp.float32: resnet9_mod.ResNet9(
        channels=_scaled({"prep": 64, "layer1": 128, "layer2": 256, "layer3": 512}, s),
        dtype=dtype,
    ),
    "alexnet": lambda s=1.0, dtype=jnp.float32: resnet9_mod.AlexNetGraph(
        channels=_scaled(
            {"prep": 64, "layer1": 192, "layer2": 384, "layer3": 256, "layer4": 256}, s
        ),
        dtype=dtype,
    ),
    "alexnet_module": lambda s=1.0, dtype=jnp.float32: _fixed_width(
        "alexnet_module", alexnet_mod.AlexNet, s, dtype),
    "vgg16": lambda s=1.0, dtype=jnp.float32: _fixed_width(
        "vgg16", vgg_mod.vgg16, s, dtype),
    # spec-built variants via the graph runtime (`core.py:136`-equivalent)
    "resnet9_graph": lambda s=1.0, dtype=jnp.float32: _graph_net("resnet9", s, dtype),
    "alexnet_graph": lambda s=1.0, dtype=jnp.float32: _graph_net("alexnet", s, dtype),
}


def _graph_net(kind: str, scale: float, dtype=jnp.float32):
    from tpu_compressed_dp.models import graph as graph_mod

    base = {"resnet9": {"prep": 64, "layer1": 128, "layer2": 256, "layer3": 512},
            "alexnet": {"prep": 64, "layer1": 192, "layer2": 384,
                        "layer3": 256, "layer4": 256}}[kind]
    ch = {k: max(int(v * scale), 8) for k, v in base.items()}
    spec = (graph_mod.resnet9_spec(channels=ch, dtype=dtype) if kind == "resnet9"
            else graph_mod.alexnet_spec(channels=ch, dtype=dtype))
    return graph_mod.GraphNet(spec)


def warmup_ratio_for_epoch(epoch: int, *, ratio: float, warmup_epochs: int,
                           method) -> float:
    """DGC-style sparsity warm-up: geometric decay ``ratio^((e+1)/N)`` toward
    ``ratio`` over the first ``warmup_epochs``, rounded to 2 significant
    digits so close epochs share a compile.  The single source of the
    schedule — the harness applies it per epoch and
    tools/time_to_accuracy.py integrates it into ``effective_sent_frac``."""
    from tpu_compressed_dp.ops.compressors import canonical_name

    if (warmup_epochs <= 0 or epoch >= warmup_epochs or method is None
            or canonical_name(method) not in ("topk", "randomk", "blocktopk")):
        return ratio
    r = ratio ** ((epoch + 1) / warmup_epochs)
    from math import floor, log10

    digits = -int(floor(log10(abs(r)))) + 1
    return min(1.0, round(r, digits))


def build_parser() -> argparse.ArgumentParser:
    # flag surface mirrors `dawn.py:8-20`
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_dir", type=str, default="./data")
    p.add_argument("--log_dir", type=str, default=".")
    p.add_argument("--network", "-n", type=str, default="resnet9", choices=sorted(MODELS))
    p.add_argument("--compress", "-c", type=str, default="none",
                   choices=["none", "layerwise", "entiremodel", "bucketed"])
    p.add_argument("--method", type=str, default="none")
    p.add_argument("--ratio", "-K", type=float, default=0.5)
    p.add_argument("--threshold", "-V", type=float, default=0.001)
    p.add_argument("--qstates", "-Q", type=int, default=255)
    p.add_argument("--rank", type=int, default=4,
                   help="r for powersgd (per-group payload r*(m + n/m) fp32 "
                        "words on the psum ring)")
    p.add_argument("--block_size", type=int, default=256,
                   help="blocktopk: elements per contiguous block")
    p.add_argument("--bucket_mb", type=float, default=25.0,
                   help="bucketed granularity: capacity per bucket")
    p.add_argument("--wire_cap_ratio", type=float, default=0.05,
                   help="wire thresholdv/adaptive_threshold: transport "
                        "capacity as a fraction of elements (size via "
                        "comm/threshold_overflow)")
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument("--clip_norm", type=float, default=0.0,
                   help="local-gradient L2 clip (mean-loss units; 0=off) — the "
                        "DGC-style stabiliser for EF + momentum (see "
                        "tools/ef_bisect.py)")
    p.add_argument("--clip_sent_norm", type=float, default=0.0,
                   help="post-aggregation L2 clip of the synced gradient "
                        "(bounds the EF residual spike; see tools/ef_bisect.py)")
    p.add_argument("--mode", type=str, default="simulate", choices=["simulate", "wire"])
    p.add_argument("--transport", default="allgather",
                   choices=["allgather", "sharded", "hierarchical"],
                   help="wire combine for index-carrying sparsifiers: flat "
                        "all_gather (O(W*k)/chip), owner-sharded reduce "
                        "(O(k + n/W)/chip, ops/wire_sharded.py; size caps "
                        "via comm/shard_overflow), or the two-level "
                        "hierarchical reduce over a --dp_pods x chips "
                        "virtual mesh (O(k + n/W_pods) DCN bytes)")
    add_topology_args(p)
    p.add_argument("--error_feedback", action="store_true")
    p.add_argument("--overlap", type=int, default=1,
                   help="chunk-pipelined sync (parallel/overlap.py): split "
                        "the gradient sync into up to K reverse-topological "
                        "chunk collectives XLA interleaves with backward + "
                        "per-chunk optimizer compute; numerics unchanged "
                        "(1 = single dispatch)")
    p.add_argument("--ratio_warmup_epochs", type=int, default=0,
                   help="DGC-style sparsity warm-up (Lin et al., ICLR'18): "
                        "keep-ratio decays geometrically from ~dense to "
                        "--ratio over the first N epochs (epoch-level, one "
                        "recompile per distinct ratio).  Early training — "
                        "where EF x momentum spikes are most destructive — "
                        "runs near-dense; only topk/randomk/blocktopk")
    p.add_argument("--epochs", type=int, default=None, help="override the 24/40 rule")
    p.add_argument("--batch_size", type=int, default=512)
    p.add_argument("--peak_lr", type=float, default=0.4)
    p.add_argument("--lr_schedule", type=str, default="dawn",
                   choices=["dawn", "step"],
                   help="'dawn' = the CIFAR triangle (`dawn.py:110`); 'step' = "
                        "the reference's ImageNet shape (warmup to peak, flat, "
                        "peak/10 at 60%%, peak/100 at 85%% — `train.py:60-72`), "
                        "the regime the reference actually ran sparsified DDP "
                        "under.  EF + momentum needs 'step' with a ~10x lower "
                        "peak than dawn's (see benchmarks/ef_momentum_bisect_r3)")
    p.add_argument("--devices", type=int, default=None, help="mesh size (default: all)")
    p.add_argument("--synthetic", action="store_true", help="synthetic data smoke run")
    p.add_argument("--synthetic_hard", action="store_true",
                   help="non-saturating synthetic benchmark (dense ~0.9 test "
                        "acc under the 24-epoch protocol) for method x k "
                        "convergence sweeps")
    p.add_argument("--synthetic_n", type=int, default=2048, help="synthetic train-set size")
    p.add_argument("--dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"],
                   help="compute dtype (params stay fp32 masters; bfloat16 "
                        "is the TPU answer to the reference's fp16util.py)")
    p.add_argument("--channels_scale", type=float, default=1.0,
                   help="width multiplier for the graph-family nets")
    p.add_argument("--seed", type=int, default=0)
    # robustness: shared --guard*/--chaos/--heartbeat surface
    add_robustness_args(p, check_note="checked at epoch end")
    # adaptive compression: shared --adaptive* surface (control/)
    add_adaptive_args(p)
    # checkpointing: shared --checkpoint_dir/--resume/--ckpt_every surface
    add_checkpoint_args(p, cadence_help="epochs between async checkpoint "
                                        "saves (requires --checkpoint_dir; "
                                        "0 = emergency/final saves only)")
    # delta state streaming: shared --stream* surface (stream/)
    add_stream_args(p, cadence_help="epochs between delta-stream appends "
                                    "(requires --stream_dir; 0 disables "
                                    "the periodic append)")
    # telemetry: shared --events/--prom surface (obs/export.py)
    add_telemetry_args(p)
    p.add_argument("--tensorboard", action="store_true",
                   help="write tensorboard scalars under <log_dir>/tb")
    p.add_argument("--profile_epoch", type=int, default=None,
                   help="jax.profiler-trace this epoch to <log_dir>/profile")
    # multi-host rendezvous (the reference's --master_address/--rank/--world_size)
    p.add_argument("--coordinator", type=str, default=None)
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    return p


def default_epochs(method: str) -> int:
    # `dawn.py:105-108`
    return 40 if method.lower() in ("randomk", "thresholdv") else 24


class ShardedBatches:
    """Per-process view of a deterministic global batch stream.

    The multi-host analog of ``DistributedSampler`` (`dataloader.py:33`):
    every process iterates the SAME global batches (identical seed -> identical
    shuffle + augmentation draws), slices its rank's contiguous shard, and
    assembles the global device array whose shards live on local devices
    (``make_global_batch``).  Identity pass-through single-process.  Eval
    batches are padded to the static batch size first so every rank's shard
    keeps one shape (`pad_batch` semantics).
    """

    def __init__(self, inner, mesh, pad_to: Optional[int] = None,
                 already_local: bool = False):
        self.inner = inner
        self.mesh = mesh
        self.pad_to = pad_to
        self.already_local = already_local  # inner yields rank-local slices

    def __len__(self):
        return len(self.inner)

    def __iter__(self):
        from tpu_compressed_dp.harness.loop import pad_batch
        from tpu_compressed_dp.parallel.mesh import make_global_batch

        rank, procs = jax.process_index(), jax.process_count()
        for b in self.inner:
            if self.pad_to is not None:
                b = pad_batch(b, self.pad_to)
            if procs == 1:
                yield b
                continue
            if self.already_local:
                local = {k: np.asarray(v) for k, v in b.items()}
            else:
                n = len(b["target"])
                per = n // procs
                local = {k: np.asarray(v)[rank * per:(rank + 1) * per]
                         for k, v in b.items()}
            yield make_global_batch(local, self.mesh)


def run(args) -> dict:
    # Pure CLI-flag consistency first, before any I/O or device work.
    if args.method.lower() != "none" and args.compress == "none":
        raise ValueError(
            f"--method {args.method} requires --compress layerwise|entiremodel "
            "(the reference silently trained dense here; we refuse instead)"
        )
    if getattr(args, "adaptive", False) and args.ratio_warmup_epochs > 0:
        raise ValueError(
            "--adaptive and --ratio_warmup_epochs both drive the keep-ratio; "
            "pick one (the controller's rung 0 is the static baseline, so "
            "adaptive runs start dense-ish on their own ladder)"
        )
    rejoin = elastic_distributed_init(args)
    mesh = make_data_mesh(args.devices)
    ndev = mesh.shape["data"]
    epochs = args.epochs if args.epochs is not None else default_epochs(args.method)
    bs = args.batch_size
    if bs % ndev:
        raise ValueError(f"batch_size {bs} not divisible by mesh size {ndev}")

    print(f"mesh: {ndev} devices; network={args.network} compress={args.compress} "
          f"method={args.method} epochs={epochs}")

    if args.synthetic_hard:
        dataset = data.synthetic_cifar10_hard(
            n_train=args.synthetic_n, n_test=max(args.synthetic_n // 4, bs))
    elif args.synthetic:
        dataset = data.synthetic_cifar10(
            n_train=args.synthetic_n, n_test=max(args.synthetic_n // 4, bs))
    else:
        dataset = data.load_cifar10(args.data_dir)

    # batches stay uint8 end-to-end; the compiled step normalises on device
    # (1 byte/pixel over the host->device wire instead of 4)
    train_x = data.pad(dataset["train"]["data"])
    test_x = dataset["test"]["data"]
    procs = jax.process_count()
    train_batches = data.Batches(
        train_x, dataset["train"]["labels"], bs, shuffle=True, augment=True,
        drop_last=True, seed=args.seed,
        shard=(jax.process_index(), procs) if procs > 1 else None)
    test_batches = data.Batches(test_x, dataset["test"]["labels"], bs,
                                shuffle=False, augment=False, drop_last=False)
    if procs > 1:
        # multi-process: every rank feeds its shard of the global batch
        # (bs % ndev == 0 was checked above; ndev counts global devices and
        # the process count divides it, so per-rank shards are equal-sized).
        # Train batches come rank-local from the sharded iterator (identical
        # RNG stream on all ranks, pixel work only for the local rows).
        train_batches = ShardedBatches(train_batches, mesh, already_local=True)
        test_batches = ShardedBatches(test_batches, mesh, pad_to=bs)

    module = MODELS[args.network](args.channels_scale,
                                  dtype=jnp.dtype(args.dtype).type)
    params, stats = init_model(module, jax.random.key(args.seed),
                               jnp.zeros((1, 32, 32, 3), jnp.float32))

    steps_per_epoch = len(train_batches)
    # `dawn.py:110`: ramp to peak at epoch 5, anneal to 0 at `epochs`.  For
    # short (smoke) runs the ramp point is pulled in so the knots stay strictly
    # increasing and the schedule still anneals to 0.
    ramp_ep = 5 if epochs > 5 else epochs / 2
    if args.lr_schedule == "step":
        # the ImageNet shape (`train.py:60-72`) expressed through the same
        # phase DSL the ImageNet harness uses: warmup -> flat peak -> /10 at
        # 60% -> /100 at 85%.  Warmup spans the first 1/8 of training (the
        # reference's 5-of-~35; a fixed 5 would cross the 60% boundary on
        # short runs and fold the knot sequence non-monotone)
        from tpu_compressed_dp.train.schedules import lr_phases_to_knots

        ramp_s = epochs / 8.0
        knots, vals = lr_phases_to_knots([
            {"ep": (0, ramp_s), "lr": (0.0, args.peak_lr)},
            {"ep": ramp_s, "lr": args.peak_lr},
            {"ep": 0.6 * epochs, "lr": args.peak_lr / 10.0},
            {"ep": 0.85 * epochs, "lr": args.peak_lr / 100.0},
        ])
        sched = piecewise_linear(knots, vals)
    else:
        sched = piecewise_linear([0, ramp_ep, epochs], [0, args.peak_lr, 0])
    lr = lambda step: sched(step / steps_per_epoch) / bs  # noqa: E731 (`dawn.py:142`)
    opt = SGD(
        lr=lr,
        momentum=args.momentum,
        nesterov=args.momentum > 0,
        weight_decay=5e-4 * bs,
    )

    def comp_for_ratio(ratio: float) -> CompressionConfig:
        return CompressionConfig(
            method=None if args.compress == "none" or args.method.lower() == "none" else args.method,
            granularity=args.compress if args.compress != "none" else "layerwise",
            mode=args.mode,
            ratio=ratio,
            threshold=args.threshold,
            qstates=args.qstates,
            block_size=args.block_size,
            bucket_mb=args.bucket_mb,
            wire_cap_ratio=args.wire_cap_ratio,
            transport=args.transport,
            dp_pods=args.dp_pods,
            hier_route_factor_ici=args.hier_route_factor_ici,
            hier_route_factor_dcn=args.hier_route_factor_dcn,
            rank=args.rank,
            error_feedback=args.error_feedback,
            sync_overlap=args.overlap,
        )

    comp = comp_for_ratio(args.ratio)

    def ratio_for_epoch(epoch: int) -> float:
        return warmup_ratio_for_epoch(
            epoch, ratio=args.ratio, warmup_epochs=args.ratio_warmup_epochs,
            method=comp.method)

    guard_cfg, chaos, crash = build_robustness(args, jnp.dtype(args.dtype))
    ctrl_cfg = build_control(args, comp)
    from tpu_compressed_dp.control import init_control_state

    state = TrainState.create(
        params, stats, opt.init(params), init_ef_state(params, comp, ndev),
        jax.random.key(args.seed + 1),
        comp=init_comp_state(params, comp, ndev),
        guard=init_guard_state(guard_cfg),
        control=init_control_state(ctrl_cfg),
    )
    apply_fn = make_normalizing_apply_fn(
        module,
        mean=np.asarray(data.CIFAR10_MEAN) * 255.0,
        std=np.asarray(data.CIFAR10_STD) * 255.0,
    )

    step_cache: dict = {}

    def train_step_for(comp_cfg: CompressionConfig):
        # keyed by the tunable knobs: everything else in comp_cfg is fixed
        # for the run, and (ratio, rank) is exactly what the warm-up
        # schedule and the adaptive controller's rung ladder vary — one
        # compile per visited rung, switches only at epoch boundaries
        key = (comp_cfg.ratio, comp_cfg.rank)
        if key not in step_cache:
            step_cache[key] = make_train_step(
                apply_fn, opt, comp_cfg, mesh,
                grad_scale=float(bs), clip_norm=args.clip_norm,
                clip_sent_norm=args.clip_sent_norm,
                guard_cfg=guard_cfg, chaos=chaos)
        return step_cache[key]

    eval_step = make_eval_step(apply_fn, mesh)

    from tpu_compressed_dp.utils.checkpoint import Checkpointer

    ckpt = Checkpointer(args.checkpoint_dir) if args.checkpoint_dir else None
    start_epoch = 0
    if args.resume:
        restorer = Checkpointer(args.resume)
        try:
            state, meta = restorer.restore(state)
        finally:
            restorer.close()
        state = state.with_mesh_sharding(mesh)
        start_epoch = int(meta.get("epoch", -1)) + 1
        print(f"resumed step {int(state.step)} from {args.resume} "
              f"(starting epoch {start_epoch})")

    # epoch summaries print master-only, like the reference's rank-0-gated
    # loggers (`logger.py:74-121`); metrics are globally reduced so every
    # rank computes identical numbers anyway
    rank0 = jax.process_index() == 0
    table, tsv = TableLogger(), TSVLogger()
    # No explicit device sync needed: run_train_epoch keeps metrics on device
    # during the epoch (async dispatch overlaps host batch prep with device
    # work) and its end-of-epoch device_get blocks on everything outstanding —
    # the role torch.cuda.synchronize played in `dawn.py:129`.
    timer = Timer()
    from tpu_compressed_dp.utils.loggers import TensorboardLogger

    tb = TensorboardLogger(
        os.path.join(args.log_dir, "tb")
        if args.log_dir and args.tensorboard and rank0 else None
    )
    hb = make_heartbeat(args)
    from tpu_compressed_dp.obs.export import telemetry_snapshot, write_prometheus
    from tpu_compressed_dp.obs.trace import StepTimeline
    from tpu_compressed_dp.utils import flops as flops_mod

    timeline = StepTimeline()
    events = make_event_stream(
        args, harness="dawn", network=args.network,
        method=args.method, compress=args.compress, mode=args.mode,
        transport=args.transport, batch_size=bs, devices=ndev, epochs=epochs)
    flight = make_flight_recorder(
        args, harness="dawn", network=args.network, method=args.method,
        compress=args.compress, devices=ndev)
    if flight is not None and chaos is not None:
        flight.note_chaos(chaos)
    if flight is not None and crash is not None:
        crash.flight = flight
    stream = make_stream(args, flight=flight, events=events)
    if ckpt is not None:
        ckpt.events = events
        ckpt.flight = flight
        # committed full checkpoints re-anchor the delta stream's window
        ckpt.stream = stream
    preempt = make_preemption()
    el = build_elastic(args, mesh, chaos=chaos, crash=crash, events=events,
                       flight=flight, stream=stream)
    if el is not None and rejoin is not None:
        # watchdog-relaunched host: adopt the running world's replicated
        # state from the re-elected coordinator's broadcast (EF rows start
        # at zero) and retrace the steps on the post-join mesh.  With
        # --stream_rejoin and a warm-committed epoch the params come off
        # the delta stream instead of the broadcast (the survivors'
        # barrier flushed it bitwise-equal to the live params before
        # admitting us, and published the warm bit in the commit).
        adopted_params, adopted_info = stream_rejoin_params(
            args, state, rejoin, flight=flight)
        state = el.join_world(state, rejoin, adopted_params=adopted_params,
                              adopted_info=adopted_info)
        mesh, ndev = el.mesh, el.world
        step_cache.clear()
        eval_step = make_eval_step(apply_fn, mesh)
    controller = None
    hide_frac = 1.0
    if ctrl_cfg is not None:
        from tpu_compressed_dp.control import Controller, comp_for_rung
        from tpu_compressed_dp.harness.loop import build_twin_pricer
        from tpu_compressed_dp.parallel.overlap import (hideable_byte_fraction,
                                                        plan_chunks)
        from tpu_compressed_dp.train.guard import schedule_step

        controller = Controller(ctrl_cfg, events=events,
                                pricer=build_twin_pricer(args, comp,
                                                         world=ndev))
        # the overlap schedule's hideable byte fraction scales the measured
        # compute into the per-update budget (signals.hideable_budget_ms);
        # ignored when --adaptive_budget_ms pins the budget
        hide_frac = hideable_byte_fraction(plan_chunks(
            [leaf.size * 4 for leaf in jax.tree_util.tree_leaves(params)],
            comp))
        print(f"adaptive: method={ctrl_cfg.method} knob={controller.knob} "
              f"rungs={ctrl_cfg.rungs} window={ctrl_cfg.window} "
              f"signal={ctrl_cfg.signal} hideable_frac={hide_frac:.3f}")
    # Per-chip forward FLOPs from XLA's cost model, once (the epoch loop
    # scales it by the measured step rate — utils/flops.py conventions:
    # train = 3x fwd, MFU vs the chip's bf16 peak, omitted off-TPU).  The
    # cost-model pass compiles the bare forward; skip it when nothing can
    # consume the result (no exporter and no known chip peak — the CPU
    # smoke-test case, where it would only slow every run down).
    want_flops = (events is not None or bool(args.prom)
                  or flops_mod.chip_peak_flops() is not None)
    fwd_flops = flops_mod.fwd_flops_xla(
        lambda p, s, x: apply_fn(p, s, x, True, {}),
        params, stats, jnp.zeros((bs // ndev, 32, 32, 3), jnp.float32)
    ) if want_flops else None
    prev_skipped = 0.0
    summary = {}
    # finally-guarded: GuardExceeded / ChaosCrash / any training failure must
    # not leak the heartbeat writer thread — an orphaned writer keeps
    # refreshing ts and turns a dead run into a stale-detection false
    # negative (the exact failure mode the watchdog reads this file for) —
    # nor a running profiler trace or an unterminated event stream
    try:
        cur_train, cur_test, cur_bs = train_batches, test_batches, bs
        epoch = start_epoch
        while epoch < epochs:
            # boundary check: a signal that landed during eval/logging stops
            # the run before the next epoch compiles/dispatches anything
            preempt.check(int(state.step))
            profiling = args.profile_epoch == epoch and args.log_dir
            # adaptive: the checkpointed rung picks the (trace-cached) step
            # variant; otherwise the DGC warm-up schedule picks the ratio
            train_step = train_step_for(
                comp_for_rung(comp, ctrl_cfg, int(state.control.rung))
                if controller is not None
                else comp_for_ratio(ratio_for_epoch(epoch)))
            try:
                with profile_trace(
                        os.path.join(args.log_dir, "profile") if profiling else None):
                    state, epoch_stats, acc = train_epoch(
                        train_step, eval_step, state, cur_train, cur_test,
                        timer, cur_bs, test_time_in_total=False,
                        crash=crash, step_offset=int(state.step),
                        guard_cfg=guard_cfg, timeline=timeline, world=ndev,
                        pods=args.dp_pods,
                        elastic=el, preempt=preempt, flight=flight,
                    )
            except Exception as err:
                failure = el.failure_from(err) if el is not None else None
                if failure is None:
                    if flight is not None and not isinstance(
                            err, resilience.Preempted):
                        # unconverted failure about to unwind the run: the
                        # dump here is the only evidence this rank leaves
                        # (guard/ckpt/elastic dumps fire on their own paths)
                        flight.observe(err, step=int(state.step))
                    raise
                # Coordinated abort: survivors remesh from the last live
                # TrainState (the pre-epoch buffers were donated away at
                # step 0, so run_train_epoch rides its local out on the
                # exception; dispatched steps drain to completion during
                # migration) and replay the rest of the epoch.  Rebuilding
                # the step cache on el.mesh is what recomputes the sharded
                # transport's owner partition; the batch views are trimmed
                # so the smaller world keeps dividing them.  Injectors fire
                # once per process, so the replay does not re-crash.
                state = getattr(err, "elastic_state", state)
                state = el.handle_failure(state, failure)
                mesh, ndev = el.mesh, el.world
                step_cache.clear()
                eval_step = make_eval_step(apply_fn, mesh)
                cur_bs = (bs // ndev) * ndev
                from tpu_compressed_dp.train.elastic import TrimBatches
                cur_train = TrimBatches(train_batches, cur_bs)
                cur_test = TrimBatches(test_batches, cur_bs)
                continue
            if el is not None:
                # epoch-boundary readmission of watchdog-relaunched hosts
                # parked in the rendezvous join barrier (no-op otherwise)
                state, grew = el.rejoin_barrier(state)
                if grew:
                    mesh, ndev = el.mesh, el.world
                    step_cache.clear()
                    eval_step = make_eval_step(apply_fn, mesh)
                    cur_bs = (bs // ndev) * ndev
                    from tpu_compressed_dp.train.elastic import TrimBatches
                    cur_train = TrimBatches(train_batches, cur_bs)
                    cur_test = TrimBatches(test_batches, cur_bs)
            if controller is not None:
                # decisions key off APPLIED updates (guard skips excluded),
                # and the tick lands BEFORE the epoch checkpoint: the saved
                # ControlState already contains this epoch's accumulation,
                # so a crash-relaunch replays the remaining windows bitwise
                # instead of losing this epoch's contribution
                applied = (schedule_step(guard_cfg, state.guard,
                                         int(state.step))
                           if guard_cfg is not None else int(state.step))
                wall_ms = (epoch_stats["train time"] * 1e3
                           / max(acc.steps, 1))
                old_rung = int(state.control.rung)
                new_control, _ = controller.tick(
                    state.control, applied=applied,
                    signals=controller.window_signals(
                        mean_bits=acc.mean("comm/sent_bits"),
                        measured_comm_ms=wall_ms,
                        compute_ms=wall_ms,
                        hideable_fraction=hide_frac))
                state = state.replace(control=new_control)
                new_rung = int(new_control.rung)
                if flight is not None:
                    flight.note_control({"epoch": epoch, "rung": new_rung,
                                         "applied": applied})
                if new_rung != old_rung and controller.knob == "rank":
                    # PowerSGD rank switch: re-seat the warm q columns at
                    # the new rank so the next rung's step variant starts
                    # from the learnt subspace, not a cold re-init
                    from tpu_compressed_dp.control import (comp_for_rung,
                                                           migrate_comp_state)
                    state = state.replace(comp=migrate_comp_state(
                        state.comp, params,
                        comp_for_rung(comp, ctrl_cfg, old_rung),
                        comp_for_rung(comp, ctrl_cfg, new_rung), ndev))
            if (ckpt is not None and args.ckpt_every > 0
                    and (epoch + 1) % args.ckpt_every == 0):
                # async: snapshot to host and return — the write overlaps
                # the next epoch; the next save (or preemption) barriers
                ckpt.save_async(state, {"epoch": epoch})
            if (stream is not None and args.stream_every > 0
                    and (epoch + 1) % args.stream_every == 0):
                # delta segment: codec on this thread, commit in the
                # background (stream/writer.py)
                stream.append_async(state.params, step=int(state.step))
            train_time = epoch_stats["train time"]
            examples = len(cur_train) * cur_bs
            thr = flops_mod.throughput_record(
                fwd_flops, acc.steps / max(train_time, 1e-9),
                examples_per_sec=examples / max(train_time, 1e-9))
            # spans drain ONCE per epoch and fan out to every consumer
            # (event stream, flight recorder's timing ring + phase profile)
            spans = timeline.drain()
            fgauges = flight_update(flight, spans=spans)
            if hb is not None:
                # last_good_step: the watchdog's "is it making progress" signal
                # — a wedged-but-alive run (skipping every step) beats but stops
                # advancing this field.  The telemetry snapshot adds step rate
                # + p95 latency for the watchdog's stall check.
                hb.update(
                    step=int(state.step),
                    last_good_step=(int(state.guard.last_good_step)
                                    if guard_cfg is not None else int(state.step)),
                    epoch=epoch,
                    telemetry=telemetry_snapshot(timeline),
                    **(ckpt.heartbeat_fields() if ckpt is not None else {}),
                    **(stream.heartbeat_fields() if stream is not None
                       else {}),
                    **({"elastic": el.metrics()} if el is not None else {}),
                    **(controller.heartbeat_fields(state.control)
                       if controller is not None else {}),
                    **({"straggler_skew_s": fgauges["straggler/skew_s"],
                        "straggler_rank": fgauges["straggler/rank"]}
                       if "straggler/skew_s" in fgauges else {}),
                )
            summary = {
                "epoch": epoch + 1,
                "lr": float(sched((epoch + 1))),
                **{k: (float(v) if isinstance(v, (int, float, np.floating)) else v)
                   for k, v in epoch_stats.items()},
                "img/s": round(thr.get("throughput/examples_per_sec", 0.0), 1),
            }
            if "throughput/mfu" in thr:
                summary["mfu"] = round(thr["throughput/mfu"], 4)
            summary.update(control_summary(controller, state.control))
            guard_last = {k: v for k, v in acc.last.items()
                          if k.startswith("guard/")}
            comm_means = {k: acc.mean(k) for k in acc.sums
                          if k.startswith("comm/")}
            control_stats = (controller.metrics(state.control)
                             if controller is not None else {})
            if events is not None:
                events.emit(
                    "epoch", epoch=epoch + 1, step=int(state.step),
                    metrics={k: v for k, v in summary.items()
                             if isinstance(v, (int, float))},
                    throughput=thr, comm=comm_means, guard=guard_last,
                    control=control_stats,
                    timeline=timeline.snapshot(),
                    step_spans=spans)
                skipped = guard_last.get("guard/skipped", 0.0)
                if skipped > prev_skipped:
                    events.emit("guard", epoch=epoch + 1,
                                step=int(state.step), **guard_last)
                prev_skipped = skipped
            if args.prom and rank0:
                write_prometheus(
                    {"loss": summary["train loss"], "lr": summary["lr"],
                     **thr, **comm_means, **guard_last, **control_stats,
                     **timeline.snapshot(),
                     **(ckpt.metrics() if ckpt is not None else {}),
                     **(stream.metrics() if stream is not None else {}),
                     **(el.metrics() if el is not None else {}),
                     **fgauges},
                    job_scoped(args, args.prom),
                    labels=prom_labels(args, harness="dawn"))
            if rank0:
                table.append(summary)
                tsv.append(summary)
                tb.update_examples_count(len(cur_train) * cur_bs)
                tb.log_metrics({f"losses/{k}": v for k, v in summary.items()
                                if k in ("train loss", "test loss", "train acc", "test acc")})
                tb.log_scalar("times/epoch_seconds", summary["train time"])
            epoch += 1
        if args.log_dir and rank0:
            tsv.save(args.log_dir)
    except resilience.Preempted as err:
        # SIGTERM/SIGINT landed: drain the in-flight async write, cut a
        # synchronous emergency checkpoint of the live state, and exit with
        # the watchdog's relaunch-immediately code (the finally below still
        # runs — ckpt.close after the emergency save is a no-op drain)
        state = getattr(err, "elastic_state", state)
        raise preempt_exit(err, ckpt=ckpt, state=state,
                           meta={"epoch": epoch - 1}, events=events,
                           flight=flight) from None
    finally:
        preempt.uninstall()
        tb.close()
        if ckpt is not None:
            ckpt.close()  # drains the background writer before events close
        if stream is not None:
            stream.close()  # drains the in-flight segment commit
        if events is not None:
            events.close()
        if hb is not None:
            hb.stop()
    return summary


def main(argv: Optional[list] = None):
    args = build_parser().parse_args(argv)
    return run(args)


if __name__ == "__main__":
    main()
