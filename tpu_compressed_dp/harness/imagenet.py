"""ImageNet ResNet-50 harness — the `IMAGENET/training/train_imagenet_nv.py`
equivalent.

Feature parity (`train_imagenet_nv.py`):
  * phase-schedule mini-DSL mixing data phases (``ep/sz/bs/min_scale/
    rect_val/keep_dl``) and LR phases (``ep/lr`` scalar or ramp), per-batch LR
    granularity (`:545-651`); the default schedule is the reference's
    one-machine 93%-top-5 recipe (`train.py:60-72`);
  * progressive image resizing with per-phase loaders (``DataManager``); on
    TPU each new (bs, sz) is simply a new jit specialisation, pre-warmed at
    phase start the way the reference preloaded loaders (`:575-580`);
  * bf16 compute + fp32 master params (the fp16 + loss-scale-1024 machinery of
    `fp16util.py` collapses to a flax dtype policy on TPU — see models/resnet.py);
  * ``--init-bn0`` zero-gamma init, ``--no-bn-wd`` BN weight-decay exclusion
    (`:168,183-184`);
  * the full compression surface (layer-wise / entire-model x 6 methods,
    simulate / wire, error feedback) in the step (`:417-422`);
  * validation every epoch with global top-1/top-5 psum (the
    ``distributed_predict`` semantics, `:523-542`), rect-val supported;
  * Orbax checkpoint-if-best + phase-boundary saves, ``--resume`` (`:193-198,
    236-253`); the EF residual checkpoints too (fixes SURVEY.md §5 gap);
  * ``--short-epoch`` 10-batch truncation (`:74-75,399,491`) and
    ``--evaluate`` val-only mode (`:58-59,225-226`).

Gradient scale: the reference ImageNet step backpropagates the *mean* loss and
allreduce-averages (`:408,417-422`), so ``grad_scale=1.0`` here (the CIFAR
harness's summed-loss protocol does not apply).

Run (smoke): ``python -m tpu_compressed_dp.harness.imagenet --synthetic
--arch resnet18 --width 16 --num_classes 10 --short_epoch``
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_compressed_dp.data import imagenet as data
from tpu_compressed_dp.harness.loop import (
    add_adaptive_args,
    add_robustness_args,
    add_stream_args,
    add_telemetry_args,
    add_topology_args,
    fabric_gauges,
    build_control,
    build_elastic,
    build_robustness,
    control_summary,
    elastic_distributed_init,
    flight_update,
    job_scoped,
    make_event_stream,
    make_flight_recorder,
    make_heartbeat,
    make_preemption,
    make_stream,
    prom_labels,
    stream_rejoin_params,
    comm_summary,
    guard_summary,
    pad_batch,
    preempt_exit,
    profile_trace,
    run_eval,
    run_train_epoch,
)
from tpu_compressed_dp.obs.export import telemetry_snapshot, write_prometheus
from tpu_compressed_dp.obs.trace import StepTimeline
from tpu_compressed_dp.utils import flops as flops_mod
from tpu_compressed_dp.models import resnet as resnet_mod
from tpu_compressed_dp.models.common import init_model, make_apply_fn
from tpu_compressed_dp.parallel.dp import (CompressionConfig, init_comp_state,
                                           init_ef_state)
from tpu_compressed_dp.parallel.mesh import (
    make_data_mesh,
    make_global_batch,
)
from tpu_compressed_dp.train.optim import SGD, bn_wd_mask
from tpu_compressed_dp.train.guard import init_guard_state
from tpu_compressed_dp.train.schedules import phase_lr_schedule_variable_bs
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.step import make_eval_step, make_train_step
from tpu_compressed_dp.utils import resilience
from tpu_compressed_dp.utils.checkpoint import Checkpointer
from tpu_compressed_dp.utils.loggers import (
    FileLogger,
    TableLogger,
    TensorboardLogger,
    TSVLogger,
)
from tpu_compressed_dp.utils.meters import NetworkMeter
from tpu_compressed_dp.utils.timer import Timer

ARCHS = {
    "resnet18": resnet_mod.resnet18,
    "resnet34": resnet_mod.resnet34,
    "resnet50": resnet_mod.resnet50,
    "resnet101": resnet_mod.resnet101,
    "resnet152": resnet_mod.resnet152,
}


def one_machine_phases() -> List[dict]:
    """The reference's single-machine schedule — 93.00 top-5 in 109 min on
    8x V100 (`IMAGENET/train.py:55-72`): 128px/bs512 -> 224px/bs224 ->
    288px/bs128 with warmup and step decays.  ``bs`` here is the *global*
    batch (reference bs was per-GPU x 8 GPUs)."""
    lr = 1.0
    scale_224 = 224 / 512
    scale_288 = 128 / 512
    return [
        {"ep": 0, "sz": 128, "bs": 512 * 8},
        {"ep": (0, 5), "lr": (lr, lr * 2)},
        {"ep": 5, "lr": lr},
        {"ep": 14, "sz": 224, "bs": 224 * 8, "lr": lr * scale_224},
        {"ep": 16, "lr": lr / 10 * scale_224},
        {"ep": 27, "lr": lr / 100 * scale_224},
        {"ep": 32, "sz": 288, "bs": 128 * 8, "min_scale": 0.5, "rect_val": True,
         "lr": lr / 100 * scale_288},
        {"ep": (33, 35), "lr": lr / 1000 * scale_288},
    ]


def smoke_phases(bs: int = 64) -> List[dict]:
    """Tiny 3-epoch progressive-resize schedule for tests and CPU smoke."""
    return [
        {"ep": 0, "sz": 64, "bs": bs},
        {"ep": (0, 1), "lr": (0.1, 0.2)},
        {"ep": 1, "lr": 0.1},
        {"ep": 2, "sz": 96, "bs": bs // 2, "rect_val": True},
        {"ep": (2, 3), "lr": (0.01, 0.001)},
    ]


def data_phases(phases: List[dict]) -> List[dict]:
    return [p for p in phases if "sz" in p or p.get("keep_dl")]


def total_epochs(phases: List[dict]) -> int:
    """``Scheduler.tot_epochs`` (`train_imagenet_nv.py:607`): max epoch edge."""
    out = 0
    for p in phases:
        ep = p["ep"]
        out = max(out, int(max(ep) if isinstance(ep, (tuple, list)) else ep) + 0)
    return out if out > 0 else 1


class PhaseData:
    """``DataManager`` equivalent (`train_imagenet_nv.py:545-598`): owns the
    current train/val loaders, swapping them at phase-start epochs."""

    def __init__(self, dataset_train, dataset_val, phases: List[dict], *,
                 workers: int = 8, seed: int = 0, min_scale_default: float = 0.08,
                 ar_buckets: int = 8):
        raw = data_phases(phases)
        if not raw or raw[0]["ep"] != 0:
            raise ValueError("first data phase must start at ep 0")
        # Resolve keep_dl up front: each effective phase carries full
        # sz/bs/... settings (a keep_dl phase inherits from its predecessor,
        # `train_imagenet_nv.py:560-565`).
        self.phases: List[dict] = []
        for p in raw:
            merged = {**self.phases[-1], **p} if p.get("keep_dl") and self.phases else dict(p)
            self.phases.append(merged)
        self.ds_train, self.ds_val = dataset_train, dataset_val
        self.workers, self.seed = workers, seed
        self.min_scale_default = min_scale_default
        self.ar_buckets = ar_buckets
        self.cur: Optional[dict] = None
        self.train_loader = None
        self.val_loader = None
        self.val_bs = None

    def phase_at(self, epoch: int) -> dict:
        """The phase governing ``epoch`` (last phase with start <= epoch)."""
        out = self.phases[0]
        for p in self.phases:
            if p["ep"] <= epoch:
                out = p
        return out

    def set_epoch(self, epoch: int) -> bool:
        """Build/swap loaders for the phase governing ``epoch``; returns True
        on a swap (= new shapes are about to hit jit).  Works mid-phase too
        (resume from any epoch, not just phase starts)."""
        phase = self.phase_at(epoch)
        swapped = False
        if phase is not self.cur:
            sz, bs = int(phase["sz"]), int(phase["bs"])
            pi, pc = jax.process_index(), jax.process_count()
            self.train_loader = data.TrainLoader(
                self.ds_train, bs // pc, sz,
                min_scale=float(phase.get("min_scale", self.min_scale_default)),
                seed=self.seed, workers=self.workers,
                process_index=pi, process_count=pc,
            )
            self.val_bs = data.val_batch_size(sz, bs)
            # Rect-val hands each process differently-shaped local batches —
            # fine under the reference's per-process NCCL, incompatible with
            # one global SPMD array; multi-host falls back to square val.
            rect = bool(phase.get("rect_val", False)) and pc == 1
            self.val_loader = data.ValLoader(
                self.ds_val, self.val_bs // pc, sz,
                rect_val=rect,
                ar_buckets=self.ar_buckets, workers=self.workers,
                process_index=pi, process_count=pc,
            )
            self.cur = phase
            swapped = True
        self.train_loader.set_epoch(epoch)
        return swapped

    def epoch_batches(self, epochs: int) -> List[int]:
        """Per-epoch step counts for the step->epoch LR map."""
        pc = jax.process_count()
        out = []
        for e in range(epochs):
            bs = int(self.phase_at(e)["bs"]) // pc
            out.append(max((len(self.ds_train) // pc) // bs, 1))
        return out


def _normalizing_apply_fn(module):
    """uint8 NHWC batches normalised on device — the
    ``BatchTransformDataLoader.process_tensors`` trick (`dataloader.py:92-99`)
    via the shared adapter."""
    from tpu_compressed_dp.models.common import make_normalizing_apply_fn

    return make_normalizing_apply_fn(module, data.IMAGENET_MEAN, data.IMAGENET_STD)


def build_parser() -> argparse.ArgumentParser:
    # flag surface mirrors `train_imagenet_nv.py:39-91`
    p = argparse.ArgumentParser(description="ImageNet compressed-DP harness")
    p.add_argument("data", nargs="?", default=None, help="ImageFolder root with train/ and validation/")
    p.add_argument("--arch", "-a", default="resnet50", choices=sorted(ARCHS))
    p.add_argument("--width", type=int, default=64, help="stem width (64 = standard)")
    p.add_argument("--num_classes", type=int, default=1000)
    p.add_argument("--phases", type=str, default=None,
                   help="JSON phase list; default = reference one-machine schedule")
    p.add_argument("--lr_scale", type=float, default=1.0,
                   help="multiply all phase LRs (bs scaling)")
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight_decay", "--wd", type=float, default=1e-4)
    p.add_argument("--no_bn_wd", action="store_true", help="exclude BN params from wd")
    p.add_argument("--init_bn0", action="store_true", help="zero-init last-BN gammas")
    p.add_argument("--fp32", action="store_true", help="disable bf16 compute")
    p.add_argument("--compress", "-c", default="none", choices=["none", "layerwise", "entiremodel", "bucketed"])
    p.add_argument("--method", default="none")
    p.add_argument("--ratio", "-K", type=float, default=0.5)
    p.add_argument("--threshold", "-V", type=float, default=0.001)
    p.add_argument("--qstates", "-Q", type=int, default=255)
    p.add_argument("--rank", type=int, default=4,
                   help="r for powersgd (psum-ring low-rank factors)")
    p.add_argument("--block_size", type=int, default=256,
                   help="blocktopk: elements per contiguous block")
    p.add_argument("--bucket_mb", type=float, default=25.0,
                   help="bucketed granularity: capacity per bucket")
    p.add_argument("--mode", default="simulate", choices=["simulate", "wire"])
    p.add_argument("--transport", default="allgather",
                   choices=["allgather", "sharded", "hierarchical"],
                   help="wire combine for index-carrying sparsifiers: flat "
                        "all_gather (O(W*k)/chip), owner-sharded reduce "
                        "(O(k + n/W)/chip, ops/wire_sharded.py; size caps "
                        "via comm/shard_overflow), or the two-level "
                        "hierarchical reduce over a --dp_pods x chips "
                        "virtual mesh (dense intra-pod psum + sparse "
                        "inter-pod exchange, O(k + n/W_pods) DCN bytes)")
    add_topology_args(p)
    p.add_argument("--error_feedback", action="store_true")
    p.add_argument("--overlap", type=int, default=1,
                   help="chunk-pipelined sync (parallel/overlap.py): up to "
                        "K reverse-topological chunk collectives interleaved "
                        "with backward + per-chunk optimizer compute; "
                        "numerics unchanged (1 = single dispatch)")
    p.add_argument("--wire_cap_ratio", type=float, default=0.05,
                   help="wire thresholdv/adaptive_threshold transport "
                        "capacity (fraction of elements)")
    p.add_argument("--clip_norm", type=float, default=0.0,
                   help="local-gradient L2 clip (0=off) — EF+momentum "
                        "stabiliser (see tools/ef_bisect.py)")
    p.add_argument("--clip_sent_norm", type=float, default=0.0,
                   help="post-aggregation L2 clip of the synced gradient "
                        "(bounds the EF residual spike)")
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--seed", type=int, default=2147483647)  # `train_imagenet_nv.py:82`
    p.add_argument("--short_epoch", action="store_true", help="10-batch epochs")
    p.add_argument("--evaluate", action="store_true")
    p.add_argument("--resume", type=str, default=None)
    p.add_argument("--checkpoint_dir", type=str, default=None)
    p.add_argument("--best_floor", type=float, default=0.0,
                   help="min top-5 before checkpointing (reference used 93)")
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--synthetic_n", type=int, default=512)
    # robustness: shared --guard*/--chaos/--heartbeat surface
    add_robustness_args(p, check_note="checked at epoch end")
    # adaptive compression: shared --adaptive* surface (control/)
    add_adaptive_args(p)
    # delta state streaming: shared --stream* surface (stream/)
    add_stream_args(p, cadence_help="epochs between delta-stream appends "
                                    "(requires --stream_dir; 0 disables "
                                    "the periodic append)")
    # telemetry: shared --events/--prom surface (obs/export.py)
    add_telemetry_args(p)
    p.add_argument("--logdir", type=str, default=None)
    p.add_argument("--tensorboard", action="store_true",
                   help="write tensorboard scalars under <logdir>/tb")
    p.add_argument("--profile_epoch", type=int, default=None,
                   help="jax.profiler-trace this epoch to <logdir>/profile")
    # multi-host rendezvous
    p.add_argument("--coordinator", type=str, default=None)
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    return p


def _truncate(it, n: Optional[int]):
    if n is None:
        yield from it
        return
    for i, b in enumerate(it):
        if i >= n:
            break
        yield b


def run(args) -> Dict[str, float]:
    # CLI-flag consistency first, before any I/O or device work (same refusal
    # as the CIFAR harness; the reference silently trained dense here).
    if args.method.lower() != "none" and args.compress == "none":
        raise ValueError(
            f"--method {args.method} requires --compress layerwise|entiremodel"
        )
    rejoin = elastic_distributed_init(args)
    mesh = make_data_mesh(args.devices)
    ndev = mesh.shape["data"]

    if args.synthetic:
        ds_train = data.SyntheticImages(args.synthetic_n, args.num_classes, seed=0)
        ds_val = data.SyntheticImages(max(args.synthetic_n // 4, 64), args.num_classes, seed=7)
    else:
        if not args.data:
            raise ValueError("pass an ImageFolder root or --synthetic")
        ds_train = data.ImageFolder(f"{args.data}/train")
        ds_val = data.ImageFolder(f"{args.data}/validation")

    phases = json.loads(args.phases) if args.phases else (
        smoke_phases() if args.synthetic else one_machine_phases()
    )
    if args.lr_scale != 1.0:
        for p in phases:
            if "lr" in p:
                lr = p["lr"]
                p["lr"] = tuple(v * args.lr_scale for v in lr) if isinstance(
                    lr, (tuple, list)) else lr * args.lr_scale
    epochs = total_epochs(phases)

    pd = PhaseData(ds_train, ds_val, phases, workers=args.workers, seed=args.seed)
    epoch_batches = pd.epoch_batches(epochs)
    if args.short_epoch:
        epoch_batches = [min(n, 10) for n in epoch_batches]
    lr_sched = phase_lr_schedule_variable_bs(phases, epoch_batches)

    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    module = ARCHS[args.arch](num_classes=args.num_classes, bn0=args.init_bn0,
                              dtype=dtype, width=args.width)
    first_sz = int(pd.phases[0]["sz"])
    params, stats = init_model(module, jax.random.key(args.seed % (2**31)),
                               jnp.zeros((1, first_sz, first_sz, 3), jnp.float32))
    apply_fn = _normalizing_apply_fn(module)

    opt = SGD(
        lr=lr_sched, momentum=args.momentum, nesterov=False,
        weight_decay=args.weight_decay,
        wd_mask=bn_wd_mask(params) if args.no_bn_wd else None,
    )
    comp = CompressionConfig(
        method=None if args.compress == "none" or args.method.lower() == "none" else args.method,
        granularity=args.compress if args.compress != "none" else "layerwise",
        mode=args.mode, ratio=args.ratio, threshold=args.threshold,
        qstates=args.qstates, block_size=args.block_size,
        bucket_mb=args.bucket_mb,
        wire_cap_ratio=args.wire_cap_ratio,
        transport=args.transport,
        dp_pods=args.dp_pods,
        hier_route_factor_ici=args.hier_route_factor_ici,
        hier_route_factor_dcn=args.hier_route_factor_dcn,
        rank=args.rank,
        error_feedback=args.error_feedback,
        sync_overlap=args.overlap,
    )
    guard_cfg, chaos, crash = build_robustness(args, dtype)
    ctrl_cfg = build_control(args, comp)
    from tpu_compressed_dp.control import init_control_state

    state = TrainState.create(
        params, stats, opt.init(params), init_ef_state(params, comp, ndev),
        jax.random.key((args.seed + 1) % (2**31)),
        comp=init_comp_state(params, comp, ndev),
        guard=init_guard_state(guard_cfg),
        control=init_control_state(ctrl_cfg),
    )

    ckpt = Checkpointer(args.checkpoint_dir) if args.checkpoint_dir else None
    start_epoch = 0
    if args.resume:
        restore = Checkpointer(args.resume)
        state, meta = restore.restore(state)
        restore.close()
        state = state.with_mesh_sharding(mesh)
        start_epoch = int(meta.get("epoch", 0)) + 1
        if ckpt is not None and restore.best_metric is not None:
            # carry best-so-far forward so a worse epoch can't evict the true
            # best (the reference restores best_top5, `train_imagenet_nv.py:195-197`)
            ckpt.best_metric = restore.best_metric
        print(f"resumed step {int(state.step)} (epoch {start_epoch})")

    step_cache: Dict = {}

    def active_comp() -> CompressionConfig:
        """The compression config the next epoch should trace under: the
        controller's checkpointed rung when adaptive, the static one else."""
        if ctrl_cfg is None:
            return comp
        from tpu_compressed_dp.control import comp_for_rung
        return comp_for_rung(comp, ctrl_cfg, int(state.control.rung))

    def train_step_for(comp_cfg: CompressionConfig):
        # keyed by the tunable knobs (the rung ladder varies exactly these);
        # cleared wholesale on remesh — entries close over the current mesh
        key = (comp_cfg.ratio, comp_cfg.rank)
        if key not in step_cache:
            step_cache[key] = make_train_step(
                apply_fn, opt, comp_cfg, mesh, grad_scale=1.0,
                clip_norm=args.clip_norm,
                clip_sent_norm=args.clip_sent_norm,
                guard_cfg=guard_cfg, chaos=chaos)
        return step_cache[key]

    train_step = train_step_for(active_comp())
    eval_step = make_eval_step(apply_fn, mesh)

    def validate(state) -> Dict[str, float]:
        # pad to the *local* static batch, then form global arrays — every
        # process runs the same batch count (DistValSampler semantics).
        # After an elastic remesh the world may stop dividing the loader's
        # batch, so the static eval batch is the largest world-divisible
        # size (identical to local_bs on the launch mesh); surplus rows of
        # a full batch are trimmed, short batches are padded+masked.
        loader = pd.val_loader
        per = int(mesh.shape["data"]) // jax.process_count()
        eval_bs = max((loader.batch_size // per) * per, per)

        def batches():
            for b in _truncate(loader, 10 if args.short_epoch else None):
                b = {k: v[:eval_bs] for k, v in b.items()}
                yield make_global_batch(pad_batch(b, eval_bs), mesh)

        return run_eval(eval_step, state, batches(), eval_bs * jax.process_count())

    table, tsv = TableLogger(), TSVLogger()
    timer = Timer()
    t0 = time.time()
    summary: Dict[str, float] = {}
    is_master = jax.process_index() == 0
    tb = TensorboardLogger(
        os.path.join(args.logdir, "tb") if args.logdir and args.tensorboard else None,
        is_master=is_master,
    )
    flog = FileLogger(args.logdir if is_master else None, rank=jax.process_index(),
                      is_master=is_master)
    net_meter = NetworkMeter()
    hb = make_heartbeat(args)
    timeline = StepTimeline()
    events = make_event_stream(
        args, harness="imagenet", arch=args.arch, method=args.method,
        compress=args.compress, mode=args.mode, transport=args.transport,
        devices=ndev, epochs=epochs)
    flight = make_flight_recorder(
        args, harness="imagenet", arch=args.arch, method=args.method,
        compress=args.compress, devices=ndev)
    if flight is not None and chaos is not None:
        flight.note_chaos(chaos)
    if flight is not None and crash is not None:
        crash.flight = flight
    stream = make_stream(args, flight=flight, events=events)
    if ckpt is not None:
        ckpt.events = events   # save/rollback records on the run's stream
        ckpt.flight = flight
        # committed full checkpoints re-anchor the delta stream's window
        ckpt.stream = stream
    preempt = make_preemption()
    el = build_elastic(args, mesh, chaos=chaos, crash=crash, events=events,
                       flight=flight, stream=stream)
    if el is not None and rejoin is not None:
        # watchdog-relaunched host: the surviving world is mid-training.
        # Adopt its replicated state (broadcast from the re-elected
        # coordinator), zero EF rows, and train on the joined mesh — the
        # jitted steps built above targeted the fresh-init mesh and are
        # rebuilt against the post-join one.  With --stream_rejoin the
        # params adopt from the delta stream, not the broadcast.
        adopted_params, adopted_info = stream_rejoin_params(
            args, state, rejoin, flight=flight)
        state = el.join_world(state, rejoin, adopted_params=adopted_params,
                              adopted_info=adopted_info)
        mesh, ndev = el.mesh, el.world
        step_cache.clear()
        train_step = train_step_for(active_comp())
        eval_step = make_eval_step(apply_fn, mesh)
    controller = None
    hide_frac = 1.0
    if ctrl_cfg is not None:
        from tpu_compressed_dp.control import Controller
        from tpu_compressed_dp.harness.loop import build_twin_pricer
        from tpu_compressed_dp.parallel.overlap import (hideable_byte_fraction,
                                                        plan_chunks)
        from tpu_compressed_dp.train.guard import schedule_step

        controller = Controller(ctrl_cfg, events=events,
                                pricer=build_twin_pricer(args, comp,
                                                         world=ndev))
        hide_frac = hideable_byte_fraction(plan_chunks(
            [leaf.size * 4 for leaf in jax.tree_util.tree_leaves(params)],
            comp))
        print(f"adaptive: method={ctrl_cfg.method} knob={controller.knob} "
              f"rungs={ctrl_cfg.rungs} window={ctrl_cfg.window} "
              f"signal={ctrl_cfg.signal} hideable_frac={hide_frac:.3f}")
    # per-(size, batch) forward FLOPs from the XLA cost model — progressive
    # resizing changes the shape per phase, so cache per shape.  Skipped
    # entirely when nothing can consume the result (no exporter, no known
    # chip peak): the cost-model pass compiles the bare forward per phase.
    fwd_cache: Dict[tuple, Optional[float]] = {}
    want_flops = (events is not None or bool(args.prom)
                  or flops_mod.chip_peak_flops() is not None)

    def fwd_flops_for_phase(phase) -> Optional[float]:
        if not want_flops:
            return None
        sz, per_chip = int(phase["sz"]), max(int(phase["bs"]) // ndev, 1)
        key = (sz, per_chip)
        if key not in fwd_cache:
            fwd_cache[key] = flops_mod.fwd_flops_xla(
                lambda p, s, x: apply_fn(p, s, x, True, {}),
                state.params, state.batch_stats,
                jnp.zeros((per_chip, sz, sz, 3), jnp.float32))
        return fwd_cache[key]

    prev_skipped = 0.0
    fabric_g: dict = {}  # previous epoch's net/ per-fabric gauges
    # finally-guarded: GuardExceeded / ChaosCrash / any failure must not
    # leak the heartbeat writer thread (an orphaned writer keeps the ts
    # fresh and defeats staleness detection), the checkpoint manager, a
    # running profiler trace, or an unterminated event stream
    try:
        if args.evaluate:
            # a finished run evaluates at its final phase's resolution
            pd.set_epoch(min(start_epoch, epochs - 1))
            stats_val = validate(state)
            print(f"top1 {stats_val['acc']*100:.2f} top5 {stats_val['acc5']*100:.2f}")
            return stats_val

        epoch = start_epoch
        while epoch < epochs:
            # a SIGTERM between epochs cuts the emergency save here rather
            # than after another full epoch of (doomed) work
            preempt.check(int(state.step))
            swapped = pd.set_epoch(epoch)
            if swapped and ckpt and epoch > 0:
                # phase-boundary save (`train_imagenet_nv.py:251-253`);
                # async — the new phase's jit warmup hides the write
                ckpt.save_async(state, {"epoch": epoch - 1,
                                        "phase_boundary": True})

            def train_batches():
                # after a remesh the loader's batch may stop dividing the
                # world; trim each batch to the largest divisible row count
                per = int(mesh.shape["data"]) // jax.process_count()
                for b in _truncate(pd.train_loader, 10 if args.short_epoch else None):
                    rows = (len(b["target"]) // per) * per
                    if rows == 0:
                        continue
                    yield make_global_batch({k: v[:rows] for k, v in b.items()},
                                            mesh)

            profiling = args.profile_epoch == epoch and args.logdir
            try:
                with profile_trace(
                        os.path.join(args.logdir, "profile") if profiling else None):
                    state, acc = run_train_epoch(train_step, state, train_batches(),
                                                 crash=crash,
                                                 step_offset=int(state.step),
                                                 guard_cfg=guard_cfg,
                                                 timeline=timeline,
                                                 elastic=el,
                                                 preempt=preempt,
                                                 flight=flight)
            except Exception as err:  # noqa: BLE001 - converted or re-raised
                failure = el.failure_from(err) if el is not None else None
                if failure is None:
                    if flight is not None and not isinstance(
                            err, resilience.Preempted):
                        # unconverted failure about to unwind the run: the
                        # dump here is the only evidence this rank leaves
                        flight.observe(err, step=int(state.step))
                    raise
                # coordinated abort: remesh from the last live TrainState
                # (donation consumed the pre-epoch buffers; run_train_epoch
                # rides its local out on the exception), migrate EF/comp
                # onto the surviving mesh, rebuild the jitted steps (the
                # sharded transport's owner partition is a function of W
                # and recomputes at trace time), re-run the epoch's rest
                state = getattr(err, "elastic_state", state)
                state = el.handle_failure(state, failure)
                mesh, ndev = el.mesh, el.world
                step_cache.clear()
                train_step = train_step_for(active_comp())
                eval_step = make_eval_step(apply_fn, mesh)
                fwd_cache.clear()
                continue
            if el is not None:
                # epoch-boundary readmission: fold any watchdog-relaunched
                # host parked in the rendezvous join barrier into a new
                # world epoch (no-op single-process / no joins pending)
                state, grew = el.rejoin_barrier(state)
                if grew:
                    mesh, ndev = el.mesh, el.world
                    step_cache.clear()
                    train_step = train_step_for(active_comp())
                    eval_step = make_eval_step(apply_fn, mesh)
                    fwd_cache.clear()
            if (stream is not None and args.stream_every > 0
                    and (epoch + 1) % args.stream_every == 0):
                # delta segment: codec on this thread, commit in the
                # background (stream/writer.py)
                stream.append_async(state.params, step=int(state.step))
            # spans drain ONCE per epoch and fan out to every consumer
            # (event stream, flight recorder's timing ring + phase profile)
            spans = timeline.drain()
            fgauges = flight_update(flight, spans=spans)
            if hb is not None:
                hb.update(
                    step=int(state.step),
                    last_good_step=(int(state.guard.last_good_step)
                                    if guard_cfg is not None else int(state.step)),
                    epoch=epoch,
                    telemetry=telemetry_snapshot(timeline),
                    **(ckpt.heartbeat_fields() if ckpt is not None else {}),
                    **(stream.heartbeat_fields() if stream is not None
                       else {}),
                    **({"elastic": el.metrics()} if el is not None else {}),
                    **(controller.heartbeat_fields(state.control)
                       if controller is not None else {}),
                    # last finished epoch's per-fabric billing: lets a
                    # fleet poll see the DCN demand without scraping prom
                    **({"net": fabric_g} if fabric_g else {}),
                    **({"straggler_skew_s": fgauges["straggler/skew_s"],
                        "straggler_rank": fgauges["straggler/rank"]}
                       if "straggler/skew_s" in fgauges else {}),
                )
            train_time = timer()
            if controller is not None:
                # decision tick at the epoch cadence, keyed to APPLIED
                # updates; lands before this epoch's save_if_best and the
                # next phase-boundary save, so the checkpointed ControlState
                # carries the accumulation (bitwise crash/resume)
                applied = (schedule_step(guard_cfg, state.guard,
                                         int(state.step))
                           if guard_cfg is not None else int(state.step))
                wall_ms = train_time * 1e3 / max(acc.steps, 1)
                old_rung = int(state.control.rung)
                # on a 2-level topology the modeled signal prices only the
                # DCN-billed share — the fabric --adaptive_bw_mbps budgets
                from tpu_compressed_dp.control.signals import \
                    billed_signal_bits

                new_control, _ = controller.tick(
                    state.control, applied=applied,
                    signals=controller.window_signals(
                        mean_bits=billed_signal_bits(
                            {k: acc.mean(k) for k in acc.sums
                             if k.startswith("comm/")}, args.dp_pods),
                        measured_comm_ms=wall_ms,
                        compute_ms=wall_ms,
                        hideable_fraction=hide_frac))
                state = state.replace(control=new_control)
                new_rung = int(new_control.rung)
                if flight is not None:
                    flight.note_control({"epoch": epoch, "rung": new_rung,
                                         "applied": applied})
                if new_rung != old_rung:
                    if controller.knob == "rank":
                        # PowerSGD rank switch: re-seat warm q columns at
                        # the new rank before the next epoch traces
                        from tpu_compressed_dp.control import (
                            comp_for_rung, migrate_comp_state)
                        state = state.replace(comp=migrate_comp_state(
                            state.comp, state.params,
                            comp_for_rung(comp, ctrl_cfg, old_rung),
                            comp_for_rung(comp, ctrl_cfg, new_rung), ndev))
                    train_step = train_step_for(active_comp())
            val_stats = validate(state)
            timer()
            top1, top5 = val_stats["acc"] * 100, val_stats["acc5"] * 100
            hours = (time.time() - t0) / 3600
            # `~~epoch\thours\ttop1\ttop5` event line (`train_imagenet_nv.py:232,243`)
            flog.event(f"~~{epoch}\t{hours:.5f}\t\t{top1:.3f}\t\t{top5:.3f}\n")
            examples = int(acc.sums.get("count", 0.0))
            img_s = examples / train_time if train_time > 0 else 0.0
            thr = flops_mod.throughput_record(
                fwd_flops_for_phase(pd.cur),
                acc.steps / max(train_time, 1e-9), examples_per_sec=img_s)
            summary = {
                "epoch": epoch, "train time": train_time,
                "train loss": acc.mean("loss"),
                "test loss": val_stats["loss"], "top1": top1, "top5": top5,
                "test acc": val_stats["acc"],  # TSVLogger's top1 column
                "total time": timer.total_time,
                "img/s": round(img_s, 1),
            }
            if "throughput/mfu" in thr:
                summary["mfu"] = round(thr["throughput/mfu"], 4)
            summary.update(comm_summary(acc))
            summary.update(guard_summary(acc))
            summary.update(control_summary(controller, state.control))
            comm_means = {k: acc.mean(k) for k in acc.sums
                          if k.startswith("comm/")}
            guard_last = {k: v for k, v in acc.last.items()
                          if k.startswith("guard/")}
            control_stats = (controller.metrics(state.control)
                             if controller is not None else {})
            # analytic per-chip link traffic at the epoch's measured rate,
            # method-aware (VERDICT r2 #2): shared transport-split arithmetic
            # with bench/sweep.py and the other harnesses
            from tpu_compressed_dp.utils.meters import per_chip_comm_bytes

            per_chip_b = per_chip_comm_bytes(comm_means, ndev, args.dp_pods)
            if per_chip_b is not None and train_time > 0:
                summary["comm MB/s"] = per_chip_b * acc.steps / train_time / 1e6
            # per-fabric net/ gauges (empty on a flat mesh): what the DCN
            # specifically must sustain — the signal a cross-pod budget is
            # set against (tools/control_report.py --bw columns)
            fabric_g = fabric_gauges(comm_means, ndev, args.dp_pods,
                                     acc.steps, train_time)
            table.append(summary)
            tsv.append(summary)
            if events is not None:
                events.emit(
                    "epoch", epoch=epoch, step=int(state.step),
                    metrics={k: v for k, v in summary.items()
                             if isinstance(v, (int, float))},
                    throughput=thr, comm=comm_means, guard=guard_last,
                    control=control_stats,
                    timeline=timeline.snapshot(),
                    step_spans=spans)
                skipped = guard_last.get("guard/skipped", 0.0)
                if skipped > prev_skipped:
                    events.emit("guard", epoch=epoch, step=int(state.step),
                                **guard_last)
                prev_skipped = skipped
            if args.prom and is_master:
                write_prometheus(
                    {"loss": summary["train loss"], **thr, **comm_means,
                     **fabric_g,
                     **guard_last, **control_stats, **timeline.snapshot(),
                     **(ckpt.metrics() if ckpt is not None else {}),
                     **(stream.metrics() if stream is not None else {}),
                     **(el.metrics() if el is not None else {}),
                     **fgauges},
                    job_scoped(args, args.prom),
                    labels=prom_labels(args, harness="imagenet"))
            # tensorboard: x-axis = cumulative examples (`logger.py:24-34`);
            # namespaces mirror the reference (losses/ times/ net/)
            tb.update_examples_count(examples)
            tb.log_scalar("losses/train_loss", acc.mean("loss"))
            tb.log_scalar("losses/test_loss", val_stats["loss"])
            tb.log_scalar("losses/top1", top1)
            tb.log_scalar("losses/top5", top5)
            tb.log_scalar("times/epoch_seconds", train_time)
            if examples and train_time > 0:
                tb.log_scalar("times/images_per_sec", img_s)
            if "throughput/mfu" in thr:
                tb.log_scalar("times/mfu", thr["throughput/mfu"])
            if per_chip_b is not None and train_time > 0:
                tb.log_scalar("net/payload_mb_per_step",
                              acc.mean("comm/sent_bits") / 8 / 1e6)
                tb.log_scalar("net/allreduce_gbps_per_chip",
                              per_chip_b * acc.steps / 1e9 / train_time)
            for k, v in fabric_g.items():
                tb.log_scalar(k, v)
            recv_g, sent_g = net_meter.update_bandwidth()
            tb.log_scalar("net/recv_gbit_s", recv_g)
            tb.log_scalar("net/transmit_gbit_s", sent_g)
            if "guard/nonfinite" in acc.sums:
                tb.log_scalar("guard/skip_rate", acc.mean("guard/nonfinite"))
                tb.log_scalar("guard/loss_scale",
                              acc.last.get("guard/loss_scale", 1.0))
                tb.log_scalar("guard/skipped", acc.last.get("guard/skipped", 0.0))
            if ckpt:
                ckpt.save_if_best(state, top5, floor=args.best_floor,
                                  meta={"epoch": epoch, "top1": top1, "top5": top5})
            epoch += 1
        if args.logdir:
            tsv.save(args.logdir)
    except resilience.Preempted as err:
        # SIGTERM/SIGINT landed: cut the emergency checkpoint (draining any
        # in-flight async write first) and exit PREEMPT_EXIT so the watchdog
        # relaunches immediately instead of burning its backoff/budget
        state = getattr(err, "elastic_state", state)
        raise preempt_exit(err, ckpt=ckpt, state=state,
                           meta={"epoch": epoch - 1},
                           events=events, flight=flight) from None
    finally:
        preempt.uninstall()
        tb.close()
        if ckpt:
            ckpt.close()   # drains the background writer before events close
        if stream is not None:
            stream.close()  # drains the in-flight segment commit
        if events is not None:
            events.close()
        if hb is not None:
            hb.stop()
    return summary


def main(argv: Optional[list] = None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
